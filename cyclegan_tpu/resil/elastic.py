"""Elastic topology recovery: any verified slot restores onto any mesh.

PR 8 made a run survive faults on ONE topology; this module removes the
weld between a checkpoint and the mesh that wrote it, and between a
SIGTERM and the epoch boundary. Three cooperating pieces:

- **Topology-aware slots.** Every save records the MeshPlan
  (dp x spatial), the per-leaf sharding specs, and the global-batch
  decomposition (n_data x batch_size x grad_accum) — `topology_record`
  builds the dict, `save_meta` threads it into the slot manifest and
  the meta.json sidecar (utils/checkpoint.py copies it verbatim).

- **Reshard-on-restore.** `preflight_elastic` runs BEFORE the data
  pipeline and step programs are built: when the sidecar's topology
  differs from the current mesh it recomputes batch_size x grad_accum
  so the GLOBAL batch is preserved exactly (the optimization trajectory
  depends on it), or refuses with CLI guidance when the old global
  batch is unreachable on the new chip count.
  `elastic_restore_if_exists` then restores through the verified-ring
  walk and, on topology drift, gathers every leaf to a host-consistent
  array and `device_put`s it under the CURRENT mesh's NamedShardings
  (logged as `elastic_reshard` telemetry). Strict mode still refuses
  shape/dtype drift — replicated weights have topology-independent
  shapes, so only a genuinely different model trips it. The resharded
  leaves are routed through `jnp.copy` so the donation hazard that
  motivated checkpoint._rebuffer (on CPU the host hop can be zero-copy
  in BOTH directions, so donating the placed buffer corrupts the heap)
  cannot reach the resharded state either.

- **Mid-epoch emergency saves.** With ``--preempt_deadline_s S`` the
  dispatch loop polls the PreemptionGuard once per dispatch
  (`MidEpochBreaker`) and, on SIGTERM, breaks out mid-epoch;
  `emergency_save` writes a step-granular slot whose sidecar persists
  (epoch, step, data seed), drops queued cosmetic service jobs so the
  grace budget belongs to the checkpoint commit, and barriers within
  the remaining deadline. On resume the deterministic per-epoch
  permutation (data/pipeline.py) fast-forwards to the exact sample
  position — at most the in-flight dispatches are lost, never the
  epoch. Mid-epoch saves are single-process only: the per-dispatch
  poll reads the host-local flag (a cross-host sync per dispatch would
  serialize the loop); multi-host runs keep the epoch-boundary
  protocol.

The whole module is host-side orchestration at restore/preemption
boundaries; its ONE device fetch (the restore-time gather in
`reshard_to_plan`) is marked `sanctioned-fetch` and the file is on
tools/check_no_sync.py's hot-path list so nothing else sneaks in.
Drilled end-to-end by ``tools/chaos_drill.py elastic_resume``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import threading
import time
from typing import Optional, Tuple

import jax

# Keys of topology_record compared by topology_matches / echoed in the
# elastic_reshard event (leaf_specs is recorded but too big to echo).
_TOPOLOGY_KEYS = ("n_devices", "n_data", "n_spatial", "data_axis",
                  "spatial_axis", "batch_size", "grad_accum",
                  "global_batch_size", "steps_per_dispatch")


class ElasticTopologyError(RuntimeError):
    """The saved run's global batch cannot be reproduced on the current
    mesh — restoring anyway would silently change the optimization
    trajectory. The message carries the CLI guidance."""


# ------------------------------------------------------------- recording


def _path_key(path) -> str:
    """Flatten a jax key path to 'a/b/c' (same scheme as
    utils/checkpoint.py so specs line up with manifest/restore paths).
    Shared with the partition-rules table so rule patterns and manifest
    keys name leaves identically."""
    from cyclegan_tpu.parallel.mesh import tree_path_key

    return tree_path_key(path)


def leaf_sharding_specs(state) -> dict:
    """Per-leaf sharding-spec strings for the slot manifest. Host-side
    metadata reads only (no device sync); non-jax leaves (numpy test
    states) record as 'host'."""
    specs = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(state)[0]:
        sharding = getattr(leaf, "sharding", None)
        spec = getattr(sharding, "spec", None)
        specs[_path_key(path)] = str(spec) if spec is not None else "host"
    return specs


def topology_record(plan, config, state=None) -> dict:
    """The topology facts a slot must carry to be restorable anywhere:
    mesh shape, axis names, and the global-batch decomposition."""
    rec = dict(plan.describe())
    rec["batch_size"] = int(config.train.batch_size)
    rec["grad_accum"] = int(config.train.grad_accum)
    rec["steps_per_dispatch"] = int(config.train.steps_per_dispatch)
    rec["global_batch_size"] = (
        plan.n_data * config.train.batch_size * config.train.grad_accum
    )
    if state is not None:
        rec["leaf_specs"] = leaf_sharding_specs(state)
    return rec


def save_meta(config, plan, state=None, mid_epoch: Optional[dict] = None,
              data_seed: Optional[int] = None,
              transfer: Optional[dict] = None) -> dict:
    """The checkpoint meta dict: model architecture (as before), the
    topology record, and the run's DOMAIN KEY (domains/registry.py) —
    every slot is self-describing about what pair it was trained on, so
    restore can refuse (or warn about) a cross-domain mix-up.
    `mid_epoch` marks a step-granular emergency slot with its resume
    position {"epoch", "step", "data_seed"}; `transfer` is the
    Mind2Mind onboarding provenance (parent_ckpt/parent_epoch/
    parent_domain/transfer_mode, domains/transfer.py) and rides every
    save of a transfer run — the lineage survives in each slot."""
    meta = dict(config.model_meta())
    meta["topology"] = topology_record(plan, config, state=state)
    meta["domain"] = str(config.data.domain)
    if data_seed is not None:
        meta["data_seed"] = int(data_seed)
    if mid_epoch is not None:
        meta["mid_epoch"] = {k: int(v) for k, v in mid_epoch.items()}
    if transfer is not None:
        meta["transfer"] = dict(transfer)
    return meta


def topology_matches(saved: Optional[dict], plan) -> bool:
    """True when the saved mesh shape equals the current plan's (axis
    names may differ cosmetically; the shape is what placement and the
    batch decomposition depend on). No record means a pre-elastic slot:
    treated as matching — there is nothing to reshard against."""
    if not isinstance(saved, dict):
        return True
    for key, cur in (("n_data", plan.n_data), ("n_spatial", plan.n_spatial)):
        if key in saved and int(saved[key]) != int(cur):
            return False
    return True


# ----------------------------------------------------------- preflight


def read_sidecar_topology(output_dir: str) -> Optional[dict]:
    """The topology record of the newest save, straight from the
    meta.json sidecar — readable before a Checkpointer (and the
    telemetry it wants) exists. Unreadable/absent degrades to None."""
    path = os.path.join(output_dir, "checkpoints", "meta.json")
    try:
        with open(path) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return None
    topo = meta.get("topology")
    return topo if isinstance(topo, dict) else None


def resolve_batch_decomposition(saved: dict, plan, config) -> Tuple[int, int]:
    """(batch_size, grad_accum) reproducing the SAVED global batch on
    the current mesh. Preference order: keep the configured pair when it
    already lands on the saved global batch; keep grad_accum (memory
    contract) and rescale batch_size; keep batch_size and rescale
    grad_accum; finally microbatch at 1. Raises ElasticTopologyError
    with CLI guidance when the saved global batch is not divisible by
    the current data-shard count."""
    try:
        gbs = int(saved["global_batch_size"])
    except (KeyError, TypeError, ValueError):
        gbs = (int(saved.get("n_data", plan.n_data))
               * int(saved.get("batch_size", config.train.batch_size))
               * int(saved.get("grad_accum", 1)))
    n_data = plan.n_data
    if gbs % n_data != 0:
        raise ElasticTopologyError(
            f"elastic restore refused: the checkpoint was written with "
            f"global batch {gbs} (n_data={saved.get('n_data')} x "
            f"batch_size={saved.get('batch_size')} x "
            f"grad_accum={saved.get('grad_accum')}), which no "
            f"batch_size x grad_accum can reproduce on {n_data} data "
            f"shards ({gbs} % {n_data} != 0). Rerun on a device/"
            f"spatial split whose data-shard count divides {gbs} "
            f"(e.g. adjust --spatial_parallelism), or retrain with "
            f"--clear_output_dir to accept a new global batch.")
    per = gbs // n_data
    old_b, old_a = config.train.batch_size, config.train.grad_accum
    if old_b * old_a == per:
        return old_b, old_a
    if per % old_a == 0:
        return per // old_a, old_a
    if config.train.steps_per_dispatch == 1:
        if per % old_b == 0:
            return old_b, per // old_b
        return 1, per
    raise ElasticTopologyError(
        f"elastic restore refused: reproducing global batch {gbs} on "
        f"{n_data} data shards needs grad_accum > 1 (per-shard batch "
        f"{per} does not divide by grad_accum={old_a}), which is "
        f"mutually exclusive with --steps_per_dispatch "
        f"{config.train.steps_per_dispatch}. Drop --steps_per_dispatch "
        f"or pick a split whose per-shard batch is reachable.")


def preflight_elastic(config, plan, echo=None):
    """Run between mesh construction and data/step building: when the
    newest save's topology differs from the current plan, rewrite
    train.batch_size/grad_accum so the global batch is preserved
    exactly. Same-topology resumes pass through untouched (a user's
    deliberate batch change on the same mesh stays their call).

    Returns (config, info) — info is None when nothing applied, else
    {"saved": <topology record>, "batch_size", "grad_accum",
    "old_batch_size", "old_grad_accum", "changed": bool} for telemetry
    once the stream exists."""
    saved = read_sidecar_topology(config.train.output_dir)
    if saved is None or topology_matches(saved, plan):
        return config, None
    batch, accum = resolve_batch_decomposition(saved, plan, config)
    info = {
        "saved": {k: saved.get(k) for k in _TOPOLOGY_KEYS},
        "batch_size": batch,
        "grad_accum": accum,
        "old_batch_size": config.train.batch_size,
        "old_grad_accum": config.train.grad_accum,
        "changed": (batch, accum) != (config.train.batch_size,
                                      config.train.grad_accum),
    }
    if info["changed"]:
        config = dataclasses.replace(
            config,
            train=dataclasses.replace(
                config.train, batch_size=batch, grad_accum=accum),
        )
        if echo is not None:
            echo(f"elastic restore: topology changed "
                 f"({saved.get('n_data')}x{saved.get('n_spatial')} -> "
                 f"{plan.n_data}x{plan.n_spatial}); recomputed "
                 f"batch_size={batch} grad_accum={accum} to preserve "
                 f"global batch {saved.get('global_batch_size')}")
    return config, info


# -------------------------------------------------------------- restore


def reshard_to_plan(state, plan, template=None):
    """Gather every leaf to a host-consistent array and place it under
    the CURRENT mesh's sharding (the template's where given, replicated
    otherwise). The host hop makes the result independent of how the
    WRITING mesh laid the arrays out (including across process counts).

    The trailing `jnp.copy` is load-bearing, not belt-and-braces: on
    CPU both `device_get` and `device_put` can be ZERO-copy, so the
    placed array may alias the restored buffer — and the train step
    DONATES its state argument. Donating an aliased buffer is the
    exact failure checkpoint._rebuffer documents (intermittent glibc
    heap corruption, garbage in post-resume saves). Routing through an
    XLA computation yields a genuinely XLA-owned buffer with the same
    sharding.

    Placement: a CycleGANState resolves every leaf through the
    partition-rules table (parallel/mesh.py:state_partition_rules — the
    declarative layout registry; an unknown path raises with the path
    named instead of silently landing replicated). Other pytrees (ad-hoc
    test states) keep the template-sharding / replicated fallback."""
    import jax.numpy as jnp

    from cyclegan_tpu.parallel.mesh import replicated, state_shardings
    from cyclegan_tpu.train.state import CycleGANState

    fallback = replicated(plan)
    t_leaves = None
    if isinstance(state, CycleGANState):
        t_leaves = jax.tree_util.tree_leaves(state_shardings(plan, state))
    elif template is not None:
        t_leaves = [
            getattr(leaf, "sharding", None)
            for leaf in jax.tree_util.tree_leaves(template)
        ]

    leaves, treedef = jax.tree_util.tree_flatten(state)
    out = []
    for i, leaf in enumerate(leaves):
        if not isinstance(leaf, jax.Array):
            out.append(leaf)
            continue
        sharding = None
        if t_leaves is not None and i < len(t_leaves):
            sharding = t_leaves[i]
        host = jax.device_get(leaf)  # sanctioned-fetch: restore-time gather, off the dispatch path by construction
        placed = jax.device_put(host, sharding or fallback)
        out.append(jnp.copy(placed))
    return jax.tree_util.tree_unflatten(treedef, out)


@dataclasses.dataclass
class ElasticResume:
    state: object
    start_epoch: int
    resumed: bool
    resume_step: int = 0          # pipeline-yield index within start_epoch
    data_seed: Optional[int] = None
    resharded: bool = False


def elastic_restore_if_exists(ckpt, template, plan, config,
                              telemetry=None, partial=False,
                              echo=None) -> ElasticResume:
    """Checkpointer.restore_if_exists plus the elastic layer: detect
    topology drift via the sidecar, reshard under the current mesh, and
    surface the mid-epoch resume position of an emergency slot. The
    mid-epoch record only applies when the restored slot IS the
    sidecar's slot (a ring fallback to an older slot resumes at its
    epoch boundary as before)."""
    state, next_epoch, resumed = ckpt.restore_if_exists(
        template, partial=partial)
    if not resumed:
        return ElasticResume(state, 0, False)
    meta = ckpt.read_meta()
    meta = meta if isinstance(meta, dict) else {}
    # Domain identity check (domains/transfer.py): a slot records the
    # pair it was trained on; resuming a different --domain onto it
    # warns — or refuses under --strict_domain — BEFORE any training
    # step can poison either run. Legacy sidecars read as the default
    # domain (utils/convert.py back-tags them explicitly).
    from cyclegan_tpu.domains import transfer as _dom_transfer

    _dom_transfer.check_domain_compat(
        meta, config.data.domain,
        strict=getattr(config.train, "strict_domain", False),
        context="resume", telemetry=telemetry, echo=echo)
    saved = meta.get("topology")
    out = ElasticResume(state, next_epoch, True)
    if isinstance(saved, dict) and not topology_matches(saved, plan):
        out.state = reshard_to_plan(state, plan, template=template)
        out.resharded = True
        n_leaves = len(jax.tree_util.tree_leaves(template))
        if telemetry is not None:
            telemetry.event(
                "elastic_reshard",
                epoch=int(next_epoch) - 1,
                n_leaves=n_leaves,
                from_topology={k: saved.get(k) for k in _TOPOLOGY_KEYS},
                to_topology={
                    k: topology_record(plan, config).get(k)
                    for k in _TOPOLOGY_KEYS},
            )
        if echo is not None:
            echo(f"elastic restore: resharded {n_leaves} leaves from "
                 f"{saved.get('n_data')}x{saved.get('n_spatial')} onto "
                 f"{plan.n_data}x{plan.n_spatial}")
    mid = meta.get("mid_epoch")
    if (isinstance(mid, dict)
            and int(meta.get("epoch", -1)) == next_epoch - 1
            and int(mid.get("epoch", -1)) == next_epoch - 1):
        out.start_epoch = next_epoch - 1
        out.resume_step = max(0, int(mid.get("step", 0)))
        if mid.get("data_seed") is not None:
            out.data_seed = int(mid["data_seed"])
        if echo is not None and out.resume_step:
            echo(f"mid-epoch resume: epoch {out.start_epoch} continues "
                 f"at step {out.resume_step}")
    return out


# ------------------------------------------- mid-epoch preemption saves


class MidEpochBreaker:
    """Per-dispatch preemption poll for the training loop. Reads the
    PreemptionGuard's HOST-LOCAL flag (no collective, no sync — the
    whole point of checking inside the dispatch loop); `note()` counts
    DISPATCHED pipeline yields so the emergency slot records the exact
    sample position. Prefetched-but-undispatched batches are deliberately
    uncounted: they were never trained, so resume re-yields them."""

    def __init__(self, guard):
        self.guard = guard
        self.batches_done = 0
        self.fired = False

    def note(self, n: int = 1) -> None:
        self.batches_done += int(n)

    def should_break(self) -> bool:
        if not self.fired and self.guard is not None \
                and self.guard.requested_locally:
            self.fired = True
        return self.fired


# Cosmetic service jobs an expiring grace window may shed: the deadline
# budget belongs to the checkpoint commit, not panel renders/FID.
_SHEDDABLE_JOB_PREFIXES = ("plot_cycle:", "fid:")


def emergency_save(ckpt, state, config, plan, data, epoch, step, guard,
                   services=None, telemetry=None, echo=None,
                   transfer: Optional[dict] = None) -> bool:
    """Write the step-granular emergency slot within the
    --preempt_deadline_s budget. The deadline clock starts at the
    SIGTERM (guard.requested_at), not here — in-flight dispatch drain
    already spent part of the grace window. Queued cosmetic jobs are
    shed so the single-worker services queue reaches the checkpoint
    commit first; the barrier then waits out the remaining budget.
    Returns True when the commit landed inside the deadline."""
    deadline = float(getattr(config.train, "preempt_deadline_s", 0.0) or 0.0)
    now = time.monotonic()
    signal_at = getattr(guard, "requested_at", None) or now
    meta = save_meta(
        config, plan, state=state,
        mid_epoch={"epoch": int(epoch), "step": int(step),
                   "data_seed": int(data.seed)},
        transfer=transfer)
    shed = 0
    if services is not None:
        shed = services.drop_pending(
            lambda name: name.startswith(_SHEDDABLE_JOB_PREFIXES))
    ckpt.save(state, epoch, meta=meta, services=services)
    committed = True
    if services is not None:
        budget = None
        if deadline > 0:
            budget = max(0.05, deadline - (time.monotonic() - signal_at))
        committed = services.barrier(timeout=budget)
    elapsed = time.monotonic() - signal_at
    margin = (deadline - elapsed) if deadline > 0 else None
    if telemetry is not None:
        telemetry.event(
            "emergency_save", epoch=int(epoch), step=int(step),
            deadline_s=deadline, elapsed_s=round(elapsed, 4),
            margin_s=round(margin, 4) if margin is not None else None,
            shed_jobs=shed, committed=bool(committed))
    if echo is not None:
        echo(f"emergency save: epoch {epoch} step {step} -> "
             f"{os.path.basename(ckpt.slot)} "
             f"({elapsed:.2f}s of {deadline:.2f}s budget"
             + (f", {shed} queued job(s) shed" if shed else "") + ")")
    return bool(committed)


# One timer per process: the injected `preempt` fault may re-fire, but
# the platform delivers exactly one kill deadline per preemption notice.
_kill_timer_lock = threading.Lock()
_kill_timer: Optional[threading.Timer] = None


def arm_preempt_kill_timer(deadline_s: float, exit_code: int = 124):
    """The hard half of the injected ``preempt`` fault: a daemon timer
    that SIGKILL-surrogates (os._exit) the process `deadline_s` after
    the simulated preemption notice, exactly as a cloud platform
    enforces its grace window. Makes the deadline-OVERRUN path testable:
    an emergency save slower than the budget dies with exit 124 instead
    of pretending the grace window was infinite. No-op when the
    deadline is unset (<= 0)."""
    global _kill_timer
    if deadline_s is None or deadline_s <= 0:
        return None
    with _kill_timer_lock:
        if _kill_timer is not None:
            return _kill_timer

        def _kill():
            sys.stderr.write(
                f"preempt kill-deadline ({deadline_s}s) expired — "
                f"hard exit {exit_code}\n")
            sys.stderr.flush()
            os._exit(exit_code)

        t = threading.Timer(float(deadline_s), _kill)
        t.daemon = True
        t.start()
        _kill_timer = t
        return t
