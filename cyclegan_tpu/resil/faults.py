"""Deterministic, seeded fault injection (``--inject``).

Spec grammar — comma-separated entries, each ``kind@key=N`` with an
optional ``xM`` repeat count (default 1)::

    --inject nan_grads@step=6
    --inject ckpt_io_error@epoch=0x2,replica_crash@flush=1
    --inject data_stall@step=3,sigterm@step=40

Kinds and where they fire (ALL host-side — see docs/DESIGN.md):

- ``nan_grads@step=K``     — the K-th dispatched train step's input
  batch is multiplied by NaN at the dispatch boundary (train/loop.py →
  train/steps.py poison helper). The poison flows through the untouched
  jitted step and surfaces as non-finite gradients — exactly the
  production failure the ``--on_nan`` tripwire exists for.
- ``ckpt_io_error@epoch=N`` — the checkpoint save I/O for epoch N
  raises ``InjectedIOError`` inside the retry wrapper
  (utils/checkpoint.py → resil/retry.py), exercising bounded backoff.
- ``replica_crash@flush=M`` — the fleet's M-th replica flush dies
  mid-flight (``InjectedCrash`` escapes the worker loop, thread exits
  without resolving futures or freeing itself) — the failure the
  FleetExecutor's self-healing monitor recovers from.
- ``data_stall@step=K``     — the K-th staged-batch fetch raises a
  transient ``InjectedIOError`` inside the data path's RetryingIterator.
- ``sigterm@step=K``        — the process signals ITSELF with SIGTERM
  at the K-th dispatched step, driving the PreemptionGuard's
  finish-epoch/checkpoint/exit path.
- ``preempt@step=K``        — a full simulated platform preemption: the
  SIGTERM of ``sigterm`` PLUS a hard kill-deadline timer
  (resil/elastic.arm_preempt_kill_timer) that ``os._exit(124)``s the
  process ``--preempt_deadline_s`` after the notice, exactly as a cloud
  grace window expires. Makes the BOUNDED mid-epoch emergency-save path
  injectable — including the overrun case where the save loses the
  race.

Determinism: firing is a pure function of the spec and the per-site
counters the run advances (no clocks, no RNG), so a drill replays
identically; ``times`` (the ``xM`` suffix) lets one fault outlast a
retry budget. The no-fault cost is a single ``injector is not None``
check at each site — ``from_spec("")`` returns None so disabled runs
never construct an injector at all.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional

# kind -> (spec index key, check site). The check site names the
# counter (or explicit index) the fault is matched against; several
# kinds share the "step" site so one dispatch check covers them all.
FAULT_KINDS: Dict[str, tuple] = {
    "nan_grads": ("step", "step"),
    "sigterm": ("step", "step"),
    "preempt": ("step", "step"),
    "data_stall": ("step", "data"),
    "ckpt_io_error": ("epoch", "ckpt"),
    "replica_crash": ("flush", "flush"),
}

_SPEC_RE = re.compile(
    r"^(?P<kind>[a-z_]+)@(?P<key>[a-z]+)=(?P<at>\d+)(?:x(?P<times>\d+))?$"
)


class InjectedIOError(OSError):
    """A transient I/O failure injected under ``--inject`` — retryable
    by design (subclasses OSError so the retry machinery treats it
    exactly like a real filesystem/network error)."""


class InjectedCrash(BaseException):
    """A simulated hard replica crash: derives from BaseException so
    the replica worker's fail-the-flush Exception handler does NOT
    absorb it — the thread dies with its futures unresolved, which is
    the failure mode the fleet monitor must recover from."""


class Fault:
    """One armed fault: fires when its site counter/index reaches
    ``at``, up to ``times`` times."""

    __slots__ = ("kind", "site", "at", "times", "fired")

    def __init__(self, kind: str, at: int, times: int = 1):
        if kind not in FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r}; have {sorted(FAULT_KINDS)}")
        if at < 0 or times < 1:
            raise ValueError(f"fault {kind}: at must be >= 0 and times >= 1")
        self.kind = kind
        self.site = FAULT_KINDS[kind][1]
        self.at = int(at)
        self.times = int(times)
        self.fired = 0

    @property
    def exhausted(self) -> bool:
        return self.fired >= self.times

    def __repr__(self) -> str:  # telemetry/debug
        key = FAULT_KINDS[self.kind][0]
        sfx = f"x{self.times}" if self.times != 1 else ""
        return f"{self.kind}@{key}={self.at}{sfx}"


def parse_spec(spec: str) -> List[Fault]:
    """Parse a ``--inject`` string into Fault objects; '' -> []."""
    faults: List[Fault] = []
    for entry in (spec or "").replace(" ", "").split(","):
        if not entry:
            continue
        m = _SPEC_RE.match(entry)
        if m is None:
            raise ValueError(
                f"bad --inject entry {entry!r}: expected kind@key=N[xM], "
                f"e.g. nan_grads@step=6 or ckpt_io_error@epoch=0x2")
        kind, key = m.group("kind"), m.group("key")
        want = FAULT_KINDS.get(kind, (None,))[0]
        if want is None:
            raise ValueError(
                f"unknown fault kind {kind!r}; have {sorted(FAULT_KINDS)}")
        if key != want:
            raise ValueError(
                f"fault {kind} is indexed by {want!r}, not {key!r} "
                f"(write {kind}@{want}=N)")
        faults.append(Fault(kind, int(m.group("at")),
                            int(m.group("times") or 1)))
    return faults


class FaultInjector:
    """The per-run fault registry. Sites pass through ``fire()`` which
    advances that site's counter (or matches an explicit index) and
    returns the faults that just armed. Thread-safe: the fleet's
    replica threads share the ``flush`` counter."""

    def __init__(self, faults: List[Fault], telemetry=None):
        self.faults = list(faults)
        self.telemetry = telemetry
        self._counters: Dict[str, int] = {}
        self._lock = threading.Lock()

    @classmethod
    def from_spec(cls, spec: Optional[str],
                  telemetry=None) -> Optional["FaultInjector"]:
        """None for an empty spec — callers keep the zero-cost
        ``injector is None`` fast path."""
        faults = parse_spec(spec or "")
        return cls(faults, telemetry=telemetry) if faults else None

    def fire(self, site: str, index: Optional[int] = None,
             advance: int = 1) -> List[Fault]:
        """Check (and consume) faults at ``site``. With ``index`` None
        the site's internal counter advances by ``advance`` and a fault
        fires if its ``at`` falls inside the covered window [c, c+adv)
        — a fused K-step dispatch covers K step indices. A counter-site
        fault with ``times`` left keeps firing on later checks even
        though the counter moved past it (a "stuck" fault: how
        ``data_stall@step=Kx2`` outlasts one retry), so retry loops
        re-check with ``advance=0``. With an explicit ``index`` (the
        checkpoint path passes the epoch) the counter is untouched and
        only exact index matches fire."""
        fired: List[Fault] = []
        with self._lock:
            if index is None:
                lo = self._counters.get(site, 0)
                hi = lo + max(0, int(advance))
                self._counters[site] = hi
            else:
                lo, hi = int(index), int(index) + 1
            for f in self.faults:
                if f.site != site or f.exhausted:
                    continue
                stuck = index is None and 0 < f.fired < f.times
                if stuck or lo <= f.at < hi:
                    f.fired += 1
                    fired.append(f)
        for f in fired:
            if self.telemetry is not None:
                self.telemetry.event(
                    "fault_injected", kind=f.kind, site=site,
                    at=f.at, fired=f.fired, of=f.times, spec=repr(f))
        return fired

    def maybe_raise(self, site: str, index: Optional[int] = None,
                    advance: int = 1) -> None:
        """I/O-site variant: a fired ckpt_io_error/data_stall raises
        ``InjectedIOError`` — transient by contract, absorbed by the
        retry wrapper it fires inside. Retry loops pass ``advance=0``
        on attempts after the first so backoff attempts don't consume
        data indices."""
        for f in self.fire(site, index=index, advance=advance):
            if f.kind in ("ckpt_io_error", "data_stall"):
                raise InjectedIOError(
                    f"injected {f.kind} ({f!r}, firing {f.fired}/{f.times})")

    def pending(self) -> List[Fault]:
        """Faults that have not (fully) fired — drills assert this
        drains to [] so a mis-indexed spec fails loudly."""
        with self._lock:
            return [f for f in self.faults if not f.exhausted]

    def __repr__(self) -> str:
        return f"FaultInjector({', '.join(map(repr, self.faults))})"
