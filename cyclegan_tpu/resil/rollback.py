"""The ``--on_nan rollback`` policy: HealthFault -> restore -> rewind.

PR 5's health monitor can SEE a non-finite step within one
deferred-fetch horizon; under ``--on_nan halt`` that knowledge buys an
orderly death. This controller turns it into recovery: restore the
newest *verified* checkpoint-ring slot into the live train state
(reusing the sharding-aware restore in utils/checkpoint.py — the NaN'd
state is only a structure/sharding template), rewind the epoch counter
to the slot's, re-seed the data pipeline so the replayed epochs walk a
salted batch order instead of marching back into the same poison, emit
a ``health_recovery`` event, and keep training. Only after
``--max_rollbacks`` CONSECUTIVE faults (no clean epoch in between) does
the original HealthFault propagate and the run halt with exit 3 —
persistent numeric collapse still fails loudly; a one-off cosmic ray or
data glitch no longer costs the run.

Everything here is host-side orchestration between epochs: zero device
syncs, zero dispatches on the no-fault path (the controller is not even
consulted until a HealthFault is already in flight)."""

from __future__ import annotations

from typing import Optional, Tuple


class RollbackController:
    """Owns the rollback budget and the recovery sequence. main.py
    constructs one when ``config.obs.on_nan == "rollback"`` and calls
    ``recover`` from its HealthFault handler; ``note_clean_epoch``
    resets the consecutive-failure count after every epoch that
    completes without a fault."""

    def __init__(self, ckpt, data=None, telemetry=None,
                 max_rollbacks: int = 2, echo=None):
        if max_rollbacks < 0:
            raise ValueError(
                f"max_rollbacks must be >= 0, got {max_rollbacks}")
        self.ckpt = ckpt
        self.data = data
        self.telemetry = telemetry
        self.max_rollbacks = int(max_rollbacks)
        self.echo = echo
        self.consecutive = 0
        self.total = 0

    def note_clean_epoch(self) -> None:
        self.consecutive = 0

    def recover(self, template, fault, epoch: int,
                services=None, partial: bool = False) -> Tuple[object, int]:
        """Attempt one rollback; returns (restored_state, next_epoch).
        Re-raises ``fault`` when the budget is exhausted or no verified
        slot exists to roll back to (the halt path — main.py's existing
        HealthFault handler then exits 3 with the stream flushed)."""
        if self.consecutive >= self.max_rollbacks:
            self._echo(
                f"rollback budget exhausted ({self.consecutive} consecutive "
                f"of max {self.max_rollbacks}): halting")
            raise fault
        # A prior epoch's async save may still be committing — its slot
        # must land (and its manifest be written) before we pick the
        # newest verified slot to restore.
        if services is not None:
            services.barrier()
        if not self.ckpt.exists():
            self._echo("no checkpoint slot exists to roll back to: halting")
            raise fault
        try:
            state, next_epoch = self.ckpt.restore(template, partial=partial)
        except Exception as e:
            self._echo(f"rollback restore failed ({type(e).__name__}: {e}): "
                       "halting")
            raise fault from e
        self.consecutive += 1
        self.total += 1
        if self.data is not None and hasattr(self.data, "reseed"):
            # Salted data order for the replayed epochs: a fault caused
            # by a pathological batch sequence must not be replayed
            # verbatim into the same wall (deterministic per salt, so a
            # drill still reproduces exactly).
            self.data.reseed(self.total)
        slot = getattr(self.ckpt, "slot", None)
        if self.telemetry is not None:
            self.telemetry.event(
                "health_recovery",
                fault_kind=getattr(fault, "kind", "unknown"),
                epoch_faulted=int(epoch),
                resume_epoch=int(next_epoch),
                slot=slot,
                consecutive=self.consecutive,
                total=self.total,
                max_rollbacks=self.max_rollbacks,
            )
            self.telemetry.flush()
        self._echo(
            f"HEALTH ROLLBACK ({getattr(fault, 'kind', '?')}): restored "
            f"{slot}, rewinding epoch {epoch} -> {next_epoch} "
            f"(rollback {self.consecutive}/{self.max_rollbacks} consecutive, "
            f"{self.total} total)")
        return state, next_epoch

    def _echo(self, msg: str) -> None:
        if self.echo is not None:
            self.echo(f"resil: {msg}")
