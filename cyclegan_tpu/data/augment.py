"""Host-side image preprocessing, matching the reference's semantics.

Reference pipeline (/root/reference/main.py:35-50):
  train: random_flip_left_right -> resize (286, 286) bilinear ->
         random_crop (256, 256, 3) -> x/127.5 - 1
  test:  resize (256, 256) bilinear -> x/127.5 - 1

Bilinear resize uses TF2's half-pixel-center convention. RNG streams are
index-seeded (numpy Philox), so augmentation is deterministic per
(seed, epoch, sample) and identical across hosts — statistical, not
bitwise, parity with TF's stateful RNG (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import numpy as np


def normalize_image(img: np.ndarray) -> np.ndarray:
    """uint8 [0,255] -> float32 [-1, 1] (main.py:35-38)."""
    return img.astype(np.float32) / 127.5 - 1.0


def quantize_uint8(img: np.ndarray) -> np.ndarray:
    """float32 [0,255] -> uint8, round-half-even (matches the native
    path's std::nearbyint). The caches store this 4x-smaller format and
    normalize on batch assembly; quantization error is <= 0.5/127.5 in
    [-1, 1] terms, below the source images' own 8-bit grain."""
    return np.rint(np.clip(img, 0, 255)).astype(np.uint8)


def resize_bilinear(img: np.ndarray, out_h: int, out_w: int) -> np.ndarray:
    """Bilinear resize with half-pixel centers (TF2 tf.image.resize
    default). img: [H, W, C] float32 -> [out_h, out_w, C] float32."""
    img = np.asarray(img, np.float32)
    in_h, in_w = img.shape[:2]
    if (in_h, in_w) == (out_h, out_w):
        return img

    def coords(out_n, in_n):
        c = (np.arange(out_n, dtype=np.float32) + 0.5) * (in_n / out_n) - 0.5
        lo = np.floor(c)
        frac = c - lo
        i0 = np.clip(lo, 0, in_n - 1).astype(np.int64)
        i1 = np.clip(lo + 1, 0, in_n - 1).astype(np.int64)
        return i0, i1, frac.astype(np.float32)

    y0, y1, fy = coords(out_h, in_h)
    x0, x1, fx = coords(out_w, in_w)
    top = img[y0][:, x0] * (1 - fx)[None, :, None] + img[y0][:, x1] * fx[None, :, None]
    bot = img[y1][:, x0] * (1 - fx)[None, :, None] + img[y1][:, x1] * fx[None, :, None]
    return top * (1 - fy)[:, None, None] + bot * fy[:, None, None]


def draw_augment_params(rng: np.random.Generator, resize_size: int, crop_size: int):
    """The RNG decision stream for one training image: (flip, oy, ox).
    Shared by the numpy and native (C++) paths so they are
    decision-identical."""
    flip = rng.random() < 0.5
    max_off = resize_size - crop_size
    oy = int(rng.integers(0, max_off + 1))
    ox = int(rng.integers(0, max_off + 1))
    return flip, oy, ox


def preprocess_train(
    img: np.ndarray,
    rng: np.random.Generator,
    resize_size: int = 286,
    crop_size: int = 256,
    use_native: bool | None = None,
    normalize: bool = True,
    allow_flip: bool = True,
) -> np.ndarray:
    """Random flip -> resize -> random crop -> normalize (main.py:40-45).

    Dispatches to the fused C++ kernel (data/native.py) when built,
    falling back to the identical-algorithm numpy path. normalize=False
    returns uint8 (cache format, see quantize_uint8). allow_flip=False
    (directional domain pairs, DomainSpec.augment_flip) suppresses the
    mirror AFTER drawing the decision stream, so crop offsets are
    identical with flipping on or off.
    """
    flip, oy, ox = draw_augment_params(rng, resize_size, crop_size)
    flip = flip and allow_flip
    if use_native is None or use_native:
        from cyclegan_tpu.data import native

        if native.available():
            return native.preprocess_one(
                img, resize_size, flip, oy, ox, crop_size, normalize=normalize
            )
        if use_native:
            raise RuntimeError("native preprocessing requested but unavailable")
    if flip:
        img = img[:, ::-1]
    img = resize_bilinear(img.astype(np.float32), resize_size, resize_size)
    img = img[oy : oy + crop_size, ox : ox + crop_size]
    return normalize_image(img) if normalize else quantize_uint8(img)


def preprocess_test(
    img: np.ndarray, crop_size: int = 256, normalize: bool = True
) -> np.ndarray:
    """Resize -> normalize (main.py:47-50). normalize=False returns the
    uint8 cache format (see quantize_uint8)."""
    img = resize_bilinear(img.astype(np.float32), crop_size, crop_size)
    return normalize_image(img) if normalize else quantize_uint8(img)
