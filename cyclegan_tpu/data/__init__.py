"""Input pipeline: unpaired two-domain image datasets, per-host sharded.

TPU-native re-design of the reference's tf.data/TFDS pipeline
(/root/reference/main.py:18-83).
"""

from cyclegan_tpu.data.sources import (
    FolderSource,
    SyntheticSource,
    TFDSSource,
    resolve_source,
)
from cyclegan_tpu.data.pipeline import CycleGANData, build_data

__all__ = [
    "FolderSource",
    "SyntheticSource",
    "TFDSSource",
    "resolve_source",
    "CycleGANData",
    "build_data",
]
