"""Dataset sources for unpaired image-to-image translation.

The reference hard-wires TFDS `cycle_gan/horse2zebra` with four splits
trainA/trainB/testA/testB (/root/reference/main.py:22-26). Here a source
is anything that can produce those four splits as uint8 RGB arrays:

- `TFDSSource`: the same TFDS datasets (horse2zebra, apple2orange,
  monet2photo, ... — main.py:22 is the only dataset-specific line in the
  reference), gated on `tensorflow_datasets` being importable.
- `FolderSource`: a directory with trainA/ trainB/ testA/ testB/ image
  folders (the standard CycleGAN dataset layout).
- `SyntheticSource`: deterministic procedurally-generated images for
  tests/benchmarks and egress-free environments.
"""

from __future__ import annotations

import os
import zlib
from typing import List, Protocol

import numpy as np

SPLITS = ("trainA", "trainB", "testA", "testB")


def split_tag(split: str) -> int:
    """Stable cross-process tag for a split name (NOT Python's hash(),
    which is salted per process and would desynchronize hosts)."""
    return zlib.crc32(split.encode()) & 0xFFFF


class Source(Protocol):
    name: str

    def split_size(self, split: str) -> int: ...

    def load(self, split: str, index: int) -> np.ndarray:
        """Return one uint8 RGB image [H, W, 3]."""
        ...


class SyntheticSource:
    """Deterministic synthetic images; index-seeded so every epoch and
    every host sees identical data without any files."""

    def __init__(self, train_size: int = 64, test_size: int = 16, image_size: int = 256):
        self.name = "synthetic"
        self._sizes = {
            "trainA": train_size,
            "trainB": train_size,
            "testA": test_size,
            "testB": test_size,
        }
        self._hw = image_size

    def split_size(self, split: str) -> int:
        return self._sizes[split]

    def load(self, split: str, index: int) -> np.ndarray:
        seed = split_tag(split) * 100003 + index
        rng = np.random.RandomState(seed % (2**31))
        hw = self._hw
        # Smooth random blobs rather than white noise so losses behave
        # like natural images (finite gradients, non-trivial cycles).
        low = rng.randint(0, 256, size=(8, 8, 3), dtype=np.uint8).astype(np.float32)
        reps = (hw + 7) // 8
        img = np.kron(low, np.ones((reps, reps, 1), np.float32))[:hw, :hw]
        img += rng.randn(hw, hw, 3) * 8.0
        return np.clip(img, 0, 255).astype(np.uint8)


def load_image_file(path: str) -> np.ndarray:
    """Decode one image file (raster formats via PIL, .npy directly) to
    uint8 RGB [H, W, 3] — the one shared decode for FolderSource and
    translate.py, so format rules can't diverge."""
    if path.endswith(".npy"):
        arr = np.load(path)
    else:
        from PIL import Image

        with Image.open(path) as im:
            arr = np.asarray(im.convert("RGB"))
    if arr.dtype != np.uint8:
        arr = np.clip(arr, 0, 255).astype(np.uint8)
    return arr


class FolderSource:
    """trainA/trainB/testA/testB folders of images under `root`."""

    EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".npy")

    def __init__(self, root: str):
        self.name = f"folder:{root}"
        self.root = root
        self._files = {}
        for split in SPLITS:
            d = os.path.join(root, split)
            if not os.path.isdir(d):
                raise FileNotFoundError(f"missing split directory: {d}")
            files = sorted(
                os.path.join(d, f)
                for f in os.listdir(d)
                if f.lower().endswith(self.EXTS)
            )
            if not files:
                raise FileNotFoundError(f"no images in {d}")
            self._files[split] = files

    def split_size(self, split: str) -> int:
        return len(self._files[split])

    def load(self, split: str, index: int) -> np.ndarray:
        return load_image_file(self._files[split][index])


class TFDSSource:
    """TFDS cycle_gan/<name> (reference main.py:22-26), import-gated.

    Prefers `builder.as_data_source` (TFDS random-access API): records
    decode LAZILY per `load`, so no split is ever resident whole — the
    pipeline's windowed preprocessing then bounds memory end to end.
    Datasets prepared in a format without random access fall back to
    materializing each split once as uint8 arrays (~260MB for
    horse2zebra; the pre-r2 behavior).
    """

    def __init__(self, dataset: str = "horse2zebra", data_dir: str | None = None):
        try:
            import tensorflow_datasets as tfds
        except ImportError as e:  # pragma: no cover - env without TFDS
            raise ImportError(
                "tensorflow_datasets is not available; use a FolderSource "
                "(--data_dir) or SyntheticSource (--data_source synthetic)"
            ) from e
        self.name = f"tfds:cycle_gan/{dataset}"
        builder = tfds.builder(f"cycle_gan/{dataset}", data_dir=data_dir)
        builder.download_and_prepare()
        self._random_access: dict | None = None
        self._splits: dict = {}
        self._sizes: dict = {}
        try:
            sources = {
                split: builder.as_data_source(split=split) for split in SPLITS
            }
            self._random_access = sources
            self._sizes = {split: len(src) for split, src in sources.items()}
        except (AttributeError, NotImplementedError, RuntimeError, ValueError):
            self._materialize(builder)

    def _materialize(self, builder) -> None:
        """Eager fallback for non-random-access dataset formats."""
        for split in SPLITS:
            ds = builder.as_dataset(split=split, as_supervised=True)
            # Label discarded, as in reference main.py:40.
            self._splits[split] = [
                np.asarray(img) for img, _ in ds.as_numpy_iterator()
            ]
            self._sizes[split] = len(self._splits[split])

    def split_size(self, split: str) -> int:
        return self._sizes[split]

    def load(self, split: str, index: int) -> np.ndarray:
        if self._random_access is not None:
            rec = self._random_access[split][index]
            # data_source records are feature dicts; label discarded
            # (main.py:40 parity).
            img = rec["image"] if isinstance(rec, dict) else rec[0]
            return np.asarray(img)
        return self._splits[split][index]


def resolve_source(data_config) -> Source:
    """Pick a source per config: explicit, else folder if data_dir given,
    else TFDS if importable, else synthetic. The config's fields are
    normally filled from a DomainSpec (domains/registry.py
    data_config_for), so `--domain <key>` lands here with source/dataset
    /data_dir already resolved; errors name the domain key so a bad
    registry entry points back at its spec."""
    c = data_config
    domain = getattr(c, "domain", None) or "?"

    def synthetic():
        return SyntheticSource(
            c.synthetic_train_size, c.synthetic_test_size, image_size=c.crop_size
        )

    if c.source == "synthetic":
        return synthetic()
    if c.source == "folder" or (c.source == "auto" and c.data_dir):
        if not c.data_dir:
            raise ValueError(
                f"domain {domain!r}: source 'folder' requires a data_dir "
                f"(--data_dir, or the spec's data_dir field)")
        return FolderSource(c.data_dir)
    if c.source == "tfds":
        return TFDSSource(c.dataset, data_dir=c.data_dir)
    # auto without data_dir: try TFDS, fall back to synthetic
    try:
        return TFDSSource(c.dataset)
    except ImportError:
        return synthetic()
