"""Two-domain zipped batch pipeline with static shapes and prefetch.

Mirrors the reference pipeline (/root/reference/main.py:18-83):
- both train domains truncated to min(|trainA|, |trainB|) (main.py:30-31),
- steps = ceil(n / global_batch) (main.py:32-33),
- per-domain map -> cache -> shuffle (main.py:53-60); the reference's
  cache-AFTER-augment quirk (augmentations frozen after epoch 1) is
  reproduced when `cache_augmented=True` and fixed when False,
- zip of the two batched domains (main.py:70-74),
- a 5-pair batch-1 plot set from the test split (main.py:76-77).

TPU-first differences:
- Every batch has a STATIC shape: the final ragged batch is zero-padded to
  the global batch size with a {0,1} per-sample weight mask (exact
  remainder semantics, one compiled program — see parallel/dp.py).
- Shuffling is a full per-epoch permutation (deterministic, seeded),
  not tf.data's buffer-256 partial shuffle — a strict improvement with
  identical training statistics.
- Per-host sharding for multi-host pods: each process materializes only
  its 1/process_count slice of every global batch (the DCN input-sharding
  story, SURVEY.md §2.4), indices deterministic so hosts never disagree.
- Background-thread prefetch overlaps host preprocessing with device
  steps (the AUTOTUNE prefetch analog, main.py:72).
- Bounded memory: caches hold post-augment UINT8 (4x smaller than the
  reference's float32 tf.data cache; quantization error <= 0.5/127.5,
  below the sources' own 8-bit grain), normalization happens on batch
  assembly in the prefetch thread, and native preprocessing runs in
  bounded windows so no full-split float32 or raw stack is ever
  transiently resident. `cache_nbytes()` is the ledger.
"""

from __future__ import annotations

import math
from typing import Iterator, List, Optional, Tuple

import numpy as np

from cyclegan_tpu.config import Config
from cyclegan_tpu.data.augment import (
    normalize_image,
    preprocess_test,
    preprocess_train,
)
from cyclegan_tpu.data.prefetch import prefetch_iter
from cyclegan_tpu.data.sources import Source, resolve_source, split_tag

Batch = Tuple[np.ndarray, np.ndarray, np.ndarray]  # x, y, weights


class CycleGANData:
    """Materialized, preprocessed two-domain dataset with epoch iterators."""

    def __init__(
        self,
        config: Config,
        global_batch_size: int,
        source: Optional[Source] = None,
        test_batch_size: Optional[int] = None,
    ):
        c = config.data
        self.config = config
        self.global_batch_size = int(global_batch_size)
        # Eval batches may be smaller than train batches: under
        # --grad_accum the train "batch" is the ACCUMULATED effective
        # batch (memory-bounded by microbatching in the step), but the
        # test/FID forwards have no microbatching — they must run at the
        # real per-dispatch size or they would OOM exactly the configs
        # accumulation exists for.
        self.test_batch_size = int(test_batch_size or global_batch_size)
        self.source = source or resolve_source(c)
        self.seed = config.train.seed
        self._base_seed = self.seed  # reseed() anchor (rollback recovery)

        self.n_train = min(self.source.split_size("trainA"), self.source.split_size("trainB"))
        self.n_test = min(self.source.split_size("testA"), self.source.split_size("testB"))
        # ceil(n / global_batch) (main.py:32-33)
        self.train_steps = math.ceil(self.n_train / self.global_batch_size)
        self.test_steps = math.ceil(self.n_test / self.test_batch_size)

        try:
            import jax

            self._process_index = jax.process_index()
            self._process_count = jax.process_count()
        except Exception:
            self._process_index, self._process_count = 0, 1

        # Test split: deterministic preprocessing, cached (main.py:62-68).
        self._test_a = self._prep_test("testA")
        self._test_b = self._prep_test("testB")

        # Train split: cache of epoch-0 augmentations (reference quirk,
        # main.py:53-54) when cache_augmented.
        self._train_cache: Optional[Tuple[List[np.ndarray], List[np.ndarray]]] = None
        if c.cache_augmented:
            self._train_cache = (
                self._prep_train("trainA", epoch=0),
                self._prep_train("trainB", epoch=0),
            )

    def reseed(self, salt: int) -> None:
        """Derive a new deterministic seed from the base seed + salt —
        the rollback path (resil/rollback.py) calls this so replayed
        epochs walk a different (but still reproducible) shuffle order
        and augmentation stream instead of re-entering the exact batch
        sequence that preceded a numeric fault. Rebuilds the epoch-0
        augmentation cache, which was materialized under the old seed."""
        self.seed = (self._base_seed + 0x9E3779B1 * int(salt)) % (1 << 32)
        if self._train_cache is not None:
            self._train_cache = (
                self._prep_train("trainA", epoch=0),
                self._prep_train("trainB", epoch=0),
            )

    def restore_seed(self, seed: int) -> None:
        """Set the EXACT effective seed a checkpoint recorded — the
        elastic-resume counterpart of reseed(): a mid-epoch emergency
        slot persists (epoch, step, data_seed) and the restored process
        must replay the identical permutation/augmentation stream even
        if rollbacks had reseeded the original run before the save."""
        seed = int(seed) % (1 << 32)
        if seed == self.seed:
            return
        self.seed = seed
        if self._train_cache is not None:
            self._train_cache = (
                self._prep_train("trainA", epoch=0),
                self._prep_train("trainB", epoch=0),
            )

    # -- preprocessing ---------------------------------------------------

    def _prep_test(self, split: str) -> List[np.ndarray]:
        c = self.config.data
        n = self.n_test
        return [
            preprocess_test(self.source.load(split, i), c.crop_size, normalize=False)
            for i in range(n)
        ]

    def _sample_rng(self, split: str, epoch: int, i: int) -> np.random.Generator:
        """The one RNG stream per (seed, split, epoch, sample) — shared by
        the numpy and native paths so they are decision-identical,
        reproducible across restarts, and identical on every host."""
        return np.random.default_rng((self.seed, split_tag(split), epoch, i))

    def _augment_one(self, split: str, epoch: int, i: int) -> np.ndarray:
        """One augmented image in the uint8 cache format (normalization
        happens centrally in _batches)."""
        c = self.config.data
        return preprocess_train(
            self.source.load(split, int(i)),
            self._sample_rng(split, epoch, int(i)),
            c.resize_size,
            c.crop_size,
            normalize=False,
            allow_flip=c.augment_flip,
        )

    # Native preprocessing window: bounds the transient raw uint8 stack
    # (~50MB at 256^2) so a 7k-image split never materializes whole —
    # wide enough that the C++ thread pool stays saturated.
    _NATIVE_WINDOW = 256

    def _prep_train(self, split: str, epoch: int) -> List[np.ndarray]:
        c = self.config.data
        from cyclegan_tpu.data import native
        from cyclegan_tpu.data.augment import draw_augment_params

        if not native.available():
            return [self._augment_one(split, epoch, i) for i in range(self.n_train)]
        out: List[np.ndarray] = []
        for lo in range(0, self.n_train, self._NATIVE_WINDOW):
            hi = min(lo + self._NATIVE_WINDOW, self.n_train)
            raws = [self.source.load(split, i) for i in range(lo, hi)]
            if len({r.shape for r in raws}) == 1:
                # Same-sized window (TFDS cycle_gan/*, synthetic): fused
                # threaded C++ batch path.
                flips, oys, oxs = [], [], []
                for i in range(lo, hi):
                    rng = self._sample_rng(split, epoch, i)
                    f, oy, ox = draw_augment_params(rng, c.resize_size, c.crop_size)
                    flips.append(int(f and c.augment_flip))
                    oys.append(oy); oxs.append(ox)
                out.extend(native.preprocess_batch(
                    np.stack(raws), c.resize_size,
                    np.asarray(flips, np.int32), np.asarray(oys, np.int32),
                    np.asarray(oxs, np.int32), c.crop_size, normalize=False,
                ))
            else:
                # Mixed-size window: per-image native path on the raws.
                out.extend(
                    preprocess_train(
                        raws[i - lo], self._sample_rng(split, epoch, i),
                        c.resize_size, c.crop_size, normalize=False,
                        allow_flip=c.augment_flip,
                    )
                    for i in range(lo, hi)
                )
        return out

    # -- iteration -------------------------------------------------------

    def _epoch_order(self, epoch: int, domain: int, n: int) -> np.ndarray:
        """Deterministic per-epoch, per-domain permutation (the shuffle of
        main.py:55/60, full-permutation instead of buffer-256)."""
        rng = np.random.default_rng((self.seed, 0xD0 + domain, epoch))
        return rng.permutation(n)

    def _host_slice(self, idx: np.ndarray) -> np.ndarray:
        """This host's contiguous slice of one global batch's indices."""
        if self._process_count == 1:
            return idx
        per_host = len(idx) // self._process_count
        lo = self._process_index * per_host
        return idx[lo : lo + per_host]

    def _batches(
        self, get_a, get_b, order_a: np.ndarray, order_b: np.ndarray,
        gbs: Optional[int] = None,
    ) -> Iterator[Batch]:
        """Yield host-local (x, y, weights) batches, each the 1/P slice of
        a zero-padded static global batch. `get_a`/`get_b` map a sample
        index to a preprocessed image and are only called for indices this
        host owns (lazy: runs inside the prefetch thread, overlapping the
        device step)."""
        gbs = gbs or self.global_batch_size
        n = len(order_a)
        crop = self.config.data.crop_size
        ch = 3
        for start in range(0, n, gbs):
            ga = order_a[start : start + gbs]
            gb = order_b[start : start + gbs]
            k = len(ga)
            weights = np.zeros((gbs,), np.float32)
            weights[:k] = 1.0
            # pad index lists to full batch (padded samples masked out)
            pad = np.zeros((gbs - k,), np.int64)
            ga = np.concatenate([ga, pad]) if k < gbs else ga
            gb = np.concatenate([gb, pad]) if k < gbs else gb
            la, lb = self._host_slice(ga), self._host_slice(gb)
            wlocal = self._host_slice(weights)
            # get_* return the uint8 cache format; normalize here, in the
            # prefetch thread, so float32 exists only batch-at-a-time.
            x = normalize_image(np.stack([get_a(i) for i in la]))
            y = normalize_image(np.stack([get_b(i) for i in lb]))
            if k < gbs:
                # zero out padded positions on this host
                x = x * wlocal[:, None, None, None]
                y = y * wlocal[:, None, None, None]
            assert x.shape[1:] == (crop, crop, ch)
            yield x, y, wlocal

    def train_epoch(
        self, epoch: int, prefetch: bool = True, start_step: int = 0,
    ) -> Iterator[Batch]:
        if self._train_cache is not None:
            items_a, items_b = self._train_cache
            get_a = items_a.__getitem__
            get_b = items_b.__getitem__
        else:
            # Fresh augmentation, lazily per owned index (runs in the
            # prefetch thread — fixes the reference's frozen-augment quirk
            # without stalling the device).
            get_a = lambda i: self._augment_one("trainA", epoch, i)
            get_b = lambda i: self._augment_one("trainB", epoch, i)
        order_a = self._epoch_order(epoch, 0, self.n_train)
        order_b = self._epoch_order(epoch, 1, self.n_train)
        if start_step:
            # Mid-epoch resume (resil/elastic.py): _batches strides the
            # order arrays in global_batch_size chunks, so dropping the
            # first start_step*gbs indices yields EXACTLY batches
            # start_step.. of the full epoch — no sample skipped or
            # repeated across the preemption seam, on any topology whose
            # batch x grad_accum decomposition preserves gbs.
            skip = int(start_step) * self.global_batch_size
            order_a = order_a[skip:]
            order_b = order_b[skip:]
        it = self._batches(get_a, get_b, order_a, order_b)
        return prefetch_iter(it, depth=2) if prefetch else it

    def test_epoch(self, prefetch: bool = True) -> Iterator[Batch]:
        order = np.arange(self.n_test)
        it = self._batches(
            self._test_a.__getitem__, self._test_b.__getitem__, order, order,
            gbs=self.test_batch_size,
        )
        return prefetch_iter(it, depth=2) if prefetch else it

    def plot_pairs(self, k: Optional[int] = None) -> List[Tuple[np.ndarray, np.ndarray]]:
        """First k test pairs at batch 1 (main.py:76-77), normalized."""
        k = k if k is not None else self.config.train.plot_samples
        k = min(k, self.n_test)
        return [
            (
                normalize_image(self._test_a[i][None, ...]),
                normalize_image(self._test_b[i][None, ...]),
            )
            for i in range(k)
        ]

    def cache_nbytes(self) -> int:
        """Memory ledger: bytes held by the resident test/train caches."""
        total = sum(a.nbytes for a in self._test_a) + sum(
            a.nbytes for a in self._test_b
        )
        if self._train_cache is not None:
            for items in self._train_cache:
                total += sum(a.nbytes for a in items)
        return total


def build_data(
    config: Config, global_batch_size: int,
    test_batch_size: Optional[int] = None,
) -> CycleGANData:
    return CycleGANData(
        config, global_batch_size, test_batch_size=test_batch_size
    )
