"""Background-thread device prefetch for the training loop.

TPU-native equivalent of the reference pipeline's host-side
`.prefetch(tf.data.AUTOTUNE)` (/root/reference/main.py:72), extended to
DEVICE staging: the worker thread runs the whole batch-prep chain —
host-side stacking plus `jax.device_put` against the mesh shardings — so
the H2D transfer of batch N+1..N+depth overlaps the device compute of
batch N instead of sitting on the dispatch critical path.
`train/loop.py` threads it around `_staged_batches`; depth is
`TrainConfig.prefetch_batches` (0 disables — staging runs inline on the
consumer thread, the pre-round-4 behavior).

JAX calls (`device_put`, `make_array_from_process_local_data`) are
thread-safe for this producer/consumer split; the jitted step dispatches
stay on the caller's thread.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator

_SENTINEL = object()


def prefetch_iter(src: Iterable, depth: int) -> Iterator:
    """Iterate `src` on a daemon worker thread, keeping up to `depth + 1`
    items staged ahead of the consumer (`depth` queued, plus the one the
    worker has already produced and is blocked on enqueueing).

    Exceptions raised by `src` re-raise at the consumer's next pull
    (after already-staged items drain). Abandoning the iterator (consumer
    exception / early close) stops the worker promptly via the
    generator's `finally` instead of leaking a blocked thread.
    """
    if depth < 1:
        raise ValueError(f"prefetch depth must be >= 1, got {depth}")
    return _prefetch_gen(src, depth)


def _prefetch_gen(src: Iterable, depth: int) -> Iterator:
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()
    err: list = []

    def _put(item) -> bool:
        """Bounded put that gives up when the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker() -> None:
        try:
            for item in src:
                if not _put(item):
                    return
        except BaseException as e:  # propagate to the consumer
            err.append(e)
        finally:
            _put(_SENTINEL)

    thread = threading.Thread(
        target=worker, daemon=True, name="cyclegan-prefetch"
    )
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item
    finally:
        stop.set()
