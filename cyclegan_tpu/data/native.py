"""ctypes binding for the native (C++) preprocessing library.

Lazily builds `cyclegan_tpu/native/libcgdata.so` with g++ on first use
(no pybind11 — plain C ABI + ctypes) and exposes the fused threaded
batch preprocess. Falls back cleanly when no compiler is available:
`load()` returns None and the pipeline uses the numpy path
(data/augment.py), which implements the identical algorithm.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "cgdata.cpp")
_SO = os.path.join(_NATIVE_DIR, "libcgdata.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # Compile to a private temp file, then atomically rename into place so
    # concurrent builders/loaders never see a partially-written .so.
    tmp = f"{_SO}.build.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-pthread",
        "-o", tmp, _SRC,
    ]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if r.returncode != 0 or not os.path.exists(tmp):
            return False
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library, or None."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # Rebuild when the source is newer than the .so; a prebuilt .so
        # without the source (packaged install) is used as-is.
        have_src = os.path.exists(_SRC)
        stale = (
            have_src
            and os.path.exists(_SO)
            and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        )
        if not os.path.exists(_SO) or stale:
            if not have_src or not _build():
                return None
        lib = _try_load_checked()
        if lib is None and have_src:
            # ABI mismatch from a stale artifact the mtime check missed
            # (restored build caches, packaged prebuilts): rebuild once.
            if _build():
                lib = _try_load_checked()
        _lib = lib
        return _lib


# The C ABI revision this binding requires (cgdata.cpp cg_version).
_ABI_VERSION = 2


def _try_load_checked() -> Optional[ctypes.CDLL]:
    """CDLL + symbol binding + ABI check; None on any mismatch so the
    numpy fallback engages instead of raising mid-pipeline."""
    try:
        lib = ctypes.CDLL(_SO)
        lib.cg_version.restype = ctypes.c_int
        if int(lib.cg_version()) != _ABI_VERSION:
            return None
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        lib.cg_preprocess.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, f32p,
        ]
        lib.cg_preprocess_batch.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, i32p, i32p, i32p, ctypes.c_int, f32p, ctypes.c_int,
        ]
        lib.cg_preprocess_u8.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, u8p,
        ]
        lib.cg_preprocess_batch_u8.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, i32p, i32p, i32p, ctypes.c_int, u8p, ctypes.c_int,
        ]
        return lib
    except (OSError, AttributeError):
        # unloadable artifact, or symbols from an older ABI missing
        return None


def available() -> bool:
    return load() is not None


def preprocess_one(
    img: np.ndarray, resize: int, flip: bool, oy: int, ox: int, crop: int,
    normalize: bool = True,
) -> np.ndarray:
    """Fused flip->resize->crop of one uint8 [H, W, 3] image.

    normalize=True: float32 in [-1, 1] (feeds the device directly).
    normalize=False: uint8 (the 4x-smaller cache format; the pipeline
    normalizes on batch assembly)."""
    lib = load()
    assert lib is not None, "native library unavailable"
    img = np.ascontiguousarray(img, np.uint8)
    if normalize:
        out = np.empty((crop, crop, 3), np.float32)
        fn = lib.cg_preprocess
    else:
        out = np.empty((crop, crop, 3), np.uint8)
        fn = lib.cg_preprocess_u8
    fn(
        img, img.shape[0], img.shape[1], resize, resize,
        int(flip), int(oy), int(ox), crop, out,
    )
    return out


def preprocess_batch(
    imgs: np.ndarray,
    resize: int,
    flips: np.ndarray,
    oys: np.ndarray,
    oxs: np.ndarray,
    crop: int,
    n_threads: int = 0,
    normalize: bool = True,
) -> np.ndarray:
    """Threaded fused preprocess of a same-sized uint8 batch [N, H, W, 3].
    See preprocess_one for the `normalize` output-format switch."""
    lib = load()
    assert lib is not None, "native library unavailable"
    imgs = np.ascontiguousarray(imgs, np.uint8)
    n, h, w, _ = imgs.shape
    if normalize:
        out = np.empty((n, crop, crop, 3), np.float32)
        fn = lib.cg_preprocess_batch
    else:
        out = np.empty((n, crop, crop, 3), np.uint8)
        fn = lib.cg_preprocess_batch_u8
    fn(
        imgs, n, h, w, resize, resize,
        np.ascontiguousarray(flips, np.int32),
        np.ascontiguousarray(oys, np.int32),
        np.ascontiguousarray(oxs, np.int32),
        crop, out, n_threads,
    )
    return out
