"""ctypes binding for the native (C++) preprocessing library.

Lazily builds `cyclegan_tpu/native/libcgdata.so` with g++ on first use
(no pybind11 — plain C ABI + ctypes) and exposes the fused threaded
batch preprocess. Falls back cleanly when no compiler is available:
`load()` returns None and the pipeline uses the numpy path
(data/augment.py), which implements the identical algorithm.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "cgdata.cpp")
_SO = os.path.join(_NATIVE_DIR, "libcgdata.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # Compile to a private temp file, then atomically rename into place so
    # concurrent builders/loaders never see a partially-written .so.
    tmp = f"{_SO}.build.{os.getpid()}"
    cmd = [
        "g++", "-O3", "-march=native", "-shared", "-fPIC", "-pthread",
        "-o", tmp, _SRC,
    ]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if r.returncode != 0 or not os.path.exists(tmp):
            return False
        os.replace(tmp, _SO)
        return True
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library, or None."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # Rebuild when the source is newer than the .so; a prebuilt .so
        # without the source (packaged install) is used as-is.
        have_src = os.path.exists(_SRC)
        stale = (
            have_src
            and os.path.exists(_SO)
            and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        )
        if not os.path.exists(_SO) or stale:
            if not have_src or not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
        lib.cg_preprocess.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, f32p,
        ]
        lib.cg_preprocess_batch.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, i32p, i32p, i32p, ctypes.c_int, f32p, ctypes.c_int,
        ]
        lib.cg_version.restype = ctypes.c_int
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def preprocess_one(
    img: np.ndarray, resize: int, flip: bool, oy: int, ox: int, crop: int
) -> np.ndarray:
    """Fused flip->resize->crop->normalize of one uint8 [H, W, 3] image."""
    lib = load()
    assert lib is not None, "native library unavailable"
    img = np.ascontiguousarray(img, np.uint8)
    out = np.empty((crop, crop, 3), np.float32)
    lib.cg_preprocess(
        img, img.shape[0], img.shape[1], resize, resize,
        int(flip), int(oy), int(ox), crop, out,
    )
    return out


def preprocess_batch(
    imgs: np.ndarray,
    resize: int,
    flips: np.ndarray,
    oys: np.ndarray,
    oxs: np.ndarray,
    crop: int,
    n_threads: int = 0,
) -> np.ndarray:
    """Threaded fused preprocess of a same-sized uint8 batch [N, H, W, 3]."""
    lib = load()
    assert lib is not None, "native library unavailable"
    imgs = np.ascontiguousarray(imgs, np.uint8)
    n, h, w, _ = imgs.shape
    out = np.empty((n, crop, crop, 3), np.float32)
    lib.cg_preprocess_batch(
        imgs, n, h, w, resize, resize,
        np.ascontiguousarray(flips, np.int32),
        np.ascontiguousarray(oys, np.int32),
        np.ascontiguousarray(oxs, np.int32),
        crop, out, n_threads,
    )
    return out
