"""Native (C++) preprocessing sources, built lazily by data/native.py.

This __init__ exists so setuptools discovers the directory as a package
and ships cgdata.cpp (pyproject [tool.setuptools.package-data]) — without
it, packaged installs would silently lose the native path.
"""
