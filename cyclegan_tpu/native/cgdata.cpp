// cgdata: native host-side image preprocessing for the cyclegan_tpu
// input pipeline.
//
// Role: the TPU-native equivalent of the tf.data C++ op kernels the
// reference leans on for its map/batch pipeline (/root/reference/
// main.py:35-50 runs tf.image.* inside TF's C++ runtime). Here the fused
// op is resize(bilinear, half-pixel centers) -> flip -> crop ->
// normalize([-1,1]) in one pass per image, with a std::thread pool over
// the batch. The Python pipeline keeps the RNG decisions (flip flag,
// crop offsets) so numpy and native paths are decision-identical.
//
// Built as a plain shared library (g++ -O3 -shared -fPIC -pthread),
// bound via ctypes — no pybind11 dependency.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <cmath>
#include <thread>
#include <vector>

namespace {

// Bilinear sample row/col helper: TF2 half-pixel-center convention.
struct Coord {
  int i0, i1;
  float frac;
};

static inline Coord coord(int out_i, int in_n, float scale) {
  float c = (static_cast<float>(out_i) + 0.5f) * scale - 0.5f;
  float lo = std::floor(c);
  Coord r;
  r.frac = c - lo;
  int i0 = static_cast<int>(lo);
  r.i0 = std::min(std::max(i0, 0), in_n - 1);
  r.i1 = std::min(std::max(i0 + 1, 0), in_n - 1);
  return r;
}

// Output policies: float32 normalized to [-1, 1] (feeding the device
// directly), or uint8 rounded half-even (the 4x-smaller cache format —
// the pipeline normalizes on batch assembly).
static inline void store_px(float v, float* o) {
  constexpr float kInv = 1.0f / 127.5f;
  // clamp: bilinear of uint8 is within [0,255] mathematically, but
  // float32 rounding can spill a ulp past +/-1 after normalizing
  *o = std::min(1.0f, std::max(-1.0f, v * kInv - 1.0f));
}

static inline void store_px(float v, uint8_t* o) {
  // std::nearbyint rounds half-even in the default FP environment,
  // matching numpy's np.rint in the fallback path (data/augment.py).
  *o = static_cast<uint8_t>(
      std::nearbyint(std::min(255.0f, std::max(0.0f, v))));
}

// Fused: uint8 [h, w, 3] -> resize to [rh, rw] -> optional horizontal
// flip (applied BEFORE resize, matching the reference op order
// main.py:40-44) -> crop [crop, crop] at (oy, ox) -> OutT (see store_px).
template <typename OutT>
void preprocess_one(const uint8_t* img, int h, int w,
                    int rh, int rw, int flip, int oy, int ox, int crop,
                    OutT* out) {
  const float sy = static_cast<float>(h) / rh;
  const float sx = static_cast<float>(w) / rw;
  // Precompute x-coords for the cropped window only.
  std::vector<Coord> xs(crop);
  for (int j = 0; j < crop; ++j) {
    Coord cx = coord(ox + j, w, sx);
    if (flip) {  // sampling a flipped image == mirrored source columns
      cx.i0 = w - 1 - cx.i0;
      cx.i1 = w - 1 - cx.i1;
    }
    xs[j] = cx;
  }
  for (int i = 0; i < crop; ++i) {
    const Coord cy = coord(oy + i, h, sy);
    const uint8_t* row0 = img + static_cast<size_t>(cy.i0) * w * 3;
    const uint8_t* row1 = img + static_cast<size_t>(cy.i1) * w * 3;
    const float fy = cy.frac;
    OutT* orow = out + static_cast<size_t>(i) * crop * 3;
    for (int j = 0; j < crop; ++j) {
      const Coord& cx = xs[j];
      const float fx = cx.frac;
      const uint8_t* p00 = row0 + cx.i0 * 3;
      const uint8_t* p01 = row0 + cx.i1 * 3;
      const uint8_t* p10 = row1 + cx.i0 * 3;
      const uint8_t* p11 = row1 + cx.i1 * 3;
      for (int ch = 0; ch < 3; ++ch) {
        const float top = p00[ch] + (p01[ch] - static_cast<float>(p00[ch])) * fx;
        const float bot = p10[ch] + (p11[ch] - static_cast<float>(p10[ch])) * fx;
        const float v = top + (bot - top) * fy;
        store_px(v, orow + j * 3 + ch);
      }
    }
  }
}

template <typename OutT>
void preprocess_batch(const uint8_t* imgs, int n, int h, int w,
                      int rh, int rw,
                      const int* flips, const int* oys, const int* oxs,
                      int crop, OutT* out, int n_threads) {
  if (n_threads < 1) {
    n_threads = static_cast<int>(std::thread::hardware_concurrency());
    if (n_threads < 1) n_threads = 1;
  }
  n_threads = std::min(n_threads, n);
  const size_t in_stride = static_cast<size_t>(h) * w * 3;
  const size_t out_stride = static_cast<size_t>(crop) * crop * 3;
  std::vector<std::thread> workers;
  workers.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) {
    workers.emplace_back([=]() {
      for (int i = t; i < n; i += n_threads) {
        preprocess_one(imgs + i * in_stride, h, w, rh, rw,
                       flips[i], oys[i], oxs[i], crop, out + i * out_stride);
      }
    });
  }
  for (auto& th : workers) th.join();
}

}  // namespace

extern "C" {

// Single image, float32 [-1, 1] output (see preprocess_one).
void cg_preprocess(const uint8_t* img, int h, int w,
                   int rh, int rw, int flip, int oy, int ox, int crop,
                   float* out) {
  preprocess_one(img, h, w, rh, rw, flip, oy, ox, crop, out);
}

// Single image, uint8 output (cache format; no normalize).
void cg_preprocess_u8(const uint8_t* img, int h, int w,
                      int rh, int rw, int flip, int oy, int ox, int crop,
                      uint8_t* out) {
  preprocess_one(img, h, w, rh, rw, flip, oy, ox, crop, out);
}

// Batch of same-sized images, threaded. imgs: [n, h, w, 3] contiguous;
// flips/oys/oxs: per-image params; out: [n, crop, crop, 3].
void cg_preprocess_batch(const uint8_t* imgs, int n, int h, int w,
                         int rh, int rw,
                         const int* flips, const int* oys, const int* oxs,
                         int crop, float* out, int n_threads) {
  preprocess_batch(imgs, n, h, w, rh, rw, flips, oys, oxs, crop, out,
                   n_threads);
}

// Batch, uint8 output (cache format; no normalize).
void cg_preprocess_batch_u8(const uint8_t* imgs, int n, int h, int w,
                            int rh, int rw,
                            const int* flips, const int* oys, const int* oxs,
                            int crop, uint8_t* out, int n_threads) {
  preprocess_batch(imgs, n, h, w, rh, rw, flips, oys, oxs, crop, out,
                   n_threads);
}

int cg_version() { return 2; }

}  // extern "C"
