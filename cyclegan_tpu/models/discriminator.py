"""70x70 PatchGAN discriminator as a Flax module.

TPU-native equivalent of the reference's `get_discriminator`
(/root/reference/cyclegan/model.py:172-213):

  Conv4x4 s2 -> 64 (WITH bias — Keras default), LeakyReLU(0.2)
  3 downsample blocks (no bias): 128 s2, 256 s2, 512 s1, each IN + LeakyReLU(0.2)
  Conv4x4 s1 -> 1 (SAME, with bias), no activation — raw logits

Output is a 32x32x1 patch map for 256^2 input; ~2.77M parameters.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from cyclegan_tpu.config import DiscriminatorConfig
from cyclegan_tpu.models.modules import Downsample, HaloConv, init_normal


class PatchGANDiscriminator(nn.Module):
    config: DiscriminatorConfig = DiscriminatorConfig()
    dtype: Optional[Any] = None
    norm_impl: str = "auto"
    # "epilogue" fuses each trunk block's IN > LeakyReLU(0.2) tail into
    # one op (the Pallas epilogue kernel where VMEM-eligible — every
    # trunk slab is at the default 256^2 sizes). Same param tree as
    # "pad"; numerics agree to fp tolerance.
    pad_impl: str = "pad"
    # spatial_impl="halo": the two stride-1 4x4 SAME sites (the last
    # trunk Downsample and the patch-logits head) run as explicit
    # asymmetric zero-mode halo exchanges (modules.HaloConv — SAME for
    # k=4 pads 1 above / 2 below). Stride-2 sites stay on the XLA
    # partitioner. Param tree unchanged; None = the historical path.
    halo_mesh: Optional[Any] = None
    data_axis: str = "data"
    spatial_axis: str = "spatial"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        cfg = self.config
        in_dtype = x.dtype
        if self.dtype is not None:
            x = x.astype(self.dtype)
        leaky = functools.partial(nn.leaky_relu, negative_slope=0.2)

        # Stem (model.py:179-186): bias on, no norm
        y = nn.Conv(
            cfg.filters,
            (4, 4),
            strides=(2, 2),
            padding="SAME",
            use_bias=True,
            kernel_init=init_normal,
            dtype=self.dtype,
        )(x)
        y = leaky(y)

        # Downsampling trunk (model.py:188-205): strides 2, 2, then 1
        filters = cfg.filters
        for i in range(cfg.num_downsampling):
            filters *= 2
            strides = (2, 2) if i < 2 else (1, 1)
            y = Downsample(
                filters,
                kernel_size=(4, 4),
                strides=strides,
                activation=leaky,
                dtype=self.dtype,
                norm_impl=self.norm_impl,
                fuse_epilogue=self.pad_impl == "epilogue",
                halo_mesh=self.halo_mesh,
                data_axis=self.data_axis,
                spatial_axis=self.spatial_axis,
            )(y)

        # Patch logits head (model.py:207-211): bias on, no activation.
        # "Conv_1" is the name the unnamed-nn.Conv layout auto-assigns
        # here (stem took "Conv_0"), pinned so the halo layout keeps the
        # identical checkpoint tree.
        if self.halo_mesh is not None:
            y = HaloConv(
                1, kernel_size=(4, 4), mode="zero", use_bias=True,
                dtype=self.dtype, mesh=self.halo_mesh,
                data_axis=self.data_axis, spatial_axis=self.spatial_axis,
                name="Conv_1",
            )(y)
        else:
            y = nn.Conv(
                1,
                (4, 4),
                strides=(1, 1),
                padding="SAME",
                use_bias=True,
                kernel_init=init_normal,
                dtype=self.dtype,
            )(y)
        return y.astype(in_dtype)
