"""ResNet-9 CycleGAN generator as a Flax module.

TPU-native equivalent of the reference's `get_generator`
(/root/reference/cyclegan/model.py:129-169):

  c7s1-64 (reflect-pad 3, Conv7x7 no-bias, IN, ReLU)
  2 downsampling blocks doubling filters 64>128>256
  9 residual blocks @256ch
  2 upsampling blocks halving 256>128>64
  reflect-pad 3, Conv7x7 -> 3ch (valid, WITH bias — Keras default), tanh

~11.4M parameters at the default sizes. Optional `remat` wraps each
residual block in jax.checkpoint to trade FLOPs for HBM at 512^2.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from cyclegan_tpu.config import GeneratorConfig
from cyclegan_tpu.models.modules import (
    Downsample,
    InstanceNorm,
    PerturbBlock,
    ResidualBlock,
    Upsample,
)


class _TrunkBody(nn.Module):
    """One residual block in (carry, _) -> (carry, None) form for nn.scan."""

    dtype: Optional[Any] = None
    norm_impl: str = "auto"
    remat: bool = False
    pad_mode: str = "reflect"
    pad_impl: str = "pad"
    halo_mesh: Optional[Any] = None
    data_axis: str = "data"
    spatial_axis: str = "spatial"

    @nn.compact
    def __call__(self, carry, _):
        block_cls = nn.remat(ResidualBlock) if self.remat else ResidualBlock
        y = block_cls(
            dtype=self.dtype, norm_impl=self.norm_impl,
            pad_mode=self.pad_mode, pad_impl=self.pad_impl,
            halo_mesh=self.halo_mesh, data_axis=self.data_axis,
            spatial_axis=self.spatial_axis,
            name="ResidualBlock_0"
        )(carry)
        return y, None


class ResNetGenerator(nn.Module):
    config: GeneratorConfig = GeneratorConfig()
    out_channels: int = 3
    dtype: Optional[Any] = None
    remat: bool = False
    scan_blocks: bool = False
    norm_impl: str = "auto"
    pad_mode: str = "reflect"  # "zero": conv built-in SAME (same param tree)
    # "fused": reflect semantics via ReflectConv; "epilogue": fused
    # scheduling everywhere PLUS the residual-block / last-upsample
    # IN>ReLU>reflect-pad chains collapsed into the Pallas epilogue
    # kernel where VMEM-eligible (ops/pallas/epilogue_kernel.py). All
    # values share one param tree.
    pad_impl: str = "pad"
    # "perturb": Perturbative-GAN trunk tier (modules.PerturbBlock) —
    # fixed masks + 1x1 convs in place of the 3x3 residual convs.
    # DIFFERENT param tree (checkpoints record it via model_meta);
    # requires the unrolled trunk (per-block mask salts).
    trunk_impl: str = "resnet"
    # Transposed-conv engine for the two upsample blocks (GANAX output
    # decomposition — ops/upsample.py): "dense" | "zeroskip" |
    # "zeroskip_fused". All three share one param tree (checkpoints
    # interchange); model_meta records the setting for provenance.
    upsample_impl: str = "dense"
    # spatial_impl="halo" support: when a Mesh with a >1 spatial axis is
    # bound here, every stride-1 conv site (the 7x7 edge convs and the
    # residual trunk's 3x3 convs) runs as an explicit shard_map halo
    # exchange (modules.HaloConv) instead of relying on the XLA SPMD
    # partitioner. Param tree unchanged; None = the historical path.
    halo_mesh: Optional[Any] = None
    data_axis: str = "data"
    spatial_axis: str = "spatial"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        from cyclegan_tpu.models.modules import parity_conv
        from cyclegan_tpu.ops.padding import reflect_pad

        cfg = self.config
        in_dtype = x.dtype
        if self.dtype is not None:
            x = x.astype(self.dtype)

        reflect = self.pad_mode == "reflect"
        epilogue = reflect and self.pad_impl == "epilogue"
        fused = reflect and self.pad_impl in ("fused", "epilogue")
        halo = self.halo_mesh is not None

        def edge_conv(features, use_bias, name):
            return parity_conv(features, pad=3, reflect=reflect, fused=fused,
                               use_bias=use_bias, dtype=self.dtype, name=name,
                               halo_mesh=self.halo_mesh,
                               data_axis=self.data_axis,
                               spatial_axis=self.spatial_axis)

        filters = cfg.filters
        # c7s1-64 (model.py:138-145)
        y = reflect_pad(x, 3) if reflect and not fused and not halo else x
        y = edge_conv(filters, use_bias=False, name="Conv_0")(y)
        y = InstanceNorm(impl=self.norm_impl)(y)
        y = nn.relu(y)

        # Downsampling (model.py:148-152)
        for _ in range(cfg.num_downsampling_blocks):
            filters *= 2
            y = Downsample(filters, dtype=self.dtype, norm_impl=self.norm_impl)(y)

        # Residual trunk (model.py:155-156). Blocks are named explicitly so
        # remat=True (nn.remat auto-names modules "CheckpointResidualBlock_N")
        # keeps the same param-tree paths as remat=False.
        #
        # scan_blocks=True rolls the 9 identical blocks into one lax.scan
        # iteration (params stacked on a leading axis under "ScannedTrunk"):
        # ~9x less trunk HLO, much faster XLA compiles — the
        # compiler-friendly-control-flow trade. Convert checkpoints between
        # layouts with stack_trunk_params/unstack_trunk_params.
        if self.scan_blocks:
            if self.trunk_impl != "resnet":
                # ModelConfig.__post_init__ rejects this combo for the
                # config-driven path; guard direct construction too.
                raise ValueError(
                    "scan_blocks requires trunk_impl='resnet' (perturb "
                    "blocks need per-block mask salts; the scanned trunk "
                    f"shares one body), got {self.trunk_impl!r}"
                )
            trunk = nn.scan(
                _TrunkBody,
                variable_axes={"params": 0},
                split_rngs={"params": True},
                length=cfg.num_residual_blocks,
            )(
                dtype=self.dtype,
                norm_impl=self.norm_impl,
                remat=self.remat,
                pad_mode=self.pad_mode,
                pad_impl=self.pad_impl,
                halo_mesh=self.halo_mesh,
                data_axis=self.data_axis,
                spatial_axis=self.spatial_axis,
                name="ScannedTrunk",
            )
            y, _ = trunk(y, None)
        elif self.trunk_impl == "perturb":
            # Cheap tier: fixed-mask + 1x1-conv blocks. Named
            # "ResidualBlock_i" like the resnet trunk so the REST of the
            # tree (edge convs, down/upsamples) stays path-identical;
            # the kernels inside differ in shape, which model_meta's
            # recorded trunk_impl makes explicit.
            block_cls = PerturbBlock
            if self.remat:
                block_cls = nn.remat(PerturbBlock)
            for i in range(cfg.num_residual_blocks):
                y = block_cls(
                    salt=i,
                    dtype=self.dtype,
                    norm_impl=self.norm_impl,
                    name=f"ResidualBlock_{i}",
                )(y)
        else:
            block_cls = ResidualBlock
            if self.remat:
                block_cls = nn.remat(ResidualBlock)
            for i in range(cfg.num_residual_blocks):
                y = block_cls(
                    dtype=self.dtype,
                    norm_impl=self.norm_impl,
                    pad_mode=self.pad_mode,
                    pad_impl=self.pad_impl,
                    halo_mesh=self.halo_mesh,
                    data_axis=self.data_axis,
                    spatial_axis=self.spatial_axis,
                    name=f"ResidualBlock_{i}",
                )(y)

        # Upsampling (model.py:159-161). Under pad_impl="epilogue" the
        # LAST upsample fuses its IN>ReLU tail with the tail conv's
        # reflect-pad(3) (pad_after) — but only when the full-resolution
        # output slab is VMEM-eligible (epilogue_eligible; at the
        # default 256^2 it is not, and the tail keeps the ReflectConv
        # schedule — the trunk's 9 epilogue sites are the win there).
        # The branch is shape-dependent, never param-tree-dependent:
        # both layouts name the norm "InstanceNorm_0" and the tail conv
        # "Conv_1" with identical shapes.
        tail_pad_after = 0
        if epilogue:
            from cyclegan_tpu.ops.pallas.epilogue_kernel import (
                epilogue_eligible,
            )

            out_hw = y.shape[1] * (2 ** cfg.num_upsample_blocks)
            out_shape = (y.shape[0], out_hw, out_hw, cfg.filters)
            if epilogue_eligible(out_shape, self.dtype or y.dtype, 3):
                tail_pad_after = 3
        for i in range(cfg.num_upsample_blocks):
            filters //= 2
            last = i == cfg.num_upsample_blocks - 1
            y = Upsample(filters, dtype=self.dtype, norm_impl=self.norm_impl,
                         pad_after=tail_pad_after if last else 0,
                         upsample_impl=self.upsample_impl)(y)

        # Final block (model.py:164-167): bias on, tanh
        if tail_pad_after:
            # input pre-padded by the upsample epilogue: plain VALID conv
            y = parity_conv(self.out_channels, pad=3, reflect=True,
                            fused=False, use_bias=True, dtype=self.dtype,
                            name="Conv_1")(y)
        else:
            y = reflect_pad(y, 3) if reflect and not fused and not halo else y
            y = edge_conv(self.out_channels, use_bias=True, name="Conv_1")(y)
        y = jnp.tanh(y)
        return y.astype(in_dtype)


def stack_trunk_params(params, num_blocks: int):
    """Convert an unrolled-trunk param tree (ResidualBlock_0..N-1) to the
    scan_blocks=True layout (leaves stacked on a leading axis under
    ScannedTrunk/ResidualBlock_0). Enables loading a checkpoint trained
    without --scan_blocks into a scanned generator."""
    import jax

    inner = dict(params["params"])
    blocks = [inner.pop(f"ResidualBlock_{i}") for i in range(num_blocks)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *blocks)
    inner["ScannedTrunk"] = {"ResidualBlock_0": stacked}
    return {**params, "params": inner}


def unstack_trunk_params(params, num_blocks: int):
    """Inverse of `stack_trunk_params`."""
    import jax

    inner = dict(params["params"])
    trunk = dict(inner.pop("ScannedTrunk"))
    stacked = trunk.pop("ResidualBlock_0")
    if trunk:
        raise ValueError(
            f"unexpected entries under ScannedTrunk: {sorted(trunk)}"
        )
    for i in range(num_blocks):
        inner[f"ResidualBlock_{i}"] = jax.tree.map(lambda x: x[i], stacked)
    return {**params, "params": inner}
