"""ResNet-9 CycleGAN generator as a Flax module.

TPU-native equivalent of the reference's `get_generator`
(/root/reference/cyclegan/model.py:129-169):

  c7s1-64 (reflect-pad 3, Conv7x7 no-bias, IN, ReLU)
  2 downsampling blocks doubling filters 64>128>256
  9 residual blocks @256ch
  2 upsampling blocks halving 256>128>64
  reflect-pad 3, Conv7x7 -> 3ch (valid, WITH bias — Keras default), tanh

~11.4M parameters at the default sizes. Optional `remat` wraps each
residual block in jax.checkpoint to trade FLOPs for HBM at 512^2.
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp

from cyclegan_tpu.config import GeneratorConfig
from cyclegan_tpu.models.modules import (
    Downsample,
    InstanceNorm,
    ResidualBlock,
    Upsample,
    init_normal,
)


class ResNetGenerator(nn.Module):
    config: GeneratorConfig = GeneratorConfig()
    out_channels: int = 3
    dtype: Optional[Any] = None
    remat: bool = False
    norm_impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        from cyclegan_tpu.ops.padding import reflect_pad

        cfg = self.config
        in_dtype = x.dtype
        if self.dtype is not None:
            x = x.astype(self.dtype)

        filters = cfg.filters
        # c7s1-64 (model.py:138-145)
        y = reflect_pad(x, 3)
        y = nn.Conv(
            filters,
            (7, 7),
            padding="VALID",
            use_bias=False,
            kernel_init=init_normal,
            dtype=self.dtype,
        )(y)
        y = InstanceNorm(impl=self.norm_impl)(y)
        y = nn.relu(y)

        # Downsampling (model.py:148-152)
        for _ in range(cfg.num_downsampling_blocks):
            filters *= 2
            y = Downsample(filters, dtype=self.dtype, norm_impl=self.norm_impl)(y)

        # Residual trunk (model.py:155-156). Blocks are named explicitly so
        # remat=True (nn.remat auto-names modules "CheckpointResidualBlock_N")
        # keeps the same param-tree paths as remat=False.
        block_cls = ResidualBlock
        if self.remat:
            block_cls = nn.remat(ResidualBlock)
        for i in range(cfg.num_residual_blocks):
            y = block_cls(
                dtype=self.dtype,
                norm_impl=self.norm_impl,
                name=f"ResidualBlock_{i}",
            )(y)

        # Upsampling (model.py:159-161)
        for _ in range(cfg.num_upsample_blocks):
            filters //= 2
            y = Upsample(filters, dtype=self.dtype, norm_impl=self.norm_impl)(y)

        # Final block (model.py:164-167): bias on, tanh
        y = reflect_pad(y, 3)
        y = nn.Conv(
            self.out_channels,
            (7, 7),
            padding="VALID",
            use_bias=True,
            kernel_init=init_normal,
            dtype=self.dtype,
        )(y)
        y = jnp.tanh(y)
        return y.astype(in_dtype)
