"""Building-block Flax modules for the CycleGAN model zoo.

TPU-native equivalents of the reference's Keras blocks
(/root/reference/cyclegan/model.py:36-126). Parameters are kept in
float32; compute may run in bfloat16 (`dtype`) so convs hit the MXU at
full rate while instance-norm statistics stay in float32.

Initialization matches the reference: conv kernels and instance-norm
gamma ~ N(0, 0.02) (model.py:10-11 — note gamma centred at 0, a
reference quirk reproduced deliberately), biases/betas zero.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from cyclegan_tpu.ops.norm import instance_norm, instance_norm_act_pad
from cyclegan_tpu.ops.padding import reflect_conv, reflect_pad
from cyclegan_tpu.ops.upsample import (
    conv_transpose_up2,
    upsample_norm_relu_pad,
    upsample_norm_relu_pad_int8,
)

Dtype = Any

# N(0, 0.02) for conv kernels and IN gammas (reference model.py:10-11).
init_normal = nn.initializers.normal(stddev=0.02)


class ReflectConv(nn.Module):
    """Conv with reflect-padding semantics, scheduled as zero-pad conv +
    fusible border corrections (ops/padding.py:reflect_conv).

    Drop-in for the reflect-pad + nn.Conv(VALID) pair: same "kernel" /
    "bias" param names, shapes, and init, so checkpoints interchange with
    the pad_impl="pad" layout when given the same module `name` (the
    callers pass name="Conv_N" to pin the auto-assigned path). Numerics
    agree to fp tolerance (border sums re-associated), not bitwise —
    pad_impl="pad" stays the parity default.
    """

    features: int
    pad: int  # kernel is (2*pad+1)^2
    use_bias: bool = False
    dtype: Optional[Dtype] = None

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        ksz = 2 * self.pad + 1
        kernel = self.param(
            "kernel", init_normal, (ksz, ksz, x.shape[-1], self.features),
            jnp.float32,
        )
        bias = (
            self.param(
                "bias", nn.initializers.zeros_init(), (self.features,),
                jnp.float32,
            )
            if self.use_bias
            else None
        )
        if self.dtype is not None:
            x = x.astype(self.dtype)
            kernel = kernel.astype(self.dtype)
            bias = bias.astype(self.dtype) if bias is not None else None
        y = reflect_conv(x, kernel, self.pad)
        if bias is not None:
            y = y + bias
        return y


class HaloConv(nn.Module):
    """Stride-1 conv whose H-axis halo exchange is EXPLICIT: the body
    runs inside shard_map on row-sharded [N, H_local, W, C] blocks and
    trades exactly the boundary rows a VALID conv needs over
    lax.ppermute (parallel/halo.py:spatial_sharded_conv), instead of
    whatever the XLA SPMD partitioner synthesizes.

    Drop-in for the reflect-pad + nn.Conv(VALID) pair (mode="reflect")
    and for nn.Conv(SAME) (mode="zero"): same "kernel"/"bias" param
    names, shapes, and init, so checkpoints interchange with the
    spatial_impl="xla" layouts when given the same module `name`.

    The shard_map island only engages when a mesh with a >1 spatial
    axis is bound AND the module is not initializing (create_state's
    batch-1 dummy init could never satisfy the in_specs); otherwise the
    module computes the identical plain pad+conv, so a halo checkpoint
    restores and serves on a single device unchanged.
    """

    features: int
    kernel_size: Tuple[int, int] = (3, 3)
    mode: str = "reflect"  # "reflect" | "zero"
    use_bias: bool = False
    dtype: Optional[Dtype] = None
    mesh: Any = None  # jax.sharding.Mesh; None = plain path
    data_axis: str = "data"
    spatial_axis: str = "spatial"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        from jax import lax

        kh, kw = self.kernel_size
        kernel = self.param(
            "kernel", init_normal, (kh, kw, x.shape[-1], self.features),
            jnp.float32,
        )
        bias = (
            self.param(
                "bias", nn.initializers.zeros_init(), (self.features,),
                jnp.float32,
            )
            if self.use_bias
            else None
        )
        if self.dtype is not None:
            x = x.astype(self.dtype)
            kernel = kernel.astype(self.dtype)
            bias = bias.astype(self.dtype) if bias is not None else None
        engaged = (
            self.mesh is not None
            and not self.is_initializing()
            and dict(zip(self.mesh.axis_names, self.mesh.devices.shape))
            .get(self.spatial_axis, 1) > 1
        )
        if engaged:
            from cyclegan_tpu.parallel.halo import spatial_sharded_conv

            y = spatial_sharded_conv(
                x, kernel, self.mesh, data_axis=self.data_axis,
                spatial_axis=self.spatial_axis, mode=self.mode,
            )
        else:
            if self.mode == "reflect":
                ph, pw = kh // 2, kw // 2
                y = (jnp.pad(x, ((0, 0), (ph, ph), (pw, pw), (0, 0)),
                             mode="reflect") if ph or pw else x)
            else:
                ph_lo, ph_hi = (kh - 1) // 2, (kh - 1) - (kh - 1) // 2
                pw_lo, pw_hi = (kw - 1) // 2, (kw - 1) - (kw - 1) // 2
                y = jnp.pad(
                    x, ((0, 0), (ph_lo, ph_hi), (pw_lo, pw_hi), (0, 0)))
            y = lax.conv_general_dilated(
                y, kernel, window_strides=(1, 1), padding="VALID",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
        if bias is not None:
            y = y + bias
        return y


def parity_conv(features: int, pad: int, reflect: bool, fused: bool,
                use_bias: bool, dtype: Optional[Dtype], name: str,
                halo_mesh: Any = None, data_axis: str = "data",
                spatial_axis: str = "spatial"):
    """The conv factory for every reference reflect-pad site, shared by
    ResidualBlock and ResNetGenerator so the checkpoint-compat invariants
    (pinned "Conv_N" names, VALID-for-reflect vs built-in-SAME-for-zero)
    have one author. Kernel size is (2*pad+1)^2 — the only geometries the
    reference uses at these sites (3x3/pad-1, 7x7/pad-3; model.py:14-33).
    `halo_mesh` routes the site through HaloConv (explicit ppermute halo
    under spatial_impl='halo') — identical param tree either way.
    """
    ksz = 2 * pad + 1
    if halo_mesh is not None:
        return HaloConv(
            features, kernel_size=(ksz, ksz),
            mode="reflect" if reflect else "zero", use_bias=use_bias,
            dtype=dtype, mesh=halo_mesh, data_axis=data_axis,
            spatial_axis=spatial_axis, name=name,
        )
    if fused:
        return ReflectConv(
            features, pad=pad, use_bias=use_bias, dtype=dtype, name=name
        )
    return nn.Conv(
        features,
        (ksz, ksz),
        padding="VALID" if reflect else "SAME",
        use_bias=use_bias,
        kernel_init=init_normal,
        dtype=dtype,
        name=name,
    )


class InstanceNorm(nn.Module):
    """Learned instance normalization (reference: tfa InstanceNormalization).

    eps=1e-3 matches tfa's GroupNormalization default; gamma init
    N(0, 0.02) matches model.py:11.
    """

    eps: float = 1e-3
    impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        ch = x.shape[-1]
        scale = self.param("scale", init_normal, (ch,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (ch,), jnp.float32)
        return instance_norm(x, scale, bias, eps=self.eps, impl=self.impl)


class FusedNormReluPad(nn.Module):
    """A conv epilogue as ONE op: instance-norm -> LeakyReLU(slope) ->
    reflect-pad(pad), emitting the consumer's input directly
    (ops/norm.py:instance_norm_act_pad — Pallas kernel when the slab is
    VMEM-eligible, XLA composition otherwise). negative_slope=0.0 is
    the residual-block ReLU form; 0.2 with pad=0 is the discriminator
    trunk tail.

    Same "scale"/"bias" param names, shapes, and init as InstanceNorm,
    so a module given the name the unfused layout auto-assigns
    ("InstanceNorm_N") keeps the checkpoint tree identical across
    pad_impl settings.
    """

    pad: int
    eps: float = 1e-3
    impl: str = "auto"
    negative_slope: float = 0.0

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        ch = x.shape[-1]
        scale = self.param("scale", init_normal, (ch,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (ch,), jnp.float32)
        return instance_norm_act_pad(
            x, scale, bias, pad=self.pad, eps=self.eps, impl=self.impl,
            negative_slope=self.negative_slope,
        )


class ResidualBlock(nn.Module):
    """reflect-pad(1) > Conv3x3 valid > IN > ReLU > reflect-pad(1) > Conv3x3
    > IN > +skip  (reference model.py:36-74). Filters inferred from input
    channels (model.py:46); convs have no bias (model.py:44).

    pad_mode="zero" swaps each reflect-pad+VALID conv for the conv's
    built-in SAME zero padding: identical kernel shapes (checkpoints
    interchange), different border semantics — the TPU perf option
    (ModelConfig.pad_mode). pad_impl="fused" keeps reflect semantics but
    schedules each site as ReflectConv (no materialized padded copy).
    pad_impl="epilogue" additionally collapses the middle
    IN > ReLU > reflect-pad chain into FusedNormReluPad (the Pallas
    epilogue kernel when VMEM-eligible), so Conv_1 consumes the padded
    slab directly as a plain VALID conv; the leading pad site stays
    ReflectConv-scheduled. All three layouts share one param tree.
    """

    dtype: Optional[Dtype] = None
    norm_impl: str = "auto"
    pad_mode: str = "reflect"
    pad_impl: str = "pad"
    halo_mesh: Any = None  # spatial_impl="halo": explicit-halo conv sites
    data_axis: str = "data"
    spatial_axis: str = "spatial"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        filters = x.shape[-1]
        reflect = self.pad_mode == "reflect"
        epilogue = reflect and self.pad_impl == "epilogue"
        fused = reflect and self.pad_impl in ("fused", "epilogue")
        halo = self.halo_mesh is not None

        def conv(name: str):
            return parity_conv(filters, pad=1, reflect=reflect, fused=fused,
                               use_bias=False, dtype=self.dtype, name=name,
                               halo_mesh=self.halo_mesh,
                               data_axis=self.data_axis,
                               spatial_axis=self.spatial_axis)

        y = reflect_pad(x, 1) if reflect and not fused and not halo else x
        y = conv("Conv_0")(y)
        if epilogue:
            y = FusedNormReluPad(pad=1, impl=self.norm_impl,
                                 name="InstanceNorm_0")(y)
            # Conv_1's input is pre-padded by the epilogue: plain VALID
            # conv, identical params to the other layouts.
            y = parity_conv(filters, pad=1, reflect=True, fused=False,
                            use_bias=False, dtype=self.dtype,
                            name="Conv_1")(y)
        else:
            y = InstanceNorm(impl=self.norm_impl, name="InstanceNorm_0")(y)
            y = nn.relu(y)
            y = reflect_pad(y, 1) if reflect and not fused and not halo else y
            y = conv("Conv_1")(y)
        y = InstanceNorm(impl=self.norm_impl, name="InstanceNorm_1")(y)
        return x + y


# Root seed for the perturb trunk's fixed masks (arXiv number of the
# Perturbative GAN paper). Part of the architecture contract: the masks
# are pure functions of (seed, block salt, layer, activation shape), so
# every reconstruction of the module — G and F, train and serve, any
# host in a mesh — sees bit-identical masks without storing them in the
# checkpoint.
PERTURB_SEED = 1902


def perturb_mask(salt: int, layer: int, shape) -> jnp.ndarray:
    """The fixed N(0,1) perturbation mask for one perturb-conv site.

    Derived in-trace from a static key: XLA constant-folds it, so it
    costs HBM for one (H, W, C) constant per site and zero per-step
    compute. NOT a parameter — the Perturbative GAN result is that the
    perturbations stay frozen while only the 1x1 combinations learn,
    and keeping it out of the param tree means no checkpoint bloat and
    no optimizer state for it.
    """
    key = jax.random.fold_in(
        jax.random.fold_in(jax.random.PRNGKey(PERTURB_SEED), salt), layer
    )
    return jax.random.normal(key, shape, jnp.float32)


class PerturbBlock(nn.Module):
    """Perturbative-GAN residual block (arXiv:1902.01514): each of the
    reference block's 3x3 convs becomes `Conv1x1(ReLU(x + fixed_mask))` —
    a frozen random perturbation provides the spatial mixing and a
    learned 1x1 conv recombines channels, cutting the conv FLOPs 9x per
    layer. Layout mirrors ResidualBlock (same module names Conv_0/1,
    InstanceNorm_0/1, same no-bias/IN/skip structure) but the kernels
    are (1, 1, f, f) — a DIFFERENT param tree, which is why checkpoints
    record trunk_impl in model_meta instead of silently interchanging.

    `salt` must be the block index: each block gets distinct masks (the
    paper's per-layer independent perturbations), which is also why the
    perturb trunk cannot ride the scanned-trunk path (one shared body).
    """

    salt: int
    dtype: Optional[Dtype] = None
    norm_impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        filters = x.shape[-1]

        def perturb_conv(layer: int, name: str, y: jnp.ndarray) -> jnp.ndarray:
            mask = perturb_mask(self.salt, layer, y.shape[1:])
            if self.dtype is not None:
                y = y.astype(self.dtype)
                mask = mask.astype(self.dtype)
            y = nn.relu(y + mask)
            return nn.Conv(
                filters,
                (1, 1),
                padding="VALID",
                use_bias=False,
                kernel_init=init_normal,
                dtype=self.dtype,
                name=name,
            )(y)

        y = perturb_conv(0, "Conv_0", x)
        y = InstanceNorm(impl=self.norm_impl, name="InstanceNorm_0")(y)
        y = perturb_conv(1, "Conv_1", y)
        y = InstanceNorm(impl=self.norm_impl, name="InstanceNorm_1")(y)
        return x + y


def _fusable_slope(activation) -> Optional[float]:
    """LeakyReLU slope of an activation the fused epilogue can serve:
    0.0 for nn.relu, the bound negative_slope for a
    functools.partial(nn.leaky_relu, ...), None for anything else."""
    if activation is nn.relu:
        return 0.0
    if (isinstance(activation, functools.partial)
            and activation.func is nn.leaky_relu):
        return float(activation.keywords.get("negative_slope", 0.01))
    return None


def _norm_act_epilogue(y, *, pad_after, norm_impl, activation, fuse=False):
    """Shared IN > activation tail of Downsample/Upsample. pad_after > 0
    fuses the chain into FusedNormReluPad (reflect-padded output for a
    downstream VALID conv — e.g. the generator's tail Conv7x7 consuming
    the last upsample); fuse=True requests the same one-op form without
    a pad (the discriminator's IN > LeakyReLU trunk tails), engaging
    whenever the activation has a fused form (ReLU or a bound
    leaky_relu — _fusable_slope) and quietly staying unfused otherwise.
    Either way the module is named "InstanceNorm_0", the name the
    unfused layout auto-assigns, so the param tree is identical."""
    slope = _fusable_slope(activation)
    if pad_after or (fuse and slope is not None):
        if slope is None:
            raise ValueError(
                "pad_after requires a ReLU/LeakyReLU epilogue (got "
                f"{activation!r}); only IN>act>reflect-pad has a fused form"
            )
        return FusedNormReluPad(pad=pad_after, impl=norm_impl,
                                negative_slope=slope,
                                name="InstanceNorm_0")(y)
    y = InstanceNorm(impl=norm_impl, name="InstanceNorm_0")(y)
    if activation is not None:
        y = activation(y)
    return y


class Downsample(nn.Module):
    """Conv (stride 2 default, SAME, no bias) > IN > optional activation
    (reference model.py:77-100). pad_after > 0 fuses the IN > ReLU tail
    with a reflect-pad of the output; fuse_epilogue=True fuses an
    unpadded IN > (Leaky)ReLU tail into one op (see _norm_act_epilogue)
    — the discriminator's pad_impl="epilogue" trunk layout.
    """

    filters: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (2, 2)
    activation: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = nn.relu
    dtype: Optional[Dtype] = None
    norm_impl: str = "auto"
    pad_after: int = 0
    fuse_epilogue: bool = False
    halo_mesh: Any = None  # spatial_impl="halo": stride-1 sites only
    data_axis: str = "data"
    spatial_axis: str = "spatial"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.halo_mesh is not None and self.strides == (1, 1):
            # The stride-1 SAME conv is the only Downsample geometry with
            # a halo to trade (stride-2 windows never straddle shard
            # boundaries the same way — those stay on the XLA partitioner).
            # nn.Conv auto-names its site "Conv_0" inside this module, the
            # name HaloConv must pin for checkpoint interchange.
            y = HaloConv(
                self.filters, kernel_size=self.kernel_size, mode="zero",
                use_bias=False, dtype=self.dtype, mesh=self.halo_mesh,
                data_axis=self.data_axis, spatial_axis=self.spatial_axis,
                name="Conv_0",
            )(x)
        else:
            y = nn.Conv(
                self.filters,
                self.kernel_size,
                strides=self.strides,
                padding="SAME",
                use_bias=False,
                kernel_init=init_normal,
                dtype=self.dtype,
            )(x)
        return _norm_act_epilogue(
            y, pad_after=self.pad_after, norm_impl=self.norm_impl,
            activation=self.activation, fuse=self.fuse_epilogue,
        )


class ZeroSkipKernel(nn.Module):
    """Param holder for the zero-skip Upsample tiers: declares the SAME
    "kernel" param — (3, 3, Cin, features), N(0, 0.02) init, float32 —
    that nn.ConvTranspose would. Callers pin it to the name the dense
    layout auto-assigns ("ConvTranspose_0"), so all three upsample_impl
    tiers share one checkpoint tree and checkpoints interchange."""

    features: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        return self.param(
            "kernel", init_normal, (3, 3, x.shape[-1], self.features),
            jnp.float32,
        )


class QuantZeroSkipKernel(nn.Module):
    """Param holder for the inference-only int8 upsample tier: declares
    "kernel" as the QUANTIZED dict — {"int8_q": int8 (3, 3, Cin,
    features), "int8_scale": f32 (1, 1, 1, features)} — exactly the
    structure serve.engine.quantize_params_int8 produces for the dense
    tier's ConvTranspose kernel (flax validates bound params by
    flattened leaf shapes, so a dict-valued param binds cleanly).
    Callers pin the name "ConvTranspose_0" so the quantized serving
    tree drops in with NO remapping: quantize the dense checkpoint,
    keep the upsample leaves as dicts, apply."""

    features: int

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> dict:
        def init_q(_rng):
            return {
                "int8_q": jnp.zeros(
                    (3, 3, x.shape[-1], self.features), jnp.int8),
                "int8_scale": jnp.ones(
                    (1, 1, 1, self.features), jnp.float32),
            }

        return self.param("kernel", init_q)


class NormParams(nn.Module):
    """Param holder declaring InstanceNorm's "scale"/"bias" (same names,
    shapes, init) without applying the op — for fused kernels that
    consume the raw params. Callers pin it to the name the unfused
    layout auto-assigns ("InstanceNorm_0")."""

    features: int

    @nn.compact
    def __call__(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        scale = self.param("scale", init_normal, (self.features,), jnp.float32)
        bias = self.param(
            "bias", nn.initializers.zeros_init(), (self.features,), jnp.float32
        )
        return scale, bias


class Upsample(nn.Module):
    """ConvTranspose (3x3, stride 2, SAME, no bias) > IN > optional
    activation (reference model.py:103-126). Output spatial dims exactly
    double the input, matching TF Conv2DTranspose SAME semantics.
    pad_after > 0 fuses the IN > ReLU tail with a reflect-pad of the
    output (see _norm_act_epilogue) — the generator uses it on the last
    upsample under pad_impl="epilogue" so the tail Conv7x7 consumes the
    padded slab VALID, with no materialized pad copy.

    upsample_impl selects the transposed-conv engine (GANAX output
    decomposition — ops/upsample.py):
      "dense":          nn.ConvTranspose on the zero-dilated input (the
                        parity reference; ~4x the live MACs).
      "zeroskip":       four per-phase dense convs + depth-to-space
                        interleave, pure XLA.
      "zeroskip_fused": the Pallas kernel fusing phase convs > IN > ReLU
                        (> reflect-pad) in one VMEM residency
                        (ops/pallas/upsample_kernel.py), XLA zeroskip
                        fallback where the slab is ineligible.
      "zeroskip_fused_int8": the inference-only serve-tier form — the
                        kernel param is the QUANTIZED dict
                        (QuantZeroSkipKernel) and the weights stay int8
                        into the Pallas kernel (in-kernel dequant); no
                        VJP exists on this path.
    The zero-skip tiers require the default 3x3/stride-2 geometry and
    declare the identical param tree via ZeroSkipKernel/NormParams
    (int8: the quantized image of that tree), so checkpoints
    interchange across all tiers.
    """

    filters: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (2, 2)
    activation: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = nn.relu
    dtype: Optional[Dtype] = None
    norm_impl: str = "auto"
    pad_after: int = 0
    upsample_impl: str = "dense"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        if self.upsample_impl == "dense":
            y = nn.ConvTranspose(
                self.filters,
                self.kernel_size,
                strides=self.strides,
                padding="SAME",
                use_bias=False,
                kernel_init=init_normal,
                dtype=self.dtype,
            )(x)
            return _norm_act_epilogue(
                y, pad_after=self.pad_after, norm_impl=self.norm_impl,
                activation=self.activation,
            )
        if self.upsample_impl not in (
                "zeroskip", "zeroskip_fused", "zeroskip_fused_int8"):
            raise ValueError(
                f"unknown upsample_impl {self.upsample_impl!r}"
            )
        if self.kernel_size != (3, 3) or self.strides != (2, 2):
            raise ValueError(
                "zero-skip upsampling is specialized to the reference "
                "3x3/stride-2 geometry; got kernel_size="
                f"{self.kernel_size}, strides={self.strides}"
            )
        if self.upsample_impl == "zeroskip_fused_int8":
            # Inference-only tier: the kernel param IS the quantized
            # dict; weights stay int8 end-to-end (in-kernel dequant on
            # TPU — ops/upsample.py upsample_norm_relu_pad_int8).
            if self.activation is not nn.relu:
                raise ValueError(
                    "upsample_impl='zeroskip_fused_int8' requires the "
                    f"ReLU epilogue (got {self.activation!r})"
                )
            qkernel = QuantZeroSkipKernel(
                self.filters, name="ConvTranspose_0")(x)
            if self.dtype is not None:
                x = x.astype(self.dtype)
            scale, bias = NormParams(self.filters, name="InstanceNorm_0")()
            return upsample_norm_relu_pad_int8(
                x, qkernel["int8_q"], qkernel["int8_scale"], scale, bias,
                pad=self.pad_after, eps=1e-3, norm_impl=self.norm_impl,
            )
        kernel = ZeroSkipKernel(self.filters, name="ConvTranspose_0")(x)
        if self.dtype is not None:
            x = x.astype(self.dtype)
            kernel = kernel.astype(self.dtype)
        if self.upsample_impl == "zeroskip":
            y = conv_transpose_up2(x, kernel, impl="zeroskip")
            return _norm_act_epilogue(
                y, pad_after=self.pad_after, norm_impl=self.norm_impl,
                activation=self.activation,
            )
        # zeroskip_fused: the whole block — phase convs, IN, ReLU, and
        # any trailing reflect-pad — is one op.
        if self.activation is not nn.relu:
            raise ValueError(
                "upsample_impl='zeroskip_fused' requires the ReLU "
                f"epilogue (got {self.activation!r})"
            )
        scale, bias = NormParams(self.filters, name="InstanceNorm_0")()
        return upsample_norm_relu_pad(
            x, kernel, scale, bias, pad=self.pad_after, eps=1e-3,
            impl="zeroskip_fused",
        )
