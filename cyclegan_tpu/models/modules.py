"""Building-block Flax modules for the CycleGAN model zoo.

TPU-native equivalents of the reference's Keras blocks
(/root/reference/cyclegan/model.py:36-126). Parameters are kept in
float32; compute may run in bfloat16 (`dtype`) so convs hit the MXU at
full rate while instance-norm statistics stay in float32.

Initialization matches the reference: conv kernels and instance-norm
gamma ~ N(0, 0.02) (model.py:10-11 — note gamma centred at 0, a
reference quirk reproduced deliberately), biases/betas zero.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from cyclegan_tpu.ops.norm import instance_norm
from cyclegan_tpu.ops.padding import reflect_pad

Dtype = Any

# N(0, 0.02) for conv kernels and IN gammas (reference model.py:10-11).
init_normal = nn.initializers.normal(stddev=0.02)


class InstanceNorm(nn.Module):
    """Learned instance normalization (reference: tfa InstanceNormalization).

    eps=1e-3 matches tfa's GroupNormalization default; gamma init
    N(0, 0.02) matches model.py:11.
    """

    eps: float = 1e-3
    impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        ch = x.shape[-1]
        scale = self.param("scale", init_normal, (ch,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros_init(), (ch,), jnp.float32)
        return instance_norm(x, scale, bias, eps=self.eps, impl=self.impl)


class ResidualBlock(nn.Module):
    """reflect-pad(1) > Conv3x3 valid > IN > ReLU > reflect-pad(1) > Conv3x3
    > IN > +skip  (reference model.py:36-74). Filters inferred from input
    channels (model.py:46); convs have no bias (model.py:44).

    pad_mode="zero" swaps each reflect-pad+VALID conv for the conv's
    built-in SAME zero padding: identical kernel shapes (checkpoints
    interchange), different border semantics — the TPU perf option
    (ModelConfig.pad_mode).
    """

    dtype: Optional[Dtype] = None
    norm_impl: str = "auto"
    pad_mode: str = "reflect"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        filters = x.shape[-1]
        reflect = self.pad_mode == "reflect"
        y = reflect_pad(x, 1) if reflect else x
        y = nn.Conv(
            filters,
            (3, 3),
            padding="VALID" if reflect else "SAME",
            use_bias=False,
            kernel_init=init_normal,
            dtype=self.dtype,
        )(y)
        y = InstanceNorm(impl=self.norm_impl)(y)
        y = nn.relu(y)
        y = reflect_pad(y, 1) if reflect else y
        y = nn.Conv(
            filters,
            (3, 3),
            padding="VALID" if reflect else "SAME",
            use_bias=False,
            kernel_init=init_normal,
            dtype=self.dtype,
        )(y)
        y = InstanceNorm(impl=self.norm_impl)(y)
        return x + y


class Downsample(nn.Module):
    """Conv (stride 2 default, SAME, no bias) > IN > optional activation
    (reference model.py:77-100).
    """

    filters: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (2, 2)
    activation: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = nn.relu
    dtype: Optional[Dtype] = None
    norm_impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        y = nn.Conv(
            self.filters,
            self.kernel_size,
            strides=self.strides,
            padding="SAME",
            use_bias=False,
            kernel_init=init_normal,
            dtype=self.dtype,
        )(x)
        y = InstanceNorm(impl=self.norm_impl)(y)
        if self.activation is not None:
            y = self.activation(y)
        return y


class Upsample(nn.Module):
    """ConvTranspose (3x3, stride 2, SAME, no bias) > IN > optional
    activation (reference model.py:103-126). Output spatial dims exactly
    double the input, matching TF Conv2DTranspose SAME semantics.
    """

    filters: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (2, 2)
    activation: Optional[Callable[[jnp.ndarray], jnp.ndarray]] = nn.relu
    dtype: Optional[Dtype] = None
    norm_impl: str = "auto"

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        y = nn.ConvTranspose(
            self.filters,
            self.kernel_size,
            strides=self.strides,
            padding="SAME",
            use_bias=False,
            kernel_init=init_normal,
            dtype=self.dtype,
        )(x)
        y = InstanceNorm(impl=self.norm_impl)(y)
        if self.activation is not None:
            y = self.activation(y)
        return y
