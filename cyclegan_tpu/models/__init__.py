"""Flax model zoo: ResNet generators and PatchGAN discriminators.

TPU-native re-design of /root/reference/cyclegan/model.py.
"""

from cyclegan_tpu.models.modules import (
    InstanceNorm,
    PerturbBlock,
    ResidualBlock,
    Downsample,
    Upsample,
)
from cyclegan_tpu.models.generator import (
    ResNetGenerator,
    stack_trunk_params,
    unstack_trunk_params,
)
from cyclegan_tpu.models.discriminator import PatchGANDiscriminator

__all__ = [
    "InstanceNorm",
    "PerturbBlock",
    "ResidualBlock",
    "Downsample",
    "Upsample",
    "ResNetGenerator",
    "PatchGANDiscriminator",
    "stack_trunk_params",
    "unstack_trunk_params",
]
