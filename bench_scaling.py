"""Weak-scaling benchmark over the visible device mesh.

BASELINE.md's scaling target: >=90% weak-scaling efficiency at global
batch 256 on a v4-32 pod. This harness measures it on whatever devices
are visible: per-device batch is held fixed while the mesh grows from 1
device to all of them, so ideal scaling doubles images/sec with device
count. Efficiency = (ips_N / N) / ips_1.

The reference cannot express this measurement at all — MirroredStrategy
publishes no scaling counters; its only timer is the per-epoch `elapse`
scalar (/root/reference/main.py:388-392).

Run on a TPU slice:   python bench_scaling.py --batch 8 --dtype bfloat16
Smoke-run on CPU:     JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                        python bench_scaling.py --image 32 --tiny

Prints ONE JSON line: {"metric": "weak_scaling_efficiency", ...}.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from cyclegan_tpu.utils.platform import ensure_platform_from_env


def measure(n_devices: int, args) -> float:
    """images/sec on the first n_devices devices, scan-mode."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cyclegan_tpu.config import (
        Config,
        DiscriminatorConfig,
        GeneratorConfig,
        ModelConfig,
        TrainConfig,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cyclegan_tpu.parallel import make_mesh_plan
    from cyclegan_tpu.parallel.mesh import replicated
    from cyclegan_tpu.train import create_state, make_train_step

    gen_cfg = (
        GeneratorConfig(filters=8, num_residual_blocks=2)
        if args.tiny
        else GeneratorConfig()
    )
    disc_cfg = DiscriminatorConfig(filters=8) if args.tiny else DiscriminatorConfig()
    cfg = Config(
        model=ModelConfig(
            generator=gen_cfg,
            discriminator=disc_cfg,
            compute_dtype=args.dtype,
            image_size=args.image,
        ),
        train=TrainConfig(batch_size=args.batch),
    )
    plan = make_mesh_plan(cfg.parallel, jax.devices()[:n_devices])
    global_batch = n_devices * args.batch

    state = jax.device_put(
        create_state(cfg, jax.random.PRNGKey(0)), replicated(plan)
    )
    step_fn = make_train_step(cfg, global_batch)
    rep = replicated(plan)
    # Stacked inputs are [k, batch, ...]: the scan axis k leads, so the
    # batch shard spec moves to dim 1 (images and weights alike).
    bs = NamedSharding(plan.mesh, P(None, plan.data_axis))

    k = args.scan_steps

    def multi_step(state, xs, ys, wts):
        def body(st, inp):
            st, m = step_fn(st, *inp)
            return st, m["loss_G/total"]
        state, losses = jax.lax.scan(body, state, (xs, ys, wts))
        return state, losses[-1]

    step = jax.jit(
        multi_step,
        in_shardings=(rep, bs, bs, bs),
        out_shardings=(rep, rep),
        donate_argnums=(0,),
    )

    rng = np.random.RandomState(0)
    s = args.image
    xs = jnp.asarray(rng.rand(k, global_batch, s, s, 3).astype(np.float32) * 2 - 1)
    ys = jnp.asarray(rng.rand(k, global_batch, s, s, 3).astype(np.float32) * 2 - 1)
    wts = jnp.ones((k, global_batch), jnp.float32)

    state, last = step(state, xs, ys, wts)
    float(jax.device_get(last))  # execution fence (not block_until_ready)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        state, last = step(state, xs, ys, wts)
    float(jax.device_get(last))
    dt = time.perf_counter() - t0
    return 2 * global_batch * k * args.iters / dt


def _emit(results, n_all, args) -> None:
    results = dict(results)
    max_n = max(results) if results else 0
    scaled = max_n > 1 and 1 in results
    if scaled:
        eff = (results[max_n] / max_n) / results[1]
    elif results and n_all == 1:
        eff = 1.0  # single-device platform: nothing to scale over
    else:
        eff = 0.0  # multi-device platform but no scaling was measured
    line = {
        "metric": "weak_scaling_efficiency",
        "value": round(eff, 4),
        "unit": "fraction",
        "vs_baseline": round(eff / 0.90, 3),  # target: >=90%
        "devices": n_all,
        "measured_devices": max_n,
        "per_device_batch": args.batch,
        "images_per_sec": {str(k): round(v, 2) for k, v in results.items()},
    }
    if not results:
        line["error"] = "no mesh size completed"
    elif not scaled and n_all > 1:
        line["error"] = "only the 1-device size completed; no scaling measured"
    print(json.dumps(line), flush=True)


def main(args) -> None:
    ensure_platform_from_env()
    from cyclegan_tpu.utils.axon_compat import cli_startup

    cli_startup()  # local-compile workaround + relay diagnosis
    from cyclegan_tpu.utils.platform import enable_compilation_cache

    enable_compilation_cache()

    results = {}

    # Same hang/kill protection as bench.py: one compile wedging — or the
    # driver's SIGTERM — must not swallow the sizes that already completed.
    import os
    import signal
    import threading

    budget = float(os.environ.get("BENCH_TIME_BUDGET_S", "480"))
    n_all_box = [0]
    emit_lock = threading.Lock()
    emitted = [False]

    def emit_once() -> bool:
        with emit_lock:
            if emitted[0]:
                return False
            emitted[0] = True
        _emit(results, n_all_box[0], args)
        return True

    def on_kill(signum, frame):
        # Disarm both first: nested delivery would deadlock the
        # non-reentrant emit lock on the main thread.
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        signal.signal(signal.SIGALRM, signal.SIG_IGN)
        if emit_once():
            os._exit(0)

    signal.signal(signal.SIGTERM, on_kill)
    signal.signal(signal.SIGALRM, on_kill)
    signal.alarm(max(0, int(budget) + 240))

    def watchdog():
        time.sleep(max(5.0, budget + 270))
        if emit_once():
            os._exit(0)

    threading.Thread(target=watchdog, daemon=True).start()

    import jax

    n_all = len(jax.devices())
    n_all_box[0] = n_all
    sizes = [1]
    n = 2
    while n < n_all:
        sizes.append(n)
        n *= 2
    if n_all not in sizes:
        sizes.append(n_all)

    t0 = time.perf_counter()
    for n in sizes:
        if results and time.perf_counter() - t0 > budget:
            print(f"[scaling] skipping {n}+ devices (budget spent)",
                  file=sys.stderr, flush=True)
            break
        try:
            ips = measure(n, args)
        except Exception as e:
            print(f"[scaling] {n} device(s): FAILED {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)
            break
        results[n] = ips
        print(f"[scaling] {n} device(s): {ips:.2f} images/sec "
              f"({ips / n:.2f}/device)", file=sys.stderr, flush=True)

    emit_once()


if __name__ == "__main__":
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", default=8, type=int, help="per-device batch")
    p.add_argument("--dtype", default="bfloat16", choices=["float32", "bfloat16"])
    p.add_argument("--image", default=256, type=int)
    p.add_argument("--scan_steps", default=4, type=int)
    p.add_argument("--iters", default=2, type=int)
    p.add_argument("--tiny", action="store_true",
                   help="tiny model (CPU smoke runs)")
    main(p.parse_args())
