"""Weak-scaling benchmark over the visible device mesh.

BASELINE.md's scaling target: >=90% weak-scaling efficiency at global
batch 256 on a v4-32 pod. This harness measures it on whatever devices
are visible: per-device batch is held fixed while the mesh grows from 1
device to all of them, so ideal scaling doubles images/sec with device
count. Efficiency = (ips_N / N) / ips_1.

The reference cannot express this measurement at all — MirroredStrategy
publishes no scaling counters; its only timer is the per-epoch `elapse`
scalar (/root/reference/main.py:388-392).

dp x spatial grid mode (`--grid`): instead of growing a pure-data mesh,
measure an explicit list of `DPxSP` cells — each cell builds the 2-D
mesh, holds per-DATA-SHARD batch fixed, and can run either conv
sharding (`--spatial_impl {xla,halo}`), remat, and gradient
accumulation. This is how the 1024^2 workload is measured: it only
exists as a (spatial >= 4, remat, accum) cell, and each cell first
passes the analytic HBM ledger (anchored on the compiler-measured
512^2/256^2 temp peaks in docs/BENCHMARKS.md) before any compile is
attempted — on TPU a predicted-OOM cell is skipped, never burned.

Run on a TPU slice:   python bench_scaling.py --batch 8 --dtype bfloat16
Smoke-run on CPU:     JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                        python bench_scaling.py --image 32 --tiny
dp x spatial grid:    python bench_scaling.py --grid 8x1,4x2,2x4 --spatial_impl halo
1024^2 cell:          python bench_scaling.py --grid 2x4 --image 1024 \
                        --batch 1 --accum 4 --remat --tiny

Prints ONE JSON line: {"metric": "weak_scaling_efficiency", ...}.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from cyclegan_tpu.utils.platform import ensure_platform_from_env


# Analytic HBM ledger anchors: XLA:TPU compiler cost analysis of the
# exact jitted step (docs/BENCHMARKS.md, docs/aot_analysis.json).
# Temp peaks scale ~linearly with per-device activation volume
# (batch x H x W), and spatial sharding divides H across the axis.
_LEDGER_ANCHOR_REMAT = (10.75, 4, 512)    # temps GB @ b4, 512^2, remat
_LEDGER_ANCHOR_PLAIN = (14.68, 16, 256)   # temps GB @ b16, 256^2
_LEDGER_CODE_ARGS_GB = 1.6                # code + args margin (b4 row)
_LEDGER_HBM_USABLE_GB = 15.75             # v5e: 16G - runtime reserve


def hbm_ledger(image: int, per_shard_batch: int, spatial: int,
               remat: bool) -> dict:
    """BENCHMARKS-style per-device HBM prediction for one grid cell.

    Accumulation is deliberately absent from the formula: the microbatch
    IS `per_shard_batch`, and peak temps track the microbatch (that is
    the point of accumulation).
    """
    gb_anchor, b_anchor, s_anchor = (
        _LEDGER_ANCHOR_REMAT if remat else _LEDGER_ANCHOR_PLAIN)
    temps = (gb_anchor * (per_shard_batch / b_anchor)
             * (image / s_anchor) ** 2 / max(1, spatial))
    predicted = temps + _LEDGER_CODE_ARGS_GB
    return {
        "anchor": f"compiler temps {gb_anchor} GB @ b{b_anchor} "
                  f"{s_anchor}^2{' remat' if remat else ''}",
        "predicted_temp_gb": round(temps, 2),
        "predicted_total_gb": round(predicted, 2),
        "hbm_usable_gb": _LEDGER_HBM_USABLE_GB,
        "fits": bool(predicted <= _LEDGER_HBM_USABLE_GB),
    }


def _build_config(args, spatial: int):
    import dataclasses

    from cyclegan_tpu.config import (
        Config,
        DiscriminatorConfig,
        GeneratorConfig,
        ModelConfig,
        ParallelConfig,
        TrainConfig,
    )

    gen_cfg = (
        GeneratorConfig(filters=8, num_residual_blocks=2)
        if args.tiny
        else GeneratorConfig()
    )
    disc_cfg = DiscriminatorConfig(filters=8) if args.tiny else DiscriminatorConfig()
    model = ModelConfig(
        generator=gen_cfg,
        discriminator=disc_cfg,
        compute_dtype=args.dtype,
        image_size=args.image,
        remat=args.remat,
    )
    model = dataclasses.replace(model, spatial_impl=args.spatial_impl)
    return Config(
        model=model,
        parallel=ParallelConfig(spatial_parallelism=spatial),
        train=TrainConfig(batch_size=args.batch),
    )


def measure(n_devices: int, args, spatial: int = 1):
    """(images/sec, timed-loop seconds) on the first n_devices devices
    arranged as an (n_devices/spatial) x spatial mesh, scan-mode (or
    accum-mode when --accum > 1). Per-DATA-SHARD batch is held fixed.
    The second element is the fenced measurement-loop wall — the
    per-cell timing the straggler observatory compares across grid
    cells (same device count, different mesh shape => same ideal
    step time)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from cyclegan_tpu.parallel import make_mesh_plan
    from cyclegan_tpu.parallel.dp import (
        shard_accum_train_step,
        shard_multi_train_step,
    )
    from cyclegan_tpu.parallel.mesh import replicated
    from cyclegan_tpu.train import (
        create_state,
        make_accum_train_step,
        make_train_step,
    )

    if n_devices % max(1, spatial):
        raise ValueError(f"{n_devices} devices not divisible by "
                         f"spatial={spatial}")
    cfg = _build_config(args, spatial)
    plan = make_mesh_plan(cfg.parallel, jax.devices()[:n_devices])
    global_batch = plan.n_data * args.batch

    state = jax.device_put(
        create_state(cfg, jax.random.PRNGKey(0)), replicated(plan)
    )
    rng = np.random.RandomState(0)
    s = args.image

    if args.accum > 1:
        # [K, micro, ...] microbatches, one optimizer update per call.
        step = shard_accum_train_step(
            plan,
            make_accum_train_step(
                cfg, global_batch * args.accum, args.accum, plan),
        )
        k = args.accum
    else:
        step = shard_multi_train_step(
            plan, make_train_step(cfg, global_batch, plan), args.scan_steps)
        k = args.scan_steps

    xs = jnp.asarray(rng.rand(k, global_batch, s, s, 3).astype(np.float32) * 2 - 1)
    ys = jnp.asarray(rng.rand(k, global_batch, s, s, 3).astype(np.float32) * 2 - 1)
    wts = jnp.ones((k, global_batch), jnp.float32)

    def fence(metrics):
        leaf = jax.tree_util.tree_leaves(metrics)[0]
        float(jax.device_get(leaf if leaf.ndim == 0 else leaf[-1]))

    state, m = step(state, xs, ys, wts)
    fence(m)  # execution fence (not block_until_ready)
    t0 = time.perf_counter()
    for _ in range(args.iters):
        state, m = step(state, xs, ys, wts)
    fence(m)
    dt = time.perf_counter() - t0
    return 2 * global_batch * k * args.iters / dt, dt


def _parse_grid(spec: str):
    """'8x1,4x2,2x4' -> [(8, 1), (4, 2), (2, 4)] (dp, spatial)."""
    cells = []
    for cell in spec.split(","):
        dp, _, sp = cell.strip().lower().partition("x")
        cells.append((int(dp), int(sp or 1)))
    return cells


def _emit(results, n_all, args, cell_timing=None) -> None:
    results = dict(results)
    grid = bool(args.grid)
    if grid:
        # Weak scaling across cells: per-device throughput of the
        # largest mesh (last measured on ties — e.g. 8x1 vs 4x2) vs the
        # smallest (first measured on ties).
        ordered = [(dp * sp, v) for (dp, sp), v in results.items()]
        scaled = len(ordered) > 1
        if scaled:
            n_lo, ips_lo = min(ordered, key=lambda t: t[0])
            n_hi, ips_hi = max(reversed(ordered), key=lambda t: t[0])
            eff = (ips_hi / n_hi) / (ips_lo / n_lo)
        else:
            eff = 1.0 if results else 0.0
        ips = {f"{dp}x{sp}": round(v, 2) for (dp, sp), v in results.items()}
        max_n = max(n for n, _ in ordered) if ordered else 0
    else:
        max_n = max(results) if results else 0
        scaled = max_n > 1 and 1 in results
        if scaled:
            eff = (results[max_n] / max_n) / results[1]
        elif results and n_all == 1:
            eff = 1.0  # single-device platform: nothing to scale over
        else:
            eff = 0.0  # multi-device platform but no scaling measured
        ips = {str(k): round(v, 2) for k, v in results.items()}
    line = {
        "metric": "weak_scaling_efficiency",
        "value": round(eff, 4),
        "unit": "fraction",
        "vs_baseline": round(eff / 0.90, 3),  # target: >=90%
        "devices": n_all,
        "measured_devices": max_n,
        "per_device_batch": args.batch,
        "images_per_sec": ips,
    }
    if grid:
        line["mode"] = "grid"
        line["image"] = args.image
        line["spatial_impl"] = args.spatial_impl
        line["remat"] = bool(args.remat)
        line["accum"] = args.accum
        if cell_timing:
            # Per-cell timing for the straggler observatory: whole-cell
            # wall (compile included) and the fenced per-iteration step
            # time — cells with the same device count share an ideal
            # step time, so the slowest cell names the straggling mesh
            # shape, not just a slower efficiency number.
            line["cell_wall_s"] = {
                f"{dp}x{sp}": round(w, 3)
                for (dp, sp), (w, _) in cell_timing.items()}
            line["cell_step_s"] = {
                f"{dp}x{sp}": round(dt / max(1, args.iters), 4)
                for (dp, sp), (_, dt) in cell_timing.items()}
            slowest = max(cell_timing,
                          key=lambda c: cell_timing[c][1])
            line["slowest_cell"] = f"{slowest[0]}x{slowest[1]}"
        if args.image >= 512:
            # Ledger for the most-sharded measured cell; when nothing
            # completed, fall back to the ATTEMPTED grid so the emitted
            # ledger still describes the config that was preflighted.
            sp_max = max((sp for _, sp in results), default=0) or max(
                (sp for _, sp in _parse_grid(args.grid)), default=1)
            line["hbm_ledger"] = hbm_ledger(
                args.image, args.batch, sp_max, args.remat)
    if not results:
        line["error"] = "no mesh size completed"
    elif not scaled and n_all > 1 and not grid:
        line["error"] = "only the 1-device size completed; no scaling measured"
    print(json.dumps(line), flush=True)


def main(args) -> None:
    ensure_platform_from_env()
    from cyclegan_tpu.utils.axon_compat import cli_startup

    cli_startup()  # local-compile workaround + relay diagnosis
    from cyclegan_tpu.utils.platform import enable_compilation_cache

    enable_compilation_cache()

    results = {}
    cell_timing = {}

    # Same hang/kill protection as bench.py: one compile wedging — or the
    # driver's SIGTERM — must not swallow the sizes that already completed.
    import os
    import signal
    import threading

    budget = float(os.environ.get("BENCH_TIME_BUDGET_S", "480"))
    n_all_box = [0]
    emit_lock = threading.Lock()
    emitted = [False]

    def emit_once() -> bool:
        with emit_lock:
            if emitted[0]:
                return False
            emitted[0] = True
        _emit(results, n_all_box[0], args, cell_timing)
        return True

    def on_kill(signum, frame):
        # Disarm both first: nested delivery would deadlock the
        # non-reentrant emit lock on the main thread.
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        signal.signal(signal.SIGALRM, signal.SIG_IGN)
        if emit_once():
            os._exit(0)

    signal.signal(signal.SIGTERM, on_kill)
    signal.signal(signal.SIGALRM, on_kill)
    signal.alarm(max(0, int(budget) + 240))

    def watchdog():
        time.sleep(max(5.0, budget + 270))
        if emit_once():
            os._exit(0)

    threading.Thread(target=watchdog, daemon=True).start()

    import jax

    n_all = len(jax.devices())
    n_all_box[0] = n_all

    if args.grid:
        cells = [(dp, sp) for dp, sp in _parse_grid(args.grid)
                 if dp * sp <= n_all]
        dropped = [c for c in _parse_grid(args.grid) if c not in cells]
        if dropped:
            print(f"[scaling] dropping cells beyond {n_all} devices: "
                  f"{dropped}", file=sys.stderr, flush=True)
        on_tpu = jax.devices()[0].platform == "tpu"
        t0 = time.perf_counter()
        for dp, sp in cells:
            if results and time.perf_counter() - t0 > budget:
                print(f"[scaling] skipping {dp}x{sp}+ (budget spent)",
                      file=sys.stderr, flush=True)
                break
            if args.image >= 512:
                ledger = hbm_ledger(args.image, args.batch, sp, args.remat)
                print(f"[scaling] {dp}x{sp} HBM ledger: "
                      f"{ledger['predicted_total_gb']} GB predicted vs "
                      f"{ledger['hbm_usable_gb']} usable "
                      f"({'fits' if ledger['fits'] else 'DOES NOT FIT'})",
                      file=sys.stderr, flush=True)
                if on_tpu and not ledger["fits"]:
                    print(f"[scaling] {dp}x{sp}: skipped (predicted OOM)",
                          file=sys.stderr, flush=True)
                    continue
            t_cell = time.perf_counter()
            try:
                ips, loop_dt = measure(dp * sp, args, spatial=sp)
            except Exception as e:
                # Cells are independent (a floor violation in one mesh
                # shape says nothing about the others) — keep going.
                print(f"[scaling] {dp}x{sp}: FAILED "
                      f"{type(e).__name__}: {e}",
                      file=sys.stderr, flush=True)
                continue
            results[(dp, sp)] = ips
            cell_timing[(dp, sp)] = (
                time.perf_counter() - t_cell, loop_dt)
            print(f"[scaling] {dp}x{sp}: {ips:.2f} images/sec "
                  f"({ips / (dp * sp):.2f}/device)",
                  file=sys.stderr, flush=True)
        emit_once()
        return

    sizes = [1]
    n = 2
    while n < n_all:
        sizes.append(n)
        n *= 2
    if n_all not in sizes:
        sizes.append(n_all)

    t0 = time.perf_counter()
    for n in sizes:
        if results and time.perf_counter() - t0 > budget:
            print(f"[scaling] skipping {n}+ devices (budget spent)",
                  file=sys.stderr, flush=True)
            break
        try:
            ips, _ = measure(n, args)
        except Exception as e:
            print(f"[scaling] {n} device(s): FAILED {type(e).__name__}: {e}",
                  file=sys.stderr, flush=True)
            break
        results[n] = ips
        print(f"[scaling] {n} device(s): {ips:.2f} images/sec "
              f"({ips / n:.2f}/device)", file=sys.stderr, flush=True)

    emit_once()


if __name__ == "__main__":
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", default=8, type=int,
                   help="per-data-shard batch (per-device when spatial=1)")
    p.add_argument("--dtype", default="bfloat16", choices=["float32", "bfloat16"])
    p.add_argument("--image", default=256, type=int)
    p.add_argument("--scan_steps", default=4, type=int)
    p.add_argument("--iters", default=2, type=int)
    p.add_argument("--tiny", action="store_true",
                   help="tiny model (CPU smoke runs)")
    p.add_argument("--grid", default=None,
                   help="comma-separated DPxSP mesh cells to measure "
                        "(e.g. 8x1,4x2,2x4); overrides the doubling scan")
    p.add_argument("--spatial_impl", default="xla", choices=["xla", "halo"],
                   help="conv sharding for spatial cells (grid mode)")
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint residual blocks (512^2+ configs)")
    p.add_argument("--accum", default=1, type=int,
                   help="gradient-accumulation microbatches per update "
                        "(>1 replaces the scan-steps loop)")
    main(p.parse_args())
