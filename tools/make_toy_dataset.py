"""Generate a two-domain toy dataset for offline qualitative runs.

Domain A: solid-filled ellipses/rectangles on a light gray background.
Domain B: the same shape family, but STRIPE-textured fills.

The A<->B translation ("add stripes" / "remove stripes") is the offline
stand-in for horse<->zebra (reference README.md:4-6): it is learnable by
a small CycleGAN in CPU-hours, and success/failure is obvious to the eye
in the X_cycle/Y_cycle panels. Images are written as trainA/ trainB/
testA/ testB .npy files in the FolderSource layout (data/sources.py).

Usage:
  python tools/make_toy_dataset.py --out /tmp/shapes2stripes \
      --train 128 --test 12 --size 64
"""

from __future__ import annotations

import argparse
import os

import numpy as np


def _draw(rng: np.random.Generator, size: int, striped: bool) -> np.ndarray:
    """One sample: 1-3 shapes, solid or striped fill, uint8 [size,size,3]."""
    img = np.full((size, size, 3), 225, np.float32)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32)
    for _ in range(int(rng.integers(1, 4))):
        cy, cx = rng.uniform(0.2, 0.8, 2) * size
        ry, rx = rng.uniform(0.12, 0.3, 2) * size
        color = rng.uniform(30, 220, 3)
        if rng.random() < 0.5:  # ellipse
            mask = ((yy - cy) / ry) ** 2 + ((xx - cx) / rx) ** 2 <= 1.0
        else:  # rectangle
            mask = (np.abs(yy - cy) <= ry) & (np.abs(xx - cx) <= rx)
        if striped:
            # Diagonal stripes, random phase/period, dark-on-color.
            period = rng.uniform(4.0, 7.0)
            phase = rng.uniform(0, period)
            stripes = ((yy + xx + phase) % period) < period / 2
            fill = np.where(stripes[..., None], color, color * 0.25)
        else:
            fill = np.broadcast_to(color, img.shape)
        img = np.where(mask[..., None], fill, img)
    img += rng.normal(0, 3.0, img.shape)  # sensor-ish grain
    return np.clip(img, 0, 255).astype(np.uint8)


def generate(out: str, train: int, test: int, size: int, seed: int = 0) -> None:
    import zlib

    specs = [("trainA", train, False), ("trainB", train, True),
             ("testA", test, False), ("testB", test, True)]
    for split, n, striped in specs:
        d = os.path.join(out, split)
        os.makedirs(d, exist_ok=True)
        for i in range(n):
            # crc32, not hash(): Python string hashing is salted per
            # process and would make the dataset non-reproducible.
            rng = np.random.default_rng(
                (seed, zlib.crc32(split.encode()) & 0xFFFF, i)
            )
            np.save(os.path.join(d, f"{i:04d}.npy"), _draw(rng, size, striped))
    print(f"wrote {2 * (train + test)} images -> {out}")


if __name__ == "__main__":
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", required=True)
    p.add_argument("--train", default=128, type=int)
    p.add_argument("--test", default=12, type=int)
    p.add_argument("--size", default=64, type=int)
    p.add_argument("--seed", default=0, type=int)
    a = p.parse_args()
    generate(a.out, a.train, a.test, a.size, a.seed)
