"""Export a training run's goodput ledger to a Chrome/Perfetto
timeline + per-epoch phase table.

The training-side twin of trace_timeline.py: where that tool renders
the serving pipeline's per-request spans, this one renders the
dispatch loop's wall-clock attribution from the events the telemetry
stream already carries — per-dispatch ``step`` records, per-pass
``epoch_steps`` aggregates, per-epoch ``goodput`` rollups
(obs/goodput.py), ``service_job`` completions, and ``loop_stall``
instants. No new instrumentation: a stream written by any traced run
renders as-is.

- **Perfetto JSON** (``--out``): one "epochs" track with a span per
  epoch (named with its goodput fraction, phase seconds in args), a
  per-split "steps" track tiling each dispatch's stage/dispatch/fetch/
  host windows, a "services" track for epoch-services jobs, and
  loop-stall instants. Timestamps are reconstructed from each event's
  stream offset ``t`` and its duration fields (spans end at emit time).
- **Phase table** (stdout): per-epoch phase fractions with the badput
  census, plus the run rollup.

Usage:
  python tools/goodput_timeline.py runs/telemetry.jsonl --out goodput.json
  python tools/goodput_timeline.py runs/telemetry.jsonl --json

Stdlib only; pure host-side file reads.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

PHASE_ORDER = ("compute", "collective", "data_wait", "host", "compile",
               "services", "idle")


def load_events(path: str) -> List[dict]:
    """All parseable events from a JSONL stream; torn lines skipped."""
    out: List[dict] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if isinstance(ev, dict) and "event" in ev:
                out.append(ev)
    return out


def export_perfetto(events: List[dict]) -> dict:
    """Chrome trace-event JSON (see trace_timeline.export_perfetto for
    the format conventions mirrored here: ph "X" spans, ph "i"
    instants, ph "M" track names, microsecond deltas)."""

    def us(t: float) -> float:
        return round(t * 1e6, 3)

    tracks: Dict[str, int] = {"epochs": 1, "services": 2}
    out: List[dict] = []
    for ev in events:
        kind = ev.get("event")
        t = ev.get("t")
        if t is None:
            continue
        if kind == "goodput":
            dur = float(ev.get("elapse_s") or 0.0)
            frac = ev.get("goodput_fraction")
            label = f"epoch {ev.get('epoch', '?')}"
            if frac is not None:
                label += f" (goodput {float(frac) * 100:.0f}%)"
            out.append({
                "name": label, "cat": "goodput", "ph": "X", "pid": 1,
                "tid": tracks["epochs"], "ts": us(t - dur),
                "dur": round(dur * 1e6, 3),
                "args": {"phases_s": ev.get("phases_s"),
                         "badput": ev.get("badput"),
                         "n_steps": ev.get("n_steps")},
            })
        elif kind == "step":
            split = ev.get("split", "train")
            track = f"steps:{split}"
            tid = tracks.setdefault(track, len(tracks) + 1)
            wall = float(ev.get("wall_s") or 0.0)
            start = t - wall
            # Tile the dispatch's windows in loop order; the remainder
            # is host work (bookkeeping between windows).
            cursor = start
            for name, key in (("stage", "stage_s"),
                              ("dispatch", "dispatch_s"),
                              ("fetch", "fetch_block_s")):
                d = float(ev.get(key) or 0.0)
                if d > 0:
                    out.append({
                        "name": name, "cat": "window", "ph": "X",
                        "pid": 1, "tid": tid, "ts": us(cursor),
                        "dur": round(d * 1e6, 3),
                        "args": {"dispatch": ev.get("dispatch"),
                                 "epoch": ev.get("epoch")},
                    })
                    cursor += d
            host = max(0.0, start + wall - cursor)
            if host > 0:
                out.append({
                    "name": "host", "cat": "window", "ph": "X",
                    "pid": 1, "tid": tid, "ts": us(cursor),
                    "dur": round(host * 1e6, 3),
                    "args": {"dispatch": ev.get("dispatch"),
                             "epoch": ev.get("epoch")},
                })
        elif kind == "service_job":
            dur = float(ev.get("seconds") or 0.0)
            out.append({
                "name": ev.get("job", "service"), "cat": "service",
                "ph": "X", "pid": 1, "tid": tracks["services"],
                "ts": us(t - dur), "dur": round(dur * 1e6, 3),
                "args": {k: v for k, v in ev.items()
                         if k not in ("event", "t")},
            })
        elif kind == "loop_stall":
            split = ev.get("split", "train")
            tid = tracks.setdefault(f"steps:{split}", len(tracks) + 1)
            out.append({
                "name": "loop_stall", "cat": "stall", "ph": "i",
                "s": "t", "pid": 1, "tid": tid, "ts": us(t),
                "args": {k: v for k, v in ev.items()
                         if k not in ("event", "t")},
            })
    meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
             "args": {"name": label}}
            for label, tid in sorted(tracks.items(), key=lambda kv: kv[1])]
    meta += [{"name": "thread_sort_index", "ph": "M", "pid": 1,
              "tid": tid, "args": {"sort_index": tid}}
             for _, tid in sorted(tracks.items(), key=lambda kv: kv[1])]
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def phase_table(events: List[dict]) -> dict:
    """Per-epoch goodput rows + the whole-run rollup (seconds-weighted
    across epochs)."""
    epochs = []
    totals = {p: 0.0 for p in PHASE_ORDER}
    elapse = 0.0
    for ev in events:
        if ev.get("event") != "goodput":
            continue
        phases = ev.get("phases_s") or {}
        epochs.append({
            "epoch": ev.get("epoch"),
            "elapse_s": ev.get("elapse_s"),
            "goodput_fraction": ev.get("goodput_fraction"),
            "phase_fractions": ev.get("phase_fractions") or {},
            "badput": ev.get("badput") or {},
        })
        for p in PHASE_ORDER:
            totals[p] += float(phases.get(p) or 0.0)
        elapse += float(ev.get("elapse_s") or 0.0)
    run = None
    if elapse > 0:
        run = {
            "elapse_s": round(elapse, 3),
            "phase_fractions": {p: round(totals[p] / elapse, 4)
                                for p in PHASE_ORDER},
            "goodput_fraction": round(totals["compute"] / elapse, 4),
        }
    return {"epochs": epochs, "run": run}


def render_table(table: dict) -> str:
    lines = []
    header = f"{'epoch':>6} {'elapse s':>9} " + " ".join(
        f"{p[:8]:>9}" for p in PHASE_ORDER)
    lines.append(header)
    for row in table["epochs"]:
        fr = row["phase_fractions"]
        lines.append(
            f"{str(row['epoch']):>6} {row['elapse_s']:>9} " + " ".join(
                f"{100 * float(fr.get(p) or 0):>8.1f}%" for p in PHASE_ORDER))
    run = table["run"]
    if run is not None:
        fr = run["phase_fractions"]
        lines.append(
            f"{'run':>6} {run['elapse_s']:>9} " + " ".join(
                f"{100 * float(fr.get(p) or 0):>8.1f}%" for p in PHASE_ORDER))
        lines.append(f"run goodput fraction: "
                     f"{run['goodput_fraction'] * 100:.1f}%")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("stream", help="JSONL telemetry stream")
    p.add_argument("--out", default=None,
                   help="write Perfetto/Chrome trace-event JSON here")
    p.add_argument("--json", action="store_true",
                   help="print the phase table as JSON instead of text")
    args = p.parse_args(argv)

    events = load_events(args.stream)
    table = phase_table(events)
    if not table["epochs"]:
        print("no goodput events in the stream (run predates the "
              "ledger, or telemetry was disabled)", file=sys.stderr)
        return 1
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(export_perfetto(events), f)
        print(f"wrote {args.out} (load at ui.perfetto.dev)",
              file=sys.stderr)
    if args.json:
        print(json.dumps(table, indent=2))
    else:
        print(render_table(table))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
