"""Run the collective comms census for a mesh and print the verdict.

Compiles the REAL sharded train step for the requested dp x spatial
mesh on host devices (abstract avals — no arrays materialized, the
dryrun stage-2 pattern), walks the lowered HLO for its collectives,
and reconciles them against the analytic ledger (obs/comms.py). Exit
status is the verdict: 0 when every axis reconciles within tolerance,
1 otherwise — `chip_autorun` runs this as a preflight step so a
mis-sharded program aborts the queue BEFORE it burns a relay window.

The gated program is the UNROLLED smoke config: the analytic site
model is validated for unrolled trunks (under scan_blocks XLA sums the
generator's three gradient contributions before a single all-reduce,
so per-site multipliers overestimate by design), and the gate's
question — did the partitioner lay out collectives on THIS mesh the
way the model expects? — is mesh-shaped, not model-shaped. Pass
`--full` to additionally compile the full-size scan program and attach
its measured (parsed-from-HLO) per-axis bytes as an advisory section.

  python tools/comms_census.py --devices 8             # gate, 4x2 mesh
  python tools/comms_census.py --devices 8 --full      # + advisory 256^2
  python tools/comms_census.py --devices 8 --out docs/comms_census.json
  python tools/comms_census.py --devices 8 --spatial_impl both  # gate xla+halo

`--spatial_impl` picks which conv sharding the gated program uses
(`xla` partitioner halos, `halo` explicit shard_map exchanges, or
`both` to gate the two programs in one run — the halo ledger adds the
mesh-wide kernel-psum axis; see obs/comms.py).

Prints ONE JSON line (the census payload; for `both`, a wrapper with
an `impls` map and the AND of the verdicts) to stdout; progress to
stderr. Forces CPU host devices — the census reads the compiled
program's text, it never needs the chip.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys


def main() -> int:
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--devices", default=8, type=int,
                   help="total mesh size (dp x spatial)")
    p.add_argument("--spatial", default=None, type=int,
                   help="spatial axis size (default: 2 when --devices "
                        "is even, matching dryrun_multichip)")
    p.add_argument("--spatial_impl", default="xla",
                   choices=("xla", "halo", "both"),
                   help="conv sharding impl(s) to gate (default: xla)")
    p.add_argument("--full", action="store_true",
                   help="also compile the full-size (256^2, scanned "
                        "trunk) program and attach its measured "
                        "collectives as an advisory section (slow)")
    p.add_argument("--link_gbps", default=45.0, type=float,
                   help="per-link one-way GB/s for the per-link time "
                        "estimate (scaling_model.py default)")
    p.add_argument("--out", default=None,
                   help="also write the census payload (pretty JSON) here")
    args = p.parse_args()

    # Host devices only: assert BEFORE jax import wins the backend race.
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={args.devices}"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from cyclegan_tpu.config import (
        Config,
        ModelConfig,
        ParallelConfig,
        TrainConfig,
        tiny_test_config,
    )
    from cyclegan_tpu.obs.comms import build_census, parse_hlo_collectives
    from cyclegan_tpu.parallel import make_mesh_plan, shard_train_step
    from cyclegan_tpu.train import create_state, make_train_step
    from cyclegan_tpu.utils.platform import enable_compilation_cache

    def compile_step(cfg, plan, gb):
        s = cfg.model.image_size
        state = jax.eval_shape(
            lambda: create_state(cfg, jax.random.PRNGKey(0)))
        step = shard_train_step(plan, make_train_step(cfg, gb, plan))
        img = jax.ShapeDtypeStruct((gb, s, s, 3), np.float32)
        w = jax.ShapeDtypeStruct((gb,), np.float32)
        return state, step.lower(state, img, img, w).compile()

    enable_compilation_cache()
    devices = jax.devices()[:args.devices]
    if len(devices) < args.devices:
        print(f"need {args.devices} devices, have {len(devices)}",
              file=sys.stderr)
        return 1
    spatial = args.spatial
    if spatial is None:
        spatial = 2 if args.devices % 2 == 0 and args.devices > 1 else 1
    par = ParallelConfig(spatial_parallelism=spatial)
    plan = make_mesh_plan(par, devices)
    impls = (("xla", "halo") if args.spatial_impl == "both"
             else (args.spatial_impl,))
    per_impl = {}
    for impl in impls:
        cfg = tiny_test_config().replace(parallel=par)
        cfg = cfg.replace(
            model=dataclasses.replace(cfg.model, spatial_impl=impl))
        gb = plan.n_data * cfg.train.batch_size
        s = cfg.model.image_size
        print(f"[comms_census] compiling mesh "
              f"{plan.n_data}x{plan.n_spatial}, {s}^2, global batch {gb}, "
              f"spatial_impl={impl} ...", file=sys.stderr, flush=True)
        state, compiled = compile_step(cfg, plan, gb)
        per_impl[impl] = build_census(plan, cfg, gb, state,
                                      hlo_text=compiled.as_text(),
                                      link_gbps=args.link_gbps)
    if len(impls) == 1:
        census = per_impl[impls[0]]
    else:
        census = {
            "schema": 1,
            "spatial_impl": "both",
            "impls": per_impl,
            "tolerance": per_impl["xla"]["tolerance"],
            "max_recon_error": max(
                c.get("max_recon_error", 0.0) for c in per_impl.values()),
            "ok": all(c.get("ok", False) for c in per_impl.values()),
        }
    if args.full:
        batch = -(-8 // plan.n_data)  # ceil: global batch >= 8
        cfg_full = Config(
            # advisory section stays on the xla impl: the scanned trunk
            # is outside the analytic model's validity domain either way
            model=ModelConfig(image_size=256, scan_blocks=True),
            parallel=par,
            train=TrainConfig(batch_size=batch),
        )
        gb_full = plan.n_data * batch
        print(f"[comms_census] compiling full-size 256^2 program "
              f"(advisory, global batch {gb_full}) ...",
              file=sys.stderr, flush=True)
        _, compiled_full = compile_step(cfg_full, plan, gb_full)
        census["full_size_measured"] = {
            "note": "compiled full-size scan program (advisory: the "
                    "analytic site model gates unrolled trunks only)",
            "image_size": 256,
            "global_batch": gb_full,
            "axes": parse_hlo_collectives(
                compiled_full.as_text(), plan.n_data,
                plan.n_spatial)["axes"],
        }
    for impl, c in per_impl.items():
        for ax, v in c.get("reconciliation", {}).items():
            print(f"[comms_census] {impl}/{ax}: analytic "
                  f"{v['analytic_bytes'] / 1e6:.2f} MB vs measured "
                  f"{v['measured_bytes'] / 1e6:.2f} MB over "
                  f"{v['measured_ops']} ops (err {v['error'] * 100:.1f}%)",
                  file=sys.stderr, flush=True)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(census, f, indent=2, sort_keys=True)
        print(f"[comms_census] wrote {args.out}", file=sys.stderr)
    print(json.dumps(census), flush=True)
    if not census.get("ok", False):
        print("[comms_census] RECONCILIATION FAILED: analytic model and "
              "compiled program disagree beyond "
              f"{census['tolerance'] * 100:.0f}% — do not burn chip time "
              "on this program", file=sys.stderr)
        return 1
    print(f"[comms_census] OK (max axis error "
          f"{census.get('max_recon_error', 0) * 100:.1f}%)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
