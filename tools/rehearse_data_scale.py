"""Rehearse the data pipeline at horse2zebra scale on the real CLI.

The memory claims of the uint8/windowed pipeline (docs, tests/test_memory.py)
are unit-tested with a counting source; this tool exercises them END TO
END: a folder dataset with the reference's asymmetric horse2zebra split
sizes (trainA 1067, trainB 1334, testA 120, testB 140 — what
/root/reference/main.py:22-26 loads via TFDS) is generated on disk at
256^2, `main.py --data_source folder` trains one full epoch over it
through the native C++ preprocessing path, and the subprocess's peak RSS
(VmHWM) is sampled throughout.

The MODEL is scaled down (--filters 4 --residual_blocks 1) so the epoch
is CPU-feasible; the DATA geometry — image count x 256^2 through load /
fused resize+flip+crop / uint8 cache / prefetch-thread normalize — is
exactly the thing being rehearsed.

Checks:
- the banner cache ledger equals the analytic uint8 ledger:
  (2*1067 + 2*120) * 256^2 * 3 = 467 MB (min-truncation kept trainB's
  1334-image tail unread; everything resident is uint8)
- peak RSS stays under --rss_budget_mb

Measured 2026-07-31 (single-core host): ledger exactly 467 MB, peak RSS
3925 MB over the 736 s run at b16. The ~3.4 GB above the ledger is NOT
data pipeline: on this CPU rehearsal the XLA "device" lives in the same
process RSS, so it includes the deferred-metric-fetch pinned-batch
window (train/loop.py MAX_IN_FLIGHT=32 dispatched batches ~= 0.8 GB of
f32 at b16/256^2), the jitted step's activation/temp buffers, compile
transients, and the jax/numpy runtime itself — all of which sit in HBM
or are absent on a real TPU host. Confirmed experimentally: re-running
with --batch 4 (same dataset, same ledger) measured peak RSS 2206 MB —
a 1.7 GB drop purely from batch-scaled device buffers, with the ledger
unchanged at 467 MB. The default budget (4608 MB) bounds the whole
b16 process with ~0.7 GB headroom; the pipeline-attributable claim is
the EXACT ledger match plus the bounded-transient design
(tests/test_memory.py).

Usage:
  python tools/rehearse_data_scale.py [--data_dir /tmp/h2z_scale]
      [--rss_budget_mb 4608] [--batch 16] [--keep_run]

Prints one JSON line with the measurements (exit 1 on a failed check).
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import re
import shutil
import subprocess
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

# Reference horse2zebra split sizes (TFDS cycle_gan/horse2zebra).
SPLITS = {"trainA": 1067, "trainB": 1334, "testA": 120, "testB": 140}
SIZE = 256


def generate_dataset(out: str, seed: int = 0) -> None:
    """Folder dataset at the reference's split sizes, shapes/stripes
    content (make_toy_dataset's drawer — learnability is irrelevant
    here, only the byte geometry is)."""
    import zlib

    import numpy as np

    from make_toy_dataset import _draw

    for split, n in SPLITS.items():
        d = os.path.join(out, split)
        os.makedirs(d, exist_ok=True)
        have = len(os.listdir(d))
        if have == n:
            continue
        striped = split.endswith("B")
        for i in range(n):
            rng = np.random.default_rng(
                (seed, zlib.crc32(split.encode()) & 0xFFFF, i)
            )
            np.save(os.path.join(d, f"{i:04d}.npy"), _draw(rng, SIZE, striped))
    print(f"dataset ready at {out}", file=sys.stderr, flush=True)


def read_vm_hwm_kb(pid: int) -> int:
    """Peak resident set (VmHWM) of a live process, in kB; 0 if gone."""
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1])
    except OSError:
        pass
    return 0


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data_dir", default="/tmp/h2z_scale")
    p.add_argument("--output_dir", default="/tmp/h2z_scale_run")
    p.add_argument("--rss_budget_mb", default=4608.0, type=float)
    p.add_argument("--batch", default=16, type=int,
                   help="global batch; shrinking it shrinks every "
                        "batch-scaled XLA:CPU buffer (pinned in-flight "
                        "window, step activations). The attribution "
                        "experiment: b16 -> b4 measured a 1.7 GB peak-RSS "
                        "drop with the cache ledger unchanged (docstring)")
    p.add_argument("--keep_run", action="store_true")
    p.add_argument("--timeout_s", default=3600, type=float)
    args = p.parse_args()

    generate_dataset(args.data_dir)
    if os.path.exists(args.output_dir):
        shutil.rmtree(args.output_dir)

    repo = str(pathlib.Path(__file__).resolve().parents[1])
    cmd = [
        sys.executable, "-u", "main.py",
        "--output_dir", args.output_dir,
        "--data_source", "folder", "--data_dir", args.data_dir,
        "--dataset", "h2z_scale",
        "--image_size", str(SIZE), "--batch_size", str(args.batch),
        "--filters", "4", "--residual_blocks", "1",
        "--epochs", "1", "--verbose", "0",
    ]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    t0 = time.time()
    proc = subprocess.Popen(cmd, cwd=repo, env=env, text=True,
                            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)
    peak_kb = 0
    while proc.poll() is None:
        peak_kb = max(peak_kb, read_vm_hwm_kb(proc.pid))
        if time.time() - t0 > args.timeout_s:
            proc.kill()
            print(json.dumps({"ok": False, "error": "timeout"}))
            return 1
        time.sleep(1.0)
    stdout = proc.stdout.read()
    if proc.returncode != 0:
        print(json.dumps({"ok": False, "error": f"rc={proc.returncode}",
                          "stdout_tail": stdout[-1000:]}))
        return 1

    m = re.search(r"cache (\d+)MB", stdout)
    ledger_mb = int(m.group(1)) if m else -1
    n_train = min(SPLITS["trainA"], SPLITS["trainB"])
    n_test = min(SPLITS["testA"], SPLITS["testB"])
    expected_mb = round((2 * n_train + 2 * n_test) * SIZE * SIZE * 3 / 1e6)
    peak_mb = peak_kb / 1024.0
    ok = ledger_mb == expected_mb and peak_mb < args.rss_budget_mb
    print(json.dumps({
        "ok": ok,
        "batch": args.batch,
        "n_train_truncated": n_train,
        "ledger_mb": ledger_mb,
        "expected_ledger_mb": expected_mb,
        "peak_rss_mb": round(peak_mb, 1),
        "rss_budget_mb": args.rss_budget_mb,
        "elapsed_s": round(time.time() - t0, 1),
    }))
    if not args.keep_run and os.path.exists(args.output_dir):
        shutil.rmtree(args.output_dir)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
