"""Compile the SHARDED multi-chip train step with the real TPU compiler.

The chipless `local_only` AOT backend accepts any topology, not just
1x1x1 — so the data-parallel program the framework would run on a real
v5e slice can be compiled by the real XLA:TPU compiler right here, with
no chips and no network. That upgrades the multi-chip validation story
one level beyond the virtual-CPU-mesh tests (tests/test_dp.py,
__graft_entry__.dryrun_multichip): same mesh, same shardings, but the
actual TPU backend choosing the collectives, fusing them, and
reporting their cost.

What it yields (merged into docs/aot_analysis.json):
- the all-reduce count and per-op bytes the TPU compiler actually
  emits for the 4-tree gradient reduction — cross-checking
  scaling_model.py's analytic 113.2 MB/step figure;
- compiler cost/memory analysis of the per-chip program (the
  weak-scaling model's per-chip step time input);
- an existence proof that the sharded program compiles for a real
  multi-chip TPU target (layouts, collectives, SPMD partitioning).

Run: PALLAS_AXON_POOL_IPS= PALLAS_AXON_TPU_GEN=v5e python
tools/aot_multichip.py [--topology 2x2x1] [--batch-per-chip 4]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

T0 = time.perf_counter()


def say(msg: str) -> None:
    print(f"[{time.perf_counter() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


_DTYPE_BYTES = {"f32": 4, "bf16": 2, "s32": 4, "u32": 4, "pred": 1, "f16": 2}


def all_reduce_traffic(hlo: str) -> dict:
    """Sum the payload bytes of every all-reduce in optimized HLO.

    Parses result shapes like `f32[11386880]` (or tuple shapes) on
    lines containing `all-reduce(`. Counts each op once; the wire
    traffic per chip for a bidirectional ring is 2*(n-1)/n times this
    payload (scaling_model.py), so the payload is the comparable
    number for the analytic model's "bytes all-reduced per step".
    """
    ops = []
    unknown_dtypes = set()
    # Sync form and the async start op (its -done twin carries the same
    # payload; counting both would double it).
    op_markers = (" all-reduce(", " all-reduce-start(")
    for line in hlo.splitlines():
        marker = next((m for m in op_markers if m in line), None)
        if marker is None:
            continue
        # "%name = f32[N]{0} all-reduce(...)" — the RESULT shape sits
        # between the '=' and the op name (possibly a tuple of shapes).
        head = line.split(marker)[0]
        head = head.split("=", 1)[1] if "=" in head else head
        shapes = re.findall(r"([a-z0-9]+)\[([0-9,]*)\]", head)
        nbytes = 0
        for dt, dims in shapes:
            if dt not in _DTYPE_BYTES:
                unknown_dtypes.add(dt)
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _DTYPE_BYTES[dt]
        ops.append(nbytes)
    out = {
        "n_all_reduce": len(ops),
        "payload_bytes_total": int(sum(ops)),
        "payload_bytes_per_op": sorted(ops, reverse=True)[:8],
    }
    if unknown_dtypes:
        # Payload under-counted — record it rather than report silently
        # wrong "ground truth" (the scaling model cites this number).
        out["unknown_dtypes_skipped"] = sorted(unknown_dtypes)
        say(f"WARNING: unknown dtypes in all-reduce shapes skipped: "
            f"{sorted(unknown_dtypes)} — payload under-counted")
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--topology", default="2x2x1",
                    help="AOT chip topology (e.g. 2x2x1 = 4 chips)")
    ap.add_argument("--batch-per-chip", type=int, default=4)
    ap.add_argument("--image", type=int, default=256)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--spatial", type=int, default=1,
                    help="spatial_parallelism: shard H over this many "
                         "chips (halo/reshard exchanges appear as whatever "
                         "collective GSPMD picks — all-to-alls on v5e 2x2)")
    args = ap.parse_args()
    if args.spatial < 1:
        raise SystemExit(f"--spatial must be >= 1, got {args.spatial}")

    from cyclegan_tpu.utils.axon_compat import register_axon_local

    if not register_axon_local(local_only=True, topology=args.topology):
        raise RuntimeError("axon plugin not present in this environment")
    gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
    say(f"registered local_only AOT backend, topology {gen}:{args.topology}")

    import jax
    import jax.numpy as jnp

    devs = jax.devices()
    say(f"devices: {len(devs)} x {devs[0].device_kind}")
    n = len(devs)

    from cyclegan_tpu.config import (
        Config, ModelConfig, ParallelConfig, TrainConfig,
    )
    from cyclegan_tpu.parallel import make_mesh_plan, shard_train_step
    from cyclegan_tpu.train import create_state, make_train_step

    if n % args.spatial:
        raise SystemExit(f"{n} chips not divisible by --spatial {args.spatial}")
    global_batch = args.batch_per_chip * (n // args.spatial)
    cfg = Config(
        model=ModelConfig(compute_dtype=args.dtype, image_size=args.image),
        train=TrainConfig(batch_size=global_batch),
        parallel=ParallelConfig(spatial_parallelism=args.spatial),
    )
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        state = create_state(cfg, jax.random.PRNGKey(0))
    plan = make_mesh_plan(cfg.parallel, devices=devs)
    step = shard_train_step(plan, make_train_step(cfg, global_batch))

    x = jax.ShapeDtypeStruct((global_batch, args.image, args.image, 3),
                             jnp.float32)
    w = jax.ShapeDtypeStruct((global_batch,), jnp.float32)
    say(f"lowering sharded step: global batch {global_batch} on {n} chips")
    lowered = step.lower(state, x, x, w)
    say("compiling (XLA:TPU SPMD via local libtpu)")
    t0 = time.perf_counter()
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0
    say(f"compiled in {compile_s:.1f}s")

    from tools.aot_analyze import extract_analysis, merge_into_report

    hlo = compiled.as_text()
    collectives = all_reduce_traffic(hlo)
    job = {
        "config": {
            "dtype": args.dtype, "image": args.image,
            "topology": f"{gen}:{args.topology}", "n_chips": n,
            "batch_per_chip": args.batch_per_chip,
            "global_batch": global_batch,
            "spatial_parallelism": args.spatial,
        },
        "compile_seconds": round(compile_s, 1),
        "collectives": collectives,
        "hlo_stats": {
            "n_fusions": hlo.count(" fusion("),
            "n_convs": hlo.count("convolution("),
            # Same sync+async accounting as all_reduce_traffic, so the
            # two reported counts cannot diverge.
            "n_all_reduce": collectives["n_all_reduce"],
            "n_collective_permute": hlo.count("collective-permute("),
            "n_all_gather": hlo.count("all-gather("),
            "n_reduce_scatter": hlo.count("reduce-scatter("),
            "n_all_to_all": hlo.count("all-to-all("),
        },
    }
    job.update(extract_analysis(compiled))

    layout = "dp" if args.spatial == 1 else f"dp{n // args.spatial}xsp{args.spatial}"
    # Topology in the tag: 2x2x1 and 4x1x1 are different programs and
    # must not overwrite each other's measured entry.
    tag = (f"multichip step/{'bf16' if args.dtype == 'bfloat16' else args.dtype}"
           f"/b{args.batch_per_chip}x{n}/{args.image}/{layout}/{args.topology}")
    merge_into_report({tag: job})
    print(json.dumps({tag: job}, indent=2))


if __name__ == "__main__":
    main()
