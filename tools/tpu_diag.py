"""Staged TPU tunnel diagnostic: init -> tiny op -> small conv -> report.

Run as the ONLY TPU process. Each stage prints a timestamped line BEFORE
it starts, so a hang is attributable to a specific stage (init vs tiny
compile vs realistic compile) — bench.py only reports after a whole
config finishes, which cannot distinguish those.

Usage: python tools/tpu_diag.py [--full]
  --full additionally builds the real generator and times one forward.
"""

from __future__ import annotations

import sys
import time

T0 = time.perf_counter()


def say(msg: str) -> None:
    print(f"[{time.perf_counter() - T0:8.1f}s] {msg}", flush=True)


def main() -> None:
    import os as _os
    import sys as _sys

    _sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from cyclegan_tpu.utils.axon_compat import (
        ensure_local_compile,
        local_compile_requested,
    )

    if local_compile_requested():
        say("registering axon LOCAL-compile backend (libtpu AOT)...")
    if ensure_local_compile():
        say("registered axon LOCAL-compile backend (libtpu AOT)")
    say("importing jax")
    import jax
    import jax.numpy as jnp

    say("jax imported; calling jax.devices() (client init / chip claim)")
    devs = jax.devices()
    say(f"init ok: {devs} backend={jax.default_backend()}")

    say("tiny op: jit(x+1) on scalar (first compile through tunnel)")
    f = jax.jit(lambda x: x + 1)
    out = f(jnp.float32(1.0))
    say("tiny op dispatched; fetching result")
    say(f"tiny op done: {float(out)}")

    say("small matmul: jit 256x256 @ 256x256 bf16")
    g = jax.jit(lambda a, b: a @ b)
    a = jnp.ones((256, 256), jnp.bfloat16)
    out = g(a, a)
    say(f"matmul done: sum={float(jnp.sum(out))}")

    say("small conv: jit 1x64x64x32 NHWC conv 3x3")
    import jax.lax as lax

    def conv(x, k):
        return lax.conv_general_dilated(
            x, k, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    h = jax.jit(conv)
    x = jnp.ones((1, 64, 64, 32), jnp.bfloat16)
    k = jnp.ones((3, 3, 32, 32), jnp.bfloat16)
    out = h(x, k)
    say(f"conv done: mean={float(jnp.mean(out)):.2f}")

    if "--full" in sys.argv:
        say("full: building real generator fwd (batch 1, 256^2)")
        import numpy as np

        from cyclegan_tpu.config import Config, ModelConfig, TrainConfig
        from cyclegan_tpu.train.state import build_models, create_state

        cfg = Config(model=ModelConfig(compute_dtype="bfloat16"),
                     train=TrainConfig(batch_size=1))
        say("create_state (init programs)")
        state = create_state(cfg, jax.random.PRNGKey(0))
        say("state created; jit generator apply")
        gen, _ = build_models(cfg)

        @jax.jit
        def fwd(p, x):
            return gen.apply(p, x)

        x = jnp.asarray(np.zeros((1, 256, 256, 3), np.float32))
        out = fwd(state.g_params, x)
        say(f"generator fwd done: {out.shape} mean={float(jnp.mean(out)):.4f}")

    say("ALL STAGES OK")


if __name__ == "__main__":
    main()
