"""Export trace events from a JSONL stream to a Chrome/Perfetto
timeline + per-hop critical-path table.

The serving pipeline (obs/trace.py) flushes one ``trace`` event per
kept request: the root span, the per-hop child spans (admit -> queue ->
stack -> submit -> device -> resolve), point events (shed/hedge/requeue
decisions), and the hedge lane's cancelled-twin ``queued`` spans
(possibly as ``late=True`` supplements sharing the trace_id — merged
back here). This tool turns any stream slice into:

- **Perfetto JSON** (``--out``): Chrome trace-event format, loadable at
  ui.perfetto.dev or chrome://tracing. One track ("thread") per replica
  plus a queue track (admit/queue/queued spans and root-span rows) and
  a hedge lane (cancelled twins + hedged device hops); point events
  render as instants on their track.
- **Critical-path table** (stdout): per (class, tenant) per-hop
  duration stats — count / mean / p50 / p95 ms — plus the e2e rollup
  and the hop-sum vs e2e reconciliation error, which for a cleanly
  traced request is ~0 by construction (the hops tile the root span).

Usage:
  python tools/trace_timeline.py runs/obs.jsonl --out trace.perfetto.json
  python tools/trace_timeline.py runs/obs.jsonl --trace-id 1f00baced00dfeed
  python tools/trace_timeline.py runs/obs.jsonl --slowest 20 --json

Stdlib only; pure host-side file reads.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional

# Span names that live on the queue track regardless of replica attr.
_QUEUE_HOPS = ("admit", "queue")
# Hop display order for the critical-path table.
_HOP_ORDER = ("admit", "queue", "stack", "submit", "device", "resolve",
              "queued")
# Training traces (obs/train_trace.py): one trace per epoch, named
# "train_epoch", whose spans are passes -> dispatches -> hop children.
_TRAIN_TRACE_NAME = "train_epoch"
_TRAIN_HOP_ORDER = ("train_pass", "test_pass", "interlude", "startup",
                    "dispatch", "drain", "data_wait", "submit",
                    "device", "resolve", "host")


def is_train_trace(tr: dict) -> bool:
    return tr.get("name") == _TRAIN_TRACE_NAME


def load_traces(path: str, limit: Optional[int] = None) -> List[dict]:
    """Read ``trace`` events from a JSONL stream, folding ``late``
    supplements into their base trace by trace_id. Returns one dict per
    trace: {trace_id, name, status, attrs, events, spans, t_start,
    t_end, dur_s, sampled, tail}. Unparseable lines are skipped (a torn
    tail from a crashed run must not kill the report)."""
    by_id: Dict[str, dict] = {}
    order: List[str] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if ev.get("event") != "trace":
                continue
            tid = ev.get("trace_id")
            if tid is None:
                continue
            if ev.get("late"):
                base = by_id.get(tid)
                if base is not None:
                    base["spans"].extend(ev.get("spans") or [])
                else:
                    # Supplement arrived without (or before) its base —
                    # keep it as a skeleton so the spans still render.
                    by_id[tid] = {"trace_id": tid, "status": "?",
                                  "spans": list(ev.get("spans") or []),
                                  "attrs": {}, "events": []}
                    order.append(tid)
                continue
            base = by_id.get(tid)
            if base is not None:
                # Base caught up with an earlier late-span skeleton
                # (or a duplicated id): keep the accumulated spans.
                extra = base["spans"]
                base = dict(ev)
                base["spans"] = list(ev.get("spans") or []) + extra
                by_id[tid] = base
            else:
                base = dict(ev)
                base["spans"] = list(ev.get("spans") or [])
                by_id[tid] = base
                order.append(tid)
            base.setdefault("attrs", {})
            base["attrs"] = base.get("attrs") or {}
            base["events"] = base.get("events") or []
            if limit is not None and len(order) > limit:
                drop = order.pop(0)
                by_id.pop(drop, None)
    return [by_id[t] for t in order if t in by_id]


def _span_track(span: dict) -> str:
    attrs = span.get("attrs") or {}
    name = span.get("name", "?")
    if name == "queued" or attrs.get("hedge"):
        return "hedge lane"
    if "replica" in attrs:
        return f"replica {attrs['replica']}"
    if name in _QUEUE_HOPS:
        return "queue"
    return "queue"


def _train_span_track(span: dict) -> str:
    """Train spans get their own track family so an epoch renders as
    passes over dispatches over hop detail, beside the serve tracks."""
    name = span.get("name", "?")
    if name.endswith("_pass") or name == "interlude":
        return "train passes"
    if name in ("dispatch", "startup", "drain"):
        return "train dispatch"
    if name == "device":
        return "train device"
    return "train hops"


def export_perfetto(traces: List[dict]) -> dict:
    """Chrome trace-event JSON: ph "X" complete events on one pid,
    one tid per track, ph "M" thread_name metadata naming the tracks,
    ph "i" instants for point events. Timestamps are microseconds
    relative to the earliest span in the slice (perf_counter epochs are
    arbitrary — only deltas mean anything)."""
    t0s = [s.get("t0") for tr in traces for s in tr["spans"]
           if s.get("t0") is not None]
    t0s += [tr.get("t_start") for tr in traces
            if tr.get("t_start") is not None]
    epoch = min(t0s) if t0s else 0.0

    def us(t: float) -> float:
        return round((t - epoch) * 1e6, 3)

    tracks: Dict[str, int] = {"requests": 1, "queue": 2,
                              "hedge lane": 3}
    events: List[dict] = []
    for tr in traces:
        tid_label = tr.get("trace_id", "?")
        attrs = tr.get("attrs") or {}
        train = is_train_trace(tr)
        if tr.get("t_start") is not None and tr.get("t_end") is not None:
            if train:
                root_track = tracks.setdefault(
                    "train epochs", len(tracks) + 1)
                root_name = f"epoch {attrs.get('epoch', '?')}"
            else:
                root_track = tracks["requests"]
                root_name = f"request {tid_label[:8]}"
            events.append({
                "name": root_name,
                "cat": tr.get("status", "?"),
                "ph": "X", "pid": 1, "tid": root_track,
                "ts": us(tr["t_start"]),
                "dur": round((tr["t_end"] - tr["t_start"]) * 1e6, 3),
                "args": dict(attrs, trace_id=tid_label,
                             status=tr.get("status")),
            })
        for span in tr["spans"]:
            t_start, t_end = span.get("t0"), span.get("t1")
            if t_start is None or t_end is None:
                continue
            track = (_train_span_track(span) if train
                     else _span_track(span))
            tid = tracks.setdefault(track, len(tracks) + 1)
            events.append({
                "name": span.get("name", "?"),
                "cat": "hop",
                "ph": "X", "pid": 1, "tid": tid,
                "ts": us(t_start),
                "dur": round((t_end - t_start) * 1e6, 3),
                "args": dict(span.get("attrs") or {},
                             trace_id=tid_label),
            })
        for ev in tr.get("events") or []:
            if ev.get("t") is None:
                continue
            inst_track = (tracks.setdefault("train epochs",
                                            len(tracks) + 1)
                          if train else tracks["queue"])
            events.append({
                "name": ev.get("name", "?"),
                "cat": "decision",
                "ph": "i", "s": "t",
                "pid": 1, "tid": inst_track,
                "ts": us(ev["t"]),
                "args": dict({k: v for k, v in ev.items()
                              if k not in ("name", "t")},
                             trace_id=tid_label),
            })
    meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
             "args": {"name": label}}
            for label, tid in sorted(tracks.items(), key=lambda kv: kv[1])]
    # sort_index keeps the track order stable (requests on top).
    meta += [{"name": "thread_sort_index", "ph": "M", "pid": 1,
              "tid": tid, "args": {"sort_index": tid}}
             for _, tid in sorted(tracks.items(), key=lambda kv: kv[1])]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def critical_path(traces: List[dict]) -> dict:
    """Per (class, tenant) per-hop stats + hop-sum vs e2e
    reconciliation. Returns {group_label: {"n", "e2e": {...}, "hops":
    {hop: {...}}, "recon_frac"}} where recon_frac is the mean
    |hop_sum - e2e| / e2e over the group's fully-traced requests."""
    groups: Dict[str, dict] = {}
    for tr in traces:
        if tr.get("status") == "?":
            continue
        attrs = tr.get("attrs") or {}
        label = "class=%s tenant=%s" % (attrs.get("class", "-"),
                                        attrs.get("tenant", "-") or "-")
        g = groups.setdefault(
            label, {"n": 0, "e2e": [], "hops": {}, "recon": []})
        g["n"] += 1
        dur = tr.get("dur_s")
        if dur is not None:
            g["e2e"].append(dur)
        hop_sum = 0.0
        complete = dur is not None
        for span in tr["spans"]:
            t0, t1 = span.get("t0"), span.get("t1")
            if t0 is None or t1 is None:
                continue
            name = span.get("name", "?")
            g["hops"].setdefault(name, []).append(t1 - t0)
            if name != "queued":  # the hedge loser's lane, not a hop
                hop_sum += t1 - t0
        if complete and dur > 0 and tr["spans"]:
            g["recon"].append(abs(hop_sum - dur) / dur)

    def stats(vals: List[float]) -> dict:
        s = sorted(vals)
        return {
            "n": len(s),
            "mean_ms": round(sum(s) / len(s) * 1e3, 3) if s else None,
            "p50_ms": round(_percentile(s, 0.5) * 1e3, 3) if s else None,
            "p95_ms": round(_percentile(s, 0.95) * 1e3, 3) if s else None,
        }

    out = {}
    for label, g in sorted(groups.items()):
        hops = {h: stats(v) for h, v in g["hops"].items()}
        ordered = {h: hops[h] for h in _HOP_ORDER if h in hops}
        ordered.update({h: v for h, v in sorted(hops.items())
                        if h not in ordered})
        out[label] = {
            "n": g["n"],
            "e2e": stats(g["e2e"]),
            "hops": ordered,
            "recon_frac": (round(sum(g["recon"]) / len(g["recon"]), 6)
                           if g["recon"] else None),
        }
    return out


def train_critical_path(traces: List[dict]) -> dict:
    """Per-epoch table for train_epoch traces, same shape as
    critical_path() so render_table works on both. recon_frac is the
    span-tiling error: |sum(root children) - epoch wall| / wall, where
    root children are the pass + interlude spans (device overlays and
    hop children are parented deeper and excluded). For a cleanly
    traced epoch this is ~0 by construction — the passes and interludes
    tile the root span exactly (obs/train_trace.py)."""
    groups: Dict[str, dict] = {}
    for tr in traces:
        if tr.get("status") == "?":
            continue
        attrs = tr.get("attrs") or {}
        label = "epoch=%s" % attrs.get("epoch", "-")
        g = groups.setdefault(
            label, {"n": 0, "e2e": [], "hops": {}, "recon": []})
        g["n"] += 1
        dur = tr.get("dur_s")
        if dur is not None:
            g["e2e"].append(dur)
        root_sum = 0.0
        for span in tr["spans"]:
            t0, t1 = span.get("t0"), span.get("t1")
            if t0 is None or t1 is None:
                continue
            name = span.get("name", "?")
            g["hops"].setdefault(name, []).append(t1 - t0)
            sattrs = span.get("attrs") or {}
            if not span.get("parent") and not sattrs.get("overlap"):
                root_sum += t1 - t0
        if dur and tr["spans"]:
            g["recon"].append(abs(root_sum - dur) / dur)

    def stats(vals: List[float]) -> dict:
        s = sorted(vals)
        return {
            "n": len(s),
            "mean_ms": round(sum(s) / len(s) * 1e3, 3) if s else None,
            "p50_ms": round(_percentile(s, 0.5) * 1e3, 3) if s else None,
            "p95_ms": round(_percentile(s, 0.95) * 1e3, 3) if s else None,
        }

    out = {}
    for label, g in sorted(groups.items()):
        hops = {h: stats(v) for h, v in g["hops"].items()}
        ordered = {h: hops[h] for h in _TRAIN_HOP_ORDER if h in hops}
        ordered.update({h: v for h, v in sorted(hops.items())
                        if h not in ordered})
        out[label] = {
            "n": g["n"],
            "e2e": stats(g["e2e"]),
            "hops": ordered,
            "recon_frac": (round(sum(g["recon"]) / len(g["recon"]), 6)
                           if g["recon"] else None),
        }
    return out


def render_table(table: dict) -> str:
    lines = []
    for label, g in table.items():
        lines.append(f"== {label}  (n={g['n']}) ==")
        lines.append(f"{'hop':<10} {'n':>6} {'mean ms':>10} "
                     f"{'p50 ms':>10} {'p95 ms':>10}")
        for hop, s in g["hops"].items():
            lines.append(
                f"{hop:<10} {s['n']:>6} {s['mean_ms']:>10} "
                f"{s['p50_ms']:>10} {s['p95_ms']:>10}")
        e = g["e2e"]
        lines.append(
            f"{'e2e':<10} {e['n']:>6} {e['mean_ms']:>10} "
            f"{e['p50_ms']:>10} {e['p95_ms']:>10}")
        recon = g["recon_frac"]
        lines.append(
            "hop-sum vs e2e reconciliation: "
            + (f"{recon * 100:.2f}% mean error"
               if recon is not None else "n/a"))
        lines.append("")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("stream", help="JSONL telemetry stream "
                                  "(--obs_jsonl / BENCH_OBS_JSONL)")
    p.add_argument("--out", default=None,
                   help="write Perfetto/Chrome trace-event JSON here")
    p.add_argument("--trace-id", default=None,
                   help="restrict to one trace_id (prefix match)")
    p.add_argument("--slowest", default=None, type=int, metavar="N",
                   help="keep only the N slowest complete traces")
    p.add_argument("--limit", default=None, type=int,
                   help="cap traces read from the stream (keeps the "
                        "most recent)")
    p.add_argument("--json", action="store_true",
                   help="print the critical-path table as JSON instead "
                        "of text")
    args = p.parse_args(argv)

    traces = load_traces(args.stream, limit=args.limit)
    if args.trace_id:
        traces = [t for t in traces
                  if t.get("trace_id", "").startswith(args.trace_id)]
    if args.slowest:
        traces = sorted(traces, key=lambda t: t.get("dur_s") or -1.0,
                        reverse=True)[:args.slowest]
    if not traces:
        print("no trace events matched "
              "(is --trace_sample > 0, or did anything fail?)",
              file=sys.stderr)
        return 1
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(export_perfetto(traces), f)
        print(f"wrote {args.out}: {len(traces)} traces "
              f"(load at ui.perfetto.dev)", file=sys.stderr)
    train = [t for t in traces if is_train_trace(t)]
    serve = [t for t in traces if not is_train_trace(t)]
    table = critical_path(serve) if serve else {}
    ttable = train_critical_path(train) if train else {}
    if args.json:
        merged = dict(table)
        merged.update(ttable)
        print(json.dumps(merged, indent=2))
    else:
        if table:
            print(render_table(table))
        if ttable:
            print("==== training epochs ====")
            print(render_table(ttable))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
