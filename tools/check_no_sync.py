"""Static no-sync check for the training hot path.

    python tools/check_no_sync.py          # exit 1 on any violation

The dispatch loop's whole performance story rests on staying
asynchronous (train/loop.py: deferred metric fetch, bounded
backpressure). The telemetry subsystem (cyclegan_tpu/obs) instruments
that loop and must never re-serialize it, so this check enforces two
rules over the hot-path files:

1. `block_until_ready` is forbidden everywhere in them. It is both a
   sync AND a lie through the remote-TPU tunnel (returns at
   dispatch-complete — docs/TPU_RUNBOOK.md ground rule 4).
2. `device_get` is forbidden except on lines carrying a
   `sanctioned-fetch` marker comment — the deferred fetches the loop's
   design already requires (backpressure window, end-of-epoch drain).
   In `cyclegan_tpu/obs/` there are no sanctioned sites at all:
   telemetry only timestamps fetches the loop performs. Likewise every
   kernel wrapper under `cyclegan_tpu/ops/pallas/` (scanned as a
   directory): they run INSIDE the fused train step, where any host
   sync would serialize the dispatch pipeline. The serving path
   (`cyclegan_tpu/serve/`, also scanned as a directory) follows the
   loop's rule: its one deferred D2H per flush lives on the completer
   thread behind a `sanctioned-fetch` marker; everywhere else a fetch
   would stall the dispatch/batching threads.

Comments and docstrings are exempt (they may DISCUSS the forbidden
calls); only code can violate. Runs in tier-1 via
tests/test_obs.py::test_hot_path_has_no_sync.
"""

from __future__ import annotations

import io
import os
import sys
import tokenize
from typing import List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FORBIDDEN_ALWAYS = ("block_until_ready",)
FORBIDDEN_UNSANCTIONED = ("device_get",)
SANCTION_MARKER = "sanctioned-fetch"

# (path, allow_sanctioned_fetches)
HOT_PATH_FILES: List[Tuple[str, bool]] = [
    ("cyclegan_tpu/train/loop.py", True),
    # The epoch-services worker exists to take host I/O OFF the dispatch
    # path; a device fetch on it would re-serialize the boundary it
    # overlaps (callers hand it already-fetched host copies).
    ("cyclegan_tpu/utils/services.py", False),
    # Both gradient engines (combined jax.grad and the fusedprop vjp
    # path) build traced-only code; any host fetch here would run once
    # per step inside the dispatch chain. Zero sanctioned sites.
    ("cyclegan_tpu/train/steps.py", False),
    # Elastic recovery: the module's ONE sanctioned site class is the
    # restore-time gather in reshard_to_plan (before any dispatch
    # exists); the breaker/emergency-save paths that run DURING the
    # loop must stay fetch-free. Overrides the resil/ directory default
    # below (explicit file entries win over directory expansion).
    ("cyclegan_tpu/resil/elastic.py", True),
]

# Directories whose EVERY .py file is hot-path. Scanned as a directory
# (not a file list) so a new module is covered the day it lands:
# - obs (no sanctioned sites): telemetry only timestamps fetches the
#   loop performs, and the health layer (obs/health.py) only computes
#   inside the jitted step / consumes already-fetched host rows — the
#   directory scan is what keeps that promise as the package grows.
# - ops/pallas (no sanctioned sites): kernel wrappers run INSIDE the
#   fused train step — a host sync there would serialize every dispatch.
# - serve (sanctioned sites allowed): the serving pipeline's whole
#   design is deferred fetches — the completer thread's one bounded
#   `device_get` per flush carries the marker; anything else (an
#   engine/batcher/server sync) would re-serialize the pipeline.
# - serve/fleet (sanctioned sites allowed): listed separately because
#   the directory scan is deliberately non-recursive; the replica
#   worker's one deferred fetch per flush is the package's only
#   sanctioned sync — admission/dispatch must stay pure host-side
#   queueing.
HOT_PATH_DIRS: List[Tuple[str, bool]] = [
    ("cyclegan_tpu/obs", False),
    ("cyclegan_tpu/ops/pallas", False),
    ("cyclegan_tpu/serve", True),
    ("cyclegan_tpu/serve/fleet", True),
    # resil (no sanctioned sites by default): fault injection, retry,
    # and rollback are pure host-side orchestration at dispatch/IO
    # boundaries — a device sync here would put a stall INSIDE the
    # recovery machinery that exists to keep the loop async under
    # failure. elastic.py alone carries an explicit file entry above
    # (one sanctioned restore-time gather).
    ("cyclegan_tpu/resil", False),
]


def hot_path_entries(repo: str = REPO) -> List[Tuple[str, bool]]:
    """The static file list plus every .py under the hot-path dirs,
    deduplicated with explicit HOT_PATH_FILES entries taking precedence
    over directory expansion (a file may need a different sanction
    policy than its directory's default). A missing directory is
    reported as a missing file entry (the check must fail loudly, not
    silently shrink)."""
    policy = {rel: allow for rel, allow in HOT_PATH_FILES}
    order = [rel for rel, _ in HOT_PATH_FILES]
    for rel, allow in HOT_PATH_DIRS:
        d = os.path.join(repo, rel)
        if not os.path.isdir(d):
            if rel not in policy:
                policy[rel] = allow
                order.append(rel)
            continue
        for name in sorted(os.listdir(d)):
            if not name.endswith(".py"):
                continue
            sub = os.path.join(rel, name)
            if sub not in policy:
                policy[sub] = allow
                order.append(sub)
    return [(rel, policy[rel]) for rel in order]


def _code_lines(source: str) -> dict:
    """line number -> code-only text (comments and string literals,
    docstrings included, stripped via the tokenizer)."""
    lines: dict = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type in (tokenize.COMMENT, tokenize.STRING, tokenize.NL,
                            tokenize.NEWLINE, tokenize.INDENT,
                            tokenize.DEDENT):
                continue
            row = tok.start[0]
            lines[row] = lines.get(row, "") + " " + tok.string
    except tokenize.TokenizeError:
        # Unparseable file: fall back to raw lines (conservative — may
        # flag mentions inside strings, better than missing real calls).
        for i, raw in enumerate(source.splitlines(), 1):
            lines[i] = raw
    return lines


def check_file(path: str, allow_sanctioned: bool) -> List[str]:
    violations = []
    with open(path) as f:
        source = f.read()
    raw_lines = source.splitlines()
    for row, code in sorted(_code_lines(source).items()):
        raw = raw_lines[row - 1] if row <= len(raw_lines) else ""
        for tok in FORBIDDEN_ALWAYS:
            if tok in code:
                violations.append(
                    f"{path}:{row}: forbidden sync `{tok}` in the hot path"
                )
        for tok in FORBIDDEN_UNSANCTIONED:
            if tok in code:
                if allow_sanctioned and SANCTION_MARKER in raw:
                    continue
                where = ("missing `# sanctioned-fetch` marker"
                         if allow_sanctioned
                         else "no sanctioned sites exist in obs/")
                violations.append(
                    f"{path}:{row}: `{tok}` outside the sanctioned fetch "
                    f"window ({where})"
                )
    return violations


def run_check(repo: str = REPO) -> List[str]:
    violations: List[str] = []
    for rel, allow in hot_path_entries(repo):
        path = os.path.join(repo, rel)
        if not os.path.exists(path):
            violations.append(f"{rel}: hot-path file missing")
            continue
        violations.extend(check_file(path, allow))
    return violations


def main() -> int:
    violations = run_check()
    if violations:
        print("no-sync check FAILED:", file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    n = len(hot_path_entries())
    print(f"no-sync check passed: {n} hot-path files clean "
          f"(block_until_ready absent; device_get only at "
          f"sanctioned-fetch sites)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
