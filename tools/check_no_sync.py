"""Static no-sync check for the training hot path.

    python tools/check_no_sync.py          # exit 1 on any violation

The dispatch loop's whole performance story rests on staying
asynchronous (train/loop.py: deferred metric fetch, bounded
backpressure), so this check enforces two rules over the hot-path
files:

1. `block_until_ready` is forbidden everywhere in them. It is both a
   sync AND a lie through the remote-TPU tunnel (returns at
   dispatch-complete — docs/TPU_RUNBOOK.md ground rule 4).
2. `device_get` is forbidden except on lines carrying a
   `sanctioned-fetch` marker comment — the deferred fetches the loop's
   design already requires (backpressure window, end-of-epoch drain).

Since graftlint landed this is a thin wrapper over its AST-based
`no-sync` rule (tools/graftlint/rules/nosync.py, which also owns the
hot-path table) — same CLI, same exit codes, same verdict messages,
but the scan now resolves names semantically: comments, docstrings,
and string literals can never violate (they may DISCUSS the forbidden
calls), aliased imports like `from jax import device_get as g` are
caught, and unrelated identifiers merely containing a forbidden token
no longer flag — the token scanner's known false-positive/negative
classes. Runs in tier-1 via
tests/test_obs.py::test_hot_path_has_no_sync.
"""

from __future__ import annotations

import os
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from graftlint.rules.nosync import (  # noqa: E402,F401  (public table re-exports)
    FORBIDDEN_ALWAYS,
    FORBIDDEN_UNSANCTIONED,
    HOT_PATH_DIRS,
    HOT_PATH_FILES,
    SANCTION_MARKER,
    check_file_violations,
    hot_path_entries as _hot_path_entries,
    run_check as _run_check,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def hot_path_entries(repo: str = REPO):
    return _hot_path_entries(repo)


def check_file(path: str, allow_sanctioned: bool) -> List[str]:
    return check_file_violations(path, allow_sanctioned)


def run_check(repo: str = REPO) -> List[str]:
    return _run_check(repo)


def main() -> int:
    violations = run_check()
    if violations:
        print("no-sync check FAILED:", file=sys.stderr)
        for v in violations:
            print(f"  {v}", file=sys.stderr)
        return 1
    n = len(hot_path_entries())
    print(f"no-sync check passed: {n} hot-path files clean "
          f"(block_until_ready absent; device_get only at "
          f"sanctioned-fetch sites)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
