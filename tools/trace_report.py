#!/usr/bin/env python
"""Mine committed profiler artifacts into op-level attribution tables.

The chip runs commit their raw profiler output (docs/chip_logs/r*/
trace_run/traces/.../vm.xplane.pb and vm.trace.json.gz) but nothing in
the repo ever reads them — the 13x bench-vs-training gap analysis needs
to know WHERE device time goes, not just that an epoch is slow. This
tool parses the XPlane protobuf with a self-contained wire-format
reader (the image's TF build lacks a working `xspace_to_tools_data`,
and installing one is off the table), so it needs no dependencies
beyond the stdlib.

What it reports, from the `/device:TPU:*` plane:

- per-op table: HLO program symbols aggregated over all occurrences,
  with device time, occurrence count, bytes accessed (HBM traffic as
  XLA's cost model recorded it), and achieved bytes/s;
- bucket rollup: conv-transpose vs plain conv vs layout-copy vs
  instance-norm stats vs fusion/other — the axes the optimisation
  roadmap (ROADMAP.md) argues about;
- device idle fraction: 1 - (merged busy intervals / plane span), the
  direct measurement of "the loop starves the chip";
- step timings from the profiler's Steps line.

For the Perfetto-style vm.trace.json.gz (host-side only — it carries
no device op detail) a smaller host-function table is printed instead.

Usage:
    python tools/trace_report.py [PATH] [--top N] [--markdown] [--json]
    python tools/trace_report.py --diff A B [--markdown] [--json]

PATH may be an .xplane.pb file, a .trace.json.gz file, or a directory
to search (default: newest profile dir under docs/chip_logs/*/).

`--diff A B` mines both artifacts and prints a per-bucket delta table
(B minus A) plus the idle-fraction delta — the check that a claimed
optimisation (fusedprop shared forwards, the perturb trunk tier)
actually moved the conv bucket rather than shuffling time between
categories.
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import struct
import sys
from collections import defaultdict
from typing import Dict, Iterator, List, Optional, Tuple

# --------------------------------------------------------------------------
# Protobuf wire-format primitives. The XPlane schema (tensorflow/profiler/
# protobuf/xplane.proto) is stable; we read only the fields we need and skip
# everything else by wire type, so unknown fields cost nothing.
# --------------------------------------------------------------------------


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    value = 0
    while True:
        b = buf[i]
        i += 1
        value |= (b & 0x7F) << shift
        if not b & 0x80:
            return value, i
        shift += 7


def _fields(buf: bytes, off: int, end: int) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) for one message's span.

    Length-delimited values come back as an (offset, length) span into
    `buf` — callers slice lazily, so scanning a 146 MB file never copies
    payloads it does not read.
    """
    i = off
    while i < end:
        tag, i = _read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == 2:  # length-delimited
            length, i = _read_varint(buf, i)
            yield field, wt, (i, length)
            i += length
        elif wt == 0:  # varint
            value, i = _read_varint(buf, i)
            yield field, wt, value
        elif wt == 1:  # 64-bit
            yield field, wt, buf[i : i + 8]
            i += 8
        elif wt == 5:  # 32-bit
            yield field, wt, buf[i : i + 4]
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wt} at offset {i}")


def _text(buf: bytes, span: Tuple[int, int]) -> str:
    off, length = span
    return buf[off : off + length].decode("utf-8", errors="replace")


# XPlane field numbers (xplane.proto).
_XSPACE_PLANES = 1
_XPLANE_NAME = 2
_XPLANE_LINES = 3
_XPLANE_EVENT_METADATA = 4  # map<int64, XEventMetadata>
_XPLANE_STAT_METADATA = 5  # map<int64, XStatMetadata>
_XLINE_NAME = 2
_XLINE_TIMESTAMP_NS = 3
_XLINE_EVENTS = 4
_XLINE_DISPLAY_NAME = 11
_XEVENT_METADATA_ID = 1
_XEVENT_OFFSET_PS = 2
_XEVENT_DURATION_PS = 3
_XEVENTMETA_NAME = 2
_XEVENTMETA_STATS = 5
_XSTAT_METADATA_ID = 1
_XSTAT_DOUBLE = 2
_XSTAT_UINT64 = 3
_XSTAT_INT64 = 4
_XSTAT_STR = 5
_XSTAT_BYTES = 6
_XSTAT_REF = 7


def iter_plane_spans(buf: bytes) -> Iterator[Tuple[str, Tuple[int, int]]]:
    """(plane_name, span) for every XPlane in an XSpace, peeking only the
    name field so non-matching planes are skipped without a full parse."""
    for field, wt, value in _fields(buf, 0, len(buf)):
        if field != _XSPACE_PLANES or wt != 2:
            continue
        off, length = value
        name = ""
        for f2, wt2, v2 in _fields(buf, off, off + length):
            if f2 == _XPLANE_NAME and wt2 == 2:
                name = _text(buf, v2)
                break
        yield name, (off, off + length)


def _parse_stat(buf: bytes, span: Tuple[int, int], stat_names: Dict[int, str]):
    """One XStat -> (stat_name, python value)."""
    off, length = span
    meta_id = None
    value = None
    for field, wt, v in _fields(buf, off, off + length):
        if field == _XSTAT_METADATA_ID and wt == 0:
            meta_id = v
        elif field == _XSTAT_DOUBLE and wt == 1:
            value = struct.unpack("<d", v)[0]
        elif field in (_XSTAT_UINT64, _XSTAT_INT64) and wt == 0:
            value = v
        elif field in (_XSTAT_STR, _XSTAT_BYTES) and wt == 2:
            value = _text(buf, v)
        elif field == _XSTAT_REF and wt == 0:
            value = stat_names.get(v, v)
    return stat_names.get(meta_id, meta_id), value


def parse_plane(buf: bytes, span: Tuple[int, int]) -> dict:
    """Fully parse one XPlane into plain python structures."""
    off, end = span
    stat_names: Dict[int, str] = {}
    meta_spans: List[Tuple[int, int]] = []
    line_spans: List[Tuple[int, int]] = []
    name = ""
    # Pass 1: stat_metadata first — event metadata stats reference it by id,
    # and map entries may appear in any order in the stream.
    for field, wt, value in _fields(buf, off, end):
        if field == _XPLANE_NAME and wt == 2:
            name = _text(buf, value)
        elif field == _XPLANE_STAT_METADATA and wt == 2:
            o, length = value
            key = None
            stat_name = None
            for f2, wt2, v2 in _fields(buf, o, o + length):
                if f2 == 1 and wt2 == 0:
                    key = v2
                elif f2 == 2 and wt2 == 2:
                    o3, l3 = v2
                    for f3, wt3, v3 in _fields(buf, o3, o3 + l3):
                        if f3 == 2 and wt3 == 2:  # XStatMetadata.name
                            stat_name = _text(buf, v3)
            if key is not None and stat_name is not None:
                stat_names[key] = stat_name
        elif field == _XPLANE_EVENT_METADATA and wt == 2:
            meta_spans.append(value)
        elif field == _XPLANE_LINES and wt == 2:
            line_spans.append(value)

    event_meta: Dict[int, dict] = {}
    for o, length in meta_spans:
        key = None
        body = None
        for f2, wt2, v2 in _fields(buf, o, o + length):
            if f2 == 1 and wt2 == 0:
                key = v2
            elif f2 == 2 and wt2 == 2:
                body = v2
        if body is None:
            continue
        bo, bl = body
        rec = {"name": "", "stats": {}}
        for f3, wt3, v3 in _fields(buf, bo, bo + bl):
            if f3 == _XEVENTMETA_NAME and wt3 == 2:
                rec["name"] = _text(buf, v3)
            elif f3 == _XEVENTMETA_STATS and wt3 == 2:
                sname, sval = _parse_stat(buf, v3, stat_names)
                if sname is not None:
                    rec["stats"][sname] = sval
        event_meta[key if key is not None else 0] = rec

    lines = []
    for o, length in line_spans:
        line = {"name": "", "timestamp_ns": 0, "events": []}
        for f2, wt2, v2 in _fields(buf, o, o + length):
            if f2 in (_XLINE_NAME, _XLINE_DISPLAY_NAME) and wt2 == 2:
                line["name"] = _text(buf, v2) or line["name"]
            elif f2 == _XLINE_TIMESTAMP_NS and wt2 == 0:
                line["timestamp_ns"] = v2
            elif f2 == _XLINE_EVENTS and wt2 == 2:
                eo, el = v2
                mid = 0
                offset_ps = 0
                duration_ps = 0
                for f3, wt3, v3 in _fields(buf, eo, eo + el):
                    if wt3 != 0:
                        continue
                    if f3 == _XEVENT_METADATA_ID:
                        mid = v3
                    elif f3 == _XEVENT_OFFSET_PS:
                        offset_ps = v3
                    elif f3 == _XEVENT_DURATION_PS:
                        duration_ps = v3
                line["events"].append((mid, offset_ps, duration_ps))
        lines.append(line)

    return {"name": name, "stat_names": stat_names, "event_meta": event_meta, "lines": lines}


# --------------------------------------------------------------------------
# Mining: op aggregation, bucket rollup, idle fraction.
# --------------------------------------------------------------------------

# Bucket identifiers, in report order. These are the axes the repo's perf
# work argues about: the generator's upsampling ConvTranspose path vs its
# plain convs, layout copies (the historical NCHW/NHWC tax), the
# instance-norm statistics reductions (Pallas epilogue target), and
# everything else.
BUCKETS = (
    "conv-transpose",
    "conv",
    "layout-copy",
    "in-stats",
    "fusion-other",
    "data-movement",
    "other",
)


def _short_name(meta: dict) -> str:
    """Stable short symbol for an HLO op: deduplicated name when XLA
    recorded one, else the lhs of the HLO text with the .NNN instance
    suffix kept (it distinguishes distinct program points)."""
    dedup = meta["stats"].get("deduplicated_name")
    if dedup:
        return str(dedup)
    name = meta["name"]
    head = name.split(" = ", 1)[0].strip()
    return head.lstrip("%") or name[:40]


def classify(meta: dict) -> str:
    cat = str(meta["stats"].get("hlo_category", "")).lower()
    prov = str(meta["stats"].get("tf_op", "")).lower()
    name = _short_name(meta).lower()
    squashed_prov = prov.replace("_", "").replace("-", "")
    if "conv" in cat or name.startswith("convolution") or "%convolution" in meta["name"].lower():
        if "convtranspose" in squashed_prov:
            return "conv-transpose"
        return "conv"
    if "copy" in cat or cat in ("transpose", "bitcast", "reshape") or name.startswith(
        ("copy", "transpose", "bitcast")
    ):
        return "layout-copy"
    # The fused zero-skip upsample kernel (ops/pallas/upsample_kernel.py)
    # surfaces as a Mosaic custom-call (or a fusion wrapping one) whose
    # provenance is the upsample_norm_relu_pad scope: it IS the
    # transposed-conv work (phase MXU dots + interleave; the IN/ReLU
    # epilogue rides along), so it rolls into conv-transpose — the
    # bucket its unfused counterpart's convs land in.
    if "upsamplenormrelupad" in squashed_prov or "zeroskip" in squashed_prov:
        return "conv-transpose"
    if "instancenorm" in squashed_prov or (
        ("reduce" in cat or name.startswith(("reduce", "variance", "mean"))) and "norm" in prov
    ):
        # Includes the Pallas epilogue custom-call sites (residual-trunk
        # AND the discriminator's fused IN>LeakyReLU tails — the
        # instance_norm_act_pad scope), keeping them out of
        # fusion-other/other.
        return "in-stats"
    if "fusion" in cat:
        # Fusions rooted in a ConvTranspose scope are part of the
        # transposed-conv cost even though XLA labels them fusion.
        if "convtranspose" in squashed_prov:
            return "conv-transpose"
        return "fusion-other"
    if "async" in cat or cat.startswith("all-") or "infeed" in cat or "outfeed" in cat:
        return "data-movement"
    return "other"


def _find_line(plane: dict, wanted: str) -> Optional[dict]:
    for line in plane["lines"]:
        if line["name"] == wanted:
            return line
    return None


def _merged_busy_ps(events: List[Tuple[int, int, int]]) -> Tuple[int, int]:
    """(busy_ps, span_ps) from possibly-overlapping event intervals."""
    if not events:
        return 0, 0
    ivs = sorted((off, off + dur) for _, off, dur in events)
    busy = 0
    cur_start, cur_end = ivs[0]
    lo = ivs[0][0]
    hi = ivs[0][1]
    for start, end in ivs[1:]:
        hi = max(hi, end)
        if start <= cur_end:
            cur_end = max(cur_end, end)
        else:
            busy += cur_end - cur_start
            cur_start, cur_end = start, end
    busy += cur_end - cur_start
    return busy, hi - lo


def mine_xplane(path: str, plane_prefix: str = "/device:") -> dict:
    """Parse PATH and aggregate the first matching device plane."""
    with open(path, "rb") as f:
        buf = f.read()
    plane = None
    available = []
    for name, span in iter_plane_spans(buf):
        available.append(name)
        if plane is None and name.startswith(plane_prefix):
            plane = parse_plane(buf, span)
    if plane is None:
        raise SystemExit(
            f"no plane matching {plane_prefix!r} in {path}; planes present: {available}"
        )

    ops_line = _find_line(plane, "XLA Ops")
    if ops_line is None or not ops_line["events"]:
        raise SystemExit(f"device plane {plane['name']} has no 'XLA Ops' line to mine")

    # Key by (symbol, category): XLA records distinct metadata ids for
    # deduplicated instances of the same program point, and listing six
    # identical `fusion.219` rows helps no one.
    per_op: Dict[Tuple[str, str], dict] = {}
    meta_cache: Dict[int, Tuple[Tuple[str, str], dict]] = {}
    for mid, _off, dur in ops_line["events"]:
        cached = meta_cache.get(mid)
        if cached is None:
            meta = plane["event_meta"].get(mid, {"name": f"<metadata {mid}>", "stats": {}})
            key = (_short_name(meta), str(meta["stats"].get("hlo_category", "")))
            cached = meta_cache[mid] = (key, meta)
        key, meta = cached
        rec = per_op.get(key)
        if rec is None:
            rec = per_op[key] = {
                "name": key[0],
                "category": key[1],
                "bucket": classify(meta),
                "provenance": str(meta["stats"].get("tf_op", ""))[:160],
                "count": 0,
                "total_ps": 0,
                "bytes_total": 0,
                "flops_total": 0,
            }
        rec["count"] += 1
        rec["total_ps"] += dur
        rec["bytes_total"] += int(meta["stats"].get("bytes_accessed", 0) or 0)
        rec["flops_total"] += int(meta["stats"].get("flops", 0) or 0)

    busy_ps, span_ps = _merged_busy_ps(ops_line["events"])
    total_op_ps = sum(r["total_ps"] for r in per_op.values())

    ops = []
    for rec in per_op.values():
        total_s = rec["total_ps"] / 1e12
        total_bytes = rec["bytes_total"]
        ops.append(
            {
                "name": rec["name"],
                "category": rec["category"],
                "bucket": rec["bucket"],
                "provenance": rec["provenance"],
                "count": rec["count"],
                "total_ms": rec["total_ps"] / 1e9,
                "avg_us": rec["total_ps"] / rec["count"] / 1e6,
                "pct_of_op_time": 100.0 * rec["total_ps"] / total_op_ps if total_op_ps else 0.0,
                "bytes_total": total_bytes,
                "gbytes_per_s": (total_bytes / total_s / 1e9) if total_s > 0 else 0.0,
                "flops_total": rec["flops_total"],
            }
        )
    ops.sort(key=lambda r: r["total_ms"], reverse=True)

    buckets = {b: {"total_ms": 0.0, "count": 0, "bytes_total": 0} for b in BUCKETS}
    for op in ops:
        b = buckets[op["bucket"]]
        b["total_ms"] += op["total_ms"]
        b["count"] += op["count"]
        b["bytes_total"] += op["bytes_total"]
    for b in buckets.values():
        b["pct_of_op_time"] = 100.0 * b["total_ms"] * 1e9 / total_op_ps if total_op_ps else 0.0

    steps_line = _find_line(plane, "Steps")
    step_ms = [dur / 1e9 for _, _, dur in steps_line["events"]] if steps_line else []

    modules_line = _find_line(plane, "XLA Modules")
    modules = []
    if modules_line:
        agg = defaultdict(lambda: [0, 0])
        for mid, _off, dur in modules_line["events"]:
            meta = plane["event_meta"].get(mid, {"name": f"<metadata {mid}>", "stats": {}})
            entry = agg[meta["name"].split("(")[0]]
            entry[0] += 1
            entry[1] += dur
        modules = [
            {"name": n, "count": c, "total_ms": ps / 1e9} for n, (c, ps) in sorted(agg.items())
        ]

    return {
        "path": path,
        "plane": plane["name"],
        "n_ops_distinct": len(ops),
        "n_op_events": len(ops_line["events"]),
        "span_ms": span_ps / 1e9,
        "busy_ms": busy_ps / 1e9,
        "idle_fraction": (1.0 - busy_ps / span_ps) if span_ps else 0.0,
        "steps_ms": step_ms,
        "modules": modules,
        "buckets": buckets,
        "ops": ops,
    }


def diff_reports(report_a: dict, report_b: dict) -> dict:
    """Per-bucket deltas between two mined device reports (B minus A).

    Works on the bucket rollup rather than per-op rows because op
    symbols are not stable across programs — a fusedprop step and a
    combined step fuse differently, so `fusion.219` in one trace has no
    counterpart in the other.  The BUCKETS axes are the comparable
    vocabulary.
    """
    rows = []
    for name in BUCKETS:
        a = report_a["buckets"].get(name, {"total_ms": 0.0, "count": 0,
                                           "pct_of_op_time": 0.0})
        b = report_b["buckets"].get(name, {"total_ms": 0.0, "count": 0,
                                           "pct_of_op_time": 0.0})
        rows.append({
            "bucket": name,
            "a_ms": a["total_ms"],
            "b_ms": b["total_ms"],
            "delta_ms": b["total_ms"] - a["total_ms"],
            "a_pct": a.get("pct_of_op_time", 0.0),
            "b_pct": b.get("pct_of_op_time", 0.0),
            "delta_pct": (b.get("pct_of_op_time", 0.0)
                          - a.get("pct_of_op_time", 0.0)),
        })
    return {
        "kind": "diff",
        "path_a": report_a["path"],
        "path_b": report_b["path"],
        "buckets": rows,
        "a_busy_ms": report_a["busy_ms"],
        "b_busy_ms": report_b["busy_ms"],
        "delta_busy_ms": report_b["busy_ms"] - report_a["busy_ms"],
        "a_idle_fraction": report_a["idle_fraction"],
        "b_idle_fraction": report_b["idle_fraction"],
        "delta_idle_fraction": (report_b["idle_fraction"]
                                - report_a["idle_fraction"]),
    }


def render_diff(diff: dict, markdown: bool) -> str:
    out: List[str] = [
        f"trace diff: A={diff['path_a']}",
        f"            B={diff['path_b']}",
        f"  busy {diff['a_busy_ms']:.2f} ms -> {diff['b_busy_ms']:.2f} ms "
        f"({diff['delta_busy_ms']:+.2f} ms); "
        f"idle {100 * diff['a_idle_fraction']:.2f}% -> "
        f"{100 * diff['b_idle_fraction']:.2f}% "
        f"({100 * diff['delta_idle_fraction']:+.2f} pp)",
        "",
    ]
    rows = sorted(diff["buckets"], key=lambda r: abs(r["delta_ms"]),
                  reverse=True)
    if markdown:
        out.append("| bucket | A (ms) | B (ms) | Δ ms | A % | B % | Δ pp |")
        out.append("|---|---:|---:|---:|---:|---:|---:|")
        for r in rows:
            out.append(
                f"| {r['bucket']} | {r['a_ms']:.2f} | {r['b_ms']:.2f} "
                f"| {r['delta_ms']:+.2f} | {r['a_pct']:.1f}% "
                f"| {r['b_pct']:.1f}% | {r['delta_pct']:+.1f} |"
            )
    else:
        out.append(f"{'bucket':<16} {'A ms':>10} {'B ms':>10} {'Δ ms':>10} "
                   f"{'A %':>7} {'B %':>7} {'Δ pp':>7}")
        for r in rows:
            out.append(
                f"{r['bucket']:<16} {r['a_ms']:>10.2f} {r['b_ms']:>10.2f} "
                f"{r['delta_ms']:>+10.2f} {r['a_pct']:>6.1f}% "
                f"{r['b_pct']:>6.1f}% {r['delta_pct']:>+7.1f}"
            )
    return "\n".join(out)


# --------------------------------------------------------------------------
# Host-trace fallback (vm.trace.json.gz has host threads only — no device
# op detail — but its top functions still show where the HOST went).
# --------------------------------------------------------------------------


def mine_host_json(path: str, top: int = 15) -> dict:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8", errors="replace") as f:
        doc = json.load(f)
    agg = defaultdict(lambda: [0, 0.0])
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "X" and "dur" in ev:
            entry = agg[ev.get("name", "?")]
            entry[0] += 1
            entry[1] += float(ev["dur"])  # microseconds
    rows = [
        {"name": n, "count": c, "total_ms": us / 1e3}
        for n, (c, us) in sorted(agg.items(), key=lambda kv: kv[1][1], reverse=True)
    ]
    return {"path": path, "kind": "host-trace", "functions": rows[:top]}


# --------------------------------------------------------------------------
# Rendering.
# --------------------------------------------------------------------------


def _fmt_bytes(n: int) -> str:
    if n >= 1e9:
        return f"{n / 1e9:.2f} GB"
    if n >= 1e6:
        return f"{n / 1e6:.2f} MB"
    return f"{n / 1e3:.1f} KB"


def render(report: dict, top: int, markdown: bool) -> str:
    out: List[str] = []
    if report.get("kind") == "host-trace":
        out.append(f"host trace: {report['path']} (no device ops in this artifact)")
        for row in report["functions"]:
            out.append(f"  {row['total_ms']:10.2f} ms  x{row['count']:<6d} {row['name']}")
        return "\n".join(out)

    steps = report["steps_ms"]
    step_note = (
        f"{len(steps)} steps, mean {sum(steps) / len(steps):.2f} ms" if steps else "no Steps line"
    )
    head = [
        f"device plane {report['plane']} from {report['path']}",
        f"  {report['n_op_events']} op events over {report['n_ops_distinct']} distinct ops; "
        f"span {report['span_ms']:.2f} ms, busy {report['busy_ms']:.2f} ms, "
        f"idle {100 * report['idle_fraction']:.2f}%",
        f"  {step_note}"
        + (
            "; modules: "
            + ", ".join(f"{m['name']} x{m['count']} {m['total_ms']:.1f} ms" for m in report["modules"])
            if report["modules"]
            else ""
        ),
    ]

    bucket_rows = sorted(
        report["buckets"].items(), key=lambda kv: kv[1]["total_ms"], reverse=True
    )
    op_rows = report["ops"][:top]

    if markdown:
        out.extend(head)
        out.append("")
        out.append("| bucket | device time (ms) | % of op time | events | bytes accessed |")
        out.append("|---|---:|---:|---:|---:|")
        for name, b in bucket_rows:
            out.append(
                f"| {name} | {b['total_ms']:.2f} | {b['pct_of_op_time']:.1f}% "
                f"| {b['count']} | {_fmt_bytes(b['bytes_total'])} |"
            )
        out.append("")
        out.append("| op | category | bucket | n | total ms | avg us | % | bytes | GB/s |")
        out.append("|---|---|---|---:|---:|---:|---:|---:|---:|")
        for op in op_rows:
            out.append(
                f"| `{op['name'][:48]}` | {op['category']} | {op['bucket']} | {op['count']} "
                f"| {op['total_ms']:.2f} | {op['avg_us']:.1f} | {op['pct_of_op_time']:.1f}% "
                f"| {_fmt_bytes(op['bytes_total'])} | {op['gbytes_per_s']:.0f} |"
            )
    else:
        out.extend(head)
        out.append("")
        out.append(f"{'bucket':<16} {'ms':>10} {'%':>7} {'events':>8}  bytes")
        for name, b in bucket_rows:
            out.append(
                f"{name:<16} {b['total_ms']:>10.2f} {b['pct_of_op_time']:>6.1f}% "
                f"{b['count']:>8d}  {_fmt_bytes(b['bytes_total'])}"
            )
        out.append("")
        out.append(f"top {len(op_rows)} ops by device time:")
        out.append(f"{'ms':>10} {'avg us':>9} {'n':>6} {'%':>6}  {'bucket':<14} op")
        for op in op_rows:
            out.append(
                f"{op['total_ms']:>10.2f} {op['avg_us']:>9.1f} {op['count']:>6d} "
                f"{op['pct_of_op_time']:>5.1f}%  {op['bucket']:<14} {op['name'][:60]}"
            )
    return "\n".join(out)


def _default_search() -> Optional[str]:
    hits = sorted(glob.glob("docs/chip_logs/*/trace_run/traces/plugins/profile/*/*.xplane.pb"))
    return hits[-1] if hits else None


def _resolve(path: Optional[str]) -> str:
    if path is None:
        found = _default_search()
        if not found:
            raise SystemExit(
                "no xplane artifact found under docs/chip_logs/*/trace_run; pass a path"
            )
        return found
    if os.path.isdir(path):
        for pattern in ("**/*.xplane.pb", "**/*.trace.json.gz"):
            hits = sorted(glob.glob(os.path.join(path, pattern), recursive=True))
            if hits:
                return hits[-1]
        raise SystemExit(f"no profiler artifacts under {path}")
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("path", nargs="?", default=None, help="xplane.pb / trace.json.gz / directory")
    ap.add_argument("--top", type=int, default=20, help="ops to list (default 20)")
    ap.add_argument("--markdown", action="store_true", help="emit markdown tables")
    ap.add_argument("--json", action="store_true", dest="as_json", help="emit full JSON report")
    ap.add_argument(
        "--plane", default="/device:", help="plane name prefix to mine (default /device:)"
    )
    ap.add_argument(
        "--diff", nargs=2, metavar=("A", "B"), default=None,
        help="mine two xplane artifacts and print the per-bucket delta "
        "table (B minus A) instead of a single report",
    )
    args = ap.parse_args(argv)

    if args.diff is not None:
        if args.path is not None:
            ap.error("--diff takes its two paths as arguments; drop the positional PATH")
        diff = diff_reports(
            mine_xplane(_resolve(args.diff[0]), plane_prefix=args.plane),
            mine_xplane(_resolve(args.diff[1]), plane_prefix=args.plane),
        )
        if args.as_json:
            print(json.dumps(diff, indent=2))
        else:
            print(render_diff(diff, markdown=args.markdown))
        return 0

    path = _resolve(args.path)
    if path.endswith((".json.gz", ".json")):
        report = mine_host_json(path, top=args.top)
    else:
        report = mine_xplane(path, plane_prefix=args.plane)
    if args.as_json:
        slim = dict(report)
        if "ops" in slim:
            slim["ops"] = slim["ops"][: args.top]
        print(json.dumps(slim, indent=2))
    else:
        print(render(report, top=args.top, markdown=args.markdown))
    return 0


if __name__ == "__main__":
    sys.exit(main())
