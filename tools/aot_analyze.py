"""TPU-compiler ground truth for the bench programs, obtained OFFLINE.

The image carries the real XLA:TPU compiler (site-packages/libtpu). The
axon plugin's ``local_only`` mode registers a chipless "TPU v5e" backend
that compiles genuine TPU executables locally — no terminal, no claim,
no network (docs/TUNNEL_POSTMORTEM.md). That turns this host into a TPU
*compiler* workbench even while the execute tunnel is down:

- ``Compiled.cost_analysis()``   — the TPU compiler's own FLOP /
  bytes-accessed accounting for the exact programs bench.py times,
  cross-checking cyclegan_tpu/utils/flops.py's analytic model.
- ``Compiled.memory_analysis()`` — argument/output/temp/peak HBM sizes
  from the compiler, replacing the hand-built 512² memory ledger in
  docs/BENCHMARKS.md with compiler-reported numbers (is 512²/b4+remat
  under 16G? does b6 exceed it?).
- optimized HLO (``as_text``)    — fusion structure: how many fusions,
  whether instance-norm moments fuse into conv epilogues (the
  mechanism behind the 95.0-vs-86.1 img/s custom-VJP-vs-Pallas result).

Run: PALLAS_AXON_POOL_IPS= python tools/aot_analyze.py [--fast]
(the env override stops the sitecustomize from registering the
remote-compile backend first; registration is process-frozen).

Writes a JSON report to docs/aot_analysis.json and prints a summary.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

T0 = time.perf_counter()


def say(msg: str) -> None:
    print(f"[{time.perf_counter() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def register_local_only() -> None:
    from cyclegan_tpu.utils.axon_compat import register_axon_local

    if not register_axon_local(local_only=True):
        raise RuntimeError("axon plugin not present in this environment")


def build_step(compute_dtype: str, batch: int, image: int, remat: bool = False,
               scan_blocks: bool = False, pad_mode: str = "reflect",
               pad_impl: str = "pad"):
    import jax

    from cyclegan_tpu.config import Config, ModelConfig, TrainConfig
    from cyclegan_tpu.train import create_state, make_train_step

    cfg = Config(
        model=ModelConfig(
            compute_dtype=compute_dtype, image_size=image, remat=remat,
            scan_blocks=scan_blocks, pad_mode=pad_mode, pad_impl=pad_impl,
        ),
        train=TrainConfig(batch_size=batch),
    )
    # Init on CPU: local_only has no executing device, and init-time
    # eager ops would otherwise need one. The abstract pytree is all
    # lower() needs.
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        state = create_state(cfg, jax.random.PRNGKey(0))
    step = make_train_step(cfg, batch)
    return cfg, state, step


def analyze(tag: str, compute_dtype: str, batch: int, image: int,
            remat: bool = False, scan_blocks: bool = False,
            pad_mode: str = "reflect", pad_impl: str = "pad",
            hlo_excerpt: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    say(f"{tag}: building")
    cfg, state, step = build_step(compute_dtype, batch, image, remat,
                                  scan_blocks, pad_mode, pad_impl)
    x = jax.ShapeDtypeStruct((batch, image, image, 3), jnp.float32)
    y = jax.ShapeDtypeStruct((batch, image, image, 3), jnp.float32)
    w = jax.ShapeDtypeStruct((batch,), jnp.float32)
    say(f"{tag}: lowering")
    lowered = jax.jit(step, donate_argnums=(0,)).lower(state, x, y, w)
    say(f"{tag}: compiling (XLA:TPU via local libtpu)")
    t0 = time.perf_counter()
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0
    say(f"{tag}: compiled in {compile_s:.1f}s")

    out: dict = {
        "config": {
            "dtype": compute_dtype, "batch": batch, "image": image,
            "remat": remat, "scan_blocks": scan_blocks, "pad_mode": pad_mode,
        },
        "compile_seconds": round(compile_s, 1),
    }

    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        out["cost_analysis"] = {
            k: float(v)
            for k, v in sorted(ca.items())
            if k in ("flops", "bytes accessed", "transcendentals")
            or k.startswith("bytes accessed")
        }
    except Exception as e:  # pragma: no cover - informational tool
        out["cost_analysis_error"] = f"{type(e).__name__}: {e}"

    try:
        ma = compiled.memory_analysis()
        for name in (
            "argument_size_in_bytes", "output_size_in_bytes",
            "temp_size_in_bytes", "generated_code_size_in_bytes",
            "alias_size_in_bytes",
        ):
            v = getattr(ma, name, None)
            if v is not None:
                out.setdefault("memory_analysis", {})[name] = int(v)
    except Exception as e:  # pragma: no cover
        out["memory_analysis_error"] = f"{type(e).__name__}: {e}"

    # Analytic cross-check from our FLOPs model (per counted image;
    # bench counts 2 images per pair-step).
    try:
        from cyclegan_tpu.utils.flops import train_step_flops_per_image

        analytic = train_step_flops_per_image(cfg) * 2 * batch
        out["analytic_flops_per_step"] = float(analytic)
        if "cost_analysis" in out and out["cost_analysis"].get("flops"):
            out["compiler_vs_analytic_flops"] = round(
                out["cost_analysis"]["flops"] / analytic, 4
            )
    except Exception as e:  # pragma: no cover
        out["analytic_flops_error"] = f"{type(e).__name__}: {e}"

    if hlo_excerpt:
        try:
            txt = compiled.as_text()
            out["hlo_stats"] = {
                "n_fusions": txt.count(" fusion("),
                "n_convs": txt.count("convolution("),
                "n_custom_calls": txt.count("custom-call("),
                "n_all_reduce": txt.count("all-reduce("),
                "n_while": txt.count(" while("),
                "chars": len(txt),
            }
        except Exception as e:  # pragma: no cover
            out["hlo_error"] = f"{type(e).__name__}: {e}"
    return out


def main() -> None:
    # Parse args BEFORE the (slow) backend registration so usage errors
    # fail in milliseconds, not after a libtpu init.
    fast = "--fast" in sys.argv
    only = None
    if "--only" in sys.argv:
        idx = sys.argv.index("--only")
        if idx + 1 >= len(sys.argv):
            raise SystemExit(
                "usage: aot_analyze.py [--fast] [--only SUBSTRING] — "
                "--only needs a job-name substring"
            )
        only = sys.argv[idx + 1]

    register_local_only()
    say("registered local_only AOT backend")
    import jax

    say(f"devices: {jax.devices()}")
    jobs = {
        "scan-headline-equivalent step/bf16/b16/256": dict(
            compute_dtype="bfloat16", batch=16, image=256, hlo_excerpt=True),
        "reference-default step/f32/b1/256": dict(
            compute_dtype="float32", batch=1, image=256),
    }
    if not fast:
        jobs.update({
            "longctx step/bf16/b4/512/remat": dict(
                compute_dtype="bfloat16", batch=4, image=512, remat=True),
            "longctx-oom-probe step/bf16/b6/512/remat": dict(
                compute_dtype="bfloat16", batch=6, image=512, remat=True),
            "compile-time-probe step/bf16/b16/256/scan-blocks": dict(
                compute_dtype="bfloat16", batch=16, image=256,
                scan_blocks=True, hlo_excerpt=True),
            # pad-probe: conv built-in zero padding vs the default
            # reflect-pad+VALID — quantifies what the reflect pads cost
            # in compiler-counted traffic at the headline config
            # (ModelConfig.pad_mode; border-semantics trade documented
            # in docs/BENCHMARKS.md).
            "pad-probe step/bf16/b16/256/zero-pad": dict(
                compute_dtype="bfloat16", batch=16, image=256,
                pad_mode="zero", hlo_excerpt=True),
            # pad-fused: same reflect semantics as the headline, scheduled
            # as ReflectConv (ops/padding.py:reflect_conv — zero-pad conv
            # + fusible border corrections). Measures how much of the
            # 32% pad traffic the parity-preserving fix recovers.
            "pad-fused step/bf16/b16/256/reflect-fused": dict(
                compute_dtype="bfloat16", batch=16, image=256,
                pad_impl="fused", hlo_excerpt=True),
            # Does the zero-pad lever extend to the long-context config?
            # (512²/b4/remat reflect = 542.2 GB.)
            "pad-probe-512 step/bf16/b4/512/remat/zero-pad": dict(
                compute_dtype="bfloat16", batch=4, image=512, remat=True,
                pad_mode="zero"),
        })

    if only is not None:
        jobs = {t: kw for t, kw in jobs.items() if only in t}
        if not jobs:
            raise SystemExit(f"--only {only!r} matches no job")

    report = {"host": "local libtpu AOT (chipless)", "jobs": {}}
    for tag, kwargs in jobs.items():
        try:
            report["jobs"][tag] = analyze(tag, **kwargs)
        except Exception as e:
            say(f"{tag}: FAILED {type(e).__name__}: {e}")
            report["jobs"][tag] = {"error": f"{type(e).__name__}: {e}"}

    all_failed = all("error" in j for j in report["jobs"].values())
    if all_failed:
        # Never overwrite a (possibly good) committed report with pure
        # failures, and exit nonzero so a caller can't mistake this for
        # analysis having happened.
        print(json.dumps(report, indent=2))
        say("every job failed — report NOT written")
        sys.exit(1)
    print(json.dumps(merge_into_report(report["jobs"]), indent=2))


def extract_analysis(compiled) -> dict:
    """Compiler cost/memory accounting for a Compiled, as report dicts.

    Shared by the sibling AOT tools (aot_multichip.py,
    aot_accum_probe.py) so the report schema has one author.
    """
    out: dict = {}
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    out["cost_analysis"] = {
        k: float(v) for k, v in sorted(ca.items())
        if k in ("flops", "bytes accessed", "transcendentals")
    }
    ma = compiled.memory_analysis()
    out["memory_analysis"] = {
        name: int(getattr(ma, name))
        for name in ("argument_size_in_bytes", "output_size_in_bytes",
                     "temp_size_in_bytes", "generated_code_size_in_bytes")
        if getattr(ma, name, None) is not None
    }
    return out


def report_path() -> str:
    return os.path.abspath(os.path.join(os.path.dirname(__file__), "..",
                                        "docs", "aot_analysis.json"))


def merge_into_report(jobs: dict, path: str | None = None) -> dict:
    """Merge `jobs` into docs/aot_analysis.json via merge_jobs; returns
    the written report."""
    path = path or report_path()
    try:
        with open(path) as f:
            report = json.load(f)
    except (OSError, ValueError):
        report = {"host": "local libtpu AOT (chipless)", "jobs": {}}
    report["jobs"] = merge_jobs(report.get("jobs", {}), jobs)
    with open(path, "w") as f:
        json.dump(report, f, indent=2)
    return report


def merge_jobs(existing: dict, new: dict) -> dict:
    """Merge a run's jobs into the prior report's jobs.

    Each job costs ~10-30 min of compile, so a --fast or
    partially-failed run must not drop previously-measured jobs, and a
    failed job must not replace a good prior entry of the same name.
    ``compile_seconds`` records whatever cache state THIS run had; the
    cold figure the docs cite survives reruns as
    ``cold_compile_seconds`` (the max ever recorded — a cache-hit
    rerun cannot clobber it). tests/test_aot_analyze.py pins all of
    this.
    """
    merged = dict(existing)
    for tag, job in new.items():
        prior = merged.get(tag)
        if "error" in job and not (prior is None or "error" in prior):
            continue  # keep the good prior entry
        if prior is not None and "compile_seconds" in job:
            cold = max(
                job["compile_seconds"],
                prior.get("compile_seconds", 0.0),
                prior.get("cold_compile_seconds", 0.0),
            )
            if cold > job["compile_seconds"]:
                job = dict(job, cold_compile_seconds=cold)
        merged[tag] = job
    return merged


if __name__ == "__main__":
    main()
