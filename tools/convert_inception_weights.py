"""Convert a torch-style InceptionV3 state dict to the framework's npz.

Maps the torchvision `inception_v3` module naming onto the
`cyclegan_tpu.eval.inception` npz key convention, transposing conv
kernels OIHW -> HWIO. The weights to use for literature-comparable FID
are the pytorch-fid release `pt_inception-2015-12-05.pth` (the TF FID
graph port — its state-dict keys match the torchvision names this
converter expects, and eval/inception.py reproduces that graph's
pooling quirks: count_include_pad=False averages, Mixed_7c max pool).
Plain torchvision IMAGENET1K_V1 weights also load, but FID numbers from
them are NOT comparable to published values.

The mapping is positional per block and pinned by
tests/test_inception_convert.py against a mock state dict with the
exact torchvision names and shapes — no network or torchvision needed.

Usage (with a .pt/.pth file readable by torch, or an npz of the raw
state dict):
  python tools/convert_inception_weights.py --input pt_inception.pth \
      --output inception_fid.npz
"""

from __future__ import annotations

import argparse
import pathlib
import sys

import numpy as np

# Running as `python tools/convert_inception_weights.py` puts tools/ on
# sys.path, not the repo root where cyclegan_tpu lives.
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

# Our ConvBN module prefix -> torchvision BasicConv2d prefix, in the
# forward order both implementations share (see eval/inception.py and
# torchvision.models.inception).
_STEM = [
    ("ConvBN_0", "Conv2d_1a_3x3"),
    ("ConvBN_1", "Conv2d_2a_3x3"),
    ("ConvBN_2", "Conv2d_2b_3x3"),
    ("ConvBN_3", "Conv2d_3b_1x1"),
    ("ConvBN_4", "Conv2d_4a_3x3"),
]

_MIXED_A = ["branch1x1", "branch5x5_1", "branch5x5_2",
            "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3", "branch_pool"]
_REDUCTION_A = ["branch3x3", "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3"]
_MIXED_B = ["branch1x1", "branch7x7_1", "branch7x7_2", "branch7x7_3",
            "branch7x7dbl_1", "branch7x7dbl_2", "branch7x7dbl_3",
            "branch7x7dbl_4", "branch7x7dbl_5", "branch_pool"]
_REDUCTION_B = ["branch3x3_1", "branch3x3_2",
                "branch7x7x3_1", "branch7x7x3_2", "branch7x7x3_3", "branch7x7x3_4"]
_MIXED_C = ["branch1x1", "branch3x3_1", "branch3x3_2a", "branch3x3_2b",
            "branch3x3dbl_1", "branch3x3dbl_2", "branch3x3dbl_3a",
            "branch3x3dbl_3b", "branch_pool"]

_BLOCKS = (
    [("MixedA_0", "Mixed_5b", _MIXED_A),
     ("MixedA_1", "Mixed_5c", _MIXED_A),
     ("MixedA_2", "Mixed_5d", _MIXED_A),
     ("ReductionA_0", "Mixed_6a", _REDUCTION_A),
     ("MixedB_0", "Mixed_6b", _MIXED_B),
     ("MixedB_1", "Mixed_6c", _MIXED_B),
     ("MixedB_2", "Mixed_6d", _MIXED_B),
     ("MixedB_3", "Mixed_6e", _MIXED_B),
     ("ReductionB_0", "Mixed_7a", _REDUCTION_B),
     ("MixedC_0", "Mixed_7b", _MIXED_C),
     ("MixedC_1", "Mixed_7c", _MIXED_C)]
)


def conv_bn_pairs():
    """Yield (our_prefix, torch_prefix) for every ConvBN in the net."""
    for ours, torch_name in _STEM:
        yield ours, torch_name
    for block_ours, block_torch, branches in _BLOCKS:
        for i, branch in enumerate(branches):
            yield f"{block_ours}/ConvBN_{i}", f"{block_torch}.{branch}"


def convert_state_dict(sd: dict) -> dict:
    """torch-style {name: np.ndarray} -> flat npz dict in the
    eval/inception key convention. Raises KeyError on missing tensors."""
    out = {}
    for ours, theirs in conv_bn_pairs():
        w = np.asarray(sd[f"{theirs}.conv.weight"])  # OIHW
        out[f"params/{ours}/Conv_0/kernel"] = np.transpose(w, (2, 3, 1, 0))
        out[f"params/{ours}/BatchNorm_0/scale"] = np.asarray(sd[f"{theirs}.bn.weight"])
        out[f"params/{ours}/BatchNorm_0/bias"] = np.asarray(sd[f"{theirs}.bn.bias"])
        out[f"batch_stats/{ours}/BatchNorm_0/mean"] = np.asarray(
            sd[f"{theirs}.bn.running_mean"]
        )
        out[f"batch_stats/{ours}/BatchNorm_0/var"] = np.asarray(
            sd[f"{theirs}.bn.running_var"]
        )
    return out


def main(args: argparse.Namespace) -> None:
    if args.input.endswith(".npz"):
        with np.load(args.input) as f:
            sd = {k: f[k] for k in f.files}
    else:
        import torch

        raw = torch.load(args.input, map_location="cpu", weights_only=True)
        if hasattr(raw, "state_dict"):
            raw = raw.state_dict()
        sd = {k: v.numpy() for k, v in raw.items()}

    out = convert_state_dict(sd)

    # Validate against the actual module tree BEFORE the destination file
    # exists: a failed conversion must not leave a bad npz behind.
    from cyclegan_tpu.utils.platform import ensure_platform_from_env

    ensure_platform_from_env()  # honor JAX_PLATFORMS over the axon plugin
    import os

    from cyclegan_tpu.eval.inception import load_params_npz, pool3_template

    _, template = pool3_template()
    tmp = args.output + ".tmp.npz"
    np.savez(tmp, **out)
    try:
        load_params_npz(tmp, template)
    except Exception:
        os.unlink(tmp)
        raise
    os.replace(tmp, args.output)
    print(f"wrote {len(out)} tensors -> {args.output} (validated)")


if __name__ == "__main__":
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--input", required=True,
                   help=".pth/.pt torch state dict, or an npz of it")
    p.add_argument("--output", required=True, help="destination npz")
    main(p.parse_args())
