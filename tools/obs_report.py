"""Fold a telemetry JSONL stream (cyclegan_tpu/obs) into a run report.

    python tools/obs_report.py <run_dir>/telemetry.jsonl

Works on training streams (main.py) and bench streams (BENCH_OBS_JSONL=
path python bench.py) — one tool for both, because both emit the same
event schema. Pure stdlib on purpose: the report must render on any box
the JSONL file lands on, including ones without jax installed.

Robustness contract: unknown event types are never fatal (forward
compatibility) but they are COUNTED and named in the render — a section
the report cannot fold must be visibly absent, not silently omitted.
Malformed lines are skipped and counted (a preempted or SIGKILLed run
legally truncates its last line mid-write), and every section renders
with whatever subset of events exists.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from typing import Dict, List, Optional, Tuple


def load_events(path: str) -> Tuple[List[dict], int]:
    """Parse the stream; returns (events, n_skipped_lines)."""
    events: List[dict] = []
    skipped = 0
    with open(path, "r", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if isinstance(rec, dict) and "event" in rec:
                events.append(rec)
            else:
                skipped += 1
    return events, skipped


def load_lint_verdict(jsonl_path: str) -> Optional[dict]:
    """The graftlint verdict for this run, if the preflight left one.

    chip_autorun writes graftlint's one-line JSON stdout next to the
    run's other logs; when a `graftlint.json` sits in the telemetry
    stream's directory, the report notes the static-discipline verdict
    alongside the runtime sections. Absent or malformed -> None (older
    runs predate the preflight; the report must still render)."""
    path = os.path.join(os.path.dirname(os.path.abspath(jsonl_path)),
                        "graftlint.json")
    if not os.path.exists(path):
        return None
    try:
        with open(path, "r", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if isinstance(rec, dict) and rec.get("tool") == "graftlint":
                    return rec
    except (OSError, ValueError):
        return None
    return None


def _percentile(vals: List[float], q: float) -> float:
    if not vals:
        return float("nan")
    s = sorted(vals)
    idx = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[idx]


def fold(events: List[dict], skipped: int = 0) -> dict:
    """Aggregate the event stream into a report structure."""
    report: dict = {
        "n_events": len(events),
        "skipped_lines": skipped,
        "manifest": None,
        "epochs": [],        # epoch events in order
        "epoch_steps": [],   # per-(epoch, split) loop aggregates
        "steps": {},         # split -> list of per-dispatch wall_s
        "stage": {},         # split -> list of per-dispatch stage_s
        "submit_ready": {},  # split -> per-dispatch submit->ready latency
        "host_work": {},     # split -> per-dispatch host-side loop work
        "memory": [],        # memory events
        "health": [],        # per-epoch model-health rollups
        "health_faults": [], # anomaly detections (nonfinite/divergence/...)
        "stalls": [],
        "loop_stalls": [],   # per-dispatch outliers (StepClock attribution)
        "services": [],      # epoch-services jobs (async ckpt/plots/FID)
        "service_errors": [],
        "bench": [],
        "bench_summary": None,
        "serve_compiles": [],   # serve engine AOT program compiles
        "serve_flushes": [],    # per-flush serving events
        "serve_summary": None,  # executor close() rollup
        "fleet_flushes": [],    # per-flush fleet dispatcher events
        "fleet_sheds": [],      # admission-control shed decisions
        "fleet_summary": None,  # FleetExecutor close() rollup
        "fleet_tenant_swaps": [],  # hot checkpoint swaps (tenant table flips)
        # Domain/transfer stream (cyclegan_tpu/domains): Mind2Mind
        # onboarding provenance and sidecar-vs-config domain disputes.
        "transfer_inits": [],
        "domain_mismatches": [],
        # Self-driving fleet overlay (autoscaler + brownout cascade +
        # hedged dispatch + p95 quarantine): scale decisions, cascade
        # level moves, hedge dispatch/cancel pairs, shadow-probe
        # verdicts, quarantine lifecycle.
        "fleet_autoscales": [],
        "fleet_brownouts": [],
        "fleet_hedges": [],
        "fleet_hedge_cancels": [],
        "fleet_quality_probes": [],
        "fleet_quarantines": [],
        # Resilience stream (cyclegan_tpu/resil): injected faults, I/O
        # retries, rollback recoveries, fleet self-healing.
        "fault_injections": [],
        "retries": [],
        "recoveries": [],        # health_recovery (NaN rollback)
        "ckpt_fallbacks": [],    # restore skipped a corrupt ring slot
        "fleet_downs": [],       # fleet_replica_down detections
        "fleet_recoveries": [],  # respawn/re-enqueue outcomes
        # Elastic topology recovery (resil/elastic.py): startup batch
        # re-decomposition, cross-mesh reshards, mid-epoch preemption
        # saves with their deadline margins.
        "elastic_preflights": [],
        "elastic_reshards": [],
        "emergency_saves": [],
        # Request-scoped tracing (obs/trace.py): kept span graphs, one
        # event per trace (+ late=True supplements for spans that
        # arrived after their trace flushed, e.g. cancelled hedge twins).
        "traces": [],
        # Scale-out observatory (obs/goodput.py, obs/comms.py): per-
        # epoch wall-clock phase rollups and collective-traffic census.
        "goodputs": [],
        "comms_censuses": [],
        # Training-trace observatory (obs/train_trace.py): straggler
        # detections and measured collective-probe rounds. Epoch traces
        # themselves arrive as `trace` events named train_epoch and are
        # split out of the request-trace rollup below.
        "train_stragglers": [],
        "collective_probes": [],
        # Forward-compat census: event kinds this folder does not know.
        # They are still ignored (never fatal), but COUNTED — the render
        # names them explicitly instead of silently dropping them.
        "unknown_kinds": {},
        "end": None,
    }
    for ev in events:
        kind = ev.get("event")
        if kind == "manifest" and report["manifest"] is None:
            report["manifest"] = ev
        elif kind == "epoch":
            report["epochs"].append(ev)
        elif kind == "epoch_steps":
            report["epoch_steps"].append(ev)
        elif kind == "step":
            split = ev.get("split", "train")
            if "wall_s" in ev:
                report["steps"].setdefault(split, []).append(float(ev["wall_s"]))
            if "stage_s" in ev:
                report["stage"].setdefault(split, []).append(float(ev["stage_s"]))
            if "submit_ready_s" in ev:
                report["submit_ready"].setdefault(split, []).append(
                    float(ev["submit_ready_s"]))
            if "host_work_s" in ev:
                report["host_work"].setdefault(split, []).append(
                    float(ev["host_work_s"]))
        elif kind == "memory":
            report["memory"].append(ev)
        elif kind == "health":
            report["health"].append(ev)
        elif kind == "health_fault":
            report["health_faults"].append(ev)
        elif kind == "stall":
            report["stalls"].append(ev)
        elif kind == "loop_stall":
            report["loop_stalls"].append(ev)
        elif kind == "service_job":
            report["services"].append(ev)
        elif kind == "service_error":
            report["service_errors"].append(ev)
        elif kind == "bench":
            report["bench"].append(ev)
        elif kind == "bench_summary":
            report["bench_summary"] = ev
        elif kind == "serve_compile":
            report["serve_compiles"].append(ev)
        elif kind == "serve_flush":
            report["serve_flushes"].append(ev)
        elif kind == "serve_summary":
            report["serve_summary"] = ev
        elif kind == "fleet_flush":
            report["fleet_flushes"].append(ev)
        elif kind == "fleet_shed":
            report["fleet_sheds"].append(ev)
        elif kind == "fleet_summary":
            report["fleet_summary"] = ev
        elif kind == "fleet_tenant_swap":
            report["fleet_tenant_swaps"].append(ev)
        elif kind == "transfer_init":
            report["transfer_inits"].append(ev)
        elif kind == "domain_mismatch":
            report["domain_mismatches"].append(ev)
        elif kind == "fleet_autoscale":
            report["fleet_autoscales"].append(ev)
        elif kind == "fleet_brownout":
            report["fleet_brownouts"].append(ev)
        elif kind == "fleet_hedge":
            report["fleet_hedges"].append(ev)
        elif kind == "fleet_hedge_cancel":
            report["fleet_hedge_cancels"].append(ev)
        elif kind == "fleet_quality_probe":
            report["fleet_quality_probes"].append(ev)
        elif kind == "fleet_quarantine":
            report["fleet_quarantines"].append(ev)
        elif kind == "fault_injected":
            report["fault_injections"].append(ev)
        elif kind == "retry":
            report["retries"].append(ev)
        elif kind == "health_recovery":
            report["recoveries"].append(ev)
        elif kind == "ckpt_fallback":
            report["ckpt_fallbacks"].append(ev)
        elif kind == "fleet_replica_down":
            report["fleet_downs"].append(ev)
        elif kind == "fleet_recovery":
            report["fleet_recoveries"].append(ev)
        elif kind == "elastic_preflight":
            report["elastic_preflights"].append(ev)
        elif kind == "elastic_reshard":
            report["elastic_reshards"].append(ev)
        elif kind == "emergency_save":
            report["emergency_saves"].append(ev)
        elif kind == "trace":
            report["traces"].append(ev)
        elif kind == "goodput":
            report["goodputs"].append(ev)
        elif kind == "comms_census":
            report["comms_censuses"].append(ev)
        elif kind == "train_straggler":
            report["train_stragglers"].append(ev)
        elif kind == "collective_probe":
            report["collective_probes"].append(ev)
        elif kind == "end":
            report["end"] = ev
        else:
            # Unknown events: never fatal (forward compatibility), but
            # counted and named in the render — an absent section must
            # be visibly absent, not silently omitted.
            key = str(kind)
            report["unknown_kinds"][key] = \
                report["unknown_kinds"].get(key, 0) + 1

    # Derived rollups ----------------------------------------------------
    train_aggs = [a for a in report["epoch_steps"] if a.get("split") == "train"]
    if train_aggs:
        walls = sum(float(a.get("wall_s", 0.0)) for a in train_aggs)
        stage = sum(float(a.get("stage_s", 0.0)) for a in train_aggs)
        report["train_starvation_fraction"] = stage / walls if walls > 0 else 0.0
    report["mfu_trajectory"] = [
        (ev.get("epoch"), ev.get("mfu")) for ev in report["epochs"]
    ]

    # Memory: per-device peak over the run + headroom vs bytes_limit.
    peaks: Dict[int, dict] = {}
    for ev in report["memory"]:
        for row in ev.get("devices", []):
            did = row.get("id")
            peak = row.get("peak_bytes_in_use", row.get("bytes_in_use"))
            if did is None or peak is None:
                continue
            cur = peaks.setdefault(did, dict(row))
            if peak >= cur.get("peak_bytes_in_use", cur.get("bytes_in_use", 0)):
                cur.update(row)
    report["memory_peaks"] = peaks

    # Model-health rollup: per-network grad-norm percentiles over the
    # per-epoch mean envelopes (plus the run max), latest D-balance, and
    # the anomaly census — the "is the model still healthy" summary next
    # to the throughput sections.
    if report["health"]:
        gnorm_pct: Dict[str, dict] = {}
        nets = sorted({
            net for ev in report["health"] for net in (ev.get("gnorm") or {})
        })
        for net in nets:
            means = [float(ev["gnorm"][net]["mean"]) for ev in report["health"]
                     if net in (ev.get("gnorm") or {})
                     and "mean" in ev["gnorm"][net]]
            maxes = [float(ev["gnorm"][net]["max"]) for ev in report["health"]
                     if net in (ev.get("gnorm") or {})
                     and "max" in ev["gnorm"][net]]
            if means:
                gnorm_pct[net] = {
                    "p50": _percentile(means, .5),
                    "p90": _percentile(means, .9),
                    "max": max(maxes) if maxes else float("nan"),
                }
        anomalies: Dict[str, int] = {}
        for ev in report["health_faults"]:
            kind = str(ev.get("kind", "?"))
            anomalies[kind] = anomalies.get(kind, 0) + 1
        report["health_rollup"] = {
            "n_epochs": len(report["health"]),
            "gnorm_percentiles": gnorm_pct,
            "last_disc": report["health"][-1].get("disc") or {},
            "last_loss": report["health"][-1].get("loss") or {},
            "nonfinite_rows": sum(
                int(ev.get("nonfinite_rows", 0)) for ev in report["health"]
            ),
            "anomalies": anomalies,
        }

    # Transfer-onboarding rollup: who this run fine-tuned from
    # (transfer_init provenance), any sidecar-vs-config domain disputes
    # along the way, and — for encoder_freeze runs — the frozen-trunk
    # gradient envelope. The freeze is masking upstream of Adam, so the
    # enc_frozen max MUST be exactly 0 over the whole run; any nonzero
    # value is a finding (the mask regressed), surfaced as frozen_leak.
    if report["transfer_inits"] or report["domain_mismatches"]:
        init = report["transfer_inits"][0] if report["transfer_inits"] \
            else {}
        frozen_max = None
        for ev in report["health"]:
            env = (ev.get("gnorm") or {}).get("enc_frozen")
            if isinstance(env, dict) and env.get("max") is not None:
                v = float(env["max"])
                frozen_max = v if frozen_max is None else max(frozen_max, v)
        report["transfer_rollup"] = {
            "mode": init.get("transfer_mode"),
            "domain": init.get("domain"),
            "parent_domain": init.get("parent_domain"),
            "parent_epoch": init.get("parent_epoch"),
            "parent_ckpt": init.get("parent_ckpt"),
            "n_domain_mismatches": len(report["domain_mismatches"]),
            "frozen_gnorm_max": frozen_max,
            "frozen_leak": (init.get("transfer_mode") == "encoder_freeze"
                            and frozen_max is not None
                            and frozen_max > 0.0),
        }

    # Serving rollup: trigger mix + fill factor quantify whether the
    # micro-batcher is running throughput-bound (full flushes) or
    # latency-bound (deadline flushes), queue-depth watermark shows how
    # close admission backpressure came to engaging.
    flushes = report["serve_flushes"]
    if flushes:
        triggers: Dict[str, int] = {}
        for ev in flushes:
            trig = str(ev.get("trigger", "?"))
            triggers[trig] = triggers.get(trig, 0) + 1
        fills = [float(ev["n"]) / float(ev["bucket"]) for ev in flushes
                 if ev.get("n") and ev.get("bucket")]
        report["serve_rollup"] = {
            "n_flushes": len(flushes),
            "n_images": sum(int(ev.get("n", 0)) for ev in flushes),
            "triggers": triggers,
            "mean_fill": (sum(fills) / len(fills)) if fills else None,
            "max_queue_depth": max(
                (int(ev.get("queue_depth", 0)) for ev in flushes),
                default=0),
            "dispatch_p50_s": _percentile(
                [float(ev["dispatch_s"]) for ev in flushes
                 if "dispatch_s" in ev], .5),
            "fetch_block_p50_s": _percentile(
                [float(ev["fetch_block_s"]) for ev in flushes
                 if "fetch_block_s" in ev], .5),
        }

    # Fleet rollup: trigger mix (refill fraction = is continuous
    # batching engaging?), per-replica flush balance, and the shed
    # census by class and reason — overload behavior in one block.
    ff = report["fleet_flushes"]
    if ff or report["fleet_sheds"]:
        triggers = {}
        per_replica: Dict[str, int] = {}
        for ev in ff:
            trig = str(ev.get("trigger", "?"))
            triggers[trig] = triggers.get(trig, 0) + 1
            rep = str(ev.get("replica", "?"))
            per_replica[rep] = per_replica.get(rep, 0) + 1
        shed_class: Dict[str, int] = {}
        shed_reason: Dict[str, int] = {}
        for ev in report["fleet_sheds"]:
            shed_class[str(ev.get("klass", "?"))] = \
                shed_class.get(str(ev.get("klass", "?")), 0) + 1
            shed_reason[str(ev.get("reason", "?"))] = \
                shed_reason.get(str(ev.get("reason", "?")), 0) + 1
        fills = [float(ev["n"]) / float(ev["bucket"]) for ev in ff
                 if ev.get("n") and ev.get("bucket")]
        report["fleet_rollup"] = {
            "n_flushes": len(ff),
            "n_images": sum(int(ev.get("n", 0)) for ev in ff),
            "triggers": triggers,
            "flushes_per_replica": per_replica,
            "mean_fill": (sum(fills) / len(fills)) if fills else None,
            "n_shed": len(report["fleet_sheds"]),
            "shed_by_class": shed_class,
            "shed_by_reason": shed_reason,
            "max_queue_depth": max(
                (int(ev.get("queue_depth", 0)) for ev in ff), default=0),
        }

    # Multi-tenant census: per-(domain/tier) request/latency/shed view,
    # stitched from the per-flush tenant field (flushes are
    # tenant-homogeneous, so each event attributes cleanly), the shed
    # events' tenant field, and — when the run closed cleanly — the
    # authoritative fleet_summary tenants/tenant_admission rollups.
    # Hot swaps are listed per tenant so a latency step change can be
    # lined up against the checkpoint flip that caused it.
    fsum = report["fleet_summary"] or {}
    tenant_keys = sorted(
        {str(ev["tenant"]) for ev in ff if ev.get("tenant")}
        | {str(ev["tenant"]) for ev in report["fleet_sheds"]
           if ev.get("tenant")}
        | {str(ev["tenant"]) for ev in report["fleet_tenant_swaps"]
           if ev.get("tenant")}
        | set(fsum.get("tenants") or {})
        | set(fsum.get("tenant_admission") or {}))
    if tenant_keys:
        tenants: Dict[str, dict] = {}
        for key in tenant_keys:
            mine = [ev for ev in ff if str(ev.get("tenant")) == key]
            row = {
                "n_flushes": len(mine),
                "n_images": sum(int(ev.get("n", 0)) for ev in mine),
                "n_shed": sum(1 for ev in report["fleet_sheds"]
                              if str(ev.get("tenant")) == key),
                "n_swaps": sum(1 for ev in report["fleet_tenant_swaps"]
                               if str(ev.get("tenant")) == key),
            }
            summary_row = (fsum.get("tenants") or {}).get(key)
            if isinstance(summary_row, dict):
                row["summary"] = summary_row
            adm_row = (fsum.get("tenant_admission") or {}).get(key)
            if isinstance(adm_row, dict):
                row["admission"] = adm_row
            tenants[key] = row
        report["tenant_rollup"] = {
            "tenants": tenants,
            "n_swaps": len(report["fleet_tenant_swaps"]),
        }

    # Self-driving-fleet rollup: the scale decision census, how deep
    # the brownout ladder went, hedge economics (dispatched vs the two
    # cancel flavors), shadow-probe verdicts, and the quarantine
    # lifecycle — the "did the fleet drive itself sensibly" block.
    if (report["fleet_autoscales"] or report["fleet_brownouts"]
            or report["fleet_hedges"] or report["fleet_hedge_cancels"]
            or report["fleet_quality_probes"]
            or report["fleet_quarantines"]):
        scale_phases: Dict[str, int] = {}
        for ev in report["fleet_autoscales"]:
            p = str(ev.get("phase", "?"))
            scale_phases[p] = scale_phases.get(p, 0) + 1
        cancels: Dict[str, int] = {}
        for ev in report["fleet_hedge_cancels"]:
            r = str(ev.get("reason", "?"))
            cancels[r] = cancels.get(r, 0) + 1
        verdicts: Dict[str, int] = {}
        for ev in report["fleet_quality_probes"]:
            v = str(ev.get("verdict", "?"))
            verdicts[v] = verdicts.get(v, 0) + 1
        q_actions: Dict[str, int] = {}
        for ev in report["fleet_quarantines"]:
            a = str(ev.get("action", "?"))
            q_actions[a] = q_actions.get(a, 0) + 1
        levels = [int(ev.get("level", 0))
                  for ev in report["fleet_brownouts"]]
        report["autoscale_rollup"] = {
            "scale_events": scale_phases,
            "final_n_active": (report["fleet_autoscales"][-1].get("n_active")
                               if report["fleet_autoscales"] else None),
            "brownout_moves": len(levels),
            "brownout_max_level": max(levels, default=0),
            "hedges_dispatched": len(report["fleet_hedges"]),
            "hedge_cancels": cancels,
            "probe_verdicts": verdicts,
            "quarantine_actions": q_actions,
        }

    # Goodput rollup: seconds-weighted phase census over the per-epoch
    # `goodput` events — where every wall-clock second of the run went,
    # the run-level goodput fraction, and the epoch that wasted the
    # most (the one to open in tools/goodput_timeline.py).
    if report["goodputs"]:
        total_s = sum(float(ev.get("elapse_s", 0.0))
                      for ev in report["goodputs"])
        phases_s: Dict[str, float] = {}
        for ev in report["goodputs"]:
            for p, s in (ev.get("phases_s") or {}).items():
                phases_s[str(p)] = phases_s.get(str(p), 0.0) + float(s)
        fracs = {p: (s / total_s if total_s > 0 else 0.0)
                 for p, s in phases_s.items()}
        worst = min(
            report["goodputs"],
            key=lambda ev: (float(ev.get("goodput_fraction", 1.0)),
                            -float(ev.get("elapse_s", 0.0))))
        report["goodput_rollup"] = {
            "n_epochs": len(report["goodputs"]),
            "elapse_s": total_s,
            "phases_s": phases_s,
            "phase_fractions": fracs,
            "goodput_fraction": fracs.get("compute", 0.0),
            "badput": dict(sorted(
                ((p, f) for p, f in fracs.items()
                 if p != "compute" and f > 0),
                key=lambda kv: -kv[1])),
            "worst_epoch": worst.get("epoch"),
            "worst_epoch_fraction": worst.get("goodput_fraction"),
        }

    # Comms-census rollup: the LAST census wins (a stream legally
    # carries one per round); per-axis analytic-vs-measured bytes and
    # the reconciliation verdict.
    if report["comms_censuses"]:
        report["comms_census_rollup"] = report["comms_censuses"][-1]

    # Request-trace rollup: status census, sampling provenance (head
    # sample vs tail-kept failure), per-hop duration stats, and the
    # slowest exemplars with their trace_id — the "which trace_id do I
    # feed tools/trace_timeline.py" block. Training epoch traces share
    # the `trace` event schema but are a different animal (one per
    # epoch, hop graph under dispatch spans) — split them out first.
    serve_traces = [ev for ev in report["traces"]
                    if ev.get("name") != "train_epoch"]
    train_traces = [ev for ev in report["traces"]
                    if ev.get("name") == "train_epoch"]
    if serve_traces:
        bases = [ev for ev in serve_traces if not ev.get("late")]
        late = [ev for ev in serve_traces if ev.get("late")]
        statuses: Dict[str, int] = {}
        hop_durs: Dict[str, List[float]] = {}
        for ev in bases:
            s = str(ev.get("status", "?"))
            statuses[s] = statuses.get(s, 0) + 1
        for ev in serve_traces:
            for span in ev.get("spans") or []:
                t0, t1 = span.get("t0"), span.get("t1")
                if t0 is None or t1 is None:
                    continue
                hop_durs.setdefault(
                    str(span.get("name", "?")), []).append(t1 - t0)
        hops = {}
        for name in sorted(hop_durs):
            vals = sorted(hop_durs[name])
            hops[name] = {
                "n": len(vals),
                "p50_ms": round(_percentile(vals, 0.5) * 1e3, 3),
                "p95_ms": round(_percentile(vals, 0.95) * 1e3, 3),
            }
        timed = [ev for ev in bases if ev.get("dur_s") is not None]
        slowest = sorted(timed, key=lambda e: e["dur_s"],
                         reverse=True)[:5]
        report["trace_rollup"] = {
            "n_traces": len(bases),
            "n_late_supplements": len(late),
            "statuses": statuses,
            "n_tail_kept": sum(1 for ev in bases if ev.get("tail")),
            "hops": hops,
            "slowest": [
                {"trace_id": ev.get("trace_id"),
                 "status": ev.get("status"),
                 "dur_ms": round(ev["dur_s"] * 1e3, 3),
                 "class": (ev.get("attrs") or {}).get("class"),
                 "tenant": (ev.get("attrs") or {}).get("tenant")}
                for ev in slowest],
        }

    # Train-trace rollup: per-hop duration stats over the dispatch hop
    # graph, span-budget accounting, and the straggler census. Blame
    # counts come from the per-detection `train_straggler` events when
    # present (one event per detection, with full component attribution)
    # and fall back to the epoch traces' accumulated attrs otherwise.
    if train_traces or report["train_stragglers"]:
        hop_durs = {}
        spans_dropped = 0
        attr_stragglers = 0
        attr_blames: Dict[str, int] = {}
        for ev in train_traces:
            attrs = ev.get("attrs") or {}
            spans_dropped += int(attrs.get("spans_dropped", 0) or 0)
            attr_stragglers += int(attrs.get("n_stragglers", 0) or 0)
            for b, n in (attrs.get("straggler_blames") or {}).items():
                attr_blames[str(b)] = attr_blames.get(str(b), 0) + int(n)
            for span in ev.get("spans") or []:
                t0, t1 = span.get("t0"), span.get("t1")
                if t0 is None or t1 is None:
                    continue
                name = str(span.get("name", "?"))
                if name in ("dispatch", "data_wait", "submit", "device",
                            "resolve", "host"):
                    hop_durs.setdefault(name, []).append(t1 - t0)
        hops = {}
        for name in ("dispatch", "data_wait", "submit", "device",
                     "resolve", "host"):
            vals = sorted(hop_durs.get(name) or [])
            if not vals:
                continue
            hops[name] = {
                "n": len(vals),
                "p50_ms": round(_percentile(vals, 0.5) * 1e3, 3),
                "p95_ms": round(_percentile(vals, 0.95) * 1e3, 3),
            }
        if report["train_stragglers"]:
            blames: Dict[str, int] = {}
            for ev in report["train_stragglers"]:
                b = str(ev.get("blame", "?"))
                blames[b] = blames.get(b, 0) + 1
            n_stragglers = len(report["train_stragglers"])
        else:
            blames, n_stragglers = attr_blames, attr_stragglers
        report["train_trace_rollup"] = {
            "n_traces": len(train_traces),
            "hops": hops,
            "spans_dropped": spans_dropped,
            "n_stragglers": n_stragglers,
            "blames": blames,
        }

    # Collective-probe rollup: the LAST measured round wins (a run
    # legally re-probes at epoch boundaries); per-axis measured vs
    # analytic step-collective seconds from its reconcile block.
    if report["collective_probes"]:
        report["collective_probe_rollup"] = report["collective_probes"][-1]
    return report


def _fmt_bytes(n: Optional[float]) -> str:
    if n is None:
        return "?"
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}TB"


def _fmt(v, spec: str = ".4f") -> str:
    if v is None:
        return "n/a"
    try:
        f = float(v)
    except (TypeError, ValueError):
        return str(v)
    if math.isnan(f):
        return "nan"
    return format(f, spec)


def render(report: dict) -> str:
    out: List[str] = []
    w = out.append

    w("=== telemetry run report ===")
    w(f"events: {report['n_events']}"
      + (f"  (skipped {report['skipped_lines']} malformed/truncated lines)"
         if report["skipped_lines"] else ""))
    unknown = report.get("unknown_kinds") or {}
    if unknown:
        # Never let an unrecognized kind vanish silently: name it, so a
        # newer emitter paired with an older report is a visible
        # version-skew signal rather than a quietly thinner report.
        w("unknown event kinds (not folded — newer emitter than this "
          "report?): " + ", ".join(
              f"{k} x{v}" for k, v in sorted(unknown.items())))

    mani = report["manifest"]
    if mani:
        mesh = mani.get("mesh") or {}
        versions = mani.get("versions") or {}
        w("-- manifest --")
        w(f"host: {mani.get('hostname', '?')} pid {mani.get('pid', '?')}"
          f"  git: {mani.get('git_sha') or 'unknown'}")
        w(f"versions: python {versions.get('python', '?')}, "
          f"jax {versions.get('jax', '?')}, jaxlib {versions.get('jaxlib', '?')}")
        if mesh:
            w(f"mesh: {mesh.get('n_devices', '?')} devices "
              f"({mesh.get('n_data', '?')} data x {mesh.get('n_spatial', '?')} "
              f"spatial), platform {mesh.get('platform', '?')} "
              f"{mesh.get('device_kind', '')}".rstrip())
        host = mani.get("host") or {}
        if host:
            w(f"processes: {host.get('process_count', 1)} "
              f"(this stream from index {host.get('process_index', 0)})")
        domain = (((mani.get("config") or {}).get("data") or {})
                  .get("domain"))
        if domain:
            w(f"domain: {domain}")
    else:
        w("-- manifest: MISSING (stream does not self-describe) --")

    if report["epochs"]:
        w("-- epochs --")
        w(f"{'epoch':>5}  {'elapse_s':>9}  {'train img/s':>11}  {'TFLOP/s':>8}  "
          f"{'MFU':>7}")
        for ev in report["epochs"]:
            # train_images_per_sec excludes the test pass + epoch-boundary
            # services; older streams only carry the whole-epoch rate.
            ips = ev.get("train_images_per_sec", ev.get("images_per_sec"))
            w(f"{ev.get('epoch', '?'):>5}  {_fmt(ev.get('elapse_s'), '.2f'):>9}  "
              f"{_fmt(ips, '.2f'):>11}  "
              f"{_fmt(ev.get('tflops_per_sec'), '.3f'):>8}  "
              f"{_fmt(ev.get('mfu'), '.4f'):>7}")

    for agg in report["epoch_steps"]:
        split = agg.get("split", "?")
        w(f"-- {split} loop, epoch {agg.get('epoch', '?')} --")
        w(f"dispatches: {agg.get('n_dispatches', '?')} "
          f"({agg.get('n_steps', '?')} steps), wall {_fmt(agg.get('wall_s'), '.2f')}s")
        w(f"time split: stage {_fmt(agg.get('stage_s'), '.3f')}s"
          f" | dispatch {_fmt(agg.get('dispatch_s'), '.3f')}s"
          f" | fetch-block {_fmt(agg.get('fetch_block_s'), '.3f')}s"
          f" | drain {_fmt(agg.get('drain_s'), '.3f')}s")
        w(f"starvation fraction: {_fmt(agg.get('starvation_fraction'))}"
          "  (loop wall spent waiting on input)")
        w(f"dispatch interval: p50 {_fmt(agg.get('wall_p50_s'))}s, "
          f"p90 {_fmt(agg.get('wall_p90_s'))}s, max {_fmt(agg.get('wall_max_s'))}s")
        if agg.get("host_work_s") is not None:
            w(f"host work (loop-side, unattributed to stage/dispatch/fetch): "
              f"{_fmt(agg.get('host_work_s'), '.3f')}s")
        if agg.get("submit_ready_p50_s") is not None:
            w(f"submit->ready: p50 {_fmt(agg.get('submit_ready_p50_s'))}s, "
              f"p90 {_fmt(agg.get('submit_ready_p90_s'))}s, "
              f"max {_fmt(agg.get('submit_ready_max_s'))}s"
              + (f"  loop stalls: {agg['n_loop_stalls']}"
                 if agg.get("n_loop_stalls") else ""))

    # Raw per-dispatch percentiles across the whole run (when step
    # events were kept — obs_step_log_every > 0).
    for split, walls in sorted(report["steps"].items()):
        w(f"-- {split} per-dispatch (all epochs, {len(walls)} records) --")
        w(f"wall: p50 {_fmt(_percentile(walls, .5))}s, "
          f"p90 {_fmt(_percentile(walls, .9))}s, "
          f"p99 {_fmt(_percentile(walls, .99))}s, "
          f"max {_fmt(max(walls))}s")
        sr = report["submit_ready"].get(split)
        if sr:
            w(f"submit->ready: p50 {_fmt(_percentile(sr, .5))}s, "
              f"p90 {_fmt(_percentile(sr, .9))}s, max {_fmt(max(sr))}s  "
              f"({len(sr)} attributed)")
        hw = report["host_work"].get(split)
        if hw:
            w(f"host work: p50 {_fmt(_percentile(hw, .5))}s, "
              f"max {_fmt(max(hw))}s")

    if "train_starvation_fraction" in report:
        w(f"run starvation fraction (train): "
          f"{_fmt(report['train_starvation_fraction'])}")

    # Goodput ledger: the wall-clock phase census. Every second of the
    # run is in exactly one phase, so the fractions answer "where did
    # the time go" without any cross-referencing.
    gp = report.get("goodput_rollup")
    if gp:
        w(f"-- goodput ledger ({gp['n_epochs']} epoch rollups, "
          f"{_fmt(gp['elapse_s'], '.1f')}s accounted) --")
        w(f"goodput fraction: {_fmt(gp['goodput_fraction'], '.3f')} "
          f"(device compute share of wall-clock)")
        if gp["badput"]:
            w("badput: " + ", ".join(
                f"{p}={_fmt(f, '.3f')}" for p, f in gp["badput"].items()))
        else:
            w("badput: none recorded")
        w(f"worst epoch: {gp.get('worst_epoch', '?')} at "
          f"{_fmt(gp.get('worst_epoch_fraction'), '.3f')} goodput "
          f"(open it in tools/goodput_timeline.py)")
        src = report["goodputs"][-1].get("comms_source")
        if src and src != "none":
            delta = report["goodputs"][-1].get("comms_probe_delta_frac")
            w(f"collective seconds source: {src}"
              + (f" (probe vs census delta {_fmt(delta, '.3f')})"
                 if delta is not None else ""))
    elif report["epoch_steps"]:
        # A training stream with loop aggregates but no rollups is a
        # version-skew signal, same convention as the traces line.
        w("-- goodput ledger: absent (no `goodput` events; stream "
          "predates obs/goodput.py?) --")

    cen = report.get("comms_census_rollup")
    if cen:
        mesh = cen.get("mesh") or {}
        w(f"-- comms census (mesh {mesh.get('n_data', '?')} data x "
          f"{mesh.get('n_spatial', '?')} spatial) --")
        recon = cen.get("reconciliation") or {}
        for ax, v in sorted(recon.items()):
            w(f"{ax} axis: analytic {_fmt_bytes(v.get('analytic_bytes'))} "
              f"vs measured {_fmt_bytes(v.get('measured_bytes'))} per "
              f"step ({v.get('measured_ops', '?')} ops, error "
              f"{_fmt(v.get('error'), '.3f')})")
        if not recon:
            ana = cen.get("analytic") or {}
            w(f"analytic only (no compiled HLO): data "
              f"{_fmt_bytes(ana.get('data_bytes'))}, spatial "
              f"{_fmt_bytes((ana.get('spatial_bytes') or 0) or None)} "
              f"per step")
        if cen.get("max_recon_error") is not None:
            tol = cen.get("tolerance")
            verdict = "OK" if cen.get("ok") else "RECONCILIATION FAILED"
            w(f"verdict: {verdict} (max axis error "
              f"{_fmt(cen['max_recon_error'], '.3f')} vs tolerance "
              f"{_fmt(tol, '.2f')})")
        if cen.get("est_step_comms_s") is not None:
            w(f"per-step collective estimate: "
              f"{_fmt(cen['est_step_comms_s'], '.6f')}s at "
              f"{_fmt(cen.get('link_gbps'), '.0f')} GB/s links")
    elif report["epoch_steps"]:
        w("-- comms census: absent (no `comms_census` event; single-"
          "device run, or stream predates obs/comms.py?) --")

    if report["memory"]:
        w("-- memory watermarks --")
        if not report["memory_peaks"]:
            w("allocator stats unavailable on this backend "
              "(CPU reports none; TPU/GPU report HBM watermarks)")
        for did, row in sorted(report["memory_peaks"].items()):
            peak = row.get("peak_bytes_in_use", row.get("bytes_in_use"))
            limit = row.get("bytes_limit")
            head = (f", headroom {_fmt_bytes(limit - peak)} "
                    f"({100 * (1 - peak / limit):.1f}%)"
                    if limit and peak is not None else "")
            w(f"device {did} ({row.get('kind', '?')}): "
              f"peak {_fmt_bytes(peak)} of {_fmt_bytes(limit)}{head}")

    hr = report.get("health_rollup")
    if hr:
        w(f"-- model health ({hr['n_epochs']} epoch rollups) --")
        for net, pct in sorted(hr["gnorm_percentiles"].items()):
            w(f"grad-norm {net}: p50 {_fmt(pct['p50'], '.4g')}, "
              f"p90 {_fmt(pct['p90'], '.4g')}, max {_fmt(pct['max'], '.4g')}")
        for side, stats in sorted(hr["last_disc"].items()):
            w(f"D-balance {side} (last epoch): "
              f"D(real) {_fmt(stats.get('real_mean'), '.3f')}"
              f"±{_fmt(stats.get('real_std'), '.3f')}, "
              f"D(fake) {_fmt(stats.get('fake_mean'), '.3f')}"
              f"±{_fmt(stats.get('fake_std'), '.3f')}")
        if hr["last_loss"]:
            w("final losses: " + ", ".join(
                f"{k}={_fmt(v, '.4f')}"
                for k, v in sorted(hr["last_loss"].items())))
        if hr["nonfinite_rows"]:
            w(f"NON-FINITE rows: {hr['nonfinite_rows']}")
        if hr["anomalies"]:
            w("anomalies: " + ", ".join(
                f"{k}={v}" for k, v in sorted(hr["anomalies"].items())))
        else:
            w("anomalies: none")
    tr = report.get("transfer_rollup")
    if tr:
        w("-- transfer onboarding --")
        if tr.get("mode"):
            w(f"fine-tuned ({tr['mode']}) onto {tr.get('domain', '?')} from "
              f"{tr.get('parent_domain', '?')} @ epoch "
              f"{tr.get('parent_epoch', '?')} ({tr.get('parent_ckpt', '?')})")
        if tr["n_domain_mismatches"]:
            w(f"DOMAIN MISMATCHES: {tr['n_domain_mismatches']} "
              f"(checkpoint sidecar disagreed with the run's domain)")
            for ev in report["domain_mismatches"][:5]:
                w(f"  {ev.get('context', '?')}: checkpoint "
                  f"{ev.get('checkpoint_domain', '?')} vs run "
                  f"{ev.get('run_domain', '?')}"
                  + ("  [strict]" if ev.get("strict") else ""))
        if tr.get("mode") == "encoder_freeze":
            if tr.get("frozen_gnorm_max") is None:
                w("frozen trunk: no enc_frozen envelope recorded "
                  "(health layer off?)")
            elif tr["frozen_leak"]:
                w(f"FROZEN-TRUNK LEAK: enc_frozen grad-norm max "
                  f"{_fmt(tr['frozen_gnorm_max'], '.4g')} "
                  f"(must be exactly 0 — the gradient mask regressed)")
            else:
                w("frozen trunk: enc_frozen grad-norm pinned at 0 over "
                  "the whole run")

    if report["health_faults"]:
        w(f"-- health faults: {len(report['health_faults'])} --")
        for ev in report["health_faults"][:10]:
            detail = {
                k: v for k, v in ev.items()
                if k not in ("event", "t", "kind", "epoch", "row", "policy",
                             "schema")
            }
            w(f"e{ev.get('epoch', '?')} row {ev.get('row', '?')}: "
              f"{ev.get('kind', '?')} [{ev.get('policy', '?')}]"
              + (f" {detail}" if detail else ""))
        if len(report["health_faults"]) > 10:
            w(f"... {len(report['health_faults']) - 10} more")

    # Resilience: what failed (or was injected), and what the recovery
    # machinery did about it. Silent absence is the healthy case.
    resil_any = (report["fault_injections"] or report["retries"]
                 or report["recoveries"] or report["ckpt_fallbacks"]
                 or report["fleet_downs"] or report["fleet_recoveries"])
    if resil_any:
        w("-- resilience --")
        if report["fault_injections"]:
            by_kind: Dict[str, int] = {}
            for ev in report["fault_injections"]:
                k = str(ev.get("kind", "?"))
                by_kind[k] = by_kind.get(k, 0) + 1
            w("injected faults: " + ", ".join(
                f"{k} x{n}" for k, n in sorted(by_kind.items())))
        if report["retries"]:
            by_site: Dict[str, List[float]] = {}
            for ev in report["retries"]:
                by_site.setdefault(str(ev.get("site", "?")), []).append(
                    float(ev.get("delay_s", 0.0)))
            for site, delays in sorted(by_site.items()):
                w(f"retries[{site}]: {len(delays)} "
                  f"(backoff total {sum(delays):.2f}s, "
                  f"max {max(delays):.2f}s)")
        for ev in report["recoveries"]:
            w(f"ROLLBACK: {ev.get('fault_kind', '?')} at epoch "
              f"{ev.get('epoch_faulted', '?')} -> restored "
              f"{ev.get('slot', '?')}, resumed epoch "
              f"{ev.get('resume_epoch', '?')} "
              f"({ev.get('consecutive', '?')}/{ev.get('max_rollbacks', '?')} "
              f"consecutive, {ev.get('total', '?')} total)")
        for ev in report["ckpt_fallbacks"]:
            failed = ev.get("failed") or []
            w(f"CKPT FALLBACK: restored {ev.get('slot', '?')} after "
              f"{len(failed)} unverifiable slot(s): "
              + "; ".join(str(f) for f in failed))
        for ev in report["fleet_downs"]:
            w(f"replica {ev.get('replica', '?')} DOWN ({ev.get('reason', '?')}, "
              f"{ev.get('inflight', 0)} in flight, "
              f"{ev.get('consecutive_failures', '?')} consecutive)")
        for ev in report["fleet_recoveries"]:
            w(f"fleet recovery: replica {ev.get('replica', '?')} "
              f"respawned={ev.get('respawned', '?')} "
              f"requeued={ev.get('requeued', 0)} failed={ev.get('failed', 0)}"
              + ("  CIRCUIT OPEN" if ev.get("circuit_open") else ""))

    # Elastic recovery: topology changes survived and mid-epoch saves
    # landed. A multi-run stream (preempt + resume appending to the same
    # file) shows the whole preemption story in one report.
    if (report["elastic_preflights"] or report["elastic_reshards"]
            or report["emergency_saves"]):
        w("-- elastic recovery --")
        for ev in report["elastic_preflights"]:
            saved = ev.get("saved") or {}
            w(f"preflight: saved topology "
              f"{saved.get('n_data', '?')}x{saved.get('n_spatial', '?')} "
              f"(global batch {saved.get('global_batch_size', '?')}) -> "
              f"batch_size {ev.get('old_batch_size', '?')}->"
              f"{ev.get('batch_size', '?')}, grad_accum "
              f"{ev.get('old_grad_accum', '?')}->{ev.get('grad_accum', '?')}")
        for ev in report["elastic_reshards"]:
            src = ev.get("from_topology") or {}
            dst = ev.get("to_topology") or {}
            w(f"RESHARD e{ev.get('epoch', '?')}: {ev.get('n_leaves', '?')} "
              f"leaves {src.get('n_data', '?')}x{src.get('n_spatial', '?')} "
              f"-> {dst.get('n_data', '?')}x{dst.get('n_spatial', '?')}")
        for ev in report["emergency_saves"]:
            w(f"EMERGENCY SAVE e{ev.get('epoch', '?')} "
              f"step {ev.get('step', '?')}: "
              f"{_fmt(ev.get('elapsed_s'), '.2f')}s of "
              f"{_fmt(ev.get('deadline_s'), '.2f')}s budget "
              f"(margin {_fmt(ev.get('margin_s'), '.2f')}s"
              + (f", shed {ev['shed_jobs']} job(s)"
                 if ev.get("shed_jobs") else "")
              + f"), committed={ev.get('committed', '?')}")

    if report["stalls"]:
        w(f"-- stalls: {len(report['stalls'])} --")
        for ev in report["stalls"]:
            w(f"t={_fmt(ev.get('t'), '.1f')}s: no step for "
              f"{_fmt(ev.get('age_s'), '.1f')}s "
              f"(deadline {_fmt(ev.get('deadline_s'), '.1f')}s, "
              f"pending depth {ev.get('pending_depth')})")
    else:
        w("stalls: none")

    # Per-dispatch outliers: each event carries the full attribution
    # split, so the report can say WHAT a slow iteration spent its time
    # on, not only that it was slow.
    if report["loop_stalls"]:
        w(f"-- loop stalls (dispatch wall > multiple of rolling median): "
          f"{len(report['loop_stalls'])} --")
        for ev in report["loop_stalls"][:20]:
            parts = []
            for key, label in (("data_wait_s", "data"),
                               ("dispatch_s", "dispatch"),
                               ("fetch_block_s", "fetch"),
                               ("host_work_s", "host")):
                if ev.get(key) is not None:
                    parts.append(f"{label} {_fmt(ev[key], '.3f')}s")
            w(f"{ev.get('split', '?')} e{ev.get('epoch', '?')} "
              f"d{ev.get('dispatch', '?')}: wall {_fmt(ev.get('wall_s'), '.3f')}s "
              f"vs median {_fmt(ev.get('median_s'), '.3f')}s"
              + ("  [" + ", ".join(parts) + "]" if parts else ""))
        if len(report["loop_stalls"]) > 20:
            w(f"... {len(report['loop_stalls']) - 20} more")

    if report["services"]:
        agg: Dict[str, List[float]] = {}
        for ev in report["services"]:
            # job names are "<kind>:e<epoch>" — fold across epochs by kind
            kind = str(ev.get("job", "?")).split(":", 1)[0]
            agg.setdefault(kind, []).append(float(ev.get("seconds", 0.0)))
        w(f"-- epoch services (off the dispatch path): "
          f"{len(report['services'])} jobs --")
        for kind, secs in sorted(agg.items()):
            w(f"{kind}: {len(secs)} jobs, total {sum(secs):.2f}s, "
              f"max {max(secs):.2f}s")
    for ev in report["service_errors"]:
        w(f"SERVICE ERROR in {ev.get('job', '?')}: {ev.get('error', '?')}")

    if report["bench"]:
        w("-- bench configs --")
        for ev in report["bench"]:
            w(f"{ev.get('key', '?')}: {_fmt(ev.get('images_per_sec'), '.2f')} "
              f"images/sec  [{ev.get('platform', '?')}]")
    if report["bench_summary"]:
        bs = report["bench_summary"]
        w(f"bench headline: {_fmt(bs.get('value'), '.2f')} {bs.get('unit', '')} "
          f"({bs.get('config', '?')}, platform {bs.get('platform', '?')}"
          + (f", mfu {_fmt(bs.get('mfu'))}" if bs.get("mfu") is not None else "")
          + ")")

    if report["serve_compiles"]:
        w(f"-- serve engine: {len(report['serve_compiles'])} AOT programs --")
        for ev in report["serve_compiles"]:
            w(f"b{ev.get('batch', '?')} i{ev.get('size', '?')} "
              f"{ev.get('dtype', '?')}"
              + (" +cycle" if ev.get("with_cycle") else "")
              + f": compile {_fmt(ev.get('seconds'), '.2f')}s")

    roll = report.get("serve_rollup")
    if roll:
        w(f"-- serving: {roll['n_images']} images in "
          f"{roll['n_flushes']} flushes --")
        trig = ", ".join(f"{k}={v}" for k, v in sorted(roll["triggers"].items()))
        w(f"flush triggers: {trig}  (full=throughput-bound, "
          f"deadline=latency-bound)")
        w(f"mean bucket fill: {_fmt(roll.get('mean_fill'), '.3f')}  "
          f"max queue depth: {roll['max_queue_depth']}")
        w(f"per-flush medians: dispatch {_fmt(roll.get('dispatch_p50_s'))}s, "
          f"fetch-block {_fmt(roll.get('fetch_block_p50_s'))}s")
    if report["serve_summary"]:
        ss = report["serve_summary"]
        w(f"serve summary: {_fmt(ss.get('images_per_sec'), '.2f')} images/sec "
          f"sustained ({ss.get('n_images', '?')} images), latency "
          f"p50 {_fmt(ss.get('latency_p50_s'))}s / "
          f"p95 {_fmt(ss.get('latency_p95_s'))}s / "
          f"p99 {_fmt(ss.get('latency_p99_s'))}s")

    froll = report.get("fleet_rollup")
    if froll:
        w(f"-- fleet: {froll['n_images']} images in "
          f"{froll['n_flushes']} flushes --")
        trig = ", ".join(f"{k}={v}"
                         for k, v in sorted(froll["triggers"].items()))
        w(f"flush triggers: {trig}  (refill=continuous batching engaged)")
        reps = ", ".join(f"r{k}={v}" for k, v in
                         sorted(froll["flushes_per_replica"].items()))
        w(f"flushes per replica: {reps}  mean fill "
          f"{_fmt(froll.get('mean_fill'), '.3f')}  "
          f"max queue depth: {froll['max_queue_depth']}")
        if froll["n_shed"]:
            by_c = ", ".join(f"{k}={v}" for k, v in
                             sorted(froll["shed_by_class"].items()))
            by_r = ", ".join(f"{k}={v}" for k, v in
                             sorted(froll["shed_by_reason"].items()))
            w(f"shed: {froll['n_shed']} ({by_c}; {by_r})")
        else:
            w("shed: none (never saturated past capacity)")
    if report["fleet_summary"]:
        fs = report["fleet_summary"]
        w(f"fleet summary: {_fmt(fs.get('images_per_sec'), '.2f')} "
          f"images/sec over {fs.get('n_replicas', '?')} replicas "
          f"({fs.get('n_images', '?')} images, "
          f"{fs.get('refill_flushes', '?')} refill flushes)")
        for name, row in sorted((fs.get("classes") or {}).items()):
            w(f"  class {name}: n={row.get('n', '?')} "
              f"p50 {_fmt(row.get('p50_s'))}s / p95 {_fmt(row.get('p95_s'))}s"
              f"  deadline misses: {row.get('deadline_misses', 0)}")

    troll = report.get("tenant_rollup")
    if troll:
        w(f"-- multi-tenant fleet: {len(troll['tenants'])} tenant(s), "
          f"{troll['n_swaps']} hot swap(s) --")
        for key, row in sorted(troll["tenants"].items()):
            parts = [f"{row['n_images']} images in {row['n_flushes']} "
                     f"flushes, shed {row['n_shed']}"]
            summ = row.get("summary") or {}
            if summ:
                slo = summ.get("slo_ms")
                parts.append(
                    f"p50 {_fmt(summ.get('p50_s'))}s / "
                    f"p95 {_fmt(summ.get('p95_s'))}s, SLO "
                    + (f"{_fmt(slo, '.0f')}ms" if slo is not None
                       else "class-default")
                    + f", misses {summ.get('slo_misses', 0)}")
            adm = row.get("admission") or {}
            if adm:
                budget = adm.get("shed_budget")
                parts.append(
                    f"admitted {adm.get('admitted', '?')}"
                    + (f", shed budget {_fmt(budget, '.2f')}"
                       if budget is not None else ""))
            if row["n_swaps"]:
                parts.append(f"{row['n_swaps']} swap(s)")
            w(f"  tenant {key}: " + "; ".join(parts))
        for ev in report["fleet_tenant_swaps"][:10]:
            w(f"  swap #{ev.get('swap', '?')} t={_fmt(ev.get('t'), '.2f')}s: "
              f"{ev.get('tenant', '?')} (queue depth "
              f"{ev.get('queue_depth', '?')} at flip)")

    aroll = report.get("autoscale_rollup")
    if aroll:
        w("-- self-driving fleet (autoscale / brownout / hedging / "
          "quarantine) --")
        if aroll["scale_events"]:
            ups = aroll["scale_events"].get("up", 0)
            downs = aroll["scale_events"].get("down", 0)
            retired = aroll["scale_events"].get("retired", 0)
            w(f"scale events: {ups} up, {downs} down "
              f"({retired} retirements completed), final active "
              f"{aroll['final_n_active']}")
        if aroll["brownout_moves"]:
            w(f"brownout: {aroll['brownout_moves']} level moves, "
              f"deepest level {aroll['brownout_max_level']}")
        if aroll["hedges_dispatched"] or aroll["hedge_cancels"]:
            canc = ", ".join(f"{k}={v}" for k, v in
                             sorted(aroll["hedge_cancels"].items()))
            w(f"hedges: {aroll['hedges_dispatched']} dispatched"
              + (f", cancelled {canc}" if canc else ""))
        if aroll["probe_verdicts"]:
            w("quality probes: " + ", ".join(
                f"{k}={v}" for k, v in
                sorted(aroll["probe_verdicts"].items())))
        if aroll["quarantine_actions"]:
            w("quarantine: " + ", ".join(
                f"{k}={v}" for k, v in
                sorted(aroll["quarantine_actions"].items())))
        # Scale timeline (stream order, capped): WHEN the fleet moved
        # and what the brownout ladder was doing around each move.
        timeline = sorted(
            report["fleet_autoscales"] + report["fleet_brownouts"],
            key=lambda ev: float(ev.get("t", 0.0)))
        for ev in timeline[:20]:
            if ev.get("event") == "fleet_autoscale":
                w(f"  t={_fmt(ev.get('t'), '.2f')}s scale "
                  f"{ev.get('phase', '?')} replica {ev.get('replica', '?')} "
                  f"-> {ev.get('n_active', '?')} active")
            else:
                w(f"  t={_fmt(ev.get('t'), '.2f')}s brownout level "
                  f"{ev.get('level', '?')} (backlog "
                  f"{_fmt(ev.get('backlog_s'), '.3f')}s, steps "
                  f"{ev.get('steps_by_class') or {}})")
        if len(timeline) > 20:
            w(f"  ... {len(timeline) - 20} more scale/brownout events")
        fs = report["fleet_summary"] or {}
        if fs.get("degraded_requests"):
            census = ", ".join(
                f"{k}={v}" for k, v in
                sorted((fs.get("degraded_census") or {}).items()))
            w(f"degraded requests: {fs['degraded_requests']}"
              + (f" ({census})" if census else ""))

    trroll = report.get("trace_rollup")
    if trroll:
        w(f"-- request traces ({trroll['n_traces']} kept"
          + (f", {trroll['n_late_supplements']} late span supplements"
             if trroll["n_late_supplements"] else "") + ") --")
        w("status: " + ", ".join(
            f"{k}={v}" for k, v in sorted(trroll["statuses"].items()))
          + f"; {trroll['n_tail_kept']} tail-kept (failure outcomes "
            "recorded regardless of --trace_sample)")
        for hop, s in trroll["hops"].items():
            w(f"  hop {hop:<8} n={s['n']:<6} p50 {s['p50_ms']:>9.3f}ms  "
              f"p95 {s['p95_ms']:>9.3f}ms")
        if trroll["slowest"]:
            w("slowest (feed the trace_id to tools/trace_timeline.py "
              "--trace-id):")
            for ex in trroll["slowest"]:
                tag = "".join(
                    f" {k}={ex[k]}" for k in ("class", "tenant")
                    if ex.get(k))
                w(f"  {ex['trace_id']}  {_fmt(ex['dur_ms'], '.3f')}ms  "
                  f"{ex['status']}{tag}")
    elif report.get("fleet_flushes") or report.get("serve_flushes"):
        # A serving stream with zero kept traces is worth a line: the
        # operator probably expected --trace_sample > 0.
        w("-- request traces: absent (no `trace` events in stream; "
          "is --trace_sample > 0?) --")

    ttr = report.get("train_trace_rollup")
    if ttr:
        w(f"-- training traces ({ttr['n_traces']} epoch trace(s)) --")
        for hop, s in ttr["hops"].items():
            w(f"  hop {hop:<9} n={s['n']:<6} p50 {s['p50_ms']:>9.3f}ms  "
              f"p95 {s['p95_ms']:>9.3f}ms")
        if ttr["spans_dropped"]:
            w(f"  SPANS DROPPED: {ttr['spans_dropped']} (epoch tiling "
              f"incomplete — raise --train_trace_max_spans)")
        if ttr["n_stragglers"]:
            blame = ", ".join(f"{k}={v}"
                              for k, v in sorted(ttr["blames"].items()))
            w(f"  stragglers: {ttr['n_stragglers']} (blame: {blame})")
        else:
            w("  stragglers: none")
    elif report["epoch_steps"]:
        # A training stream without epoch traces is worth the same
        # version/config-skew line the serving streams get.
        w("-- training traces: absent (no `train_epoch` traces; is "
          "--train_trace_sample > 0?) --")

    probe = report.get("collective_probe_rollup")
    if probe:
        mesh = probe.get("mesh") or {}
        w(f"-- collective probe (measured, mesh "
          f"{mesh.get('n_data', '?')} data x "
          f"{mesh.get('n_spatial', '?')} spatial) --")
        rec = probe.get("reconcile") or {}
        for ax, v in sorted((rec.get("axes") or {}).items()):
            line = (f"{ax} axis: measured "
                    f"{_fmt(v.get('measured_s'), '.6f')}s/step at "
                    f"{_fmt(v.get('probe_gbps'), '.2f')} Gbit/s")
            if v.get("est_s") is not None:
                line += (f" vs analytic {_fmt(v.get('est_s'), '.6f')}s "
                         f"(delta {_fmt(v.get('delta_frac'), '.3f')})")
            w(line)
        if probe.get("measured_step_comms_s") is not None:
            line = (f"per-step collective (measured): "
                    f"{_fmt(probe['measured_step_comms_s'], '.6f')}s")
            if rec.get("est_step_comms_s") is not None:
                line += (f" vs analytic "
                         f"{_fmt(rec.get('est_step_comms_s'), '.6f')}s "
                         f"(delta {_fmt(rec.get('delta_frac'), '.3f')})")
            w(line)

    lint = report.get("lint")
    if lint:
        counts = lint.get("counts") or {}
        detail = (", ".join(f"{k}={v}" for k, v in sorted(counts.items()))
                  or "no live findings")
        w(f"-- static discipline (graftlint preflight) --")
        w(f"verdict: {'PASSED' if lint.get('ok') else 'FAILED'}  "
          f"({lint.get('files_scanned', '?')} files, "
          f"rules: {', '.join(lint.get('rules') or ['?'])})")
        w(f"findings: {detail}; {lint.get('n_suppressed', 0)} suppressed, "
          f"{lint.get('n_baselined', 0)} baselined")
        for f in (lint.get("findings") or [])[:10]:
            w(f"  {f.get('path', '?')}:{f.get('line', '?')}: "
              f"[{f.get('rule', '?')}] {f.get('message', '?')}")

    end = report["end"]
    if end:
        w(f"run end: {end.get('status', '?')} at t={_fmt(end.get('t'), '.1f')}s")
    else:
        w("run end: NO end event — stream truncated (crash, SIGKILL, or "
          "still running)")
    return "\n".join(out)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("jsonl", help="telemetry stream to fold")
    parser.add_argument("--json", action="store_true",
                        help="emit the folded report as JSON instead of text")
    parser.add_argument("--probe-json", action="store_true",
                        help="emit only the last collective_probe payload "
                             "as JSON (the round's measured-collective "
                             "artifact; exits 3 when the stream has none)")
    args = parser.parse_args(argv)
    try:
        events, skipped = load_events(args.jsonl)
    except OSError as e:
        print(f"cannot read {args.jsonl}: {e}", file=sys.stderr)
        return 2
    report = fold(events, skipped)
    if args.probe_json:
        probe = report.get("collective_probe_rollup")
        if not probe:
            print(f"no collective_probe event in {args.jsonl}",
                  file=sys.stderr)
            return 3
        try:
            print(json.dumps(probe, indent=2, sort_keys=True,
                             default=str))
        except BrokenPipeError:
            sys.stderr.close()
        return 0
    lint = load_lint_verdict(args.jsonl)
    if lint is not None:
        report["lint"] = lint
    try:
        if args.json:
            print(json.dumps(report, indent=2, default=str))
        else:
            print(render(report))
    except BrokenPipeError:
        # `obs_report.py ... | head` closes our stdout early — that is a
        # reader's prerogative, not an error.
        sys.stderr.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
