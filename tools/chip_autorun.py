"""Automatic chip-window runner: watch the relay, cash the whole queue.

Four rounds of evidence (docs/TUNNEL_POSTMORTEM.md, VERDICT r4) say the
TPU loopback relay comes up rarely, briefly, and unpredictably; a
human-sequenced runbook missed every window but one. This daemon makes
capture automatic: poll the relay sockets (cheap TCP connects — never
an axon client), and on a down->up transition that HOLDS for a
confirmation poll, run docs/TPU_RUNBOOK.md's queue as one supervised
session:

    diag -> bench cold -> bench warm -> pad A/B sweep (zero/fused)
    -> epilogue sweep (pad_impl=epilogue, local-compile forced)
    -> accum 512^2 row -> 512^2 scan rows -> serving sweep
    (bench_serve: pipeline + fleet + int8 tiers) -> profiler trace
    -> timed main.py run

Each step is a subprocess with a generous timeout, stdout+stderr teed
to docs/chip_logs/<run>/<step>.log, and its artifacts git-committed
IMMEDIATELY on completion — a window that closes mid-queue loses
nothing already landed. Per-step completion is recorded in
docs/chip_autorun_status.json, so a SECOND window resumes the queue at
the first incomplete step instead of repeating finished work.

Ground rules enforced (TPU_RUNBOOK "learned the hard way"):
  - ONE axon client at a time: the runner refuses to start while
    another chip-capable process is alive, and runs steps strictly
    sequentially.
  - never kill mid-compile: per-step timeouts sit far beyond any
    observed healthy compile (cold fused programs <=10 min each over
    the remote leg). Hitting one means the tunnel is already wedged;
    the step is killed, the kill logged loudly, and the QUEUE ABORTS —
    no further clients are started against a sick relay.
  - no Mosaic through the remote-compile leg (ground rule 2b): the
    only pallas-bearing step (epilogue_sweep) forces the local-compile
    registration so its Mosaic programs build against the in-image
    libtpu; every other step is XLA-only.
  - local-compile fallback: :8082+:8083 up with :8093 down runs every
    step under PALLAS_AXON_POOL_IPS= CYCLEGAN_AXON_LOCAL_COMPILE=1
    (compiles against the in-image libtpu; the persistent cache makes
    them hot — tools/cache_warm.py).

Usage:
    nohup python tools/chip_autorun.py --watch >/tmp/chip_autorun.log 2>&1 &
    python tools/chip_autorun.py --once      # health-check + run queue now
    python tools/chip_autorun.py --dry-run   # print the queue, run nothing

The parent process never imports jax (a dead relay can wedge backend
init); all chip work happens in the step subprocesses.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import time
from dataclasses import dataclass, field

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROUND_TAG = os.environ.get("CHIP_AUTORUN_TAG", "r05")
LOG_DIR_REL = os.path.join("docs", "chip_logs", ROUND_TAG)
STATUS_REL = os.path.join("docs", "chip_autorun_status.json")
POLL_S = float(os.environ.get("CHIP_AUTORUN_POLL_S", "45"))
CONFIRM_S = float(os.environ.get("CHIP_AUTORUN_CONFIRM_S", "10"))
# While the relay stays up with queue steps incomplete, retry a
# refused/aborted attempt this often (a manual client exiting, or a
# transiently sick tunnel healing, must not require a socket flap).
RETRY_S = float(os.environ.get("CHIP_AUTORUN_RETRY_S", "600"))
# Directories larger than this get a MANIFEST committed instead of
# their contents (profiler traces can be arbitrarily large).
MAX_COMMIT_DIR_BYTES = 40 * 1024 * 1024

RELAY_PORTS = (8082, 8083, 8093)


def relay_status() -> dict:
    """Socket-connect probe of the loopback relay legs. Never spawns an
    axon client; safe at any frequency. Overridable for tests via
    CHIP_AUTORUN_FAKE_RELAY=8082:open,8083:open,8093:closed."""
    fake = os.environ.get("CHIP_AUTORUN_FAKE_RELAY")
    if fake:
        out = {}
        for part in fake.split(","):
            port, state = part.split(":")
            out[int(port)] = state
        return out
    out = {}
    for port in RELAY_PORTS:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=2):
                out[port] = "open"
        except OSError:
            out[port] = "closed"
    return out


def relay_mode(status: dict) -> str | None:
    """Map socket states to an execution mode.

    "remote": claim/execute (:8082) + remote-compile (:8093) up — the
    normal path (axon_compat.relay_ok's full-relay criterion).
    "local_compile": claim legs (:8082+:8083) up, compile service down —
    every step runs with the local-libtpu compile registration.
    None: chip execution impossible.
    """
    if status.get(8082) == "open" and status.get(8093) == "open":
        return "remote"
    if status.get(8082) == "open" and status.get(8083) == "open":
        return "local_compile"
    return None


@dataclass
class Step:
    name: str
    argv: list
    timeout_s: float
    env: dict = field(default_factory=dict)
    artifacts: list = field(default_factory=list)  # repo-relative paths
    stdout_to: str | None = None  # repo-relative: capture stdout (bench JSON)
    abort_queue_on_fail: bool = False  # diag failing means relay is sick
    # Health probes re-run at EVERY attempt: completion/give-up state
    # never skips them (a past ok says nothing about THIS window, and
    # skipping the probe would launch long clients unverified).
    always_run: bool = False
    # (src_abs, dest_repo_rel) pairs copied into the repo AFTER the step
    # completes, then committed like artifacts — lets a step write its
    # bulky output dir OUTSIDE the repo (checkpoints!) while the select
    # evidence (e.g. the profiler trace) still lands in git.
    collect: list = field(default_factory=list)


def build_queue(mode: str, round_tag: str = ROUND_TAG) -> list:
    """The TPU_RUNBOOK queue, highest value first (VERDICT r4 item 1).

    Budgets: a healthy cold compile through the remote leg is 2-5 min
    per distinct program (TPU_RUNBOOK ground rule 3); each step's
    timeout covers every program it compiles cold plus measurement,
    with ~3x slack. bench cold warms the persistent cache, so
    bench warm (the record that matters) measures hot.
    """
    py = sys.executable
    env = {}
    if mode == "local_compile":
        env = {"PALLAS_AXON_POOL_IPS": "", "CYCLEGAN_AXON_LOCAL_COMPILE": "1"}
    sweeps = os.path.join("docs", "bench_sweeps.json")
    # serve_sweep's telemetry stream + Perfetto export stage OUTSIDE
    # the repo (like the profiler trace run); serve_trace collects the
    # keeper slice into the round's chip_logs dir.
    serve_obs = f"/tmp/chip_serve_obs_{round_tag}.jsonl"
    serve_perfetto = f"/tmp/chip_serve_trace_{round_tag}.perfetto.json"
    # Training-trace round (ISSUE 20): a short fully-sampled traced run
    # (every dispatch minted a span graph, probe at startup + each
    # epoch) streams to /tmp; the train_trace step folds it.
    train_obs = f"/tmp/chip_train_obs_{round_tag}.jsonl"
    train_perfetto = f"/tmp/chip_train_trace_{round_tag}.perfetto.json"
    q = [
        # Static-discipline preflight: graftlint over the whole tree
        # (donation-aliasing, no-sync, tracer-leak, compile-site census
        # vs the committed baseline). Runs BEFORE the diag because it
        # needs no TPU at all — a donation-aliasing or hot-path-sync
        # finding means the code about to occupy hours of chip time
        # carries a known heap-corruption or serialization class, so
        # the queue aborts without burning the window. The one-line
        # JSON verdict lands next to the round's logs, where
        # obs_report.py picks it up.
        Step("graftlint", [py, "tools/graftlint", "--json"], 300.0,
             env=env, abort_queue_on_fail=True, always_run=True,
             stdout_to=os.path.join(
                 "docs", "chip_logs", round_tag, "graftlint.json")),
        # Comms-census preflight: compile the round's target mesh (the
        # validated unrolled smoke program) on HOST devices (tools/
        # comms_census.py forces
        # JAX_PLATFORMS=cpu — never an axon client, needs no TPU) and
        # reconcile its compiled collectives against the analytic
        # ledger (obs/comms.py). A mis-sharded program — the partitioner
        # silently resharding where the model says halo, or a gradient
        # tree dropping out of the all-reduce — fails reconciliation
        # here and aborts the queue BEFORE any chip time burns on it.
        # Gates BOTH conv shardings (xla partitioner halos + explicit
        # shard_map halo exchanges) so the spatial_sweep below never
        # runs a halo program the ledger can't account for.
        Step("comms_census",
             [py, "tools/comms_census.py", "--devices", "8",
              "--spatial_impl", "both"], 1800.0,
             env={**env, "JAX_PLATFORMS": "cpu"},
             abort_queue_on_fail=True, always_run=True,
             stdout_to=os.path.join(
                 "docs", "chip_logs", round_tag, "comms_census.json")),
        # Staged health probe: attributes any hang to init vs compile
        # vs execute. A failure here aborts the queue — the relay is
        # not actually healthy, and further clients would pile onto it.
        Step("diag", [py, "tools/tpu_diag.py", "--full"], 1800.0,
             env=env, abort_queue_on_fail=True, always_run=True),
        # Official-number runs: cold warms every TPU_CONFIGS program
        # into the persistent cache; warm is the headline record.
        Step("bench_cold", [py, "bench.py"], 6300.0,
             env={**env, "BENCH_TIME_BUDGET_S": "5400"},
             stdout_to=os.path.join(
                 "docs", f"bench_{round_tag}_onchip_cold.json")),
        Step("bench_warm", [py, "bench.py"], 1800.0,
             env={**env, "BENCH_TIME_BUDGET_S": "900"},
             stdout_to=os.path.join(
                 "docs", f"bench_{round_tag}_onchip.json")),
        # The compiler-certified ~1.4x pad lever (zero) + the
        # parity-preserving fused variant (runbook item 6).
        Step("pad_sweep",
             [py, "tools/chip_sweep.py", "scan:b16zero", "scan:b24zero",
              "scan:b16fused"], 3600.0, env=env, artifacts=[sweeps]),
        # The parity pad-gap contender (pad_impl="epilogue"): the trunk
        # IN>ReLU>reflect-pad chains as one Pallas kernel. A Mosaic
        # program, so this step ALWAYS forces the local-compile
        # registration regardless of mode — ground rule 2b: Mosaic never
        # crosses the remote-compile leg (docs/TUNNEL_POSTMORTEM.md
        # incident 2). In a remote window whose :8083 leg is down the
        # sweep records an error row and the queue continues.
        Step("epilogue_sweep",
             [py, "tools/chip_sweep.py", "scan:b16epi"], 2700.0,
             env={**env, "PALLAS_AXON_POOL_IPS": "",
                  "CYCLEGAN_AXON_LOCAL_COMPILE": "1"},
             artifacts=[sweeps]),
        # The FLOP-reduction levers (ISSUE 7, ROADMAP item 3): fusedprop
        # shared-forward gradients (fp — gradient-parity, 18g+14d vs
        # 18g+16d analytic FLOPs/pair) and the Perturbative-GAN cheap
        # trunk (pb — quality tier, health-gated), both at the headline
        # scan:b16 geometry plus the combined stack (fppb). The combined
        # baseline these rows pair against is bench_warm's scan b16 row;
        # cache_warm pre-warms all three programs.
        Step("grad_sweep",
             [py, "tools/chip_sweep.py", "scan:b16fp", "scan:b16pb",
              "scan:b16fppb"], 3600.0, env=env, artifacts=[sweeps]),
        # The GANAX zero-skip upsample tiers (ISSUE 14): zs is the pure
        # XLA phase decomposition (~4x fewer upsample MACs), zsf the
        # fused Pallas kernel, fpzs the stacked-levers row (fusedprop +
        # zeroskip). zsf is a Mosaic program, so like epilogue_sweep the
        # step forces local-compile registration (ground rule 2b); the
        # dense baselines these rows pair against are bench_warm's scan
        # b16 and fp rows; cache_warm pre-warms all three programs.
        Step("upsample_sweep",
             [py, "tools/chip_sweep.py", "scan:b16zs", "scan:b16zsf",
              "scan:b16fpzs"], 3600.0,
             env={**env, "PALLAS_AXON_POOL_IPS": "",
                  "CYCLEGAN_AXON_LOCAL_COMPILE": "1"},
             artifacts=[sweeps]),
        # 512^2 HBM-relief rows (runbook item 5): accum 8x1 (the
        # certified memory contract) and the plain/zero 512 scans.
        Step("accum512", [py, "tools/chip_sweep.py", "accum:b1k8i512"],
             2700.0, env=env, artifacts=[sweeps]),
        Step("scan512",
             [py, "tools/chip_sweep.py", "scan:b4k2i512",
              "scan:b4k2zeroi512"], 3600.0, env=env, artifacts=[sweeps]),
        # dp x spatial weak-scaling sweep (ISSUE 18): bench_scaling in
        # grid mode over the (data x spatial) factorizations of the
        # 8-device mesh at the headline geometry, explicit-halo conv
        # sharding. One JSON line with img/s per grid cell plus the
        # measured weak-scaling efficiency — the number
        # scaling_model.py --measured diffs against the analytic ~99%
        # prediction, and run_compare gates in absolute points
        # (--max_scaling_efficiency_drop). comms_census above has
        # already certified the halo program's collectives by the time
        # this runs.
        Step("spatial_sweep",
             [py, "bench_scaling.py", "--grid", "8x1,4x2,2x4",
              "--batch", "4", "--iters", "20", "--spatial_impl", "halo"],
             3600.0, env={**env, "BENCH_TIME_BUDGET_S": "3000"},
             stdout_to=os.path.join(
                 "docs", f"scaling_{round_tag}_onchip.json")),
        # The first 1024^2 cell: spatial=4 shrinks per-device
        # activation temps 4x, remat + accum shrink the rest — the
        # HBM ledger (bench_scaling.hbm_ledger, anchored on the
        # compiler-measured 512^2/256^2 temps in docs/BENCHMARKS.md)
        # predicts ~4.3 GB of 15.75 GB usable. bench_scaling preflights
        # that ledger per cell and skips a predicted non-fit instead of
        # OOMing the relay window.
        Step("spatial_1024",
             [py, "bench_scaling.py", "--grid", "2x4", "--image", "1024",
              "--batch", "1", "--accum", "2", "--remat", "--iters", "4",
              "--spatial_impl", "halo"], 3600.0,
             env={**env, "BENCH_TIME_BUDGET_S": "3000"},
             stdout_to=os.path.join(
                 "docs", f"scaling1024_{round_tag}_onchip.json")),
        # Serving open-loop sweep on chip (ROADMAP serving item): the
        # bench_serve contract — serial baseline, saturated pipeline,
        # offered-load curve, fleet/int8 tiers, trace_overhead — lands
        # as one JSON line, validated before commit like the bench
        # steps. Budget covers the serve-program compiles (cache_warm
        # pre-warms them) plus the sweep itself. The telemetry stream
        # (incl. the trace_overhead phase's span graphs at sample=1.0)
        # goes to /tmp; the serve_trace step below folds it.
        Step("serve_sweep", [py, "bench_serve.py"], 3600.0,
             env={**env, "BENCH_SERVE_TIME_BUDGET_S": "1800",
                  "BENCH_OBS_JSONL": serve_obs},
             stdout_to=os.path.join(
                 "docs", f"bench_serve_{round_tag}_onchip.json")),
        # Archive the round's request traces next to the bench JSON:
        # the critical-path table (per class/tenant per-hop p50/p95 +
        # hop-sum-vs-e2e reconciliation) commits via stdout_to, and the
        # Perfetto timeline + the raw trace slice collect into the
        # round's chip_logs dir — a latency regression three rounds
        # later diffs against THESE spans, not a rerun.
        Step("serve_trace",
             [py, "tools/trace_timeline.py", serve_obs,
              "--out", serve_perfetto, "--json"], 300.0, env=env,
             collect=[(serve_perfetto,
                       os.path.join("docs", "chip_logs", round_tag,
                                    "serve_trace.perfetto.json")),
                      (serve_obs,
                       os.path.join("docs", "chip_logs", round_tag,
                                    "serve_obs.jsonl"))],
             stdout_to=os.path.join(
                 "docs", "chip_logs", round_tag,
                 "serve_trace_table.json")),
        # Profiler trace of the headline config (runbook item 3):
        # attributes the unexplained 18% between the 337 ms measured
        # step and the 277 ms bandwidth floor.
        # Output dir OUTSIDE the repo (the run checkpoints at its final
        # epoch — hundreds of MB); only the profiler trace is collected
        # into git, size-guarded by commit_paths' MANIFEST fallback.
        Step("trace",
             [py, "main.py", "--trace", "4", "--bf16", "--batch_size", "16",
              "--data_source", "synthetic", "--synthetic_train_size", "96",
              "--synthetic_test_size", "16", "--epochs", "1",
              "--output_dir", "/tmp/chip_autorun_trace"],
             3600.0, env=env,
             collect=[("/tmp/chip_autorun_trace/traces",
                       os.path.join(LOG_DIR_REL, "trace_run", "traces"))]),
        # Chaos drill on chip (resil acceptance): the same scripted
        # fault drills tier-1 runs on CPU — NaN rollback through the
        # verified ring, replica-crash self-healing, retried ckpt I/O,
        # the elastic preempt/resume drill (full set here, including
        # the deadline-overrun kill edge tier-1 skips in --fast mode),
        # and the overload_brownout drill (autoscaler grows/retires
        # replicas through a surge while the brownout cascade degrades
        # tiers before shedding and hedged dispatch covers the tail) —
        # executed against the real accelerator path. One JSON line,
        # exit nonzero if any recovery invariant fails.
        Step("chaos_drill", [py, "tools/chaos_drill.py"], 3600.0,
             env=env,
             stdout_to=os.path.join(
                 "docs", f"chaos_drill_{round_tag}.json")),
        # End-to-end timed training run — the direct analog of the
        # reference's only perf signal (main.py:388-392 epoch timing);
        # numbers print to the step log. Output dir is OUTSIDE the
        # repo: checkpoints are hundreds of MB and must not be
        # committed; the log carries elapse + images/sec.
        Step("timed_main",
             [py, "main.py", "--epochs", "2", "--batch_size", "16", "--bf16",
              "--steps_per_dispatch", "8", "--prefetch_batches", "2",
              "--data_source", "synthetic", "--synthetic_train_size", "2048",
              "--synthetic_test_size", "64",
              "--output_dir", "/tmp/chip_autorun_timed"],
             5400.0, env=env),
        # Training-run distributed tracing on chip (ISSUE 20): the same
        # geometry as timed_main but short and FULLY sampled — every
        # fused dispatch mints its data_wait/host/submit/device/resolve
        # span graph from StepClock's deferred timestamps (zero extra
        # dispatches), the collective probe times psum/ppermute per mesh
        # axis at startup and each epoch boundary, and the straggler
        # detector attributes any outlier dispatch. timed_main above
        # stays UNtraced so the headline number has no trace overhead;
        # run_compare's --max_train_trace_overhead gates the pair.
        # Output dir outside the repo (checkpoints); the obs stream goes
        # to /tmp for the fold step below.
        Step("train_traced",
             [py, "main.py", "--epochs", "2", "--batch_size", "16", "--bf16",
              "--steps_per_dispatch", "8", "--prefetch_batches", "2",
              "--data_source", "synthetic", "--synthetic_train_size", "512",
              "--synthetic_test_size", "64",
              "--train_trace_sample", "1.0", "--probe_every", "1",
              "--obs_jsonl", train_obs,
              "--output_dir", "/tmp/chip_autorun_train_traced"],
             3600.0, env=env),
        # Archive the round's epoch span graphs next to the serve ones:
        # the per-epoch critical-path table (per-hop p50/p95 + span-sum
        # vs epoch-wall reconciliation) commits via stdout_to; the
        # Perfetto timeline + raw slice (incl. collective_probe and
        # train_straggler events) collect into the round's chip_logs
        # dir — a goodput regression rounds later diffs THESE spans.
        Step("train_trace",
             [py, "tools/trace_timeline.py", train_obs,
              "--out", train_perfetto, "--json"], 300.0, env=env,
             collect=[(train_perfetto,
                       os.path.join("docs", "chip_logs", round_tag,
                                    "train_trace.perfetto.json")),
                      (train_obs,
                       os.path.join("docs", "chip_logs", round_tag,
                                    "train_obs.jsonl"))],
             stdout_to=os.path.join(
                 "docs", "chip_logs", round_tag,
                 "train_trace_table.json")),
        # The round's measured-collective artifact: the traced run
        # probed the REAL device mesh at startup + every epoch boundary
        # (psum/ppermute per axis/payload bucket, reconciled against
        # the analytic census); extract the last probe payload from the
        # stream — re-running the CPU-forcing probe CLI here would
        # measure the wrong fabric.
        Step("collective_probe",
             [py, "tools/obs_report.py", train_obs, "--probe-json"],
             120.0, env=env,
             stdout_to=os.path.join(
                 "docs", "chip_logs", round_tag,
                 "collective_probe.json")),
    ]
    return q


# ----------------------------------------------------------------- run


def _say(msg: str) -> None:
    print(f"[{time.strftime('%F %T')}] {msg}", flush=True)


def _git(repo: str, *args: str) -> subprocess.CompletedProcess:
    """git helper that NEVER raises: a commit hiccup (slow disk, lock
    contention) must not crash the daemon mid-window."""
    try:
        return subprocess.run(["git", "-C", repo, *args],
                              capture_output=True, text=True, timeout=300)
    except subprocess.TimeoutExpired:
        return subprocess.CompletedProcess(
            ["git", *args], returncode=124, stdout="",
            stderr=f"git {' '.join(args[:1])} timed out after 300s")


def _dir_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total


def _manifest_for(path: str) -> str:
    lines = ["# too large to commit; sizes only"]
    for root, _dirs, files in os.walk(path):
        for f in sorted(files):
            p = os.path.join(root, f)
            rel = os.path.relpath(p, path)
            try:
                lines.append(f"{os.path.getsize(p):>12}  {rel}")
            except OSError:
                pass
    return "\n".join(lines) + "\n"


def commit_paths(repo: str, paths: list, message: str) -> bool:
    """Stage `paths` (repo-relative; oversized dirs are replaced by a
    MANIFEST) and commit. Returns True iff a commit was created."""
    to_add = []
    for rel in paths:
        abs_p = os.path.join(repo, rel)
        if os.path.isdir(abs_p) and _dir_bytes(abs_p) > MAX_COMMIT_DIR_BYTES:
            manifest = abs_p.rstrip("/") + ".MANIFEST"
            with open(manifest, "w") as f:
                f.write(_manifest_for(abs_p))
            to_add.append(os.path.relpath(manifest, repo))
            # Fence the raw dir off from any future `git add -A` too: the
            # r5 window's 146MB xplane blob got committed exactly that way
            # after only the MANIFEST was staged here. The data stays on
            # disk for tools/trace_report.py; it just can't enter history.
            try:
                with open(os.path.join(abs_p, ".gitignore"), "w") as f:
                    f.write("*\n")
            except OSError:
                pass  # unwritable dir: the MANIFEST guard still holds
            _say(f"{rel}: {_dir_bytes(abs_p)} bytes — committing MANIFEST "
                 "only (dir self-gitignored)")
        elif os.path.exists(abs_p):
            to_add.append(rel)
    if not to_add:
        return False
    r = _git(repo, "add", "--", *to_add)
    if r.returncode != 0:
        _say(f"git add failed: {r.stderr.strip()}")
        return False
    r = _git(repo, "commit", "-m", message, "--", *to_add)
    if r.returncode != 0:
        # "nothing to commit" is normal when a step produced no change
        out = (r.stdout + r.stderr).strip()
        _say(f"git commit: {out.splitlines()[-1] if out else 'failed'}")
        return False
    return True


def load_status(repo: str) -> dict:
    path = os.path.join(repo, STATUS_REL)
    try:
        with open(path) as f:
            return json.load(f)
    except (FileNotFoundError, ValueError):
        return {"steps": []}


def save_status(repo: str, status: dict) -> None:
    path = os.path.join(repo, STATUS_REL)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(status, f, indent=1)
    os.replace(tmp, path)


def _this_round(status: dict) -> list:
    """Ledger records scoped to the CURRENT round tag: a step completed
    in r05 must not skip the identically-named step of r06 — each
    round's captures are fresh evidence (records carry their tag;
    legacy tagless records are treated as foreign)."""
    return [s for s in status["steps"] if s.get("tag") == ROUND_TAG]


def completed_steps(status: dict) -> set:
    return {s["name"] for s in _this_round(status)
            if s.get("status") == "ok"}


def given_up_steps(status: dict, strikes: int = 2) -> set:
    """Steps that hit their timeout `strikes` times THIS round: stop
    re-running them automatically (each retry kills a client against a
    possibly just-slow tunnel — ground rule 2 territory) so the REST of
    the queue still gets its chance on later windows."""
    counts: dict = {}
    for s in _this_round(status):
        if s.get("status") == "timeout_killed":
            counts[s["name"]] = counts.get(s["name"], 0) + 1
    return {name for name, n in counts.items() if n >= strikes}


def _argv_is_chip_client(argv: list, repo: str, cwd: str | None = None) -> bool:
    """True if this parsed argv looks like one of the repo's
    chip-capable entry points. Matching rules, each closing an observed
    or reviewed false-positive that would make the watcher refuse every
    window:
    - per-TOKEN, never substring over the joined cmdline (the session
      driver's --append-system-prompt MENTIONS bench.py/tpu_diag.py
      inside one giant argv element);
    - only the SCRIPT position (first non-option token after argv[0])
      is matched, so `python sometool.py --input bench.py` — a marker
      name as a data argument — is not a client;
    - main.py is generic: a relative token resolves against the
      process's own cwd (`cwd`), and only THIS repo's main.py counts.
    """
    if not argv:
        return False
    if "python" not in os.path.basename(argv[0]):
        return False
    markers = ("bench.py", "chip_sweep.py", "tpu_diag.py",
               "aot_analyze.py", "aot_multichip.py", "aot_accum_probe.py",
               "cache_warm.py", "main.py")
    script = next((t for t in argv[1:] if not t.startswith("-")), None)
    if script is None:
        return False
    base = os.path.basename(script)
    if base not in markers:
        return False
    if base != "main.py":
        return True
    if os.path.isabs(script):
        path = script
    elif cwd:
        path = os.path.join(cwd, script)
    else:
        return False  # relative main.py with unknown cwd: can't claim it's ours
    return os.path.realpath(path).startswith(
        os.path.realpath(repo) + os.sep)


def other_chip_clients(repo: str) -> list:
    """PIDs of other processes that look like chip clients (ground rule
    1: one axon client at a time). Scans /proc argv token-wise,
    excluding ourselves and our ancestors."""
    me = os.getpid()
    ancestors = set()
    pid = me
    for _ in range(16):
        try:
            with open(f"/proc/{pid}/stat") as f:
                pid = int(f.read().split()[3])
            ancestors.add(pid)
        except (OSError, ValueError, IndexError):
            break
    hits = []
    for d in os.listdir("/proc"):
        if not d.isdigit() or int(d) == me or int(d) in ancestors:
            continue
        try:
            with open(f"/proc/{d}/cmdline", "rb") as f:
                argv = [t.decode("utf-8", "replace")
                        for t in f.read().split(b"\0") if t]
        except OSError:
            continue
        try:
            proc_cwd = os.readlink(f"/proc/{d}/cwd")
        except OSError:
            proc_cwd = None
        if not _argv_is_chip_client(argv, repo, cwd=proc_cwd):
            continue
        # A JAX_PLATFORMS=cpu process can never claim the chip (the
        # repo's CLIs re-assert the env var over the sitecustomize) —
        # offline CPU work (tests, quality A/Bs) must not block a
        # window.
        try:
            with open(f"/proc/{d}/environ", "rb") as f:
                env_entries = f.read().split(b"\0")
            if b"JAX_PLATFORMS=cpu" in env_entries:
                continue
        except OSError:
            pass  # unreadable environ: assume it could be a client
        hits.append((int(d), " ".join(argv)[:300]))
    return hits


def run_step(repo: str, step: Step, log_dir: str) -> dict:
    """Run one queue step supervised; returns its status record."""
    os.makedirs(log_dir, exist_ok=True)
    log_path = os.path.join(log_dir, f"{step.name}.log")
    env = dict(os.environ)
    env.update(step.env)
    rec = {"name": step.name, "tag": ROUND_TAG, "argv": step.argv,
           "started": time.strftime("%FT%TZ", time.gmtime()),
           "mode_env": {k: v for k, v in step.env.items()}}
    t0 = time.perf_counter()
    stdout_path = (os.path.join(repo, step.stdout_to)
                   if step.stdout_to else None)
    _say(f"step {step.name}: starting ({' '.join(step.argv)})")
    try:
        with open(log_path, "w") as log_f:
            if stdout_path:
                os.makedirs(os.path.dirname(stdout_path), exist_ok=True)
                out_f = open(stdout_path, "w")
            else:
                out_f = log_f
            try:
                # start_new_session: the step gets its own process
                # group, so a timeout kill reaps GRANDCHILDREN too
                # (bench.py spawns probe/CPU-worker subprocesses; an
                # orphaned one matches other_chip_clients' markers and
                # would block the next window attempt for ~95 min).
                p = subprocess.Popen(
                    step.argv, cwd=repo, env=env, stdout=out_f,
                    stderr=log_f if stdout_path else subprocess.STDOUT,
                    start_new_session=True)
                try:
                    rc = p.wait(timeout=step.timeout_s)
                    rec["rc"] = rc
                    rec["status"] = "ok" if rc == 0 else "failed"
                except subprocess.TimeoutExpired:
                    # The generous budget was exceeded: the tunnel is
                    # wedged. This kill is exactly the mid-compile kill
                    # ground rule 2 forbids against a HEALTHY relay —
                    # record it loudly; the caller aborts the queue.
                    import signal as _signal

                    try:
                        os.killpg(os.getpgid(p.pid), _signal.SIGKILL)
                    except (ProcessLookupError, PermissionError):
                        p.kill()
                    try:
                        p.wait(timeout=30)
                    except subprocess.TimeoutExpired:
                        # D-state child SIGKILL can't reap (wedged
                        # transport I/O): record and move on — the
                        # zombie-to-be will trip other_chip_clients,
                        # which is the correct conservative behavior;
                        # crashing the daemon here would silently end
                        # all future window capture.
                        _say(f"step {step.name}: child {p.pid} did not "
                             "die within 30s of SIGKILL (D-state?)")
                    rec["status"] = "timeout_killed"
                    rec["rc"] = None
                    _say(f"step {step.name}: TIMEOUT after "
                         f"{step.timeout_s:.0f}s — process group killed "
                         "(tunnel presumed wedged); queue will abort")
            finally:
                if stdout_path:
                    out_f.close()
    except OSError as e:
        rec["status"] = "failed"
        rec["rc"] = None
        rec["error"] = str(e)
        _say(f"step {step.name}: spawn failed: {e}")
    rec["wall_s"] = round(time.perf_counter() - t0, 1)
    _say(f"step {step.name}: {rec['status']} in {rec['wall_s']}s")
    return rec


def run_queue(repo: str, queue: list, resume_from: set = frozenset(),
              mode: str | None = None) -> bool:
    """Run the queue, committing artifacts after every step. Returns
    True iff every step completed ok (now or in a prior window).
    `mode` is the relay mode the queue was BUILT for: if the live mode
    shifts mid-queue (e.g. :8093 drops, remote -> local_compile), the
    queue stops so the caller rebuilds it with the right compile-leg
    env instead of hanging a step on a dead leg."""
    log_dir = os.path.join(repo, LOG_DIR_REL)
    all_ok = True
    for step in queue:
        if step.name in resume_from and not step.always_run:
            _say(f"step {step.name}: already completed in a prior "
                 "window — skipping")
            continue
        status_now = relay_status()
        mode_now = relay_mode(status_now)
        if mode_now is None:
            _say(f"relay went down before step {step.name} "
                 f"({status_now}) — stopping queue; will resume on "
                 "next window")
            return False
        if mode is not None and mode_now != mode:
            _say(f"relay mode shifted {mode} -> {mode_now} before step "
                 f"{step.name} — stopping so the queue is rebuilt with "
                 "the right compile-leg env")
            return False
        rec = run_step(repo, step, log_dir)
        rec["relay_at_start"] = status_now
        status = load_status(repo)
        status["steps"].append(rec)
        save_status(repo, status)
        arts = list(step.artifacts)
        for src, dest_rel in step.collect:
            dest = os.path.join(repo, dest_rel)
            try:
                if os.path.isdir(src):
                    if os.path.isdir(dest):
                        shutil.rmtree(dest)
                    shutil.copytree(src, dest)
                elif os.path.exists(src):
                    os.makedirs(os.path.dirname(dest), exist_ok=True)
                    shutil.copy2(src, dest)
                else:
                    _say(f"step {step.name}: collect source missing: {src}")
                    continue
                arts.append(dest_rel)
            except OSError as e:
                _say(f"step {step.name}: collect {src} failed: {e}")
        if step.stdout_to and rec["status"] == "ok":
            # only commit a record a failed/killed step couldn't have
            # truncated: the stdout file is pre-created before Popen,
            # so on failure it holds partial bytes — committing that
            # as the official bench JSON would poison every consumer
            # that globs for the newest record.
            try:
                with open(os.path.join(repo, step.stdout_to)) as f:
                    json.load(f)
                arts.append(step.stdout_to)
            except (OSError, ValueError) as e:
                _say(f"step {step.name}: stdout record not committed "
                     f"(unparseable: {e})")
                # Rename the torn file aside so record-globbing consumers
                # never pick it up, while keeping the bytes for forensics.
                bad = os.path.join(repo, step.stdout_to)
                try:
                    os.replace(bad, bad + ".partial")
                    _say(f"step {step.name}: moved aside as "
                         f"{step.stdout_to}.partial")
                except OSError:
                    pass
        arts.append(os.path.relpath(
            os.path.join(log_dir, f"{step.name}.log"), repo))
        arts.append(STATUS_REL)
        try:
            commit_paths(repo, arts,
                         f"chip({ROUND_TAG}): {step.name} {rec['status']} "
                         f"in {rec['wall_s']:.0f}s")
        except Exception as e:  # a commit hiccup must not lose the window
            _say(f"artifact commit failed (continuing): {e}")
        if rec["status"] != "ok":
            all_ok = False
            if rec["status"] == "timeout_killed" or step.abort_queue_on_fail:
                _say("aborting queue (relay presumed sick); remaining "
                     "steps stay queued for the next window")
                return False
    return all_ok


def attempt_window(repo: str) -> bool:
    """One recovery attempt: confirm the relay, guard single-client,
    run whatever of the queue is still incomplete."""
    status = relay_status()
    mode = relay_mode(status)
    if mode is None:
        _say(f"relay not usable: {status}")
        return False
    time.sleep(CONFIRM_S)
    status2 = relay_status()
    if relay_mode(status2) is None:
        _say(f"relay flapped during confirmation ({status} -> {status2}); "
             "not starting")
        return False
    mode = relay_mode(status2)
    clients = other_chip_clients(repo)
    if clients:
        _say(f"refusing to start: other chip client(s) alive: {clients}")
        return False
    status_led = load_status(repo)
    queue = build_queue(mode)
    # Health probes (always_run) are exempt from both completion skip
    # and give-up: they re-run every attempt, and their failure aborts
    # the attempt — so they can never be skipped into an unverified
    # client launch, nor retired while the rest of the queue pends.
    always = {s.name for s in queue if s.always_run}
    skip = (completed_steps(status_led) | given_up_steps(status_led)) - always
    remaining = [s.name for s in queue
                 if s.name not in skip and not s.always_run]
    if not remaining:
        _say("queue fully completed (or remaining steps given up) — "
             "nothing to do")
        return True
    _say(f"RELAY UP (mode={mode}) — running queue: "
         f"{sorted(always) + remaining}")
    return run_queue(repo, queue, resume_from=skip, mode=mode)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--watch", action="store_true",
                   help="daemon: poll the relay, run the queue on recovery")
    g.add_argument("--once", action="store_true",
                   help="health-check and run the (remaining) queue now")
    g.add_argument("--dry-run", action="store_true",
                   help="print the queue for both modes; execute nothing")
    ap.add_argument("--prewarm", action="store_true",
                    help="before watching (or before --once's attempt), "
                         "run tools/cache_warm.py once — offline "
                         "local-AOT compiles of every official program "
                         "into the persistent cache, no relay needed — "
                         "and commit its report; a fresh container "
                         "becomes driver-ready while the relay is still "
                         "down. Nonzero step status means a program "
                         "failed to COMPILE (warm-mode semantics), not "
                         "that the cache was merely cold")
    args = ap.parse_args()

    if args.dry_run:
        for mode in ("remote", "local_compile"):
            print(f"== mode {mode} ==")
            for s in build_queue(mode):
                env = " ".join(f"{k}={v}" for k, v in s.env.items())
                print(f"  {s.name:<12} timeout={s.timeout_s:>6.0f}s "
                      f"{env + ' ' if env else ''}{' '.join(s.argv)}")
                for a in s.artifacts + ([s.stdout_to] if s.stdout_to else []):
                    print(f"  {'':<12} artifact: {a}")
        return 0

    # Single-instance lock (watch + once share it: both can start
    # clients). flock, not O_EXCL+pid-file: the kernel releases it when
    # the holder dies (no stale-lock state), and acquisition is atomic
    # (no stale-recovery TOCTOU where two racers each unlink the
    # other's fresh lock and both run).
    import fcntl

    lock = os.environ.get("CHIP_AUTORUN_LOCK", "/tmp/chip_autorun.lock")
    lock_fd = os.open(lock, os.O_CREAT | os.O_WRONLY, 0o644)
    try:
        fcntl.flock(lock_fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        os.close(lock_fd)
        print("another chip_autorun holds the lock; exiting")
        return 1
    os.ftruncate(lock_fd, 0)
    os.write(lock_fd, str(os.getpid()).encode())  # diagnostics only
    try:
        if args.prewarm:
            # Cache-warm bootstrap (VERDICT r4 item 4): offline local-AOT
            # compiles of every official program — no relay involved, so
            # it can run right now; its report records hit/miss per
            # program, i.e. whether the container started driver-ready.
            # warm mode, not --check: exit 0 = cache ready (whether it
            # was already warm or was warmed just now); nonzero only
            # when a program cannot compile at all
            rec = run_step(
                REPO,
                Step("cache_prewarm",
                     [sys.executable, "tools/cache_warm.py"],
                     4 * 3600.0, env={"PALLAS_AXON_POOL_IPS": ""}),
                os.path.join(REPO, LOG_DIR_REL))
            status = load_status(REPO)
            status["steps"].append(rec)
            save_status(REPO, status)
            commit_paths(
                REPO,
                [os.path.join("docs", "cache_warm_report.json"),
                 os.path.join(LOG_DIR_REL, "cache_prewarm.log"), STATUS_REL],
                f"chip({ROUND_TAG}): cache prewarm {rec['status']} "
                f"in {rec['wall_s']:.0f}s")
        if args.once:
            ok = attempt_window(REPO)
            return 0 if ok else 1
        _say(f"watching relay ({POLL_S:.0f}s poll); queue tag {ROUND_TAG}")
        prev = None
        last_attempt = 0.0
        fails = 0
        while True:
            mode = relay_mode(relay_status())
            if mode != prev:
                _say(f"relay transition: {prev} -> {mode}")
            # Attempt on every transition to up, AND periodically while
            # the relay STAYS up with queue steps still incomplete — a
            # refused attempt (e.g. a manual chip client was running,
            # or a step aborted) must not idle away an hours-long
            # window just because the sockets never flapped.
            if mode is not None:
                led = load_status(REPO)
                skip = completed_steps(led) | given_up_steps(led)
                pending = [s.name for s in build_queue(mode)
                           if s.name not in skip and not s.always_run]
                # Back off while attempts keep failing against an
                # up-but-sick relay (each failed attempt may have cost
                # a client kill); any success resets the cadence.
                interval = min(RETRY_S * (2 ** fails), 7200.0)
                due = (mode != prev
                       or time.monotonic() - last_attempt >= interval)
                if pending and due:
                    last_attempt = time.monotonic()
                    fails = 0 if attempt_window(REPO) else fails + 1
            prev = mode
            time.sleep(POLL_S)
    finally:
        # flock releases with the fd (and automatically on death);
        # leave the file in place — it carries the last holder's pid
        try:
            os.close(lock_fd)
        except OSError:
            pass


if __name__ == "__main__":
    sys.exit(main())
