"""Scripted chaos drills for the resilience stack (cyclegan_tpu/resil).

    python tools/chaos_drill.py --fast      # tier-1 budget (CPU)
    python tools/chaos_drill.py             # full drill set

Fault injection (``--inject``) makes failure deterministic; this tool
makes RECOVERY an asserted invariant instead of a hope. Three drills,
one per recovery subsystem:

- **nan_rollback** — a real `python main.py` training run on synthetic
  data with ``--inject nan_grads@step=K --on_nan rollback``: the
  poisoned dispatch must trip the health monitor, the run must restore
  the newest verified checkpoint-ring slot, rewind, re-seed the data
  order, and still FINISH with exit 0, a ``health_recovery`` event, and
  zero non-finite faults after the recovery point. The full (non-fast)
  set adds the budget edge: the same fault under ``--max_rollbacks 0``
  must halt with exit 3 — rollback never hides persistent collapse.
- **fleet_crash** — an in-process FleetExecutor over a tiny real engine
  with ``replica_crash@flush=M``: the monitor must detect the dead
  replica, re-enqueue its in-flight requests, respawn the worker, and
  every submitted future must resolve (result or a typed shed/deadline
  error) — no hung futures, no unjoined replica threads at close.
- **ckpt_retry** — an in-process checkpoint ring with
  ``ckpt_io_error@epoch=N``: the injected I/O error must be absorbed by
  the bounded-backoff retry (``retry`` events in the stream), the slot
  must verify against its sha256 manifest, and restore must round-trip
  the state bit-exactly while the ring prunes to ``keep`` slots.

Output: one JSON line on stdout
(``{"metric": "cyclegan_chaos_drill", ..., "pass": bool}``), human
progress on stderr, exit 0 iff every drill passed. Wired into tier-1
via tests/test_resil.py and into hardware rounds via tools/chip_autorun.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _log(msg: str) -> None:
    print(f"chaos_drill: {msg}", file=sys.stderr, flush=True)


class _Recorder:
    """Minimal telemetry double for the in-process drills: records
    every event so the drill can assert on the stream the real
    MetricsLogger would have written. Thread-safe (fleet replica and
    monitor threads emit concurrently)."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self.events = []

    def event(self, kind: str, /, **fields) -> None:
        with self._lock:
            self.events.append(dict(fields, event=kind))

    def kinds(self):
        with self._lock:
            return [e["event"] for e in self.events]

    def of(self, kind: str):
        with self._lock:
            return [e for e in self.events if e["event"] == kind]

    def flush(self) -> None:
        pass

    def close(self, status: str = "completed") -> None:
        pass


# --------------------------------------------------------------- drill (a)

def _main_argv(out: str, *, epochs: int, extra) -> list:
    return [
        sys.executable, "main.py",
        "--output_dir", out,
        "--data_source", "synthetic", "--image_size", "32",
        "--filters", "8", "--residual_blocks", "1",
        "--epochs", str(epochs), "--batch_size", "2",
        "--synthetic_train_size", "8", "--synthetic_test_size", "2",
        "--verbose", "0",
    ] + list(extra)


def _run_main(out: str, *, epochs: int, extra, timeout: float):
    env = dict(os.environ, PYTHONPATH=REPO)
    # The drill harness may run under the test suite's virtual-device
    # XLA_FLAGS; the child is a plain single-host run.
    env.pop("XLA_FLAGS", None)
    os.makedirs(out, exist_ok=True)
    return subprocess.run(
        _main_argv(out, epochs=epochs, extra=extra), cwd=REPO, env=env,
        capture_output=True, text=True, timeout=timeout)


def _read_events(out: str) -> list:
    path = os.path.join(out, "telemetry.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(l) for l in f if l.strip()]


def drill_nan_rollback(workdir: str, fast: bool) -> dict:
    """Poisoned dispatch at step K under --on_nan rollback: the run must
    recover from the verified ring slot and complete clean."""
    checks = {}
    epochs = 2 if fast else 3
    out = os.path.join(workdir, "nan_rollback")
    # 4 steps/epoch (8 images / batch 2): step 6 poisons epoch 1, after
    # epoch 0's ring slot (checkpoint_every default) has landed.
    # data_stall@step=1 rides along to exercise the retrying data
    # iterator inside the same run.
    r = _run_main(
        out, epochs=epochs, timeout=900.0,
        extra=["--on_nan", "rollback", "--max_rollbacks", "2",
               "--ckpt_keep", "2",
               "--inject", "nan_grads@step=6,data_stall@step=1"])
    evs = _read_events(out)
    kinds = [e.get("event") for e in evs]
    checks["exit_0"] = r.returncode == 0
    checks["fault_injected_nan"] = any(
        e.get("event") == "fault_injected" and e.get("kind") == "nan_grads"
        for e in evs)
    checks["health_fault_rollback_policy"] = any(
        e.get("event") == "health_fault" and e.get("policy") == "rollback"
        for e in evs)
    checks["health_recovery"] = "health_recovery" in kinds
    checks["data_retry_event"] = any(
        e.get("event") == "retry" and e.get("site") == "data" for e in evs)
    recs = [i for i, k in enumerate(kinds) if k == "health_recovery"]
    if recs:
        rec = evs[recs[-1]]
        checks["rewound"] = (rec.get("resume_epoch", 99) <=
                             rec.get("epoch_faulted", -1))
        # THE recovery invariant: after the rollback, training is clean
        # — no non-finite fault ever fires again.
        checks["clean_after_recovery"] = not any(
            e.get("event") == "health_fault" for e in evs[recs[-1] + 1:])
    else:
        checks["rewound"] = checks["clean_after_recovery"] = False
    checks["completed"] = bool(evs) and evs[-1].get("event") == "end" \
        and evs[-1].get("status") == "completed"
    detail = {
        "checks": checks,
        "returncode": r.returncode,
        "n_recoveries": len(recs),
        "n_events": len(evs),
    }
    if not all(checks.values()):
        detail["stdout_tail"] = r.stdout[-2000:]
        detail["stderr_tail"] = r.stderr[-2000:]

    if not fast:
        # Budget edge: identical fault, zero rollback budget -> the
        # HealthFault must propagate (exit 3), not be silently eaten.
        out0 = os.path.join(workdir, "nan_budget0")
        r0 = _run_main(
            out0, epochs=2, timeout=900.0,
            extra=["--on_nan", "rollback", "--max_rollbacks", "0",
                   "--ckpt_keep", "2", "--inject", "nan_grads@step=6"])
        evs0 = _read_events(out0)
        checks["budget0_exit_3"] = r0.returncode == 3
        checks["budget0_status_health_fault"] = bool(evs0) and \
            evs0[-1].get("status") == "health_fault"
        detail["budget0_returncode"] = r0.returncode

    return {"pass": all(checks.values()), "detail": detail}


# --------------------------------------------------------------- drill (b)

def drill_fleet_crash(n_requests: int = 24) -> dict:
    """replica_crash mid-flush: every future resolves, throughput
    resumes on the respawned worker, close() joins every thread."""
    import concurrent.futures as cf

    import jax
    import jax.numpy as jnp
    import numpy as np

    from cyclegan_tpu.config import GeneratorConfig, ModelConfig
    from cyclegan_tpu.resil import FaultInjector
    from cyclegan_tpu.serve.engine import (
        InferenceEngine,
        ServeConfig,
        build_generator,
    )
    from cyclegan_tpu.serve.fleet import (
        DeadlineExceeded,
        FleetConfig,
        FleetExecutor,
        ReplicaCrashed,
        ShedError,
    )

    checks = {}
    cfg = ModelConfig(
        generator=GeneratorConfig(filters=4, num_residual_blocks=1),
        image_size=16, compute_dtype="float32")
    gen = build_generator(cfg)
    params = gen.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 16, 16, 3), jnp.float32))
    engine = InferenceEngine(
        cfg, params,
        serve_cfg=ServeConfig(batch_buckets=(2,), sizes=(16,)))
    rec = _Recorder()
    injector = FaultInjector.from_spec("replica_crash@flush=2",
                                       telemetry=rec)
    ex = FleetExecutor(
        engine,
        FleetConfig(n_replicas=2, max_wait_ms=2.0, health_poll_s=0.02),
        logger=rec, injector=injector)
    rng = np.random.RandomState(0)
    ok = failed = 0

    def drain(futs):
        nonlocal ok, failed
        done, not_done = cf.wait(futs, timeout=120.0)
        for f in done:
            err = f.exception()
            if err is None:
                ok += 1
            elif isinstance(err, (ShedError, DeadlineExceeded,
                                  ReplicaCrashed)):
                failed += 1
            else:
                checks["typed_failures_only"] = False
        return len(not_done) == 0

    try:
        futs = [ex.submit(rng.rand(16, 16, 3).astype(np.float32),
                          klass="batch")
                for _ in range(n_requests)]
        checks["no_hung_futures"] = drain(futs)
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline and \
                "fleet_recovery" not in rec.kinds():
            time.sleep(0.02)
        checks["replica_down_event"] = "fleet_replica_down" in rec.kinds()
        checks["recovery_event"] = "fleet_recovery" in rec.kinds()
        # Throughput recovered: a SECOND wave submitted after the
        # recovery event must be served by the healed fleet.
        wave2 = [ex.submit(rng.rand(16, 16, 3).astype(np.float32),
                           klass="batch")
                 for _ in range(max(4, n_requests // 3))]
        checks["post_recovery_wave_drains"] = drain(wave2)
        checks.setdefault("typed_failures_only", True)
        # The crash strands at most one flush; with attempts < cap the
        # re-enqueued requests should actually SUCCEED, so nearly
        # everything completes with a result.
        checks["most_requests_served"] = ok >= len(futs) + len(wave2) - 2
        stats = ex.stats()
        checks["recovery_counted"] = stats.get("recoveries", 0) >= 1
        checks["no_circuit_open"] = stats.get("circuits_open", 1) == 0
    finally:
        summary = ex.close()
    checks["all_replicas_joined"] = summary.get("unjoined_replicas") == []
    return {
        "pass": all(checks.values()),
        "detail": {
            "checks": checks,
            "served": ok,
            "typed_failures": failed,
            "recoveries": summary.get("recoveries"),
            "requeued": summary.get("requeued_requests"),
            "flushes_per_replica": [r.n_flushes for r in ex.replicas],
        },
    }


# --------------------------------------------------------------- drill (c)

def drill_ckpt_retry(workdir: str) -> dict:
    """ckpt_io_error on the save path: absorbed by bounded backoff
    (retry events), slot verifies, restore round-trips, ring prunes."""
    import numpy as np

    from cyclegan_tpu.resil import FaultInjector
    from cyclegan_tpu.utils.checkpoint import Checkpointer

    checks = {}
    rec = _Recorder()
    injector = FaultInjector.from_spec("ckpt_io_error@epoch=0",
                                       telemetry=rec)
    out = os.path.join(workdir, "ckpt_retry")
    ckpt = Checkpointer(out, keep=2, telemetry=rec, injector=injector)
    states = {
        e: {"w": np.full((8,), float(e), np.float32),
            "b": np.arange(4, dtype=np.float32) + e}
        for e in range(3)
    }
    for e in range(3):
        ckpt.save(states[e], epoch=e, meta={"drill": True})
    checks["io_error_injected"] = any(
        ev.get("kind") == "ckpt_io_error" for ev in rec.of("fault_injected"))
    retries = [ev for ev in rec.of("retry") if ev.get("site") == "ckpt"]
    checks["retry_events"] = len(retries) >= 1
    checks["backoff_bounded"] = all(
        0.0 <= ev.get("delay_s", -1.0) <= 2.0 for ev in retries)
    checks["ring_pruned_to_keep"] = len(ckpt.slots()) == 2
    ok, det = ckpt.verify()
    checks["newest_slot_verified"] = ok
    template = {"w": np.zeros((8,), np.float32),
                "b": np.zeros((4,), np.float32)}
    state, next_epoch = ckpt.restore(template)
    checks["resume_epoch"] = next_epoch == 3
    checks["roundtrip_exact"] = (
        np.array_equal(np.asarray(state["w"]), states[2]["w"])
        and np.array_equal(np.asarray(state["b"]), states[2]["b"]))
    return {
        "pass": all(checks.values()),
        "detail": {
            "checks": checks,
            "n_retry_events": len(retries),
            "verify": det,
            "slots": [os.path.basename(s) for _, s in ckpt.slots()],
        },
    }


# ------------------------------------------------------------------ driver

def run_drills(workdir: str, fast: bool, only=None) -> dict:
    import jax

    drills = {}
    t0 = time.perf_counter()
    plan = [
        ("nan_rollback", lambda: drill_nan_rollback(workdir, fast)),
        ("fleet_crash", lambda: drill_fleet_crash(12 if fast else 24)),
        ("ckpt_retry", lambda: drill_ckpt_retry(workdir)),
    ]
    for name, fn in plan:
        if only and name not in only:
            continue
        _log(f"drill {name} ...")
        t = time.perf_counter()
        try:
            res = fn()
        except Exception as e:  # noqa: BLE001 — a crashed drill is a FAIL, not a traceback-only exit
            import traceback

            res = {"pass": False,
                   "detail": {"error": f"{type(e).__name__}: {e}",
                              "traceback": traceback.format_exc()[-2000:]}}
        res["elapsed_s"] = round(time.perf_counter() - t, 2)
        drills[name] = res
        _log(f"drill {name}: {'PASS' if res['pass'] else 'FAIL'} "
             f"({res['elapsed_s']}s)")
    return {
        "metric": "cyclegan_chaos_drill",
        "fast": bool(fast),
        "platform": jax.default_backend(),
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "drills": drills,
        "pass": bool(drills) and all(d["pass"] for d in drills.values()),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--fast", action="store_true",
                   help="tier-1 budget: shorter training run, smaller "
                        "fleet load, skip the rollback-budget edge case")
    p.add_argument("--only", action="append", default=None,
                   choices=["nan_rollback", "fleet_crash", "ckpt_retry"],
                   help="run a subset (repeatable)")
    p.add_argument("--workdir", default=None,
                   help="scratch dir (default: a fresh temp dir)")
    args = p.parse_args(argv)
    import tempfile

    if args.workdir:
        os.makedirs(args.workdir, exist_ok=True)
        report = run_drills(args.workdir, args.fast, args.only)
    else:
        with tempfile.TemporaryDirectory(prefix="chaos_drill_") as wd:
            report = run_drills(wd, args.fast, args.only)
    print(json.dumps(report))
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
