"""Scripted chaos drills for the resilience stack (cyclegan_tpu/resil).

    python tools/chaos_drill.py --fast      # tier-1 budget (CPU)
    python tools/chaos_drill.py             # full drill set

Fault injection (``--inject``) makes failure deterministic; this tool
makes RECOVERY an asserted invariant instead of a hope. Five drills,
one per recovery subsystem:

- **nan_rollback** — a real `python main.py` training run on synthetic
  data with ``--inject nan_grads@step=K --on_nan rollback``: the
  poisoned dispatch must trip the health monitor, the run must restore
  the newest verified checkpoint-ring slot, rewind, re-seed the data
  order, and still FINISH with exit 0, a ``health_recovery`` event, and
  zero non-finite faults after the recovery point. The full (non-fast)
  set adds the budget edge: the same fault under ``--max_rollbacks 0``
  must halt with exit 3 — rollback never hides persistent collapse.
- **fleet_crash** — an in-process FleetExecutor over a tiny real engine
  with ``replica_crash@flush=M``: the monitor must detect the dead
  replica, re-enqueue its in-flight requests, respawn the worker, and
  every submitted future must resolve (result or a typed shed/deadline
  error) — no hung futures, no unjoined replica threads at close.
- **ckpt_retry** — an in-process checkpoint ring with
  ``ckpt_io_error@epoch=N``: the injected I/O error must be absorbed by
  the bounded-backoff retry (``retry`` events in the stream), the slot
  must verify against its sha256 manifest, and restore must round-trip
  the state bit-exactly while the ring prunes to ``keep`` slots.
- **elastic_resume** — the cross-mesh equivalence drill: a run on an
  8-way data mesh is preempted MID-epoch (``preempt@step=K`` +
  ``--preempt_deadline_s``), must land its emergency save inside the
  deadline budget, then resume in the same output dir on a different
  4x2 data-by-spatial mesh. The resumed run's per-step losses must
  match an uninterrupted control run across the preemption seam
  (<= 1e-5 elementwise, f32), with zero samples skipped or repeated
  and final test metrics equal to the control's. The full set adds the
  deadline-overrun edge: an impossibly small budget must trip the
  armed kill timer (exit 124) rather than hang in the save.
- **overload_brownout** — the self-driving-fleet drill: an in-process
  autoscaling FleetExecutor (base+int8 tiers, brownout cascade, hedged
  dispatch) is hit with mixed-class traffic at ~2x its single-replica
  drain capacity. The fleet must scale UP within the
  hysteresis+cooldown bound, the brownout must engage (degraded
  requests served cheaper) BEFORE any shed, `interactive` must see
  zero sheds and an in-deadline p95 throughout, and after the surge
  decays the fleet must drain-and-retire back down to min_replicas.

Output: one JSON line on stdout
(``{"metric": "cyclegan_chaos_drill", ..., "pass": bool}``), human
progress on stderr, exit 0 iff every drill passed. Wired into tier-1
via tests/test_resil.py and into hardware rounds via tools/chip_autorun.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _log(msg: str) -> None:
    print(f"chaos_drill: {msg}", file=sys.stderr, flush=True)


class _Recorder:
    """Minimal telemetry double for the in-process drills: records
    every event so the drill can assert on the stream the real
    MetricsLogger would have written. Thread-safe (fleet replica and
    monitor threads emit concurrently)."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self.events = []

    def event(self, kind: str, /, **fields) -> None:
        with self._lock:
            self.events.append(dict(fields, event=kind))

    def kinds(self):
        with self._lock:
            return [e["event"] for e in self.events]

    def of(self, kind: str):
        with self._lock:
            return [e for e in self.events if e["event"] == kind]

    def flush(self) -> None:
        pass

    def close(self, status: str = "completed") -> None:
        pass


# --------------------------------------------------------------- drill (a)

def _main_argv(out: str, *, epochs: int, extra, image: int = 32,
               filters: int = 8, batch: int = 2, train: int = 8,
               test: int = 2) -> list:
    return [
        sys.executable, "main.py",
        "--output_dir", out,
        "--data_source", "synthetic", "--image_size", str(image),
        "--filters", str(filters), "--residual_blocks", "1",
        "--epochs", str(epochs), "--batch_size", str(batch),
        "--synthetic_train_size", str(train),
        "--synthetic_test_size", str(test),
        "--verbose", "0",
    ] + list(extra)


def _run_main(out: str, *, epochs: int, extra, timeout: float,
              env_extra=None, **shape):
    env = dict(os.environ, PYTHONPATH=REPO)
    # The drill harness may run under the test suite's virtual-device
    # XLA_FLAGS; the child is a plain single-host run unless the drill
    # pins its own topology via env_extra (applied after the pop).
    env.pop("XLA_FLAGS", None)
    if env_extra:
        env.update(env_extra)
    os.makedirs(out, exist_ok=True)
    return subprocess.run(
        _main_argv(out, epochs=epochs, extra=extra, **shape), cwd=REPO,
        env=env, capture_output=True, text=True, timeout=timeout)


def _read_events(out: str) -> list:
    path = os.path.join(out, "telemetry.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(l) for l in f if l.strip()]


def drill_nan_rollback(workdir: str, fast: bool) -> dict:
    """Poisoned dispatch at step K under --on_nan rollback: the run must
    recover from the verified ring slot and complete clean."""
    checks = {}
    epochs = 2 if fast else 3
    out = os.path.join(workdir, "nan_rollback")
    # 4 steps/epoch (8 images / batch 2): step 6 poisons epoch 1, after
    # epoch 0's ring slot (checkpoint_every default) has landed.
    # data_stall@step=1 rides along to exercise the retrying data
    # iterator inside the same run.
    r = _run_main(
        out, epochs=epochs, timeout=900.0,
        extra=["--on_nan", "rollback", "--max_rollbacks", "2",
               "--ckpt_keep", "2",
               "--inject", "nan_grads@step=6,data_stall@step=1"])
    evs = _read_events(out)
    kinds = [e.get("event") for e in evs]
    checks["exit_0"] = r.returncode == 0
    checks["fault_injected_nan"] = any(
        e.get("event") == "fault_injected" and e.get("kind") == "nan_grads"
        for e in evs)
    checks["health_fault_rollback_policy"] = any(
        e.get("event") == "health_fault" and e.get("policy") == "rollback"
        for e in evs)
    checks["health_recovery"] = "health_recovery" in kinds
    checks["data_retry_event"] = any(
        e.get("event") == "retry" and e.get("site") == "data" for e in evs)
    recs = [i for i, k in enumerate(kinds) if k == "health_recovery"]
    if recs:
        rec = evs[recs[-1]]
        checks["rewound"] = (rec.get("resume_epoch", 99) <=
                             rec.get("epoch_faulted", -1))
        # THE recovery invariant: after the rollback, training is clean
        # — no non-finite fault ever fires again.
        checks["clean_after_recovery"] = not any(
            e.get("event") == "health_fault" for e in evs[recs[-1] + 1:])
    else:
        checks["rewound"] = checks["clean_after_recovery"] = False
    checks["completed"] = bool(evs) and evs[-1].get("event") == "end" \
        and evs[-1].get("status") == "completed"
    detail = {
        "checks": checks,
        "returncode": r.returncode,
        "n_recoveries": len(recs),
        "n_events": len(evs),
    }
    if not all(checks.values()):
        detail["stdout_tail"] = r.stdout[-2000:]
        detail["stderr_tail"] = r.stderr[-2000:]

    if not fast:
        # Budget edge: identical fault, zero rollback budget -> the
        # HealthFault must propagate (exit 3), not be silently eaten.
        out0 = os.path.join(workdir, "nan_budget0")
        r0 = _run_main(
            out0, epochs=2, timeout=900.0,
            extra=["--on_nan", "rollback", "--max_rollbacks", "0",
                   "--ckpt_keep", "2", "--inject", "nan_grads@step=6"])
        evs0 = _read_events(out0)
        checks["budget0_exit_3"] = r0.returncode == 3
        checks["budget0_status_health_fault"] = bool(evs0) and \
            evs0[-1].get("status") == "health_fault"
        detail["budget0_returncode"] = r0.returncode

    return {"pass": all(checks.values()), "detail": detail}


# --------------------------------------------------------------- drill (b)

def drill_fleet_crash(n_requests: int = 24) -> dict:
    """replica_crash mid-flush: every future resolves, throughput
    resumes on the respawned worker, close() joins every thread."""
    import concurrent.futures as cf

    import jax
    import jax.numpy as jnp
    import numpy as np

    from cyclegan_tpu.config import GeneratorConfig, ModelConfig
    from cyclegan_tpu.resil import FaultInjector
    from cyclegan_tpu.serve.engine import (
        InferenceEngine,
        ServeConfig,
        build_generator,
    )
    from cyclegan_tpu.serve.fleet import (
        DeadlineExceeded,
        FleetConfig,
        FleetExecutor,
        ReplicaCrashed,
        ShedError,
    )

    checks = {}
    cfg = ModelConfig(
        generator=GeneratorConfig(filters=4, num_residual_blocks=1),
        image_size=16, compute_dtype="float32")
    gen = build_generator(cfg)
    params = gen.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 16, 16, 3), jnp.float32))
    engine = InferenceEngine(
        cfg, params,
        serve_cfg=ServeConfig(batch_buckets=(2,), sizes=(16,)))
    rec = _Recorder()
    injector = FaultInjector.from_spec("replica_crash@flush=2",
                                       telemetry=rec)
    ex = FleetExecutor(
        engine,
        FleetConfig(n_replicas=2, max_wait_ms=2.0, health_poll_s=0.02),
        logger=rec, injector=injector)
    rng = np.random.RandomState(0)
    ok = failed = 0

    def drain(futs):
        nonlocal ok, failed
        done, not_done = cf.wait(futs, timeout=120.0)
        for f in done:
            err = f.exception()
            if err is None:
                ok += 1
            elif isinstance(err, (ShedError, DeadlineExceeded,
                                  ReplicaCrashed)):
                failed += 1
            else:
                checks["typed_failures_only"] = False
        return len(not_done) == 0

    try:
        futs = [ex.submit(rng.rand(16, 16, 3).astype(np.float32),
                          klass="batch")
                for _ in range(n_requests)]
        checks["no_hung_futures"] = drain(futs)
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline and \
                "fleet_recovery" not in rec.kinds():
            time.sleep(0.02)
        checks["replica_down_event"] = "fleet_replica_down" in rec.kinds()
        checks["recovery_event"] = "fleet_recovery" in rec.kinds()
        # Throughput recovered: a SECOND wave submitted after the
        # recovery event must be served by the healed fleet.
        wave2 = [ex.submit(rng.rand(16, 16, 3).astype(np.float32),
                           klass="batch")
                 for _ in range(max(4, n_requests // 3))]
        checks["post_recovery_wave_drains"] = drain(wave2)
        checks.setdefault("typed_failures_only", True)
        # The crash strands at most one flush; with attempts < cap the
        # re-enqueued requests should actually SUCCEED, so nearly
        # everything completes with a result.
        checks["most_requests_served"] = ok >= len(futs) + len(wave2) - 2
        stats = ex.stats()
        checks["recovery_counted"] = stats.get("recoveries", 0) >= 1
        checks["no_circuit_open"] = stats.get("circuits_open", 1) == 0
    finally:
        summary = ex.close()
    checks["all_replicas_joined"] = summary.get("unjoined_replicas") == []
    return {
        "pass": all(checks.values()),
        "detail": {
            "checks": checks,
            "served": ok,
            "typed_failures": failed,
            "recoveries": summary.get("recoveries"),
            "requeued": summary.get("requeued_requests"),
            "flushes_per_replica": [r.n_flushes for r in ex.replicas],
        },
    }


# --------------------------------------------------------------- drill (c)

def drill_ckpt_retry(workdir: str) -> dict:
    """ckpt_io_error on the save path: absorbed by bounded backoff
    (retry events), slot verifies, restore round-trips, ring prunes."""
    import numpy as np

    from cyclegan_tpu.resil import FaultInjector
    from cyclegan_tpu.utils.checkpoint import Checkpointer

    checks = {}
    rec = _Recorder()
    injector = FaultInjector.from_spec("ckpt_io_error@epoch=0",
                                       telemetry=rec)
    out = os.path.join(workdir, "ckpt_retry")
    ckpt = Checkpointer(out, keep=2, telemetry=rec, injector=injector)
    states = {
        e: {"w": np.full((8,), float(e), np.float32),
            "b": np.arange(4, dtype=np.float32) + e}
        for e in range(3)
    }
    for e in range(3):
        ckpt.save(states[e], epoch=e, meta={"drill": True})
    checks["io_error_injected"] = any(
        ev.get("kind") == "ckpt_io_error" for ev in rec.of("fault_injected"))
    retries = [ev for ev in rec.of("retry") if ev.get("site") == "ckpt"]
    checks["retry_events"] = len(retries) >= 1
    checks["backoff_bounded"] = all(
        0.0 <= ev.get("delay_s", -1.0) <= 2.0 for ev in retries)
    checks["ring_pruned_to_keep"] = len(ckpt.slots()) == 2
    ok, det = ckpt.verify()
    checks["newest_slot_verified"] = ok
    template = {"w": np.zeros((8,), np.float32),
                "b": np.zeros((4,), np.float32)}
    state, next_epoch = ckpt.restore(template)
    checks["resume_epoch"] = next_epoch == 3
    checks["roundtrip_exact"] = (
        np.array_equal(np.asarray(state["w"]), states[2]["w"])
        and np.array_equal(np.asarray(state["b"]), states[2]["b"]))
    return {
        "pass": all(checks.values()),
        "detail": {
            "checks": checks,
            "n_retry_events": len(retries),
            "verify": det,
            "slots": [os.path.basename(s) for _, s in ckpt.slots()],
        },
    }


# --------------------------------------------------------------- drill (d)

# Fixed topologies for the cross-mesh drill: preempt on an 8-way data
# mesh, resume on 4 data x 2 spatial. Both run on 8 virtual CPU
# devices so the drill is hardware-independent.
_ELASTIC_ENV = {"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
# image 16 / filters 4 / batch 1 / 32 train images on dp8 -> global
# batch 8, 4 steps per epoch; the dp4xsp2 resume recomputes the
# per-shard batch to 2 so the global batch (and data order) is
# unchanged.
_ELASTIC_SHAPE = dict(image=16, filters=4, batch=1, train=32, test=4)


def _losses_of(events, epoch):
    return [e for e in events
            if e.get("event") == "step_losses" and e.get("epoch") == epoch]


def drill_elastic_resume(workdir: str, fast: bool) -> dict:
    """Mid-epoch preempt on mesh A, resume on mesh B: per-step losses
    must match the uninterrupted control across the seam, no sample
    skipped or repeated, emergency save inside the deadline budget."""
    checks = {}
    tol = 1e-5
    common = ["--fid_every", "0"]
    out_ctl = os.path.join(workdir, "elastic_ctl")
    out_run = os.path.join(workdir, "elastic_run")

    # Control: uninterrupted 2-epoch run on the 8-way data mesh.
    rc = _run_main(out_ctl, epochs=2, timeout=900.0, extra=common,
                   env_extra=_ELASTIC_ENV, **_ELASTIC_SHAPE)
    checks["control_exit_0"] = rc.returncode == 0
    ctl_evs = _read_events(out_ctl)

    # Run 1: identical config, SIGTERM injected at dispatch 5 (epoch 1,
    # step 1). The dispatch in flight completes, the breaker latches,
    # and the emergency save lands at epoch 1 step 2 with the data seed
    # in the slot manifest.
    r1 = _run_main(out_run, epochs=2, timeout=900.0,
                   extra=common + ["--inject", "preempt@step=5",
                                   "--preempt_deadline_s", "30"],
                   env_extra=_ELASTIC_ENV, **_ELASTIC_SHAPE)
    evs1 = _read_events(out_run)
    checks["preempt_exit_0"] = r1.returncode == 0
    checks["fault_injected_preempt"] = any(
        e.get("event") == "fault_injected" and e.get("kind") == "preempt"
        for e in evs1)
    ems = [e for e in evs1 if e.get("event") == "emergency_save"]
    checks["emergency_save_committed"] = any(
        e.get("committed") for e in ems)
    checks["save_within_deadline"] = bool(ems) and all(
        e.get("margin_s", -1.0) >= 0.0 for e in ems)
    checks["status_preempted"] = bool(evs1) and \
        evs1[-1].get("event") == "end" and \
        evs1[-1].get("status") == "preempted"

    # Run 2: same output dir, different topology (4 data x 2 spatial).
    # Preflight recomputes the per-shard batch, restore reshards every
    # leaf, and the data pipeline fast-forwards to the saved position.
    r2 = _run_main(out_run, epochs=2, timeout=900.0,
                   extra=common + ["--spatial_parallelism", "2"],
                   env_extra=_ELASTIC_ENV, **_ELASTIC_SHAPE)
    all_evs = _read_events(out_run)
    evs2 = all_evs[len(evs1):]  # telemetry.jsonl appends across runs
    checks["resume_exit_0"] = r2.returncode == 0
    resh = [e for e in evs2 if e.get("event") == "elastic_reshard"]
    checks["resharded"] = bool(resh) and resh[-1].get("n_leaves", 0) > 0
    checks["status_completed"] = bool(evs2) and \
        evs2[-1].get("event") == "end" and \
        evs2[-1].get("status") == "completed"
    checks["no_health_faults"] = not any(
        e.get("event") == "health_fault" for e in evs2)

    # The equivalence seam: control epoch-1 losses [0:k) must match run
    # 1's partial epoch, [k:] must match run 2's resumed tail — same
    # steps, same samples, same numbers.
    ctl_sl = _losses_of(ctl_evs, 1)
    pre_sl = _losses_of(evs1, 1)
    post_sl = _losses_of(evs2, 1)
    seam_maxdiff = None
    if ctl_sl and pre_sl and post_sl:
        ctl_e, pre_e, post_e = ctl_sl[0], pre_sl[0], post_sl[0]
        k = int(pre_e["n_steps"])
        checks["resume_at_seam"] = (
            int(post_e["start_step"]) == k
            and k + int(post_e["n_steps"]) == int(ctl_e["n_steps"]))
        checks["save_step_is_seam"] = bool(ems) and \
            int(ems[-1].get("step", -1)) == k
        keys = [key for key in ctl_e if key.startswith("loss_")]
        diffs = []
        for key in keys:
            diffs += [abs(a - b)
                      for a, b in zip(ctl_e[key][:k], pre_e[key])]
            diffs += [abs(a - b)
                      for a, b in zip(ctl_e[key][k:], post_e[key])]
        seam_maxdiff = max(diffs) if diffs else None
        checks["losses_match_control"] = bool(diffs) and seam_maxdiff <= tol
    else:
        checks["resume_at_seam"] = checks["save_step_is_seam"] = False
        checks["losses_match_control"] = False

    # End state equivalence: final-epoch test metrics. These aggregate
    # over the whole test set, and a 4x2 mesh sums partial reductions in
    # a different order than 8x1, so the contract is isclose semantics
    # (rtol+atol), not the per-step absolute bound.
    def _final_metrics(events):
        eps = [e for e in events
               if e.get("event") == "epoch" and e.get("epoch") == 1]
        return eps[-1].get("test_metrics") if eps else None

    cm, rm = _final_metrics(ctl_evs), _final_metrics(evs2)
    if isinstance(cm, dict) and isinstance(rm, dict):
        checks["final_metrics_match"] = set(cm) == set(rm) and all(
            abs(float(cm[key]) - float(rm[key]))
            <= tol + tol * abs(float(cm[key]))
            for key in cm)
    else:
        checks["final_metrics_match"] = False

    detail = {
        "checks": checks,
        "returncodes": [rc.returncode, r1.returncode, r2.returncode],
        "seam_maxdiff": seam_maxdiff,
        "emergency": [{k: v for k, v in e.items() if k != "t"}
                      for e in ems],
        "resharded_leaves": resh[-1].get("n_leaves") if resh else None,
    }
    if not all(checks.values()):
        for name, r in (("control", rc), ("preempt", r1), ("resume", r2)):
            detail[f"{name}_stderr_tail"] = r.stderr[-1500:]

    if not fast:
        # Deadline-overrun edge: a 20ms budget cannot fit the save, so
        # the kill timer armed at SIGTERM must fire os._exit(124)
        # instead of letting the run overstay its preemption notice.
        out_kill = os.path.join(workdir, "elastic_overrun")
        rk = _run_main(out_kill, epochs=2, timeout=900.0,
                       extra=common + ["--inject", "preempt@step=5",
                                       "--preempt_deadline_s", "0.02"],
                       env_extra=_ELASTIC_ENV, **_ELASTIC_SHAPE)
        checks["overrun_killed_124"] = rk.returncode == 124
        detail["overrun_returncode"] = rk.returncode

    return {"pass": all(checks.values()), "detail": detail}


# --------------------------------------------------------------- drill (e)

def drill_overload_brownout(fast: bool) -> dict:
    """Mixed-class traffic at ~2x measured drain capacity against an
    autoscaling, brownout-enabled fleet: scale-up inside the
    hysteresis+cooldown bound, degrade-before-shed ordering, zero
    interactive sheds with an in-deadline p95, scale back down after
    the surge decays."""
    import concurrent.futures as cf

    import jax
    import jax.numpy as jnp
    import numpy as np

    from cyclegan_tpu.config import GeneratorConfig, ModelConfig
    from cyclegan_tpu.serve.engine import (
        InferenceEngine,
        ServeConfig,
        build_generator,
    )
    from cyclegan_tpu.serve.fleet import (
        AutoscaleConfig,
        CascadeConfig,
        DeadlineExceeded,
        FleetConfig,
        FleetExecutor,
        ReplicaCrashed,
        ShedError,
    )

    checks = {}
    cfg = ModelConfig(
        generator=GeneratorConfig(filters=4, num_residual_blocks=1),
        image_size=16, compute_dtype="float32")
    gen = build_generator(cfg)
    params = gen.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 16, 16, 3), jnp.float32))
    engine = InferenceEngine(
        cfg, params,
        serve_cfg=ServeConfig(batch_buckets=(1, 2), sizes=(16,),
                              int8_tier=True, infer_tier=True))
    rec = _Recorder()
    # Capacity must leave backlog headroom ABOVE the autoscale trigger
    # (capacity/drain > up_backlog_s), or the queue saturates and sheds
    # while the backlog signal never crosses the scale-up threshold.
    auto = AutoscaleConfig(min_replicas=1, max_replicas=3, eval_s=0.05,
                           hysteresis=2, cooldown_s=0.4,
                           up_backlog_s=0.1)
    casc = CascadeConfig(tiers=("base", "int8", "int8_fused"),
                         enter_backlog_s=0.05,
                         exit_backlog_s=0.02, hysteresis=2,
                         cooldown_s=0.1, shadow_fraction=0.1)
    ex = FleetExecutor(
        engine,
        FleetConfig(n_replicas=1, capacity=256, max_wait_ms=2.0,
                    health_poll_s=0.02, autoscale=auto, cascade=casc,
                    hedge_ms=500.0),
        logger=rec)
    rng = np.random.RandomState(0)
    img = rng.rand(16, 16, 3).astype(np.float32)
    # Deterministic 2/3/5 interactive/batch/best_effort mix.
    mix = (["interactive"] * 2 + ["batch"] * 3 + ["best_effort"] * 5)
    futs = []
    ok = shed = expired = 0

    def _submit(klass):
        nonlocal shed
        try:
            futs.append(ex.submit(img.copy(), klass=klass))
        except ShedError:
            shed += 1

    try:
        # Calibrate: closed-loop wave to measure single-replica drain.
        warm = [ex.submit(img.copy(), klass="batch") for _ in range(8)]
        cf.wait(warm, timeout=60.0)
        t0 = time.perf_counter()
        warm2 = [ex.submit(img.copy(), klass="batch") for _ in range(24)]
        cf.wait(warm2, timeout=60.0)
        drain = 24.0 / max(time.perf_counter() - t0, 1e-3)
        futs.extend(warm + warm2)
        # Surge: open-loop at ~2x the measured drain, in 5 ms ticks.
        surge_s = 2.5 if fast else 6.0
        tick_s = 0.005
        per_tick = max(1, int(round(2.0 * drain * tick_s)))
        t_surge = time.perf_counter()
        t_up = None
        i = 0
        while time.perf_counter() - t_surge < surge_s:
            for _ in range(per_tick):
                _submit(mix[i % len(mix)])
                i += 1
            if t_up is None and any(
                    e.get("phase") == "up"
                    for e in rec.of("fleet_autoscale")):
                t_up = time.perf_counter() - t_surge
            time.sleep(tick_s)
        # Scale-up must land within the structural bound: hysteresis
        # evaluations plus the cooldown plus monitor slack.
        up_bound = (auto.hysteresis * auto.eval_s + auto.cooldown_s
                    + 20 * 0.02 + 1.0)
        checks["scaled_up"] = t_up is not None
        checks["scale_up_within_bound"] = (t_up is not None
                                           and t_up <= up_bound)
        # Degrade-before-shed: the first brownout level-raise precedes
        # the first shed in the event stream (trivially true when the
        # cascade absorbed the whole surge and nothing shed).
        kinds = rec.kinds()
        first_brown = next(
            (j for j, e in enumerate(rec.events)
             if e["event"] == "fleet_brownout" and e.get("level", 0) >= 1),
            None)
        first_shed = next(
            (j for j, k in enumerate(kinds) if k == "fleet_shed"), None)
        checks["brownout_engaged"] = first_brown is not None
        checks["degrade_before_shed"] = (
            first_brown is not None
            and (first_shed is None or first_brown < first_shed))
        checks["zero_interactive_sheds"] = not any(
            e.get("klass") == "interactive" for e in rec.of("fleet_shed"))
        # Decay: stop submitting, drain the queue, and the fleet must
        # retire back to min_replicas (drain-before-retire, so nothing
        # strands).
        done, not_done = cf.wait(futs, timeout=120.0)
        checks["no_hung_futures"] = len(not_done) == 0
        for f in done:
            err = f.exception()
            if err is None:
                ok += 1
            elif isinstance(err, (ShedError, DeadlineExceeded,
                                  ReplicaCrashed)):
                expired += 1
            else:
                checks["typed_failures_only"] = False
        checks.setdefault("typed_failures_only", True)
        deadline = time.perf_counter() + 30.0
        n_active = ex.stats()["n_replicas_active"]
        while time.perf_counter() < deadline and n_active > 1:
            time.sleep(0.05)
            n_active = ex.stats()["n_replicas_active"]
        stats = ex.stats()
        checks["scaled_back_down"] = n_active == auto.min_replicas
        checks["degraded_served_cheaper"] = stats["degraded_requests"] > 0
        checks["shadow_probes_sampled"] = (
            stats["brownout"]["shadow"]["submitted"] >= 1)
        inter = stats["classes"].get("interactive", {})
        checks["interactive_p95_in_deadline"] = (
            inter.get("n", 0) > 0 and inter.get("p95_s", 99.0) <= 0.5)
        checks["no_recovery_needed"] = stats["recoveries"] == 0
    finally:
        summary = ex.close()
    checks["all_replicas_joined"] = summary.get("unjoined_replicas") == []
    return {
        "pass": all(checks.values()),
        "detail": {
            "checks": checks,
            "drain_calibrated_per_s": round(drain, 1),
            "submitted": len(futs) + shed,
            "served": ok,
            "shed_submit": shed,
            "typed_failures": expired,
            "t_scale_up_s": round(t_up, 3) if t_up is not None else None,
            "scale_ups": summary.get("scale_ups"),
            "scale_downs": summary.get("scale_downs"),
            "degraded": summary.get("degraded_requests"),
            "degraded_census": summary.get("degraded_census"),
            "interactive": summary.get("classes", {}).get("interactive"),
            "shed_total": summary.get("shed"),
        },
    }


# ------------------------------------------------------------------ driver

def run_drills(workdir: str, fast: bool, only=None) -> dict:
    import jax

    drills = {}
    t0 = time.perf_counter()
    plan = [
        ("nan_rollback", lambda: drill_nan_rollback(workdir, fast)),
        ("fleet_crash", lambda: drill_fleet_crash(12 if fast else 24)),
        ("ckpt_retry", lambda: drill_ckpt_retry(workdir)),
        ("elastic_resume", lambda: drill_elastic_resume(workdir, fast)),
        ("overload_brownout", lambda: drill_overload_brownout(fast)),
    ]
    for name, fn in plan:
        if only and name not in only:
            continue
        _log(f"drill {name} ...")
        t = time.perf_counter()
        try:
            res = fn()
        except Exception as e:  # noqa: BLE001 — a crashed drill is a FAIL, not a traceback-only exit
            import traceback

            res = {"pass": False,
                   "detail": {"error": f"{type(e).__name__}: {e}",
                              "traceback": traceback.format_exc()[-2000:]}}
        res["elapsed_s"] = round(time.perf_counter() - t, 2)
        drills[name] = res
        _log(f"drill {name}: {'PASS' if res['pass'] else 'FAIL'} "
             f"({res['elapsed_s']}s)")
    return {
        "metric": "cyclegan_chaos_drill",
        "fast": bool(fast),
        "platform": jax.default_backend(),
        "elapsed_s": round(time.perf_counter() - t0, 2),
        "drills": drills,
        "pass": bool(drills) and all(d["pass"] for d in drills.values()),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--fast", action="store_true",
                   help="tier-1 budget: shorter training run, smaller "
                        "fleet load, skip the rollback-budget edge case")
    p.add_argument("--only", action="append", default=None,
                   choices=["nan_rollback", "fleet_crash", "ckpt_retry",
                            "elastic_resume", "overload_brownout"],
                   help="run a subset (repeatable)")
    p.add_argument("--workdir", default=None,
                   help="scratch dir (default: a fresh temp dir)")
    args = p.parse_args(argv)
    import tempfile

    if args.workdir:
        os.makedirs(args.workdir, exist_ok=True)
        report = run_drills(args.workdir, args.fast, args.only)
    else:
        with tempfile.TemporaryDirectory(prefix="chaos_drill_") as wd:
            report = run_drills(wd, args.fast, args.only)
    print(json.dumps(report))
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
