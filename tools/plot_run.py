"""Plot scalar curves from a training run's TensorBoard event files,
or model-health curves from its telemetry JSONL stream.

Offline matplotlib rendering (Agg backend, same off-main-thread
discipline as the epoch-services plot jobs) of any logged scalar
(loss_*, error/*, fid/*, perf/*) straight from `<output_dir>`'s event
files — no TensorBoard server needed. Used to produce the committed
FID-vs-epoch curves in docs/images/.

With `--jsonl` the input is the obs telemetry stream instead: the
per-epoch `health` events (obs/health.py) become a two-panel figure —
loss-term trajectories on top, per-network grad-norm envelopes
(min..max band around the mean) below, with `health_fault` epochs
marked as vertical lines. This is the flight-recorder view: a diverging
loss, a grad-norm blowup, and the anomaly that flagged it on one page.

Usage:
  python tools/plot_run.py --run /tmp/toyrun --tags "fid/.*" \
      --out docs/images/toy_fid_curve.png --title "FID vs epoch"
  python tools/plot_run.py --jsonl /tmp/toyrun/telemetry.jsonl \
      --out /tmp/health.png --title "model health"
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import struct
from collections import defaultdict


def read_scalars(run_dir: str) -> dict:
    """{tag: [(step, value), ...]} from every event file under run_dir
    (tensorboardX record format: u64 length, u32 crc, payload, u32 crc).

    Writers live in subdirectories (the test writer logs to <run>/test/
    with the SAME tag names as the train writer — utils/summary.py), so
    tags from a subdirectory are prefixed with it: "loss_G/total" is the
    train curve, "test/loss_G/total" the test curve — never interleaved.
    """
    from tensorboardX.proto import event_pb2

    series = defaultdict(list)
    for path in sorted(glob.glob(os.path.join(run_dir, "**", "events.out.tfevents.*"),
                                 recursive=True)):
        subdir = os.path.relpath(os.path.dirname(path), run_dir)
        prefix = "" if subdir == "." else subdir.replace(os.sep, "/") + "/"
        with open(path, "rb") as f:
            data = f.read()
        i = 0
        while i + 12 <= len(data):
            (length,) = struct.unpack_from("<Q", data, i)
            i += 12
            if i + length > len(data):
                break  # truncated tail (live run): keep what parsed
            ev = event_pb2.Event()
            ev.ParseFromString(data[i:i + length])
            i += length + 4
            for v in ev.summary.value:
                if v.HasField("simple_value"):
                    series[prefix + v.tag].append(
                        (int(ev.step), float(v.simple_value))
                    )
    return {k: sorted(vs) for k, vs in series.items()}


def plot(series: dict, tags: list, out: str, title: str = "",
         logy: bool = False) -> list:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    patterns = [re.compile(t) for t in tags]
    chosen = sorted(
        tag for tag in series if any(p.fullmatch(tag) for p in patterns)
    )
    if not chosen:
        raise SystemExit(
            f"no tags match {tags}; available: {sorted(series)[:20]} ..."
        )
    fig, ax = plt.subplots(figsize=(7, 4))
    for tag in chosen:
        steps, values = zip(*series[tag])
        ax.plot(steps, values, label=tag, linewidth=1.5)
    ax.set_xlabel("epoch")
    if logy:
        ax.set_yscale("log")
    if title:
        ax.set_title(title)
    ax.legend(fontsize=8)
    ax.grid(alpha=0.3)
    fig.tight_layout()
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    fig.savefig(out, dpi=120)
    print(f"plotted {len(chosen)} series -> {out}")
    return chosen


def read_health_events(jsonl_path: str) -> tuple:
    """(health_events, fault_events) from a telemetry stream, in order.
    Malformed lines are skipped (truncated tails are legal)."""
    health, faults = [], []
    with open(jsonl_path, "r", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if not isinstance(ev, dict):
                continue
            if ev.get("event") == "health":
                health.append(ev)
            elif ev.get("event") == "health_fault":
                faults.append(ev)
    return health, faults


def plot_health(health: list, faults: list, out: str, title: str = "",
                logy: bool = False) -> int:
    """Two-panel health figure: loss trajectories + grad-norm envelopes
    with anomaly markers. Returns the number of series drawn."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if not health:
        raise SystemExit(
            "no `health` events in the stream (run with the health layer "
            "enabled — it is on by default; --no_health disables it)"
        )
    epochs = [ev.get("epoch", i) for i, ev in enumerate(health)]
    fig, (ax_loss, ax_gnorm) = plt.subplots(
        2, 1, figsize=(7, 6), sharex=True
    )
    n_series = 0

    loss_keys = sorted({k for ev in health for k in (ev.get("loss") or {})})
    for key in loss_keys:
        ys = [(ev.get("loss") or {}).get(key) for ev in health]
        ax_loss.plot(epochs, ys, label=key, linewidth=1.5)
        n_series += 1
    ax_loss.set_ylabel("loss (epoch mean)")
    ax_loss.legend(fontsize=7)
    ax_loss.grid(alpha=0.3)

    nets = sorted({net for ev in health for net in (ev.get("gnorm") or {})})
    for net in nets:
        means = [(ev.get("gnorm") or {}).get(net, {}).get("mean")
                 for ev in health]
        lows = [(ev.get("gnorm") or {}).get(net, {}).get("min")
                for ev in health]
        highs = [(ev.get("gnorm") or {}).get(net, {}).get("max")
                 for ev in health]
        (line,) = ax_gnorm.plot(epochs, means, label=f"gnorm {net}",
                                linewidth=1.5)
        if all(v is not None for v in lows + highs):
            ax_gnorm.fill_between(epochs, lows, highs, alpha=0.15,
                                  color=line.get_color())
        n_series += 1
    ax_gnorm.set_ylabel("grad norm (min..max)")
    ax_gnorm.set_xlabel("epoch")
    if logy:
        ax_loss.set_yscale("log")
        ax_gnorm.set_yscale("log")
    ax_gnorm.legend(fontsize=7)
    ax_gnorm.grid(alpha=0.3)

    # Anomaly markers: one vertical line per faulting epoch, labeled by
    # kind once (legend dedup).
    seen_kinds = set()
    for ev in faults:
        kind = str(ev.get("kind", "fault"))
        label = kind if kind not in seen_kinds else None
        seen_kinds.add(kind)
        for ax in (ax_loss, ax_gnorm):
            ax.axvline(ev.get("epoch", 0), color="red", alpha=0.5,
                       linestyle="--", linewidth=1.0,
                       label=label if ax is ax_loss else None)
    if seen_kinds:
        ax_loss.legend(fontsize=7)

    if title:
        ax_loss.set_title(title)
    fig.tight_layout()
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    fig.savefig(out, dpi=120)
    print(f"plotted {n_series} health series "
          f"({len(faults)} fault markers) -> {out}")
    return n_series


def read_goodput_events(jsonl_path: str) -> list:
    """Per-epoch `goodput` rollups (obs/goodput.py) from a telemetry
    stream, in order. Malformed lines are skipped."""
    out = []
    with open(jsonl_path, "r", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if isinstance(ev, dict) and ev.get("event") == "goodput":
                out.append(ev)
    return out


# Canonical phase order for the stacked bars (obs/goodput.py PHASES);
# unknown phases from newer streams stack after these, alphabetically.
_GOODPUT_PHASES = ("compute", "collective", "data_wait", "host",
                   "compile", "services", "idle")
_PHASE_COLORS = {
    "compute": "#2a9d2a",
    "collective": "#6a5acd",
    "data_wait": "#e07b39",
    "host": "#d4b106",
    "compile": "#8b5a2b",
    "services": "#4682b4",
    "idle": "#b0b0b0",
}


def plot_goodput(events: list, out: str, title: str = "") -> int:
    """Stacked per-epoch phase-fraction bars from `goodput` rollups:
    green is device compute (the goodput), everything above it is
    badput with its cause labeled. Returns the number of bars drawn."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if not events:
        raise SystemExit(
            "no `goodput` events in the stream (the ledger needs "
            "StepClock data — streams predating obs/goodput.py or "
            "metrics-disabled runs have none)"
        )
    epochs = [int(ev.get("epoch", i)) for i, ev in enumerate(events)]
    seen = {p for ev in events for p in (ev.get("phase_fractions") or {})}
    phases = [p for p in _GOODPUT_PHASES if p in seen]
    phases += sorted(seen - set(phases))

    fig, ax = plt.subplots(figsize=(max(7, 0.6 * len(epochs) + 3), 4.5))
    bottoms = [0.0] * len(events)
    for phase in phases:
        vals = [float((ev.get("phase_fractions") or {}).get(phase, 0.0))
                for ev in events]
        if not any(vals):
            continue
        ax.bar(epochs, vals, bottom=bottoms, width=0.8, label=phase,
               color=_PHASE_COLORS.get(phase))
        bottoms = [b + v for b, v in zip(bottoms, vals)]
    # Label each epoch with its goodput % and its dominant badput cause
    # — the one-glance answer to "where did the wall-clock go".
    for x, ev in zip(epochs, events):
        gp = ev.get("goodput_fraction")
        badput = ev.get("badput") or {}
        worst = max(badput, key=badput.get) if badput else None
        text = f"{100 * float(gp):.0f}%" if gp is not None else "?"
        if worst:
            text += f"\n{worst} {100 * float(badput[worst]):.0f}%"
        ax.text(x, 1.02, text, ha="center", va="bottom", fontsize=7)
    ax.set_xlabel("epoch")
    ax.set_ylabel("wall-clock fraction")
    ax.set_ylim(0, 1.18)
    ax.set_xticks(epochs)
    ax.legend(fontsize=7, ncol=min(4, len(phases)), loc="lower right")
    ax.grid(alpha=0.3, axis="y")
    if title:
        ax.set_title(title)
    fig.tight_layout()
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    fig.savefig(out, dpi=120)
    print(f"plotted {len(events)} goodput bars "
          f"({len(phases)} phases) -> {out}")
    return len(events)


if __name__ == "__main__":
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--run", help="training output dir (TensorBoard mode)")
    p.add_argument("--tags", nargs="+",
                   help="regex(es) matched against full scalar tags "
                        "(TensorBoard mode)")
    p.add_argument("--jsonl", help="telemetry stream: plot `health` "
                                   "events (or `goodput` rollups with "
                                   "--jsonl_mode goodput) instead of "
                                   "TB scalars")
    p.add_argument("--jsonl_mode", default="health",
                   choices=("health", "goodput"),
                   help="which stream view to render: the two-panel "
                        "health figure, or the stacked per-epoch "
                        "goodput/badput phase bars")
    p.add_argument("--out", required=True, help="destination PNG")
    p.add_argument("--title", default="")
    p.add_argument("--logy", action="store_true")
    a = p.parse_args()
    if a.jsonl and a.jsonl_mode == "goodput":
        plot_goodput(read_goodput_events(a.jsonl), a.out, a.title)
    elif a.jsonl:
        health, faults = read_health_events(a.jsonl)
        plot_health(health, faults, a.out, a.title, a.logy)
    elif a.run and a.tags:
        plot(read_scalars(a.run), a.tags, a.out, a.title, a.logy)
    else:
        p.error("need either --jsonl or both --run and --tags")
