"""Plot scalar curves from a training run's TensorBoard event files.

Offline matplotlib rendering of any logged scalar (loss_*, error/*,
fid/*, perf/*) straight from `<output_dir>`'s event files — no
TensorBoard server needed. Used to produce the committed FID-vs-epoch
curves in docs/images/.

Usage:
  python tools/plot_run.py --run /tmp/toyrun --tags "fid/.*" \
      --out docs/images/toy_fid_curve.png --title "FID vs epoch"
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import struct
from collections import defaultdict


def read_scalars(run_dir: str) -> dict:
    """{tag: [(step, value), ...]} from every event file under run_dir
    (tensorboardX record format: u64 length, u32 crc, payload, u32 crc).

    Writers live in subdirectories (the test writer logs to <run>/test/
    with the SAME tag names as the train writer — utils/summary.py), so
    tags from a subdirectory are prefixed with it: "loss_G/total" is the
    train curve, "test/loss_G/total" the test curve — never interleaved.
    """
    from tensorboardX.proto import event_pb2

    series = defaultdict(list)
    for path in sorted(glob.glob(os.path.join(run_dir, "**", "events.out.tfevents.*"),
                                 recursive=True)):
        subdir = os.path.relpath(os.path.dirname(path), run_dir)
        prefix = "" if subdir == "." else subdir.replace(os.sep, "/") + "/"
        with open(path, "rb") as f:
            data = f.read()
        i = 0
        while i + 12 <= len(data):
            (length,) = struct.unpack_from("<Q", data, i)
            i += 12
            if i + length > len(data):
                break  # truncated tail (live run): keep what parsed
            ev = event_pb2.Event()
            ev.ParseFromString(data[i:i + length])
            i += length + 4
            for v in ev.summary.value:
                if v.HasField("simple_value"):
                    series[prefix + v.tag].append(
                        (int(ev.step), float(v.simple_value))
                    )
    return {k: sorted(vs) for k, vs in series.items()}


def plot(series: dict, tags: list, out: str, title: str = "",
         logy: bool = False) -> list:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    patterns = [re.compile(t) for t in tags]
    chosen = sorted(
        tag for tag in series if any(p.fullmatch(tag) for p in patterns)
    )
    if not chosen:
        raise SystemExit(
            f"no tags match {tags}; available: {sorted(series)[:20]} ..."
        )
    fig, ax = plt.subplots(figsize=(7, 4))
    for tag in chosen:
        steps, values = zip(*series[tag])
        ax.plot(steps, values, label=tag, linewidth=1.5)
    ax.set_xlabel("epoch")
    if logy:
        ax.set_yscale("log")
    if title:
        ax.set_title(title)
    ax.legend(fontsize=8)
    ax.grid(alpha=0.3)
    fig.tight_layout()
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    fig.savefig(out, dpi=120)
    print(f"plotted {len(chosen)} series -> {out}")
    return chosen


if __name__ == "__main__":
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--run", required=True, help="training output dir")
    p.add_argument("--tags", nargs="+", required=True,
                   help="regex(es) matched against full scalar tags")
    p.add_argument("--out", required=True, help="destination PNG")
    p.add_argument("--title", default="")
    p.add_argument("--logy", action="store_true")
    a = p.parse_args()
    plot(read_scalars(a.run), a.tags, a.out, a.title, a.logy)
