"""graftlint CLI.

    python tools/graftlint [paths…] [--json] [--census-json OUT]
                           [--rules a,b] [--severity rule=level]
                           [--baseline PATH | --no-baseline]
                           [--update-baseline REASON]

Exit codes: 0 clean (info-only findings included), 1 any live
error/warning finding, 2 usage/internal error. `--json` prints ONE
JSON line to stdout (the repo's tooling contract — bench.py,
chaos_drill.py); text mode prints one line per finding plus a verdict
line. The census inventory (`--census-json`) is written regardless of
the lint verdict, so a failing run still produces the registry seed.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from graftlint import engine
from graftlint.engine import BASELINE_NAME
from graftlint.rules import ALL_RULES, make_rules
from graftlint.rules.census import CompileSiteCensusRule


def _default_repo() -> str:
    # tools/graftlint/cli.py -> repo root
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    parser.add_argument("paths", nargs="*",
                        help="repo-relative files/dirs to scan "
                             "(default: the full scan-target set)")
    parser.add_argument("--repo", default=_default_repo(),
                        help="repository root (default: auto)")
    parser.add_argument("--json", action="store_true",
                        help="one JSON line instead of text")
    parser.add_argument("--rules",
                        help=f"comma list from {sorted(ALL_RULES)}")
    parser.add_argument("--severity", action="append", default=[],
                        metavar="RULE=LEVEL",
                        help="override a rule's severity "
                             "(error|warning|info); repeatable")
    parser.add_argument("--baseline",
                        help=f"baseline path (default: <repo>/"
                             f"{BASELINE_NAME})")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the committed baseline")
    parser.add_argument("--update-baseline", metavar="REASON",
                        help="grandfather every live finding into the "
                             "baseline with REASON, then exit 0")
    parser.add_argument("--census-json", metavar="OUT",
                        help="write the compile-site inventory here "
                             "('-' for stdout)")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, cls in sorted(ALL_RULES.items()):
            print(f"{name:22s} [{cls.default_severity}] "
                  f"{cls.description}")
        return 0

    severities = {}
    for spec in args.severity:
        if "=" not in spec:
            print(f"--severity wants RULE=LEVEL, got {spec!r}",
                  file=sys.stderr)
            return 2
        rule, level = spec.split("=", 1)
        if level not in engine.SEVERITIES:
            print(f"unknown severity {level!r} (want one of "
                  f"{engine.SEVERITIES})", file=sys.stderr)
            return 2
        severities[rule] = level
    rule_names = ([r.strip() for r in args.rules.split(",") if r.strip()]
                  if args.rules else None)
    try:
        rules = make_rules(rule_names, severities)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2

    repo = os.path.abspath(args.repo)
    files = None
    if args.paths:
        files = engine.iter_scan_files(repo, tuple(args.paths))
        if not files:
            print(f"no .py files under {args.paths}", file=sys.stderr)
            return 2

    baseline = None
    baseline_path = args.baseline or os.path.join(repo, BASELINE_NAME)
    if not args.no_baseline:
        baseline = engine.load_baseline(baseline_path)

    result = engine.run(repo, rules, files=files, baseline=baseline)

    census = next((r for r in rules
                   if isinstance(r, CompileSiteCensusRule)), None)
    if args.census_json and census is not None:
        inv = census.inventory()
        if args.census_json == "-":
            print(json.dumps(inv, indent=2, sort_keys=True))
        else:
            out = (args.census_json if os.path.isabs(args.census_json)
                   else os.path.join(repo, args.census_json))
            with open(out, "w") as f:
                json.dump(inv, f, indent=2, sort_keys=True)
                f.write("\n")
            if not args.json:
                print(f"census: {inv['n_sites']} compile sites -> "
                      f"{args.census_json}")
    elif args.census_json:
        print("--census-json needs the compile-site-census rule enabled",
              file=sys.stderr)
        return 2

    if args.update_baseline:
        # New baseline = entries that still match a finding (original
        # reasons kept; stale ones dropped) + every live finding under
        # the given reason.
        existing = {(e["rule"], e["path"], e["fingerprint"]):
                    e.get("reason", "") for e in (baseline or [])}
        entries = [
            {"rule": f.rule, "path": f.path, "fingerprint": f.fingerprint,
             "reason": existing.get((f.rule, f.path, f.fingerprint),
                                    args.update_baseline),
             "severity": f.severity, "message": f.message}
            for f in result.baselined + result.findings
        ]
        entries.sort(key=lambda e: (e["path"], e["rule"], e["fingerprint"]))
        with open(baseline_path, "w") as f:
            json.dump({"version": 1, "entries": entries}, f, indent=2)
            f.write("\n")
        print(f"baseline updated: {len(result.findings)} new finding(s) "
              f"grandfathered, {len(result.baselined)} kept, "
              f"{len(result.stale_baseline)} stale dropped -> "
              f"{os.path.relpath(baseline_path, repo)}")
        return 0

    if args.json:
        print(result.as_json_line())
    else:
        print(result.render_text())
    return 0 if result.ok else 1
