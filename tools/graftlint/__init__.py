"""graftlint: AST/dataflow static analysis for TPU discipline.

Stdlib-only (`ast` + `tokenize`, no jax import — the linter must run on
any box, including CI images and the chip_autorun daemon's parent
process, which never imports jax). Four rules over the package:

- donation-aliasing: host-owned buffers must not reach donate_argnums
  call sites without jnp.copy/_rebuffer (the PR-8/PR-10 bug class).
- no-sync: the hot path stays asynchronous (check_no_sync.py semantics,
  alias-aware on the AST).
- tracer-leak: host control flow / concretization on traced values,
  jit-in-loop retraces, unhashable static args.
- compile-site-census: the jit/lower/compile/shard_map inventory that
  seeds ROADMAP item 5's AOT program registry.

Run it:

    python tools/graftlint                # text verdict, exit 1 on findings
    python tools/graftlint --json         # one JSON line (tooling contract)
    python tools/graftlint --census-json docs/compile_sites_r01.json
"""

from graftlint.engine import (  # noqa: F401
    Finding,
    LintResult,
    Module,
    Rule,
    SCAN_TARGETS,
    iter_scan_files,
    load_baseline,
    run,
)
from graftlint.rules import ALL_RULES, make_rules  # noqa: F401

__version__ = "1.0"
