"""Shared AST machinery for graftlint rules.

Three layers, all stdlib-`ast`:

- **Import resolution** (`ImportMap`, `resolve`): a dotted expression like
  ``jnp.copy`` or an aliased import ``from jax import device_get as g``
  resolves to its fully-qualified name (``jax.numpy.copy``,
  ``jax.device_get``) so rules match *semantics*, not spellings — the
  exact false-negative class the token-scan lint could not see.

- **Jit classification** (`JitInfo`, `jit_call_info`): recognizes the
  repo's program-construction grammar — ``jax.jit(f, donate_argnums=…)``,
  ``partial(jax.jit, …)`` decorators, ``.lower(…)`` on a jit object,
  ``.compile()`` on a lowered object, and ``shard_map`` in both its
  ``jax.shard_map`` and ``jax.experimental.shard_map`` spellings
  (including the ``_shard_map = jax.shard_map`` rebinding idiom in
  parallel/collective.py).

- **`FlowWalker`**: one intraprocedural forward pass per scope that
  tracks (a) which names are bound to jit/lowered/compiled objects
  (including through module-local helper functions whose return value is
  such an object — how ``lower_forward(…).compile()`` in serve/engine.py
  is recognized), and (b) which values are *tainted*, i.e. originate
  from buffers XLA does not own: orbax/tensorstore restores,
  ``np.asarray``/``np.frombuffer``, ``jax.device_get`` host gathers —
  propagated through ``device_put``, containers, tree flatten/unflatten,
  and method calls, and cleared only by the sanctioned re-buffering ops
  ``jnp.copy`` / ``_rebuffer``. Rules subclass the walker and receive
  events (compile sites, donated-call sinks, jitted defs, loop-scoped
  jits) via the ``on_*`` hooks.

The analysis is deliberately intraprocedural with module-level function
summaries: unknown calls launder taint (precision over recall), and the
two historical donation bugs this framework exists to catch (PR-8
``_rebuffer``, PR-10 elastic ``jnp.copy``) are pinned as single-module
corpus fixtures in tests/data/lint_corpus/.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import tokenize
from typing import Dict, List, Optional, Tuple

# --------------------------------------------------------------- imports


def build_import_map(tree: ast.AST) -> Dict[str, str]:
    """name-in-scope -> fully qualified dotted name.

    ``import numpy as np`` -> {"np": "numpy"}; ``import jax`` ->
    {"jax": "jax"}; ``from jax import device_get as g`` ->
    {"g": "jax.device_get"}. Collected over the whole module (imports
    inside functions included — the repo lazy-imports jax constantly).
    """
    imports: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    imports[a.asname] = a.name
                else:
                    head = a.name.split(".")[0]
                    imports[head] = head
        elif isinstance(node, ast.ImportFrom):
            if node.module is None or node.level:
                continue  # relative imports: out of scope
            for a in node.names:
                if a.name == "*":
                    continue
                imports[a.asname or a.name] = f"{node.module}.{a.name}"
    return imports


def dotted_parts(node: ast.AST) -> Optional[List[str]]:
    """["self", "_ckptr", "restore"] for self._ckptr.restore; None if the
    expression is not a pure Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def resolve(node: ast.AST, imports: Dict[str, str]) -> Optional[str]:
    """Fully-qualified dotted name of an expression, through import
    aliases. `jnp.copy` -> "jax.numpy.copy"; unknown heads stay as
    written ("self._ckptr.restore")."""
    parts = dotted_parts(node)
    if not parts:
        return None
    head = imports.get(parts[0])
    if head is not None:
        return ".".join([head] + parts[1:])
    return ".".join(parts)


# -------------------------------------------------------------- comments


def comment_map(source: str) -> Dict[int, str]:
    """line -> comment text (the part after '#'), tokenizer-accurate so
    '#' inside string literals never reads as a comment."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string.lstrip("#").strip()
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # suppressions unavailable on an unparseable file
    return out


# ------------------------------------------------------ jit classification

JIT_NAMES = ("jax.jit", "jax.pjit", "jax.experimental.pjit.pjit")
PARTIAL_NAMES = ("functools.partial", "partial")
SHARD_MAP_NAMES = ("jax.shard_map", "jax.experimental.shard_map.shard_map")


@dataclasses.dataclass(frozen=True)
class JitInfo:
    """What we know about a program-construction expression."""

    kind: str  # "jit" | "lowered" | "compiled"
    donate_argnums: Tuple[int, ...] = ()
    donate_argnames: Tuple[str, ...] = ()
    static_argnums: Tuple[int, ...] = ()
    static_argnames: Tuple[str, ...] = ()

    def evolved(self, kind: str) -> "JitInfo":
        return dataclasses.replace(self, kind=kind)

    @property
    def donates(self) -> bool:
        return bool(self.donate_argnums or self.donate_argnames)


class JitFactory:
    """partial(jax.jit, …): calling it yields a jit-wrapped callable."""

    def __init__(self, info: JitInfo):
        self.info = info


class ShardMapMarker:
    """A name bound to shard_map (e.g. `_shard_map = jax.shard_map`)."""


SHARD_MAP = ShardMapMarker()


def _int_tuple(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
        return tuple(out)
    return ()


def _str_tuple(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.append(e.value)
        return tuple(out)
    return ()


def _info_from_kwargs(call: ast.Call) -> JitInfo:
    kw = {k.arg: k.value for k in call.keywords if k.arg}
    return JitInfo(
        kind="jit",
        donate_argnums=_int_tuple(kw.get("donate_argnums", ast.Tuple(elts=[]))),
        donate_argnames=_str_tuple(kw.get("donate_argnames", ast.Tuple(elts=[]))),
        static_argnums=_int_tuple(kw.get("static_argnums", ast.Tuple(elts=[]))),
        static_argnames=_str_tuple(kw.get("static_argnames", ast.Tuple(elts=[]))),
    )


def jit_call_info(call: ast.Call, imports: Dict[str, str]) -> Optional[JitInfo]:
    """JitInfo for `jax.jit(…)` / `pjit(…)` call expressions, else None."""
    name = resolve(call.func, imports)
    if name in JIT_NAMES:
        return _info_from_kwargs(call)
    return None


def partial_jit_info(call: ast.Call,
                     imports: Dict[str, str]) -> Optional[JitInfo]:
    """JitInfo for `partial(jax.jit, …)` factory expressions, else None."""
    name = resolve(call.func, imports)
    if name in PARTIAL_NAMES and call.args:
        if resolve(call.args[0], imports) in JIT_NAMES:
            return _info_from_kwargs(call)
    return None


# ---------------------------------------------------------------- taint

# Fully-qualified callables whose RESULT is a buffer XLA does not own.
SOURCE_CALLS = {
    "jax.device_get": "host gather (jax.device_get)",
    "numpy.asarray": "host numpy buffer (np.asarray)",
    "numpy.frombuffer": "host numpy buffer (np.frombuffer)",
}
# Method names treated as checkpoint-restore calls regardless of the
# receiver: orbax checkpointers, the repo's Checkpointer, tensorstore.
SOURCE_METHODS = {
    "restore": "checkpoint restore",
    "restore_if_exists": "checkpoint restore",
}
# Sanctioned re-buffering ops: route the value through an XLA
# computation, yielding an XLA-owned buffer (checkpoint._rebuffer docs).
SANITIZER_CALLS = {"jax.numpy.copy", "jax.numpy.array"}
SANITIZER_NAMES = {"_rebuffer"}
TREE_MAP_NAMES = {"jax.tree.map", "jax.tree_util.tree_map", "jax.tree_map"}
TREE_UNFLATTEN_NAMES = {"jax.tree_util.tree_unflatten", "jax.tree.unflatten"}
TREE_FLATTEN_NAMES = {"jax.tree_util.tree_flatten", "jax.tree.flatten",
                      "jax.tree_util.tree_leaves", "jax.tree.leaves"}


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self.parent = parent
        self.bindings: Dict[str, object] = {}  # JitInfo | JitFactory | marker
        self.taint: Dict[str, str] = {}        # name/dotted -> origin

    def lookup_binding(self, name: str):
        s: Optional[Scope] = self
        while s is not None:
            if name in s.bindings:
                return s.bindings[name]
            s = s.parent
        return None

    def lookup_taint(self, name: str) -> Optional[str]:
        s: Optional[Scope] = self
        while s is not None:
            if name in s.taint:
                return s.taint[name]
            s = s.parent
        return None


@dataclasses.dataclass
class EvalResult:
    taint: Optional[str] = None   # origin description, None = clean
    binding: object = None        # JitInfo | JitFactory | SHARD_MAP | None


_CONTAINER_CTORS = {"tuple", "list", "dict", "set"}


class FlowWalker:
    """One forward pass per scope. Subclass and override the `on_*`
    hooks; call `run()`. Loop bodies are processed twice (taint
    introduced late in the body reaches uses at its top on the second
    pass); event hooks deduplicate on node identity so the double pass
    never double-reports."""

    def __init__(self, tree: ast.AST, imports: Dict[str, str]):
        self.tree = tree
        self.imports = imports
        self.defs_by_name: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs_by_name.setdefault(node.name, node)
        self._seen: set = set()
        self._loop_depth = 0

    # ---- hooks -----------------------------------------------------------
    def on_compile_site(self, kind: str, node: ast.AST, info: Optional[JitInfo],
                        qualname: str) -> None:
        """kind in {"jit", "lower", "compile", "shard_map"}."""

    def on_jitted_def(self, funcdef, info: JitInfo, qualname: str) -> None:
        """A module function definitely traced under jax.jit."""

    def on_donated_taint(self, node: ast.AST, where: str, origin: str,
                         qualname: str) -> None:
        """A tainted value reached a donated argument position."""

    def on_unhashable_static(self, node: ast.AST, where: str,
                             qualname: str) -> None:
        """A list/dict/set literal passed at a static_argnums position."""

    def on_jit_in_loop(self, node: ast.AST, qualname: str) -> None:
        """jax.jit constructed inside a loop body (retrace hazard)."""

    # ---- driver ----------------------------------------------------------
    def run(self) -> None:
        self._walk_body(self.tree.body, Scope(), "")

    def _once(self, node: ast.AST, tag: str) -> bool:
        key = (id(node), tag)
        if key in self._seen:
            return False
        self._seen.add(key)
        return True

    # ---- statements ------------------------------------------------------
    def _walk_body(self, body, scope: Scope, qualname: str) -> None:
        for stmt in body:
            self._stmt(stmt, scope, qualname)

    def _stmt(self, s, scope: Scope, qualname: str) -> None:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._function(s, scope, qualname)
        elif isinstance(s, ast.ClassDef):
            for d in s.decorator_list:
                self._eval(d, scope, qualname)
            self._walk_body(s.body, scope,
                            f"{qualname}.{s.name}" if qualname else s.name)
        elif isinstance(s, ast.Assign):
            r = self._eval(s.value, scope, qualname)
            for t in s.targets:
                self._assign(t, r, scope, qualname)
        elif isinstance(s, ast.AnnAssign):
            if s.value is not None:
                r = self._eval(s.value, scope, qualname)
                self._assign(s.target, r, scope, qualname)
        elif isinstance(s, ast.AugAssign):
            r = self._eval(s.value, scope, qualname)
            if r.taint is None:
                # x += clean leaves x's taint alone; x += tainted taints.
                return
            self._assign(s.target, r, scope, qualname)
        elif isinstance(s, ast.Expr):
            self._eval(s.value, scope, qualname)
        elif isinstance(s, ast.Return):
            if s.value is not None:
                r = self._eval(s.value, scope, qualname)
                scope.bindings.setdefault("__returns__", []).append(r)  # type: ignore[union-attr]
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            it = self._eval(s.iter, scope, qualname)
            self._assign(s.target, EvalResult(taint=it.taint), scope, qualname)
            self._loop_depth += 1
            try:
                self._walk_body(s.body, scope, qualname)
                self._walk_body(s.body, scope, qualname)  # fixpoint lite
            finally:
                self._loop_depth -= 1
            self._walk_body(s.orelse, scope, qualname)
        elif isinstance(s, ast.While):
            self._eval(s.test, scope, qualname)
            self._loop_depth += 1
            try:
                self._walk_body(s.body, scope, qualname)
                self._walk_body(s.body, scope, qualname)
            finally:
                self._loop_depth -= 1
            self._walk_body(s.orelse, scope, qualname)
        elif isinstance(s, ast.If):
            self._eval(s.test, scope, qualname)
            # Taint is union-merged across branches: either path may run.
            before = dict(scope.taint)
            self._walk_body(s.body, scope, qualname)
            after_then = dict(scope.taint)
            scope.taint = dict(before)
            self._walk_body(s.orelse, scope, qualname)
            for k, v in after_then.items():
                scope.taint.setdefault(k, v)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                r = self._eval(item.context_expr, scope, qualname)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, r, scope, qualname)
            self._walk_body(s.body, scope, qualname)
        elif isinstance(s, ast.Try):
            self._walk_body(s.body, scope, qualname)
            for h in s.handlers:
                self._walk_body(h.body, scope, qualname)
            self._walk_body(s.orelse, scope, qualname)
            self._walk_body(s.finalbody, scope, qualname)
        elif isinstance(s, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self._eval(child, scope, qualname)
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                parts = dotted_parts(t)
                if parts:
                    scope.taint.pop(".".join(parts), None)
        # Import/Pass/Global/Nonlocal/Break/Continue: nothing to do.

    def _function(self, f, scope: Scope, qualname: str) -> None:
        fq = f"{qualname}.{f.name}" if qualname else f.name
        jitted: Optional[JitInfo] = None
        for dec in f.decorator_list:
            info = self._decorator_info(dec, scope, qualname)
            if info is not None:
                jitted = info
        child = Scope(parent=scope)
        child.bindings["__returns__"] = []
        self._walk_body(f.body, child, fq)
        # Module-local summary: a helper whose return value is a
        # jit/lowered/compiled object makes its CALLERS construction-
        # site-aware (serve/engine.py lower_forward(…).compile()).
        returns = child.bindings.get("__returns__", [])
        infos = [r.binding for r in returns if isinstance(r.binding, JitInfo)]
        if infos and len(infos) == len(returns):
            scope.bindings[f.name] = _Summary(infos[0])
        if jitted is not None:
            scope.bindings[f.name] = jitted
            if self._once(f, "jitted_def"):
                self.on_jitted_def(f, jitted, fq)

    def _decorator_info(self, dec, scope: Scope,
                        qualname: str) -> Optional[JitInfo]:
        if isinstance(dec, ast.Call):
            info = jit_call_info(dec, self.imports)
            if info is None:
                info = partial_jit_info(dec, self.imports)
            if info is not None:
                if self._once(dec, "site"):
                    self.on_compile_site("jit", dec, info, qualname)
                return info
            self._eval(dec, scope, qualname)
            return None
        if resolve(dec, self.imports) in JIT_NAMES:
            info = JitInfo(kind="jit")
            if self._once(dec, "site"):
                self.on_compile_site("jit", dec, info, qualname)
            return info
        return None

    def _assign(self, target, r: EvalResult, scope: Scope,
                qualname: str) -> None:
        if isinstance(target, ast.Name):
            key = target.id
        else:
            parts = dotted_parts(target)
            if parts is None:
                if isinstance(target, (ast.Tuple, ast.List)):
                    for e in target.elts:
                        inner = e.value if isinstance(e, ast.Starred) else e
                        self._assign(inner, r, scope, qualname)
                elif isinstance(target, ast.Subscript):
                    # container[key] = tainted -> the container is tainted
                    base = dotted_parts(target.value)
                    if base and r.taint is not None:
                        scope.taint[".".join(base)] = r.taint
                return
            key = ".".join(parts)
        if r.taint is not None:
            scope.taint[key] = r.taint
        else:
            scope.taint.pop(key, None)
        if r.binding is not None:
            scope.bindings[key] = r.binding
        else:
            scope.bindings.pop(key, None)

    # ---- expressions -----------------------------------------------------
    def _eval(self, node, scope: Scope, qualname: str) -> EvalResult:
        if isinstance(node, ast.Call):
            return self._call(node, scope, qualname)
        if isinstance(node, ast.Name):
            return EvalResult(taint=scope.lookup_taint(node.id),
                              binding=scope.lookup_binding(node.id))
        if isinstance(node, ast.Attribute):
            parts = dotted_parts(node)
            if parts:
                key = ".".join(parts)
                t = scope.lookup_taint(key)
                b = scope.lookup_binding(key)
                if t is None:
                    t = scope.lookup_taint(parts[0])
                if b is None and resolve(node, self.imports) in SHARD_MAP_NAMES:
                    b = SHARD_MAP
                return EvalResult(taint=t, binding=b)
            base = self._eval(node.value, scope, qualname)
            return EvalResult(taint=base.taint)
        if isinstance(node, ast.Subscript):
            self._eval(node.slice, scope, qualname)
            base = self._eval(node.value, scope, qualname)
            return EvalResult(taint=base.taint)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            taint = None
            for e in node.elts:
                r = self._eval(e, scope, qualname)
                taint = taint or r.taint
            return EvalResult(taint=taint)
        if isinstance(node, ast.Dict):
            taint = None
            for k in list(node.keys) + list(node.values):
                if k is None:
                    continue
                r = self._eval(k, scope, qualname)
                taint = taint or r.taint
            return EvalResult(taint=taint)
        if isinstance(node, ast.Starred):
            return self._eval(node.value, scope, qualname)
        if isinstance(node, ast.IfExp):
            self._eval(node.test, scope, qualname)
            a = self._eval(node.body, scope, qualname)
            b = self._eval(node.orelse, scope, qualname)
            return EvalResult(taint=a.taint or b.taint,
                              binding=a.binding or b.binding)
        if isinstance(node, ast.BoolOp):
            taint = None
            for v in node.values:
                r = self._eval(v, scope, qualname)
                taint = taint or r.taint
            return EvalResult(taint=taint)
        if isinstance(node, ast.NamedExpr):
            r = self._eval(node.value, scope, qualname)
            self._assign(node.target, r, scope, qualname)
            return r
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            for gen in node.generators:
                it = self._eval(gen.iter, scope, qualname)
                self._assign(gen.target, EvalResult(taint=it.taint), scope,
                             qualname)
                for cond in gen.ifs:
                    self._eval(cond, scope, qualname)
            if isinstance(node, ast.DictComp):
                self._eval(node.key, scope, qualname)
                return EvalResult(
                    taint=self._eval(node.value, scope, qualname).taint)
            return EvalResult(
                taint=self._eval(node.elt, scope, qualname).taint)
        if isinstance(node, ast.Lambda):
            return EvalResult()  # bodies evaluated where applied (tree.map)
        if isinstance(node, ast.Await):
            return self._eval(node.value, scope, qualname)
        # Arithmetic/comparisons produce fresh XLA buffers: clean. Still
        # recurse so nested calls are seen (sinks inside `f(x) + 1`).
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._eval(child, scope, qualname)
        return EvalResult()

    def _lambda_sanitizes(self, fn) -> bool:
        """Does a tree.map mapping function body route through a
        sanitizer? (checkpoint._rebuffer's `lambda x: jnp.copy(x)…`)."""
        if isinstance(fn, ast.Lambda):
            for sub in ast.walk(fn.body):
                if isinstance(sub, ast.Call):
                    if resolve(sub.func, self.imports) in SANITIZER_CALLS:
                        return True
                    if (isinstance(sub.func, ast.Name)
                            and sub.func.id in SANITIZER_NAMES):
                        return True
        if isinstance(fn, ast.Name) and fn.id in SANITIZER_NAMES:
            return True
        return False

    def _call(self, node: ast.Call, scope: Scope,
              qualname: str) -> EvalResult:
        func = node.func
        resolved = resolve(func, self.imports)

        # Evaluate the callee expression itself (chained calls like
        # jax.jit(f).lower(x) classify through here).
        func_binding = None
        if isinstance(func, ast.Attribute) and func.attr in ("lower",
                                                             "compile"):
            recv = self._eval(func.value, scope, qualname)
            func_binding = recv.binding
        elif isinstance(func, (ast.Name, ast.Attribute)):
            func_binding = self._eval(func, scope, qualname).binding
        elif isinstance(func, ast.Call):
            func_binding = self._call(func, scope, qualname).binding

        # Argument taints (evaluated exactly once).
        arg_results = [self._eval(a, scope, qualname) for a in node.args]
        kw_results = {k.arg: self._eval(k.value, scope, qualname)
                      for k in node.keywords}

        # ---- construction sites ----------------------------------------
        info = jit_call_info(node, self.imports)
        if info is not None:
            if self._once(node, "site"):
                self.on_compile_site("jit", node, info, qualname)
                if self._loop_depth:
                    self.on_jit_in_loop(node, qualname)
            if node.args:
                target = node.args[0]
                if isinstance(target, ast.Name):
                    d = self.defs_by_name.get(target.id)
                    if d is not None and self._once(d, "jitted_def"):
                        self.on_jitted_def(d, info, qualname)
                elif isinstance(target, ast.Lambda):
                    pass  # traced lambda: nothing nameable to analyze
            return EvalResult(binding=info)
        pinfo = partial_jit_info(node, self.imports)
        if pinfo is not None:
            if self._once(node, "site"):
                self.on_compile_site("jit", node, pinfo, qualname)
                if self._loop_depth:
                    self.on_jit_in_loop(node, qualname)
            return EvalResult(binding=JitFactory(pinfo))
        if isinstance(func_binding, JitFactory):
            # Applying partial(jax.jit, …) to a function: the jit object.
            if node.args and isinstance(node.args[0], ast.Name):
                d = self.defs_by_name.get(node.args[0].id)
                if d is not None and self._once(d, "jitted_def"):
                    self.on_jitted_def(d, func_binding.info, qualname)
            return EvalResult(binding=func_binding.info)
        if (isinstance(func, ast.Attribute) and func.attr == "lower"
                and isinstance(func_binding, JitInfo)
                and func_binding.kind == "jit"):
            if self._once(node, "site"):
                self.on_compile_site("lower", node, func_binding, qualname)
            return EvalResult(binding=func_binding.evolved("lowered"))
        if (isinstance(func, ast.Attribute) and func.attr == "compile"
                and isinstance(func_binding, JitInfo)
                and func_binding.kind == "lowered"):
            if self._once(node, "site"):
                self.on_compile_site("compile", node, func_binding, qualname)
            return EvalResult(binding=func_binding.evolved("compiled"))
        if func_binding is SHARD_MAP or resolved in SHARD_MAP_NAMES:
            if self._once(node, "site"):
                self.on_compile_site("shard_map", node, None, qualname)
            return EvalResult()
        if isinstance(func_binding, _Summary):
            # Calling a module-local helper whose return is a
            # jit/lowered/compiled object: propagate its classification
            # (the construction sites inside it are censused there).
            summary = func_binding.info
            if summary.kind in ("jit", "compiled"):
                self._check_donated_call(node, summary, arg_results,
                                         kw_results, scope, qualname)
            return EvalResult(binding=summary)

        # ---- execution sinks -------------------------------------------
        if isinstance(func_binding, JitInfo):
            if func_binding.kind in ("jit", "compiled"):
                self._check_donated_call(node, func_binding, arg_results,
                                         kw_results, scope, qualname)
            return EvalResult()

        # ---- taint sources / sanitizers / propagation ------------------
        if resolved in SOURCE_CALLS:
            return EvalResult(taint=SOURCE_CALLS[resolved])
        if (isinstance(func, ast.Attribute)
                and func.attr in SOURCE_METHODS
                and resolved not in SANITIZER_CALLS):
            return EvalResult(taint=SOURCE_METHODS[func.attr])
        if resolved in SANITIZER_CALLS:
            return EvalResult()
        if isinstance(func, ast.Name) and func.id in SANITIZER_NAMES:
            return EvalResult()
        if isinstance(func, ast.Attribute) and func.attr in SANITIZER_NAMES:
            return EvalResult()
        if resolved == "jax.device_put":
            if arg_results and arg_results[0].taint:
                return EvalResult(
                    taint=f"device_put of {arg_results[0].taint}")
            return EvalResult()
        if resolved in TREE_MAP_NAMES:
            if node.args and self._lambda_sanitizes(node.args[0]):
                return EvalResult()
            taint = None
            for r in arg_results[1:]:
                taint = taint or r.taint
            return EvalResult(taint=taint)
        if resolved in TREE_UNFLATTEN_NAMES:
            if len(arg_results) > 1:
                return EvalResult(taint=arg_results[1].taint)
            return EvalResult()
        if resolved in TREE_FLATTEN_NAMES:
            if arg_results:
                return EvalResult(taint=arg_results[0].taint)
            return EvalResult()
        if resolved == "retry_call" or (isinstance(func, ast.Name)
                                        and func.id == "retry_call"):
            # retry.retry_call(f, *args): behaves as calling f.
            if node.args:
                f0 = node.args[0]
                if isinstance(f0, ast.Lambda):
                    body = self._eval(f0.body, scope, qualname)
                    return EvalResult(taint=body.taint)
                if (isinstance(f0, ast.Attribute)
                        and f0.attr in SOURCE_METHODS):
                    return EvalResult(taint=SOURCE_METHODS[f0.attr])
            return EvalResult()
        if isinstance(func, ast.Name) and func.id in _CONTAINER_CTORS:
            taint = None
            for r in arg_results:
                taint = taint or r.taint
            return EvalResult(taint=taint)
        if isinstance(func, ast.Attribute):
            base = self._eval(func.value, scope, qualname)
            if func.attr in ("append", "extend", "insert", "add", "update"):
                # container.append(tainted): the container carries it.
                tainted_arg = next(
                    (r.taint for r in arg_results if r.taint), None)
                if tainted_arg is not None:
                    parts = dotted_parts(func.value)
                    if parts:
                        scope.taint[".".join(parts)] = tainted_arg
                return EvalResult()
            if base.taint is not None:
                # A method of a tainted object returns a derived view
                # (state.replace(…), manifest.get(…)): stay tainted.
                return EvalResult(taint=base.taint)
        return EvalResult()

    def _check_donated_call(self, node: ast.Call, info: JitInfo,
                            arg_results, kw_results, scope: Scope,
                            qualname: str) -> None:
        for pos in info.donate_argnums:
            if pos < len(node.args):
                if isinstance(node.args[pos], ast.Starred):
                    continue
                r = arg_results[pos]
                if r.taint and self._once(node, f"donate{pos}"):
                    self.on_donated_taint(
                        node, f"argument {pos}", r.taint, qualname)
        for k in node.keywords:
            if k.arg in info.donate_argnames:
                r = kw_results.get(k.arg)
                if r is not None and r.taint and self._once(
                        node, f"donate_{k.arg}"):
                    self.on_donated_taint(
                        node, f"argument {k.arg!r}", r.taint, qualname)
        for pos in info.static_argnums:
            if pos < len(node.args) and isinstance(
                    node.args[pos], (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.SetComp,
                                     ast.DictComp)):
                if self._once(node, f"static{pos}"):
                    self.on_unhashable_static(node, f"argument {pos}",
                                              qualname)
        for k in node.keywords:
            if k.arg in info.static_argnames and isinstance(
                    k.value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                              ast.SetComp, ast.DictComp)):
                if self._once(node, f"static_{k.arg}"):
                    self.on_unhashable_static(node, f"argument {k.arg!r}",
                                              qualname)


class _Summary:
    """Return-value classification of a module-local helper function."""

    def __init__(self, info: JitInfo):
        self.info = info
