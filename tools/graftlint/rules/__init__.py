"""graftlint rule registry."""

from __future__ import annotations

from typing import Dict, List, Optional, Type

from graftlint.engine import Rule
from graftlint.rules.census import CompileSiteCensusRule
from graftlint.rules.donation import DonationAliasingRule
from graftlint.rules.nosync import NoSyncRule
from graftlint.rules.tracer import TracerLeakRule

ALL_RULES: Dict[str, Type[Rule]] = {
    r.name: r for r in (
        DonationAliasingRule,
        NoSyncRule,
        TracerLeakRule,
        CompileSiteCensusRule,
    )
}


def make_rules(names: Optional[List[str]] = None,
               severities: Optional[Dict[str, str]] = None) -> List[Rule]:
    """Instantiate rules by name (all by default), with optional
    per-rule severity overrides (`{"tracer-leak": "warning"}`)."""
    severities = severities or {}
    unknown = set(names or ()) - set(ALL_RULES)
    if unknown:
        raise ValueError(
            f"unknown rule(s) {sorted(unknown)}; "
            f"available: {sorted(ALL_RULES)}")
    chosen = names if names is not None else list(ALL_RULES)
    return [ALL_RULES[n](severity=severities.get(n)) for n in chosen]
