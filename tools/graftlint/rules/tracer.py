"""tracer-leak / retrace-hazard rule.

Applies to functions with direct evidence of being traced — decorated
with `@jax.jit` / `@partial(jax.jit, …)`, or passed by name to a
`jax.jit(…)` call in the same module (the FlowWalker reports both).
Traced parameters are the function's parameters minus
`static_argnums`/`static_argnames`.

Checks, in decreasing severity:

- **tracer-leak** (error): host control flow on a traced value — an
  `if`/`while`/`assert` test whose truthiness depends on a traced
  parameter (`if x:` raises TracerBoolConversionError at trace time);
  `float()`/`int()`/`bool()`/`complex()` of a traced value; `.item()` /
  `.tolist()` on one. Static inspections are exempt: any use reaching
  the test only through `.shape`/`.ndim`/`.dtype`/`.size`/`.aval`/
  `.sharding`, through `len()`/`isinstance()`/`hasattr()`, or under an
  `is`/`is not` comparison stays host-side by construction.
- **numpy-on-tracer** (error): a `np.*` call with a traced argument —
  NumPy either raises a ConcretizationError or silently pulls the value
  to host, serializing the dispatch either way.
- **retrace** (warning): `jax.jit` constructed inside a loop body (a
  fresh jit object per iteration throws away the trace cache —
  including the closure-capture variant, where a lambda or nested def
  re-created per iteration bakes loop-varying Python scalars into each
  new program), and list/dict/set literals passed at
  `static_argnums`/`static_argnames` positions (unhashable — TypeError
  at call time). These are flagged where the walker sees the call.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from graftlint.astutil import FlowWalker, JitInfo, resolve
from graftlint.engine import Finding, Module, Rule

# Attribute accesses on a traced value that stay host-side (static
# metadata, not the value).
SAFE_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding",
              "weak_type", "nbytes", "itemsize"}
SAFE_CALLS = {"len", "isinstance", "hasattr", "getattr", "type", "id",
              "repr", "str"}
CONCRETIZING_CASTS = {"float", "int", "bool", "complex"}
CONCRETIZING_METHODS = {"item", "tolist", "__bool__", "__float__",
                        "__int__"}


def _param_names(funcdef) -> List[str]:
    a = funcdef.args
    return ([p.arg for p in a.posonlyargs] + [p.arg for p in a.args]
            + [p.arg for p in a.kwonlyargs]
            + ([a.vararg.arg] if a.vararg else [])
            + ([a.kwarg.arg] if a.kwarg else []))


def _traced_params(funcdef, info: JitInfo) -> Set[str]:
    pos = [p.arg for p in funcdef.args.posonlyargs] + [
        p.arg for p in funcdef.args.args]
    static = set(info.static_argnames)
    for i in info.static_argnums:
        if i < len(pos):
            static.add(pos[i])
    return {p for p in _param_names(funcdef) if p not in static}


class _TraceScan:
    """Walk one jitted function body looking for concretizations of its
    traced parameters."""

    def __init__(self, module: Module, rule: "TracerLeakRule",
                 funcdef, info: JitInfo, qualname: str):
        self.module = module
        self.rule = rule
        self.funcdef = funcdef
        self.qualname = qualname
        self.traced = _traced_params(funcdef, info)
        self.findings: List[Finding] = []
        self._occ: dict = {}

    # -- traced-value reachability ---------------------------------------
    def _is_concretizing_use(self, node: ast.AST) -> bool:
        """Does evaluating `node`'s truthiness/value concretize a traced
        parameter? True iff a traced Name appears NOT protected by a
        static-metadata access."""
        return self._scan_expr(node)

    def _scan_expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Attribute):
            if node.attr in SAFE_ATTRS:
                return False
            return self._scan_expr(node.value)
        if isinstance(node, ast.Call):
            fname = node.func.id if isinstance(node.func, ast.Name) else None
            if fname in SAFE_CALLS:
                return False
            if isinstance(node.func, ast.Attribute):
                # x.astype(...), jnp.sum(x): traced-in, traced-out — the
                # call RESULT is a tracer, so the truthiness hazard
                # remains; keep scanning into receiver and args.
                pass
            return any(self._scan_expr(c) for c in ast.iter_child_nodes(node)
                       if isinstance(c, ast.expr))
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False  # identity checks never concretize
            return (self._scan_expr(node.left)
                    or any(self._scan_expr(c) for c in node.comparators))
        if isinstance(node, ast.Subscript):
            # x[i] of a traced x is a tracer; shape tuples are not.
            return self._scan_expr(node.value) or self._scan_expr(node.slice)
        return any(self._scan_expr(c) for c in ast.iter_child_nodes(node)
                   if isinstance(c, ast.expr))

    def _emit(self, node: ast.AST, kind: str, message: str,
              severity: Optional[str] = None) -> None:
        key = f"tracer:{self.qualname}:{kind}"
        k = self._occ[key] = self._occ.get(key, 0) + 1
        self.findings.append(Finding(
            self.rule.name, self.module.rel, node.lineno,
            severity or self.rule.severity, message,
            fingerprint=f"{key}#{k}"))

    # -- the walk ---------------------------------------------------------
    def run(self) -> List[Finding]:
        shadowed = self.traced.copy()
        for node in ast.walk(self.funcdef):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not self.funcdef:
                # Nested defs rebinding a traced name would need scope
                # tracking; skip their parameter names conservatively.
                for p in (_param_names(node)
                          if not isinstance(node, ast.Lambda)
                          else [a.arg for a in node.args.args]):
                    shadowed.discard(p)
        self.traced = shadowed
        for node in ast.walk(self.funcdef):
            if isinstance(node, (ast.If, ast.While)):
                if self._is_concretizing_use(node.test):
                    self._emit(
                        node.test, "control-flow",
                        f"host control flow on a traced value in jitted "
                        f"`{self.qualname}` — `"
                        f"{self.module.segment(node.test, 60)}` forces "
                        f"concretization at trace time (use lax.cond/"
                        f"jnp.where, or mark the argument static)")
            elif isinstance(node, ast.Assert):
                if self._is_concretizing_use(node.test):
                    self._emit(
                        node.test, "control-flow",
                        f"assert on a traced value in jitted "
                        f"`{self.qualname}` (use checkify or a static "
                        f"precondition)")
            elif isinstance(node, ast.Call):
                fname = (node.func.id
                         if isinstance(node.func, ast.Name) else None)
                if fname in CONCRETIZING_CASTS and node.args:
                    if self._scan_expr(node.args[0]):
                        self._emit(
                            node, "cast",
                            f"`{fname}()` of a traced value in jitted "
                            f"`{self.qualname}` concretizes at trace "
                            f"time — every step pays a host sync (keep "
                            f"it a jnp scalar or mark the arg static)")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in CONCRETIZING_METHODS):
                    if self._scan_expr(node.func.value):
                        self._emit(
                            node, "item",
                            f"`.{node.func.attr}()` on a traced value in "
                            f"jitted `{self.qualname}` — host "
                            f"concretization inside the traced program")
                else:
                    resolved = resolve(node.func, self.module.imports)
                    if (resolved and resolved.split(".")[0] == "numpy"
                            and any(self._scan_expr(a)
                                    for a in node.args)):
                        self._emit(
                            node, "numpy",
                            f"numpy call `{resolved}` on a traced value "
                            f"in jitted `{self.qualname}` — np.* "
                            f"concretizes tracers (use jnp.*)")
        return self.findings


class _TracerWalker(FlowWalker):
    def __init__(self, module: Module, rule: "TracerLeakRule"):
        super().__init__(module.tree, module.imports)
        self.module = module
        self.rule = rule
        self.findings: List[Finding] = []
        self._occ: dict = {}

    def _emit(self, node, kind, qualname, message, severity) -> None:
        key = f"tracer:{qualname or '<module>'}:{kind}"
        k = self._occ[key] = self._occ.get(key, 0) + 1
        self.findings.append(Finding(
            self.rule.name, self.module.rel, node.lineno, severity,
            message, fingerprint=f"{key}#{k}"))

    def on_jitted_def(self, funcdef, info: JitInfo, qualname: str) -> None:
        fq = (f"{qualname}.{funcdef.name}"
              if qualname and not qualname.endswith(funcdef.name)
              else (qualname or funcdef.name))
        self.findings.extend(
            _TraceScan(self.module, self.rule, funcdef, info, fq).run())

    def on_jit_in_loop(self, node, qualname: str) -> None:
        self._emit(
            node, "jit-in-loop", qualname,
            f"jax.jit constructed inside a loop body in "
            f"`{qualname or '<module>'}` — a fresh jit object per "
            f"iteration retraces every time (hoist the jit, or close "
            f"over loop state explicitly)", "warning")

    def on_unhashable_static(self, node, where: str, qualname: str) -> None:
        self._emit(
            node, "unhashable-static", qualname,
            f"unhashable literal passed at static {where} in "
            f"`{qualname or '<module>'}` — static args must hash "
            f"(tuple it)", self.rule.severity)


class TracerLeakRule(Rule):
    name = "tracer-leak"
    description = ("host control flow / concretization on traced values "
                   "inside jitted functions; jit-in-loop retrace hazards; "
                   "unhashable static args")
    default_severity = "error"

    def check(self, module: Module) -> List[Finding]:
        walker = _TracerWalker(module, self)
        walker.run()
        return walker.findings
