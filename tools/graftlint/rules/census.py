"""compile-site census: every program-construction site in the tree.

ROADMAP item 5 wants one AOT program registry keyed by (module, shape
bucket, dtype, mesh, impl flags); before it can be built, someone has
to know where programs are constructed TODAY. This rule enumerates
every `jax.jit` / `partial(jax.jit, …)` / `.lower(…)` / `.compile()` /
`shard_map` construction site — recognized semantically through the
FlowWalker (so `re.compile` and `str.lower` never count, while
`lower_forward(…).compile()` does, via the module-local helper
summary) — and records its keying evidence: donated/static argument
specs and the source text of the call's arguments and keywords, which
is where the shape bucket, dtype, mesh, and impl flags live at today's
ad-hoc sites.

Two outputs:

- The machine inventory (`inventory()` / `--census-json`), committed as
  docs/compile_sites_r01.json to seed the registry.
- One **warning** finding per site not covered by the registry
  allowlist (tools/graftlint/registry_allowlist.json — intentionally
  empty until the registry exists). Warnings, not errors, for now: the
  current sites are grandfathered in graftlint_baseline.json, so the
  effect is purely prospective — a NEW compile site fails CI until it
  is either registered (once the registry lands) or consciously
  baselined with a reason. That is the discipline ROADMAP item 5 needs
  to stop the site count multiplying under it.
"""

from __future__ import annotations

import ast
import json
import os
from typing import Dict, List, Optional

from graftlint.astutil import FlowWalker, JitInfo
from graftlint.engine import Finding, Module, Rule

ALLOWLIST_REL = os.path.join("tools", "graftlint",
                             "registry_allowlist.json")


def load_allowlist(repo: str) -> set:
    """Site keys (`path::kind::enclosing#occ`) the future AOT program
    registry owns. Empty until ROADMAP item 5 builds it."""
    path = os.path.join(repo, ALLOWLIST_REL)
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        data = json.load(f)
    return set(data.get("sites", []))


class _CensusWalker(FlowWalker):
    def __init__(self, module: Module, rule: "CompileSiteCensusRule"):
        super().__init__(module.tree, module.imports)
        self.module = module
        self.rule = rule
        self.sites: List[dict] = []
        self._occ: Dict[str, int] = {}

    def on_compile_site(self, kind: str, node: ast.AST,
                        info: Optional[JitInfo], qualname: str) -> None:
        enclosing = qualname or "<module>"
        okey = f"{kind}:{enclosing}"
        occ = self._occ[okey] = self._occ.get(okey, 0) + 1
        site = {
            "path": self.module.rel,
            "line": node.lineno,
            "kind": kind,
            "enclosing": enclosing,
            "occurrence": occ,
            "call": self.module.segment(node, limit=200),
        }
        if info is not None:
            if info.donate_argnums:
                site["donate_argnums"] = list(info.donate_argnums)
            if info.donate_argnames:
                site["donate_argnames"] = list(info.donate_argnames)
            if info.static_argnums:
                site["static_argnums"] = list(info.static_argnums)
            if info.static_argnames:
                site["static_argnames"] = list(info.static_argnames)
        if isinstance(node, ast.Call):
            args = [self.module.segment(a, limit=60) for a in node.args]
            if args:
                site["args"] = args
            kw = {k.arg: self.module.segment(k.value, limit=60)
                  for k in node.keywords if k.arg}
            if kw:
                site["keywords"] = kw
        self.sites.append(site)


def site_key(site: dict) -> str:
    return (f"{site['path']}::{site['kind']}::{site['enclosing']}"
            f"#{site['occurrence']}")


class CompileSiteCensusRule(Rule):
    name = "compile-site-census"
    description = ("inventory of jit/lower/compile/shard_map construction "
                   "sites; sites outside the AOT registry allowlist warn")
    default_severity = "warning"

    def __init__(self, severity: Optional[str] = None):
        super().__init__(severity)
        self.sites: List[dict] = []
        self._allowlist: Optional[set] = None

    def check(self, module: Module) -> List[Finding]:
        if self._allowlist is None:
            self._allowlist = load_allowlist(module.repo)
        walker = _CensusWalker(module, self)
        walker.run()
        self.sites.extend(walker.sites)
        findings = []
        for site in walker.sites:
            key = site_key(site)
            if key in self._allowlist:
                continue
            findings.append(Finding(
                self.name, module.rel, site["line"], self.severity,
                f"{site['kind']} construction site in "
                f"`{site['enclosing']}` is outside the AOT program-"
                f"registry allowlist (ROADMAP item 5): `{site['call'][:80]}`"
                f" — register it, or baseline with a reason",
                fingerprint=(f"census:{site['kind']}:{site['enclosing']}"
                             f"#{site['occurrence']}")))
        return findings

    def inventory(self) -> dict:
        kinds: Dict[str, int] = {}
        for s in self.sites:
            kinds[s["kind"]] = kinds.get(s["kind"], 0) + 1
        return {
            "tool": "graftlint",
            "rule": self.name,
            "n_sites": len(self.sites),
            "by_kind": kinds,
            "sites": sorted(self.sites,
                            key=lambda s: (s["path"], s["line"])),
        }
