"""no-sync rule: the hot path stays asynchronous — now on the AST.

Semantics are tools/check_no_sync.py's (that script is a thin wrapper
over this module since graftlint landed), with its token-scanner blind
spots fixed:

1. `block_until_ready` is forbidden everywhere in the hot-path table —
   as a method (`x.block_until_ready()`), the top-level function
   (`jax.block_until_ready(x)`), an aliased import
   (`from jax import block_until_ready as wait`), or a bare reference
   (`f = x.block_until_ready`). It is both a sync AND a lie through the
   remote-TPU tunnel (docs/TPU_RUNBOOK.md ground rule 4).
2. `device_get` is forbidden except on lines carrying a
   `sanctioned-fetch` marker comment, and only in files whose table
   entry allows sanctioned fetches at all. Aliased imports
   (`from jax import device_get as g`) are resolved and flagged — the
   token scanner's known false-negative class. Names inside string
   literals and comments never flag — its false-positive class (the AST
   has no string-literal identifiers by construction).

The hot-path table (files + directories with per-entry sanction
policy) lives here, moved verbatim from check_no_sync.py — one source
of truth for the wrapper, this rule, and the tier-1 test.
"""

from __future__ import annotations

import ast
import io
import os
import tokenize
from typing import List, Optional, Tuple

from graftlint import astutil
from graftlint.engine import Finding, Module, Rule

FORBIDDEN_ALWAYS = ("block_until_ready",)
FORBIDDEN_UNSANCTIONED = ("device_get",)
SANCTION_MARKER = "sanctioned-fetch"

# (path, allow_sanctioned_fetches)
HOT_PATH_FILES: List[Tuple[str, bool]] = [
    ("cyclegan_tpu/train/loop.py", True),
    # The epoch-services worker exists to take host I/O OFF the dispatch
    # path; a device fetch on it would re-serialize the boundary it
    # overlaps (callers hand it already-fetched host copies).
    ("cyclegan_tpu/utils/services.py", False),
    # Both gradient engines (combined jax.grad and the fusedprop vjp
    # path) build traced-only code; any host fetch here would run once
    # per step inside the dispatch chain. Zero sanctioned sites.
    ("cyclegan_tpu/train/steps.py", False),
    # Elastic recovery: the module's ONE sanctioned site class is the
    # restore-time gather in reshard_to_plan (before any dispatch
    # exists); the breaker/emergency-save paths that run DURING the
    # loop must stay fetch-free. Overrides the resil/ directory default
    # below (explicit file entries win over directory expansion).
    ("cyclegan_tpu/resil/elastic.py", True),
    # Collective-probe microbench: its WHOLE JOB is to time fenced
    # collectives, so its device_get fences are sanctioned — but it runs
    # only at startup and epoch boundaries, never under an open
    # StepClock. Overrides the obs/ directory's zero-fetch default.
    ("cyclegan_tpu/obs/collective_probe.py", True),
]

# Directories whose EVERY .py file is hot-path. Scanned as a directory
# (not a file list) so a new module is covered the day it lands:
# - obs (no sanctioned sites): telemetry only timestamps fetches the
#   loop performs.
# - ops/pallas (no sanctioned sites): kernel wrappers run INSIDE the
#   fused train step — a host sync there would serialize every dispatch.
# - serve / serve/fleet (sanctioned sites allowed): the pipeline's one
#   deferred D2H per flush lives on the completer/replica thread behind
#   a marker; anything else would re-serialize the pipeline. Listed
#   separately because directory scans are deliberately non-recursive.
# - resil (no sanctioned sites by default): recovery machinery is pure
#   host-side orchestration; elastic.py alone carries a file entry.
# - domains (no sanctioned sites): the registry is pure data, and the
#   transfer freeze mask runs INSIDE the jitted step (trace-time tree
#   surgery) — a host fetch there would sync every dispatch; the
#   parent restore rides Checkpointer's already-policed path.
HOT_PATH_DIRS: List[Tuple[str, bool]] = [
    ("cyclegan_tpu/domains", False),
    ("cyclegan_tpu/obs", False),
    ("cyclegan_tpu/ops/pallas", False),
    ("cyclegan_tpu/serve", True),
    ("cyclegan_tpu/serve/fleet", True),
    ("cyclegan_tpu/resil", False),
]


def hot_path_entries(repo: str) -> List[Tuple[str, bool]]:
    """The static file list plus every .py under the hot-path dirs,
    deduplicated with explicit HOT_PATH_FILES entries taking precedence
    over directory expansion (a file may need a different sanction
    policy than its directory's default). A missing directory is
    reported as a missing file entry (the check must fail loudly, not
    silently shrink)."""
    policy = {rel: allow for rel, allow in HOT_PATH_FILES}
    order = [rel for rel, _ in HOT_PATH_FILES]
    for rel, allow in HOT_PATH_DIRS:
        d = os.path.join(repo, rel)
        if not os.path.isdir(d):
            if rel not in policy:
                policy[rel] = allow
                order.append(rel)
            continue
        for name in sorted(os.listdir(d)):
            if not name.endswith(".py"):
                continue
            sub = os.path.join(rel, name)
            if sub not in policy:
                policy[sub] = allow
                order.append(sub)
    return [(rel, policy[rel]) for rel in order]


# --------------------------------------------------------------- core scan


def _ast_hits(source: str) -> Optional[List[Tuple[int, str]]]:
    """[(line, token)] for every real reference to a forbidden name;
    None if the file does not parse (caller falls back to tokens).

    A "reference" is an Attribute access with the forbidden name, or a
    Name that an import alias resolves to `jax.<forbidden>` — never a
    string literal, comment, or unrelated identifier that merely
    contains the token as a substring.
    """
    try:
        tree = ast.parse(source)
    except (SyntaxError, ValueError):
        return None
    imports = astutil.build_import_map(tree)
    watched = FORBIDDEN_ALWAYS + FORBIDDEN_UNSANCTIONED
    hits: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in watched:
            hits.append((node.lineno, node.attr))
        elif isinstance(node, ast.Name):
            resolved = imports.get(node.id)
            if resolved and "." in resolved:
                tail = resolved.rsplit(".", 1)[1]
                if tail in watched and resolved.startswith("jax"):
                    hits.append((node.lineno, tail))
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            # `from jax import device_get` puts the name in scope even
            # unaliased; the import line itself is the first reference.
            for a in node.names:
                if a.name in watched and isinstance(node, ast.ImportFrom):
                    hits.append((node.lineno, a.name))
    return hits


def _token_hits(source: str) -> List[Tuple[int, str]]:
    """Fallback for unparseable files: the original token scan
    (conservative — flags any code-token mention, still never strings
    or comments when the tokenizer survives, raw lines otherwise)."""
    lines: dict = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type in (tokenize.COMMENT, tokenize.STRING, tokenize.NL,
                            tokenize.NEWLINE, tokenize.INDENT,
                            tokenize.DEDENT):
                continue
            row = tok.start[0]
            lines[row] = lines.get(row, "") + " " + tok.string
    except (tokenize.TokenError, IndentationError):
        for i, raw in enumerate(source.splitlines(), 1):
            lines[i] = raw
    hits: List[Tuple[int, str]] = []
    for row, code in sorted(lines.items()):
        for tok in FORBIDDEN_ALWAYS + FORBIDDEN_UNSANCTIONED:
            if tok in code:
                hits.append((row, tok))
    return hits


def scan_source(source: str, allow_sanctioned: bool) -> List[Tuple[int, str, str]]:
    """-> [(line, token, verdict-message)] for every violation.

    Deduplicated per (line, token) — the historical per-line verdict
    granularity check_no_sync.py's callers (and its tier-1 test) pin.
    """
    hits = _ast_hits(source)
    if hits is None:
        hits = _token_hits(source)
    raw_lines = source.splitlines()
    seen = set()
    out: List[Tuple[int, str, str]] = []
    for row, tok in sorted(hits):
        if (row, tok) in seen:
            continue
        seen.add((row, tok))
        raw = raw_lines[row - 1] if row <= len(raw_lines) else ""
        if tok in FORBIDDEN_ALWAYS:
            out.append((row, tok, f"forbidden sync `{tok}` in the hot path"))
            continue
        if allow_sanctioned and SANCTION_MARKER in raw:
            continue
        where = ("missing `# sanctioned-fetch` marker"
                 if allow_sanctioned
                 else "no sanctioned sites exist in obs/")
        out.append((row, tok,
                    f"`{tok}` outside the sanctioned fetch window ({where})"))
    return out


def check_file_violations(path: str, allow_sanctioned: bool) -> List[str]:
    """check_no_sync.py's `check_file` body: message strings with the
    historical format, for byte-compatible wrapper output."""
    with open(path) as f:
        source = f.read()
    return [f"{path}:{row}: {msg}"
            for row, _tok, msg in scan_source(source, allow_sanctioned)]


def run_check(repo: str) -> List[str]:
    """check_no_sync.py's `run_check` body (historical message format)."""
    violations: List[str] = []
    for rel, allow in hot_path_entries(repo):
        path = os.path.join(repo, rel)
        if not os.path.exists(path):
            violations.append(f"{rel}: hot-path file missing")
            continue
        violations.extend(check_file_violations(path, allow))
    return violations


# ------------------------------------------------------------- the rule


class NoSyncRule(Rule):
    name = "no-sync"
    description = ("hot-path files stay asynchronous: block_until_ready "
                   "forbidden, device_get only at sanctioned-fetch sites")
    default_severity = "error"

    def __init__(self, severity: Optional[str] = None):
        super().__init__(severity)
        self._policy_cache: Optional[dict] = None

    def _policy(self, repo: str) -> dict:
        if self._policy_cache is None:
            self._policy_cache = dict(hot_path_entries(repo))
        return self._policy_cache

    def check(self, module: Module) -> List[Finding]:
        policy = self._policy(module.repo)
        if module.rel not in policy:
            return []
        allow = policy[module.rel]
        findings = []
        occ: dict = {}
        for row, tok, msg in scan_source(module.source, allow):
            k = occ[tok] = occ.get(tok, 0) + 1
            findings.append(Finding(
                self.name, module.rel, row, self.severity, msg,
                fingerprint=f"no-sync:{tok}#{k}"))
        return findings

    def finalize(self, repo: str) -> List[Finding]:
        out = []
        for rel, _allow in hot_path_entries(repo):
            if not os.path.exists(os.path.join(repo, rel)):
                out.append(Finding(
                    self.name, rel, 0, self.severity,
                    "hot-path file missing",
                    fingerprint="no-sync:missing"))
        return out
