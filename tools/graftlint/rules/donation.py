"""donation-aliasing rule: XLA must own every buffer it is donated.

The two worst bugs in this codebase's history were the same static
pattern ("malloc(): largebin double linked list corrupted"):

- PR-8: orbax/tensorstore-restored checkpoint state reached the train
  step — which donates its state argument — without a deep copy.
  Donating a buffer tensorstore still manages let XLA write into (and
  free) memory it did not own: every post-resume save was NaN-corrupt
  and the process intermittently died in glibc heap asserts. The fix is
  checkpoint._rebuffer.
- PR-10: the elastic reshard path did `device_get` → `device_put` and
  handed the placed leaves to the donating step. On CPU BOTH hops can
  be zero-copy, so the "placed" array aliased the restored buffer —
  the identical corruption, one abstraction higher. The fix routes
  every leaf through `jnp.copy`.

This rule is the dataflow generalization: an intraprocedural pass
(astutil.FlowWalker) tracks values originating from checkpoint
restores, `np.asarray`/`np.frombuffer` host buffers, and
`jax.device_get` gathers — through assignments, containers,
tree flatten/unflatten, `device_put`, and method derivations — and
flags any such value reaching an argument position its callee donates
(`donate_argnums`/`donate_argnames` on `jax.jit`, through
`.lower().compile()` chains and module-local helper summaries), unless
it passed through a sanctioned re-buffering op (`jnp.copy` /
`_rebuffer`), which launders the taint by construction.

Both historical patterns are pinned pre-fix in
tests/data/lint_corpus/; the post-fix shapes in the live tree analyze
clean. Scope is intraprocedural by design — unknown calls launder
taint (precision over recall), and cross-module flows are the chaos
drills' job, not this rule's.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from graftlint.astutil import FlowWalker, JitInfo
from graftlint.engine import Finding, Module, Rule


class _DonationWalker(FlowWalker):
    def __init__(self, module: Module, rule: "DonationAliasingRule"):
        super().__init__(module.tree, module.imports)
        self.module = module
        self.rule = rule
        self.findings: List[Finding] = []
        self._occ: dict = {}

    def on_donated_taint(self, node: ast.Call, where: str, origin: str,
                         qualname: str) -> None:
        callee = self.module.segment(node.func, limit=60)
        key = f"donation:{qualname or '<module>'}:{where}"
        k = self._occ[key] = self._occ.get(key, 0) + 1
        self.findings.append(Finding(
            self.rule.name, self.module.rel, node.lineno,
            self.rule.severity,
            f"value from {origin} reaches donated {where} of `{callee}` "
            f"without re-buffering (route it through jnp.copy or "
            f"checkpoint._rebuffer — donating a buffer XLA does not own "
            f"corrupts the heap; see docs/DESIGN.md §Static discipline)",
            fingerprint=f"{key}#{k}"))


class DonationAliasingRule(Rule):
    name = "donation-aliasing"
    description = ("host-owned / possibly-aliased buffers must not reach "
                   "donate_argnums call sites without jnp.copy/_rebuffer")
    default_severity = "error"

    def check(self, module: Module) -> List[Finding]:
        walker = _DonationWalker(module, self)
        walker.run()
        return walker.findings
