"""`python tools/graftlint` entry point.

Running a directory puts the directory ITSELF on sys.path[0]; the
package imports (`graftlint.engine` …) need its parent (tools/) there
instead.
"""

import os
import sys

_TOOLS = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

from graftlint.cli import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
