"""graftlint rule engine: scan set, suppressions, baseline, verdicts.

The contract every rule plugs into:

- A rule produces `Finding`s with a **fingerprint** — a stable identity
  that deliberately excludes line numbers (rule-specific: enclosing
  qualname + kind + occurrence index), so the committed baseline
  survives unrelated edits shifting lines.

- `# graftlint: disable=<rule>[,<rule>…] -- <reason>` on the finding's
  line suppresses it. The reason is REQUIRED: a disable comment without
  one does not suppress, and is itself reported (rule `suppression`).
  `disable=all` suppresses every rule on the line.

- `graftlint_baseline.json` at the repo root grandfathers pre-existing
  findings: entries are `{rule, path, fingerprint, reason}` (reason
  required here too). A matched finding is demoted to "baselined"; an
  entry matching nothing is reported as stale (informational — stale
  entries never fail the run, so deleting dead code never breaks CI).

- Exit semantics: any live (unsuppressed, unbaselined) finding of
  severity `error` or `warning` fails; `info` findings never do.

Output modes match the repo's tooling contract: human text to stdout,
or `--json` as ONE JSON line (the bench/chaos_drill convention).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from graftlint import astutil

# Scanned roots, repo-relative (ISSUE 11: every package directory plus
# the top-level entry points). tests/ is excluded on purpose — the lint
# corpus under tests/data/ reproduces the historical bugs and would
# light up any scan that included it.
SCAN_TARGETS: Tuple[str, ...] = (
    "cyclegan_tpu",
    "tools",
    "bench.py",
    "bench_scaling.py",
    "bench_serve.py",
    "main.py",
    "translate.py",
    "scaling_model.py",
    "__graft_entry__.py",
)

SEVERITIES = ("error", "warning", "info")

BASELINE_NAME = "graftlint_baseline.json"

_DISABLE_RE = re.compile(
    r"graftlint:\s*disable=([A-Za-z0-9_,\-]+)(?:\s*--\s*(\S.*))?")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str        # repo-relative
    line: int
    severity: str    # "error" | "warning" | "info"
    message: str
    fingerprint: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.severity}: "
                f"[{self.rule}] {self.message}")


class Module:
    """One parsed scan unit handed to every rule."""

    def __init__(self, repo: str, rel: str, source: str):
        self.repo = repo
        self.rel = rel
        self.path = os.path.join(repo, rel)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)  # SyntaxError handled by caller
        self.imports = astutil.build_import_map(self.tree)
        self.comments = astutil.comment_map(source)

    def raw_line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def segment(self, node: ast.AST, limit: int = 160) -> str:
        try:
            text = ast.get_source_segment(self.source, node) or ""
        except Exception:
            text = ""
        text = " ".join(text.split())
        return text[:limit] + ("…" if len(text) > limit else "")


class Rule:
    """Base class. `name` is the id used in disable= comments and the
    baseline; `default_severity` is what findings carry unless the rule
    (or a CLI override) says otherwise."""

    name: str = ""
    description: str = ""
    default_severity: str = "error"

    def __init__(self, severity: Optional[str] = None):
        self.severity = severity or self.default_severity

    def check(self, module: Module) -> List[Finding]:
        raise NotImplementedError

    def finalize(self, repo: str) -> List[Finding]:
        """Called once after every module; for whole-repo rules."""
        return []


# ------------------------------------------------------------ scan set


def iter_scan_files(repo: str,
                    targets: Sequence[str] = SCAN_TARGETS) -> List[str]:
    """Repo-relative .py files under the scan targets, sorted, test and
    cache dirs excluded."""
    out: List[str] = []
    for target in targets:
        abs_t = os.path.join(repo, target)
        if os.path.isfile(abs_t) and target.endswith(".py"):
            out.append(target)
            continue
        if not os.path.isdir(abs_t):
            continue
        for root, dirs, files in os.walk(abs_t):
            dirs[:] = sorted(d for d in dirs
                             if d not in ("__pycache__", ".git", "tests"))
            for name in sorted(files):
                if name.endswith(".py"):
                    out.append(os.path.relpath(os.path.join(root, name),
                                               repo))
    return sorted(set(out))


# -------------------------------------------------------- suppressions


def parse_suppressions(
        comments: Dict[int, str]) -> Tuple[Dict[int, set], List[Tuple[int, str]]]:
    """-> ({line: {rule, …}}, [(line, rules-str) for reasonless disables]).

    A disable without `-- <reason>` suppresses nothing and is reported.
    """
    active: Dict[int, set] = {}
    bad: List[Tuple[int, str]] = []
    for line, text in comments.items():
        m = _DISABLE_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        if not m.group(2):
            bad.append((line, ",".join(sorted(rules))))
            continue
        active.setdefault(line, set()).update(rules)
    return active, bad


# ------------------------------------------------------------ baseline


def load_baseline(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        data = json.load(f)
    entries = data.get("entries", []) if isinstance(data, dict) else data
    out = []
    for e in entries:
        if not isinstance(e, dict):
            continue
        if not all(k in e for k in ("rule", "path", "fingerprint")):
            continue
        out.append(e)
    return out


def write_baseline(path: str, findings: Sequence[Finding],
                   reason: str) -> None:
    """Grandfather `findings` (used by --update-baseline)."""
    entries = [
        {"rule": f.rule, "path": f.path, "fingerprint": f.fingerprint,
         "reason": reason, "severity": f.severity, "message": f.message}
        for f in sorted(findings, key=lambda f: (f.path, f.rule, f.line))
    ]
    with open(path, "w") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=2)
        f.write("\n")


# ---------------------------------------------------------------- run


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]           # live (fail CI if error/warning)
    suppressed: List[Finding]
    baselined: List[Finding]
    stale_baseline: List[dict]
    files_scanned: int
    rules_run: List[str]

    @property
    def ok(self) -> bool:
        return not any(f.severity in ("error", "warning")
                       for f in self.findings)

    def as_json_line(self) -> str:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.severity] = counts.get(f.severity, 0) + 1
        return json.dumps({
            "tool": "graftlint",
            "ok": self.ok,
            "files_scanned": self.files_scanned,
            "rules": self.rules_run,
            "counts": counts,
            "n_suppressed": len(self.suppressed),
            "n_baselined": len(self.baselined),
            "n_stale_baseline": len(self.stale_baseline),
            "findings": [f.as_dict() for f in self.findings],
        }, sort_keys=True)

    def render_text(self) -> str:
        out = []
        for f in sorted(self.findings,
                        key=lambda f: (f.path, f.line, f.rule)):
            out.append(f.render())
        n_fail = sum(1 for f in self.findings
                     if f.severity in ("error", "warning"))
        verdict = "PASSED" if self.ok else "FAILED"
        out.append(
            f"graftlint {verdict}: {self.files_scanned} files, "
            f"{len(self.rules_run)} rules "
            f"({', '.join(self.rules_run)}); "
            f"{n_fail} finding(s), {len(self.suppressed)} suppressed, "
            f"{len(self.baselined)} baselined"
        )
        if self.stale_baseline:
            out.append(
                f"  note: {len(self.stale_baseline)} stale baseline "
                f"entr{'y' if len(self.stale_baseline) == 1 else 'ies'} "
                f"(matched nothing — safe to drop):")
            for e in self.stale_baseline[:20]:
                out.append(f"    {e['path']}: [{e['rule']}] "
                           f"{e['fingerprint']}")
        return "\n".join(out)


def run(repo: str, rules: Sequence[Rule],
        files: Optional[Sequence[str]] = None,
        baseline: Optional[Sequence[dict]] = None) -> LintResult:
    repo = os.path.abspath(repo)
    rels = list(files) if files is not None else iter_scan_files(repo)
    raw: List[Finding] = []
    suppressed: List[Finding] = []
    per_file_suppressions: Dict[str, Dict[int, set]] = {}

    for rel in rels:
        path = os.path.join(repo, rel)
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                source = f.read()
            module = Module(repo, rel, source)
        except OSError as e:
            raw.append(Finding("parse", rel, 0, "error",
                               f"unreadable: {e}", "parse:unreadable"))
            continue
        except SyntaxError as e:
            raw.append(Finding("parse", rel, e.lineno or 0, "error",
                               f"syntax error: {e.msg}",
                               "parse:syntax-error"))
            continue
        except ValueError as e:  # e.g. null bytes in source
            raw.append(Finding("parse", rel, 0, "error",
                               f"unparseable: {e}", "parse:unparseable"))
            continue
        active, bad = parse_suppressions(module.comments)
        per_file_suppressions[rel] = active
        for line, rules_str in bad:
            raw.append(Finding(
                "suppression", rel, line, "error",
                f"graftlint disable={rules_str} without a reason — "
                f"suppressions require `-- <reason>` and this one "
                f"suppresses nothing",
                f"suppression:{rules_str}#{line}"))
        for rule in rules:
            raw.extend(rule.check(module))
    for rule in rules:
        raw.extend(rule.finalize(repo))

    # Apply same-line suppressions (reason already validated).
    live: List[Finding] = []
    for f in raw:
        rules_here = per_file_suppressions.get(f.path, {}).get(f.line, set())
        if f.rule != "suppression" and (
                f.rule in rules_here or "all" in rules_here):
            suppressed.append(f)
        else:
            live.append(f)

    # Apply the baseline: one entry grandfathers one finding.
    baselined: List[Finding] = []
    stale: List[dict] = []
    if baseline:
        index: Dict[Tuple[str, str, str], List[dict]] = {}
        for e in baseline:
            index.setdefault(
                (e["rule"], e["path"], e["fingerprint"]), []).append(e)
        remaining: List[Finding] = []
        for f in live:
            bucket = index.get((f.rule, f.path, f.fingerprint))
            if bucket:
                bucket.pop()
                baselined.append(f)
            else:
                remaining.append(f)
        live = remaining
        for bucket in index.values():
            stale.extend(bucket)

    return LintResult(
        findings=live, suppressed=suppressed, baselined=baselined,
        stale_baseline=stale, files_scanned=len(rels),
        rules_run=[r.name for r in rules])
