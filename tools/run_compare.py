"""Cross-run regression gate: diff two obs JSONL streams (or two
committed BENCH_r*.json records) and exit nonzero past thresholds.

    python tools/run_compare.py BASE.jsonl CAND.jsonl
    python tools/run_compare.py BENCH_r04.json BENCH_r05.json
    python tools/run_compare.py BENCH_r01.json ... BENCH_r05.json  # series

The repo already commits the artifacts a regression check needs — every
training/bench run can write a telemetry JSONL stream (cyclegan_tpu/obs)
and each bench round lands a BENCH_r*.json — but until now nothing
compared one run against another: a 20% throughput regression or a
newly-NaN'ing config would ship silently. This tool is the missing
guard, built to the same rules as tools/obs_report.py: pure stdlib (it
must run on any box the artifacts land on), unknown events ignored,
malformed lines skipped, deterministic output (sorted keys, fixed
formatting) so two invocations on the same inputs byte-match.

Axes for a stream pair (each gated by its own threshold flag):
  throughput   mean train images/sec over `epoch` events
  losses       final-epoch loss means from the last `health` event
  grad norms   per-network max-envelope over `health` events
  anomalies    `health_fault` count (plus watchdog/loop stalls, reported
               but not gated — they attribute speed, not health)
  elastic      engages when the candidate resharded or emergency-saved
               (resil/elastic.py): every emergency save must have
               committed inside its deadline, and per-epoch `step_losses`
               trajectories must match the base elementwise within
               --max_elastic_loss_diff — a resumed run that diverges
               from its uninterrupted base after the preemption seam
               FAILS, as does one whose step counts drifted (a skipped
               or repeated sample)
  domain       streams carry the run's domain pair (manifest
               config.data.domain) — a cross-domain pair SKIPs the
               training axes (horse2zebra vs monet2photo trajectories
               are not comparable), EXCEPT when the candidate is a
               transfer-onboarded run (domains/transfer.py) whose
               recorded parent_domain matches the base: then the
               transfer axis alone engages
  goodput      seconds-weighted goodput fraction from the per-epoch
               `goodput` rollups (obs/goodput.py): a candidate whose
               fraction drops more than --max_goodput_drop below the
               base wasted wall-clock somewhere (data-wait, host work,
               checkpoint barriers) even at unchanged steady-state
               img/s; SKIPs when either stream predates the ledger
  comms-census candidate-side invariant (like the serve trace-overhead
               gate): the last `comms_census` event's analytic-vs-
               compiled reconciliation error must sit inside the
               census's own tolerance (10%) — census drift means the
               model or the sharding changed silently
  train-trace  two gates (obs/train_trace.py): the interleaved
               traced-vs-untraced pair prices the epoch tracer via
               per-step wall p50 (--max_train_trace_overhead), and the
               candidate's goodput phase seconds must agree with its
               epoch span tiling within 5% of pass wall — the ledger
               and the trace fold the same StepClock numbers, so a gap
               means one of them lies
  transfer     a fine-tune (`transfer_init` in the stream) is gated
               against its parent run: final losses within
               --max_loss_increase of the parent's, epoch count at most
               --max_transfer_epoch_frac of the parent's (the onboarding
               economics the registry promises), and for encoder_freeze
               runs the frozen-trunk gradient envelope
               (health/gnorm_enc_frozen) must be exactly zero

For bench records the axis is per-config images/sec from the `all`
sweep dict (intersection of configs) plus the headline value.
Cross-platform pairs (cpu seed rounds vs the first TPU round) are
SKIPPED, not failed: the committed series legally changes platform.

bench_serve records (metric `cyclegan_serve_*`) get a serving axis:
saturated pipeline + fleet + int8-tier + int8_fused-tier images/sec
(each gated by --max_bench_drop), the fused tier's unrounded
max|int8_fused - f32| quality probe (candidate-side, gated by
--max_int8_fused_drift), the p95 latency set — low-load, saturated, the
overload sweep's per-class p95s, and the autoscale phases' per-class
p95s — gated by --max_serve_p95_increase, and the class-ordered-
shedding invariant (a candidate that sheds `interactive` while
`best_effort` goes unshed FAILS regardless of the base). When the
record carries the autoscale phase two more candidate invariants
engage, gated the same way shed ordering is: brownout ordering (a
brownout-enabled fleet that shed ANY request while degrading NONE
skipped the cheap-tier rung of the ladder) and the surge interactive
bound (interactive p95 during the surge must not exceed the fixed
fleet's overload interactive p95, and the autoscale trace must shed
zero interactive requests — the self-driving fleet has to do at least
as well as static overprovisioning). The same cross-platform SKIP
rule applies.

With 3+ files the tool runs the consecutive-pair gate over the whole
series (this is how bench.py's end-of-run hook uses it: newest
committed round vs the record just produced).

Exit codes: 0 all gates pass, 1 any gate failed, 2 usage/IO error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

PASS, FAIL, SKIP, INFO = "PASS", "FAIL", "SKIP", "INFO"


# ---------------------------------------------------------------------------
# Profile extraction
# ---------------------------------------------------------------------------


def load_profile(path: str) -> dict:
    """Read one artifact into a comparable profile. Bench records are a
    single JSON object (with `parsed`/`metric`); anything else is
    treated as a telemetry JSONL stream."""
    with open(path, "r", errors="replace") as f:
        text = f.read()
    try:
        obj = json.loads(text)
    except ValueError:
        obj = None
    if isinstance(obj, dict) and ("parsed" in obj or "metric" in obj):
        parsed = obj.get("parsed") if isinstance(obj.get("parsed"), dict) \
            else obj
        if str(parsed.get("metric", "")).startswith("cyclegan_serve"):
            return serve_profile(obj, name=os.path.basename(path))
        if str(parsed.get("metric", "")).startswith("weak_scaling"):
            return scaling_profile(obj, name=os.path.basename(path))
        return bench_profile(obj, name=os.path.basename(path))
    events = []
    skipped = 0
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if isinstance(rec, dict) and "event" in rec:
            events.append(rec)
        else:
            skipped += 1
    return stream_profile(events, skipped, name=os.path.basename(path))


def bench_profile(record: dict, name: str = "?") -> dict:
    """Profile of one bench.py summary record (BENCH_r*.json wraps the
    emitted line under `parsed`; a bare emitted line works too)."""
    parsed = record.get("parsed") if isinstance(record.get("parsed"), dict) \
        else record
    return {
        "kind": "bench",
        "name": name,
        "platform": parsed.get("platform"),
        "value": _float(parsed.get("value")),
        "config": parsed.get("config"),
        "unit": parsed.get("unit"),
        "all": {
            str(k): fv
            for k, v in (parsed.get("all") or {}).items()
            if (fv := _float(v)) is not None
        },
    }


def scaling_profile(record: dict, name: str = "?") -> dict:
    """Profile of one bench_scaling.py weak-scaling record (plain
    doubling scan or dp x spatial grid mode)."""
    parsed = record.get("parsed") if isinstance(record.get("parsed"), dict) \
        else record
    return {
        "kind": "scaling",
        "name": name,
        "value": _float(parsed.get("value")),
        "mode": parsed.get("mode") or "scan",
        "spatial_impl": parsed.get("spatial_impl"),
        "measured_devices": parsed.get("measured_devices"),
        "ips": {
            str(k): fv
            for k, v in (parsed.get("images_per_sec") or {}).items()
            if (fv := _float(v)) is not None
        },
    }


def serve_profile(record: dict, name: str = "?") -> dict:
    """Profile of one bench_serve.py summary record: the saturated
    pipeline/fleet/int8 throughputs, every p95 the record carries
    (low-load, saturated, overload per-class), and the overload shed
    census (for the class-ordering invariant)."""
    parsed = record.get("parsed") if isinstance(record.get("parsed"), dict) \
        else record
    fleet = parsed.get("fleet") if isinstance(parsed.get("fleet"), dict) \
        else {}
    int8 = parsed.get("int8") if isinstance(parsed.get("int8"), dict) \
        else {}
    int8_fused = parsed.get("int8_fused") \
        if isinstance(parsed.get("int8_fused"), dict) else {}
    overload = fleet.get("overload") \
        if isinstance(fleet.get("overload"), dict) else {}
    p95: Dict[str, float] = {}
    for label, src in (("low_load", parsed.get("latency_low_load_ms")),
                       ("saturated", parsed.get("latency_saturated_ms")),
                       ("fleet_saturated",
                        fleet.get("latency_saturated_ms"))):
        if isinstance(src, dict) and (v := _float(src.get("p95_ms"))) \
                is not None:
            p95[label] = v
    for k, v in overload.items():
        if str(k).endswith("_p95_ms") and (fv := _float(v)) is not None:
            p95[f"overload {str(k)[:-len('_p95_ms')]}"] = fv
    shed = overload.get("shed_by_class") \
        if isinstance(overload.get("shed_by_class"), dict) else {}
    # Autoscale phase (surge -> sustain -> decay through the
    # self-driving fleet): per-phase per-class p95s join the diffable
    # p95 set; the shed/degraded censuses feed the candidate-side
    # ordering invariants in _compare_serve.
    autoscale = fleet.get("autoscale") \
        if isinstance(fleet.get("autoscale"), dict) else {}
    auto_phases = autoscale.get("phases") \
        if isinstance(autoscale.get("phases"), dict) else {}
    auto_shed: Dict[str, int] = {}
    for phase, row in sorted(auto_phases.items()):
        if not isinstance(row, dict):
            continue
        for k, v in row.items():
            if str(k).endswith("_p95_ms") and (fv := _float(v)) is not None:
                p95[f"autoscale {phase} {str(k)[:-len('_p95_ms')]}"] = fv
        by_class = row.get("shed_by_class") \
            if isinstance(row.get("shed_by_class"), dict) else {}
        for k, v in by_class.items():
            if isinstance(v, (int, float)):
                auto_shed[str(k)] = auto_shed.get(str(k), 0) + int(v)
    surge = auto_phases.get("surge") \
        if isinstance(auto_phases.get("surge"), dict) else {}
    return {
        "kind": "serve",
        "name": name,
        "platform": parsed.get("platform"),
        "value": _float(parsed.get("value")),
        "unit": parsed.get("unit"),
        "config": parsed.get("config"),
        "fleet_ips": _float(fleet.get("images_per_sec")),
        "int8_ips": _float(int8.get("images_per_sec")),
        "int8_fused_ips": _float(int8_fused.get("images_per_sec")),
        "int8_fused_drift": _float(int8_fused.get("max_abs_diff_vs_base")),
        "p95_ms": p95,
        "shed_by_class": {str(k): int(v) for k, v in shed.items()
                          if isinstance(v, (int, float))},
        "has_autoscale": bool(autoscale),
        "autoscale_brownout": bool(autoscale.get("brownout_enabled")),
        "autoscale_degraded": int(autoscale.get("degraded_requests") or 0),
        "autoscale_shed_by_class": auto_shed,
        "autoscale_surge_interactive_p95": _float(
            surge.get("interactive_p95_ms")),
        "fixed_fleet_interactive_p95": _float(
            autoscale.get("fixed_fleet_interactive_p95_ms")),
        "autoscale_scale_ups": autoscale.get("scale_ups"),
        "autoscale_scale_downs": autoscale.get("scale_downs"),
        "trace_overhead_frac": _float(
            (fleet.get("trace_overhead") or {}).get("overhead_frac")
            if isinstance(fleet.get("trace_overhead"), dict) else None),
    }


def stream_profile(events: List[dict], skipped: int = 0, name: str = "?") -> dict:
    """Profile of one telemetry JSONL stream."""
    epochs = [e for e in events if e.get("event") == "epoch"]
    healths = [e for e in events if e.get("event") == "health"]
    # Domain identity (PR-13): the manifest serializes the whole Config,
    # so the run's domain key rides every stream for free; transfer
    # provenance arrives as its own `transfer_init` event. Streams that
    # predate domains profile as None and compare as before.
    domain = None
    upsample_impl = None
    manifest = next((e for e in events if e.get("event") == "manifest"),
                    None)
    if manifest is not None:
        data_cfg = ((manifest.get("config") or {}).get("data") or {})
        d = data_cfg.get("domain")
        domain = str(d) if d else None
        # Upsample tier (PR-14): dense vs zeroskip vs zeroskip_fused.
        # Streams that predate the GANAX engine profile as None and the
        # upsample axis stays out of the report.
        model_cfg = ((manifest.get("config") or {}).get("model") or {})
        u = model_cfg.get("upsample_impl")
        upsample_impl = str(u) if u else None
    transfer = next((e for e in events
                     if e.get("event") == "transfer_init"), None)
    if transfer is not None:
        transfer = {k: transfer.get(k)
                    for k in ("parent_ckpt", "parent_epoch",
                              "parent_domain", "transfer_mode", "domain")}
    faults = [e for e in events if e.get("event") == "health_fault"]
    stalls = sum(1 for e in events
                 if e.get("event") in ("stall", "loop_stall"))
    ips = [
        v for e in epochs
        if (v := _float(e.get("train_images_per_sec",
                              e.get("images_per_sec")))) is not None
    ]
    gnorm_max: Dict[str, float] = {}
    for ev in healths:
        for net, env in sorted((ev.get("gnorm") or {}).items()):
            v = _float((env or {}).get("max"))
            if v is not None:
                gnorm_max[net] = max(gnorm_max.get(net, v), v)
    final_losses: Dict[str, float] = {}
    if healths:
        final_losses = {
            str(k): fv
            for k, v in (healths[-1].get("loss") or {}).items()
            if (fv := _float(v)) is not None
        }
    fault_kinds: Dict[str, int] = {}
    for ev in faults:
        kind = str(ev.get("kind", "?"))
        fault_kinds[kind] = fault_kinds.get(kind, 0) + 1
    # Recovery profile (cyclegan_tpu/resil): how often the run had to
    # save itself, and whether a fault actually halted it. A fault the
    # rollback policy absorbed is NOT halting; one that propagated
    # (policy halt, or rollback budget exhausted -> end status
    # health_fault) is.
    n_rollbacks = sum(1 for e in events
                      if e.get("event") == "health_recovery")
    n_fleet_recoveries = sum(1 for e in events
                             if e.get("event") == "fleet_recovery")
    n_retries = sum(1 for e in events if e.get("event") == "retry")
    # Elastic profile: reshard/emergency-save counts plus the per-epoch
    # step-loss trajectories. A preempted-and-resumed stream carries the
    # seam epoch as SEGMENTS (one step_losses event per start_step);
    # concatenating them in start order rebuilds the full epoch so it
    # compares 1:1 against an uninterrupted base.
    n_reshards = sum(1 for e in events
                     if e.get("event") == "elastic_reshard")
    saves = [e for e in events if e.get("event") == "emergency_save"]
    n_uncommitted = sum(
        1 for e in saves
        if not e.get("committed")
        or (_float(e.get("margin_s")) is not None
            and float(e["margin_s"]) < 0.0))
    segments: Dict[int, Dict[int, dict]] = {}
    for e in events:
        if e.get("event") == "step_losses":
            ep = int(e.get("epoch", -1))
            # last event per (epoch, start_step) wins: a re-resumed run
            # legally re-emits the same segment
            segments.setdefault(ep, {})[int(e.get("start_step", 0))] = e
    step_losses: Dict[int, Dict[str, List[float]]] = {}
    for ep, by_start in segments.items():
        series: Dict[str, List[float]] = {}
        for start in sorted(by_start):
            for k, v in by_start[start].items():
                if str(k).startswith("loss_") and isinstance(v, list):
                    series.setdefault(str(k), []).extend(
                        float(x) for x in v)
        step_losses[ep] = series
    # Goodput ledger (PR-16): seconds-weighted goodput fraction over
    # the run's per-epoch rollups, plus the last comms census's
    # reconciliation verdict. Streams predating the ledger profile as
    # None and the axes SKIP / stay candidate-side.
    gp_num = gp_den = 0.0
    for e in events:
        if e.get("event") != "goodput":
            continue
        frac = _float(e.get("goodput_fraction"))
        dur = _float(e.get("elapse_s"))
        if frac is not None and dur:
            gp_num += frac * dur
            gp_den += dur
    goodput = (gp_num / gp_den) if gp_den > 0 else None
    census = next((e for e in reversed(events)
                   if e.get("event") == "comms_census"), None)
    census_err = _float(census.get("max_recon_error")) \
        if census is not None else None
    census_tol = (_float(census.get("tolerance")) or 0.10) \
        if census is not None else None
    # Train-trace observatory (PR-19): (a) mean per-step wall p50 over
    # the train passes — the quantity the interleaved traced-vs-
    # untraced overhead pair prices; (b) the worst per-epoch
    # disagreement between the goodput ledger's phase seconds and the
    # same phases re-derived from the epoch trace's span graph
    # (dispatch-span attrs + pass-span geometry), normalized by the
    # pass wall. The two are independent folds of the same StepClock
    # numbers, so a gap means one of them dropped or double-counted
    # seconds.
    train_traces = [e for e in events if e.get("event") == "trace"
                    and e.get("name") == "train_epoch"]
    wall_p50s = [
        v for e in events
        if e.get("event") == "epoch_steps" and e.get("split") == "train"
        and (v := _float(e.get("wall_p50_s"))) is not None]
    train_step_p50 = (sum(wall_p50s) / len(wall_p50s)) \
        if wall_p50s else None
    trace_recon = None
    if train_traces:
        gp_by_epoch: Dict[int, dict] = {}
        for e in events:
            if e.get("event") == "goodput" and e.get("epoch") is not None:
                gp_by_epoch[int(e["epoch"])] = e
        for tr in train_traces:
            ep = (tr.get("attrs") or {}).get("epoch")
            gp = gp_by_epoch.get(int(ep)) if ep is not None else None
            if gp is None:
                continue
            sums = {"compute": 0.0, "data_wait": 0.0, "host": 0.0}
            passes_wall = 0.0
            for span in tr.get("spans") or []:
                # NB: keep this local distinct from the profile's
                # `name` parameter (shadowing it mislabels the run).
                sname = span.get("name")
                attrs = span.get("attrs") or {}
                if sname == "dispatch":
                    sums["compute"] += float(
                        attrs.get("fetch_block_s") or 0.0)
                    sums["data_wait"] += float(
                        attrs.get("data_wait_s") or 0.0)
                    sums["host"] += (
                        float(attrs.get("dispatch_s") or 0.0)
                        + float(attrs.get("host_work_s") or 0.0))
                elif isinstance(sname, str) and sname.endswith("_pass"):
                    sums["compute"] += float(attrs.get("drain_s") or 0.0)
                    t0, t1 = span.get("t0"), span.get("t1")
                    if t0 is not None and t1 is not None:
                        passes_wall += t1 - t0
            ph = gp.get("phases_s") or {}

            def g(p: str) -> float:
                return float(ph.get(p) or 0.0)

            denom = _float(gp.get("passes_wall_s")) or passes_wall
            if not denom:
                continue
            err = max(
                abs(sums["compute"] - (g("compute") + g("collective"))),
                abs(sums["data_wait"] - g("data_wait")),
                abs(sums["host"] - (g("host") + g("compile"))),
            ) / denom
            trace_recon = err if trace_recon is None \
                else max(trace_recon, err)
    end = next((e for e in events if e.get("event") == "end"), None)
    halting = sum(1 for e in faults if e.get("policy") == "halt")
    if end is not None and end.get("status") == "health_fault":
        halting = max(halting, 1)
    return {
        "kind": "stream",
        "name": name,
        "domain": domain,
        "upsample_impl": upsample_impl,
        "transfer": transfer,
        "n_events": len(events),
        "skipped_lines": skipped,
        "n_epochs": len(epochs),
        "throughput": (sum(ips) / len(ips)) if ips else None,
        "final_losses": final_losses,
        "gnorm_max": gnorm_max,
        "faults": fault_kinds,
        "n_faults": sum(fault_kinds.values()),
        "n_stalls": stalls,
        "n_rollbacks": n_rollbacks,
        "n_halting_faults": halting,
        "n_fleet_recoveries": n_fleet_recoveries,
        "n_retries": n_retries,
        "n_reshards": n_reshards,
        "n_emergency_saves": len(saves),
        "n_uncommitted_saves": n_uncommitted,
        "step_losses": step_losses,
        "goodput_fraction": goodput,
        "census_recon_error": census_err,
        "census_tolerance": census_tol,
        "train_traced": bool(train_traces),
        "train_step_p50_s": train_step_p50,
        "train_trace_recon": trace_recon,
        "end_status": end.get("status") if end else None,
    }


def _float(v) -> Optional[float]:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return None
    return f if f == f else None  # NaN profiles as missing


# ---------------------------------------------------------------------------
# Comparison
# ---------------------------------------------------------------------------

Check = Tuple[str, str, str]  # (status, axis, detail)


def compare_profiles(base: dict, cand: dict, th: argparse.Namespace) -> List[Check]:
    if base["kind"] != cand["kind"]:
        return [(FAIL, "kind",
                 f"cannot compare a {base['kind']} artifact against a "
                 f"{cand['kind']} artifact")]
    if base["kind"] == "bench":
        return _compare_bench(base, cand, th)
    if base["kind"] == "serve":
        return _compare_serve(base, cand, th)
    if base["kind"] == "scaling":
        return _compare_scaling(base, cand, th)
    return _compare_streams(base, cand, th)


def _rel_drop(base: float, cand: float) -> float:
    return (base - cand) / base if base > 0 else 0.0


def _compare_bench(base: dict, cand: dict, th) -> List[Check]:
    checks: List[Check] = []
    if base.get("platform") != cand.get("platform"):
        return [(SKIP, "platform",
                 f"platform changed {base.get('platform')} -> "
                 f"{cand.get('platform')}: perf not comparable")]
    bv, cv = base.get("value"), cand.get("value")
    if bv is not None and cv is not None:
        drop = _rel_drop(bv, cv)
        status = FAIL if drop > th.max_bench_drop else PASS
        checks.append((status, "bench headline",
                       f"{bv:.2f} -> {cv:.2f} {base.get('unit') or ''}".rstrip()
                       + f" (drop {100 * drop:.1f}% vs limit "
                         f"{100 * th.max_bench_drop:.1f}%)"))
    common = sorted(set(base["all"]) & set(cand["all"]))
    for key in common:
        bv, cv = base["all"][key], cand["all"][key]
        drop = _rel_drop(bv, cv)
        status = FAIL if drop > th.max_bench_drop else PASS
        checks.append((status, f"bench {key}",
                       f"{bv:.2f} -> {cv:.2f} (drop {100 * drop:.1f}%)"))
    only_base = sorted(set(base["all"]) - set(cand["all"]))
    if only_base:
        checks.append((INFO, "bench configs",
                       f"{len(only_base)} config(s) not re-measured: "
                       + ", ".join(only_base)))
    if not checks:
        checks.append((SKIP, "bench", "no comparable values in either record"))
    return checks


def _compare_scaling(base: dict, cand: dict, th) -> List[Check]:
    """Weak-scaling gate: efficiency is a fraction of ideal, so the
    budget is ABSOLUTE points (a 0.97 -> 0.91 regression is 6 points
    of lost scaling, not a 6% throughput story); per-mesh img/s cells
    ride the relative --max_bench_drop budget."""
    checks: List[Check] = []
    bv, cv = base.get("value"), cand.get("value")
    if bv is not None and cv is not None:
        if base.get("mode") != cand.get("mode"):
            checks.append((INFO, "scaling mode",
                           f"{base.get('mode')} -> {cand.get('mode')}: "
                           "efficiency definitions differ"))
        drop = bv - cv
        status = FAIL if drop > th.max_scaling_efficiency_drop else PASS
        checks.append((status, "scaling efficiency",
                       f"{bv:.4f} -> {cv:.4f} (drop {100 * drop:.1f} points "
                       f"vs limit "
                       f"{100 * th.max_scaling_efficiency_drop:.1f})"))
    common = sorted(set(base["ips"]) & set(cand["ips"]))
    for key in common:
        bi, ci = base["ips"][key], cand["ips"][key]
        drop = _rel_drop(bi, ci)
        status = FAIL if drop > th.max_bench_drop else PASS
        checks.append((status, f"scaling {key}",
                       f"{bi:.2f} -> {ci:.2f} img/s "
                       f"(drop {100 * drop:.1f}%)"))
    only_base = sorted(set(base["ips"]) - set(cand["ips"]))
    if only_base:
        checks.append((INFO, "scaling cells",
                       f"{len(only_base)} mesh size(s) not re-measured: "
                       + ", ".join(only_base)))
    if not checks:
        checks.append((SKIP, "scaling",
                       "no comparable values in either record"))
    return checks


def _compare_serve(base: dict, cand: dict, th) -> List[Check]:
    checks: List[Check] = []
    if base.get("platform") != cand.get("platform"):
        return [(SKIP, "platform",
                 f"platform changed {base.get('platform')} -> "
                 f"{cand.get('platform')}: serving perf not comparable")]
    for axis, key in (("serve headline", "value"),
                      ("serve fleet", "fleet_ips"),
                      ("serve int8", "int8_ips"),
                      ("serve int8_fused", "int8_fused_ips")):
        bv, cv = base.get(key), cand.get(key)
        if bv is None or cv is None:
            checks.append((SKIP, axis,
                           "missing in one record (older round?)"))
            continue
        drop = _rel_drop(bv, cv)
        status = FAIL if drop > th.max_bench_drop else PASS
        checks.append((status, axis,
                       f"{bv:.2f} -> {cv:.2f} img/s (drop {100 * drop:.1f}% "
                       f"vs limit {100 * th.max_bench_drop:.1f}%)"))
    # Fused-tier quality probe — a CANDIDATE invariant (the base may
    # predate the tier): the unrounded max|int8_fused - f32| from the
    # bench round is the same shadow-probe budget the brownout ladder
    # serves under, so a drifted fused program fails here before it
    # ever fails a drill.
    drift = cand.get("int8_fused_drift")
    if drift is not None:
        over = drift > th.max_int8_fused_drift
        checks.append((
            FAIL if over else PASS, "serve int8_fused drift",
            f"max|int8_fused - f32| {drift:.3e} vs limit "
            f"{th.max_int8_fused_drift:g} (shadow-probe quality budget)"))
    elif cand.get("int8_fused_ips") is not None:
        checks.append((SKIP, "serve int8_fused drift",
                       "fused tier measured but no drift recorded"))
    common_p95 = sorted(set(base["p95_ms"]) & set(cand["p95_ms"]))
    for key in common_p95:
        bv, cv = base["p95_ms"][key], cand["p95_ms"][key]
        limit = bv * (1.0 + th.max_serve_p95_increase)
        status = FAIL if cv > limit else PASS
        checks.append((status, f"serve p95 {key}",
                       f"{bv:.1f} -> {cv:.1f} ms (limit {limit:.1f})"))
    if not common_p95:
        checks.append((SKIP, "serve p95", "no common p95 rows"))
    # Class-ordered shedding is an invariant of the CANDIDATE, not a
    # diff: interactive shed while best_effort went unshed means the
    # admission queue picked victims in the wrong order.
    shed = cand.get("shed_by_class") or {}
    if shed:
        ordered = not (shed.get("interactive", 0) > 0
                       and shed.get("best_effort", 0) == 0)
        checks.append((PASS if ordered else FAIL, "serve shed ordering",
                       f"overload shed {_fmt_kinds(shed)}"
                       + ("" if ordered else
                          " — interactive shed before best_effort")))
    else:
        checks.append((INFO, "serve shed ordering",
                       "no overload shedding recorded"))
    # Autoscale-phase invariants — like shed ordering, these judge the
    # CANDIDATE alone (the base may predate the self-driving fleet).
    if cand.get("has_autoscale"):
        auto_shed = cand.get("autoscale_shed_by_class") or {}
        degraded = cand.get("autoscale_degraded", 0)
        n_shed = sum(auto_shed.values())
        if cand.get("autoscale_brownout"):
            # Brownout ordering: the ladder degrades tiers BEFORE the
            # queue sheds. A brownout-enabled fleet that shed anything
            # without degrading anything skipped its cheap-tier rungs.
            ordered = not (n_shed > 0 and degraded == 0)
            checks.append((
                PASS if ordered else FAIL, "serve brownout ordering",
                f"autoscale trace degraded {degraded} request(s), shed "
                f"{_fmt_kinds(auto_shed)}"
                + ("" if ordered else
                   " — shed without degrading (brownout never engaged)")))
        n_int = auto_shed.get("interactive", 0)
        checks.append((
            PASS if n_int == 0 else FAIL, "serve autoscale interactive shed",
            f"{n_int} interactive request(s) shed across the autoscale "
            f"trace (any is a failure: interactive work rides out the "
            f"surge on scale-up + brownout)"))
        sp95 = cand.get("autoscale_surge_interactive_p95")
        ref = cand.get("fixed_fleet_interactive_p95")
        if sp95 is not None and ref is not None:
            checks.append((
                PASS if sp95 <= ref else FAIL,
                "serve autoscale surge p95",
                f"surge interactive p95 {sp95:.1f} ms vs fixed-fleet "
                f"overload {ref:.1f} ms (must not exceed it)"))
        else:
            checks.append((SKIP, "serve autoscale surge p95",
                           "surge or fixed-fleet interactive p95 missing"))
        checks.append((INFO, "serve autoscale churn",
                       f"scale_ups {cand.get('autoscale_scale_ups')}, "
                       f"scale_downs {cand.get('autoscale_scale_downs')}"))
    # Tracing-overhead gate — a CANDIDATE invariant (the base may
    # predate request tracing): full head sampling must price in under
    # the budget, or the span hot path grew a hidden cost (a sync, a
    # lock on the record path, per-span allocation blowup).
    toh = cand.get("trace_overhead_frac")
    if toh is not None:
        over = toh > th.max_trace_overhead
        checks.append((
            FAIL if over else PASS, "serve trace overhead",
            f"saturated img/s at --trace_sample 1.0 costs "
            f"{100 * toh:.2f}% vs sample 0.0 (limit "
            f"{100 * th.max_trace_overhead:.1f}%)"))
    elif cand.get("fleet_ips") is not None:
        checks.append((SKIP, "serve trace overhead",
                       "no trace_overhead phase in candidate record"))
    return checks


def _compare_streams(base: dict, cand: dict, th) -> List[Check]:
    checks: List[Check] = []

    # Domain gate (mirrors the cross-platform SKIP for bench records):
    # loss/gnorm trajectories of different domain pairs are not
    # comparable — UNLESS the candidate is a transfer-onboarding run
    # whose recorded parent domain is the base's domain, in which case
    # the pair is exactly the Mind2Mind comparison the transfer axis
    # gates (parent run -> fine-tune run).
    b_dom, c_dom = base.get("domain"), cand.get("domain")
    transfer = cand.get("transfer")
    if b_dom and c_dom and b_dom != c_dom:
        if transfer and transfer.get("parent_domain") == b_dom:
            return _transfer_checks(base, cand, th)
        return [(SKIP, "domain",
                 f"domain changed {b_dom} -> {c_dom}: training "
                 f"trajectories not comparable (transfer runs gate via "
                 f"their recorded parent)")]
    if transfer:
        # Same-domain fine-tune (e.g. refreshing a pair from its own
        # older checkpoint): the regular axes still apply, the transfer
        # axis rides along.
        checks.extend(_transfer_checks(base, cand, th))

    # Upsample-impl axis (PR-14): when the two streams ran different
    # generator upsample tiers (dense vs zeroskip vs zeroskip_fused),
    # the pair IS the GANAX equivalence experiment — the decomposed
    # engine claims bit-compatible training, so the loss trajectories
    # must land inside the usual relative-with-floor slack. Unlike the
    # domain gate this axis never SKIPs: an impl change that cannot
    # demonstrate equivalence (no common loss means) FAILS, because a
    # silent skip is exactly how a divergent kernel would ship.
    b_up, c_up = base.get("upsample_impl"), cand.get("upsample_impl")
    if b_up and c_up and b_up != c_up:
        common = sorted(set(base["final_losses"])
                        & set(cand["final_losses"]))
        if not common:
            checks.append((FAIL, "upsample-impl",
                           f"upsample changed {b_up} -> {c_up} with no "
                           f"common loss trajectories: an impl change "
                           f"must prove loss equivalence, never skip it"))
        else:
            worst_key, worst_excess = None, None
            for key in common:
                bv = base["final_losses"][key]
                cv = cand["final_losses"][key]
                limit = bv + th.max_loss_increase * max(abs(bv), 0.1)
                excess = cv - limit
                if worst_excess is None or excess > worst_excess:
                    worst_excess, worst_key = excess, key
            status = FAIL if worst_excess > 0 else PASS
            checks.append((status, "upsample-impl",
                           f"upsample changed {b_up} -> {c_up}: "
                           f"{len(common)} loss trajectories gated, "
                           f"worst margin {worst_key} "
                           f"{'+' if worst_excess > 0 else ''}"
                           f"{worst_excess:.4f} vs limit"))
    elif b_up and c_up:
        checks.append((INFO, "upsample-impl",
                       f"both streams ran upsample_impl={b_up}"))

    bt, ct = base.get("throughput"), cand.get("throughput")
    if bt is not None and ct is not None:
        drop = _rel_drop(bt, ct)
        status = FAIL if drop > th.max_throughput_drop else PASS
        checks.append((status, "throughput",
                       f"{bt:.2f} -> {ct:.2f} img/s (drop {100 * drop:.1f}% "
                       f"vs limit {100 * th.max_throughput_drop:.1f}%)"))
    else:
        checks.append((SKIP, "throughput",
                       "missing epoch throughput in one stream"))

    common_losses = sorted(set(base["final_losses"]) & set(cand["final_losses"]))
    for key in common_losses:
        bv, cv = base["final_losses"][key], cand["final_losses"][key]
        # Relative-with-floor slack: GAN losses legally sit near their
        # LSGAN fixed points, so a pure ratio would flag noise on
        # near-zero values.
        limit = bv + th.max_loss_increase * max(abs(bv), 0.1)
        status = FAIL if cv > limit else PASS
        checks.append((status, f"loss {key}",
                       f"final {bv:.4f} -> {cv:.4f} (limit {limit:.4f})"))
    if not common_losses:
        checks.append((SKIP, "losses",
                       "no common health loss trajectories "
                       "(stream predates the health layer?)"))

    common_nets = sorted(set(base["gnorm_max"]) & set(cand["gnorm_max"]))
    for net in common_nets:
        bv, cv = base["gnorm_max"][net], cand["gnorm_max"][net]
        limit = th.max_gnorm_ratio * max(bv, 1e-6)
        status = FAIL if cv > limit else PASS
        checks.append((status, f"gnorm {net}",
                       f"max envelope {bv:.4g} -> {cv:.4g} "
                       f"(limit {limit:.4g})"))
    if not common_nets:
        checks.append((SKIP, "gnorm", "no common grad-norm envelopes"))

    new_faults = cand["n_faults"] - base["n_faults"]
    status = FAIL if new_faults > th.max_new_faults else PASS
    checks.append((status, "anomalies",
                   f"health faults {base['n_faults']} -> {cand['n_faults']} "
                   f"({_fmt_kinds(cand['faults'])}) vs allowed "
                   f"+{th.max_new_faults}"))
    checks.append((INFO, "stalls",
                   f"watchdog/loop stalls {base['n_stalls']} -> "
                   f"{cand['n_stalls']} (reported, not gated)"))

    # Recovery axis: a candidate that newly HALTS on a fault, or leans
    # harder on the rollback machinery than its base, regressed even if
    # every epoch it finished looks healthy.
    b_halt = base.get("n_halting_faults", 0)
    c_halt = cand.get("n_halting_faults", 0)
    status = FAIL if c_halt > b_halt else PASS
    checks.append((status, "recovery halting-faults",
                   f"halting faults {b_halt} -> {c_halt} "
                   f"(any increase fails)"))
    b_roll = base.get("n_rollbacks", 0)
    c_roll = cand.get("n_rollbacks", 0)
    status = FAIL if c_roll > b_roll else PASS
    checks.append((status, "recovery rollbacks",
                   f"NaN rollbacks {b_roll} -> {c_roll} "
                   f"(any increase fails)"))
    if base.get("n_retries", 0) or cand.get("n_retries", 0) \
            or base.get("n_fleet_recoveries", 0) \
            or cand.get("n_fleet_recoveries", 0):
        checks.append((INFO, "recovery churn",
                       f"I/O retries {base.get('n_retries', 0)} -> "
                       f"{cand.get('n_retries', 0)}, fleet recoveries "
                       f"{base.get('n_fleet_recoveries', 0)} -> "
                       f"{cand.get('n_fleet_recoveries', 0)} "
                       f"(reported, not gated)"))

    # Goodput axis (PR-16): the ledger classifies every wall-clock
    # second of the run; the gated quantity is the seconds-weighted
    # fraction spent in device compute. A candidate whose goodput
    # fraction drops more than --max_goodput_drop below the base wasted
    # chip time SOMEWHERE (data-wait, host work, checkpoint barriers)
    # even if its steady-state img/s looks unchanged — throughput
    # measures the steps that ran, goodput measures the seconds that
    # didn't.
    b_gp, c_gp = base.get("goodput_fraction"), cand.get("goodput_fraction")
    if b_gp is not None and c_gp is not None:
        drop = b_gp - c_gp
        status = FAIL if drop > th.max_goodput_drop else PASS
        checks.append((status, "goodput",
                       f"goodput fraction {b_gp:.3f} -> {c_gp:.3f} "
                       f"(drop {drop:+.3f} vs limit "
                       f"{th.max_goodput_drop:.3f})"))
    else:
        checks.append((SKIP, "goodput",
                       "no goodput ledger in one stream "
                       "(predates the ledger?)"))

    # Comms-census axis: candidate-side invariant (like the serve trace
    # overhead gate) — judged on the candidate alone, because the claim
    # is absolute: the analytic collective ledger must reconcile with
    # the compiled program within the census's own tolerance. Census
    # drift means the model or the sharding changed silently.
    c_err = cand.get("census_recon_error")
    if c_err is not None:
        tol = cand.get("census_tolerance") or 0.10
        status = FAIL if c_err > tol else PASS
        checks.append((status, "comms-census",
                       f"analytic vs compiled reconciliation error "
                       f"{100 * c_err:.1f}% (limit {100 * tol:.0f}%)"))
    else:
        checks.append((SKIP, "comms-census",
                       "no comms_census event in the candidate stream"))

    # Train-trace axes (PR-19). (1) Overhead pair: when the candidate
    # traced its epochs (--train_trace_sample > 0) and the base ran the
    # identical config untraced, the per-step wall p50 prices the
    # tracer — the span graph is built from timestamps the StepClock
    # already takes, so it must cost ~nothing; past the budget it grew
    # a hidden sync or allocation. (2) Candidate-side invariant: the
    # goodput ledger's phase seconds and the epoch span tiling are two
    # independent folds of the same clock — past 5% of pass wall, one
    # of them is dropping or double-counting seconds.
    b_p50 = base.get("train_step_p50_s")
    c_p50 = cand.get("train_step_p50_s")
    if cand.get("train_traced") and not base.get("train_traced") \
            and b_p50 and c_p50:
        oh = (c_p50 - b_p50) / b_p50
        status = FAIL if oh > th.max_train_trace_overhead else PASS
        checks.append((status, "train-trace overhead",
                       f"per-step wall p50 {b_p50:.4f}s -> {c_p50:.4f}s "
                       f"traced ({100 * oh:+.2f}% vs limit "
                       f"{100 * th.max_train_trace_overhead:.1f}%)"))
    recon = cand.get("train_trace_recon")
    if recon is not None:
        status = FAIL if recon > 0.05 else PASS
        checks.append((status, "train-trace recon",
                       f"goodput phases vs span tiling disagree by "
                       f"{100 * recon:.2f}% of pass wall (limit 5%)"))
    elif cand.get("train_traced"):
        checks.append((SKIP, "train-trace recon",
                       "train traces without matching goodput rollups"))

    # Elastic axis: engages when the candidate resharded across
    # topologies or emergency-saved mid-epoch. The claim under gate is
    # cross-mesh EQUIVALENCE: same per-step losses as the base, same
    # step counts (a drifted count means a sample was skipped or
    # repeated at the seam), and every emergency save committed inside
    # its deadline budget.
    if cand.get("n_reshards", 0) or cand.get("n_emergency_saves", 0):
        n_bad = cand.get("n_uncommitted_saves", 0)
        checks.append((
            FAIL if n_bad else PASS, "elastic emergency-saves",
            f"{cand.get('n_emergency_saves', 0)} save(s), "
            f"{cand.get('n_reshards', 0)} reshard(s); "
            f"{n_bad} missed the deadline budget (any miss fails)"))
        common_eps = sorted(set(base.get("step_losses") or {})
                            & set(cand.get("step_losses") or {}))
        worst = 0.0
        drift: List[str] = []
        n_series = 0
        for ep in common_eps:
            bs = base["step_losses"][ep]
            cs = cand["step_losses"][ep]
            for key in sorted(set(bs) & set(cs)):
                if len(bs[key]) != len(cs[key]):
                    drift.append(f"e{ep} {key}: {len(bs[key])} vs "
                                 f"{len(cs[key])} steps")
                    continue
                n_series += 1
                if bs[key]:
                    worst = max(worst, max(
                        abs(a - b) for a, b in zip(bs[key], cs[key])))
        if drift:
            checks.append((FAIL, "elastic step-losses",
                           "step-count drift (skipped/repeated sample): "
                           + "; ".join(drift[:4])))
        elif n_series:
            status = FAIL if worst > th.max_elastic_loss_diff else PASS
            checks.append((status, "elastic step-losses",
                           f"{n_series} trajectories over epochs "
                           f"{common_eps}: max |diff| {worst:.3g} vs "
                           f"limit {th.max_elastic_loss_diff:.3g}"))
        else:
            checks.append((SKIP, "elastic step-losses",
                           "no common step_losses trajectories to gate"))
    return checks


def _transfer_checks(base: dict, cand: dict, th) -> List[Check]:
    """The transfer-onboarding axis: the candidate fine-tuned from the
    base (Mind2Mind). Three claims under gate: the fine-tune's final
    losses land within the usual loss slack of the parent's (transfer
    must not END worse than where it started from), it gets there in at
    most --max_transfer_epoch_frac of the parent's epochs (the whole
    economic point of onboarding from a trained pair), and a frozen
    encoder trunk really was frozen (its grad-norm envelope pins at
    exactly 0 — any nonzero is masked-gradient machinery failing)."""
    checks: List[Check] = []
    t = cand.get("transfer") or {}
    checks.append((INFO, "transfer provenance",
                   f"mode {t.get('transfer_mode')!r}, parent "
                   f"{t.get('parent_domain')!r} @ epoch "
                   f"{t.get('parent_epoch')} ({t.get('parent_ckpt')})"))
    common = sorted(set(base.get("final_losses") or {})
                    & set(cand.get("final_losses") or {}))
    for key in common:
        bv, cv = base["final_losses"][key], cand["final_losses"][key]
        limit = bv + th.max_loss_increase * max(abs(bv), 0.1)
        status = FAIL if cv > limit else PASS
        checks.append((status, f"transfer loss {key}",
                       f"fine-tune final {cv:.4f} vs parent {bv:.4f} "
                       f"(limit {limit:.4f})"))
    if not common:
        checks.append((SKIP, "transfer losses",
                       "no common final loss means against the parent"))
    b_ep, c_ep = base.get("n_epochs"), cand.get("n_epochs")
    if b_ep and c_ep:
        limit_ep = th.max_transfer_epoch_frac * b_ep
        status = FAIL if c_ep > limit_ep else PASS
        checks.append((status, "transfer epochs",
                       f"fine-tune ran {c_ep} epoch(s) vs parent "
                       f"{b_ep} (limit {limit_ep:.1f} = "
                       f"{100 * th.max_transfer_epoch_frac:.0f}%)"))
    else:
        checks.append((SKIP, "transfer epochs",
                       "epoch count missing in one stream"))
    if t.get("transfer_mode") == "encoder_freeze":
        frozen = (cand.get("gnorm_max") or {}).get("enc_frozen")
        if frozen is None:
            checks.append((SKIP, "transfer frozen-trunk",
                           "no enc_frozen grad-norm envelope recorded"))
        else:
            checks.append((PASS if frozen == 0.0 else FAIL,
                           "transfer frozen-trunk",
                           f"frozen encoder grad-norm max envelope "
                           f"{frozen:.4g} (must be exactly 0)"))
    return checks


def _fmt_kinds(kinds: Dict[str, int]) -> str:
    if not kinds:
        return "none"
    return ", ".join(f"{k}={v}" for k, v in sorted(kinds.items()))


# ---------------------------------------------------------------------------
# Report
# ---------------------------------------------------------------------------


def render_pair(base: dict, cand: dict, checks: List[Check]) -> str:
    lines = [f"== run_compare: {base['name']} -> {cand['name']} "
             f"[{base['kind']}] =="]
    for status, axis, detail in checks:
        lines.append(f"[{status}] {axis}: {detail}")
    n_fail = sum(1 for s, _, _ in checks if s == FAIL)
    lines.append(f"result: {'FAIL' if n_fail else 'PASS'} "
                 f"({n_fail} failed / {len(checks)} checks)")
    return "\n".join(lines)


def run(paths: List[str], th: argparse.Namespace, out=None) -> int:
    out = out or sys.stdout
    try:
        profiles = [load_profile(p) for p in paths]
    except OSError as e:
        print(f"run_compare: cannot read input: {e}", file=sys.stderr)
        return 2
    failed = False
    reports = []
    for base, cand in zip(profiles, profiles[1:]):
        checks = compare_profiles(base, cand, th)
        failed = failed or any(s == FAIL for s, _, _ in checks)
        reports.append((base, cand, checks))
    if th.json:
        print(json.dumps(
            [{
                "base": b["name"], "cand": c["name"], "kind": b["kind"],
                "checks": [
                    {"status": s, "axis": a, "detail": d} for s, a, d in ch
                ],
            } for b, c, ch in reports],
            indent=2, sort_keys=True), file=out)
    else:
        print("\n\n".join(render_pair(b, c, ch) for b, c, ch in reports),
              file=out)
    return 1 if failed else 0


def make_thresholds(
    max_throughput_drop: float = 0.15,
    max_loss_increase: float = 0.25,
    max_gnorm_ratio: float = 5.0,
    max_new_faults: int = 0,
    max_bench_drop: float = 0.10,
    max_serve_p95_increase: float = 0.50,
    max_elastic_loss_diff: float = 1e-5,
    max_transfer_epoch_frac: float = 0.25,
    max_trace_overhead: float = 0.03,
    max_train_trace_overhead: float = 0.03,
    max_goodput_drop: float = 0.05,
    max_int8_fused_drift: float = 0.05,
    max_scaling_efficiency_drop: float = 0.05,
    json: bool = False,
) -> argparse.Namespace:
    """Programmatic threshold bundle (bench.py's end-of-run hook)."""
    return argparse.Namespace(
        max_throughput_drop=max_throughput_drop,
        max_loss_increase=max_loss_increase,
        max_gnorm_ratio=max_gnorm_ratio,
        max_new_faults=max_new_faults,
        max_bench_drop=max_bench_drop,
        max_serve_p95_increase=max_serve_p95_increase,
        max_elastic_loss_diff=max_elastic_loss_diff,
        max_transfer_epoch_frac=max_transfer_epoch_frac,
        max_trace_overhead=max_trace_overhead,
        max_train_trace_overhead=max_train_trace_overhead,
        max_goodput_drop=max_goodput_drop,
        max_int8_fused_drift=max_int8_fused_drift,
        max_scaling_efficiency_drop=max_scaling_efficiency_drop,
        json=json,
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("runs", nargs="+",
                        help="2+ artifacts: telemetry JSONL streams or "
                             "BENCH_r*.json records; 3+ gates every "
                             "consecutive pair (series mode)")
    parser.add_argument("--max_throughput_drop", default=0.15, type=float,
                        help="max relative drop in mean train img/s")
    parser.add_argument("--max_loss_increase", default=0.25, type=float,
                        help="max relative increase of each final loss "
                             "mean (with a 0.1 absolute floor on the base)")
    parser.add_argument("--max_gnorm_ratio", default=5.0, type=float,
                        help="max candidate/base ratio of each network's "
                             "grad-norm max envelope")
    parser.add_argument("--max_new_faults", default=0, type=int,
                        help="max new health_fault events vs base")
    parser.add_argument("--max_bench_drop", default=0.10, type=float,
                        help="max relative drop of bench images/sec "
                             "(headline and per-config)")
    parser.add_argument("--max_serve_p95_increase", default=0.50, type=float,
                        help="max relative increase of any serve p95 latency "
                             "(per phase and class)")
    parser.add_argument("--max_elastic_loss_diff", default=1e-5, type=float,
                        help="max elementwise |diff| of per-step loss "
                             "trajectories when the candidate resharded "
                             "or resumed mid-epoch (f32 equivalence)")
    parser.add_argument("--max_trace_overhead", default=0.03, type=float,
                        help="max fractional throughput cost of serving "
                             "at --trace_sample 1.0 vs 0.0 (candidate-"
                             "side; bench_serve trace_overhead phase)")
    parser.add_argument("--max_train_trace_overhead", default=0.03,
                        type=float,
                        help="max fractional per-step wall cost of "
                             "training with --train_trace_sample > 0 vs "
                             "an untraced base stream of the same config")
    parser.add_argument("--max_int8_fused_drift", default=0.05, type=float,
                        help="max unrounded max|int8_fused - f32| a "
                             "candidate bench_serve round may record for "
                             "the fused inference tier (candidate-side "
                             "shadow-probe quality budget)")
    parser.add_argument("--max_goodput_drop", default=0.05, type=float,
                        help="max absolute drop of the seconds-weighted "
                             "goodput fraction (obs/goodput.py ledger) "
                             "vs base")
    parser.add_argument("--max_scaling_efficiency_drop", default=0.05,
                        type=float,
                        help="max ABSOLUTE drop (in fraction points) of "
                             "the weak-scaling efficiency between two "
                             "bench_scaling records")
    parser.add_argument("--max_transfer_epoch_frac", default=0.25, type=float,
                        help="max epochs a transfer-onboarded fine-tune may "
                             "run, as a fraction of its parent's from-scratch "
                             "epoch count, while still reaching the loss gate")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report")
    args = parser.parse_args(argv)
    if len(args.runs) < 2:
        parser.error("need at least two artifacts to compare")
    return run(args.runs, args)


if __name__ == "__main__":
    raise SystemExit(main())
