"""Compare the pad-scheme quality A/B runs (reflect vs zero vs fused).

VERDICT r3 item 2's CPU half: same data, same seeds, same budget —
only the pad flags differ. Reads each run's TensorBoard event files
(tools/plot_run.py reader) and prints a markdown comparison of:
- final + trajectory FID (fid/<featurizer>/G(A)_vs_B and F(B)_vs_A),
- the four reference test MAE metrics at the final epoch,
- generator/discriminator loss-curve divergence vs the reflect control
  (max |Δ| over epochs; fused should shadow reflect until fp-level
  divergence compounds, zero may genuinely differ).

Usage:
  python tools/pad_ab_report.py --runs reflect=/tmp/ab_reflect \
      zero=/tmp/ab_zero fused=/tmp/ab_fused
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from plot_run import read_scalars  # noqa: E402

# tensorboardX sanitizes tag punctuation: "error/MAE(X, F(G(X)))" is
# stored as "error/MAE_X__F_G_X___".
ERROR_TAGS = [
    ("MAE(X, F(G(X)))", "test/error/MAE_X__F_G_X___"),
    ("MAE(X, F(X))", "test/error/MAE_X__F_X__"),
    ("MAE(Y, G(F(Y)))", "test/error/MAE_Y__G_F_Y___"),
    ("MAE(Y, G(Y))", "test/error/MAE_Y__G_Y__"),
]
LOSS_TAGS = ["loss_G/total", "loss_F/total", "loss_X/loss", "loss_Y/loss"]


def last(series, tag):
    pts = series.get(tag) or []
    return pts[-1][1] if pts else None


def fid_tags(series):
    return sorted(t for t in series if t.startswith("fid/") or "/fid/" in t)


def fmt(v):
    return "—" if v is None else f"{v:.4f}"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--runs", nargs="+", required=True,
                    metavar="NAME=DIR", help="first run is the control")
    args = ap.parse_args()
    runs = {}
    for spec in args.runs:
        name, _, d = spec.partition("=")
        if not d or not os.path.isdir(d):
            raise SystemExit(f"bad run spec or missing dir: {spec}")
        if name in runs:
            raise SystemExit(f"duplicate run name: {name}")
        runs[name] = read_scalars(d)
    control_name = next(iter(runs))
    control = runs[control_name]

    print(f"## Pad-scheme A/B ({' vs '.join(runs)})\n")

    all_fid = sorted({t for s in runs.values() for t in fid_tags(s)})
    if all_fid:
        print("| FID (final) | " + " | ".join(runs) + " |")
        print("|---|" + "---|" * len(runs))
        for t in all_fid:
            print(f"| `{t}` | " + " | ".join(
                fmt(last(s, t)) for s in runs.values()) + " |")
        print()

    print("| test MAE (final epoch) | " + " | ".join(runs) + " |")
    print("|---|" + "---|" * len(runs))
    for label, t in ERROR_TAGS:
        print(f"| `{label}` | " + " | ".join(
            fmt(last(s, t)) for s in runs.values()) + " |")
    print()

    if len(runs) > 1:
        print(f"| max abs Δ loss vs {control_name} | " +
              " | ".join(n for n in runs if n != control_name) + " |")
        print("|---|" + "---|" * (len(runs) - 1))
        for t in LOSS_TAGS:
            cells = []
            cpts = dict(control.get(t) or [])
            for name, s in runs.items():
                if name == control_name:
                    continue
                opts = dict(s.get(t) or [])
                common = sorted(set(cpts) & set(opts))
                d = max((abs(cpts[e] - opts[e]) for e in common), default=None)
                cells.append(fmt(d))
            print(f"| `{t}` | " + " | ".join(cells) + " |")


if __name__ == "__main__":
    main()
