"""Forward-only generator traffic probe: pad vs zero vs fused, offline.

The full-step pad-fused AOT job came back WORSE than the materialized-pad
baseline (317 GB vs 227.3 GB — docs/aot_analysis.json), against the
fusion-epilogue prediction. This probe compiles ONLY the generator
forward (no grads, no optimizer) for each pad scheme, so the regression
can be attributed: if fused-forward is near zero-forward, the blowup is
in autodiff's backward (thin-slice VJPs scatter-adding into full-size
zeros — fixable with a custom VJP); if fused-forward is already bad, the
zero-pad-conv + pad/add-correction epilogue itself does not fuse on
XLA:TPU and the schedule needs a different shape (e.g. concat assembly).

Run: PALLAS_AXON_POOL_IPS= python tools/aot_fwd_probe.py
Appends results as jobs named "fwd-probe gen/<scheme>/bf16/b16/256" to
docs/aot_analysis.json (merge semantics — aot_analyze.merge_into_report).
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from aot_analyze import (  # noqa: E402
    extract_analysis,
    merge_into_report,
    register_local_only,
    say,
)


def main() -> None:
    register_local_only()
    say("registered local_only AOT backend")
    import jax
    import jax.numpy as jnp

    from cyclegan_tpu.config import GeneratorConfig
    from cyclegan_tpu.models import ResNetGenerator

    batch, image = 16, 256
    schemes = {
        "pad": dict(pad_mode="reflect", pad_impl="pad"),
        "zero": dict(pad_mode="zero", pad_impl="pad"),
        "fused": dict(pad_mode="reflect", pad_impl="fused"),
    }
    jobs = {}
    for name, kw in schemes.items():
        tag = f"fwd-probe gen/{name}/bf16/b{batch}/{image}"
        say(f"{tag}: building")
        gen = ResNetGenerator(
            config=GeneratorConfig(), dtype=jnp.bfloat16, **kw
        )
        x = jax.ShapeDtypeStruct((batch, image, image, 3), jnp.float32)
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            params = jax.eval_shape(
                gen.init, jax.random.PRNGKey(0),
                jnp.zeros((1, image, image, 3), jnp.float32),
            )
        say(f"{tag}: lowering + compiling")
        t0 = time.perf_counter()
        try:
            compiled = jax.jit(gen.apply).lower(params, x).compile()  # graftlint: disable=tracer-leak -- per-scheme AOT probe; a fresh program per config is the point
            out = extract_analysis(compiled)
            out["compile_seconds"] = round(time.perf_counter() - t0, 1)
            ca = out.get("cost_analysis", {})
            say(f"{tag}: {ca.get('bytes accessed', 0) / 1e9:.1f} GB, "
                f"{out['compile_seconds']}s")
        except Exception as e:  # record, keep probing other schemes
            out = {"error": f"{type(e).__name__}: {e}"}
            say(f"{tag}: FAILED {out['error']}")
        out["config"] = dict(kw, batch=batch, image=image, fwd_only=True)
        jobs[tag] = out

    merge_into_report(jobs)
    for tag, j in jobs.items():
        ca = j.get("cost_analysis", {})
        print(tag, round(ca.get("bytes accessed", 0) / 1e9, 2), "GB")


if __name__ == "__main__":
    main()
