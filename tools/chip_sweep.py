"""Ad-hoc on-chip config sweeps reusing bench.py's measurement core.

TPU_RUNBOOK item 2: re-sweep scan batches under the custom-VJP norm
(the r1 sweep predates it for every batch but 16) and probe k=16 vs
k=8 scan. Each result is appended to docs/bench_sweeps.json (override
with CYCLEGAN_SWEEP_RECORD) as {"key", "img_per_sec" | "error", "ts"}
so the record is regenerable:

    python tools/chip_sweep.py scan:b8 scan:b24 scan:b32 scan:b16k16

Spec grammar:
<scan|dispatch|accum>:b<batch>[k<K>][pallas][zero|fused|epi][fp][pb]
[zs|zsf][pf][i<image>]
— parts in that order; k defaults to 8 for scan / 1 for dispatch, image
to 256; `zero` selects pad_mode="zero" (conv built-in SAME padding, the
compiler-certified −32% traffic variant — docs/BENCHMARKS.md pad-probe);
`fp` selects grad_impl="fusedprop" (FusedProp shared-forward gradients —
train/steps.py; gradient-parity engine, 18g+14d vs 18g+16d analytic
FLOPs/pair);
`pb` selects trunk_impl="perturb" (the Perturbative-GAN cheap generator
trunk — fixed masks + 1x1 convs; a quality tier, not a parity config);
`fused` selects pad_impl="fused" (ReflectConv: reflect SEMANTICS without
materialized pads — the parity-preserving variant of the same lever);
`epi` selects pad_impl="epilogue" (the fused scheduling PLUS the trunk
IN>ReLU>reflect-pad chains collapsed into the Pallas epilogue kernel —
ops/pallas/epilogue_kernel.py; a Mosaic program, so it is gated like
`pallas` specs below);
`zs` selects upsample_impl="zeroskip" (GANAX output decomposition —
four per-phase dense convs + depth-to-space interleave, ~4x fewer
upsample MACs, pure XLA; ops/upsample.py);
`zsf` selects upsample_impl="zeroskip_fused" (the Pallas phase-conv +
IN + ReLU kernel, ops/pallas/upsample_kernel.py — a Mosaic program,
gated like `pallas`/`epi` specs);
`pf` (dispatch only) stages inputs via the device-prefetch worker — the
round-4 real-loop contract (`--prefetch_batches`), same XLA program as
the plain dispatch spec.
`accum` mode is the gradient-accumulation step (`--grad_accum`,
TPU_RUNBOOK item 5): b = MICRObatch, k = microbatches per update
(default 8), so `accum:b1k8i512` is the compiler-certified 512² config
— one update from 8 microbatches of 1, activation memory bounded by the
microbatch. `pf` does not apply (inputs are device-staged).
Runs ONE config per spec sequentially in this process (ground rule:
one axon client at a time). A failed measurement — an OOM, or a pallas
spec refused off-CPU — is recorded as an error row and the sweep
continues; only a malformed spec or a corrupt record file aborts (both
before any compile).

Infrastructure failures are NOT measurements: a remote-compile HTTP
500, a tpu_compile_helper crash, or a dropped tunnel connection says
nothing about the config under test, so those are printed but NOT
appended to the record file (a transient infra row would sit in the
ground-truth record masquerading as a property of the config — the r5
512² scan rows died exactly this way). The sweep still tries its
remaining specs, then exits 3 so an unattended driver (chip_autorun)
knows the window needs a retry rather than counting the step done.

`pallas` and `epi` specs carry Mosaic programs and are REFUSED off the
CPU backend unless compiles are LOCAL (CYCLEGAN_AXON_LOCAL_COMPILE=1 —
Mosaic compiles against the in-image libtpu and never touches the
remote-compile service) or CYCLEGAN_ALLOW_PALLAS_REMOTE=1:
remote-compiling the Mosaic program hung the compile service and cost
the session its tunnel (docs/TUNNEL_POSTMORTEM.md incident 2, runbook
ground rule 2b). The norm kernel's characterization lives in
docs/aot_analysis.json instead.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

RECORD_PATH = os.environ.get("CYCLEGAN_SWEEP_RECORD") or os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "bench_sweeps.json")

SPEC_RE = re.compile(
    r"(scan|dispatch|accum):b(\d+)(?:k(\d+))?(pallas)?(zero|fused|epi)?"
    r"(fp)?(pb)?(zsf|zs)?(pf)?(?:i(\d+))?")


def parse_spec(spec: str):
    """spec -> (mode, batch, k, pallas, pad_mode, pad_impl, grad_impl,
    trunk_impl, upsample_impl, prefetch, image).
    Raises SystemExit on a malformed spec or zero batch/k/image (the
    regex's \\d+ admits 0, which `k or default` would silently coerce to
    the default — a mislabeled record in a file the docs treat as ground
    truth)."""
    m = SPEC_RE.fullmatch(spec)
    if not m:
        raise SystemExit(f"bad spec: {spec}")
    pad_word = m.group(5)
    mode, batch, k, pallas, prefetch, image = (
        m.group(1), int(m.group(2)),
        int(m.group(3)) if m.group(3) else None,
        bool(m.group(4)), bool(m.group(9)),
        int(m.group(10)) if m.group(10) else 256)
    pad_mode = "zero" if pad_word == "zero" else "reflect"
    pad_impl = {"fused": "fused", "epi": "epilogue"}.get(pad_word, "pad")
    grad_impl = "fusedprop" if m.group(6) else "combined"
    trunk_impl = "perturb" if m.group(7) else "resnet"
    upsample_impl = {"zs": "zeroskip", "zsf": "zeroskip_fused"}.get(
        m.group(8), "dense")
    if batch < 1 or image < 1 or (k is not None and k < 1):
        raise SystemExit(f"bad spec: {spec} (batch/k/image must be >= 1)")
    if prefetch and mode != "dispatch":
        raise SystemExit(f"bad spec: {spec} (pf applies to dispatch only)")
    if trunk_impl == "perturb" and pad_impl == "epilogue":
        # Mirrors ModelConfig validation: the epilogue kernel fuses the
        # resnet trunk's pad chains; a perturb trunk has none.
        raise SystemExit(f"bad spec: {spec} (pb is incompatible with epi)")
    if k is None:
        k = 1 if mode == "dispatch" else 8
    return (mode, batch, k, pallas, pad_mode, pad_impl, grad_impl,
            trunk_impl, upsample_impl, prefetch, image)


def _load_records() -> list:
    try:
        with open(RECORD_PATH) as f:
            return json.load(f)
    except FileNotFoundError:
        return []
    except ValueError as e:
        # A corrupt record file must ABORT, not silently reset: each row
        # cost minutes of tunnel compile time and may be unreproducible.
        raise SystemExit(
            f"{RECORD_PATH} is corrupt ({e}); refusing to overwrite — "
            "repair or move it, then re-run") from e


def _append_record(rec: dict) -> None:
    records = _load_records()
    records.append(rec)
    tmp = RECORD_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(records, f, indent=1)
    os.replace(tmp, RECORD_PATH)


def _pallas_blocked() -> str | None:
    """Return a refusal reason when a pallas spec may not run here.

    The check reads jax's EFFECTIVE platform config (after
    ensure_platform_from_env), not the env var: the axon sitecustomize
    force-overrides jax_platforms at interpreter start and
    ensure_platform_from_env swallows update failures, so the env var
    alone can say "cpu" while the process would still compile through
    the tunnel. Reading the config does not initialize a backend."""
    if os.environ.get("CYCLEGAN_ALLOW_PALLAS_REMOTE") == "1":
        return None
    from cyclegan_tpu.utils.axon_compat import local_compile_requested

    if local_compile_requested():
        # Local-compile mode builds every program (Mosaic included)
        # against the in-image libtpu; nothing crosses the
        # remote-compile leg, so pallas/epi specs are safe to run.
        return None
    import jax

    effective = str(getattr(jax.config, "jax_platforms", None) or "")
    if effective.split(",")[0] == "cpu":
        return None
    return ("refusing to send a Mosaic/pallas program through the "
            f"remote-compile leg (effective platforms={effective!r}; "
            "tunnel-lethal — postmortem incident 2). Set "
            "CYCLEGAN_ALLOW_PALLAS_REMOTE=1 to override.")


# Substrings that mark a failure of the measurement INFRASTRUCTURE (the
# remote-compile relay, its helper subprocess, or the tunnel transport)
# rather than of the config under test. Matched case-insensitively
# against the stringified exception. "http 50" covers 500/502/503/504
# from the compile relay.
INFRA_ERROR_MARKERS = (
    "remote_compile",
    "tpu_compile_helper",
    "http 50",
    "connection refused",
    "connection reset",
    "connection aborted",
    "failed to connect",
    "broken pipe",
    "socket closed",
)

# An OOM is a RESULT: it is exactly what a batch/image sweep exists to
# find the boundary of. Checked before the infra markers so an OOM whose
# traceback happens to mention the relay still records as a row.
_OOM_MARKERS = ("resource_exhausted", "out of memory", " oom")


def classify_error(msg: str) -> str:
    """'oom' | 'infra' | 'other' for a stringified measurement error."""
    low = msg.lower()
    if any(m in low for m in _OOM_MARKERS):
        return "oom"
    if any(m in low for m in INFRA_ERROR_MARKERS):
        return "infra"
    return "other"


def run_spec(spec: str) -> bool:
    """Measure one spec; returns True when the attempt died on
    infrastructure (nothing recorded, caller should exit nonzero)."""
    # abort BEFORE compile
    (mode, batch, k, pallas, pad_mode, pad_impl, grad_impl, trunk_impl,
     upsample_impl, prefetch, image) = parse_spec(spec)
    # Honor JAX_PLATFORMS=cpu (the axon sitecustomize overrides the env
    # var; main.py re-asserts it the same way) so the tool is drivable
    # off-chip and fails fast instead of hanging when the relay is down.
    from cyclegan_tpu.utils.platform import ensure_platform_from_env
    ensure_platform_from_env()

    t0 = time.perf_counter()
    rec = {"key": spec, "ts": time.strftime("%Y-%m-%dT%H:%MZ", time.gmtime())}
    # `epi`/`zsf` specs compile Mosaic kernels — same refusal gate as
    # explicit `pallas` specs (ground rule 2b).
    blocked = (_pallas_blocked()
               if (pallas or pad_impl == "epilogue"
                   or upsample_impl == "zeroskip_fused") else None)
    if blocked:
        # A refusal is a RECORDED result, like an OOM: it costs no
        # compile, and aborting here would silently drop the remaining
        # specs of an unattended multi-spec sweep.
        rec["error"] = f"refused: {blocked}"
        print(f"[sweep] {spec}: {rec['error']}", flush=True)
        rec["wall_s"] = 0.0
        _append_record(rec)
        return False
    import bench

    norm = "pallas" if pallas else "auto"
    try:
        if mode == "scan":
            ips = bench.bench_scan("bfloat16", batch, image=image,
                                   norm_impl=norm, k=k, pad_mode=pad_mode,
                                   pad_impl=pad_impl, grad_impl=grad_impl,
                                   trunk_impl=trunk_impl,
                                   upsample_impl=upsample_impl)
        elif mode == "accum":
            ips = bench.bench_accum("bfloat16", micro=batch, image=image,
                                    accum=k, norm_impl=norm,
                                    pad_mode=pad_mode, pad_impl=pad_impl,
                                    grad_impl=grad_impl,
                                    trunk_impl=trunk_impl,
                                    upsample_impl=upsample_impl)
        else:
            ips = bench.bench_dispatch("bfloat16", batch, image=image,
                                       norm_impl=norm, k=k,
                                       pad_mode=pad_mode,
                                       pad_impl=pad_impl,
                                       prefetch=prefetch,
                                       grad_impl=grad_impl,
                                       trunk_impl=trunk_impl,
                                       upsample_impl=upsample_impl)
        rec["img_per_sec"] = round(ips, 2)
        print(f"[sweep] {spec}: {ips:.2f} img/s "
              f"({time.perf_counter() - t0:.0f}s incl. compile)", flush=True)
    except Exception as e:  # OOM is a RESULT here; infra death is not
        msg = f"{type(e).__name__}: {str(e)[:300]}"
        if classify_error(msg) == "infra":
            print(f"[sweep] {spec}: INFRA FAILURE (not recorded): {msg}",
                  flush=True)
            return True
        rec["error"] = msg
        print(f"[sweep] {spec}: {rec['error']}", flush=True)
    rec["wall_s"] = round(time.perf_counter() - t0, 1)
    _append_record(rec)
    return False


def main() -> None:
    specs = sys.argv[1:]
    if not specs:
        raise SystemExit(__doc__)
    _load_records()  # fail fast on a corrupt record file, BEFORE any compile
    for spec in specs:
        parse_spec(spec)  # validate the WHOLE list before the first compile
    infra_failures = [spec for spec in specs if run_spec(spec)]
    if infra_failures:
        print(f"[sweep] {len(infra_failures)} spec(s) died on "
              f"infrastructure: {' '.join(infra_failures)} — no rows "
              "recorded for them; rerun when the relay is healthy",
              flush=True)
        raise SystemExit(3)


if __name__ == "__main__":
    main()
