#!/bin/bash
# Passive TPU-relay watch: check the loopback relay sockets every
# INTERVAL seconds (default 300) and log TRANSITIONS, so a session can
# notice recovery without spawning axon clients (socket connects only —
# never counts against the one-TPU-process rule).
#
# "up" means the legs chip work actually needs (axon_compat.relay_ok):
# the claim/execute leg :8082 AND the remote-compile leg :8093 — 8093
# alone is not enough (tests/test_relay_probe.py).
#
# Usage: nohup tools/relay_watch.sh [logfile] [interval_s] &
# First healthy signal: a "RELAY UP" line — then follow
# docs/TPU_RUNBOOK.md's queue.
LOG="${1:-/tmp/relay_watch.log}"
INTERVAL="${2:-300}"

port_open() {
  (exec 3<>"/dev/tcp/127.0.0.1/$1") 2>/dev/null
}

prev=""
while true; do
  up=down
  if port_open 8082 && port_open 8093; then
    up=up
  fi
  if [ "$up" != "$prev" ]; then
    echo "$(date +%F\ %T) relay:$up" >> "$LOG"
    if [ "$up" = up ]; then
      echo "$(date +%F\ %T) RELAY UP — run docs/TPU_RUNBOOK.md queue" >> "$LOG"
    fi
    prev="$up"
  fi
  sleep "$INTERVAL"
done
