#!/bin/bash
# Round-5 pad-scheme quality A/B (VERDICT r4 item 3): harden the round-4
# zero-pad clearance with (a) a second scale — 128^2, filters 32, 3
# residual blocks — and (b) a third reflect seed at the round-4 64^2
# scale, so the seed-noise floor is estimated from MULTIPLE replicate
# pairs at both scales.
#
# All CPU (JAX_PLATFORMS=cpu), all offline; datasets are deterministic
# (tools/make_toy_dataset.py seeds by (seed, split, index)), so the 64^2
# run is directly comparable to the four round-4 runs (docs/RESULTS.md).
# Budget: 12 epochs at 128^2 (calibrated ~6-8 min/epoch uncontended on
# this 1-core host; 60-epoch round-4 budget does not fit three 128^2
# runs in a round) — FID every 3 epochs, final metrics compared with
# tools/pad_ab_report.py.
#
# Usage: nohup tools/pad_ab_scale.sh [workdir] >/tmp/pad_ab_r5.log 2>&1 &
set -e
WORK=${1:-/tmp/pad_ab_r5}
EPOCHS=${PAD_AB_EPOCHS:-12}
cd "$(dirname "$0")/.."
mkdir -p "$WORK"

export JAX_PLATFORMS=cpu

if [ ! -d "$WORK/data128/trainA" ]; then
  python tools/make_toy_dataset.py --out "$WORK/data128" \
    --train 24 --test 8 --size 128
fi
if [ ! -d "$WORK/data64/trainA" ]; then
  # the round-4 dataset, regenerated bit-identically (seed 0 default)
  python tools/make_toy_dataset.py --out "$WORK/data64" \
    --train 64 --test 12 --size 64
fi

run128() { # name extra-flags...
  name=$1; shift
  if [ -f "$WORK/$name/.done" ]; then echo "== $name: already done"; return; fi
  echo "== $name: starting $(date +%T)"
  python -u main.py --output_dir "$WORK/$name" --epochs "$EPOCHS" \
    --batch_size 8 --data_source folder --data_dir "$WORK/data128" \
    --image_size 128 --filters 32 --residual_blocks 3 --scan_blocks \
    --verbose 0 --fid_every 3 "$@" 2>&1 | grep -v cpu_aot_loader
  touch "$WORK/$name/.done"
  echo "== $name: done $(date +%T)"
}

# order: reflect control first (its program is already in the compile
# cache from calibration), zero second (new program — one compile),
# seed replicate last (cache hit again)
run128 reflect128 --seed 1234
run128 zero128    --seed 1234 --pad_mode zero
run128 reflect128_s999 --seed 999

# round-4-scale third seed: same config as the four round-4 runs
if [ ! -f "$WORK/reflect64_s777/.done" ]; then
  echo "== reflect64_s777: starting $(date +%T)"
  python -u main.py --output_dir "$WORK/reflect64_s777" --epochs 60 \
    --batch_size 8 --data_source folder --data_dir "$WORK/data64" \
    --image_size 64 --filters 12 --residual_blocks 4 --scan_blocks \
    --verbose 0 --fid_every 10 --seed 777 2>&1 | grep -v cpu_aot_loader
  touch "$WORK/reflect64_s777/.done"
  echo "== reflect64_s777: done $(date +%T)"
fi

echo "== all runs done $(date +%T); compare with:"
echo "python tools/pad_ab_report.py --runs reflect=$WORK/reflect128 zero=$WORK/zero128 reflect999=$WORK/reflect128_s999"
