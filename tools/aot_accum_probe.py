"""Compiler check of the grad-accumulation memory contract at 512².

The designed use case (docs/BENCHMARKS.md memory ledger, TPU_RUNBOOK
item 5): `--grad_accum 8` with microbatch 1 at 512² should train where
plain batch-8 OOMs, because peak activation memory tracks the
MICRObatch while the update sees the full effective batch
(train/steps.py:make_accum_train_step). With the chip unreachable, the
real XLA:TPU compiler can still adjudicate the contract offline: the
accumulation program's compiler-reported temp HBM must sit near the
plain microbatch program's, far below the (un-compilable-on-16G)
big-batch program's.

Run: PALLAS_AXON_POOL_IPS= python tools/aot_accum_probe.py
Merges jobs into docs/aot_analysis.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

T0 = time.perf_counter()


def say(msg: str) -> None:
    print(f"[{time.perf_counter() - T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def compile_job(build):
    from tools.aot_analyze import extract_analysis

    lowered = build()
    say("compiling")
    t0 = time.perf_counter()
    compiled = lowered.compile()
    compile_s = time.perf_counter() - t0
    say(f"compiled in {compile_s:.1f}s")
    job = {"compile_seconds": round(compile_s, 1)}
    job.update(extract_analysis(compiled))
    return job


def main() -> None:
    from cyclegan_tpu.utils.axon_compat import register_axon_local

    if not register_axon_local(local_only=True):
        raise RuntimeError("axon plugin not present in this environment")
    say("registered local_only AOT backend")

    import jax
    import jax.numpy as jnp

    from cyclegan_tpu.config import Config, ModelConfig, TrainConfig
    from cyclegan_tpu.train import create_state
    from cyclegan_tpu.train.steps import make_accum_train_step, make_train_step

    image, accum, micro = 512, 8, 1
    effective = accum * micro
    cfg = Config(
        model=ModelConfig(compute_dtype="bfloat16", image_size=image),
        train=TrainConfig(batch_size=effective, grad_accum=accum),
    )
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        state = create_state(cfg, jax.random.PRNGKey(0))

    jobs = {}

    def accum_build():
        say("building accum program: 8 microbatches of 1 @ 512^2")
        step = make_accum_train_step(cfg, effective, accum)
        xs = jax.ShapeDtypeStruct((accum, micro, image, image, 3), jnp.float32)
        ws = jax.ShapeDtypeStruct((accum, micro), jnp.float32)
        return jax.jit(step, donate_argnums=(0,)).lower(state, xs, xs, ws)

    jobs["accum-probe step/bf16/accum8xmicro1/512"] = compile_job(accum_build)

    def micro_build():
        say("building plain microbatch program: b1 @ 512^2")
        step = make_train_step(cfg, 1)
        x = jax.ShapeDtypeStruct((1, image, image, 3), jnp.float32)
        w = jax.ShapeDtypeStruct((1,), jnp.float32)
        return jax.jit(step, donate_argnums=(0,)).lower(state, x, x, w)

    jobs["accum-baseline step/bf16/b1/512"] = compile_job(micro_build)

    from tools.aot_analyze import merge_into_report

    merge_into_report(jobs)
    print(json.dumps(jobs, indent=2))


if __name__ == "__main__":
    main()
