"""Warm + verify the persistent compile cache for the driver's window.

VERDICT r4 weak #6: the driver's bench window is ~480 s, a cold compile
of one fused program is 2-5 min, so a cold cache means most of
bench.py's official list budget-skips. This tool makes a fresh
container driver-ready OFFLINE: it compiles the EXACT programs
bench.py's TPU_CONFIGS (plus the chip_autorun sweep queue) request,
against the in-image libtpu via the axon ``local_only`` AOT backend,
with the persistent cache enabled (utils/platform.py — the same cache a
later chip session's local-compile path reads). No chip, relay, or
network involved.

Program identity: configs come from ``bench._config_for`` (shared
constructor) and the jit wrappers are bench's own (``_fused_k_step``,
``donate_argnums=(0,)``), so the traced HLO is bench's byte-for-byte.
The one caveat (documented in TPU_RUNBOOK): the REMOTE-compile leg
(:8093) compiles server-side with its own cache — offline warming
covers the local-compile path (CYCLEGAN_AXON_LOCAL_COMPILE=1), which
is also what chip_autorun falls back to when :8093 is down.

Hit/miss telling: a true cache hit deserializes in seconds; a miss
compiles for minutes on this 1-core host AND writes a new cache file.
Both signals are recorded per program (wall seconds + whether the
cache-dir file set grew).

Usage:
    PALLAS_AXON_POOL_IPS= python tools/cache_warm.py           # warm all
    PALLAS_AXON_POOL_IPS= python tools/cache_warm.py --check   # exit 1
        # if any official program was NOT already cached (it still
        # warms it — by completion the cache IS ready)
    python tools/cache_warm.py --list      # list programs, no compiles
Writes the report to docs/cache_warm_report.json. A program that fails
to COMPILE exits 2 in any mode (the driver window would hit the same
error).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REPORT_PATH = os.environ.get("CYCLEGAN_CACHE_WARM_REPORT") or os.path.join(
    REPO, "docs", "cache_warm_report.json")
HIT_THRESHOLD_S = 20.0


def say(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def official_programs() -> list:
    """Every distinct XLA program the driver window can request:
    bench.TPU_CONFIGS (the official list) + chip_autorun's sweep/accum
    specs + the serving engine's bucket programs (serve_programs — so a
    fresh chip lease pays serve compiles offline, not at first request).
    Returned as (key, spec-dict) with spec fields mirroring
    bench's call parameters; duplicate programs (e.g. dispatch k8 vs
    its pf variant — same XLA program, host-side staging only) are
    deduplicated by program signature."""
    import bench

    progs = []
    seen = {}

    def add(key, mode, dtype, batch, image=256, k=1, pad_mode="reflect",
            pad_impl="pad", accum=None, grad_impl="combined",
            trunk_impl="resnet", upsample_impl="dense"):
        # program signature: pf changes nothing (host-side staging);
        # steps ≡ dispatch-k1 (plain per-step jit); scan ≡ dispatch-k>1
        # (both run bench._fused_k_step's scanned program). grad_impl,
        # trunk_impl, and upsample_impl change the traced HLO, so they
        # are part of identity.
        if mode == "accum":
            prog_mode = "accum"
        elif mode == "steps" or (mode == "dispatch" and k == 1):
            prog_mode = "step"
        else:
            prog_mode = "fused_k"
        sig = (prog_mode, dtype, batch, image, k if prog_mode != "step"
               else 1, pad_mode, pad_impl, accum, grad_impl, trunk_impl,
               upsample_impl)
        if sig in seen:
            seen[sig]["covers"].append(key)
            return
        entry = {"key": key, "mode": mode, "dtype": dtype,
                 "batch": batch, "image": image, "k": k,
                 "pad_mode": pad_mode, "pad_impl": pad_impl,
                 "accum": accum, "grad_impl": grad_impl,
                 "trunk_impl": trunk_impl, "upsample_impl": upsample_impl,
                 "covers": [key]}
        seen[sig] = entry
        progs.append(entry)

    for c in bench.TPU_CONFIGS:
        add(bench._config_key(c), c["mode"], c["dtype"], c["batch"],
            image=c.get("image", 256),
            k=c.get("k", 8 if c["mode"] == "scan" else 1),
            pad_mode=c.get("pad_mode", "reflect"),
            pad_impl=c.get("pad_impl", "pad"),
            grad_impl=c.get("grad_impl", "combined"),
            trunk_impl=c.get("trunk_impl", "resnet"),
            upsample_impl=c.get("upsample_impl", "dense"))
    # chip_autorun queue rows (tools/chip_autorun.py build_queue).
    # k=8 matches chip_sweep's scan default (parse_spec) — the k the
    # sweep will actually compile; omitting it would warm k=1 programs
    # the driver never requests.
    add("sweep scan:b16zero", "scan", "bfloat16", 16, k=8, pad_mode="zero")
    add("sweep scan:b24zero", "scan", "bfloat16", 24, k=8, pad_mode="zero")
    add("sweep scan:b16fused", "scan", "bfloat16", 16, k=8,
        pad_impl="fused")
    # chip_autorun's epilogue_sweep step (pad_impl="epilogue" — the
    # Pallas trunk-epilogue program; Mosaic lowers against the local
    # libtpu, same as the runner's forced local-compile registration).
    # Dedups against the TPU_CONFIGS /epi row by signature.
    add("sweep scan:b16epi", "scan", "bfloat16", 16, k=8,
        pad_impl="epilogue")
    # chip_autorun's grad_sweep step (ISSUE 7): the fusedprop gradient
    # engine and the perturb trunk tier at the headline geometry. The
    # fp/pb rows dedup against the TPU_CONFIGS /fusedprop and /perturb
    # rows by signature; the combined b16 baseline they are compared
    # against is already warmed by row 1.
    add("sweep scan:b16fp", "scan", "bfloat16", 16, k=8,
        grad_impl="fusedprop")
    add("sweep scan:b16pb", "scan", "bfloat16", 16, k=8,
        trunk_impl="perturb")
    add("sweep scan:b16fppb", "scan", "bfloat16", 16, k=8,
        grad_impl="fusedprop", trunk_impl="perturb")
    # chip_autorun's upsample_sweep step (ISSUE 14): the zero-skip
    # upsample tiers at the headline geometry. zs/zsf dedup against the
    # TPU_CONFIGS /zskip and /zskipf rows by signature; the fp+zs combo
    # is the sweep's stacked-levers row.
    add("sweep scan:b16zs", "scan", "bfloat16", 16, k=8,
        upsample_impl="zeroskip")
    add("sweep scan:b16zsf", "scan", "bfloat16", 16, k=8,
        upsample_impl="zeroskip_fused")
    add("sweep scan:b16fpzs", "scan", "bfloat16", 16, k=8,
        grad_impl="fusedprop", upsample_impl="zeroskip")
    add("sweep accum:b1k8i512", "accum", "bfloat16", 1, image=512, k=8,
        accum=8)
    add("sweep scan:b4k2i512", "scan", "bfloat16", 4, image=512, k=2)
    add("sweep scan:b4k2zeroi512", "scan", "bfloat16", 4, image=512, k=2,
        pad_mode="zero")
    progs.extend(serve_programs())
    return progs


def serve_programs() -> list:
    """The serving engine's AOT programs (cyclegan_tpu/serve/engine.py):
    one generator forward per (size, batch bucket, dtype) of the default
    bucket grammar, traced through engine.lower_forward — byte-for-byte
    what InferenceEngine compiles at startup, so a warmed chip lease
    answers its first request without a compile. Warmed for both serving
    dtypes (f32 = checkpoint default, bf16 = the chip fast path) plus
    the fused forward+cycle program translate.py --panels requests."""
    from cyclegan_tpu.serve.engine import (
        DEFAULT_BATCH_BUCKETS,
        DEFAULT_SIZES,
    )

    progs = []
    for size in DEFAULT_SIZES:
        for batch in DEFAULT_BATCH_BUCKETS:
            for dtype in ("float32", "bfloat16"):
                short = "bf16" if dtype == "bfloat16" else "f32"
                progs.append({
                    "key": f"serve {short}:b{batch}i{size}",
                    "mode": "serve", "dtype": dtype, "batch": batch,
                    "image": size, "k": 1, "pad_mode": "reflect",
                    "pad_impl": "pad", "accum": None, "with_cycle": False,
                    "covers": [f"serve/{dtype}/b{batch}/i{size}"],
                })
                # Zero-skip serving twin (ISSUE 14): a checkpoint whose
                # model_meta records upsample_impl="zeroskip" compiles a
                # DIFFERENT forward — warm it so such a lease answers
                # its first request compile-free too.
                progs.append({
                    "key": f"serve {short}zs:b{batch}i{size}",
                    "mode": "serve", "dtype": dtype, "batch": batch,
                    "image": size, "k": 1, "pad_mode": "reflect",
                    "pad_impl": "pad", "accum": None, "with_cycle": False,
                    "upsample_impl": "zeroskip",
                    "covers": [f"serve/{dtype}/b{batch}/i{size}/zskip"],
                })
        # The int8 weight-quantized tier (server --int8 / fleet class
        # routing): f32 accumulate over per-channel-dequantized weights,
        # one program per bucket — same grammar as the base tier.
        for batch in DEFAULT_BATCH_BUCKETS:
            progs.append({
                "key": f"serve int8:b{batch}i{size}",
                "mode": "serve", "dtype": "float32", "batch": batch,
                "image": size, "k": 1, "pad_mode": "reflect",
                "pad_impl": "pad", "accum": None, "with_cycle": False,
                "quantized": True,
                "covers": [f"serve/int8/b{batch}/i{size}"],
            })
        # The int8_fused inference-only tier (server --int8_fused /
        # brownout rung below int8): the SAME quantized tree, traced
        # with upsample_impl="zeroskip_fused_int8" (upsample weights
        # stay int8 into the Pallas kernel) + forward-only norm builds.
        for batch in DEFAULT_BATCH_BUCKETS:
            progs.append({
                "key": f"serve int8f:b{batch}i{size}",
                "mode": "serve", "dtype": "float32", "batch": batch,
                "image": size, "k": 1, "pad_mode": "reflect",
                "pad_impl": "pad", "accum": None, "with_cycle": False,
                "quantized": "fused",
                "covers": [f"serve/int8_fused/b{batch}/i{size}"],
            })
        # The --panels fused two-pass program, largest bucket only
        # (panel requests are batch-CLI traffic, not the server's
        # low-latency path).
        big = DEFAULT_BATCH_BUCKETS[-1]
        progs.append({
            "key": f"serve f32cycle:b{big}i{size}",
            "mode": "serve", "dtype": "float32", "batch": big,
            "image": size, "k": 1, "pad_mode": "reflect",
            "pad_impl": "pad", "accum": None, "with_cycle": True,
            "covers": [f"serve/float32/b{big}/i{size}/cycle"],
        })
    return progs


def _lower(prog: dict):
    """Lower the exact program bench would jit for this config."""
    import jax
    import jax.numpy as jnp

    import bench
    from cyclegan_tpu.train import create_state, make_train_step

    batch, image, k = prog["batch"], prog["image"], prog["k"]
    if prog["mode"] == "serve":
        # Serving engine program: engine.lower_forward IS the trace the
        # InferenceEngine compiles at startup; params enter as
        # ShapeDtypeStruct trees (no weights needed — lowering only
        # consumes avals).
        from cyclegan_tpu.serve.engine import (
            lower_forward,
            param_specs,
            quantized_param_specs,
            serve_model_config,
        )

        model_cfg = serve_model_config(
            prog["dtype"], image,
            upsample_impl=prog.get("upsample_impl", "dense"))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            if prog.get("quantized"):
                # The int8 tier's params enter as the quantized tree
                # (int8 weights + f32 scales); eval_shape turns the
                # startup quantization into pure avals — identical
                # trace to InferenceEngine's int8_tier compile.
                p_spec = quantized_param_specs(model_cfg, (image,))
                if prog["quantized"] == "fused":
                    # int8_fused traces the fused generator (in-kernel
                    # dequant upsample, forward-only norms) against the
                    # SAME quantized avals — mirrors the engine's
                    # infer_tier compile exactly.
                    import dataclasses

                    fused_cfg = dataclasses.replace(
                        model_cfg,
                        upsample_impl="zeroskip_fused_int8",
                        instance_norm_impl="auto_fwd")
                    return lower_forward(fused_cfg, p_spec, None, batch,
                                         image, False, quantized="fused")
                return lower_forward(model_cfg, p_spec, None, batch,
                                     image, False, quantized=True)
            p_spec = param_specs(model_cfg, (image,))
        bwd = p_spec if prog.get("with_cycle") else None
        return lower_forward(model_cfg, p_spec, bwd, batch, image,
                             bool(prog.get("with_cycle")))
    if prog["mode"] == "accum":
        from cyclegan_tpu.train.steps import make_accum_train_step

        accum, micro = prog["accum"], batch
        effective = accum * micro
        cfg = bench._config_for(
            prog["dtype"], effective, image, "auto",
            prog["pad_mode"], prog["pad_impl"], grad_accum=accum,
            grad_impl=prog.get("grad_impl", "combined"),
            trunk_impl=prog.get("trunk_impl", "resnet"),
            upsample_impl=prog.get("upsample_impl", "dense"))
        with jax.default_device(jax.local_devices(backend="cpu")[0]):
            state = create_state(cfg, jax.random.PRNGKey(0))
        step = make_accum_train_step(cfg, effective, accum)
        xs = jax.ShapeDtypeStruct((accum, micro, image, image, 3),
                                  jnp.float32)
        ws = jax.ShapeDtypeStruct((accum, micro), jnp.float32)
        return jax.jit(step, donate_argnums=(0,)).lower(state, xs, xs, ws)

    cfg = bench._config_for(prog["dtype"], batch, image, "auto",
                            prog["pad_mode"], prog["pad_impl"],
                            grad_impl=prog.get("grad_impl", "combined"),
                            trunk_impl=prog.get("trunk_impl", "resnet"),
                            upsample_impl=prog.get("upsample_impl", "dense"))
    with jax.default_device(jax.local_devices(backend="cpu")[0]):
        state = create_state(cfg, jax.random.PRNGKey(0))
    step_fn = make_train_step(cfg, batch)
    x = jax.ShapeDtypeStruct((batch, image, image, 3), jnp.float32)
    w = jax.ShapeDtypeStruct((batch,), jnp.float32)
    if prog["mode"] in ("steps",) or (prog["mode"] == "dispatch" and k == 1):
        return jax.jit(step_fn, donate_argnums=(0,)).lower(state, x, x, w)
    xs = jax.ShapeDtypeStruct((k, batch, image, image, 3), jnp.float32)
    ws = jax.ShapeDtypeStruct((k, batch), jnp.float32)
    return bench._fused_k_step(step_fn, k).lower(state, xs, xs, ws)


def _cache_dir() -> str:
    return os.environ.get("JAX_COMPILATION_CACHE_DIR",
                          os.path.expanduser("~/.cache/jax_comp_cache"))


def _cache_files() -> set:
    try:
        return set(os.listdir(_cache_dir()))
    except FileNotFoundError:
        return set()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if any program was not already cached "
                         "(it is still warmed), or if readiness is "
                         "unverifiable (axon plugin absent); exit 2 on "
                         "compile errors in any mode")
    ap.add_argument("--list", action="store_true",
                    help="print the program list and exit (imports "
                         "bench/jax to read TPU_CONFIGS; no compiles)")
    ap.add_argument("--only", nargs="*", default=None, metavar="SUBSTR",
                    help="warm only programs whose key contains SUBSTR")
    args = ap.parse_args(argv)

    if args.list:
        # official_programs imports bench (and therefore jax) to read
        # TPU_CONFIGS; pin the platform so listing works with the relay
        # down and never claims the chip
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        for p in official_programs():
            print(p["key"])
        return 0

    from cyclegan_tpu.utils.axon_compat import register_axon_local

    def write_report(report: dict) -> None:
        os.makedirs(os.path.dirname(REPORT_PATH), exist_ok=True)
        tmp = REPORT_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=1)
        os.replace(tmp, REPORT_PATH)
        say(f"report -> {REPORT_PATH}")

    if not register_axon_local(local_only=True):
        # Still write a report: a later evidence reader must see THIS
        # run produced no hit/miss data, not a stale prior container's.
        write_report({"axon_plugin": "absent",
                      "ts": time.strftime("%FT%TZ", time.gmtime()),
                      "programs": []})
        say("axon plugin absent (CPU environment) — nothing to warm; the "
            "persistent cache only matters for the TPU compile path")
        # --check means "verify driver readiness" — unverifiable here
        return 1 if args.check else 0
    # register_axon_local enabled the persistent cache; lower the write
    # threshold so even fast re-compiles land
    import jax

    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    say(f"cache dir: {_cache_dir()}")
    report = {"axon_plugin": "present", "cache_dir": _cache_dir(),
              "ts": time.strftime("%FT%TZ", time.gmtime()),
              "programs": []}
    all_hit = True
    any_error = False
    progs = official_programs()
    if args.only:
        progs = [p for p in progs
                 if any(s in p["key"] for s in args.only)]
    for prog in progs:
        say(f"{prog['key']}: lowering")
        before = _cache_files()
        t0 = time.perf_counter()
        try:
            lowered = _lower(prog)
            lower_s = time.perf_counter() - t0
            say(f"{prog['key']}: compiling (persistent cache consulted)")
            t1 = time.perf_counter()
            lowered.compile()
            compile_s = time.perf_counter() - t1
        except Exception as e:
            report["programs"].append(
                {"key": prog["key"],
                 "error": f"{type(e).__name__}: {str(e)[:300]}"})
            say(f"{prog['key']}: FAILED {type(e).__name__}: {e}")
            all_hit = False
            any_error = True
            continue
        grew = len(_cache_files() - before)
        hit = compile_s < HIT_THRESHOLD_S and grew == 0
        report["programs"].append({
            "key": prog["key"], "lower_s": round(lower_s, 1),
            "compile_s": round(compile_s, 1),
            "cache_files_written": grew, "was_cached": hit,
        })
        say(f"{prog['key']}: {'HIT' if hit else 'compiled'} "
            f"({compile_s:.1f}s, {grew} cache file(s) written)")
        all_hit = all_hit and hit

    write_report(report)
    if any_error:
        # A program that cannot COMPILE is a failure in any mode — the
        # driver window would hit the same error.
        say("at least one program failed to compile")
        return 2
    if args.check and not all_hit:
        say("--check: at least one official program was cold (now warmed)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
