"""Analytic weak-scaling model for data-parallel CycleGAN training.

BASELINE.md's scaling bar (>=90% weak-scaling efficiency at global batch
256 on a v4-32 slice) cannot be measured in this environment — one chip
behind a tunnel, and virtual CPU devices tell nothing about ICI. This
model predicts the efficiency from first principles so the target does
not silently rot (companion to bench_scaling.py, which measures the same
quantity whenever a real slice is available).

Model
-----
Per step, each chip computes the fused train step on its local batch and
all-reduces the four gradient trees over the "data" mesh axis
(parallel/dp.py:73-90 — XLA inserts the collective; the reference's
NCCL analog is /root/reference/main.py:249-260).

- compute time: t_step = counted_images_per_chip / ips_1chip, with
  ips_1chip measured (docs/BENCHMARKS.md) or scaled across chip
  generations by peak-FLOPs ratio at equal MFU (conservative for newer
  chips with more HBM bandwidth per FLOP).
- comm time (no-overlap lower bound on efficiency): bidirectional-ring
  all-reduce over ONE torus dimension,
      t_comm = 2 * (N-1)/N * grad_bytes / B_ring,
  B_ring = 2 links * per-link one-way bandwidth. This is pessimistic
  twice over: XLA all-reduces over ALL torus dimensions at once (3 on
  v4, 2 on v5e), and overlaps the collective with the tail of the
  backward pass.
- efficiency = t_step / (t_step + t_comm).

Gradient bytes are counted from the REAL parameter trees (create_state
under jax.eval_shape — no arrays materialized): 4 trees, f32 grads.
Compiler cross-check (round 3): the real XLA:TPU SPMD compile of the
sharded step on a 4-chip AOT topology emits 3 fused all-reduces with a
158.7 MB total payload — 1.40x this model's 113.2 MB parameter-exact
count (tools/aot_multichip.py; docs/aot_analysis.json). Use
`--grad_bytes 158684236` to reproduce the compiler-payload variant:
predicted v4-32 efficiency moves 99.0% -> 98.7%, comfortably above the
>=90% bar either way (docs/BENCHMARKS.md).

ICI assumptions (overridable via flags; public figures):
- v4:  3D torus, 45 GB/s one-way per link  (peak 275 bf16 TFLOP/s)
- v5e: 2D torus, 45 GB/s one-way per link  (peak 197 bf16 TFLOP/s)

Usage:
  python scaling_model.py                   # the BASELINE v4-32 target
  python scaling_model.py --chip v5e --devices 16
  python scaling_model.py --link_gbps 20    # sensitivity: slower ICI
  python scaling_model.py --from_census docs/comms_census.json

`--from_census` re-predicts efficiency from a committed comms-census
artifact (obs/comms.py: the ledger reconciled against the *compiled*
program) instead of the closed-form byte estimate — the measured
data-axis all-reduce payload replaces `grad_bytes()`, and the
prediction is printed beside the closed-form one so drift between the
two is visible in every run.

Prints a per-assumption table to stderr and ONE JSON line to stdout.
"""

from __future__ import annotations

import argparse
import json
import sys

# Measured single-chip throughput (docs/BENCHMARKS.md, scan/bf16/b16 on
# the v5e chip) used to derive step time; counted images = 2 per pair.
MEASURED_V5E_IPS = 95.0
MEASURED_BATCH_PAIRS = 16

CHIPS = {
    # name: (bf16 peak TFLOP/s, torus dims, per-link one-way GB/s)
    "v4": (275.0, 3, 45.0),
    "v5e": (197.0, 2, 45.0),
}


def grad_bytes() -> int:
    """f32 bytes all-reduced per step: every parameter of all 4 trees
    (2 generators + 2 discriminators), sized from the real models."""
    import jax

    from cyclegan_tpu.config import Config
    from cyclegan_tpu.train import create_state

    cfg = Config()
    state = jax.eval_shape(lambda: create_state(cfg, jax.random.PRNGKey(0)))
    n = 0
    for tree in (state.g_params, state.f_params, state.dx_params, state.dy_params):
        n += sum(leaf.size for leaf in jax.tree.leaves(tree))
    return 4 * n


def predict(
    n_devices: int,
    batch_pairs: int,
    chip: str,
    link_gbps: float | None = None,
    ips_1chip: float | None = None,
    bytes_per_step: int | None = None,
) -> dict:
    """Predicted weak-scaling efficiency for an N-chip DP mesh."""
    peak, dims, default_link = CHIPS[chip]
    link = default_link if link_gbps is None else link_gbps
    if ips_1chip is None:
        # Equal-MFU scaling from the measured v5e rate.
        ips_1chip = MEASURED_V5E_IPS * peak / CHIPS["v5e"][0]
    d_bytes = grad_bytes() if bytes_per_step is None else bytes_per_step

    counted = 2 * batch_pairs
    t_step = counted / ips_1chip
    b_ring = 2 * link * 1e9  # bidirectional ring over one torus dimension
    t_comm = 2 * (n_devices - 1) / n_devices * d_bytes / b_ring
    eff = t_step / (t_step + t_comm)
    return {
        "chip": chip,
        "n_devices": n_devices,
        "global_batch_pairs": n_devices * batch_pairs,
        "grad_bytes_per_step": d_bytes,
        "ips_1chip": round(ips_1chip, 1),
        "t_step_ms": round(t_step * 1e3, 2),
        "t_comm_ms_no_overlap": round(t_comm * 1e3, 3),
        "predicted_efficiency": round(eff, 4),
        "assumptions": {
            "link_gbps_oneway": link,
            "torus_dims_available": dims,
            "torus_dims_used": 1,
            "overlap": "none (lower bound)",
        },
    }


def load_census_bytes(path: str, impl: str = "xla") -> dict:
    """Per-step gradient-reduction collective bytes from a comms-census
    artifact: a JSON file holding one census payload (possibly the
    `--spatial_impl both` wrapper with an `impls` map — `impl` picks
    which program), or a JSONL telemetry stream (the LAST
    `comms_census` event wins). Prefers the measured (parsed-from-HLO)
    bytes; falls back to the analytic ledger for census runs without
    HLO text. For halo programs the payload is data-axis + mesh-wide
    bytes: check_rep's kernel psums ride the same links the data
    all-reduce does."""
    payload = None
    with open(path, "r", encoding="utf-8") as f:
        text = f.read().strip()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            if "impls" in doc:
                doc = doc["impls"].get(impl) or next(
                    iter(doc["impls"].values()))
            payload = doc if "analytic" in doc else None
    except ValueError:
        doc = None
    if payload is None:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                continue
            if isinstance(ev, dict) and ev.get("event") == "comms_census":
                payload = ev
    if payload is None:
        raise SystemExit(f"no comms_census payload in {path}")
    full = (payload.get("full_size_measured") or {}).get("axes", {})
    measured = (payload.get("measured") or {}).get("axes", {})
    if full.get("data", {}).get("bytes"):
        # The advisory full-size section is the flagship program as XLA
        # actually compiled it — the right payload for the v4-32
        # question even though the gated census ran the smoke config.
        d_bytes, source = int(full["data"]["bytes"]), "measured-full-size"
        d_bytes += int(full.get("other", {}).get("bytes", 0))
    elif measured.get("data", {}).get("bytes"):
        d_bytes, source = int(measured["data"]["bytes"]), "measured"
        d_bytes += int(measured.get("other", {}).get("bytes", 0))
    else:
        d_bytes = int(payload["analytic"]["data_bytes"]
                      + payload["analytic"].get("mesh_bytes", 0))
        source = "analytic"
    return {
        "bytes_per_step": d_bytes,
        "source": source,
        "spatial_impl": payload.get("analytic", {}).get(
            "spatial_impl", "xla"),
        "mesh": payload.get("mesh", {}),
        "max_recon_error": payload.get("max_recon_error"),
    }


def load_measured_efficiency(spec: str) -> dict:
    """A measured weak-scaling efficiency: either a bare float
    ('0.973') or a path to a bench_scaling.py / MULTICHIP round
    artifact — the LAST well-formed weak_scaling_efficiency JSON line
    wins (MULTICHIP_r*.json stores the run tail under 'tail')."""
    try:
        return {"value": float(spec), "source": "literal"}
    except ValueError:
        pass
    with open(spec, "r", encoding="utf-8") as f:
        text = f.read()
    try:
        doc = json.loads(text)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "tail" in doc:
        text = doc["tail"] if isinstance(doc["tail"], str) else "\n".join(
            str(t) for t in doc["tail"])
    found = None
    for line in text.splitlines():
        line = line.strip()
        if not line or '"weak_scaling_efficiency"' not in line:
            continue
        try:
            ev = json.loads(line)
        except ValueError:
            continue
        if (isinstance(ev, dict)
                and ev.get("metric") == "weak_scaling_efficiency"):
            found = ev
    if found is None and isinstance(doc, dict) and (
            doc.get("metric") == "weak_scaling_efficiency"):
        found = doc
    if found is None:
        raise SystemExit(f"no weak_scaling_efficiency line in {spec}")
    out = {"value": float(found["value"]), "source": spec}
    for k in ("images_per_sec", "measured_devices", "spatial_impl", "mode"):
        if k in found:
            out[k] = found[k]
    return out


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--chip", default="v4", choices=sorted(CHIPS))
    p.add_argument("--devices", default=16, type=int,
                   help="chips in the DP mesh (v4-32 = 32 TensorCores = "
                        "16 megacore chips)")
    p.add_argument("--batch", default=MEASURED_BATCH_PAIRS, type=int,
                   help="per-chip batch in pairs (16 => global 256 on 16 chips)")
    p.add_argument("--link_gbps", default=None, type=float,
                   help="override per-link one-way ICI GB/s (sensitivity)")
    p.add_argument("--ips", default=None, type=float,
                   help="override single-chip images/sec (default: measured "
                        "95.0 on v5e, peak-ratio-scaled to --chip)")
    p.add_argument("--grad_bytes", default=None, type=int,
                   help="override all-reduced bytes/step (default: "
                        "parameter-exact count from the real trees; pass "
                        "158684236 for the compiler-measured payload, "
                        "tools/aot_multichip.py)")
    p.add_argument("--from_census", default=None, metavar="PATH",
                   help="comms-census artifact (JSON payload or JSONL "
                        "stream): re-predict with the compiled ledger's "
                        "data-axis bytes beside the closed-form estimate")
    p.add_argument("--census_impl", default="xla", choices=["xla", "halo"],
                   help="which program to read from a --spatial_impl both "
                        "census wrapper")
    p.add_argument("--measured", default=None, metavar="EFF_OR_PATH",
                   help="measured weak-scaling efficiency (bare float, or "
                        "a bench_scaling/MULTICHIP artifact path): emit "
                        "the predicted-vs-measured delta")
    args = p.parse_args()

    out = predict(args.devices, args.batch, args.chip,
                  link_gbps=args.link_gbps, ips_1chip=args.ips,
                  bytes_per_step=args.grad_bytes)
    print(
        f"[scaling_model] {out['chip']} x {out['n_devices']} chips, "
        f"global batch {out['global_batch_pairs']} pairs: "
        f"t_step {out['t_step_ms']} ms, all-reduce "
        f"{out['grad_bytes_per_step'] / 1e6:.1f} MB -> "
        f"{out['t_comm_ms_no_overlap']} ms (1-dim ring, no overlap) => "
        f"efficiency {out['predicted_efficiency'] * 100:.1f}%",
        file=sys.stderr,
        flush=True,
    )
    line = {
        "metric": "weak_scaling_efficiency_predicted",
        "value": out["predicted_efficiency"],
        "unit": "fraction",
        "vs_baseline": round(out["predicted_efficiency"] / 0.90, 3),
    }
    line.update(out)
    if args.from_census:
        census = load_census_bytes(args.from_census, impl=args.census_impl)
        cen_out = predict(args.devices, args.batch, args.chip,
                          link_gbps=args.link_gbps, ips_1chip=args.ips,
                          bytes_per_step=census["bytes_per_step"])
        print(
            f"[scaling_model] from census ({census['source']} data-axis "
            f"bytes, mesh {census['mesh'].get('n_data', '?')}x"
            f"{census['mesh'].get('n_spatial', '?')}): all-reduce "
            f"{cen_out['grad_bytes_per_step'] / 1e6:.1f} MB => efficiency "
            f"{cen_out['predicted_efficiency'] * 100:.1f}% "
            f"(closed-form {out['predicted_efficiency'] * 100:.1f}%)",
            file=sys.stderr,
            flush=True,
        )
        line["from_census"] = {
            "predicted_efficiency": cen_out["predicted_efficiency"],
            "grad_bytes_per_step": cen_out["grad_bytes_per_step"],
            "t_comm_ms_no_overlap": cen_out["t_comm_ms_no_overlap"],
            "source": census["source"],
            "spatial_impl": census.get("spatial_impl", "xla"),
            "census_mesh": census["mesh"],
            "census_max_recon_error": census["max_recon_error"],
        }
    if args.measured:
        meas = load_measured_efficiency(args.measured)
        predicted = line.get("from_census", {}).get(
            "predicted_efficiency", out["predicted_efficiency"])
        delta = meas["value"] - predicted
        print(
            f"[scaling_model] measured {meas['value'] * 100:.1f}% vs "
            f"predicted {predicted * 100:.1f}% => delta "
            f"{delta * 100:+.1f} points ({meas['source']})",
            file=sys.stderr,
            flush=True,
        )
        line["measured"] = meas
        line["measured_vs_predicted_delta"] = round(delta, 4)
    print(json.dumps(line), flush=True)


if __name__ == "__main__":
    main()
