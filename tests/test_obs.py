"""Telemetry subsystem (cyclegan_tpu/obs): JSONL stream semantics, run
manifest, stall watchdog, StepClock attribution, preemption-time flush,
the no-sync static guarantee, and the real-loop integration.

All CPU-runnable tier-1 — the subsystem is host-side by design, so
nothing here needs a device beyond the suite's virtual CPU mesh.
"""

import json
import os
import signal
import sys
import time

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
sys.path.insert(0, REPO)

from cyclegan_tpu.config import ObsConfig  # noqa: E402
from cyclegan_tpu.obs import (  # noqa: E402
    NULL_TELEMETRY,
    MetricsLogger,
    NullMetricsLogger,
    StallWatchdog,
    StepClock,
    build_manifest,
    make_telemetry,
    memory_watermarks,
)
from cyclegan_tpu.utils.preemption import PreemptionGuard  # noqa: E402


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---------------------------------------------------------------- JSONL


def test_jsonl_roundtrip_and_incremental_flush(tmp_path):
    path = str(tmp_path / "t.jsonl")
    log = MetricsLogger(path)
    log.event("alpha", x=1, name="a")
    log.event("beta", arr=np.float32(2.5), vec=np.arange(3))
    log.event("gamma", nested={"k": [1, 2]})

    # No close/flush call: line buffering must already have landed every
    # completed event (the property that preserves a preempted run's
    # telemetry).
    evs = _events(path)
    assert [e["event"] for e in evs] == ["alpha", "beta", "gamma"]
    # Envelope: monotonic non-decreasing t offsets.
    ts = [e["t"] for e in evs]
    assert ts == sorted(ts) and all(t >= 0 for t in ts)
    # numpy payloads serialized to JSON natives.
    assert evs[1]["arr"] == 2.5 and evs[1]["vec"] == [0, 1, 2]
    assert evs[2]["nested"] == {"k": [1, 2]}

    log.close()
    log.close()  # idempotent
    log.event("dropped", x=1)  # post-close events drop, never raise
    assert len(_events(path)) == 3


def test_jsonl_unserializable_payload_is_survivable(tmp_path):
    path = str(tmp_path / "t.jsonl")
    log = MetricsLogger(path)
    log.event("weird", obj=object())  # repr-coerced, not an exception
    log.event("after", ok=True)
    log.close()
    evs = _events(path)
    assert [e["event"] for e in evs] == ["weird", "after"]


def test_null_logger_is_silent(tmp_path):
    log = NullMetricsLogger(str(tmp_path / "never.jsonl"))
    log.event("x", a=1)
    log.flush()
    log.close()
    assert not os.path.exists(str(tmp_path / "never.jsonl"))


# ------------------------------------------------------------- manifest


def test_manifest_fields(tiny_config):
    m = build_manifest(tiny_config)
    assert m["schema_version"] >= 1
    assert m["versions"]["jax"] == jax.__version__
    assert "python" in m["versions"]
    assert isinstance(m["argv"], list) and m["pid"] == os.getpid()
    # Full config tree rides along, so the stream reproduces the run.
    assert m["config"]["data"]["source"] == "synthetic"
    assert m["config"]["model"]["image_size"] == 32
    # git SHA is best-effort but this repo IS a checkout.
    assert m["git_sha"] is None or len(m["git_sha"]) == 40
    # Device-derived fields (CPU suite: platform cpu).
    assert m["mesh"]["platform"] == "cpu"
    assert m["host"]["process_count"] >= 1
    json.dumps(m)  # the whole manifest is JSON-able


def test_manifest_without_device_query(tiny_config):
    """bench.py's mode: no backend query (a dead TPU transport blocks
    them), so no mesh/host fields unless a plan provides them."""
    m = build_manifest(None, query_devices=False, role="bench")
    assert m["role"] == "bench"
    assert "mesh" not in m and "host" not in m
    assert "jax" in m["versions"]


# ------------------------------------------------------------- watchdog


def test_watchdog_fires_on_stall_and_rearms(tmp_path):
    path = str(tmp_path / "t.jsonl")
    log = MetricsLogger(path)
    wd = StallWatchdog(log, deadline_s=0.15, poll_s=0.02,
                       depth_fn=lambda: 5, echo=False)
    wd.start()
    try:
        time.sleep(0.5)
        evs = [e for e in _events(path) if e["event"] == "stall"]
        # Fires once per stall episode, not once per poll.
        assert len(evs) == 1
        assert evs[0]["pending_depth"] == 5
        assert evs[0]["deadline_s"] == 0.15
        assert evs[0]["age_s"] > 0.15

        wd.beat()  # progress: re-arms
        time.sleep(0.5)
        evs = [e for e in _events(path) if e["event"] == "stall"]
        assert len(evs) == 2  # second episode logged
    finally:
        wd.stop()
        log.close()


def test_watchdog_quiet_while_stepping(tmp_path):
    path = str(tmp_path / "t.jsonl")
    log = MetricsLogger(path)
    wd = StallWatchdog(log, deadline_s=0.3, poll_s=0.02, echo=False)
    wd.start()
    try:
        for _ in range(10):
            time.sleep(0.05)
            wd.beat()
    finally:
        wd.stop()
        log.close()
    assert [e for e in _events(path) if e["event"] == "stall"] == []


def test_watchdog_disabled_at_zero_deadline(tmp_path):
    log = NullMetricsLogger()
    wd = StallWatchdog(log, deadline_s=0.0)
    wd.start()  # must not spawn a thread
    assert wd._thread is None
    wd.stop()


# ------------------------------------------------------------ StepClock


def _scripted_clock(times):
    """Deterministic replacement for perf_counter."""
    it = iter(times)
    return lambda: next(it)


def test_stepclock_attribution_and_aggregate(tmp_path):
    path = str(tmp_path / "t.jsonl")
    log = MetricsLogger(path)
    beats = []
    # clock() call sites: __init__, then per iteration stage_begin /
    # staged / dispatched, and finish.
    times = [
        0.0,             # __init__ (t_open)
        0.0, 1.0, 1.5,   # iter 0: stage 1.0s, dispatch 0.5s
        2.0, 2.2, 2.7,   # iter 1 (closes iter 0 at wall 2.0): stage .2, disp .5
        10.0,            # finish (closes iter 1 at wall 8.0)
    ]
    clock = StepClock(log, epoch=3, split="train", log_every=1,
                      heartbeat=lambda: beats.append(1),
                      clock=_scripted_clock(times))

    clock.stage_begin(); clock.staged()
    clock.dispatched(steps=2, kind="multi")
    clock.fetched(0.25, steps=2)

    clock.stage_begin(); clock.staged()
    clock.dispatched(steps=1, pinned=4, kind="accum")

    agg = clock.finish()

    evs = _events(path)
    steps = [e for e in evs if e["event"] == "step"]
    assert len(steps) == 2
    assert steps[0]["epoch"] == 3 and steps[0]["split"] == "train"
    assert steps[0]["steps"] == 2 and steps[0]["kind"] == "multi"
    assert steps[0]["stage_s"] == pytest.approx(1.0)
    assert steps[0]["dispatch_s"] == pytest.approx(0.5)
    assert steps[0]["fetch_block_s"] == pytest.approx(0.25)
    assert steps[0]["wall_s"] == pytest.approx(2.0)  # closed at next begin
    assert steps[1]["kind"] == "accum"

    assert agg["n_dispatches"] == 2 and agg["n_steps"] == 3
    assert agg["wall_s"] == pytest.approx(10.0)
    assert agg["stage_s"] == pytest.approx(1.2)
    assert agg["dispatch_s"] == pytest.approx(1.0)
    assert agg["fetch_block_s"] == pytest.approx(0.25)
    assert agg["starvation_fraction"] == pytest.approx(0.12)
    assert agg["wall_p50_s"] in (pytest.approx(2.0), pytest.approx(8.0))
    assert agg["wall_max_s"] == pytest.approx(8.0)
    assert evs[-1]["event"] == "epoch_steps"
    # Dispatches and fetches beat the watchdog heartbeat.
    assert len(beats) >= 3
    # accum pinned 4 then never fetched: depth drained only by finish...
    log.close()


def test_stepclock_depth_tracks_pinned_batches(tmp_path):
    log = NullMetricsLogger()
    clock = StepClock(log, epoch=0)
    clock.stage_begin(); clock.staged(); clock.dispatched(steps=8, kind="multi")
    assert clock.depth == 8
    clock.stage_begin(); clock.staged()
    clock.dispatched(steps=1, pinned=4, kind="accum")
    assert clock.depth == 12
    clock.fetched(0.0, steps=8)
    assert clock.depth == 4
    clock.fetched(0.0, steps=1, pinned=4)
    assert clock.depth == 0
    clock.drained(0.0)
    assert clock.depth == 0


def test_stepclock_loop_stall_event(tmp_path):
    """One dispatch whose loop-iteration wall blows past the rolling
    median must emit a loop_stall event carrying its attribution split —
    even at log_every=0, where per-step events are suppressed."""
    path = str(tmp_path / "t.jsonl")
    log = MetricsLogger(path)
    times = [0.0]
    t = 0.0
    for _ in range(6):  # six uniform 1.0s iterations arm + seed the median
        times += [t, t + 0.2, t + 0.4]
        t += 1.0
    times += [t, t + 0.2, t + 0.4]  # outlier iteration ...
    times += [t + 30.0]             # ... closed by finish() at wall 30.0
    clock = StepClock(log, epoch=1, split="train", log_every=0,
                      stall_multiple=10.0, clock=_scripted_clock(times))
    for _ in range(7):
        clock.stage_begin(); clock.staged(); clock.dispatched()
    agg = clock.finish()
    log.close()

    evs = _events(path)
    stalls = [e for e in evs if e["event"] == "loop_stall"]
    assert len(stalls) == 1
    s = stalls[0]
    assert s["dispatch"] == 6 and s["split"] == "train" and s["epoch"] == 1
    assert s["wall_s"] == pytest.approx(30.0)
    assert s["median_s"] == pytest.approx(1.0)
    for key in ("data_wait_s", "dispatch_s", "fetch_block_s", "host_work_s"):
        assert key in s
    assert agg["n_loop_stalls"] == 1
    # log_every=0 still suppressed the per-step records themselves.
    assert [e["event"] for e in evs if e["event"] == "step"] == []


def test_stepclock_stall_detection_needs_min_samples(tmp_path):
    """The first dispatch (compile) is routinely 100x the rest; with
    fewer than STALL_MIN_SAMPLES prior walls nothing may fire."""
    path = str(tmp_path / "t.jsonl")
    log = MetricsLogger(path)
    # iteration 0: 60s (compile); then three 1.0s iterations.
    times = [0.0, 0.0, 0.1, 0.2, 60.0, 60.1, 60.2,
             61.0, 61.1, 61.2, 62.0, 62.1, 62.2, 63.0]
    clock = StepClock(log, epoch=0, split="train", log_every=0,
                      stall_multiple=10.0, clock=_scripted_clock(times))
    for _ in range(4):
        clock.stage_begin(); clock.staged(); clock.dispatched()
    agg = clock.finish()
    log.close()
    assert agg["n_loop_stalls"] == 0
    assert all(e["event"] != "loop_stall" for e in _events(path))


def test_stepclock_submit_ready_from_deferred_fetch(tmp_path):
    """The loop's backpressure fetch proves the oldest dispatch finished;
    its submit→ready latency must land in that dispatch's OWN record,
    even though the record's wall closed earlier."""
    path = str(tmp_path / "t.jsonl")
    log = MetricsLogger(path)
    times = [0.0,
             0.0, 0.1, 0.2,   # d0 submitted at 0.2
             1.0, 1.1, 1.2,   # closes d0 at wall 1.0; d1 submitted at 1.2
             2.0]             # finish closes d1
    clock = StepClock(log, epoch=0, split="train", log_every=1,
                      clock=_scripted_clock(times))
    clock.stage_begin(); clock.staged(); clock.dispatched()
    clock.stage_begin(); clock.staged(); clock.dispatched()
    clock.fetched(0.05, at=1.7)  # d0 proven ready at 1.7 -> 1.5s latency
    agg = clock.finish()
    log.close()

    steps = [e for e in _events(path) if e["event"] == "step"]
    assert [e["dispatch"] for e in steps] == [0, 1]
    assert steps[0]["submit_ready_s"] == pytest.approx(1.5)
    assert steps[0]["host_work_s"] >= 0.0
    assert "submit_ready_s" not in steps[1]  # never proven ready
    assert agg["submit_ready_p50_s"] == pytest.approx(1.5)
    assert agg["submit_ready_max_s"] == pytest.approx(1.5)


def test_stepclock_drain_resolves_all_pending_submits(tmp_path):
    path = str(tmp_path / "t.jsonl")
    log = MetricsLogger(path)
    times = [0.0,
             0.0, 0.1, 0.2,   # d0 submitted at 0.2
             1.0, 1.1, 1.2,   # d1 submitted at 1.2
             5.0]             # finish
    clock = StepClock(log, epoch=0, split="test", log_every=1,
                      clock=_scripted_clock(times))
    clock.stage_begin(); clock.staged(); clock.dispatched()
    clock.stage_begin(); clock.staged(); clock.dispatched()
    clock.drained(0.3, n_entries=2, at=3.2)  # both proven ready at 3.2
    agg = clock.finish()
    log.close()

    steps = [e for e in _events(path) if e["event"] == "step"]
    assert steps[0]["submit_ready_s"] == pytest.approx(3.0)
    assert steps[1]["submit_ready_s"] == pytest.approx(2.0)
    assert agg["submit_ready_max_s"] == pytest.approx(3.0)


def test_stepclock_log_every_zero_keeps_only_aggregate(tmp_path):
    path = str(tmp_path / "t.jsonl")
    log = MetricsLogger(path)
    clock = StepClock(log, epoch=0, log_every=0)
    for _ in range(3):
        clock.stage_begin(); clock.staged(); clock.dispatched()
    clock.finish()
    log.close()
    kinds = [e["event"] for e in _events(path)]
    assert kinds == ["epoch_steps"]


# ----------------------------------------------------- no-sync guarantee


def test_hot_path_has_no_sync():
    """The instrumentation adds no host-device synchronization: the
    static check over train/loop.py and the whole obs/ package passes
    (block_until_ready absent, device_get only at sanctioned-fetch
    sites). This is the tier-1 wiring of tools/check_no_sync.py."""
    from check_no_sync import run_check

    assert run_check() == []


def test_check_no_sync_catches_violations(tmp_path):
    """The checker actually detects both violation classes (it isn't
    vacuously green)."""
    from check_no_sync import check_file

    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n"
        "x.block_until_ready()\n"
        "jax.device_get(x)\n"
        "jax.device_get(y)  # sanctioned-fetch: test\n"
        "# a comment mentioning block_until_ready is fine\n"
        's = "block_until_ready in a string is fine"\n'
    )
    v = check_file(str(bad), allow_sanctioned=True)
    assert len(v) == 2  # the real call + the unsanctioned device_get
    v = check_file(str(bad), allow_sanctioned=False)
    assert len(v) == 3  # marker comments don't sanction obs/ files


# ------------------------------------------------------ memory sampling


def test_memory_watermarks_shape():
    sample = memory_watermarks()
    assert isinstance(sample["available"], bool)
    assert len(sample["devices"]) == jax.local_device_count()
    for row in sample["devices"]:
        assert "id" in row and "kind" in row
    json.dumps(sample)


# ------------------------------------------------- preemption-time flush


def test_preemption_guard_runs_flush_callbacks(tmp_path):
    calls = []

    def boom():
        raise RuntimeError("broken callback must not break shutdown")

    guard = PreemptionGuard(signals=(signal.SIGUSR1,),
                            on_signal=(boom, lambda: calls.append("a")))
    guard.add_callback(lambda: calls.append("b"))
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        time.sleep(0.05)
        assert guard.requested_locally
        assert calls == ["a", "b"]
    finally:
        guard.uninstall()


def test_preemption_flushes_jsonl_tail(tmp_path):
    path = str(tmp_path / "t.jsonl")
    log = MetricsLogger(path)
    guard = PreemptionGuard(signals=(signal.SIGUSR1,), on_signal=(log.flush,))
    try:
        log.event("before_sigterm", x=1)
        os.kill(os.getpid(), signal.SIGUSR1)
        time.sleep(0.05)
        # Every event written before the signal is on disk afterwards.
        assert [e["event"] for e in _events(path)] == ["before_sigterm"]
    finally:
        guard.uninstall()
        log.close()


# ------------------------------------------------------ telemetry bundle


def test_make_telemetry_disabled_paths(tmp_path):
    out = str(tmp_path)
    assert make_telemetry(ObsConfig(enabled=False), out) is NULL_TELEMETRY
    assert make_telemetry(ObsConfig(), out, primary=False) is NULL_TELEMETRY
    assert make_telemetry(ObsConfig(jsonl_path="none"), out) is NULL_TELEMETRY
    # The null bundle's clock has the full no-op surface.
    clock = NULL_TELEMETRY.step_clock(0)
    clock.stage_begin(); clock.staged(); clock.dispatched()
    clock.fetched(0.0); clock.drained(0.0)
    assert clock.finish() == {}
    NULL_TELEMETRY.manifest(None)
    NULL_TELEMETRY.epoch(0, images_per_sec=1.0)
    NULL_TELEMETRY.memory(0)
    NULL_TELEMETRY.close()


def test_make_telemetry_default_path_and_watchdog(tmp_path):
    out = str(tmp_path / "run")
    cfg = ObsConfig(watchdog_deadline_s=30.0)
    tele = make_telemetry(cfg, out)
    try:
        assert tele.enabled
        assert tele.logger.path == os.path.join(out, "telemetry.jsonl")
        assert tele.watchdog is not None
        assert tele.watchdog.deadline_s == 30.0
        clock = tele.step_clock(0)
        clock.stage_begin(); clock.staged(); clock.dispatched()
        # The clock's depth feeds the watchdog's stall diagnostics.
        assert tele.watchdog._depth_fn() == 1
    finally:
        tele.close()
    evs = _events(tele.logger.path)
    assert evs[-1]["event"] == "end" and evs[-1]["status"] == "completed"


# ------------------------------------------------------ loop integration


def test_train_and_test_epoch_emit_stream(tiny_config, devices, tmp_path):
    """The real loop, instrumented: one train + one test pass over the
    synthetic dataset write step, epoch_steps, epoch, and memory events
    — and the run report folds them without error."""
    from cyclegan_tpu.data import build_data
    from cyclegan_tpu.parallel import make_mesh_plan, shard_test_step, shard_train_step
    from cyclegan_tpu.parallel.mesh import replicated
    from cyclegan_tpu.train import create_state, make_test_step, make_train_step
    from cyclegan_tpu.train import loop
    from cyclegan_tpu.utils.summary import NullSummary

    config = tiny_config
    plan = make_mesh_plan(config.parallel, devices[:4])
    gb = 4
    data = build_data(config, gb)
    state = jax.device_put(create_state(config, jax.random.PRNGKey(0)),
                           replicated(plan))
    train_step = shard_train_step(plan, make_train_step(config, gb))
    test_step = shard_test_step(plan, make_test_step(config, gb))
    summary = NullSummary()

    path = str(tmp_path / "telemetry.jsonl")
    tele = make_telemetry(ObsConfig(jsonl_path=path), str(tmp_path))
    tele.manifest(config, plan=plan)
    state = loop.train_epoch(config, data, plan, train_step, state, summary,
                             epoch=0, obs=tele)
    results = loop.test_epoch(config, data, plan, test_step, state, summary,
                              epoch=0, obs=tele)
    tele.epoch(0, elapse_s=1.0, images_per_sec=16.0,
               tflops_per_sec=0.001, mfu=None,
               test_metrics={k: float(v) for k, v in results.items()})
    tele.memory(0)
    tele.close()

    evs = _events(path)
    kinds = [e["event"] for e in evs]
    assert kinds[0] == "manifest" and kinds[-1] == "end"
    train_steps = [e for e in evs
                   if e["event"] == "step" and e["split"] == "train"]
    test_steps = [e for e in evs
                  if e["event"] == "step" and e["split"] == "test"]
    assert len(train_steps) == data.train_steps
    assert len(test_steps) == data.test_steps
    aggs = {(e["split"]): e for e in evs if e["event"] == "epoch_steps"}
    assert aggs["train"]["n_steps"] == data.train_steps
    assert aggs["test"]["n_dispatches"] == data.test_steps
    assert 0.0 <= aggs["train"]["starvation_fraction"] <= 1.0
    epoch_evs = [e for e in evs if e["event"] == "epoch"]
    assert epoch_evs and epoch_evs[0]["images_per_sec"] == 16.0
    assert "mfu" in epoch_evs[0]  # present even when unknown (null)
    assert any(e["event"] == "memory" for e in evs)

    # The report tool folds the real stream.
    from obs_report import fold, load_events, render

    events, skipped = load_events(path)
    assert skipped == 0
    text = render(fold(events, skipped))
    assert "starvation fraction" in text
    assert "run end: completed" in text


def test_train_epoch_without_obs_is_unchanged(tiny_config, devices):
    """obs=None (every existing caller): the loop still runs — the
    telemetry argument is strictly additive."""
    from cyclegan_tpu.data import build_data
    from cyclegan_tpu.parallel import make_mesh_plan, shard_train_step
    from cyclegan_tpu.parallel.mesh import replicated
    from cyclegan_tpu.train import create_state, make_train_step
    from cyclegan_tpu.train import loop
    from cyclegan_tpu.utils.summary import NullSummary

    config = tiny_config
    plan = make_mesh_plan(config.parallel, devices[:4])
    data = build_data(config, 4)
    state = jax.device_put(create_state(config, jax.random.PRNGKey(0)),
                           replicated(plan))
    step = shard_train_step(plan, make_train_step(config, 4))
    loop.train_epoch(config, data, plan, step, state, NullSummary(), epoch=0)


def test_print_epoch_summary_tolerates_missing_keys(capsys):
    from cyclegan_tpu.train import loop

    # A test epoch that produced no results must not raise KeyError.
    loop.print_epoch_summary({}, elapse=1.5)
    out = capsys.readouterr().out
    assert "nan" in out and "Elapse: 1.50s" in out

    loop.print_epoch_summary(
        {"error/MAE(X, F(G(X)))": 0.25}, elapse=2.0)
    out = capsys.readouterr().out
    assert "0.2500" in out
