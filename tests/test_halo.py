"""Explicit ppermute halo exchange == unsharded convolution.

The XLA-partitioner spatial path is covered by tests/test_dp.py; here the
explicit ring-exchange backend (parallel/halo.py) is held to the same
bar: bit-identical to the single-device reflect-pad / SAME conv it
replaces, on every shard including the mirrored boundary shards.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax

from cyclegan_tpu.config import ParallelConfig
from cyclegan_tpu.ops.padding import reflect_pad
from cyclegan_tpu.parallel.halo import make_sharded_conv, sharded_conv
from cyclegan_tpu.parallel.mesh import make_mesh_plan


def _reference_conv(x, k, mode):
    p = k.shape[0] // 2
    if mode == "reflect":
        y = reflect_pad(x, p)
        padding = "VALID"
    else:
        y = x
        padding = "SAME"
    return lax.conv_general_dilated(
        y, k, (1, 1), padding, dimension_numbers=("NHWC", "HWIO", "NHWC")
    )


@pytest.mark.parametrize("mode", ["reflect", "zero"])
@pytest.mark.parametrize("ksize", [3, 7])
@pytest.mark.parametrize("spatial", [4, 8])
def test_sharded_conv_matches_unsharded(devices, mode, ksize, spatial):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 32, 16, 4), jnp.float32)
    k = jnp.asarray(rng.randn(ksize, ksize, 4, 5) * 0.1, jnp.float32)

    plan = make_mesh_plan(
        ParallelConfig(spatial_parallelism=spatial), devices=devices
    )
    sharded = make_sharded_conv(plan, mode=mode)
    np.testing.assert_array_equal(
        np.asarray(sharded(x, k)), np.asarray(_reference_conv(x, k, mode))
    )


def test_halo_needs_enough_rows(devices):
    """H_local smaller than the halo is a user error, not silent garbage."""
    plan = make_mesh_plan(ParallelConfig(spatial_parallelism=8), devices=devices)
    x = jnp.zeros((1, 8, 8, 1))  # 1 row per shard < halo+1 for k=7
    k = jnp.zeros((7, 7, 1, 1))
    with pytest.raises(ValueError, match="too small for halo"):
        make_sharded_conv(plan)(x, k)


def test_zero_mode_allows_one_row_per_shard(devices):
    """Zero mode needs only `halo` local rows (no boundary mirror): a
    3x3 'SAME' conv with exactly one row per shard must work."""
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(1, 8, 8, 2), jnp.float32)
    k = jnp.asarray(rng.randn(3, 3, 2, 2) * 0.1, jnp.float32)
    plan = make_mesh_plan(ParallelConfig(spatial_parallelism=8), devices=devices)
    np.testing.assert_array_equal(
        np.asarray(make_sharded_conv(plan, mode="zero")(x, k)),
        np.asarray(_reference_conv(x, k, "zero")),
    )


def test_even_kernel_rejected(devices):
    with pytest.raises(ValueError, match="odd kernel"):
        sharded_conv(jnp.zeros((1, 8, 8, 1)), jnp.zeros((4, 4, 1, 1)), "spatial")


def test_gradients_flow_through_halo(devices):
    """d(sum(conv))/dx through the ring exchange equals the unsharded
    gradient — ppermute transposes correctly under AD."""
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(1, 16, 8, 2), jnp.float32)
    k = jnp.asarray(rng.randn(3, 3, 2, 3) * 0.1, jnp.float32)
    plan = make_mesh_plan(ParallelConfig(spatial_parallelism=4), devices=devices[:4])
    sharded = make_sharded_conv(plan)

    g_sharded = jax.grad(lambda a: jnp.sum(sharded(a, k) ** 2))(x)
    g_ref = jax.grad(lambda a: jnp.sum(_reference_conv(a, k, "reflect") ** 2))(x)
    np.testing.assert_allclose(
        np.asarray(g_sharded), np.asarray(g_ref), rtol=1e-5, atol=1e-5
    )
