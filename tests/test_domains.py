"""Domain subsystem (cyclegan_tpu/domains): the declarative registry
that makes `--domain` a data lookup, the (domain, tier) tenant-key
contract the fleet shares, and Mind2Mind transfer onboarding — parent
restore through the verified ring, encoder-freeze gradient masking, and
sidecar provenance.

Registry tests are pure host-side (specs are data); the transfer tests
run real tiny models on the CPU mesh because the freeze contract is
bit-exactness of the frozen leaves through a real jitted step.
"""

import dataclasses
import json
import os
import sys
import threading

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from cyclegan_tpu.domains.registry import (  # noqa: E402
    BUILTIN_SPECS,
    DEFAULT_DOMAIN,
    DomainError,
    DomainRegistry,
    DomainSpec,
    data_config_for,
    default_registry,
    load_registry_file,
    split_tenant_key,
    tenant_key,
)
from cyclegan_tpu.domains.transfer import (  # noqa: E402
    ENCODER_MODULES,
    TransferError,
    apply_freeze,
    check_domain_compat,
    frozen_leaves,
    mask_encoder_grads,
    restore_parent,
    sidecar_domain,
    spec_summary,
    validate_mode,
)


class _Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self.events = []

    def event(self, kind, /, **fields):
        with self._lock:
            self.events.append(dict(fields, event=kind))

    def of(self, kind):
        with self._lock:
            return [e for e in self.events if e["event"] == kind]


# -- registry: spec validation ---------------------------------------------

def test_bad_specs_fail_at_construction_with_field_named():
    with pytest.raises(DomainError, match="key"):
        DomainSpec(key="Horse2Zebra")  # uppercase breaks the grammar
    with pytest.raises(DomainError, match="source"):
        DomainSpec(key="pair", source="s3")
    with pytest.raises(DomainError, match="data_dir"):
        DomainSpec(key="pair", source="folder")  # folder needs a root
    with pytest.raises(DomainError, match="data_dir"):
        DomainSpec(key="pair", source="synthetic", data_dir="/x")
    with pytest.raises(DomainError, match="crop_size"):
        DomainSpec(key="pair", resize_size=128, crop_size=256)
    with pytest.raises(DomainError, match="group"):
        DomainSpec(key="pair", group="Bad Group")


def test_registry_refuses_duplicates_and_mixed_group_resolutions():
    with pytest.raises(DomainError, match="duplicate"):
        DomainRegistry([DomainSpec(key="pair"), DomainSpec(key="pair")])
    # One generator serves a shared group: crop sizes must agree.
    with pytest.raises(DomainError, match="mixes crop sizes"):
        DomainRegistry([
            DomainSpec(key="a2b", group="shared", crop_size=256),
            DomainSpec(key="c2d", group="shared", crop_size=128,
                       resize_size=143),
        ])


def test_builtin_registry_resolves_default_and_refuses_unknown():
    reg = default_registry()
    assert DEFAULT_DOMAIN == "horse2zebra"
    spec = reg.resolve(DEFAULT_DOMAIN)
    assert spec.source == "tfds" and spec.tfds_dataset == "horse2zebra"
    assert "apple2orange" in reg
    # The art2photo shared-generator group is populated and sorted.
    assert reg.group_members("art2photo") == [
        "cezanne2photo", "monet2photo", "ukiyoe2photo", "vangogh2photo"]
    with pytest.raises(DomainError, match="unknown domain"):
        reg.resolve("zebra2horse")
    with pytest.raises(DomainError, match="unknown shared-generator"):
        reg.group_members("nope")
    # Directional pairs must not mirror.
    assert reg.resolve("maps").augment_flip is False


def test_registry_file_merges_over_builtins_and_refuses_typos(tmp_path):
    path = tmp_path / "domains.json"
    path.write_text(json.dumps({"domains": [
        # New local-dir pair ...
        {"key": "scans2sketch", "source": "folder",
         "data_dir": str(tmp_path), "augment_flip": False},
        # ... and a redefinition of a built-in key (local mirror).
        {"key": "horse2zebra", "source": "folder",
         "data_dir": str(tmp_path)},
    ]}))
    reg = default_registry(str(path))
    assert reg.resolve("scans2sketch").data_dir == str(tmp_path)
    assert reg.resolve("horse2zebra").source == "folder"
    assert "apple2orange" in reg  # built-ins survive the merge

    bad = tmp_path / "typo.json"
    bad.write_text(json.dumps(
        {"domains": [{"key": "pair", "agument_flip": False}]}))
    with pytest.raises(DomainError, match="agument_flip"):
        load_registry_file(str(bad))
    notalist = tmp_path / "shape.json"
    notalist.write_text(json.dumps({"domains": {"key": "pair"}}))
    with pytest.raises(DomainError, match="list"):
        load_registry_file(str(notalist))


def test_second_domain_is_config_only(tiny_config):
    """The tentpole claim: onboarding apple2orange is a registry lookup
    threaded into DataConfig — no code, and non-domain knobs (the tiny
    synthetic sizes) survive the thread-through."""
    reg = default_registry()
    cfg = data_config_for(reg.resolve("apple2orange"),
                          base=tiny_config.data)
    assert cfg.domain == "apple2orange"
    assert cfg.dataset == "apple2orange"
    assert cfg.source == "tfds"
    assert cfg.synthetic_train_size == tiny_config.data.synthetic_train_size
    drill = data_config_for(reg.resolve("synthetic_drill"),
                            base=tiny_config.data)
    assert drill.source == "synthetic"
    assert drill.synthetic_train_size == 64  # spec's own drill size wins


def test_tenant_key_roundtrip_and_refusals():
    assert tenant_key("horse2zebra", "int8") == "horse2zebra/int8"
    assert split_tenant_key("horse2zebra/int8") == ("horse2zebra", "int8")
    for bad in ("horse2zebra", "/int8", "horse2zebra/", ""):
        with pytest.raises(DomainError):
            split_tenant_key(bad)
    with pytest.raises(DomainError):
        tenant_key("Bad Domain", "base")
    with pytest.raises(DomainError):
        tenant_key("horse2zebra", "a/b")


def test_builtin_specs_all_resolve_under_the_key_grammar():
    reg = DomainRegistry(BUILTIN_SPECS)
    for key in reg.keys():
        tenant_key(key, "base")  # every built-in key is tenant-safe


# -- transfer: mode + freeze mask ------------------------------------------

def test_validate_mode_refuses_unknown():
    assert validate_mode("encoder_freeze") == "encoder_freeze"
    with pytest.raises(TransferError, match="freeze_encoder"):
        validate_mode("freeze_encoder")  # the likely typo, named back


def _gen_params(tiny_config):
    import jax
    import jax.numpy as jnp

    from cyclegan_tpu.serve.engine import build_generator

    gen = build_generator(tiny_config.model)
    s = tiny_config.model.image_size
    return gen.init(jax.random.PRNGKey(0),
                    jnp.zeros((1, s, s, 3), jnp.float32))


def test_mask_zeroes_exactly_the_encoder_trunk(tiny_config):
    import jax

    params = _gen_params(tiny_config)
    masked = mask_encoder_grads(params)
    flat = jax.tree_util.tree_flatten_with_path(masked)[0]
    n_frozen = n_live = 0
    for path, leaf in flat:
        keys = {getattr(p, "key", None) for p in path}
        if keys & set(ENCODER_MODULES):
            assert not np.any(np.asarray(leaf)), f"unmasked {path}"
            n_frozen += 1
        else:
            n_live += 1
    assert n_frozen > 0 and n_live > 0
    # frozen_leaves picks out the same set the mask zeroes.
    assert len(frozen_leaves(params)) == n_frozen


def test_apply_freeze_leaves_discriminators_alone(tiny_config):
    params = _gen_params(tiny_config)
    fake_disc = {"params": {"Conv_0": np.ones((2, 2), np.float32)}}
    g, f, dx, dy = apply_freeze((params, params, fake_disc, fake_disc))
    assert dx is fake_disc and dy is fake_disc  # untouched, not even copied
    assert not np.any(np.asarray(frozen_leaves(g)[0]))
    assert not np.any(np.asarray(frozen_leaves(f)[0]))


def test_encoder_freeze_pins_params_through_a_real_step(tiny_config):
    """The end-to-end freeze contract: one jitted train step under
    transfer_mode='encoder_freeze' leaves both generators' encoder
    trunks BIT-IDENTICAL while the rest of the model moves, and the
    health metrics carry the enc_frozen group pinned at exactly 0."""
    import jax
    import jax.numpy as jnp

    from cyclegan_tpu.train import create_state, make_train_step

    cfg = dataclasses.replace(
        tiny_config,
        train=dataclasses.replace(tiny_config.train, init_from="/parent",
                                  transfer_mode="encoder_freeze"),
        obs=dataclasses.replace(tiny_config.obs, health=True),
    )
    state = create_state(cfg, jax.random.PRNGKey(0))
    s = cfg.model.image_size
    x = np.random.RandomState(0).rand(2, s, s, 3).astype(np.float32) * 2 - 1
    step = jax.jit(make_train_step(cfg, 2))
    new_state, metrics = step(state, jnp.asarray(x), jnp.asarray(x),
                              jnp.ones((2,), jnp.float32))
    for old_p, new_p in ((state.g_params, new_state.g_params),
                         (state.f_params, new_state.f_params)):
        for a, b in zip(frozen_leaves(old_p), frozen_leaves(new_p)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        moved = any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(old_p), jax.tree.leaves(new_p)))
        assert moved, "freeze must not pin the whole generator"
    assert float(metrics["health/gnorm_enc_frozen"]) == 0.0
    assert float(metrics["health/upd_ratio_enc_frozen"]) == 0.0
    # An unfrozen run must NOT emit the group (the health layer would
    # report a phantom fifth network).
    plain = jax.jit(make_train_step(tiny_config, 2))
    _, plain_metrics = plain(state, jnp.asarray(x), jnp.asarray(x),
                             jnp.ones((2,), jnp.float32))
    assert "health/gnorm_enc_frozen" not in plain_metrics


# -- transfer: domain compatibility + sidecars -----------------------------

def test_sidecar_domain_back_tags_legacy_metadata():
    assert sidecar_domain(None) == DEFAULT_DOMAIN
    assert sidecar_domain({}) == DEFAULT_DOMAIN
    assert sidecar_domain({"epoch": 3}) == DEFAULT_DOMAIN
    assert sidecar_domain({"domain": "maps"}) == "maps"


def test_check_domain_compat_warns_then_strict_refuses():
    rec = _Recorder()
    warnings = []
    assert check_domain_compat({"domain": "maps"}, "maps", strict=True)
    ok = check_domain_compat({"domain": "maps"}, "facades", strict=False,
                             telemetry=rec, echo=warnings.append)
    assert ok is False
    assert warnings and "--strict_domain" in warnings[0]
    (ev,) = rec.of("domain_mismatch")
    assert ev["checkpoint_domain"] == "maps"
    assert ev["run_domain"] == "facades"
    assert ev["strict"] is False
    with pytest.raises(DomainError, match="strict_domain"):
        check_domain_compat({"domain": "maps"}, "facades", strict=True)


# -- transfer: parent restore ----------------------------------------------

def test_restore_parent_seeds_params_fresh_optimizer(tiny_config, tmp_path):
    import jax

    from cyclegan_tpu.train import create_state
    from cyclegan_tpu.utils.checkpoint import Checkpointer

    parent = create_state(tiny_config, jax.random.PRNGKey(0))
    Checkpointer(str(tmp_path)).save(parent, epoch=7,
                                     meta={"domain": DEFAULT_DOMAIN})
    child_cfg = dataclasses.replace(
        tiny_config,
        data=dataclasses.replace(tiny_config.data, domain="apple2orange"),
        train=dataclasses.replace(tiny_config.train,
                                  init_from=str(tmp_path),
                                  transfer_mode="encoder_freeze"),
    )
    rec = _Recorder()
    template = create_state(child_cfg, jax.random.PRNGKey(1))
    state, prov = restore_parent(child_cfg, template, telemetry=rec)
    # Params came from the parent...
    for a, b in zip(jax.tree.leaves(parent.g_params),
                    jax.tree.leaves(state.g_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # ... but the optimizer state and step are the CHILD's fresh ones.
    assert state.g_opt is template.g_opt
    assert int(state.step) == 0
    assert prov == {
        "parent_ckpt": str(tmp_path),
        "parent_epoch": 7,
        "parent_domain": DEFAULT_DOMAIN,
        "transfer_mode": "encoder_freeze",
        "domain": "apple2orange",
    }
    (ev,) = rec.of("transfer_init")
    assert ev["parent_domain"] == DEFAULT_DOMAIN
    # Cross-domain is the POINT of transfer: the mismatch is recorded,
    # not fatal (strict off by default).
    (mm,) = rec.of("domain_mismatch")
    assert mm["context"] == "transfer init"
    assert spec_summary(child_cfg)["frozen_modules"] == list(ENCODER_MODULES)


def test_restore_parent_refusals(tiny_config, tmp_path):
    import jax

    from cyclegan_tpu.train import create_state
    from cyclegan_tpu.utils.checkpoint import Checkpointer

    empty = tmp_path / "empty"
    empty.mkdir()
    cfg = dataclasses.replace(
        tiny_config,
        train=dataclasses.replace(tiny_config.train,
                                  init_from=str(empty)))
    template = create_state(cfg, jax.random.PRNGKey(1))
    with pytest.raises(TransferError, match="no checkpoint slots"):
        restore_parent(cfg, template)
    # Strict mode refuses a cross-domain parent before any restore.
    ring = tmp_path / "ring"
    Checkpointer(str(ring)).save(template, epoch=0,
                                 meta={"domain": "maps"})
    strict_cfg = dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, init_from=str(ring),
                                       strict_domain=True))
    with pytest.raises(DomainError, match="strict_domain"):
        restore_parent(strict_cfg, template)


# -- static discipline ------------------------------------------------------


def test_no_sync_check_covers_domains_directory():
    """The freeze mask runs inside the jitted step, so domains/ is
    hot-path for the no-sync gate — with ZERO sanctioned fetch sites
    (False), unlike serve/'s deferred-D2H allowance."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from check_no_sync import hot_path_entries, run_check

    entries = dict(hot_path_entries())
    for mod in ("registry", "transfer", "__init__"):
        assert entries.get(f"cyclegan_tpu/domains/{mod}.py") is False
    assert run_check() == []
