"""Worker process for the two-process multi-host test (test_multihost.py).

Each process owns 2 virtual CPU devices (4 global). The worker initializes
jax.distributed, builds the 4-device data mesh, assembles its half of a
fixed global batch via shard_batch's make_array_from_process_local_data
path, runs two fused train steps, and prints the metrics as JSON — which
must be identical on every process and equal to a single-process run of
the same global batch.
"""

import json
import os
import sys

import jax

# The image's sitecustomize force-overrides jax_platforms at interpreter
# start; re-assert CPU before any backend/distributed initialization.
jax.config.update("jax_platforms", "cpu")

jax.distributed.initialize(
    coordinator_address=os.environ["TEST_COORD"],
    num_processes=int(os.environ["TEST_NPROC"]),
    process_id=int(os.environ["TEST_PID"]),
)

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cyclegan_tpu.config import tiny_test_config  # noqa: E402
from cyclegan_tpu.parallel import make_mesh_plan, shard_batch, shard_train_step  # noqa: E402
from cyclegan_tpu.parallel.mesh import replicated  # noqa: E402
from cyclegan_tpu.train import create_state, make_train_step  # noqa: E402


def main():
    assert jax.process_count() == int(os.environ["TEST_NPROC"])
    # Defaults preserve the original 2-proc x 2-local = 4-device layout;
    # TEST_LOCAL_DEVICES / TEST_SPATIAL widen it (e.g. 2 x 4 = 8 global
    # with a 4x2 data x spatial mesh — halo exchange composing with the
    # cross-process runtime).
    local = int(os.environ.get("TEST_LOCAL_DEVICES", "2"))
    spatial = int(os.environ.get("TEST_SPATIAL", "1"))
    n_global = local * jax.process_count()
    assert len(jax.devices()) == n_global

    import dataclasses

    config = tiny_test_config()
    config = dataclasses.replace(
        config,
        parallel=dataclasses.replace(
            config.parallel, spatial_parallelism=spatial
        ),
    )
    plan = make_mesh_plan(config.parallel)
    assert plan.n_data == n_global // spatial
    global_batch = plan.n_data

    state = create_state(config, jax.random.PRNGKey(0))
    state = jax.device_put(state, replicated(plan))
    step = shard_train_step(plan, make_train_step(config, global_batch))

    s = config.model.image_size
    rng = np.random.RandomState(0)  # same stream on every process
    for i in range(2):
        x = rng.rand(global_batch, s, s, 3).astype(np.float32) * 2 - 1
        y = rng.rand(global_batch, s, s, 3).astype(np.float32) * 2 - 1
        w = np.ones((global_batch,), np.float32)
        # Each process passes only ITS slice; shard_batch assembles the
        # global arrays from process-local data (the DCN input story).
        per = global_batch // jax.process_count()
        lo = jax.process_index() * per
        xs, ys, ws = shard_batch(plan, x[lo:lo + per], y[lo:lo + per], w[lo:lo + per])
        state, metrics = step(state, xs, ys, ws)

    out = {k: float(v) for k, v in jax.device_get(metrics).items()}
    print("METRICS " + json.dumps(out, sort_keys=True), flush=True)

    # Cross-host FID reduction: each process accumulates only ITS slice
    # of fixed global feature sets; after allreduce_accumulators every
    # process must hold the full-set statistics (FID vs the whole-set
    # accumulator == 0 up to float roundoff, identically on all hosts).
    from cyclegan_tpu.eval.fid import (
        FIDAccumulator,
        allreduce_accumulators,
        fid_from_accumulators,
    )

    # THREE accumulators reduced in ONE collective, with distinct feature
    # sets per accumulator: exercises the j>0 stride-slice path of the
    # batched payload layout (evaluate.py reduces four per FID sweep) —
    # an offset bug in any slice must fail here, not just for j=0.
    n_acc = 3
    feat_sets = [
        np.random.RandomState(7 + j).randn(33 + 4 * j, 16) for j in range(n_acc)
    ]  # same on every process; ODD sizes so per-host counts are ragged
    wholes, locals_ = [], []
    for feats in feat_sets:
        whole = FIDAccumulator(16)
        whole.update(feats)
        wholes.append(whole)
        per = feats.shape[0] // jax.process_count()
        lo = jax.process_index() * per
        local = FIDAccumulator(16)
        # Remainder rows go to the last process so counts differ per host.
        hi = lo + per if jax.process_index() < jax.process_count() - 1 else None
        local.update(feats[lo:hi])
        locals_.append(local)
    merged = allreduce_accumulators(locals_)

    # The uint32 bit-preserving gather makes the reduction EXACT in f64,
    # not merely close: expose the max moment deviation for the test.
    fid = moment_err = 0.0
    n_total = []
    for whole, m in zip(wholes, merged):
        # abs(): a negative distance regression must not hide under max().
        fid = max(fid, abs(fid_from_accumulators(m, whole)))
        mu_w, cov_w = whole.stats()
        mu_m, cov_m = m.stats()
        moment_err = max(
            moment_err,
            float(np.abs(mu_w - mu_m).max()),
            float(np.abs(cov_w - cov_m).max()),
        )
        n_total.append(m.n)
    print("FID " + json.dumps({"n": n_total, "fid_vs_whole": float(fid),
                               "moment_err": moment_err}),
          flush=True)


if __name__ == "__main__":
    main()
