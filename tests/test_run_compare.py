"""tools/run_compare.py: the cross-run regression gate.

Two kinds of pins: (1) the committed fixture streams under tests/data/
(run_base / run_pass / run_fail) gate deterministically — a healthy
candidate exits 0, a regressed one trips every stream axis and exits
1; (2) the repo's own committed BENCH_r*.json series must pass its own
gate (including the legal cpu->tpu platform change, which SKIPs
rather than fails).
"""

from __future__ import annotations

import glob
import io
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
))

import run_compare  # noqa: E402
from run_compare import (  # noqa: E402
    FAIL,
    PASS,
    SKIP,
    bench_profile,
    compare_profiles,
    load_profile,
    make_thresholds,
    stream_profile,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "tests", "data")
BASE = os.path.join(DATA, "run_base.jsonl")
GOOD = os.path.join(DATA, "run_pass.jsonl")
BAD = os.path.join(DATA, "run_fail.jsonl")


# ------------------------------------------------- profile extraction


def test_stream_profile_from_fixture():
    p = load_profile(BASE)
    assert p["kind"] == "stream"
    assert p["n_epochs"] == 3
    assert p["throughput"] == pytest.approx(100.0)
    assert p["final_losses"]["loss_G/total"] == pytest.approx(2.8)
    assert p["gnorm_max"]["G"] == pytest.approx(2.0)  # max over epochs
    assert p["n_faults"] == 0 and p["end_status"] == "completed"


def test_stream_profile_counts_faults_and_skips_garbage():
    events = [
        {"event": "health_fault", "kind": "divergence"},
        {"event": "health_fault", "kind": "divergence"},
        {"event": "health_fault", "kind": "nonfinite"},
        {"event": "stall"},
        {"event": "loop_stall"},
        {"event": "mystery_future_kind"},  # unknown events ignored
    ]
    p = stream_profile(events, skipped=2)
    assert p["faults"] == {"divergence": 2, "nonfinite": 1}
    assert p["n_faults"] == 3 and p["n_stalls"] == 2
    assert p["skipped_lines"] == 2
    assert p["throughput"] is None  # no epoch events


def test_bench_profile_wrapped_and_bare():
    parsed = {"metric": "images_per_sec", "value": 95.17, "platform": "tpu",
              "config": "scan/bfloat16/b16", "unit": "images/sec",
              "all": {"a": 1.0, "b": "garbage", "c": None}}
    for record in (parsed, {"parsed": parsed, "rc": 0}):
        p = bench_profile(record)
        assert p["kind"] == "bench" and p["value"] == pytest.approx(95.17)
        assert p["all"] == {"a": 1.0}  # non-floats profiled out


def test_nan_profiles_as_missing():
    assert run_compare._float(float("nan")) is None
    assert run_compare._float("1.5") == 1.5
    assert run_compare._float(None) is None


# ------------------------------------------------- the gate


def test_fixture_pair_passes():
    assert run_compare.run([BASE, GOOD], make_thresholds(),
                           out=io.StringIO()) == 0


def test_fixture_pair_fails_on_every_stream_axis():
    checks = compare_profiles(load_profile(BASE), load_profile(BAD),
                              make_thresholds())
    failed_axes = {axis for s, axis, _ in checks if s == FAIL}
    assert "throughput" in failed_axes            # 100 -> ~59 img/s
    assert "loss loss_G/total" in failed_axes     # 2.8 -> 12.4
    assert "gnorm G" in failed_axes               # 2.0 -> 80 max envelope
    assert "anomalies" in failed_axes             # 0 -> 2 faults
    # The healthy networks still pass: the gate localizes the blowup.
    assert (PASS, "gnorm F") in [(s, a) for s, a, _ in checks]
    assert run_compare.run([BASE, BAD], make_thresholds(),
                           out=io.StringIO()) == 1


def test_thresholds_are_adjustable():
    th = make_thresholds(max_throughput_drop=0.9, max_loss_increase=10.0,
                         max_gnorm_ratio=100.0, max_new_faults=5)
    assert run_compare.run([BASE, BAD], th, out=io.StringIO()) == 0


def test_mixed_artifact_kinds_fail():
    bench = os.path.join(REPO, "BENCH_r01.json")
    checks = compare_profiles(load_profile(bench), load_profile(BASE),
                              make_thresholds())
    assert checks[0][0] == FAIL and checks[0][1] == "kind"


# ------------------------------------------------- committed BENCH series


def _bench_series():
    return sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))


def test_committed_bench_series_passes_gate():
    """The repo's own committed rounds are the gate's first real user:
    the full consecutive-pair series must exit 0 today, so a future
    round that regresses >10% makes THIS test point at the pair."""
    series = _bench_series()
    assert len(series) >= 2
    assert run_compare.run(series, make_thresholds(), out=io.StringIO()) == 0


def test_cross_platform_bench_pair_skips():
    """r01..r04 are cpu seed rounds, r05 the first tpu round: a platform
    change is SKIP (perf not comparable), never FAIL."""
    profiles = [load_profile(p) for p in _bench_series()]
    platforms = [p["platform"] for p in profiles]
    for base, cand in zip(profiles, profiles[1:]):
        checks = compare_profiles(base, cand, make_thresholds())
        if base["platform"] != cand["platform"]:
            assert [s for s, _, _ in checks] == [SKIP]
    # The committed series actually exercises the skip path.
    assert len(set(platforms)) > 1


def test_output_is_deterministic():
    def render():
        buf = io.StringIO()
        run_compare.run([BASE, GOOD, BAD], make_thresholds(json=True),
                        out=buf)
        return buf.getvalue()

    first = render()
    assert first == render()
    parsed = json.loads(first)
    assert [p["cand"] for p in parsed] == ["run_pass.jsonl", "run_fail.jsonl"]


def test_cli_exit_codes(capsys):
    assert run_compare.main([BASE, GOOD]) == 0
    assert run_compare.main([BASE, BAD]) == 1
    assert run_compare.main(["/nonexistent.jsonl", BASE]) == 2
    capsys.readouterr()
