"""tools/run_compare.py: the cross-run regression gate.

Two kinds of pins: (1) the committed fixture streams under tests/data/
(run_base / run_pass / run_fail) gate deterministically — a healthy
candidate exits 0, a regressed one trips every stream axis and exits
1; (2) the repo's own committed BENCH_r*.json series must pass its own
gate (including the legal cpu->tpu platform change, which SKIPs
rather than fails).
"""

from __future__ import annotations

import glob
import io
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"
))

import run_compare  # noqa: E402
from run_compare import (  # noqa: E402
    FAIL,
    PASS,
    SKIP,
    bench_profile,
    compare_profiles,
    load_profile,
    make_thresholds,
    stream_profile,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "tests", "data")
BASE = os.path.join(DATA, "run_base.jsonl")
GOOD = os.path.join(DATA, "run_pass.jsonl")
BAD = os.path.join(DATA, "run_fail.jsonl")


# ------------------------------------------------- profile extraction


def test_stream_profile_from_fixture():
    p = load_profile(BASE)
    assert p["kind"] == "stream"
    assert p["n_epochs"] == 3
    assert p["throughput"] == pytest.approx(100.0)
    assert p["final_losses"]["loss_G/total"] == pytest.approx(2.8)
    assert p["gnorm_max"]["G"] == pytest.approx(2.0)  # max over epochs
    assert p["n_faults"] == 0 and p["end_status"] == "completed"


def test_stream_profile_counts_faults_and_skips_garbage():
    events = [
        {"event": "health_fault", "kind": "divergence"},
        {"event": "health_fault", "kind": "divergence"},
        {"event": "health_fault", "kind": "nonfinite"},
        {"event": "stall"},
        {"event": "loop_stall"},
        {"event": "mystery_future_kind"},  # unknown events ignored
    ]
    p = stream_profile(events, skipped=2)
    assert p["faults"] == {"divergence": 2, "nonfinite": 1}
    assert p["n_faults"] == 3 and p["n_stalls"] == 2
    assert p["skipped_lines"] == 2
    assert p["throughput"] is None  # no epoch events


def test_bench_profile_wrapped_and_bare():
    parsed = {"metric": "images_per_sec", "value": 95.17, "platform": "tpu",
              "config": "scan/bfloat16/b16", "unit": "images/sec",
              "all": {"a": 1.0, "b": "garbage", "c": None}}
    for record in (parsed, {"parsed": parsed, "rc": 0}):
        p = bench_profile(record)
        assert p["kind"] == "bench" and p["value"] == pytest.approx(95.17)
        assert p["all"] == {"a": 1.0}  # non-floats profiled out


def test_nan_profiles_as_missing():
    assert run_compare._float(float("nan")) is None
    assert run_compare._float("1.5") == 1.5
    assert run_compare._float(None) is None


# ------------------------------------------------- the gate


def test_fixture_pair_passes():
    assert run_compare.run([BASE, GOOD], make_thresholds(),
                           out=io.StringIO()) == 0


def test_fixture_pair_fails_on_every_stream_axis():
    checks = compare_profiles(load_profile(BASE), load_profile(BAD),
                              make_thresholds())
    failed_axes = {axis for s, axis, _ in checks if s == FAIL}
    assert "throughput" in failed_axes            # 100 -> ~59 img/s
    assert "loss loss_G/total" in failed_axes     # 2.8 -> 12.4
    assert "gnorm G" in failed_axes               # 2.0 -> 80 max envelope
    assert "anomalies" in failed_axes             # 0 -> 2 faults
    # The healthy networks still pass: the gate localizes the blowup.
    assert (PASS, "gnorm F") in [(s, a) for s, a, _ in checks]
    assert run_compare.run([BASE, BAD], make_thresholds(),
                           out=io.StringIO()) == 1


def test_thresholds_are_adjustable():
    th = make_thresholds(max_throughput_drop=0.9, max_loss_increase=10.0,
                         max_gnorm_ratio=100.0, max_new_faults=5)
    assert run_compare.run([BASE, BAD], th, out=io.StringIO()) == 0


def test_mixed_artifact_kinds_fail():
    bench = os.path.join(REPO, "BENCH_r01.json")
    checks = compare_profiles(load_profile(bench), load_profile(BASE),
                              make_thresholds())
    assert checks[0][0] == FAIL and checks[0][1] == "kind"


# ------------------------------------------------- serving axis


def _serve_record(**over):
    rec = {
        "metric": "cyclegan_serve_images_per_sec_1chip",
        "value": 150.0, "unit": "images/sec", "platform": "cpu",
        "config": "serve/float32/b4/i64",
        "latency_low_load_ms": {"p50_ms": 12.0, "p95_ms": 14.0},
        "latency_saturated_ms": {"p50_ms": 80.0, "p95_ms": 140.0},
        "fleet": {
            "n_replicas": 2, "images_per_sec": 165.0,
            "latency_saturated_ms": {"p50_ms": 130.0, "p95_ms": 140.0},
            "overload": {
                "shed_by_class": {"best_effort": 5},
                "interactive_p95_ms": 70.0, "batch_p95_ms": 75.0,
            },
        },
        "int8": {"images_per_sec": 168.0, "p95_ms": 136.0},
    }
    rec.update(over)
    return rec


def test_serve_profile_extracts_fleet_and_classes():
    p = run_compare.serve_profile(_serve_record(), "x.json")
    assert p["kind"] == "serve"
    assert p["value"] == pytest.approx(150.0)
    assert p["fleet_ips"] == pytest.approx(165.0)
    assert p["int8_ips"] == pytest.approx(168.0)
    assert p["p95_ms"]["low_load"] == pytest.approx(14.0)
    assert p["p95_ms"]["overload interactive"] == pytest.approx(70.0)
    assert p["shed_by_class"] == {"best_effort": 5}


def test_serve_pair_passes_and_gates_regressions(tmp_path):
    base = tmp_path / "base.json"
    base.write_text(json.dumps(_serve_record()) + "\n")
    same = tmp_path / "same.json"
    same.write_text(json.dumps(_serve_record()) + "\n")
    assert run_compare.run([str(base), str(same)], make_thresholds(),
                           out=io.StringIO()) == 0
    # A fleet-throughput collapse and a p95 blowup each trip the gate.
    slow = tmp_path / "slow.json"
    bad_fleet = _serve_record()
    bad_fleet["fleet"] = dict(bad_fleet["fleet"], images_per_sec=100.0)
    slow.write_text(json.dumps(bad_fleet) + "\n")
    assert run_compare.run([str(base), str(slow)], make_thresholds(),
                           out=io.StringIO()) == 1
    lat = tmp_path / "lat.json"
    bad_lat = _serve_record(
        latency_low_load_ms={"p50_ms": 12.0, "p95_ms": 50.0})
    lat.write_text(json.dumps(bad_lat) + "\n")
    assert run_compare.run([str(base), str(lat)], make_thresholds(),
                           out=io.StringIO()) == 1


def test_serve_shed_ordering_invariant():
    """A candidate that shed interactive while best_effort went unshed
    violates the class-ordering contract — FAIL regardless of speed."""
    base = run_compare.serve_profile(_serve_record(), "base.json")
    bad = _serve_record()
    bad["fleet"] = dict(bad["fleet"],
                        overload={"shed_by_class": {"interactive": 2},
                                  "interactive_p95_ms": 70.0})
    cand = run_compare.serve_profile(bad, "cand.json")
    checks = compare_profiles(base, cand, make_thresholds())
    assert (FAIL, "serve shed ordering") in [(s, a) for s, a, _ in checks]


def _autoscale_block(**over):
    blk = {
        "min_replicas": 1, "max_replicas": 2, "brownout_enabled": True,
        "phases": {
            "surge": {"offered_rate": 300.0, "duration_s": 2.5,
                      "shed_by_class": {"best_effort": 3},
                      "interactive_p95_ms": 40.0, "batch_p95_ms": 90.0},
            "sustain": {"offered_rate": 195.0, "duration_s": 2.0,
                        "shed_by_class": {},
                        "interactive_p95_ms": 20.0},
            "decay": {"offered_rate": 37.5, "duration_s": 2.0,
                      "shed_by_class": {},
                      "interactive_p95_ms": 15.0},
        },
        "scale_events": [{"event": "fleet_autoscale", "phase": "up",
                          "n_active": 2, "t_s": 0.4}],
        "scale_ups": 1, "scale_downs": 1,
        "degraded_requests": 120,
        "fixed_fleet_interactive_p95_ms": 70.0,
    }
    blk.update(over)
    return blk


def test_serve_profile_extracts_autoscale_phase():
    rec = _serve_record()
    rec["fleet"] = dict(rec["fleet"], autoscale=_autoscale_block())
    p = run_compare.serve_profile(rec, "x.json")
    assert p["has_autoscale"] and p["autoscale_brownout"]
    assert p["p95_ms"]["autoscale surge interactive"] == pytest.approx(40.0)
    assert p["p95_ms"]["autoscale decay interactive"] == pytest.approx(15.0)
    assert p["autoscale_shed_by_class"] == {"best_effort": 3}
    assert p["autoscale_surge_interactive_p95"] == pytest.approx(40.0)
    assert p["fixed_fleet_interactive_p95"] == pytest.approx(70.0)


def test_serve_autoscale_gates():
    """The three autoscale-phase candidate invariants: brownout
    ordering (degrade before shed), zero interactive sheds, and the
    surge interactive p95 bounded by the fixed fleet's overload p95."""
    base = run_compare.serve_profile(_serve_record(), "base.json")

    def cand_with(**over):
        rec = _serve_record()
        rec["fleet"] = dict(rec["fleet"],
                            autoscale=_autoscale_block(**over))
        return run_compare.serve_profile(rec, "cand.json")

    ok = compare_profiles(base, cand_with(), make_thresholds())
    for axis in ("serve brownout ordering",
                 "serve autoscale interactive shed",
                 "serve autoscale surge p95"):
        assert (PASS, axis) in [(s, a) for s, a, _ in ok], axis

    # Shed without a single degradation: the ladder was skipped.
    bad = compare_profiles(base, cand_with(degraded_requests=0),
                           make_thresholds())
    assert (FAIL, "serve brownout ordering") in [(s, a) for s, a, _ in bad]

    # Any interactive shed during the trace fails.
    phases = _autoscale_block()["phases"]
    phases["surge"] = dict(phases["surge"],
                           shed_by_class={"interactive": 1})
    bad = compare_profiles(base, cand_with(phases=phases),
                           make_thresholds())
    assert (FAIL, "serve autoscale interactive shed") \
        in [(s, a) for s, a, _ in bad]

    # Surge interactive p95 above the fixed-fleet reference fails.
    phases = _autoscale_block()["phases"]
    phases["surge"] = dict(phases["surge"], interactive_p95_ms=71.0)
    bad = compare_profiles(base, cand_with(phases=phases),
                           make_thresholds())
    assert (FAIL, "serve autoscale surge p95") \
        in [(s, a) for s, a, _ in bad]


def test_serve_cross_platform_pair_skips():
    base = run_compare.serve_profile(_serve_record(), "base.json")
    cand = run_compare.serve_profile(_serve_record(platform="tpu"),
                                     "cand.json")
    checks = compare_profiles(base, cand, make_thresholds())
    assert [s for s, _, _ in checks] == [SKIP]


# ------------------------------------------------- committed BENCH series


def _bench_series():
    return sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))


def test_committed_bench_series_passes_gate():
    """The repo's own committed rounds are the gate's first real user:
    the full consecutive-pair series must exit 0 today, so a future
    round that regresses >10% makes THIS test point at the pair."""
    series = _bench_series()
    assert len(series) >= 2
    assert run_compare.run(series, make_thresholds(), out=io.StringIO()) == 0


def test_cross_platform_bench_pair_skips():
    """r01..r04 are cpu seed rounds, r05 the first tpu round: a platform
    change is SKIP (perf not comparable), never FAIL."""
    profiles = [load_profile(p) for p in _bench_series()]
    platforms = [p["platform"] for p in profiles]
    for base, cand in zip(profiles, profiles[1:]):
        checks = compare_profiles(base, cand, make_thresholds())
        if base["platform"] != cand["platform"]:
            assert [s for s, _, _ in checks] == [SKIP]
    # The committed series actually exercises the skip path.
    assert len(set(platforms)) > 1


def test_output_is_deterministic():
    def render():
        buf = io.StringIO()
        run_compare.run([BASE, GOOD, BAD], make_thresholds(json=True),
                        out=buf)
        return buf.getvalue()

    first = render()
    assert first == render()
    parsed = json.loads(first)
    assert [p["cand"] for p in parsed] == ["run_pass.jsonl", "run_fail.jsonl"]


def test_cli_exit_codes(capsys):
    assert run_compare.main([BASE, GOOD]) == 0
    assert run_compare.main([BASE, BAD]) == 1
    assert run_compare.main(["/nonexistent.jsonl", BASE]) == 2
    capsys.readouterr()


# ------------------------------------------------- upsample-impl axis


def _impl_stream(impl, loss=2.8):
    events = [
        {"event": "manifest",
         "config": {"data": {"domain": "horse2zebra"},
                    "model": {"upsample_impl": impl}}},
        {"event": "epoch", "train_images_per_sec": 100.0},
        {"event": "health", "loss": {"loss_G/total": loss}},
        {"event": "end", "status": "completed"},
    ]
    return stream_profile(events, name=f"run_{impl}.jsonl")


def test_stream_profile_extracts_upsample_impl():
    assert _impl_stream("zeroskip")["upsample_impl"] == "zeroskip"
    # streams predating the engine profile as None and stay off the axis
    p = stream_profile([{"event": "epoch", "train_images_per_sec": 1.0}])
    assert p["upsample_impl"] is None
    checks = compare_profiles(p, p, make_thresholds())
    assert not [c for c in checks if c[1] == "upsample-impl"]


def test_upsample_impl_change_gates_losses():
    base = _impl_stream("dense")
    # equivalent trajectories: the impl change PASSes the axis
    ok = compare_profiles(base, _impl_stream("zeroskip"), make_thresholds())
    row = next(c for c in ok if c[1] == "upsample-impl")
    assert row[0] == PASS and "dense -> zeroskip" in row[2]
    # a drifted loss FAILs the axis (plus the regular loss gate)
    bad = compare_profiles(base, _impl_stream("zeroskip_fused", loss=9.9),
                           make_thresholds())
    assert next(c for c in bad if c[1] == "upsample-impl")[0] == FAIL


def test_upsample_impl_change_never_skips_silently():
    """An impl change with nothing to gate against must FAIL, not SKIP:
    a divergent kernel shipping behind a missing trajectory is exactly
    what the axis exists to catch."""
    base = _impl_stream("dense")
    cand = stream_profile([
        {"event": "manifest",
         "config": {"data": {"domain": "horse2zebra"},
                    "model": {"upsample_impl": "zeroskip"}}},
        {"event": "epoch", "train_images_per_sec": 100.0},
    ], name="no_losses.jsonl")
    checks = compare_profiles(base, cand, make_thresholds())
    row = next(c for c in checks if c[1] == "upsample-impl")
    assert row[0] == FAIL and "never skip" in row[2]


def test_same_upsample_impl_reports_info():
    checks = compare_profiles(_impl_stream("zeroskip"),
                              _impl_stream("zeroskip"), make_thresholds())
    row = next(c for c in checks if c[1] == "upsample-impl")
    assert row[0] == "INFO"
