"""Pallas instance-norm kernel vs the XLA reference implementation —
forward and backward — run in interpret mode on CPU (the driver/bench
exercise the compiled TPU path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu.ops.norm import _instance_norm_xla
from cyclegan_tpu.ops.pallas.norm_kernel import (
    MAX_RESIDENT_HW,
    eligible,
    instance_norm_pallas,
)


def _rand(shape, seed=0, dtype=jnp.float32):
    k = jax.random.PRNGKey(seed)
    return (jax.random.normal(k, shape) * 2 + 0.5).astype(dtype)


@pytest.mark.parametrize("shape", [(2, 8, 8, 128), (1, 16, 16, 256), (2, 4, 4, 64), (1, 8, 8, 32)])
def test_pallas_forward_matches_xla(shape):
    x = _rand(shape)
    c = shape[-1]
    scale = _rand((c,), 1)
    bias = _rand((c,), 2)
    got = instance_norm_pallas(x, scale, bias, eps=1e-3, interpret=True)
    want = _instance_norm_xla(x, scale, bias, eps=1e-3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_pallas_backward_matches_xla():
    shape = (2, 8, 8, 64)
    x = _rand(shape)
    scale = _rand((shape[-1],), 1)
    bias = _rand((shape[-1],), 2)

    def loss_pallas(x, s, b):
        y = instance_norm_pallas(x, s, b, eps=1e-3, interpret=True)
        return jnp.sum(jnp.sin(y) * y)

    def loss_xla(x, s, b):
        y = _instance_norm_xla(x, s, b, eps=1e-3)
        return jnp.sum(jnp.sin(y) * y)

    g_p = jax.grad(loss_pallas, argnums=(0, 1, 2))(x, scale, bias)
    g_x = jax.grad(loss_xla, argnums=(0, 1, 2))(x, scale, bias)
    for a, b_, name in zip(g_p, g_x, ["dx", "dscale", "dbias"]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-5, err_msg=name
        )


def test_pallas_bfloat16_forward():
    shape = (1, 8, 8, 128)
    x = _rand(shape, dtype=jnp.bfloat16)
    scale = _rand((128,), 1)
    bias = _rand((128,), 2)
    got = instance_norm_pallas(x, scale, bias, interpret=True)
    assert got.dtype == jnp.bfloat16
    want = _instance_norm_xla(x, scale, bias, eps=1e-3)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32), rtol=0.05, atol=0.05
    )


def test_eligibility_gate():
    assert eligible((1, 64, 64, 256))  # generator trunk at 256^2
    assert not eligible((1, 256, 256, 64))  # outermost layer: too big
    assert not eligible((1, 64, 64))  # not 4-D
    assert MAX_RESIDENT_HW * 128 * 4 <= 8 * 1024 * 1024


def test_ineligible_raises():
    x = _rand((1, 128, 128, 64))
    with pytest.raises(NotImplementedError):
        instance_norm_pallas(x, jnp.ones(64), jnp.zeros(64), interpret=True)
