"""Gradient accumulation (train/steps.py make_accum_train_step).

The contract is EXACTNESS, not approximation: losses scale as
sum(w*per_sample)/global_batch (reference main.py:172-174), so K summed
microbatch gradients equal the big-batch gradient by linearity, and one
accumulated update must match the single-big-batch update to float
tolerance — params, optimizer state, and metrics.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu.train import (
    create_state,
    make_accum_train_step,
    make_train_step,
)


def _batches(config, n, seed=0):
    rng = np.random.RandomState(seed)
    s = config.model.image_size
    x = rng.rand(n, s, s, 3).astype(np.float32) * 2 - 1
    y = rng.rand(n, s, s, 3).astype(np.float32) * 2 - 1
    w = np.ones((n,), np.float32)
    return x, y, w


def _assert_trees_close(a, b, rtol, atol, what):
    for (pa, la), (pb, lb) in zip(
        jax.tree_util.tree_leaves_with_path(a),
        jax.tree_util.tree_leaves_with_path(b),
    ):
        assert pa == pb
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=rtol, atol=atol,
            err_msg=f"{what}: {jax.tree_util.keystr(pa)}",
        )


@pytest.mark.parametrize("accum", [2, 4])
def test_accum_equals_big_batch(tiny_config, accum):
    micro = 2
    gbs = micro * accum
    x, y, w = _batches(tiny_config, gbs)

    big = jax.jit(make_train_step(tiny_config, gbs))
    acc = jax.jit(make_accum_train_step(tiny_config, gbs, accum))

    state0 = create_state(tiny_config, jax.random.PRNGKey(0))
    state_big, m_big = big(state0, x, y, w)

    state0 = create_state(tiny_config, jax.random.PRNGKey(0))
    xs = x.reshape(accum, micro, *x.shape[1:])
    ys = y.reshape(accum, micro, *y.shape[1:])
    ws = w.reshape(accum, micro)
    state_acc, m_acc = acc(state0, xs, ys, ws)

    for k in m_big:
        np.testing.assert_allclose(
            float(m_acc[k]), float(m_big[k]), rtol=1e-5, atol=1e-6, err_msg=k
        )
    _assert_trees_close(state_big.g_params, state_acc.g_params, 1e-5, 1e-7, "g")
    _assert_trees_close(state_big.dx_params, state_acc.dx_params, 1e-5, 1e-7, "dx")
    _assert_trees_close(state_big.g_opt, state_acc.g_opt, 1e-5, 1e-7, "g_opt")
    assert int(state_acc.step) == int(state_big.step) == 1  # ONE update


def test_accum_respects_weight_mask(tiny_config):
    """Ragged effective batches: zero-weight padding rows land in some
    microbatch and must not perturb the update."""
    micro, accum = 2, 2
    gbs = micro * accum
    x, y, w = _batches(tiny_config, gbs)
    w = np.array([1, 1, 1, 0], np.float32)  # last sample is padding
    x[3] = 0.0
    y[3] = 0.0

    big = jax.jit(make_train_step(tiny_config, gbs))
    acc = jax.jit(make_accum_train_step(tiny_config, gbs, accum))

    s_big, m_big = big(create_state(tiny_config, jax.random.PRNGKey(0)), x, y, w)
    s_acc, m_acc = acc(
        create_state(tiny_config, jax.random.PRNGKey(0)),
        x.reshape(accum, micro, *x.shape[1:]),
        y.reshape(accum, micro, *y.shape[1:]),
        w.reshape(accum, micro),
    )
    for k in m_big:
        np.testing.assert_allclose(
            float(m_acc[k]), float(m_big[k]), rtol=1e-5, atol=1e-6, err_msg=k
        )
    _assert_trees_close(s_big.f_params, s_acc.f_params, 1e-5, 1e-7, "f")


def test_sharded_accum_matches_single_device(tiny_config):
    """shard_accum_train_step on the 8-device mesh == unsharded accum:
    microbatches shard over "data", the update sees the effective batch."""
    from cyclegan_tpu.parallel import make_mesh_plan
    from cyclegan_tpu.parallel.dp import shard_accum_train_step, shard_stacked_batch
    from cyclegan_tpu.parallel.mesh import replicated

    accum, micro = 2, 8  # micro 8 -> 1 sample/device on the 8-dev mesh
    gbs = accum * micro
    x, y, w = _batches(tiny_config, gbs, seed=3)

    ref_step = jax.jit(make_accum_train_step(tiny_config, gbs, accum))
    s_ref, m_ref = ref_step(
        create_state(tiny_config, jax.random.PRNGKey(0)),
        x.reshape(accum, micro, *x.shape[1:]),
        y.reshape(accum, micro, *y.shape[1:]),
        w.reshape(accum, micro),
    )

    plan = make_mesh_plan(tiny_config.parallel)
    state = jax.device_put(
        create_state(tiny_config, jax.random.PRNGKey(0)), replicated(plan)
    )
    step = shard_accum_train_step(
        plan, make_accum_train_step(tiny_config, gbs, accum)
    )
    xs, ys, ws = shard_stacked_batch(
        plan,
        x.reshape(accum, micro, *x.shape[1:]),
        y.reshape(accum, micro, *y.shape[1:]),
        w.reshape(accum, micro),
    )
    s_sh, m_sh = step(state, xs, ys, ws)

    for k in m_ref:
        np.testing.assert_allclose(
            float(m_sh[k]), float(m_ref[k]), rtol=1e-5, atol=1e-6, err_msg=k
        )
    _assert_trees_close(s_ref.g_params, s_sh.g_params, 1e-5, 1e-6, "g")
