"""Inception-FID end to end with an ACTUAL weights file.

The weights-gated path (eval/features.py InceptionFeatures) had never
executed with real weights in this offline image. Here the torch oracle
model provides one: random-initialized torchvision-style state dict ->
tools/convert_inception_weights.py -> npz -> the evaluate CLI with
--features inception. The scores are meaningless as FID (random
weights), but every line of the weights-gated code path runs: npz
validation, 299x299 resize, pool3 apply, accumulator sweep, tag naming.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))
sys.path.insert(0, os.path.join(REPO, "tools"))

torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def weights_npz(tmp_path_factory):
    from convert_inception_weights import convert_state_dict
    from torch_inception import TorchInceptionPool3, randomize_

    model = TorchInceptionPool3()
    randomize_(model, seed=11)
    sd = {k: v.numpy() for k, v in model.state_dict().items()}
    path = tmp_path_factory.mktemp("w") / "inception_rand.npz"
    np.savez(path, **convert_state_dict(sd))
    return str(path)


@pytest.mark.slow
def test_evaluate_cli_with_inception_weights(weights_npz, tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, "-m", "cyclegan_tpu.eval.evaluate",
         "--output_dir", str(tmp_path / "none"),
         "--data_source", "synthetic", "--image_size", "32",
         "--synthetic_test_size", "3", "--batch_size", "3",
         "--features", "inception", "--feature_weights", weights_npz],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    scores = json.loads(r.stdout.strip().splitlines()[-1])
    assert set(scores) == {
        "fid/inception_v3_pool3/G(A)_vs_B",
        "fid/inception_v3_pool3/F(B)_vs_A",
    }
    for v in scores.values():
        assert np.isfinite(v) and v >= 0


def test_build_feature_extractor_inception(weights_npz):
    """In-process: the extractor loads the npz and produces 2048-d
    features from [-1, 1] images at a non-Inception resolution."""
    from cyclegan_tpu.eval.features import build_feature_extractor

    fx = build_feature_extractor("inception", weights_npz)
    assert fx.name == "inception_v3_pool3"
    rng = np.random.RandomState(0)
    imgs = (rng.rand(2, 64, 64, 3).astype(np.float32) * 2) - 1
    feats = np.asarray(fx(imgs))
    assert feats.shape == (2, 2048)
    assert np.isfinite(feats).all()


def test_auto_prefers_inception_when_weights_usable(weights_npz):
    from cyclegan_tpu.eval.features import build_feature_extractor

    fx = build_feature_extractor("auto", weights_npz)
    assert fx.name == "inception_v3_pool3"


def test_auto_falls_back_on_garbage_weights(tmp_path, capsys):
    from cyclegan_tpu.eval.features import build_feature_extractor

    bad = tmp_path / "bad.npz"
    bad.write_bytes(b"not an npz")
    fx = build_feature_extractor("auto", str(bad))
    assert fx.name == "random_inception_v3_pool3"
    # The not-Inception-comparable warning is the behavior distinguishing
    # "auto" fallback from plain "random" — it must actually be emitted.
    assert "NOT comparable" in capsys.readouterr().err
