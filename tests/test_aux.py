"""Auxiliary subsystems: trace capture, multi-host helpers, preemption.

The reference has none of these (SURVEY.md §5 — tracing limited to a
wall-clock scalar, no failure handling, single-host only); these are the
TPU-framework additions, so the tests define their contracts.
"""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np

from cyclegan_tpu.utils import distributed
from cyclegan_tpu.utils.preemption import PreemptionGuard
from cyclegan_tpu.utils.profiler import TraceCapture, maybe_trace
from cyclegan_tpu.utils.summary import NullSummary, make_summary


def test_trace_capture_writes_trace(tmp_path):
    tracer = TraceCapture(str(tmp_path), num_steps=3)
    f = jax.jit(lambda x: x * 2 + 1)
    x = jnp.ones((8, 8))
    for _ in range(5):
        f(x).block_until_ready()
        tracer.step()
    assert not tracer.enabled  # stopped itself after num_steps
    trace_dir = tmp_path / "traces"
    assert trace_dir.is_dir()
    # jax writes plugins/profile/<ts>/*.trace.json.gz (or .pb) files
    found = [
        os.path.join(dp, fn)
        for dp, _, fns in os.walk(trace_dir)
        for fn in fns
    ]
    assert found, "no trace files produced"


def test_maybe_trace_disabled_is_noop(tmp_path):
    tracer = maybe_trace(str(tmp_path), 0)
    for _ in range(3):
        tracer.step()
    tracer.stop()
    assert not (tmp_path / "traces").exists()


def test_distributed_single_host_helpers():
    assert distributed.process_count() == 1
    assert distributed.process_index() == 0
    assert distributed.is_primary()
    assert distributed.sync_flag(True) is True
    assert distributed.sync_flag(False) is False
    # no multi-host env vars -> no-op
    assert distributed.maybe_initialize() is False


def test_preemption_guard_signal_and_programmatic():
    guard = PreemptionGuard(signals=(signal.SIGUSR1,))
    try:
        assert not guard.should_stop()
        os.kill(os.getpid(), signal.SIGUSR1)
        assert guard.requested_locally
        assert guard.should_stop()
    finally:
        guard.uninstall()

    guard2 = PreemptionGuard(install=False)
    assert not guard2.should_stop()
    guard2.request_stop()
    assert guard2.should_stop()


def test_null_summary_noops(tmp_path):
    s = make_summary(str(tmp_path / "x"), primary=False)
    assert isinstance(s, NullSummary)
    s.scalar("a", 1.0, step=0)
    s.image("b", np.zeros((4, 4, 3), np.uint8), step=0)
    s.image_cycle("c", np.zeros((1, 3, 4, 4, 3), np.uint8), step=0)
    s.close()
    assert not (tmp_path / "x").exists()  # never touched the filesystem

    s2 = make_summary(str(tmp_path / "y"), primary=True)
    s2.scalar("a", 1.0, step=0)
    s2.close()
    assert any(f.startswith("events") for f in os.listdir(tmp_path / "y"))
