"""Native (C++) preprocessing vs the numpy reference path: same
algorithm, decision-identical RNG, numerically close outputs."""

import numpy as np
import pytest

from cyclegan_tpu.data import native
from cyclegan_tpu.data.augment import (
    draw_augment_params,
    normalize_image,
    preprocess_train,
    resize_bilinear,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


def _img(seed=0, h=64, w=64):
    return np.random.RandomState(seed).randint(0, 256, (h, w, 3), dtype=np.uint8)


def numpy_ref(img, resize, flip, oy, ox, crop):
    if flip:
        img = img[:, ::-1]
    out = resize_bilinear(img.astype(np.float32), resize, resize)
    return normalize_image(out[oy : oy + crop, ox : ox + crop])


@pytest.mark.parametrize("flip", [False, True])
@pytest.mark.parametrize("off", [(0, 0), (3, 7), (16, 16)])
def test_native_matches_numpy(flip, off):
    img = _img()
    oy, ox = off
    got = native.preprocess_one(img, 80, flip, oy, ox, 64)
    want = numpy_ref(img, 80, flip, oy, ox, 64)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_native_upscale_from_odd_size():
    img = _img(1, 50, 37)
    got = native.preprocess_one(img, 61, True, 5, 2, 48)
    want = numpy_ref(img, 61, True, 5, 2, 48)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_native_batch_threaded():
    n = 16
    imgs = np.stack([_img(i) for i in range(n)])
    rng = np.random.RandomState(0)
    flips = rng.randint(0, 2, n).astype(np.int32)
    oys = rng.randint(0, 17, n).astype(np.int32)
    oxs = rng.randint(0, 17, n).astype(np.int32)
    got = native.preprocess_batch(imgs, 80, flips, oys, oxs, 64, n_threads=4)
    for i in range(n):
        want = numpy_ref(imgs[i], 80, bool(flips[i]), oys[i], oxs[i], 64)
        np.testing.assert_allclose(got[i], want, rtol=1e-5, atol=1e-5, err_msg=str(i))


def test_preprocess_train_dispatches_native():
    img = _img(3)
    rng1 = np.random.default_rng(7)
    rng2 = np.random.default_rng(7)
    via_native = preprocess_train(img, rng1, 80, 64, use_native=True)
    via_numpy = preprocess_train(img, rng2, 80, 64, use_native=False)
    np.testing.assert_allclose(via_native, via_numpy, rtol=1e-5, atol=1e-5)


def test_output_range():
    out = native.preprocess_one(_img(4), 80, False, 0, 0, 64)
    assert out.min() >= -1.0 and out.max() <= 1.0


def numpy_ref_u8(img, resize, flip, oy, ox, crop):
    if flip:
        img = img[:, ::-1]
    out = resize_bilinear(img.astype(np.float32), resize, resize)
    return np.rint(np.clip(out[oy : oy + crop, ox : ox + crop], 0, 255)).astype(
        np.uint8
    )


def test_native_u8_matches_numpy_quantization():
    """uint8 cache outputs: same rounding (half-even) both paths; allow
    off-by-one only where float arithmetic order puts a value within a
    ulp of a .5 boundary."""
    img = _img(11, 96, 80)
    got = native.preprocess_one(img, 80, True, 3, 7, 64, normalize=False)
    assert got.dtype == np.uint8
    want = numpy_ref_u8(img, 80, True, 3, 7, 64)
    diff = np.abs(got.astype(np.int16) - want.astype(np.int16))
    assert diff.max() <= 1
    assert (diff > 0).mean() < 0.01  # only boundary pixels may differ


def test_native_batch_u8():
    n = 6
    imgs = np.stack([_img(i) for i in range(n)])
    rng = np.random.RandomState(0)
    flips = rng.randint(0, 2, n).astype(np.int32)
    oys = rng.randint(0, 17, n).astype(np.int32)
    oxs = rng.randint(0, 17, n).astype(np.int32)
    got = native.preprocess_batch(
        imgs, 80, flips, oys, oxs, 64, n_threads=3, normalize=False
    )
    assert got.dtype == np.uint8 and got.shape == (n, 64, 64, 3)
    for i in range(n):
        want = numpy_ref_u8(imgs[i], 80, bool(flips[i]), oys[i], oxs[i], 64)
        diff = np.abs(got[i].astype(np.int16) - want.astype(np.int16))
        assert diff.max() <= 1, i


def test_u8_normalize_roundtrip_close_to_float_path():
    """normalize(u8 cache) must sit within one quantum of the direct
    float path — the cache format loses nothing visible."""
    img = _img(12, 70, 90)
    f32 = native.preprocess_one(img, 80, False, 2, 5, 64)
    u8 = native.preprocess_one(img, 80, False, 2, 5, 64, normalize=False)
    np.testing.assert_allclose(
        normalize_image(u8), f32, atol=0.5 / 127.5 + 1e-6
    )
