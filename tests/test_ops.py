"""Unit tests for primitive ops (SURVEY.md §4: InstanceNorm vs analytic
values, ReflectionPad vs jnp.pad semantics)."""

import jax
import jax.numpy as jnp
import numpy as np

from cyclegan_tpu.ops import instance_norm, reflect_pad


def test_reflect_pad_matches_tf_reflect_semantics():
    # tf.pad REFLECT == numpy "reflect": border pixel not repeated.
    x = jnp.arange(1 * 3 * 3 * 1, dtype=jnp.float32).reshape(1, 3, 3, 1)
    y = reflect_pad(x, 1)
    assert y.shape == (1, 5, 5, 1)
    # padded column 1 == original column 0; rows reflect as [r1, r0, r1, r2, r1]
    row = np.asarray(y[0, :, 1, 0])
    col = np.asarray(x[0, :, 0, 0])
    np.testing.assert_allclose(row, [col[1], col[0], col[1], col[2], col[1]])


def test_reflect_pad_3():
    x = jnp.ones((2, 10, 10, 3))
    assert reflect_pad(x, 3).shape == (2, 16, 16, 3)


def test_instance_norm_analytic():
    # Per (N, C) statistics over (H, W): construct a case with known moments.
    rng = np.random.RandomState(0)
    x = rng.randn(2, 8, 8, 4).astype(np.float32) * 3.0 + 1.5
    scale = np.ones(4, np.float32)
    bias = np.zeros(4, np.float32)
    y = np.asarray(instance_norm(jnp.asarray(x), jnp.asarray(scale), jnp.asarray(bias), eps=0.0, impl="xla"))
    # Each (n, c) slice should have ~0 mean, ~1 std.
    m = y.mean(axis=(1, 2))
    s = y.std(axis=(1, 2))
    np.testing.assert_allclose(m, np.zeros_like(m), atol=1e-5)
    np.testing.assert_allclose(s, np.ones_like(s), atol=1e-4)


def test_instance_norm_gamma_beta_and_eps():
    x = jnp.ones((1, 4, 4, 2)) * 5.0  # zero variance
    scale = jnp.asarray([2.0, 3.0])
    bias = jnp.asarray([1.0, -1.0])
    # var=0 -> normalized = 0 -> y = bias exactly, eps keeps it finite.
    y = instance_norm(x, scale, bias, eps=1e-3, impl="xla")
    np.testing.assert_allclose(np.asarray(y[0, 0, 0]), [1.0, -1.0], atol=1e-6)


def test_instance_norm_per_sample_independence():
    # DP-shardable: sample i's output must not depend on sample j.
    rng = np.random.RandomState(1)
    x = rng.randn(4, 6, 6, 3).astype(np.float32)
    scale = rng.randn(3).astype(np.float32)
    bias = rng.randn(3).astype(np.float32)
    full = np.asarray(instance_norm(jnp.asarray(x), jnp.asarray(scale), jnp.asarray(bias), impl="xla"))
    solo = np.asarray(instance_norm(jnp.asarray(x[1:2]), jnp.asarray(scale), jnp.asarray(bias), impl="xla"))
    np.testing.assert_allclose(full[1:2], solo, rtol=1e-5, atol=1e-6)


def test_instance_norm_bfloat16_stats_in_fp32():
    rng = np.random.RandomState(2)
    x = (rng.randn(1, 8, 8, 2) * 100).astype(np.float32)
    xb = jnp.asarray(x, jnp.bfloat16)
    y = instance_norm(xb, jnp.ones(2), jnp.zeros(2), impl="xla")
    assert y.dtype == jnp.bfloat16
    yf = np.asarray(y.astype(jnp.float32))
    assert abs(yf.mean()) < 0.05


def test_instance_norm_custom_vjp_matches_autodiff():
    """The 4-D path's hand-written VJP (norm.py instance_norm_backward,
    written so bf16 activations are the only large residual) must equal
    plain autodiff through the same f32 forward — for dx, dscale, dbias,
    in both f32 and bf16."""
    from cyclegan_tpu.ops.norm import _xla_forward

    rng = np.random.RandomState(3)
    x32 = rng.randn(2, 6, 6, 4).astype(np.float32)
    scale = rng.randn(4).astype(np.float32)
    bias = rng.randn(4).astype(np.float32)
    g32 = rng.randn(2, 6, 6, 4).astype(np.float32)

    for dtype, tol in ((jnp.float32, 1e-5), (jnp.bfloat16, 1e-2)):
        x = jnp.asarray(x32, dtype)
        g = jnp.asarray(g32, dtype)

        def loss_custom(x, s, b):
            return jnp.sum(instance_norm(x, s, b, impl="xla").astype(jnp.float32) * g.astype(jnp.float32))

        def loss_ref(x, s, b):
            return jnp.sum(_xla_forward(x, s, b, 1e-3)[0].astype(jnp.float32) * g.astype(jnp.float32))

        got = jax.grad(loss_custom, argnums=(0, 1, 2))(x, jnp.asarray(scale), jnp.asarray(bias))
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(x, jnp.asarray(scale), jnp.asarray(bias))
        for a, b_ in zip(got, want):
            assert a.dtype == b_.dtype
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b_, np.float32),
                rtol=tol, atol=tol,
            )


class TestReflectConv:
    """ops.reflect_conv: reflect-pad+VALID conv semantics without the
    materialized padded copy (zero-pad conv + border-correction convs).
    Contract: numerically == conv_valid(reflect_pad(x, p), k) to fp
    tolerance, forward and backward, for the generator's two site
    geometries (3x3/pad-1 and 7x7/pad-3)."""

    def _ref(self, x, k, p):
        from jax import lax

        return lax.conv_general_dilated(
            reflect_pad(x, p), k, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def _rand(self, key, shape):
        return jax.random.normal(jax.random.PRNGKey(key), shape, jnp.float32)

    def test_matches_reference_pad1_and_pad3(self):
        from cyclegan_tpu.ops import reflect_conv

        for key, (p, H, W, C, O) in enumerate(
                [(1, 8, 9, 3, 4), (3, 12, 10, 2, 3), (3, 7, 7, 2, 2)]):
            x = self._rand(key, (2, H, W, C))
            k = self._rand(100 + key, (2 * p + 1, 2 * p + 1, C, O))
            np.testing.assert_allclose(
                np.asarray(reflect_conv(x, k, p)),
                np.asarray(self._ref(x, k, p)),
                rtol=1e-4, atol=1e-5)

    def test_gradients_match_reference(self):
        # Exercises the hand-written custom VJP (ops/padding.py
        # _reflect_conv_bwd) against autodiff of the materialized-pad
        # reference at BOTH generator geometries, including the minimum
        # legal size for p=3 (every output pixel touched by corrections).
        from cyclegan_tpu.ops import reflect_conv

        for key, (p, H, W, C, O) in enumerate(
                [(1, 9, 8, 3, 2), (3, 12, 10, 2, 3), (3, 7, 7, 2, 2)]):
            x = self._rand(7 + key, (2, H, W, C))
            k = self._rand(50 + key, (2 * p + 1, 2 * p + 1, C, O))

            def loss(fn):
                return jax.grad(
                    lambda x_, k_: jnp.sum(jnp.tanh(fn(x_, k_))),
                    argnums=(0, 1),
                )(x, k)

            # Tolerances are fp-reassociation noise, not approximation:
            # under x64 both grads agree with the reference to ~1e-14.
            # dk sums over N*H*W products, so its f32 noise floor is a
            # few ulp higher than dx's.
            gx_f, gk_f = loss(lambda x_, k_: reflect_conv(x_, k_, p))
            gx_r, gk_r = loss(lambda x_, k_: self._ref(x_, k_, p))
            np.testing.assert_allclose(
                np.asarray(gx_f), np.asarray(gx_r), rtol=1e-4, atol=1e-5,
                err_msg=f"dx mismatch at p={p} {H}x{W}")
            np.testing.assert_allclose(
                np.asarray(gk_f), np.asarray(gk_r), rtol=1e-4, atol=5e-5,
                err_msg=f"dk mismatch at p={p} {H}x{W}")

    def test_rejects_wrong_kernel_or_tiny_image(self):
        import pytest

        from cyclegan_tpu.ops import reflect_conv

        x = self._rand(0, (1, 8, 8, 2))
        with pytest.raises(ValueError, match="kernel"):
            reflect_conv(x, self._rand(1, (5, 5, 2, 2)), 1)
        with pytest.raises(ValueError, match="H, W"):
            reflect_conv(self._rand(2, (1, 6, 6, 2)),
                         self._rand(3, (7, 7, 2, 2)), 3)
