"""Unit tests for primitive ops (SURVEY.md §4: InstanceNorm vs analytic
values, ReflectionPad vs jnp.pad semantics)."""

import jax
import jax.numpy as jnp
import numpy as np

from cyclegan_tpu.ops import instance_norm, reflect_pad


def test_reflect_pad_matches_tf_reflect_semantics():
    # tf.pad REFLECT == numpy "reflect": border pixel not repeated.
    x = jnp.arange(1 * 3 * 3 * 1, dtype=jnp.float32).reshape(1, 3, 3, 1)
    y = reflect_pad(x, 1)
    assert y.shape == (1, 5, 5, 1)
    # padded column 1 == original column 0; rows reflect as [r1, r0, r1, r2, r1]
    row = np.asarray(y[0, :, 1, 0])
    col = np.asarray(x[0, :, 0, 0])
    np.testing.assert_allclose(row, [col[1], col[0], col[1], col[2], col[1]])


def test_reflect_pad_3():
    x = jnp.ones((2, 10, 10, 3))
    assert reflect_pad(x, 3).shape == (2, 16, 16, 3)


def test_instance_norm_analytic():
    # Per (N, C) statistics over (H, W): construct a case with known moments.
    rng = np.random.RandomState(0)
    x = rng.randn(2, 8, 8, 4).astype(np.float32) * 3.0 + 1.5
    scale = np.ones(4, np.float32)
    bias = np.zeros(4, np.float32)
    y = np.asarray(instance_norm(jnp.asarray(x), jnp.asarray(scale), jnp.asarray(bias), eps=0.0, impl="xla"))
    # Each (n, c) slice should have ~0 mean, ~1 std.
    m = y.mean(axis=(1, 2))
    s = y.std(axis=(1, 2))
    np.testing.assert_allclose(m, np.zeros_like(m), atol=1e-5)
    np.testing.assert_allclose(s, np.ones_like(s), atol=1e-4)


def test_instance_norm_gamma_beta_and_eps():
    x = jnp.ones((1, 4, 4, 2)) * 5.0  # zero variance
    scale = jnp.asarray([2.0, 3.0])
    bias = jnp.asarray([1.0, -1.0])
    # var=0 -> normalized = 0 -> y = bias exactly, eps keeps it finite.
    y = instance_norm(x, scale, bias, eps=1e-3, impl="xla")
    np.testing.assert_allclose(np.asarray(y[0, 0, 0]), [1.0, -1.0], atol=1e-6)


def test_instance_norm_per_sample_independence():
    # DP-shardable: sample i's output must not depend on sample j.
    rng = np.random.RandomState(1)
    x = rng.randn(4, 6, 6, 3).astype(np.float32)
    scale = rng.randn(3).astype(np.float32)
    bias = rng.randn(3).astype(np.float32)
    full = np.asarray(instance_norm(jnp.asarray(x), jnp.asarray(scale), jnp.asarray(bias), impl="xla"))
    solo = np.asarray(instance_norm(jnp.asarray(x[1:2]), jnp.asarray(scale), jnp.asarray(bias), impl="xla"))
    np.testing.assert_allclose(full[1:2], solo, rtol=1e-5, atol=1e-6)


def test_instance_norm_bfloat16_stats_in_fp32():
    rng = np.random.RandomState(2)
    x = (rng.randn(1, 8, 8, 2) * 100).astype(np.float32)
    xb = jnp.asarray(x, jnp.bfloat16)
    y = instance_norm(xb, jnp.ones(2), jnp.zeros(2), impl="xla")
    assert y.dtype == jnp.bfloat16
    yf = np.asarray(y.astype(jnp.float32))
    assert abs(yf.mean()) < 0.05


def test_instance_norm_custom_vjp_matches_autodiff():
    """The 4-D path's hand-written VJP (norm.py instance_norm_backward,
    written so bf16 activations are the only large residual) must equal
    plain autodiff through the same f32 forward — for dx, dscale, dbias,
    in both f32 and bf16."""
    from cyclegan_tpu.ops.norm import _xla_forward

    rng = np.random.RandomState(3)
    x32 = rng.randn(2, 6, 6, 4).astype(np.float32)
    scale = rng.randn(4).astype(np.float32)
    bias = rng.randn(4).astype(np.float32)
    g32 = rng.randn(2, 6, 6, 4).astype(np.float32)

    for dtype, tol in ((jnp.float32, 1e-5), (jnp.bfloat16, 1e-2)):
        x = jnp.asarray(x32, dtype)
        g = jnp.asarray(g32, dtype)

        def loss_custom(x, s, b):
            return jnp.sum(instance_norm(x, s, b, impl="xla").astype(jnp.float32) * g.astype(jnp.float32))

        def loss_ref(x, s, b):
            return jnp.sum(_xla_forward(x, s, b, 1e-3)[0].astype(jnp.float32) * g.astype(jnp.float32))

        got = jax.grad(loss_custom, argnums=(0, 1, 2))(x, jnp.asarray(scale), jnp.asarray(bias))
        want = jax.grad(loss_ref, argnums=(0, 1, 2))(x, jnp.asarray(scale), jnp.asarray(bias))
        for a, b_ in zip(got, want):
            assert a.dtype == b_.dtype
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b_, np.float32),
                rtol=tol, atol=tol,
            )
