"""Independent torch re-implementation of the reference's training
semantics, used as a cross-framework oracle by test_torch_parity.py.

This mirrors the REFERENCE procedure (/root/reference/main.py:207-262)
literally: all losses computed from pre-update weights on one retained
graph, then four `torch.autograd.grad` pulls — each loss w.r.t. its own
network's parameters only — exactly what the persistent GradientTape +
per-net `minimize(var_list=...)` does. Comparing against our fused
single-backward JAX step (cyclegan_tpu/train/steps.py) proves the
stop_gradient placement there reproduces the tape semantics.

Weight conventions (flax -> torch):
- Conv kernel (kh, kw, cin, cout) -> conv2d weight (cout, cin, kh, kw).
- flax ConvTranspose(SAME) kernel -> conv_transpose2d weight
  (cin, cout, kh, kw) with a SPATIAL FLIP, full output cropped at the
  origin (flax's lax.conv_transpose applies the kernel unflipped — a
  reparameterization of Keras/torch's gradient-based transpose; verified
  exact in test_torch_parity.py).
- SAME padding reproduces TF's asymmetric rule (extra pad at the end).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np
import torch
import torch.nn.functional as F

EPS_IN = 1e-3  # InstanceNorm epsilon (tfa default; ops/norm.py)


def tf_same_pad(x: torch.Tensor, k: int, s: int) -> torch.Tensor:
    """TF 'SAME' for an NCHW tensor: total = (ceil(in/s)-1)*s + k - in,
    beg = total//2 (extra at the end)."""
    h, w = x.shape[-2], x.shape[-1]

    def amts(n):
        out = -(-n // s)
        total = max((out - 1) * s + k - n, 0)
        beg = total // 2
        return beg, total - beg

    t, b = amts(h)
    l, r = amts(w)
    return F.pad(x, (l, r, t, b))


def conv(x, kernel, bias, stride=1, same=True):
    """flax-kernel conv. kernel: torch tensor shaped (kh,kw,cin,cout)."""
    w = kernel.permute(3, 2, 0, 1)
    if same:
        x = tf_same_pad(x, kernel.shape[0], stride)
    return F.conv2d(x, w, bias, stride=stride)


def conv_transpose_same2(x, kernel):
    """flax ConvTranspose(SAME, stride 2, no bias): flip + crop at origin."""
    w = torch.flip(kernel, dims=(0, 1)).permute(2, 3, 0, 1)
    full = F.conv_transpose2d(x, w, stride=2)
    out_h, out_w = 2 * x.shape[-2], 2 * x.shape[-1]
    return full[:, :, :out_h, :out_w]


def instance_norm(x, scale, bias):
    """Per-(N,C) moments over HW, biased variance, f32 (ops/norm.py)."""
    mean = x.mean(dim=(2, 3), keepdim=True)
    var = ((x - mean) ** 2).mean(dim=(2, 3), keepdim=True)
    y = (x - mean) * torch.rsqrt(var + EPS_IN)
    return y * scale[None, :, None, None] + bias[None, :, None, None]


def reflect_pad(x, p):
    return F.pad(x, (p, p, p, p), mode="reflect")


def to_torch_tree(params) -> Dict:
    """flax FrozenDict/dict -> nested dict of requires_grad torch leaves."""
    def rec(node):
        if hasattr(node, "items"):
            return {k: rec(v) for k, v in node.items()}
        t = torch.tensor(np.asarray(node), dtype=torch.float32)
        t.requires_grad_(True)
        return t

    return rec(params)


def leaves(tree) -> List[torch.Tensor]:
    """Flatten in sorted-key order (matches jax.tree flattening order)."""
    out = []
    for k in sorted(tree.keys()):
        v = tree[k]
        if isinstance(v, dict):
            out.extend(leaves(v))
        else:
            out.append(v)
    return out


def generator_forward(p: Dict, x: torch.Tensor, gen_cfg) -> torch.Tensor:
    """Mirror of models/generator.py ResNetGenerator for any config."""
    m = p["params"]
    y = reflect_pad(x, 3)
    y = conv(y, m["Conv_0"]["kernel"], None, stride=1, same=False)
    y = instance_norm(y, m["InstanceNorm_0"]["scale"], m["InstanceNorm_0"]["bias"])
    y = F.relu(y)
    for i in range(gen_cfg.num_downsampling_blocks):
        d = m[f"Downsample_{i}"]
        y = conv(y, d["Conv_0"]["kernel"], None, stride=2, same=True)
        y = instance_norm(y, d["InstanceNorm_0"]["scale"], d["InstanceNorm_0"]["bias"])
        y = F.relu(y)
    for i in range(gen_cfg.num_residual_blocks):
        r = m[f"ResidualBlock_{i}"]
        z = reflect_pad(y, 1)
        z = conv(z, r["Conv_0"]["kernel"], None, stride=1, same=False)
        z = instance_norm(z, r["InstanceNorm_0"]["scale"], r["InstanceNorm_0"]["bias"])
        z = F.relu(z)
        z = reflect_pad(z, 1)
        z = conv(z, r["Conv_1"]["kernel"], None, stride=1, same=False)
        z = instance_norm(z, r["InstanceNorm_1"]["scale"], r["InstanceNorm_1"]["bias"])
        y = y + z
    for i in range(gen_cfg.num_upsample_blocks):
        u = m[f"Upsample_{i}"]
        y = conv_transpose_same2(y, u["ConvTranspose_0"]["kernel"])
        y = instance_norm(y, u["InstanceNorm_0"]["scale"], u["InstanceNorm_0"]["bias"])
        y = F.relu(y)
    y = reflect_pad(y, 3)
    y = conv(y, m["Conv_1"]["kernel"], m["Conv_1"]["bias"], stride=1, same=False)
    return torch.tanh(y)


def discriminator_forward(p: Dict, x: torch.Tensor, disc_cfg) -> torch.Tensor:
    """Mirror of models/discriminator.py PatchGANDiscriminator."""
    m = p["params"]
    y = conv(x, m["Conv_0"]["kernel"], m["Conv_0"]["bias"], stride=2, same=True)
    y = F.leaky_relu(y, 0.2)
    for i in range(disc_cfg.num_downsampling):
        d = m[f"Downsample_{i}"]
        stride = 2 if i < 2 else 1
        y = conv(y, d["Conv_0"]["kernel"], None, stride=stride, same=True)
        y = instance_norm(y, d["InstanceNorm_0"]["scale"], d["InstanceNorm_0"]["bias"])
        y = F.leaky_relu(y, 0.2)
    return conv(y, m["Conv_1"]["kernel"], m["Conv_1"]["bias"], stride=1, same=True)


def per_sample_mean(x: torch.Tensor) -> torch.Tensor:
    return x.mean(dim=tuple(range(1, x.ndim)))


def scaled(per_sample: torch.Tensor, gbs: float) -> torch.Tensor:
    return per_sample.sum() / gbs


def reference_losses(config, tg, tf_, tdx, tdy, x, y, gbs):
    """All ten training losses from pre-update weights (main.py:207-247).
    NO detach anywhere — the reference's tape keeps the full graph; the
    per-net gradient restriction happens in the autograd.grad pulls."""
    gen_cfg = config.model.generator
    disc_cfg = config.model.discriminator
    lam_c = config.loss.lambda_cycle
    lam_i = config.loss.lambda_identity

    G = lambda p, a: generator_forward(p, a, gen_cfg)
    D = lambda p, a: discriminator_forward(p, a, disc_cfg)

    fake_y = G(tg, x)
    fake_x = G(tf_, y)

    mse1 = lambda t: per_sample_mean((1.0 - t) ** 2)
    mse0 = lambda t: per_sample_mean(t ** 2)
    mae = lambda a, b: per_sample_mean((a - b).abs())

    g_adv = scaled(mse1(D(tdy, fake_y)), gbs)
    f_adv = scaled(mse1(D(tdx, fake_x)), gbs)
    g_cycle = lam_c * scaled(mae(y, G(tg, fake_x)), gbs)
    f_cycle = lam_c * scaled(mae(x, G(tf_, fake_y)), gbs)
    g_id = lam_i * scaled(mae(y, G(tg, y)), gbs)
    f_id = lam_i * scaled(mae(x, G(tf_, x)), gbs)
    g_total = g_adv + g_cycle + g_id
    f_total = f_adv + f_cycle + f_id
    x_loss = scaled(0.5 * (mse1(D(tdx, x)) + mse0(D(tdx, fake_x))), gbs)
    y_loss = scaled(0.5 * (mse1(D(tdy, y)) + mse0(D(tdy, fake_y))), gbs)
    return {
        "loss_G/loss": g_adv, "loss_G/cycle": g_cycle, "loss_G/identity": g_id,
        "loss_G/total": g_total,
        "loss_F/loss": f_adv, "loss_F/cycle": f_cycle, "loss_F/identity": f_id,
        "loss_F/total": f_total,
        "loss_X/loss": x_loss, "loss_Y/loss": y_loss,
    }


def reference_grads(config, tg, tf_, tdx, tdy, x, y, gbs):
    """The four per-network gradient pulls of main.py:249-260."""
    L = reference_losses(config, tg, tf_, tdx, tdy, x, y, gbs)
    pulls = [
        (L["loss_G/total"], leaves(tg)),
        (L["loss_F/total"], leaves(tf_)),
        (L["loss_X/loss"], leaves(tdx)),
        (L["loss_Y/loss"], leaves(tdy)),
    ]
    grads = [
        torch.autograd.grad(loss, ps, retain_graph=True, allow_unused=False)
        for loss, ps in pulls
    ]
    return L, grads
