"""FID harness tests: Newton-Schulz sqrtm vs scipy, streaming moments vs
numpy, identity/monotonicity properties, end-to-end evaluate_fid."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu.eval import (
    FIDAccumulator,
    RandomConvFeatures,
    frechet_distance,
    matrix_sqrt_newton_schulz,
)
from cyclegan_tpu.eval.fid import fid_from_accumulators


def test_matrix_sqrt_matches_scipy():
    from scipy.linalg import sqrtm

    rng = np.random.RandomState(0)
    a = rng.randn(32, 16)
    psd = (a @ a.T + 0.1 * np.eye(32)).astype(np.float32)
    got = np.asarray(matrix_sqrt_newton_schulz(jnp.asarray(psd)))
    want = np.real(sqrtm(psd.astype(np.float64)))
    np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-3)
    np.testing.assert_allclose(got @ got, psd, rtol=1e-2, atol=1e-3)


def test_accumulator_matches_numpy():
    rng = np.random.RandomState(1)
    feats = rng.randn(100, 8)
    acc = FIDAccumulator(8)
    for chunk in np.array_split(feats, 7):
        acc.update(chunk)
    mu, cov = acc.stats()
    np.testing.assert_allclose(mu, feats.mean(axis=0), rtol=1e-10)
    np.testing.assert_allclose(cov, np.cov(feats, rowvar=False), rtol=1e-8)


def test_fid_identity_is_zero():
    rng = np.random.RandomState(2)
    feats = rng.randn(200, 16).astype(np.float32)
    a, b = FIDAccumulator(16), FIDAccumulator(16)
    a.update(feats)
    b.update(feats)
    assert abs(fid_from_accumulators(a, b)) < 1e-2


def test_fid_analytic_mean_shift():
    # Equal covariances, mean shift d: FID = |d|^2.
    rng = np.random.RandomState(3)
    base = rng.randn(5000, 4).astype(np.float32)
    shift = np.asarray([1.0, 0.0, -2.0, 0.5], np.float32)
    a, b = FIDAccumulator(4), FIDAccumulator(4)
    a.update(base)
    b.update(base + shift)
    got = fid_from_accumulators(a, b)
    np.testing.assert_allclose(got, np.sum(shift**2), rtol=0.05)


def test_fid_monotone_in_noise():
    rng = np.random.RandomState(4)
    base = rng.randn(500, 8).astype(np.float32)
    ref = FIDAccumulator(8)
    ref.update(base)
    prev = -1.0
    for sigma in [0.1, 0.5, 2.0]:
        acc = FIDAccumulator(8)
        acc.update(base * (1 + sigma) + sigma * rng.randn(500, 8))
        fid = fid_from_accumulators(ref, acc)
        assert fid > prev
        prev = fid


def test_random_features_deterministic():
    f1 = RandomConvFeatures()
    f2 = RandomConvFeatures()
    x = jnp.asarray(np.random.RandomState(5).rand(2, 32, 32, 3), jnp.float32)
    np.testing.assert_array_equal(np.asarray(f1(x)), np.asarray(f2(x)))
    assert f1(x).shape == (2, 2048)


@pytest.mark.slow
def test_evaluate_fid_end_to_end(tiny_config):
    from cyclegan_tpu.data import build_data
    from cyclegan_tpu.eval.evaluate import evaluate_fid
    from cyclegan_tpu.train import create_state

    cfg = tiny_config
    data = build_data(cfg, global_batch_size=2)
    state = create_state(cfg, jax.random.PRNGKey(0))
    fx = RandomConvFeatures()
    scores = evaluate_fid(cfg, state, data, fx)
    assert len(scores) == 2
    for k, v in scores.items():
        assert np.isfinite(v) and v >= 0, k


@pytest.mark.slow
def test_fid_evaluator_is_reusable(tiny_config):
    """make_fid_evaluator (the --fid_every path) jits its translate fn
    once; repeated calls on evolving states must not retrace and must
    track the state (identical state -> identical score)."""
    from cyclegan_tpu.data import build_data
    from cyclegan_tpu.eval.evaluate import make_fid_evaluator
    from cyclegan_tpu.train import create_state

    cfg = tiny_config
    data = build_data(cfg, global_batch_size=2)
    fx = RandomConvFeatures()
    evaluate = make_fid_evaluator(cfg, data, fx)

    s0 = create_state(cfg, jax.random.PRNGKey(0))
    s1 = create_state(cfg, jax.random.PRNGKey(7))
    a = evaluate(s0)
    b = evaluate(s1)
    c = evaluate(s0)
    assert a.keys() == b.keys()
    for k in a:
        assert np.isfinite(b[k])
        np.testing.assert_allclose(a[k], c[k], rtol=1e-6)
    assert any(abs(a[k] - b[k]) > 1e-9 for k in a), "scores ignore the state"
    # The no-retrace property itself: one compiled program serves all calls.
    assert evaluate.translate._cache_size() == 1


def test_combine_accumulators_is_exact():
    """Split-then-merge moments == single-pass moments (the cross-host
    reduction is a pure sum, no approximation)."""
    from cyclegan_tpu.eval.fid import FIDAccumulator, combine_accumulators

    rng = np.random.RandomState(3)
    feats = rng.randn(64, 8)

    whole = FIDAccumulator(8)
    whole.update(feats)

    parts = [FIDAccumulator(8) for _ in range(3)]
    parts[0].update(feats[:10])
    parts[1].update(feats[10:41])
    parts[2].update(feats[41:])
    merged = combine_accumulators(parts)

    assert merged.n == whole.n
    for a, b in zip(whole.stats(), merged.stats()):
        np.testing.assert_allclose(a, b, rtol=1e-10, atol=1e-12)


def test_allreduce_accumulator_single_process_noop():
    from cyclegan_tpu.eval.fid import FIDAccumulator, allreduce_accumulator

    acc = FIDAccumulator(4)
    acc.update(np.random.RandomState(0).randn(5, 4))
    out = allreduce_accumulator(acc)
    assert out is acc  # single-process: identity, no copies
