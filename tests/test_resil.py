"""Tests for cyclegan_tpu/resil: the fault-injection registry, bounded
backoff retry, the rollback controller, and the end-to-end chaos drill.

Determinism is the load-bearing property throughout: a fault spec must
fire at exactly the index it names (so a drill replays identically),
and backoff jitter must be a pure function of (site, salt, attempt)
(so two runs of the same drill log the same delays)."""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cyclegan_tpu.resil import (  # noqa: E402
    DEFAULT_RETRY_POLICY,
    Fault,
    FaultInjector,
    InjectedCrash,
    InjectedIOError,
    RetryingIterator,
    RetryPolicy,
    RollbackController,
    backoff_delay,
    retry_call,
)
from cyclegan_tpu.resil.faults import parse_spec  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class Recorder:
    def __init__(self):
        self.events = []

    def event(self, kind, /, **fields):
        self.events.append(dict(fields, event=kind))

    def of(self, kind):
        return [e for e in self.events if e["event"] == kind]

    def flush(self):
        pass


# ------------------------------------------------------------- spec parsing


def test_parse_spec_entries_and_defaults():
    faults = parse_spec("nan_grads@step=6, ckpt_io_error@epoch=0x2")
    assert [repr(f) for f in faults] == ["nan_grads@step=6",
                                        "ckpt_io_error@epoch=0x2"]
    assert faults[0].times == 1 and faults[1].times == 2
    assert parse_spec("") == [] and parse_spec(None) == []


@pytest.mark.parametrize("bad", [
    "nan_grads",                 # no index
    "nan_grads@step=x",          # non-numeric
    "warp_core_breach@step=1",   # unknown kind
    "nan_grads@epoch=1",         # wrong index key for the kind
    "nan_grads@step=1y2",        # bad repeat suffix
])
def test_parse_spec_rejects_bad_entries(bad):
    with pytest.raises(ValueError):
        parse_spec(bad)


def test_fault_validates_bounds():
    with pytest.raises(ValueError):
        Fault("nan_grads", at=-1)
    with pytest.raises(ValueError):
        Fault("nan_grads", at=0, times=0)
    with pytest.raises(ValueError):
        Fault("not_a_kind", at=0)


def test_from_spec_empty_returns_none():
    """The zero-cost contract: a disabled run never constructs an
    injector, so every site's guard is a single `is not None`."""
    assert FaultInjector.from_spec("") is None
    assert FaultInjector.from_spec(None) is None
    assert FaultInjector.from_spec("nan_grads@step=1") is not None


# ------------------------------------------------------------ fire windows


def test_fire_exact_counter_match():
    inj = FaultInjector.from_spec("nan_grads@step=2")
    assert inj.fire("step") == []            # covers [0, 1)
    assert inj.fire("step") == []            # [1, 2)
    fired = inj.fire("step")                 # [2, 3)
    assert [f.kind for f in fired] == ["nan_grads"]
    assert inj.fire("step") == []            # exhausted
    assert inj.pending() == []


def test_fire_window_covers_fused_multi_step_advance():
    """A fused K-step dispatch advances the counter by K; a fault whose
    index lands anywhere inside the window fires on that dispatch."""
    inj = FaultInjector.from_spec("nan_grads@step=6")
    assert inj.fire("step", advance=4) == []       # [0, 4)
    fired = inj.fire("step", advance=4)            # [4, 8) covers 6
    assert [f.kind for f in fired] == ["nan_grads"]


def test_fire_stuck_fault_outlasts_counter():
    """An xM fault that has started firing keeps firing on later checks
    until exhausted — this is what lets data_stall@step=KxM outlast a
    retry loop whose re-checks pass advance=0."""
    inj = FaultInjector.from_spec("data_stall@step=1x3")
    assert inj.fire("data") == []
    assert len(inj.fire("data")) == 1       # at=1 fires
    assert len(inj.fire("data", advance=0)) == 1  # stuck re-fire
    assert len(inj.fire("data", advance=0)) == 1  # third and last
    assert inj.fire("data", advance=0) == []
    assert inj.pending() == []


def test_fire_explicit_index_leaves_counter_alone():
    inj = FaultInjector.from_spec("ckpt_io_error@epoch=3")
    assert inj.fire("ckpt", index=0) == []
    assert inj.fire("ckpt", index=2) == []
    assert len(inj.fire("ckpt", index=3)) == 1
    assert inj.fire("ckpt", index=3) == []  # times=1 consumed


def test_fire_emits_fault_injected_event():
    rec = Recorder()
    inj = FaultInjector.from_spec("replica_crash@flush=0", telemetry=rec)
    inj.fire("flush")
    (ev,) = rec.of("fault_injected")
    assert ev["kind"] == "replica_crash" and ev["site"] == "flush"
    assert ev["spec"] == "replica_crash@flush=0"


def test_maybe_raise_raises_io_error_for_io_kinds():
    inj = FaultInjector.from_spec("ckpt_io_error@epoch=1")
    inj.maybe_raise("ckpt", index=0)  # no match, no raise
    with pytest.raises(InjectedIOError):
        inj.maybe_raise("ckpt", index=1)


def test_injected_crash_escapes_plain_exception_handler():
    """InjectedCrash subclasses BaseException so a replica's
    fail-the-flush `except Exception` cannot absorb it."""
    assert not issubclass(InjectedCrash, Exception)
    with pytest.raises(InjectedCrash):
        try:
            raise InjectedCrash("boom")
        except Exception:  # noqa: BLE001 — the point of the test
            pytest.fail("InjectedCrash must not be caught as Exception")


# ------------------------------------------------------------------ retry


def test_backoff_delay_deterministic_capped_and_jittered():
    p = RetryPolicy(base_delay_s=0.1, multiplier=2.0, max_delay_s=0.3,
                    jitter=0.25)
    d0 = backoff_delay(p, 0, site="ckpt", salt=7)
    assert d0 == backoff_delay(p, 0, site="ckpt", salt=7)  # pure function
    assert backoff_delay(p, 0, site="ckpt", salt=8) != d0  # salt decorrelates
    # Jitter only shaves: (1-jitter)*base <= d <= base, and the cap holds
    # even where the exponent would exceed it.
    assert 0.075 <= d0 <= 0.1
    assert backoff_delay(p, 10, site="x") <= 0.3
    assert backoff_delay(RetryPolicy(jitter=0.0), 0) == 0.05


def test_retry_policy_validates():
    with pytest.raises(ValueError):
        RetryPolicy(attempts=0)
    with pytest.raises(ValueError):
        RetryPolicy(jitter=1.0)


def test_retry_call_absorbs_transients_with_events():
    rec = Recorder()
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    slept = []
    assert retry_call(flaky, site="ckpt", telemetry=rec,
                      sleep=slept.append) == "ok"
    assert calls["n"] == 3 and len(slept) == 2
    evs = rec.of("retry")
    assert [e["attempt"] for e in evs] == [1, 2]
    assert all(e["site"] == "ckpt" and "OSError" in e["error"] for e in evs)


def test_retry_call_budget_exhaustion_reraises():
    def always_fails():
        raise OSError("persistent")

    with pytest.raises(OSError, match="persistent"):
        retry_call(always_fails, site="ckpt",
                   policy=RetryPolicy(attempts=2), sleep=lambda s: None)


def test_retry_call_non_retryable_propagates_immediately():
    calls = {"n": 0}

    def buggy():
        calls["n"] += 1
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        retry_call(buggy, site="ckpt", sleep=lambda s: None)
    assert calls["n"] == 1


def test_retry_call_absorbs_injected_ckpt_io_error():
    rec = Recorder()
    inj = FaultInjector.from_spec("ckpt_io_error@epoch=5", telemetry=rec)
    out = retry_call(lambda: "saved", site="ckpt", index=5, injector=inj,
                     telemetry=rec, sleep=lambda s: None)
    assert out == "saved"
    assert len(rec.of("fault_injected")) == 1
    assert len(rec.of("retry")) == 1
    assert inj.pending() == []


def test_retrying_iterator_passthrough_and_stop():
    it = RetryingIterator(iter([1, 2, 3]))
    assert list(it) == [1, 2, 3]
    with pytest.raises(StopIteration):
        next(it)


def test_retrying_iterator_absorbs_injected_stall():
    rec = Recorder()
    inj = FaultInjector.from_spec("data_stall@step=1", telemetry=rec)
    it = RetryingIterator(iter("abc"), telemetry=rec, injector=inj,
                          sleep=lambda s: None)
    assert list(it) == ["a", "b", "c"]
    assert len(rec.of("retry")) == 1
    assert inj.pending() == []


def test_retrying_iterator_persistent_stall_exhausts_budget():
    """x4 stall against a 3-try budget: the wrapper re-raises on the
    final attempt instead of looping forever (bounded by design)."""
    inj = FaultInjector.from_spec("data_stall@step=0x4")
    it = RetryingIterator(iter("ab"), injector=inj,
                          policy=RetryPolicy(attempts=3),
                          sleep=lambda s: None)
    with pytest.raises(InjectedIOError):
        next(it)


# --------------------------------------------------------------- rollback


class FakeCkpt:
    def __init__(self, state="good", fail=None, have=True):
        self._state = state
        self._fail = fail
        self._have = have
        self.slot = "/ckpts/checkpoint-e00004"
        self.n_restores = 0

    def exists(self):
        return self._have

    def restore(self, template, partial=False):
        self.n_restores += 1
        if self._fail is not None:
            raise self._fail
        return self._state, 5


class FakeFault(Exception):
    kind = "nonfinite"


class FakeData:
    def __init__(self):
        self.salts = []

    def reseed(self, salt):
        self.salts.append(salt)


def test_rollback_restores_reseeds_and_counts():
    rec = Recorder()
    ckpt, data = FakeCkpt(), FakeData()
    rb = RollbackController(ckpt, data=data, telemetry=rec,
                            max_rollbacks=2)
    state, nxt = rb.recover("template", FakeFault(), epoch=7)
    assert (state, nxt) == ("good", 5)
    assert data.salts == [1]
    assert rb.consecutive == 1 and rb.total == 1
    (ev,) = rec.of("health_recovery")
    assert ev["fault_kind"] == "nonfinite"
    assert ev["epoch_faulted"] == 7 and ev["resume_epoch"] == 5
    assert ev["slot"] == ckpt.slot

    rb.note_clean_epoch()
    assert rb.consecutive == 0
    state, _ = rb.recover("template", FakeFault(), epoch=9)
    assert data.salts == [1, 2]  # salt advances with total, not consecutive


def test_rollback_budget_exhaustion_reraises_original_fault():
    rb = RollbackController(FakeCkpt(), max_rollbacks=1)
    rb.recover("t", FakeFault(), epoch=3)
    fault = FakeFault()
    with pytest.raises(FakeFault) as e:
        rb.recover("t", fault, epoch=4)
    assert e.value is fault


def test_rollback_zero_budget_never_restores():
    ckpt = FakeCkpt()
    rb = RollbackController(ckpt, max_rollbacks=0)
    with pytest.raises(FakeFault):
        rb.recover("t", FakeFault(), epoch=0)
    assert ckpt.n_restores == 0


def test_rollback_without_slots_or_on_restore_failure_halts():
    with pytest.raises(FakeFault):
        RollbackController(FakeCkpt(have=False),
                           max_rollbacks=2).recover("t", FakeFault(), 0)
    broken = FakeCkpt(fail=RuntimeError("every slot failed"))
    with pytest.raises(FakeFault):
        RollbackController(broken, max_rollbacks=2).recover(
            "t", FakeFault(), 0)
    assert broken.n_restores == 1


def test_rollback_validates_budget():
    with pytest.raises(ValueError):
        RollbackController(FakeCkpt(), max_rollbacks=-1)


# ------------------------------------------------------------ chaos drill


def test_chaos_drill_fast_passes_end_to_end(tmp_path):
    """The acceptance drill: `python tools/chaos_drill.py --fast` on CPU
    must pass the three single-topology drills — NaN rollback through
    the verified ring (a real main.py run), replica-crash self-healing,
    and retried checkpoint I/O — and emit one parseable JSON line. The
    fourth drill (elastic_resume, three main.py runs) is budgeted
    separately in tests/test_elastic.py so each subprocess stays inside
    its own timeout."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, "tools/chaos_drill.py", "--fast",
         "--only", "nan_rollback", "--only", "fleet_crash",
         "--only", "ckpt_retry", "--workdir", str(tmp_path)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=580)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    report = json.loads(r.stdout.strip().splitlines()[-1])
    assert report["metric"] == "cyclegan_chaos_drill"
    assert report["pass"] is True
    assert set(report["drills"]) == {"nan_rollback", "fleet_crash",
                                     "ckpt_retry"}
    for name, drill in report["drills"].items():
        assert drill["pass"], (name, drill)
