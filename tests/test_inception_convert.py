"""Weight-converter mapping (tools/convert_inception_weights.py).

torchvision isn't installed in this image, so the converter is pinned
against a MOCK state dict carrying the exact torchvision inception_v3
tensor names with shapes derived (inversely) from our own module tree:
completeness in both directions, OIHW->HWIO transposition, and
end-to-end loadability are all asserted without the real weights.
"""

import sys
import pathlib

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "tools"))

from convert_inception_weights import conv_bn_pairs, convert_state_dict  # noqa: E402

from cyclegan_tpu.eval.inception import (  # noqa: E402
    InceptionV3Pool3,
    flatten_params,
    load_params_npz,
)


def _template():
    net = InceptionV3Pool3()
    return net, jax.eval_shape(
        lambda: net.init(jax.random.PRNGKey(0), jnp.zeros((1, 299, 299, 3)))
    )


def _net_template_shapes():
    """(net, template, {flat key: shape}) — shared by every test here."""
    net, template = _template()
    shapes = {
        k: tuple(v.shape)
        for k, v in flatten_params(
            jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), template)
        ).items()
    }
    return net, template, shapes


def _mock_state_dict(flat_shapes, seed=0):
    """torchvision-named state dict with shapes inverse-derived from our
    flat key shapes."""
    rng = np.random.RandomState(seed)
    sd = {}
    for ours, theirs in conv_bn_pairs():
        kh, kw, cin, cout = flat_shapes[f"params/{ours}/Conv_0/kernel"]
        # Fan-in scaling: unit-variance weights overflow float32 through
        # 94 stacked conv layers.
        scale = 1.0 / np.sqrt(kh * kw * cin)
        sd[f"{theirs}.conv.weight"] = (
            rng.randn(cout, cin, kh, kw).astype(np.float32) * scale
        )
        (c,) = flat_shapes[f"params/{ours}/BatchNorm_0/scale"]
        sd[f"{theirs}.bn.weight"] = rng.rand(c).astype(np.float32) + 0.5
        sd[f"{theirs}.bn.bias"] = rng.randn(c).astype(np.float32) * 0.1
        sd[f"{theirs}.bn.running_mean"] = rng.randn(c).astype(np.float32) * 0.1
        sd[f"{theirs}.bn.running_var"] = rng.rand(c).astype(np.float32) + 0.5
    return sd


def test_mapping_is_complete_and_loads(tmp_path):
    net, template, flat_shapes = _net_template_shapes()

    out = convert_state_dict(_mock_state_dict(flat_shapes))
    # Exactly our key set: nothing missing, nothing extra.
    assert set(out) == set(flat_shapes)
    for k, v in out.items():
        assert v.shape == flat_shapes[k], k

    path = str(tmp_path / "converted.npz")
    np.savez(path, **out)
    variables = load_params_npz(path, template)
    feats = net.apply(variables, jnp.zeros((1, 299, 299, 3)))
    assert feats.shape == (1, 2048)
    assert np.isfinite(np.asarray(feats)).all()


def test_kernel_transposition():
    """A marked torch OIHW kernel must land HWIO under the right key."""
    _, _, flat_shapes = _net_template_shapes()
    sd = _mock_state_dict(flat_shapes)
    marked = np.asarray(sd["Conv2d_1a_3x3.conv.weight"])  # [32, 3, 3, 3]
    out = convert_state_dict(sd)
    got = out["params/ConvBN_0/Conv_0/kernel"]  # [3, 3, 3, 32] HWIO
    np.testing.assert_array_equal(got, np.transpose(marked, (2, 3, 1, 0)))


def test_missing_tensor_is_loud():
    import pytest

    _, _, flat_shapes = _net_template_shapes()
    sd = _mock_state_dict(flat_shapes)
    del sd["Mixed_6b.branch7x7_2.conv.weight"]
    with pytest.raises(KeyError, match="Mixed_6b.branch7x7_2"):
        convert_state_dict(sd)
