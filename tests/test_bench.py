"""bench.py emission contract: exactly one JSON line on stdout, even when
configs fail or the driver kills the process mid-run."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _last_json(capsys):
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, f"expected one JSON line, got {out}"
    return json.loads(out[0])


def test_emit_empty(capsys):
    bench._emit({}, done=True)
    d = _last_json(capsys)
    assert d["value"] == 0.0 and "error" in d
    assert d["metric"] == "cyclegan_256_train_images_per_sec_1chip"


def test_emit_best_and_partial(capsys):
    bench._emit({"steps/float32/b1": 25.0, "scan/bfloat16/b8": 81.7}, done=False)
    d = _last_json(capsys)
    assert d["value"] == 81.7
    assert d["config"] == "scan/bfloat16/b8"
    assert d["vs_baseline"] == round(81.7 / 15.0, 3)
    assert d["partial"] is True
    assert set(d["all"]) == {"steps/float32/b1", "scan/bfloat16/b8"}


def test_emit_done_has_no_partial_flag(capsys):
    bench._emit({"k": 1.0}, done=True)
    assert "partial" not in _last_json(capsys)
