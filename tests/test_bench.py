"""bench.py emission contract: exactly one JSON line on stdout, even when
configs fail or the driver kills the process mid-run."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _last_json(capsys):
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1, f"expected one JSON line, got {out}"
    return json.loads(out[0])


def test_emit_empty(capsys):
    bench._emit({}, done=True)
    d = _last_json(capsys)
    assert d["value"] == 0.0 and "error" in d
    assert d["metric"] == "cyclegan_256_train_images_per_sec_1chip"


def test_emit_best_and_partial(capsys):
    bench._emit({"steps/float32/b1": 25.0, "scan/bfloat16/b8": 81.7}, done=False)
    d = _last_json(capsys)
    assert d["value"] == 81.7
    assert d["config"] == "scan/bfloat16/b8"
    assert d["vs_baseline"] == round(81.7 / 15.0, 3)
    assert d["partial"] is True
    assert set(d["all"]) == {"steps/float32/b1", "scan/bfloat16/b8"}


def test_emit_done_has_no_partial_flag(capsys):
    bench._emit({"k": 1.0}, done=True)
    assert "partial" not in _last_json(capsys)


def test_emit_includes_flops_accounting(capsys):
    bench._emit({"scan/bfloat16/b16": 95.0}, done=True)
    d = _last_json(capsys)
    # Analytic accounting rides along; MFU only when on a known TPU.
    assert d["flops_per_image"] > 9e11
    assert abs(d["tflops_per_sec"] - 95.0 * d["flops_per_image"] / 1e12) < 0.01
    assert "mfu" not in d  # platform is not tpu in tests


def test_emit_merges_cpu_worker_results(tmp_path, capsys, monkeypatch):
    """On a non-TPU platform the emitters fold in the concurrent CPU
    worker's incremental results file; in-process results win on clash."""
    path = tmp_path / "worker.json"
    path.write_text(json.dumps(
        {"steps/float32/b1": 0.02, "scan/bfloat16/b16": 7.0,
         bench._WORKER_DONE_KEY: True}
    ))
    monkeypatch.setattr(bench, "_WORKER_RESULTS_PATH", str(path))
    bench._emit({"scan/bfloat16/b16": 95.0}, done=True)
    d = _last_json(capsys)
    assert d["value"] == 95.0  # in-process beats worker on the clash
    assert d["all"]["steps/float32/b1"] == 0.02
    assert bench._WORKER_DONE_KEY not in d["all"]


def test_emit_never_mixes_cpu_worker_into_tpu_line(tmp_path, capsys, monkeypatch):
    """Chip emissions must be pure chip data: worker (CPU) numbers are
    dropped, not presented under platform=tpu."""
    path = tmp_path / "worker.json"
    path.write_text(json.dumps({"steps/float32/b1": 0.02}))
    monkeypatch.setattr(bench, "_WORKER_RESULTS_PATH", str(path))
    monkeypatch.setattr(bench, "_PLATFORM", "tpu")
    bench._emit({"scan/bfloat16/b16": 95.0}, done=False)
    d = _last_json(capsys)
    assert d["platform"] == "tpu"
    assert "steps/float32/b1" not in d["all"]
    assert "note" not in d


def test_emit_pure_worker_fallback_relabels_platform_cpu(tmp_path, capsys, monkeypatch):
    """If the tunnel re-wedged before any chip config completed, the
    worker's numbers carry the line — labeled cpu even though a _build
    had already recorded tpu."""
    path = tmp_path / "worker.json"
    path.write_text(json.dumps({"steps/float32/b1": 0.02}))
    monkeypatch.setattr(bench, "_WORKER_RESULTS_PATH", str(path))
    monkeypatch.setattr(bench, "_PLATFORM", "tpu")
    bench._emit({}, done=False)
    d = _last_json(capsys)
    assert d["platform"] == "cpu"
    assert d["value"] == 0.02
    assert "mfu" not in d and "note" in d


def test_emit_survives_malformed_peak_override(capsys, monkeypatch):
    """BENCH_PEAK_TFLOPS garbage must not break the emission contract:
    a raise inside _emit would permanently disarm every later emitter."""
    monkeypatch.setenv("BENCH_PEAK_TFLOPS", "459tflops")
    bench._emit({"scan/bfloat16/b16": 95.0}, done=True)
    d = _last_json(capsys)
    assert d["value"] == 95.0
    assert "mfu" not in d


def test_config_key_format():
    assert bench._config_key(
        {"mode": "scan", "dtype": "bfloat16", "batch": 16}
    ) == "scan/bfloat16/b16"
    assert bench._config_key(
        {"mode": "dispatch", "dtype": "bfloat16", "batch": 16, "k": 8}
    ) == "dispatch/bfloat16/b16/k8"
    assert bench._config_key(
        {"mode": "steps", "dtype": "float32", "batch": 4, "image": 512}
    ) == "steps/float32/b4/i512"
    assert bench._config_key(
        {"mode": "dispatch", "dtype": "bfloat16", "batch": 16, "k": 8,
         "prefetch": True}
    ) == "dispatch/bfloat16/b16/k8/pf"
    assert bench._config_key(
        {"mode": "scan", "dtype": "bfloat16", "batch": 16,
         "pad_impl": "fused"}
    ) == "scan/bfloat16/b16/fused"
    assert bench._config_key(
        {"mode": "scan", "dtype": "bfloat16", "batch": 16,
         "pad_mode": "zero"}
    ) == "scan/bfloat16/b16/zero"
    # grad_impl / trunk_impl segments: defaults add nothing (BENCH_r05
    # keys stay stable for run_compare), non-defaults land after the
    # pad-impl segment and before /zero.
    assert bench._config_key(
        {"mode": "scan", "dtype": "bfloat16", "batch": 16,
         "grad_impl": "fusedprop"}
    ) == "scan/bfloat16/b16/fusedprop"
    assert bench._config_key(
        {"mode": "scan", "dtype": "bfloat16", "batch": 16,
         "trunk_impl": "perturb"}
    ) == "scan/bfloat16/b16/perturb"
    assert bench._config_key(
        {"mode": "scan", "dtype": "bfloat16", "batch": 16,
         "grad_impl": "fusedprop", "trunk_impl": "perturb",
         "pad_mode": "zero"}
    ) == "scan/bfloat16/b16/fusedprop/perturb/zero"
    assert bench._config_key(
        {"mode": "steps", "dtype": "float32", "batch": 1,
         "grad_impl": "combined", "trunk_impl": "resnet"}
    ) == "steps/float32/b1"
    # upsample_impl segments: default adds nothing, zeroskip -> /zskip,
    # zeroskip_fused -> /zskipf (both headline-eligible parity tiers;
    # run_compare pairs them against the matching dense rows)
    assert bench._config_key(
        {"mode": "steps", "dtype": "float32", "batch": 1,
         "upsample_impl": "dense"}
    ) == "steps/float32/b1"
    assert bench._config_key(
        {"mode": "steps", "dtype": "float32", "batch": 1,
         "upsample_impl": "zeroskip"}
    ) == "steps/float32/b1/zskip"
    assert bench._config_key(
        {"mode": "scan", "dtype": "bfloat16", "batch": 16,
         "upsample_impl": "zeroskip_fused"}
    ) == "scan/bfloat16/b16/zskipf"
    assert bench._config_key(
        {"mode": "scan", "dtype": "bfloat16", "batch": 16,
         "grad_impl": "fusedprop", "upsample_impl": "zeroskip"}
    ) == "scan/bfloat16/b16/fusedprop/zskip"


def test_emit_headline_excludes_perturb_rows(capsys):
    """The perturb trunk is a different (cheaper) model — its img/s may
    ride in `all` but must never claim the reference-parity headline."""
    bench._emit({"scan/bfloat16/b16": 95.0,
                 "scan/bfloat16/b16/perturb": 200.0}, done=True)
    d = _last_json(capsys)
    assert d["value"] == 95.0 and d["config"] == "scan/bfloat16/b16"
    assert d["all"]["scan/bfloat16/b16/perturb"] == 200.0


def test_emit_headline_allows_fusedprop_rows(capsys):
    """fusedprop computes the SAME model and gradients — it is parity
    tier and may claim the headline when it wins."""
    bench._emit({"scan/bfloat16/b16": 95.0,
                 "scan/bfloat16/b16/fusedprop": 110.0}, done=True)
    d = _last_json(capsys)
    assert d["value"] == 110.0
    assert d["config"] == "scan/bfloat16/b16/fusedprop"


def test_emit_headline_excludes_zero_pad_rows(capsys):
    """/zero rows (non-parity border semantics) ride in `all` but must
    not claim the headline `value` — the metric means the REFERENCE's
    train step."""
    bench._emit({"scan/bfloat16/b16": 95.0,
                 "scan/bfloat16/b16/zero": 140.0}, done=True)
    d = _last_json(capsys)
    assert d["value"] == 95.0 and d["config"] == "scan/bfloat16/b16"
    assert d["all"]["scan/bfloat16/b16/zero"] == 140.0
    # a zero-only result set still emits (fallback pool)
    bench._emit({"scan/bfloat16/b16/zero": 140.0}, done=True)
    d = _last_json(capsys)
    assert d["value"] == 140.0


def test_flops_accounting_follows_winning_geometry():
    """ADVICE r2: a 512^2 winner must be accounted at 512^2 FLOPs, not
    the default 256^2 (which would overstate MFU ~4x the other way)."""
    base = bench._flops_accounting(10.0, "cpu", "scan/bfloat16/b16")
    big = bench._flops_accounting(10.0, "cpu", "steps/bfloat16/b4/i512")
    assert big["flops_per_image"] > 3.5 * base["flops_per_image"]


def test_emit_includes_probe_log(capsys, monkeypatch):
    """A fallback emission must record the probe attempts (when, how
    long, and what each saw) so the tunnel outage is on the record."""
    monkeypatch.setattr(
        bench, "_PROBE_LOG",
        [{"at_s": 0.0, "wait_s": 150.0, "result": "hung"}],
    )
    bench._emit({}, done=False)
    d = _last_json(capsys)
    assert d["probes"][0]["result"] == "hung"
    bench._emit({"scan/bfloat16/b16": 95.0}, done=True)  # non-empty path too
    d = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert d["probes"][0]["wait_s"] == 150.0


def test_bench_dispatch_smoke(monkeypatch):
    """Flow check for the dispatch mode (host-fed batches, k=1 plain jit
    vs k>1 fused scan) with a stub step — the real model at 256^2 is a
    chip job."""
    import jax.numpy as jnp

    def fake_build(dtype, batch, image, norm, pad_mode="reflect",
                   pad_impl="pad", grad_impl="combined",
                   trunk_impl="resnet", upsample_impl="dense"):
        state = jnp.zeros(())

        def step_fn(st, x, y, w):
            return st + 1.0, {"loss_G/total": st + jnp.mean(x) + jnp.mean(y)}

        return state, step_fn, None

    monkeypatch.setattr(bench, "_build", fake_build)
    assert bench.bench_dispatch("float32", 2, image=8, k=1, iters=2) > 0
    assert bench.bench_dispatch("float32", 2, image=8, k=3, iters=2) > 0
    # round-4 prefetch variant: same program, staged inputs
    assert bench.bench_dispatch("float32", 2, image=8, k=3, iters=2,
                                prefetch=True) > 0


def test_bench_accum_smoke(monkeypatch):
    """Flow check for the accum mode (grad-accumulation step, 512^2
    HBM-relief row) with stubbed state/step — the real program is a chip
    job; its EXACTNESS vs the big-batch step is pinned by
    tests/test_accum.py."""
    import jax.numpy as jnp

    import cyclegan_tpu.train as train_mod
    import cyclegan_tpu.train.steps as steps_mod

    monkeypatch.setattr(train_mod, "create_state",
                        lambda cfg, rng: jnp.zeros(()))

    captured = {}

    def fake_make(cfg, effective, accum):
        captured["effective"], captured["accum"] = effective, accum

        def accum_step(st, xs, ys, ws):
            return st + 1.0, {"loss_G/total": st + jnp.mean(xs) + jnp.mean(ys)}

        return accum_step

    monkeypatch.setattr(steps_mod, "make_accum_train_step", fake_make)
    ips = bench.bench_accum("float32", micro=2, image=8, accum=3, iters=2)
    assert ips > 0
    # effective batch = micro * accum; the update sees the full batch
    assert captured == {"effective": 6, "accum": 3}


def test_read_worker_results_tolerates_missing_and_garbage(tmp_path):
    assert bench._read_worker_results(None) == {}
    assert bench._read_worker_results(str(tmp_path / "nope.json")) == {}
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert bench._read_worker_results(str(bad)) == {}
