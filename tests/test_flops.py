"""Analytic FLOPs accounting (utils/flops.py).

The layer walk is validated structurally: the conv kernel shapes it
produces must reproduce the REAL models' conv parameter counts exactly
(params are the (ci, co, kh, kw) part of each layer tuple), so any drift
between the walk and models/{generator,discriminator}.py fails here.
"""

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cyclegan_tpu.config import Config, GeneratorConfig, ModelConfig
from cyclegan_tpu.models.discriminator import PatchGANDiscriminator
from cyclegan_tpu.models.generator import ResNetGenerator
from cyclegan_tpu.utils import flops as F


def _conv_param_count(params) -> int:
    """Count conv kernel elements only (the walk does not model IN
    scale/bias or conv biases)."""
    total = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        if any(getattr(p, "key", None) == "kernel" for p in path):
            total += leaf.size
    return total


def test_generator_layer_walk_matches_real_params():
    model = ResNetGenerator()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    walked = sum(
        ci * co * kh * kw for _, _, ci, co, kh, kw in F.generator_layers(64)
    )
    assert walked == _conv_param_count(params)


def test_discriminator_layer_walk_matches_real_params():
    model = PatchGANDiscriminator()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    walked = sum(
        ci * co * kh * kw for _, _, ci, co, kh, kw in F.discriminator_layers(64)
    )
    assert walked == _conv_param_count(params)


def test_nondefault_architecture_walk_matches_real_params():
    cfg = GeneratorConfig(filters=16, num_residual_blocks=3)
    model = ResNetGenerator(config=cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    walked = sum(
        ci * co * kh * kw
        for _, _, ci, co, kh, kw in F.generator_layers(
            32, filters=16, num_residual_blocks=3
        )
    )
    assert walked == _conv_param_count(params)


def test_step_flops_magnitude():
    cfg = Config()
    g = F.generator_fwd_flops(cfg)
    d = F.discriminator_fwd_flops(cfg)
    # Known magnitudes for the 256^2 default architecture.
    assert 90e9 < g < 110e9
    assert 5e9 < d < 8e9
    pair = F.train_step_flops_per_pair(cfg)
    assert pair == 18 * g + 16 * d
    assert F.train_step_flops_per_image(cfg) == pair / 2.0


def test_flops_scale_quadratically_with_image_size():
    small = Config(model=ModelConfig(image_size=128))
    big = Config(model=ModelConfig(image_size=256))
    ratio = F.train_step_flops_per_pair(big) / F.train_step_flops_per_pair(small)
    assert abs(ratio - 4.0) < 0.1


def test_peak_lookup():
    assert F.peak_tflops_for_device_kind("TPU v5 lite") == 197.0
    assert F.peak_tflops_for_device_kind("TPU v4") == 275.0
    assert F.peak_tflops_for_device_kind("weird accelerator") is None
