"""Analytic FLOPs accounting (utils/flops.py).

The layer walk is validated structurally: the conv kernel shapes it
produces must reproduce the REAL models' conv parameter counts exactly
(params are the (ci, co, kh, kw) part of each layer tuple), so any drift
between the walk and models/{generator,discriminator}.py fails here.
"""

import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cyclegan_tpu.config import Config, GeneratorConfig, ModelConfig, TrainConfig
from cyclegan_tpu.models.discriminator import PatchGANDiscriminator
from cyclegan_tpu.models.generator import ResNetGenerator
from cyclegan_tpu.utils import flops as F


def _conv_param_count(params) -> int:
    """Count conv kernel elements only (the walk does not model IN
    scale/bias or conv biases)."""
    total = 0
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        if any(getattr(p, "key", None) == "kernel" for p in path):
            total += leaf.size
    return total


def test_generator_layer_walk_matches_real_params():
    model = ResNetGenerator()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    walked = sum(
        ci * co * kh * kw for _, _, ci, co, kh, kw in F.generator_layers(64)
    )
    assert walked == _conv_param_count(params)


def test_discriminator_layer_walk_matches_real_params():
    model = PatchGANDiscriminator()
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    walked = sum(
        ci * co * kh * kw for _, _, ci, co, kh, kw in F.discriminator_layers(64)
    )
    assert walked == _conv_param_count(params)


def test_nondefault_architecture_walk_matches_real_params():
    cfg = GeneratorConfig(filters=16, num_residual_blocks=3)
    model = ResNetGenerator(config=cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)))
    walked = sum(
        ci * co * kh * kw
        for _, _, ci, co, kh, kw in F.generator_layers(
            32, filters=16, num_residual_blocks=3
        )
    )
    assert walked == _conv_param_count(params)


def test_perturb_layer_walk_matches_real_params():
    """The perturb trunk swaps the residual 3x3s for 1x1s; the walk's
    kernel shapes must track the REAL perturb generator's params."""
    model = ResNetGenerator(trunk_impl="perturb")
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)))
    walked = sum(
        ci * co * kh * kw
        for _, _, ci, co, kh, kw in F.generator_layers(
            64, trunk_impl="perturb"
        )
    )
    assert walked == _conv_param_count(params)


def test_step_flops_magnitude():
    cfg = Config()
    g = F.generator_fwd_flops(cfg)
    d = F.discriminator_fwd_flops(cfg)
    # Known magnitudes for the 256^2 default architecture. The dense
    # generator count includes the transposed convs' EXECUTED MACs on
    # the zero-dilated grid (~113.6G — upsample_impl="zeroskip" drops
    # the inserted-zero multiplies, see
    # test_zeroskip_flops_strictly_lower).
    assert 100e9 < g < 125e9
    assert 5e9 < d < 8e9
    pair = F.train_step_flops_per_pair(cfg)
    assert pair == 18 * g + 16 * d
    assert F.train_step_flops_per_image(cfg) == pair / 2.0


def test_fusedprop_flops_strictly_lower():
    """FusedProp shares each discriminator's fake forward between the
    adversarial and D gradients: 14d per pair instead of 16d. The
    analytic model must record the saving, and it must be a strict
    improvement (the acceptance criterion of the optimisation)."""
    combined = Config()
    fused = Config(train=TrainConfig(grad_impl="fusedprop"))
    g = F.generator_fwd_flops(combined)
    d = F.discriminator_fwd_flops(combined)
    pair_c = F.train_step_flops_per_pair(combined)
    pair_fp = F.train_step_flops_per_pair(fused)
    assert pair_c == 18 * g + 16 * d
    assert pair_fp == 18 * g + 14 * d
    assert pair_fp < pair_c


def test_zeroskip_flops_strictly_lower():
    """The GANAX output decomposition (ops/upsample.py) skips the
    inserted-zero MACs of the stride-2 transposed convs: each upsample
    computes in_h*in_w live taps instead of out_h*out_w dense ones — a
    4x cut on those layers, and a strict improvement overall (the
    acceptance criterion of the optimisation). Identical param tree, so
    the param-count walk must NOT change."""
    dense = Config()
    for impl in ("zeroskip", "zeroskip_fused"):
        zs = Config(model=ModelConfig(upsample_impl=impl))
        assert F.generator_fwd_flops(zs) < F.generator_fwd_flops(dense)
        assert F.train_step_flops_per_pair(zs) < (
            F.train_step_flops_per_pair(dense))
    # The saving is exactly 3/4 of the dense upsample MACs: a dense
    # upsample executes (2s)^2 * ci * co * 9 MACs on the zero-dilated
    # grid, the zeroskip form s^2 * ci * co * 9 live taps. At 256^2 the
    # two upsamples see s=64 (256ch -> 128ch) and s=128 (128ch -> 64ch).
    zs = Config(model=ModelConfig(upsample_impl="zeroskip"))
    got_saving = F.generator_fwd_flops(dense) - F.generator_fwd_flops(zs)
    want_saving = sum(
        2 * 3 * s * s * ci * co * 9
        for s, ci, co in [(64, 256, 128), (128, 128, 64)]
    )
    assert got_saving == want_saving
    # zeroskip param walk == dense param walk (checkpoints interchange)
    assert [(ci, co, kh, kw) for _, _, ci, co, kh, kw in
            F.generator_layers(64, upsample_impl="zeroskip")] == \
        [(ci, co, kh, kw) for _, _, ci, co, kh, kw in
         F.generator_layers(64)]


def test_perturb_trunk_flops_strictly_lower():
    resnet = Config()
    perturb = Config(model=ModelConfig(trunk_impl="perturb"))
    assert F.generator_fwd_flops(perturb) < F.generator_fwd_flops(resnet)
    assert F.train_step_flops_per_pair(perturb) < (
        F.train_step_flops_per_pair(resnet))


def test_flops_scale_quadratically_with_image_size():
    small = Config(model=ModelConfig(image_size=128))
    big = Config(model=ModelConfig(image_size=256))
    ratio = F.train_step_flops_per_pair(big) / F.train_step_flops_per_pair(small)
    assert abs(ratio - 4.0) < 0.1


def test_peak_lookup():
    assert F.peak_tflops_for_device_kind("TPU v5 lite") == 197.0
    assert F.peak_tflops_for_device_kind("TPU v4") == 275.0
    assert F.peak_tflops_for_device_kind("weird accelerator") is None
