"""Independent torch implementation of the FID-standard InceptionV3
pool3 graph — the numerical oracle for cyclegan_tpu/eval/inception.py.

Written from the published architecture (Szegedy et al. 2015; the
pytorch-fid `pt_inception-2015-12-05` graph for the two FID quirks:
count_include_pad=False average pools and Mixed_7c's max-pool branch),
NOT by importing torchvision — this environment has none, and an import
would defeat the point of an independent check. Module names match the
torchvision state-dict convention so tools/convert_inception_weights.py
maps this model's state dict onto the Flax port unchanged.

Input: [N, 3, 299, 299] in [-1, 1]. Output: [N, 2048] pool3 features.
"""

from __future__ import annotations

import torch
import torch.nn as nn
import torch.nn.functional as F


class BasicConv2d(nn.Module):
    def __init__(self, cin, cout, **kw):
        super().__init__()
        self.conv = nn.Conv2d(cin, cout, bias=False, **kw)
        self.bn = nn.BatchNorm2d(cout, eps=1e-3)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


def _avg3(x):
    # FID-graph average pool: 3x3 stride 1, border windows averaged over
    # valid pixels only.
    return F.avg_pool2d(x, 3, stride=1, padding=1, count_include_pad=False)


class Mixed5(nn.Module):  # 35x35 (InceptionA)
    def __init__(self, cin, pool_features):
        super().__init__()
        self.branch1x1 = BasicConv2d(cin, 64, kernel_size=1)
        self.branch5x5_1 = BasicConv2d(cin, 48, kernel_size=1)
        self.branch5x5_2 = BasicConv2d(48, 64, kernel_size=5, padding=2)
        self.branch3x3dbl_1 = BasicConv2d(cin, 64, kernel_size=1)
        self.branch3x3dbl_2 = BasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = BasicConv2d(96, 96, kernel_size=3, padding=1)
        self.branch_pool = BasicConv2d(cin, pool_features, kernel_size=1)

    def forward(self, x):
        b0 = self.branch1x1(x)
        b1 = self.branch5x5_2(self.branch5x5_1(x))
        b2 = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        b3 = self.branch_pool(_avg3(x))
        return torch.cat([b0, b1, b2, b3], 1)


class Mixed6a(nn.Module):  # 35 -> 17 (InceptionB)
    def __init__(self, cin):
        super().__init__()
        self.branch3x3 = BasicConv2d(cin, 384, kernel_size=3, stride=2)
        self.branch3x3dbl_1 = BasicConv2d(cin, 64, kernel_size=1)
        self.branch3x3dbl_2 = BasicConv2d(64, 96, kernel_size=3, padding=1)
        self.branch3x3dbl_3 = BasicConv2d(96, 96, kernel_size=3, stride=2)

    def forward(self, x):
        b0 = self.branch3x3(x)
        b1 = self.branch3x3dbl_3(self.branch3x3dbl_2(self.branch3x3dbl_1(x)))
        b2 = F.max_pool2d(x, 3, stride=2)
        return torch.cat([b0, b1, b2], 1)


class Mixed6(nn.Module):  # 17x17 (InceptionC)
    def __init__(self, cin, c7):
        super().__init__()
        self.branch1x1 = BasicConv2d(cin, 192, kernel_size=1)
        self.branch7x7_1 = BasicConv2d(cin, c7, kernel_size=1)
        self.branch7x7_2 = BasicConv2d(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7_3 = BasicConv2d(c7, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_1 = BasicConv2d(cin, c7, kernel_size=1)
        self.branch7x7dbl_2 = BasicConv2d(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_3 = BasicConv2d(c7, c7, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7dbl_4 = BasicConv2d(c7, c7, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7dbl_5 = BasicConv2d(c7, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch_pool = BasicConv2d(cin, 192, kernel_size=1)

    def forward(self, x):
        b0 = self.branch1x1(x)
        b1 = self.branch7x7_3(self.branch7x7_2(self.branch7x7_1(x)))
        b2 = self.branch7x7dbl_5(
            self.branch7x7dbl_4(
                self.branch7x7dbl_3(self.branch7x7dbl_2(self.branch7x7dbl_1(x)))
            )
        )
        b3 = self.branch_pool(_avg3(x))
        return torch.cat([b0, b1, b2, b3], 1)


class Mixed7a(nn.Module):  # 17 -> 8 (InceptionD)
    def __init__(self, cin):
        super().__init__()
        self.branch3x3_1 = BasicConv2d(cin, 192, kernel_size=1)
        self.branch3x3_2 = BasicConv2d(192, 320, kernel_size=3, stride=2)
        self.branch7x7x3_1 = BasicConv2d(cin, 192, kernel_size=1)
        self.branch7x7x3_2 = BasicConv2d(192, 192, kernel_size=(1, 7), padding=(0, 3))
        self.branch7x7x3_3 = BasicConv2d(192, 192, kernel_size=(7, 1), padding=(3, 0))
        self.branch7x7x3_4 = BasicConv2d(192, 192, kernel_size=3, stride=2)

    def forward(self, x):
        b0 = self.branch3x3_2(self.branch3x3_1(x))
        b1 = self.branch7x7x3_4(
            self.branch7x7x3_3(self.branch7x7x3_2(self.branch7x7x3_1(x)))
        )
        b2 = F.max_pool2d(x, 3, stride=2)
        return torch.cat([b0, b1, b2], 1)


class Mixed7(nn.Module):  # 8x8 (InceptionE; pool="max" = FID Mixed_7c)
    def __init__(self, cin, pool="avg"):
        super().__init__()
        self.pool = pool
        self.branch1x1 = BasicConv2d(cin, 320, kernel_size=1)
        self.branch3x3_1 = BasicConv2d(cin, 384, kernel_size=1)
        self.branch3x3_2a = BasicConv2d(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3_2b = BasicConv2d(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch3x3dbl_1 = BasicConv2d(cin, 448, kernel_size=1)
        self.branch3x3dbl_2 = BasicConv2d(448, 384, kernel_size=3, padding=1)
        self.branch3x3dbl_3a = BasicConv2d(384, 384, kernel_size=(1, 3), padding=(0, 1))
        self.branch3x3dbl_3b = BasicConv2d(384, 384, kernel_size=(3, 1), padding=(1, 0))
        self.branch_pool = BasicConv2d(cin, 192, kernel_size=1)

    def forward(self, x):
        b0 = self.branch1x1(x)
        b1 = self.branch3x3_1(x)
        b1 = torch.cat([self.branch3x3_2a(b1), self.branch3x3_2b(b1)], 1)
        b2 = self.branch3x3dbl_2(self.branch3x3dbl_1(x))
        b2 = torch.cat([self.branch3x3dbl_3a(b2), self.branch3x3dbl_3b(b2)], 1)
        if self.pool == "max":
            pooled = F.max_pool2d(x, 3, stride=1, padding=1)
        else:
            pooled = _avg3(x)
        b3 = self.branch_pool(pooled)
        return torch.cat([b0, b1, b2, b3], 1)


class TorchInceptionPool3(nn.Module):
    """Stem through Mixed_7c, global-average-pooled to [N, 2048]."""

    def __init__(self):
        super().__init__()
        self.Conv2d_1a_3x3 = BasicConv2d(3, 32, kernel_size=3, stride=2)
        self.Conv2d_2a_3x3 = BasicConv2d(32, 32, kernel_size=3)
        self.Conv2d_2b_3x3 = BasicConv2d(32, 64, kernel_size=3, padding=1)
        self.Conv2d_3b_1x1 = BasicConv2d(64, 80, kernel_size=1)
        self.Conv2d_4a_3x3 = BasicConv2d(80, 192, kernel_size=3)
        self.Mixed_5b = Mixed5(192, 32)
        self.Mixed_5c = Mixed5(256, 64)
        self.Mixed_5d = Mixed5(288, 64)
        self.Mixed_6a = Mixed6a(288)
        self.Mixed_6b = Mixed6(768, 128)
        self.Mixed_6c = Mixed6(768, 160)
        self.Mixed_6d = Mixed6(768, 160)
        self.Mixed_6e = Mixed6(768, 192)
        self.Mixed_7a = Mixed7a(768)
        self.Mixed_7b = Mixed7(1280, pool="avg")
        self.Mixed_7c = Mixed7(2048, pool="max")

    def forward(self, x):
        x = self.Conv2d_1a_3x3(x)
        x = self.Conv2d_2a_3x3(x)
        x = self.Conv2d_2b_3x3(x)
        x = F.max_pool2d(x, 3, stride=2)
        x = self.Conv2d_3b_1x1(x)
        x = self.Conv2d_4a_3x3(x)
        x = F.max_pool2d(x, 3, stride=2)
        x = self.Mixed_5b(x)
        x = self.Mixed_5c(x)
        x = self.Mixed_5d(x)
        x = self.Mixed_6a(x)
        x = self.Mixed_6b(x)
        x = self.Mixed_6c(x)
        x = self.Mixed_6d(x)
        x = self.Mixed_6e(x)
        x = self.Mixed_7a(x)
        x = self.Mixed_7b(x)
        x = self.Mixed_7c(x)
        return torch.mean(x, dim=(2, 3))


def randomize_(model: TorchInceptionPool3, seed: int = 0) -> None:
    """Deterministic non-trivial weights INCLUDING batch-norm running
    stats (default mean=0/var=1 would leave the stats mapping untested)."""
    g = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, nn.Conv2d):
                m.weight.normal_(0.0, 0.05, generator=g)
            elif isinstance(m, nn.BatchNorm2d):
                m.weight.normal_(1.0, 0.2, generator=g)
                m.bias.normal_(0.0, 0.1, generator=g)
                m.running_mean.normal_(0.0, 0.5, generator=g)
                m.running_var.uniform_(0.5, 2.0, generator=g)
