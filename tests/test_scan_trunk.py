"""scan_blocks=True (lax.scan residual trunk) is a pure layout/compile
trade: same function, same parameter count, stacked param layout.

The unrolled trunk is the reference semantics (model.py:155-156, nine
sequential blocks); the scanned trunk must be numerically identical given
converted params, and the layout converters must round-trip.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu.config import GeneratorConfig
from cyclegan_tpu.models import (
    ResNetGenerator,
    stack_trunk_params,
    unstack_trunk_params,
)

CFG = GeneratorConfig(filters=4, num_residual_blocks=3)


def _x(seed=0, n=2, s=16):
    return jnp.asarray(np.random.RandomState(seed).rand(n, s, s, 3), jnp.float32)


def test_scan_matches_unrolled_given_converted_params():
    x = _x()
    plain = ResNetGenerator(config=CFG, scan_blocks=False)
    scanned = ResNetGenerator(config=CFG, scan_blocks=True)
    params = plain.init(jax.random.PRNGKey(0), x)
    sparams = stack_trunk_params(params, CFG.num_residual_blocks)
    np.testing.assert_allclose(
        np.asarray(plain.apply(params, x)),
        np.asarray(scanned.apply(sparams, x)),
        rtol=1e-6,
        atol=1e-6,
    )


def test_roundtrip_and_param_count():
    x = _x()
    plain = ResNetGenerator(config=CFG, scan_blocks=False)
    scanned = ResNetGenerator(config=CFG, scan_blocks=True)
    params = plain.init(jax.random.PRNGKey(1), x)
    sparams = scanned.init(jax.random.PRNGKey(1), x)

    n = lambda p: sum(a.size for a in jax.tree.leaves(p))
    assert n(params) == n(sparams)

    back = unstack_trunk_params(
        stack_trunk_params(params, CFG.num_residual_blocks), CFG.num_residual_blocks
    )
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("remat", [False, True])
def test_scan_gradients_match_unrolled(remat):
    """One backward through the scanned trunk == unrolled gradients
    (stacked back to the unrolled layout for comparison)."""
    x = _x(2)
    plain = ResNetGenerator(config=CFG, scan_blocks=False)
    scanned = ResNetGenerator(config=CFG, scan_blocks=True, remat=remat)
    params = plain.init(jax.random.PRNGKey(2), x)
    sparams = stack_trunk_params(params, CFG.num_residual_blocks)

    g_plain = jax.grad(lambda p: jnp.sum(plain.apply(p, x) ** 2))(params)
    g_scan = jax.grad(lambda p: jnp.sum(scanned.apply(p, x) ** 2))(sparams)
    g_scan_unrolled = unstack_trunk_params(g_scan, CFG.num_residual_blocks)

    flat_a = jax.tree_util.tree_flatten_with_path(g_plain)[0]
    flat_b = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_flatten_with_path(g_scan_unrolled)[0]
    )
    assert len(flat_a) == len(flat_b)
    for key, a in flat_a:
        np.testing.assert_allclose(
            np.asarray(a),
            np.asarray(flat_b[jax.tree_util.keystr(key)]),
            rtol=1e-5,
            atol=1e-6,
            err_msg=jax.tree_util.keystr(key),
        )


def test_scanned_hlo_is_smaller():
    """The point of scan_blocks: the trunk compiles to one loop body, not
    nine inlined copies — the lowered HLO text must shrink."""
    cfg = GeneratorConfig(filters=4, num_residual_blocks=9)
    x = _x(0, 1, 16)
    plain = ResNetGenerator(config=cfg, scan_blocks=False)
    scanned = ResNetGenerator(config=cfg, scan_blocks=True)
    p = plain.init(jax.random.PRNGKey(0), x)
    sp = scanned.init(jax.random.PRNGKey(0), x)
    hlo_plain = jax.jit(plain.apply).lower(p, x).as_text()
    hlo_scan = jax.jit(scanned.apply).lower(sp, x).as_text()
    # At tiny test sizes the fixed stem/head HLO dominates, so the whole-
    # program shrink is modest; the trunk itself collapses 9x.
    assert len(hlo_scan) < 0.8 * len(hlo_plain), (
        f"scan HLO {len(hlo_scan)}B not <80% of unrolled {len(hlo_plain)}B"
    )
