"""tools/obs_report.py: folding and rendering telemetry JSONL streams.

Pure host-side — no jax needed by the tool itself (it must render
streams on machines without jax), so these tests exercise it on
synthetic streams written as plain text.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

from obs_report import _percentile, fold, load_events, render  # noqa: E402


def _write_stream(path, events):
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def _synthetic_events():
    """A plausible 2-epoch training stream."""
    evs = [
        {"event": "manifest", "t": 0.0, "schema_version": 1,
         "hostname": "tpu-host", "pid": 42, "git_sha": "a" * 40,
         "versions": {"python": "3.10.0", "jax": "0.4.37",
                      "jaxlib": "0.4.36"},
         "mesh": {"n_devices": 8, "n_data": 8, "n_spatial": 1,
                  "platform": "tpu", "device_kind": "TPU v4"},
         "host": {"process_index": 0, "process_count": 2,
                  "local_device_count": 4}},
    ]
    t = 1.0
    for epoch in range(2):
        for i in range(10):
            evs.append({"event": "step", "t": t, "split": "train",
                        "epoch": epoch, "dispatch": i, "steps": 1,
                        "kind": "single", "stage_s": 0.01,
                        "dispatch_s": 0.002, "fetch_block_s": 0.08,
                        "depth": 1, "wall_s": 0.1})
            t += 0.1
        evs.append({"event": "epoch_steps", "t": t, "split": "train",
                    "epoch": epoch, "n_dispatches": 10, "n_steps": 10,
                    "wall_s": 1.0, "stage_s": 0.1, "dispatch_s": 0.02,
                    "fetch_block_s": 0.8, "drain_s": 0.05,
                    "starvation_fraction": 0.1, "wall_p50_s": 0.1,
                    "wall_p90_s": 0.1, "wall_max_s": 0.1})
        evs.append({"event": "epoch", "t": t, "epoch": epoch,
                    "elapse_s": 1.0, "images_per_sec": 80.0,
                    "tflops_per_sec": 5.0, "mfu": 0.3 + 0.1 * epoch})
        evs.append({"event": "memory", "t": t, "epoch": epoch,
                    "available": True, "devices": [
                        {"id": 0, "kind": "TPU v4",
                         "bytes_in_use": 1 << 30,
                         "peak_bytes_in_use": (2 + epoch) << 30,
                         "bytes_limit": 8 << 30}]})
        t += 0.5
    evs.append({"event": "stall", "t": t, "age_s": 65.0,
                "deadline_s": 60.0, "pending_depth": 32})
    evs.append({"event": "end", "t": t + 1, "status": "completed"})
    return evs


def test_fold_synthetic_stream(tmp_path):
    path = str(tmp_path / "t.jsonl")
    _write_stream(path, _synthetic_events())
    events, skipped = load_events(path)
    assert skipped == 0
    rep = fold(events, skipped)

    assert rep["manifest"]["hostname"] == "tpu-host"
    assert len(rep["epochs"]) == 2
    assert len(rep["epoch_steps"]) == 2
    assert len(rep["steps"]["train"]) == 20
    # Derived rollups.
    assert rep["train_starvation_fraction"] == pytest.approx(0.1)
    assert rep["mfu_trajectory"] == [(0, pytest.approx(0.3)),
                                     (1, pytest.approx(0.4))]
    # Memory peak is the max across samples (epoch 1's 3GB beats 2GB).
    assert rep["memory_peaks"][0]["peak_bytes_in_use"] == 3 << 30
    assert len(rep["stalls"]) == 1
    assert rep["end"]["status"] == "completed"


def test_render_synthetic_stream(tmp_path):
    path = str(tmp_path / "t.jsonl")
    _write_stream(path, _synthetic_events())
    events, skipped = load_events(path)
    text = render(fold(events, skipped))

    assert "tpu-host" in text
    assert "jax 0.4.37" in text
    assert "8 devices" in text and "platform tpu" in text
    assert "starvation fraction" in text
    assert "0.3000" in text and "0.4000" in text  # MFU column
    assert "peak 3.0GB of 8.0GB" in text
    assert "headroom 5.0GB" in text
    assert "pending depth 32" in text
    assert "run end: completed" in text


def test_tolerates_garbage_and_truncation(tmp_path):
    path = str(tmp_path / "t.jsonl")
    evs = _synthetic_events()
    with open(path, "w") as f:
        f.write("not json at all\n")
        f.write(json.dumps(evs[0]) + "\n")
        f.write(json.dumps({"no_event_key": 1}) + "\n")
        f.write(json.dumps({"event": "from_the_future", "t": 9.9,
                            "payload": [1, 2]}) + "\n")
        # A SIGKILLed run legally truncates its last line mid-write.
        f.write(json.dumps(evs[1])[: len(json.dumps(evs[1])) // 2])
    events, skipped = load_events(path)
    assert skipped == 3  # garbage + missing-event-key + truncated tail
    rep = fold(events, skipped)
    assert rep["manifest"] is not None
    text = render(rep)
    assert "skipped 3 malformed/truncated lines" in text
    # No end event: the report must say so, not crash.
    assert "NO end event" in text


def test_empty_and_partial_streams(tmp_path):
    path = str(tmp_path / "empty.jsonl")
    open(path, "w").close()
    events, skipped = load_events(path)
    rep = fold(events, skipped)
    text = render(rep)
    assert "manifest: MISSING" in text
    assert "stalls: none" in text

    # Steps only — no manifest, no epoch events (a crashed first epoch).
    path2 = str(tmp_path / "partial.jsonl")
    _write_stream(path2, [
        {"event": "step", "t": 0.1, "split": "train", "epoch": 0,
         "wall_s": 0.2, "stage_s": 0.05},
    ])
    events, skipped = load_events(path2)
    text = render(fold(events, skipped))
    assert "per-dispatch" in text


def test_bench_stream_sections(tmp_path):
    path = str(tmp_path / "bench.jsonl")
    _write_stream(path, [
        {"event": "manifest", "t": 0.0, "role": "bench",
         "versions": {"python": "3.10.0"}},
        {"event": "bench", "t": 10.0, "key": "baseline",
         "images_per_sec": 90.5, "platform": "tpu", "spent_s": 9.8},
        {"event": "bench_error", "t": 12.0, "key": "broken",
         "error": "boom"},
        {"event": "bench", "t": 20.0, "key": "fused_k8",
         "images_per_sec": 140.2, "platform": "tpu", "spent_s": 9.9},
        {"event": "bench_summary", "t": 21.0, "value": 140.2,
         "unit": "images/sec", "config": "fused_k8", "platform": "tpu",
         "mfu": 0.41},
        {"event": "end", "t": 21.1, "status": "completed"},
    ])
    events, skipped = load_events(path)
    rep = fold(events, skipped)
    assert [b["key"] for b in rep["bench"]] == ["baseline", "fused_k8"]
    assert rep["bench_summary"]["value"] == 140.2
    text = render(rep)
    assert "baseline: 90.50 images/sec" in text
    assert "bench headline: 140.20 images/sec (fused_k8" in text
    assert "mfu 0.4100" in text


def test_self_driving_fleet_sections_fold_and_render(tmp_path):
    """The autoscale/brownout/hedge/quarantine events fold into the
    autoscale_rollup and render as the self-driving-fleet section with
    the scale timeline."""
    path = str(tmp_path / "fleet.jsonl")
    _write_stream(path, [
        {"event": "manifest", "t": 0.0, "role": "serve"},
        {"event": "fleet_brownout", "t": 1.0, "level": 1,
         "quality_cap": 3, "steps_by_class": {"best_effort": 1},
         "backlog_s": 0.12},
        {"event": "fleet_autoscale", "t": 1.2, "phase": "up",
         "replica": 1, "n_active": 2},
        {"event": "fleet_hedge", "t": 1.5, "klass": "interactive",
         "replica": 0, "age_ms": 61.0, "hedge_ms": 60.0},
        {"event": "fleet_hedge_cancel", "t": 1.6, "klass": "interactive",
         "reason": "won_elsewhere", "depth": 3},
        {"event": "fleet_quality_probe", "t": 1.8, "tier_full": "base",
         "delta": 0.01, "ewma": 0.01, "verdict": "narrow",
         "quality_cap": 2, "level": 1},
        {"event": "fleet_quarantine", "t": 2.0, "action": "quarantine",
         "replica": 0, "p95_s": 0.9, "fleet_median_s": 0.1},
        {"event": "fleet_quarantine", "t": 2.5, "action": "readmit",
         "replica": 0, "probe_s": 0.1, "bound_s": 0.2, "strikes": 0},
        {"event": "fleet_autoscale", "t": 3.0, "phase": "down",
         "replica": 1, "n_active": 1},
        {"event": "fleet_autoscale", "t": 3.1, "phase": "retired",
         "replica": 1, "n_active": 1},
        {"event": "fleet_summary", "t": 4.0, "n_images": 100,
         "n_replicas": 2, "degraded_requests": 7,
         "degraded_census": {"best_effort:int8": 7},
         "scale_ups": 1, "scale_downs": 1},
        {"event": "end", "t": 4.1, "status": "completed"},
    ])
    events, skipped = load_events(path)
    rep = fold(events, skipped)
    roll = rep["autoscale_rollup"]
    assert roll["scale_events"] == {"up": 1, "down": 1, "retired": 1}
    assert roll["final_n_active"] == 1
    assert roll["brownout_moves"] == 1 and roll["brownout_max_level"] == 1
    assert roll["hedges_dispatched"] == 1
    assert roll["hedge_cancels"] == {"won_elsewhere": 1}
    assert roll["probe_verdicts"] == {"narrow": 1}
    assert roll["quarantine_actions"] == {"quarantine": 1, "readmit": 1}
    text = render(rep)
    assert "-- self-driving fleet" in text
    assert "scale events: 1 up, 1 down (1 retirements completed)" in text
    assert "brownout: 1 level moves, deepest level 1" in text
    assert "hedges: 1 dispatched, cancelled won_elsewhere=1" in text
    assert "quality probes: narrow=1" in text
    assert "quarantine: quarantine=1, readmit=1" in text
    assert "t=1.20s scale up replica 1 -> 2 active" in text
    assert "t=1.00s brownout level 1" in text
    assert "degraded requests: 7 (best_effort:int8=7)" in text
    # A stream without any self-driving events renders no section.
    plain = fold([{"event": "end", "t": 1.0, "status": "completed"}], 0)
    assert "autoscale_rollup" not in plain
    assert "-- self-driving fleet" not in render(plain)


def test_health_sections_fold_and_render():
    """The flight-recorder fixture (tests/data/run_fail.jsonl, also the
    run_compare FAIL fixture) carries health + health_fault events: the
    report folds them into grad-norm percentiles, D-balance, final
    losses, and an anomaly census."""
    path = os.path.join(REPO, "tests", "data", "run_fail.jsonl")
    events, skipped = load_events(path)
    assert skipped == 0
    rep = fold(events, skipped)

    assert len(rep["health"]) == 3
    hr = rep["health_rollup"]
    assert hr["n_epochs"] == 3
    # max over the per-epoch max envelopes; p50 over the per-epoch means.
    assert hr["gnorm_percentiles"]["G"]["max"] == pytest.approx(80.0)
    assert hr["gnorm_percentiles"]["G"]["p50"] == pytest.approx(9.0)
    assert hr["anomalies"] == {"d_collapse": 1, "divergence": 1}
    assert hr["last_loss"]["loss_G/total"] == pytest.approx(12.4)

    text = render(rep)
    assert "model health (3 epoch rollups)" in text
    assert "grad-norm G:" in text
    assert "D-balance dX (last epoch): D(real) 0.990" in text
    assert "anomalies: d_collapse=1, divergence=1" in text
    assert "health faults: 2" in text
    assert "divergence [warn]" in text


def test_healthless_stream_renders_without_health_section(tmp_path):
    """Streams that predate the health layer keep rendering unchanged
    (consumers ignore unknown events, and absent ones too)."""
    path = str(tmp_path / "t.jsonl")
    _write_stream(path, _synthetic_events())
    events, skipped = load_events(path)
    text = render(fold(events, skipped))
    assert "model health" not in text
    assert "health faults" not in text


def test_percentile_nearest_rank():
    assert _percentile([], 0.5) != _percentile([], 0.5)  # nan
    assert _percentile([3.0], 0.99) == 3.0
    vals = [float(i) for i in range(1, 11)]
    assert _percentile(vals, 0.0) == 1.0
    assert _percentile(vals, 1.0) == 10.0
    assert _percentile(vals, 0.5) in (5.0, 6.0)


def test_cli_text_and_json(tmp_path):
    path = str(tmp_path / "t.jsonl")
    _write_stream(path, _synthetic_events())
    tool = os.path.join(REPO, "tools", "obs_report.py")

    out = subprocess.run([sys.executable, tool, path],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "telemetry run report" in out.stdout

    out = subprocess.run([sys.executable, tool, path, "--json"],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    rep = json.loads(out.stdout)
    assert rep["n_events"] == len(_synthetic_events())

    out = subprocess.run([sys.executable, tool,
                          str(tmp_path / "missing.jsonl")],
                         capture_output=True, text=True, timeout=60)
    assert out.returncode == 2
