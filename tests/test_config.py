"""ModelConfig validation: bad enum values must fail loudly.

Round-3 advisor finding: a typo like pad_mode='Reflect' silently selected
zero/SAME padding (non-parity border numerics) when Config was built
programmatically — only main.py's argparse choices guarded it.
"""

import pytest

from cyclegan_tpu.config import Config, ModelConfig


def test_pad_mode_typo_raises():
    with pytest.raises(ValueError, match="pad_mode"):
        ModelConfig(pad_mode="Reflect")


def test_pad_mode_valid_values_accepted():
    assert ModelConfig(pad_mode="reflect").pad_mode == "reflect"
    assert ModelConfig(pad_mode="zero").pad_mode == "zero"


def test_instance_norm_impl_typo_raises():
    with pytest.raises(ValueError, match="instance_norm_impl"):
        ModelConfig(instance_norm_impl="Pallas")


def test_default_config_constructs():
    assert Config().model.pad_mode == "reflect"


def test_pad_impl_typo_raises():
    with pytest.raises(ValueError, match="pad_impl"):
        ModelConfig(pad_impl="Epilogue")


def test_pad_impl_valid_values_accepted():
    assert ModelConfig(pad_impl="pad").pad_impl == "pad"
    assert ModelConfig(pad_impl="fused").pad_impl == "fused"
    assert ModelConfig(pad_impl="epilogue").pad_impl == "epilogue"


def test_zero_pad_mode_rejects_reflect_schedules():
    # "fused"/"epilogue" schedule REFLECT semantics; combining them with
    # pad_mode="zero" is a contradiction that must fail at construction,
    # not silently pick one interpretation at trace time.
    for impl in ("fused", "epilogue"):
        with pytest.raises(ValueError, match="reflect"):
            ModelConfig(pad_mode="zero", pad_impl=impl)


def test_epilogue_rejects_xla_norm():
    with pytest.raises(ValueError, match="epilogue"):
        ModelConfig(pad_impl="epilogue", instance_norm_impl="xla")


def test_epilogue_rejects_ineligible_trunk_shape():
    # At 512^2 the residual trunk is 128^2 — past the epilogue slab
    # budget for either compute dtype. The flag would buy nothing (every
    # site silently falls back), so construction fails with the numbers.
    for dtype in ("float32", "bfloat16"):
        with pytest.raises(ValueError, match="VMEM"):
            ModelConfig(pad_impl="epilogue", image_size=512,
                        compute_dtype=dtype)


def test_epilogue_accepted_on_eligible_shapes():
    # The default 256^2 trunk (64^2) fits in both dtypes.
    assert ModelConfig(pad_impl="epilogue").pad_impl == "epilogue"
    assert ModelConfig(
        pad_impl="epilogue", compute_dtype="bfloat16"
    ).pad_impl == "epilogue"
