"""ModelConfig validation: bad enum values must fail loudly.

Round-3 advisor finding: a typo like pad_mode='Reflect' silently selected
zero/SAME padding (non-parity border numerics) when Config was built
programmatically — only main.py's argparse choices guarded it.
"""

import pytest

from cyclegan_tpu.config import Config, ModelConfig


def test_pad_mode_typo_raises():
    with pytest.raises(ValueError, match="pad_mode"):
        ModelConfig(pad_mode="Reflect")


def test_pad_mode_valid_values_accepted():
    assert ModelConfig(pad_mode="reflect").pad_mode == "reflect"
    assert ModelConfig(pad_mode="zero").pad_mode == "zero"


def test_instance_norm_impl_typo_raises():
    with pytest.raises(ValueError, match="instance_norm_impl"):
        ModelConfig(instance_norm_impl="Pallas")


def test_default_config_constructs():
    assert Config().model.pad_mode == "reflect"
