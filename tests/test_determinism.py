"""Bitwise run-to-run determinism.

The reference's only reproducibility mechanism is seeding np/tf once
(/root/reference/main.py:366-367) — actual run-to-run determinism is not
guaranteed under tf.data's threaded shuffle. Here determinism is a
contract: same seed => identical init, identical per-epoch data order and
augmentations, identical metrics, bit for bit.
"""

import jax
import numpy as np

from cyclegan_tpu.data import build_data
from cyclegan_tpu.parallel import make_mesh_plan, shard_batch, shard_train_step
from cyclegan_tpu.parallel.mesh import replicated
from cyclegan_tpu.train import create_state, make_train_step


def _run_two_steps(tiny_config, devices):
    config = tiny_config
    plan = make_mesh_plan(config.parallel, devices[:4])
    global_batch = 4
    data = build_data(config, global_batch)
    state = create_state(config, jax.random.PRNGKey(config.train.seed))
    state = jax.device_put(state, replicated(plan))
    step = shard_train_step(plan, make_train_step(config, global_batch))
    out = []
    for i, (x, y, w) in enumerate(data.train_epoch(0, prefetch=False)):
        xs, ys, ws = shard_batch(plan, x, y, w)
        state, metrics = step(state, xs, ys, ws)
        out.append({k: float(v) for k, v in jax.device_get(metrics).items()})
        if i == 1:
            break
    return out


def test_same_seed_bitwise_identical(tiny_config, devices):
    a = _run_two_steps(tiny_config, devices)
    b = _run_two_steps(tiny_config, devices)
    assert a == b  # exact float equality, not allclose


def test_data_order_is_seeded_per_epoch(tiny_config):
    data = build_data(tiny_config, 4)
    e0 = list(data.train_epoch(0, prefetch=False))
    e0b = list(data.train_epoch(0, prefetch=False))
    e1 = list(data.train_epoch(1, prefetch=False))
    for (x0, y0, w0), (x0b, y0b, w0b) in zip(e0, e0b):
        np.testing.assert_array_equal(x0, x0b)
        np.testing.assert_array_equal(y0, y0b)
        np.testing.assert_array_equal(w0, w0b)
    # different epoch => different order (full permutation reshuffles)
    assert any(
        not np.array_equal(x0, x1) for (x0, _, _), (x1, _, _) in zip(e0, e1)
    )
