"""TFDSSource exercised end-to-end via a fake `tensorflow_datasets`.

The reference's ONLY data source is TFDS (/root/reference/main.py:22-26);
this environment has no tensorflow_datasets and no egress, so a
test-local shim module (builder -> in-memory arrays) stands in. Both
TFDSSource paths run: the lazy random-access `as_data_source` path and
the materializing `as_dataset` fallback — covering label discard
(main.py:40), split wiring, and the full CycleGANData pipeline on top.
"""

import sys
import types

import numpy as np
import pytest

from cyclegan_tpu.config import Config, DataConfig, TrainConfig
from cyclegan_tpu.data.pipeline import CycleGANData
from cyclegan_tpu.data.sources import SPLITS, TFDSSource, resolve_source

SIZES = {"trainA": 5, "trainB": 4, "testA": 3, "testB": 2}
HW = 32


def _img(split: str, i: int) -> np.ndarray:
    rng = np.random.RandomState(hash((split, i)) % (2**31))
    return rng.randint(0, 256, size=(HW, HW, 3), dtype=np.uint8)


class _FakeBuilder:
    """Mimics the tfds builder surface TFDSSource touches."""

    def __init__(self, *, random_access: bool):
        self._random_access = random_access
        self.prepared = False
        self.as_dataset_calls = []
        self.as_data_source_calls = []

    def download_and_prepare(self):
        self.prepared = True

    def as_data_source(self, split):
        self.as_data_source_calls.append(split)
        if not self._random_access:
            raise NotImplementedError("no random-access format prepared")
        imgs = [_img(split, i) for i in range(SIZES[split])]
        # Real data_source records are feature dicts with the label kept.
        return [{"image": im, "label": np.int64(0)} for im in imgs]

    def as_dataset(self, split, as_supervised):
        assert as_supervised, "TFDSSource must request (image, label) tuples"
        self.as_dataset_calls.append(split)

        class _DS:
            def as_numpy_iterator(self_inner):
                for i in range(SIZES[split]):
                    yield _img(split, i), np.int64(1)

        return _DS()


@pytest.fixture
def fake_tfds(monkeypatch):
    """Install a fake tensorflow_datasets; yields the builder registry."""
    builders = {}

    def builder(name, data_dir=None):
        assert name.startswith("cycle_gan/"), name
        b = builders.setdefault(name, _FakeBuilder(
            random_access=builders.get("__random_access__", True)
        ))
        return b

    mod = types.SimpleNamespace(builder=builder)
    monkeypatch.setitem(sys.modules, "tensorflow_datasets", mod)
    return builders


def _check_source(src: TFDSSource):
    assert src.name == "tfds:cycle_gan/horse2zebra"
    for split in SPLITS:
        assert src.split_size(split) == SIZES[split]
    img = src.load("trainA", 2)
    assert img.dtype == np.uint8 and img.shape == (HW, HW, 3)
    np.testing.assert_array_equal(img, _img("trainA", 2))  # label discarded


def test_lazy_random_access_path(fake_tfds):
    src = TFDSSource("horse2zebra")
    b = fake_tfds["cycle_gan/horse2zebra"]
    assert b.prepared
    assert sorted(b.as_data_source_calls) == sorted(SPLITS)
    assert b.as_dataset_calls == []  # nothing materialized
    _check_source(src)


def test_materializing_fallback_path(fake_tfds):
    fake_tfds["__random_access__"] = False
    src = TFDSSource("horse2zebra")
    b = fake_tfds["cycle_gan/horse2zebra"]
    assert sorted(b.as_dataset_calls) == sorted(SPLITS)
    _check_source(src)


def test_resolve_source_tfds(fake_tfds):
    cfg = DataConfig(source="tfds", dataset="horse2zebra")
    src = resolve_source(cfg)
    assert isinstance(src, TFDSSource)
    assert src.split_size("trainB") == SIZES["trainB"]


def test_pipeline_end_to_end_over_tfds(fake_tfds):
    """The reference's whole data path: TFDS -> min-truncate -> augment ->
    cache -> zip -> static ragged batches."""
    cfg = Config(
        data=DataConfig(
            source="tfds", resize_size=36, crop_size=HW, cache_augmented=True
        ),
        train=TrainConfig(batch_size=3),
    )
    data = CycleGANData(cfg, global_batch_size=3)
    assert data.n_train == 4  # min(5, 4): main.py:30-31
    assert data.n_test == 2
    assert data.train_steps == 2  # ceil(4/3)
    batches = list(data.train_epoch(0, prefetch=False))
    assert len(batches) == 2
    x, y, w = batches[1]  # ragged final batch, zero-padded
    assert x.shape == (3, HW, HW, 3) and x.dtype == np.float32
    assert w.tolist() == [1.0, 0.0, 0.0]
    assert float(x.min()) >= -1.0 and float(x.max()) <= 1.0
    np.testing.assert_array_equal(x[1], 0.0)  # padded position masked
