"""steps_per_dispatch (fused lax.scan multi-step dispatch) == per-step
dispatch, including the epoch remainder path and per-step metrics.

The scan body is the same train_step, so K fused steps must reproduce the
per-step update sequence exactly — this is a dispatch-latency optimization
(parallel/dp.py shard_multi_train_step), not a semantics change.
"""

import dataclasses

import jax
import numpy as np

from cyclegan_tpu.parallel import (
    make_mesh_plan,
    shard_batch,
    shard_multi_train_step,
    shard_stacked_batch,
    shard_train_step,
)
from cyclegan_tpu.parallel.mesh import replicated
from cyclegan_tpu.train import create_state, make_train_step
from cyclegan_tpu.train import loop


def _batches(config, n_steps, global_batch):
    rng = np.random.RandomState(0)
    s = config.model.image_size
    out = []
    for _ in range(n_steps):
        x = rng.rand(global_batch, s, s, 3).astype(np.float32) * 2 - 1
        y = rng.rand(global_batch, s, s, 3).astype(np.float32) * 2 - 1
        out.append((x, y, np.ones((global_batch,), np.float32)))
    return out


def test_multi_step_equals_per_step(tiny_config, devices):
    plan = make_mesh_plan(devices=devices)  # 8-way data parallel
    gb = plan.n_data  # batch 1 per shard
    k = 3
    batches = _batches(tiny_config, k, gb)
    step = make_train_step(tiny_config, gb)

    state0 = create_state(tiny_config, jax.random.PRNGKey(0))
    state0 = jax.device_put(state0, replicated(plan))

    # Per-step dispatch.
    single = shard_train_step(plan, step)
    state_a = state0
    metrics_a = []
    for x, y, w in batches:
        state_a, m = single(state_a, *shard_batch(plan, x, y, w))
        metrics_a.append(jax.device_get(m))

    # One fused dispatch. (state0 was donated above — rebuild it.)
    state0 = create_state(tiny_config, jax.random.PRNGKey(0))
    state0 = jax.device_put(state0, replicated(plan))
    multi = shard_multi_train_step(plan, step, k)
    xs, ys, ws = shard_stacked_batch(
        plan,
        np.stack([b[0] for b in batches]),
        np.stack([b[1] for b in batches]),
        np.stack([b[2] for b in batches]),
    )
    state_b, stacked = multi(state0, xs, ys, ws)
    stacked = jax.device_get(stacked)

    for i, m in enumerate(metrics_a):
        for key in m:
            np.testing.assert_allclose(
                float(m[key]), float(stacked[key][i]), rtol=1e-5, atol=1e-6,
                err_msg=f"step {i} {key}",
            )
    for a, b in zip(jax.tree.leaves(state_a), jax.tree.leaves(state_b)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )


def test_train_epoch_with_remainder(tiny_config, devices):
    """5 batches at K=2: two fused dispatches + one per-step remainder —
    the full loop.train_epoch path, equal to the K=1 epoch."""

    class _FakeData:
        train_steps = 5

        def __init__(self, batches):
            self.batches = batches

        def train_epoch(self, epoch, prefetch=True, start_step=0):
            return iter(self.batches[start_step:])

    class _NullSummary:
        def scalar(self, *a, **kw):
            pass

    plan = make_mesh_plan(devices=devices)
    gb = plan.n_data
    cfg1 = tiny_config
    cfg2 = dataclasses.replace(
        tiny_config, train=dataclasses.replace(tiny_config.train, steps_per_dispatch=2)
    )
    data = _FakeData(_batches(cfg1, 5, gb))
    step = make_train_step(cfg1, gb)
    single = shard_train_step(plan, step)

    def run(cfg, multi):
        s = create_state(cfg, jax.random.PRNGKey(1))
        s = jax.device_put(s, replicated(plan))
        return loop.train_epoch(
            cfg, data, plan, single, s, _NullSummary(), 0, multi_step_fn=multi
        )

    state_1 = run(cfg1, None)
    state_2 = run(cfg2, shard_multi_train_step(plan, step, 2))
    for a, b in zip(jax.tree.leaves(state_1), jax.tree.leaves(state_2)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
        )
