"""Device-prefetch input staging (data/prefetch.py + loop._staged_batches):
order-exact, exception-transparent, and semantically invisible to training
(prefetch 0 == prefetch 2)."""

import dataclasses

import jax
import numpy as np
import pytest

from cyclegan_tpu.data.prefetch import prefetch_iter
from cyclegan_tpu.parallel import make_mesh_plan, shard_train_step
from cyclegan_tpu.parallel.mesh import replicated
from cyclegan_tpu.train import create_state, loop, make_train_step

from tests.test_multistep import _batches


def test_prefetch_preserves_order_and_values():
    assert list(prefetch_iter(iter(range(100)), depth=3)) == list(range(100))


def test_prefetch_depth_validated():
    with pytest.raises(ValueError, match="depth"):
        prefetch_iter(iter([]), depth=0)


def test_prefetch_propagates_source_exception():
    def boom():
        yield 1
        yield 2
        raise RuntimeError("source failed")

    it = prefetch_iter(boom(), depth=2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(RuntimeError, match="source failed"):
        next(it)


def test_prefetch_abandoned_consumer_stops_worker():
    import threading

    n_before = threading.active_count()
    it = prefetch_iter(iter(range(10_000)), depth=1)
    next(it)
    it.close()  # generator finally -> stop event
    # The worker must wind down (daemon threads would not block exit, but
    # a leak per abandoned epoch would still accumulate).
    for _ in range(50):
        if threading.active_count() <= n_before:
            break
        import time

        time.sleep(0.1)
    assert threading.active_count() <= n_before


def test_train_epoch_same_result_with_and_without_prefetch(
        tiny_config, devices):
    class _FakeData:
        train_steps = 4

        def __init__(self, batches):
            self.batches = batches

        def train_epoch(self, epoch, prefetch=True, start_step=0):
            return iter(self.batches[start_step:])

    class _NullSummary:
        def scalar(self, *a, **kw):
            pass

    plan = make_mesh_plan(devices=devices)
    gb = plan.n_data
    data = _FakeData(_batches(tiny_config, 4, gb))
    step = make_train_step(tiny_config, gb)
    single = shard_train_step(plan, step)

    def run(depth):
        cfg = dataclasses.replace(
            tiny_config,
            train=dataclasses.replace(
                tiny_config.train, prefetch_batches=depth
            ),
        )
        s = create_state(cfg, jax.random.PRNGKey(2))
        s = jax.device_put(s, replicated(plan))
        return loop.train_epoch(cfg, data, plan, single, s, _NullSummary(), 0)

    state_inline = run(0)
    state_prefetch = run(2)
    for a, b in zip(jax.tree.leaves(state_inline),
                    jax.tree.leaves(state_prefetch)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=0)  # bitwise: same dispatches


def test_train_epoch_accum_path_with_prefetch(tiny_config, devices):
    """grad_accum > 1 routed through the prefetch worker ("accum" staged
    kind) matches inline staging bitwise — closes the accum x prefetch
    interplay gap (the equivalence test above only covers "single")."""
    from cyclegan_tpu.parallel.dp import shard_accum_train_step
    from cyclegan_tpu.train import make_accum_train_step

    class _FakeData:
        train_steps = 3

        def __init__(self, batches):
            self.batches = batches

        def train_epoch(self, epoch, prefetch=True, start_step=0):
            return iter(self.batches[start_step:])

    class _NullSummary:
        def scalar(self, *a, **kw):
            pass

    plan = make_mesh_plan(devices=devices)
    accum, micro = 2, plan.n_data
    gb = accum * micro  # pipeline yields EFFECTIVE batches under accum
    data = _FakeData(_batches(tiny_config, 3, gb))

    def run(depth):
        cfg = dataclasses.replace(
            tiny_config,
            train=dataclasses.replace(
                tiny_config.train, grad_accum=accum, prefetch_batches=depth
            ),
        )
        step = shard_accum_train_step(
            plan, make_accum_train_step(cfg, gb, accum)
        )
        s = create_state(cfg, jax.random.PRNGKey(3))
        s = jax.device_put(s, replicated(plan))
        return loop.train_epoch(cfg, data, plan, step, s, _NullSummary(), 0)

    for a, b in zip(jax.tree.leaves(run(0)), jax.tree.leaves(run(2))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=0)
