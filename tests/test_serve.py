"""Serving engine (cyclegan_tpu/serve): bucket grammar, micro-batcher
edge cases, ragged-tail padding, bf16 numerics, pipelined executor
telemetry, and the HTTP front-end.

All tier-1: tiny generator (filters=4, 1 residual block) at 16/32 px on
the virtual CPU mesh, so every AOT program compiles in seconds and
caches across runs (conftest compile cache).
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

from cyclegan_tpu.config import GeneratorConfig, ModelConfig  # noqa: E402
from cyclegan_tpu.serve.batcher import MicroBatcher, Request  # noqa: E402
from cyclegan_tpu.serve.engine import (  # noqa: E402
    InferenceEngine,
    ServeConfig,
    build_generator,
    preprocess_request,
)
from cyclegan_tpu.serve.executor import PipelinedExecutor  # noqa: E402


def _tiny_model_cfg(dtype="float32"):
    return ModelConfig(
        generator=GeneratorConfig(filters=4, num_residual_blocks=1),
        image_size=32,
        compute_dtype=dtype,
    )


@pytest.fixture(scope="module")
def tiny_params():
    import jax
    import jax.numpy as jnp

    gen = build_generator(_tiny_model_cfg())
    dummy = jnp.zeros((1, 32, 32, 3), jnp.float32)
    return gen.init(jax.random.PRNGKey(0), dummy)


@pytest.fixture(scope="module")
def engine(tiny_params):
    """f32 engine over the full bucket grammar exercised below:
    batch buckets {1, 4}, resolution buckets {16, 32}."""
    return InferenceEngine(
        _tiny_model_cfg(), tiny_params,
        serve_cfg=ServeConfig(batch_buckets=(1, 4), sizes=(16, 32),
                              dtype="float32"))


def _images(n, size=32, seed=0):
    rng = np.random.RandomState(seed)
    return rng.uniform(-1, 1, (n, size, size, 3)).astype(np.float32)


# -- config validation ----------------------------------------------------

def test_serve_config_validation():
    with pytest.raises(ValueError):
        ServeConfig(dtype="float16")
    with pytest.raises(ValueError):
        ServeConfig(batch_buckets=())
    with pytest.raises(ValueError):
        ServeConfig(sizes=(0,))
    with pytest.raises(ValueError):
        ServeConfig(batch_buckets=(1, -4))


def test_with_cycle_requires_bwd_params(tiny_params):
    with pytest.raises(ValueError, match="bwd_params"):
        InferenceEngine(_tiny_model_cfg(), tiny_params, bwd_params=None,
                        serve_cfg=ServeConfig(with_cycle=True))


# -- micro-batcher edge cases ---------------------------------------------

def _resolving_flush(record, fail=None):
    def flush(batch, trigger):
        if fail is not None and fail[0]:
            raise RuntimeError("poisoned flush")
        record.append((len(batch), trigger))
        for r in batch:
            r.future.set_result(len(batch))
    return flush


def test_batcher_flushes_full_buckets():
    record = []
    b = MicroBatcher(_resolving_flush(record), max_batch=4, max_wait_s=5.0)
    futs = [b.submit(Request(i, 32)) for i in range(8)]
    assert all(f.result(timeout=30) == 4 for f in futs)
    b.close()
    assert record == [(4, "full"), (4, "full")]
    assert b.n_requests == 8 and b.n_flushes == 2


def test_batcher_deadline_flush_with_slow_producer():
    """A partial bucket must flush at the max-wait deadline — a lone
    request never waits for companions that are not coming."""
    record = []
    b = MicroBatcher(_resolving_flush(record), max_batch=8, max_wait_s=0.05)
    t0 = time.perf_counter()
    futs = [b.submit(Request(i, 32)) for i in range(2)]
    assert all(f.result(timeout=30) == 2 for f in futs)
    waited = time.perf_counter() - t0
    b.close()
    assert record == [(2, "deadline")]
    # Deadline anchors at the FIRST request's submit time.
    assert 0.05 <= waited < 5.0


def test_batcher_drains_residue_on_close():
    record = []
    b = MicroBatcher(_resolving_flush(record), max_batch=8, max_wait_s=60.0)
    futs = [b.submit(Request(i, 32)) for i in range(3)]
    b.close()
    assert record == [(3, "drain")]
    assert all(f.result(timeout=5) == 3 for f in futs)


def test_batcher_flush_exception_fails_futures_not_engine():
    """flush_fn raising fails THAT flush's futures; the worker keeps
    serving later submissions."""
    record, fail = [], [True]
    b = MicroBatcher(_resolving_flush(record, fail),
                     max_batch=2, max_wait_s=0.02)
    bad = [b.submit(Request(i, 32)) for i in range(2)]
    for f in bad:
        with pytest.raises(RuntimeError, match="poisoned"):
            f.result(timeout=30)
    fail[0] = False
    good = b.submit(Request(9, 32))
    assert good.result(timeout=30) == 1
    b.close()
    assert record == [(1, "deadline")]


def test_batcher_max_queue_watermark():
    release = threading.Event()

    def slow_flush(batch, trigger):
        release.wait(timeout=30)
        for r in batch:
            r.future.set_result(None)

    b = MicroBatcher(slow_flush, max_batch=1, max_wait_s=0.0, max_queue=64)
    futs = [b.submit(Request(i, 32)) for i in range(5)]
    assert b.max_depth >= 1
    release.set()
    for f in futs:
        f.result(timeout=30)
    b.close()


# -- bucket grammar -------------------------------------------------------

def test_exactly_one_program_per_bucket(engine):
    assert set(engine.programs) == {(16, 1), (16, 4), (32, 1), (32, 4)}
    assert engine.max_batch == 4


def test_batch_bucket_boundaries(engine):
    assert engine.batch_bucket(1) == 1
    assert engine.batch_bucket(2) == 4
    assert engine.batch_bucket(4) == 4
    assert engine.batch_bucket(5) is None  # caller must split


def test_size_bucket_boundaries(engine):
    assert engine.size_bucket(8, 8) == 16
    assert engine.size_bucket(16, 16) == 16
    assert engine.size_bucket(17, 4) == 32
    assert engine.size_bucket(32, 32) == 32
    # Oversized requests clamp to the largest bucket (resized down).
    assert engine.size_bucket(100, 40) == 32


def test_run_rejects_off_grammar_flushes(engine):
    with pytest.raises(ValueError, match="exceeds the largest"):
        engine.run(_images(5))
    with pytest.raises(ValueError, match="size bucket"):
        engine.run(_images(2, size=32), size=16)
    with pytest.raises(KeyError):
        engine.run(_images(2, size=24))  # 24 is not a resolution bucket


# -- numerics -------------------------------------------------------------

def test_ragged_tail_padding_matches_direct_apply(engine, tiny_params):
    """A ragged flush of 3 into the 4-bucket must produce the same first
    3 rows as applying the generator to those 3 images directly — the
    zero rows are dead weight, never numerics."""
    x = _images(3)
    outs, n = engine.run(x, size=32)
    assert n == 3
    fake = np.asarray(outs[0])
    assert fake.shape == (4, 32, 32, 3) and fake.dtype == np.float32
    gen = build_generator(_tiny_model_cfg())
    ref = np.asarray(gen.apply(tiny_params, x))
    np.testing.assert_allclose(fake[:3], ref, atol=1e-5, rtol=1e-5)


def test_bf16_serving_pinned_against_f32(engine, tiny_params):
    """The bf16 path reuses the SAME f32 params (compute-dtype casting);
    its float32 outputs must track the f32 program within bf16 noise.
    tanh-bounded outputs in [-1, 1] make an absolute tolerance the right
    pin."""
    bf16 = InferenceEngine(
        _tiny_model_cfg(), tiny_params,
        serve_cfg=ServeConfig(batch_buckets=(4,), sizes=(32,),
                              dtype="bfloat16"))
    x = _images(4, seed=3)
    ref = np.asarray(engine.run(x, size=32)[0][0])
    got = np.asarray(bf16.run(x, size=32)[0][0])
    assert got.dtype == np.float32  # cast back inside the program
    assert float(np.max(np.abs(got - ref))) < 0.1
    assert float(np.mean(np.abs(got - ref))) < 0.02


def test_fused_cycle_program(engine, tiny_params):
    """with_cycle=True fuses both generator passes into ONE program; its
    fake output must match the single-pass program and its cycled output
    must be the cycle generator applied to that fake."""
    import jax

    gen = build_generator(_tiny_model_cfg())
    bwd = gen.init(jax.random.PRNGKey(7),
                   np.zeros((1, 32, 32, 3), np.float32))
    cyc = InferenceEngine(
        _tiny_model_cfg(), tiny_params, bwd_params=bwd,
        serve_cfg=ServeConfig(batch_buckets=(4,), sizes=(32,),
                              dtype="float32", with_cycle=True))
    x = _images(4, seed=5)
    outs, n = cyc.run(x, size=32)
    assert len(outs) == 2 and n == 4
    fake, cycled = np.asarray(outs[0]), np.asarray(outs[1])
    ref_fake = np.asarray(engine.run(x, size=32)[0][0])
    np.testing.assert_allclose(fake, ref_fake, atol=1e-5, rtol=1e-5)
    ref_cycled = np.asarray(gen.apply(bwd, fake))
    np.testing.assert_allclose(cycled, ref_cycled, atol=1e-5, rtol=1e-5)


# -- pipelined executor ---------------------------------------------------

def test_executor_end_to_end_with_telemetry(engine, tmp_path):
    """Raw uploads of assorted sizes route to their resolution buckets,
    every future resolves, and the run leaves a foldable obs stream
    (serve_flush + serve_summary on the PR-1 schema)."""
    from obs_report import fold, load_events, render

    from cyclegan_tpu.obs import MetricsLogger

    stream = tmp_path / "serve.jsonl"
    logger = MetricsLogger(str(stream))
    ex = PipelinedExecutor(engine, max_wait_ms=20.0, logger=logger)
    rng = np.random.RandomState(0)
    shapes = [(40, 40), (16, 12), (33, 20), (8, 8), (32, 32)] * 2
    futs = [ex.submit_raw(rng.randint(0, 255, s + (3,), np.uint8))
            for s in shapes]
    results = [f.result(timeout=120) for f in futs]
    for s, res in zip(shapes, results):
        expect = engine.size_bucket(*s)
        assert res["fake"].shape == (expect, expect, 3)
        assert "cycled" not in res  # single-pass engine: no cycle output
    summary = ex.close()
    logger.close()
    assert summary["n_images"] == len(shapes)
    assert summary["n_flushes"] >= 2  # at least one flush per size bucket
    assert summary["images_per_sec"] > 0
    assert summary["latency_p95_s"] >= summary["latency_p50_s"]

    events, skipped = load_events(str(stream))
    assert skipped == 0
    report = fold(events)
    assert len(report["serve_flushes"]) == summary["n_flushes"]
    assert report["serve_summary"]["n_images"] == len(shapes)
    roll = report["serve_rollup"]
    assert roll["n_images"] == len(shapes)
    assert set(roll["triggers"]) <= {"full", "deadline", "drain"}
    text = render(report)
    assert "serving:" in text and "serve summary:" in text


def test_executor_public_stats_snapshot(engine):
    """stats() is the executor's PUBLIC snapshot — the HTTP /stats
    handler consumes exactly this, never `executor._batchers`. It must
    surface the batcher high-water mark and per-bucket depths."""
    ex = PipelinedExecutor(engine, max_wait_ms=5.0)
    futs = [ex.submit(_images(1)[0]) for _ in range(3)]
    for f in futs:
        f.result(timeout=120)
    snap = ex.stats()
    assert set(snap) >= {"queue_depths", "max_queue_depth", "n_flushes",
                         "n_queued_requests", "n_images_done", "tiers"}
    assert snap["n_queued_requests"] == 3
    assert snap["n_images_done"] == 3
    assert snap["max_queue_depth"] >= 1
    assert "32/base" in snap["queue_depths"]
    assert snap["tiers"] == ["base"]
    ex.close()


def test_executor_rejects_unbucketed_max_batch(engine):
    with pytest.raises(ValueError, match="exceeds"):
        PipelinedExecutor(engine, max_batch=16)


def test_executor_closed_rejects_submissions(engine):
    ex = PipelinedExecutor(engine, max_wait_ms=1.0)
    ex.close()
    with pytest.raises(RuntimeError, match="closed"):
        ex.submit(_images(1)[0])
    assert ex.close() == {}  # idempotent


# -- HTTP front-end -------------------------------------------------------

def test_http_server_round_trip(engine):
    import io
    import urllib.request

    from cyclegan_tpu.serve.server import make_server

    ex = PipelinedExecutor(engine, max_wait_ms=5.0)
    server, app = make_server(ex, port=0)
    host, port = server.server_address[:2]
    th = threading.Thread(target=server.serve_forever, daemon=True)
    th.start()
    try:
        base = f"http://{host}:{port}"
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
            assert r.status == 200

        buf = io.BytesIO()
        np.save(buf, np.random.RandomState(0)
                .randint(0, 255, (20, 28, 3), np.uint8))
        req = urllib.request.Request(
            f"{base}/translate", data=buf.getvalue(), method="POST")
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "image/png"
            body = r.read()
        assert body[:8] == b"\x89PNG\r\n\x1a\n"

        with urllib.request.urlopen(f"{base}/stats", timeout=30) as r:
            stats = json.loads(r.read())
        assert stats["n_requests"] == 1 and stats["n_errors"] == 0

        # A garbage upload 500s without killing the server.
        req = urllib.request.Request(
            f"{base}/translate", data=b"not an image", method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 500
        with urllib.request.urlopen(f"{base}/healthz", timeout=30) as r:
            assert r.status == 200
    finally:
        server.shutdown()
        ex.close()


# -- hot-path no-sync coverage --------------------------------------------

def test_no_sync_check_covers_serve_directory():
    from check_no_sync import hot_path_entries, run_check

    entries = dict(hot_path_entries())
    for mod in ("engine", "batcher", "executor", "server", "__init__"):
        assert entries.get(f"cyclegan_tpu/serve/{mod}.py") is True
    assert run_check() == []


# -- bench_serve contract -------------------------------------------------

def test_bench_serve_emits_one_json_line(capsys):
    import bench_serve

    bench_serve._emit({"metric": "cyclegan_serve_images_per_sec_1chip",
                       "value": 1.0, "unit": "images/sec"})
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    d = json.loads(out[0])
    assert d["metric"] == "cyclegan_serve_images_per_sec_1chip"


def test_bench_serve_percentile_empty_is_finite():
    import bench_serve

    assert bench_serve._percentile([], 0.95) == 0.0
    assert bench_serve._percentile([1.0, 2.0, 3.0], 0.5) == 2.0


@pytest.mark.slow
def test_bench_serve_cpu_end_to_end(tmp_path):
    """Full bench_serve.py subprocess on the CPU toy geometry: exactly
    one JSON line, speedup + latency fields present, obs stream foldable."""
    import subprocess

    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               BENCH_SERVE_TIME_BUDGET_S="240",
               BENCH_OBS_JSONL=str(tmp_path / "bench_serve.jsonl"))
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench_serve.py"),
         "--n", "8", "--skip_sweep"],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln]
    assert len(lines) == 1, proc.stdout
    d = json.loads(lines[0])
    assert d["metric"] == "cyclegan_serve_images_per_sec_1chip"
    assert d["value"] > 0 and d["serial_images_per_sec"] > 0
    assert "speedup_vs_serial" in d and "latency_saturated_ms" in d
    assert d["platform"] == "cpu" and "note" in d
