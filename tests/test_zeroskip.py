"""GANAX zero-skip transposed-conv engine (ops/upsample.py,
ops/pallas/upsample_kernel.py) vs the dense nn.ConvTranspose lowering —
forward and backward parity, odd/ragged shapes, the VMEM eligibility
boundary with its XLA fallback, checkpoint interchange across the three
Upsample tiers, and the fused discriminator tail.

The decomposition's claim is exactness: the four phase convolutions
compute the SAME sums as the lhs-dilated conv minus the multiplies
against inserted zeros, so f32 parity is gated at 1e-5 (channel
reduction order is the only legal difference) and bf16 at 1e-2.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu.config import (
    DiscriminatorConfig,
    GeneratorConfig,
    ModelConfig,
)
from cyclegan_tpu.models import PatchGANDiscriminator, ResNetGenerator
from cyclegan_tpu.ops.norm import instance_norm, instance_norm_relu_pad
from cyclegan_tpu.ops.pallas import vmem
from cyclegan_tpu.ops.pallas.upsample_kernel import (
    upsample_eligible,
    upsample_norm_relu_pad_pallas,
)
from cyclegan_tpu.ops.upsample import (
    conv_transpose_up2,
    conv_transpose_up2_dense,
    conv_transpose_zeroskip,
    upsample_norm_relu_pad,
)


def _rand(shape, seed=0, dtype=jnp.float32):
    k = jax.random.PRNGKey(seed)
    return (jax.random.normal(k, shape) * 2 + 0.5).astype(dtype)


# Shapes chosen to break a decomposition that only works on friendly
# tiles: batch > 1, non-square H != W (axis mix-ups in the interleave),
# odd extents (the SAME/s2 output is (2H, 2W) regardless of parity),
# H or W of 1 (every tap hits the zero boundary), and Cin != Cout.
SHAPES = [
    ((2, 8, 8, 16), 8),
    ((1, 16, 16, 4), 8),
    ((1, 5, 9, 3), 6),
    ((2, 7, 4, 5), 3),
    ((1, 1, 6, 2), 4),
    ((1, 3, 1, 2), 2),
]


@pytest.mark.parametrize("shape,cout", SHAPES)
def test_zeroskip_forward_matches_dense(shape, cout):
    x = _rand(shape)
    kernel = _rand((3, 3, shape[-1], cout), 1)
    got = conv_transpose_zeroskip(x, kernel)
    want = conv_transpose_up2_dense(x, kernel)
    assert got.shape == want.shape == (
        shape[0], 2 * shape[1], 2 * shape[2], cout
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("shape,cout", SHAPES)
def test_zeroskip_backward_matches_dense(shape, cout):
    x = _rand(shape)
    kernel = _rand((3, 3, shape[-1], cout), 1)

    def loss(fn):
        return lambda x, k: jnp.sum(jnp.sin(fn(x, k)) * fn(x, k))

    g_z = jax.grad(loss(conv_transpose_zeroskip), argnums=(0, 1))(x, kernel)
    g_d = jax.grad(loss(conv_transpose_up2_dense), argnums=(0, 1))(x, kernel)
    # sin(y)*y amplifies the reduction-order noise, and near-cancelling
    # gradient elements can land ~2e-4 off in absolute terms; the
    # element-wise distributions otherwise agree to 1e-5 like the
    # forward.
    for a, b, name in zip(g_z, g_d, ["dx", "dkernel"]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4, err_msg=name
        )


def test_zeroskip_bfloat16_parity():
    x = _rand((2, 8, 8, 8), dtype=jnp.bfloat16)
    kernel = _rand((3, 3, 8, 16), 1, dtype=jnp.bfloat16)
    got = conv_transpose_zeroskip(x, kernel)
    want = conv_transpose_up2_dense(x, kernel)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=1e-2, atol=1e-2,
    )


def test_dispatch_impl_selects_engine():
    x = _rand((1, 6, 6, 4))
    kernel = _rand((3, 3, 4, 8), 1)
    for impl in ("dense", "zeroskip"):
        got = conv_transpose_up2(x, kernel, impl=impl)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(conv_transpose_up2_dense(x, kernel)),
            rtol=1e-5, atol=1e-5,
        )


# ------------------------------------------------- fused Pallas kernel


def _fused_reference(x, kernel, scale, bias, pad, eps=1e-3):
    """The unfused composition the kernel must match: dense transposed
    conv -> IN -> ReLU (-> reflect-pad)."""
    from cyclegan_tpu.ops.padding import reflect_pad

    y = conv_transpose_up2_dense(x, kernel)
    y = jax.nn.relu(instance_norm(y, scale, bias, eps=eps, impl="xla"))
    return reflect_pad(y, pad) if pad else y


FUSED_SHAPES = [
    ((2, 8, 8, 16), 8, 0),
    ((1, 6, 10, 4), 8, 0),
    ((1, 8, 8, 8), 16, 3),   # the pad_impl="epilogue" last-upsample form
    ((2, 5, 7, 3), 4, 1),
]


@pytest.mark.parametrize("shape,cout,pad", FUSED_SHAPES)
def test_fused_forward_matches_reference(shape, cout, pad):
    x = _rand(shape)
    kernel = _rand((3, 3, shape[-1], cout), 1)
    scale = _rand((cout,), 2)
    bias = _rand((cout,), 3)
    got = upsample_norm_relu_pad_pallas(
        x, kernel, scale, bias, pad=pad, interpret=True
    )
    want = _fused_reference(x, kernel, scale, bias, pad)
    assert got.shape == want.shape
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("shape,cout,pad", FUSED_SHAPES)
def test_fused_backward_matches_reference(shape, cout, pad):
    x = _rand(shape)
    kernel = _rand((3, 3, shape[-1], cout), 1)
    scale = _rand((cout,), 2)
    bias = _rand((cout,), 3)

    def loss(fn):
        def inner(x, k, s, b):
            y = fn(x, k, s, b)
            return jnp.sum(jnp.sin(y) * y)
        return inner

    g_p = jax.grad(
        loss(lambda x, k, s, b: upsample_norm_relu_pad_pallas(
            x, k, s, b, pad=pad, interpret=True)),
        argnums=(0, 1, 2, 3),
    )(x, kernel, scale, bias)
    g_r = jax.grad(
        loss(lambda x, k, s, b: _fused_reference(x, k, s, b, pad)),
        argnums=(0, 1, 2, 3),
    )(x, kernel, scale, bias)
    for a, b, name in zip(g_p, g_r, ["dx", "dkernel", "dscale", "dbias"]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=5e-5, err_msg=name
        )


def test_fused_bfloat16_forward():
    x = _rand((1, 8, 8, 8), dtype=jnp.bfloat16)
    kernel = _rand((3, 3, 8, 16), 1, dtype=jnp.bfloat16)
    scale = _rand((16,), 2)
    bias = _rand((16,), 3)
    got = upsample_norm_relu_pad_pallas(
        x, kernel, scale, bias, pad=0, interpret=True
    )
    assert got.dtype == jnp.bfloat16
    want = _fused_reference(x, kernel, scale, bias, 0)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=1e-2, atol=1e-2,
    )


# --------------------------------------------------- eligibility gate


def test_upsample_eligibility_is_dtype_aware():
    # the generator's FIRST upsample at 256^2 (64^2 input, 256ch):
    # eligible under bf16, past the budget under f32
    assert upsample_eligible((1, 64, 64, 256), jnp.bfloat16, 0)
    assert not upsample_eligible((1, 64, 64, 256), jnp.float32, 0)
    # the SECOND upsample (128^2 input, 128ch): ineligible either way —
    # the XLA zeroskip fallback covers it by construction
    assert not upsample_eligible((1, 128, 128, 128), jnp.bfloat16, 0)
    assert not upsample_eligible((1, 128, 128, 128), jnp.float32, 0)
    # reflect constraint applies to the DOUBLED output resolution
    assert upsample_eligible((1, 2, 8, 4), jnp.float32, 3)   # 4 > pad
    assert not upsample_eligible((1, 1, 8, 4), jnp.float32, 3)  # 2 <= pad
    assert not upsample_eligible((1, 64, 64), jnp.float32, 0)  # not 4-D


def test_upsample_vmem_accounting():
    h = w = 8
    c_in = 4
    got = vmem.upsample_bytes(h, w, c_in, 1, 4)
    want = (
        (h + 1) * (w + 1) * c_in          # zero-extended input slab
        + 9 * c_in * vmem.C_BLK           # kernel block
        + 4 * h * w * vmem.C_BLK          # four phase results
        + (2 * h + 2) * (2 * w + 2) * vmem.C_BLK  # padded output
    ) * 4
    assert got == want
    # the budget boundary really is the budget
    assert vmem.upsample_fits(64, 64, 256, 0, 2)
    assert not vmem.upsample_fits(64, 64, 256, 0, 4)


def test_fused_ineligible_shape_raises():
    x = _rand((1, 128, 128, 8))
    with pytest.raises(NotImplementedError):
        upsample_norm_relu_pad_pallas(
            x, _rand((3, 3, 8, 8), 1), jnp.ones(8), jnp.zeros(8),
            interpret=True,
        )


def test_dispatch_falls_back_across_the_boundary():
    """upsample_norm_relu_pad(impl='zeroskip_fused') must serve BOTH
    dispatch arms with the same math: one VMEM-eligible shape (Pallas
    interpret path off-TPU) and one past the budget (XLA composition)."""
    for shape, cout in [((1, 8, 8, 8), 8), ((1, 128, 128, 8), 8)]:
        x = _rand(shape)
        kernel = _rand((3, 3, shape[-1], cout), 1)
        scale = _rand((cout,), 2)
        bias = _rand((cout,), 3)
        got = upsample_norm_relu_pad(
            x, kernel, scale, bias, pad=0, impl="zeroskip_fused"
        )
        want = _fused_reference(x, kernel, scale, bias, 0)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )


# ------------------------------------- model tiers share one param tree

SMALL_GEN = GeneratorConfig(
    filters=8, num_downsampling_blocks=2, num_residual_blocks=1
)


def _gen(upsample_impl, **kw):
    return ResNetGenerator(
        config=SMALL_GEN, upsample_impl=upsample_impl, **kw
    )


def test_upsample_tiers_share_param_tree_and_outputs():
    """The acceptance claim behind checkpoint interchange: init under
    any tier, apply under any other — identical tree structure AND
    shapes, near-identical outputs."""
    x = _rand((1, 32, 32, 3))
    params = _gen("dense").init(jax.random.PRNGKey(0), x)
    ref = _gen("dense").apply(params, x)
    for impl in ("zeroskip", "zeroskip_fused"):
        p2 = jax.eval_shape(_gen(impl).init, jax.random.PRNGKey(0), x)
        assert jax.tree_util.tree_structure(p2) \
            == jax.tree_util.tree_structure(params)
        assert jax.tree_util.tree_map(lambda l: l.shape, p2) \
            == jax.tree_util.tree_map(lambda l: l.shape, params)
        out = _gen(impl).apply(params, x)  # dense-initialized checkpoint
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4,
            err_msg=impl,
        )


def test_upsample_tiers_interchange_under_epilogue_pad():
    """pad_impl='epilogue' routes the LAST upsample through the fused
    tail (pad_after=3); the engines must still agree there."""
    x = _rand((1, 32, 32, 3))
    kw = dict(pad_mode="reflect", pad_impl="epilogue")
    params = _gen("dense", **kw).init(jax.random.PRNGKey(0), x)
    ref = _gen("dense", **kw).apply(params, x)
    for impl in ("zeroskip", "zeroskip_fused"):
        out = _gen(impl, **kw).apply(params, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4,
            err_msg=impl,
        )


def test_generator_rejects_unknown_upsample_impl():
    x = jnp.zeros((1, 32, 32, 3))
    with pytest.raises(ValueError, match="upsample_impl"):
        _gen("bogus").init(jax.random.PRNGKey(0), x)


# ------------------------------------------ fused discriminator tails


def test_discriminator_fused_tail_matches_plain():
    """pad_impl='epilogue' collapses each trunk block's IN ->
    LeakyReLU(0.2) into instance_norm_act_pad; same params, same
    logits."""
    cfg = DiscriminatorConfig(filters=8)
    x = _rand((1, 64, 64, 3))
    plain = PatchGANDiscriminator(config=cfg, pad_impl="pad")
    fused = PatchGANDiscriminator(config=cfg, pad_impl="epilogue")
    params = plain.init(jax.random.PRNGKey(0), x)
    assert jax.tree_util.tree_structure(
        jax.eval_shape(fused.init, jax.random.PRNGKey(0), x)
    ) == jax.tree_util.tree_structure(params)
    np.testing.assert_allclose(
        np.asarray(fused.apply(params, x)),
        np.asarray(plain.apply(params, x)),
        rtol=1e-5, atol=1e-5,
    )


# ------------------------------------------------- config validation


def test_config_rejects_unknown_upsample_impl():
    with pytest.raises(ValueError, match="upsample_impl"):
        ModelConfig(upsample_impl="bogus")


def test_config_rejects_fused_upsample_with_xla_norm():
    with pytest.raises(ValueError, match="zeroskip_fused"):
        ModelConfig(upsample_impl="zeroskip_fused", instance_norm_impl="xla")


def test_config_accepts_all_tiers():
    for impl in ("dense", "zeroskip", "zeroskip_fused"):
        cfg = ModelConfig(upsample_impl=impl)
        assert cfg.upsample_impl == impl
        assert dataclasses.replace(cfg, upsample_impl="dense")
