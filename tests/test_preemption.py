"""Direct unit tests for utils/preemption.PreemptionGuard.

The guard has been load-bearing since PR 2 (SIGTERM -> finish epoch ->
checkpoint -> clean exit) and since this round it is also a fault-drill
target (``--inject sigterm@step=K``), but it only had indirect coverage
through the loop tests. These pin its contract directly: handler
install/uninstall hygiene, callback ordering and isolation, and the
cross-host stop agreement."""

import os
import signal
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cyclegan_tpu.utils import distributed  # noqa: E402
from cyclegan_tpu.utils.preemption import PreemptionGuard  # noqa: E402


def test_signal_sets_flag_and_runs_callbacks_in_order():
    order = []
    guard = PreemptionGuard(
        signals=(signal.SIGUSR1,),
        on_signal=(lambda: order.append("first"),
                   lambda: order.append("second")))
    try:
        assert not guard.requested_locally
        os.kill(os.getpid(), signal.SIGUSR1)
        assert guard.requested_locally
        # Callbacks ran inside the handler, in registration order —
        # the flush hooks must see the stop flag already set.
        assert order == ["first", "second"]
    finally:
        guard.uninstall()


def test_add_callback_after_install_still_fires():
    seen = []
    guard = PreemptionGuard(signals=(signal.SIGUSR1,))
    try:
        guard.add_callback(lambda: seen.append("late"))
        os.kill(os.getpid(), signal.SIGUSR1)
        assert seen == ["late"]
    finally:
        guard.uninstall()


def test_broken_callback_does_not_break_shutdown_or_later_callbacks():
    seen = []

    def broken():
        raise RuntimeError("flush hook bug")

    guard = PreemptionGuard(
        signals=(signal.SIGUSR1,),
        on_signal=(broken, lambda: seen.append("after-broken")))
    try:
        os.kill(os.getpid(), signal.SIGUSR1)
        assert guard.requested_locally       # the flag landed anyway
        assert seen == ["after-broken"]      # later callbacks still ran
    finally:
        guard.uninstall()


def test_uninstall_restores_previous_handler():
    hits = []

    def prev_handler(signum, frame):
        hits.append(signum)

    original = signal.signal(signal.SIGUSR1, prev_handler)
    try:
        guard = PreemptionGuard(signals=(signal.SIGUSR1,))
        assert signal.getsignal(signal.SIGUSR1) == guard._handle
        guard.uninstall()
        assert signal.getsignal(signal.SIGUSR1) is prev_handler
        # The restored handler actually receives the signal again.
        os.kill(os.getpid(), signal.SIGUSR1)
        assert hits == [signal.SIGUSR1]
        assert not guard.requested_locally
        # Idempotent: a second uninstall must not touch handlers.
        guard.uninstall()
        assert signal.getsignal(signal.SIGUSR1) is prev_handler
    finally:
        signal.signal(signal.SIGUSR1, original)


def test_install_false_traps_nothing():
    guard = PreemptionGuard(signals=(signal.SIGUSR1,), install=False)
    assert guard._prev == {}
    guard.request_stop()
    assert guard.requested_locally
    guard.uninstall()  # no-op, must not raise


def test_should_stop_agrees_across_hosts(monkeypatch):
    """The epoch-boundary check all-reduces the local flag: every
    process must come out with the same answer even when the SIGTERM
    landed on only one host. sync_flag is monkeypatched to play the
    'other hosts' so the test runs single-process."""
    calls = []

    def fake_sync(flag):
        calls.append(flag)
        # Round 1: no host signalled. Round 2: SOME OTHER host was
        # signalled, so the reduction is True even though ours is False.
        return bool(flag) or len(calls) >= 2

    monkeypatch.setattr(distributed, "sync_flag", fake_sync)
    guard = PreemptionGuard(install=False)
    assert guard.should_stop() is False      # nobody signalled
    assert guard.should_stop() is True       # another host was
    assert calls == [False, False]

    guard.request_stop()
    assert guard.should_stop() is True       # our own flag propagates
    assert calls[-1] is True
