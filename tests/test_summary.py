"""Observability-layer unit tests: Summary writers, scalar accumulation,
and cycle-panel plotting (reference cyclegan/utils.py:14-145).

test_e2e covers these through the CLI; here each behavior is pinned
directly — tag layout, the split train/test writer directories
(utils.py:21-24), the (x+1)*127.5 uint8 rescale (utils.py:127-131), and
the X_cycle/Y_cycle panel families (utils.py:133-144).
"""

from __future__ import annotations

import os

import numpy as np

from cyclegan_tpu.utils.dicts import append_dict, mean_dict
from cyclegan_tpu.utils.plotting import plot_cycle, to_uint8
from cyclegan_tpu.utils.summary import Summary


def _event_files(d):
    return [f for f in os.listdir(d) if f.startswith("events")]


def test_summary_split_writers(tmp_path):
    """Train events land in output_dir, test events in output_dir/test
    (reference utils.py:21-24) so TensorBoard overlays them."""
    s = Summary(str(tmp_path))
    s.scalar("loss_G/total", 1.5, step=0, training=True)
    s.scalar("loss_G/total", 1.2, step=0, training=False)
    s.image("panel", np.zeros((8, 8, 3), np.uint8), step=0)
    s.close()
    assert _event_files(tmp_path)
    assert _event_files(tmp_path / "test")


def test_summary_figure_renders(tmp_path):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(2, 2))
    ax.plot([0, 1], [1, 0])
    s = Summary(str(tmp_path))
    s.figure("fig", fig, step=3)
    s.close()
    assert _event_files(tmp_path)
    assert not plt.fignum_exists(fig.number)  # closed by default


def test_append_and_mean_dict():
    """append_dict accumulates per key (reference utils.py:101-109);
    mean_dict reduces to the epoch mean (main.py:340-341)."""
    acc = {}
    append_dict(acc, {"a": 1.0, "b": 2.0})
    append_dict(acc, {"a": 3.0, "b": 4.0})
    means = mean_dict(acc)
    assert means == {"a": 2.0, "b": 3.0}


def test_to_uint8_rescale():
    """(x + 1) * 127.5 with clipping (reference utils.py:127-131)."""
    x = np.array([-1.0, 0.0, 1.0, 1.5, -2.0], np.float32)
    out = to_uint8(x)
    assert out.dtype == np.uint8
    assert list(out) == [0, 127, 255, 255, 0]


def test_plot_cycle_emits_both_panel_families(tmp_path):
    """plot_cycle runs the inference cycle over the plot pairs and emits
    X_cycle = [X, G(X), F(G(X))] and Y_cycle = [Y, F(Y), G(F(Y))]
    (reference utils.py:133-144), one 1x3 panel per sample."""
    calls = []

    class SpySummary(Summary):
        def __init__(self):
            self._writers = []

        def image_cycle(self, tag, images, titles=None, step=0, training=False):
            calls.append((tag, images.shape, tuple(titles), step))

    def cycle_fn(state, x, y):
        # Deterministic stand-in for the jitted generators.
        return -y, -x, x * 0.5, y * 0.5

    pairs = [
        (np.full((1, 4, 4, 3), -0.5, np.float32), np.full((1, 4, 4, 3), 0.5, np.float32))
        for _ in range(2)
    ]
    plot_cycle(pairs, cycle_fn, state=None, summary=SpySummary(), epoch=7)

    assert [c[0] for c in calls] == ["X_cycle", "Y_cycle"]
    for tag, shape, titles, step in calls:
        assert shape == (2, 3, 4, 4, 3)  # [n_pairs, 3 panels, H, W, C]
        assert step == 7
    assert calls[0][2] == ("X", "G(X)", "F(G(X))")
    assert calls[1][2] == ("Y", "F(Y)", "G(F(Y))")
