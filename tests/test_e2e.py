"""End-to-end slice test: `python main.py` on synthetic data — epoch
loop, TB event files, checkpoint write, auto-resume on second run
(the minimum end-to-end slice of SURVEY.md §7 step 4)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_main(out_dir, extra=()):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # single CPU device is fine here
    cmd = [
        sys.executable, "main.py",
        "--output_dir", str(out_dir),
        "--epochs", "1",
        "--batch_size", "2",
        "--verbose", "0",
        "--data_source", "synthetic",
        "--image_size", "32",
        "--synthetic_train_size", "4",
        "--synthetic_test_size", "2",
        *extra,
    ]
    return subprocess.run(
        cmd, cwd=REPO, env=env, capture_output=True, text=True, timeout=900
    )


@pytest.mark.slow
def test_main_end_to_end_and_resume(tmp_path):
    out = tmp_path / "run"
    r = run_main(out, extra=("--trace", "2"))
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    # TB event files for train and test writers (utils.py:21-24 parity)
    assert any(f.startswith("events") for f in os.listdir(out))
    assert any(f.startswith("events") for f in os.listdir(out / "test"))
    # --trace N captured a profiler trace (SURVEY.md §5 tracing subsystem)
    assert (out / "traces").is_dir() and any((out / "traces").rglob("*"))
    # single checkpoint slot written (main.py:400-401 parity)
    assert (out / "checkpoints" / "checkpoint").is_dir()
    assert "MAE(X, F(G(X)))" in r.stdout

    # Second run resumes (epochs=1 already done -> trains nothing more)
    r2 = run_main(out)
    assert r2.returncode == 0, f"stdout:\n{r2.stdout}\nstderr:\n{r2.stderr}"
    assert "Resumed" in r2.stdout


@pytest.mark.slow
def test_main_with_periodic_fid(tmp_path):
    """--fid_every through the CLI: fid/* scalars computed on the test
    split at the final epoch and printed (offline random-conv features)."""
    out = tmp_path / "run"
    r = run_main(out, extra=("--fid_every", "1", "--fid_features", "random"))
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "fid/" in r.stdout


@pytest.mark.slow
def test_main_scan_blocks_bf16(tmp_path):
    """--scan_blocks + --bf16 through the CLI: the scanned residual
    trunk and mixed precision compose end-to-end (loop, checkpoint)."""
    out = tmp_path / "run"
    r = run_main(out, extra=("--scan_blocks", "--bf16"))
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert (out / "checkpoints" / "checkpoint").is_dir()
    assert "MAE(X, F(G(X)))" in r.stdout

    # Resume restores the STACKED trunk layout (ScannedTrunk params +
    # Adam mirrors), not just the unrolled one test_main_end_to_end_and
    # _resume covers.
    r2 = run_main(out, extra=("--scan_blocks", "--bf16"))
    assert r2.returncode == 0, f"stdout:\n{r2.stdout}\nstderr:\n{r2.stderr}"
    assert "Resumed" in r2.stdout


@pytest.mark.slow
def test_main_clear_output_dir(tmp_path):
    """--clear_output_dir (reference main.py:359-362 rmtree semantics):
    the output dir is wiped before training, so stale artifacts are
    gone and the run starts FRESH instead of auto-resuming from the
    old slot."""
    out = tmp_path / "run"
    r = run_main(out)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    sentinel = out / "stale.txt"
    sentinel.write_text("x")
    r2 = run_main(out, extra=("--clear_output_dir",))
    assert r2.returncode == 0, f"stdout:\n{r2.stdout}\nstderr:\n{r2.stderr}"
    assert not sentinel.exists()
    assert "Resumed" not in r2.stdout


@pytest.mark.slow
def test_main_steps_per_dispatch_cli(tmp_path):
    """--steps_per_dispatch K through the CLI: with 4 train samples at
    batch 2, the epoch is exactly one fused K=2 dispatch (no remainder)
    — the fused path carries the whole epoch, then a second epoch count
    exercises resume through the multi-step wiring. Loop-level
    equivalence to per-step is tests/test_multistep.py; this pins the
    CLI plumbing (main.py builds BOTH the per-step and fused programs)."""
    out = tmp_path / "run"
    r = run_main(out, extra=("--steps_per_dispatch", "2"))
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert (out / "checkpoints" / "checkpoint").is_dir()
    assert "MAE(X, F(G(X)))" in r.stdout

    r2 = run_main(out, extra=("--steps_per_dispatch", "2", "--epochs", "2"))
    assert r2.returncode == 0, f"stdout:\n{r2.stdout}\nstderr:\n{r2.stderr}"
    assert "Resumed" in r2.stdout


@pytest.mark.slow
def test_main_grad_accum_cli(tmp_path):
    """--grad_accum A through the CLI: effective batch = A x batch,
    accumulated updates, normal artifacts; mutually exclusive with
    --steps_per_dispatch."""
    out = tmp_path / "run"
    r = run_main(out, extra=("--grad_accum", "2"))
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "global batch size: 4 (2x accumulated)" in r.stdout
    assert (out / "checkpoints" / "checkpoint").is_dir()
    assert "MAE(X, F(G(X)))" in r.stdout

    r = run_main(tmp_path / "bad",
                 extra=("--grad_accum", "2", "--steps_per_dispatch", "2"))
    assert r.returncode != 0
    assert "mutually exclusive" in (r.stdout + r.stderr)
