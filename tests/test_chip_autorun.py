"""Guard the automatic chip-window runner (tools/chip_autorun.py).

These pin the machinery, not measurements: the mode decision from
relay-socket states, the queue's content/order/budgets, per-step
artifact commits (so a window that closes mid-queue loses nothing
already landed), resume-at-first-incomplete-step semantics, the
timeout-means-wedged abort, and the oversized-artifact MANIFEST guard.
Nothing here touches jax or any relay socket: relay state is injected
via CHIP_AUTORUN_FAKE_RELAY and steps are stub subprocesses in a
throwaway git repo.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import chip_autorun  # noqa: E402  (parent module imports no jax)
from chip_autorun import Step, build_queue, relay_mode  # noqa: E402


# ------------------------------------------------------------- mode map

@pytest.mark.parametrize("status,expect", [
    ({8082: "open", 8083: "open", 8093: "open"}, "remote"),
    ({8082: "open", 8083: "closed", 8093: "open"}, "remote"),
    ({8082: "open", 8083: "open", 8093: "closed"}, "local_compile"),
    ({8082: "closed", 8083: "open", 8093: "open"}, None),  # no claim leg
    ({8082: "open", 8083: "closed", 8093: "closed"}, None),
    ({8082: "closed", 8083: "closed", 8093: "closed"}, None),
    ({}, None),
])
def test_relay_mode(status, expect):
    assert relay_mode(status) == expect


def test_fake_relay_env_round_trips(monkeypatch):
    monkeypatch.setenv("CHIP_AUTORUN_FAKE_RELAY",
                       "8082:open,8083:open,8093:closed")
    assert chip_autorun.relay_status() == {
        8082: "open", 8083: "open", 8093: "closed"}


# ---------------------------------------------------------------- queue

def test_queue_order_and_budgets():
    q = build_queue("remote")
    names = [s.name for s in q]
    # Highest value first (VERDICT r4 item 1): the no-TPU static
    # preflights (lint, then the comms census — abort before burning
    # the window on a mis-sharded program), health probe, official
    # number cold then warm, the pad lever, 512^2 rows, the serving
    # sweep (+ its trace archive), trace, e2e run.
    assert names == ["graftlint", "comms_census", "diag",
                     "bench_cold", "bench_warm",
                     "pad_sweep", "epilogue_sweep", "grad_sweep",
                     "upsample_sweep", "accum512", "scan512",
                     "spatial_sweep", "spatial_1024",
                     "serve_sweep", "serve_trace", "trace",
                     "chaos_drill", "timed_main",
                     "train_traced", "train_trace", "collective_probe"]
    by = {s.name: s for s in q}
    assert by["diag"].abort_queue_on_fail  # diag failing = relay sick
    # lint failing = known bug class in the code about to burn the
    # window; abort before any chip work, re-check every attempt
    assert by["graftlint"].abort_queue_on_fail
    assert by["graftlint"].always_run
    assert by["graftlint"].stdout_to.endswith("graftlint.json")
    # census failing = mis-sharded program; abort before chip time,
    # on host devices only (never a TPU client before diag probes it)
    assert by["comms_census"].abort_queue_on_fail
    assert by["comms_census"].always_run
    assert by["comms_census"].env.get("JAX_PLATFORMS") == "cpu"
    assert by["comms_census"].stdout_to.endswith("comms_census.json")
    # the census gates BOTH conv shardings so the spatial sweeps below
    # never run a halo program the ledger can't account for
    assert "both" in by["comms_census"].argv
    # dp x spatial sweep + the 1024^2 cell: halo impl, one JSON line each
    for name in ("spatial_sweep", "spatial_1024"):
        argv = by[name].argv
        assert "bench_scaling.py" in argv[1]
        assert argv[argv.index("--spatial_impl") + 1] == "halo"
        assert by[name].stdout_to.endswith("_onchip.json")
    assert "--grid" in by["spatial_1024"].argv
    assert "--remat" in by["spatial_1024"].argv
    # cold run gets the cache-warming budget; warm run is the record
    assert float(by["bench_cold"].env["BENCH_TIME_BUDGET_S"]) > float(
        by["bench_warm"].env["BENCH_TIME_BUDGET_S"])
    assert by["bench_cold"].stdout_to.endswith("_cold.json")
    assert by["bench_warm"].stdout_to and not (
        by["bench_warm"].stdout_to.endswith("_cold.json"))
    # every chip step outlives its own worst-case compile chain; the
    # static preflight and the trace-archive fold compile nothing and
    # keep tight budgets
    for s in q:
        if s.name in ("graftlint", "serve_trace", "train_trace",
                      "collective_probe"):
            assert s.timeout_s >= 120.0
            continue
        assert s.timeout_s >= 1800.0, s.name


def test_queue_pad_sweep_covers_the_lever():
    specs = {s.name: s for s in build_queue("remote")}["pad_sweep"].argv
    assert "scan:b16zero" in specs and "scan:b16fused" in specs


def test_queue_never_enables_pallas():
    for s in build_queue("remote") + build_queue("local_compile"):
        assert "pallas" not in " ".join(s.argv)
        assert s.env.get("CYCLEGAN_ALLOW_PALLAS_REMOTE") is None


def test_local_compile_mode_sets_env_on_every_step():
    for s in build_queue("local_compile"):
        assert s.env["PALLAS_AXON_POOL_IPS"] == ""
        assert s.env["CYCLEGAN_AXON_LOCAL_COMPILE"] == "1"
    for s in build_queue("remote"):
        if s.name in ("epilogue_sweep", "upsample_sweep"):
            continue  # deliberately local-compile in BOTH modes (below)
        assert "CYCLEGAN_AXON_LOCAL_COMPILE" not in s.env


def test_epilogue_sweep_always_forces_local_compile():
    """The epilogue row runs a Mosaic program, which must NEVER cross
    the remote-compile leg (ground rule 2b) — so the step pins the
    local-compile env in remote mode too, not just local_compile."""
    for mode in ("remote", "local_compile"):
        s = {st.name: st for st in build_queue(mode)}["epilogue_sweep"]
        assert s.env["CYCLEGAN_AXON_LOCAL_COMPILE"] == "1"
        assert s.env["PALLAS_AXON_POOL_IPS"] == ""
        assert "scan:b16epi" in s.argv


def test_upsample_sweep_always_forces_local_compile():
    """The zeroskip_fused row is a Mosaic program like the epilogue
    (ground rule 2b): the upsample_sweep step pins local compile in
    BOTH modes and carries the zs/zsf/fpzs grid."""
    for mode in ("remote", "local_compile"):
        s = {st.name: st for st in build_queue(mode)}["upsample_sweep"]
        assert s.env["CYCLEGAN_AXON_LOCAL_COMPILE"] == "1"
        assert s.env["PALLAS_AXON_POOL_IPS"] == ""
        for spec in ("scan:b16zs", "scan:b16zsf", "scan:b16fpzs"):
            assert spec in s.argv


def test_serve_sweep_keeps_the_one_json_line_contract():
    """The serving sweep lands like the bench steps: stdout captured to
    a round-tagged docs JSON (validated before commit), with an explicit
    time budget the step timeout outlives."""
    for mode in ("remote", "local_compile"):
        s = {st.name: st for st in build_queue(mode)}["serve_sweep"]
        assert s.argv[-1].endswith("bench_serve.py")
        assert s.stdout_to.startswith("docs") and \
            s.stdout_to.endswith("_onchip.json")
        assert "bench_serve" in s.stdout_to
        budget = float(s.env["BENCH_SERVE_TIME_BUDGET_S"])
        assert budget + 120 <= s.timeout_s  # SIGALRM partial-line slack


def test_timed_main_writes_outside_repo():
    # checkpoints are hundreds of MB; the timed run must not point its
    # output_dir inside the repo where the step-commit would sweep it up
    argv = [s for s in build_queue("remote") if s.name == "timed_main"][0].argv
    out = argv[argv.index("--output_dir") + 1]
    assert os.path.isabs(out) and not out.startswith(REPO + os.sep)


def test_train_trace_round_contract():
    """The traced training run: fully sampled spans + per-epoch probe,
    obs stream to /tmp, checkpoints OUTSIDE the repo; the fold step
    collects the Perfetto timeline + raw slice and commits the
    critical-path table via stdout_to. timed_main stays untraced (the
    headline number carries no trace overhead)."""
    by = {s.name: s for s in build_queue("remote")}
    run = by["train_traced"].argv
    assert run[run.index("--train_trace_sample") + 1] == "1.0"
    assert run[run.index("--probe_every") + 1] == "1"
    obs = run[run.index("--obs_jsonl") + 1]
    out = run[run.index("--output_dir") + 1]
    assert os.path.isabs(out) and not out.startswith(REPO + os.sep)
    assert os.path.isabs(obs) and not obs.startswith(REPO + os.sep)
    assert "--train_trace_sample" not in by["timed_main"].argv
    fold = by["train_trace"]
    assert "trace_timeline.py" in fold.argv[1]
    assert obs in fold.argv
    srcs = {src for src, _ in fold.collect}
    dests = {dest for _, dest in fold.collect}
    assert obs in srcs
    assert all(d.startswith("docs/chip_logs/") for d in dests)
    assert fold.stdout_to.endswith("train_trace_table.json")
    # the round's measured-collective artifact comes out of the traced
    # run's obs stream (the probe ran on the real mesh); re-running the
    # probe CLI post-hoc would measure the wrong fabric
    probe = by["collective_probe"]
    assert "obs_report.py" in probe.argv[1]
    assert "--probe-json" in probe.argv
    assert obs in probe.argv
    assert probe.stdout_to.endswith("collective_probe.json")
    assert probe.stdout_to.startswith("docs/chip_logs/")


def test_dry_run_prints_queue_and_executes_nothing(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chip_autorun.py"),
         "--dry-run"],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "mode remote" in r.stdout and "mode local_compile" in r.stdout
    for name in ("diag", "bench_cold", "bench_warm", "pad_sweep",
                 "epilogue_sweep", "accum512", "scan512", "trace",
                 "timed_main"):
        assert name in r.stdout


# ----------------------------------------------------- supervised queue

@pytest.fixture()
def fake_repo(tmp_path, monkeypatch):
    repo = tmp_path / "repo"
    repo.mkdir()
    subprocess.run(["git", "init", "-q"], cwd=repo, check=True)
    subprocess.run(["git", "config", "user.email", "t@t"], cwd=repo,
                   check=True)
    subprocess.run(["git", "config", "user.name", "t"], cwd=repo, check=True)
    monkeypatch.setenv("CHIP_AUTORUN_FAKE_RELAY",
                       "8082:open,8083:open,8093:open")
    return str(repo)


def _stub_step(name, script, timeout_s=30.0, **kw):
    return Step(name, [sys.executable, "-c", script], timeout_s, **kw)


def _commits(repo):
    r = subprocess.run(["git", "log", "--format=%s"], cwd=repo,
                       capture_output=True, text=True)
    return r.stdout.strip().splitlines()


def test_run_queue_commits_each_step_immediately(fake_repo):
    q = [
        _stub_step("one", "open('a.txt','w').write('1')",
                   artifacts=["a.txt"]),
        _stub_step("two", "open('b.txt','w').write('2')",
                   artifacts=["b.txt"]),
    ]
    assert chip_autorun.run_queue(fake_repo, q)
    log = _commits(fake_repo)
    assert len(log) == 2
    assert "one ok" in log[1] and "two ok" in log[0]
    status = chip_autorun.load_status(fake_repo)
    assert [s["name"] for s in status["steps"]] == ["one", "two"]
    assert all(s["status"] == "ok" for s in status["steps"])
    # the per-step log itself is committed evidence
    assert os.path.exists(os.path.join(
        fake_repo, chip_autorun.LOG_DIR_REL, "one.log"))


def test_run_queue_resume_skips_completed(fake_repo):
    q = [
        _stub_step("one", "open('a.txt','w').write('1')",
                   artifacts=["a.txt"]),
        _stub_step("two", "open('b.txt','w').write('2')",
                   artifacts=["b.txt"]),
    ]
    assert chip_autorun.run_queue(fake_repo, q, resume_from={"one"})
    assert not os.path.exists(os.path.join(fake_repo, "a.txt"))
    assert os.path.exists(os.path.join(fake_repo, "b.txt"))


def test_run_queue_stdout_capture(fake_repo):
    q = [_stub_step("bench_stub", "print('{\"metric\": 1}')",
                    stdout_to="docs/bench_stub.json")]
    assert chip_autorun.run_queue(fake_repo, q)
    rec = json.loads(
        open(os.path.join(fake_repo, "docs", "bench_stub.json")).read())
    assert rec == {"metric": 1}
    assert any("bench_stub ok" in c for c in _commits(fake_repo))


def test_run_queue_timeout_aborts_remaining(fake_repo):
    q = [
        _stub_step("hang", "import time; time.sleep(60)", timeout_s=1.5),
        _stub_step("never", "open('never.txt','w').write('x')",
                   artifacts=["never.txt"]),
    ]
    assert chip_autorun.run_queue(fake_repo, q) is False
    assert not os.path.exists(os.path.join(fake_repo, "never.txt"))
    status = chip_autorun.load_status(fake_repo)
    assert status["steps"][0]["status"] == "timeout_killed"
    # the kill itself is committed evidence (ledger + step log)
    assert any("timeout_killed" in c for c in _commits(fake_repo))


def test_run_queue_abort_on_fail_step(fake_repo):
    q = [
        _stub_step("diag", "raise SystemExit(3)", abort_queue_on_fail=True),
        _stub_step("never", "open('never.txt','w').write('x')",
                   artifacts=["never.txt"]),
    ]
    assert chip_autorun.run_queue(fake_repo, q) is False
    assert not os.path.exists(os.path.join(fake_repo, "never.txt"))


def test_run_queue_plain_failure_continues(fake_repo):
    q = [
        _stub_step("oom_row", "raise SystemExit(1)"),
        _stub_step("next", "open('n.txt','w').write('x')",
                   artifacts=["n.txt"]),
    ]
    # a failed measurement (e.g. an OOM row) must not strand the queue
    assert chip_autorun.run_queue(fake_repo, q) is False
    assert os.path.exists(os.path.join(fake_repo, "n.txt"))


def test_run_queue_stops_when_relay_drops(fake_repo, monkeypatch):
    monkeypatch.setenv("CHIP_AUTORUN_FAKE_RELAY",
                       "8082:closed,8083:closed,8093:closed")
    q = [_stub_step("one", "open('a.txt','w').write('1')",
                    artifacts=["a.txt"])]
    assert chip_autorun.run_queue(fake_repo, q) is False
    assert not os.path.exists(os.path.join(fake_repo, "a.txt"))


def test_run_queue_timeout_kills_grandchildren(fake_repo):
    """A timed-out step's whole process GROUP dies: an orphaned
    bench.py CPU-worker would match other_chip_clients' markers and
    block the next window attempt (code-review r5 finding)."""
    script = (
        "import subprocess, sys, time, os\n"
        "child = subprocess.Popen([sys.executable, '-c',"
        " 'import time; time.sleep(60)'])\n"
        "open('childpid.txt', 'w').write(str(child.pid))\n"
        "time.sleep(60)\n"
    )
    # 10s timeout, not 3: on a loaded 1-core host the stub interpreter
    # can take seconds to even start — the kill must land AFTER the
    # grandchild exists or the test asserts nothing (observed flaky in
    # the full suite under a concurrent training run)
    q = [_stub_step("hang_tree", script, timeout_s=10.0,
                    artifacts=["childpid.txt"])]
    assert chip_autorun.run_queue(fake_repo, q) is False
    pid = int(open(os.path.join(fake_repo, "childpid.txt")).read())
    for _ in range(50):  # grace for the SIGKILL to land + reap
        if not os.path.exists(f"/proc/{pid}"):
            break
        # a zombie (reparented, unreaped) is dead for our purposes
        with open(f"/proc/{pid}/stat") as f:
            if f.read().split()[2] == "Z":
                break
        import time
        time.sleep(0.1)
    else:
        raise AssertionError(f"grandchild {pid} survived the group kill")


def test_given_up_steps_two_strikes():
    tag = chip_autorun.ROUND_TAG
    status = {"steps": [
        {"name": "bench_cold", "status": "timeout_killed", "tag": tag},
        {"name": "bench_cold", "status": "timeout_killed", "tag": tag},
        {"name": "diag", "status": "timeout_killed", "tag": tag},
        {"name": "pad_sweep", "status": "ok", "tag": tag},
    ]}
    assert chip_autorun.given_up_steps(status) == {"bench_cold"}


def test_ledger_is_round_scoped():
    """A step completed (or struck out) in a PRIOR round must not skip
    this round's identically-named step — each round's captures are
    fresh evidence (code-review r5 finding)."""
    old = {"steps": [
        {"name": "bench_cold", "status": "ok", "tag": "r04"},
        {"name": "pad_sweep", "status": "timeout_killed", "tag": "r04"},
        {"name": "pad_sweep", "status": "timeout_killed", "tag": "r04"},
        {"name": "bench_warm", "status": "ok"},  # legacy tagless
    ]}
    assert chip_autorun.completed_steps(old) == set()
    assert chip_autorun.given_up_steps(old) == set()


def test_attempt_window_skips_given_up_steps(fake_repo, monkeypatch):
    """Two timeout strikes retire a step so retries can't kill-loop a
    client against a slow tunnel; with every step completed or given
    up, an attempt is a no-op success."""
    monkeypatch.setattr(chip_autorun, "CONFIRM_S", 0.0)
    tag = chip_autorun.ROUND_TAG
    steps = []
    for s in build_queue("remote"):
        if s.name == "bench_cold":
            steps += [{"name": s.name, "status": "timeout_killed",
                       "tag": tag}] * 2
        else:
            steps.append({"name": s.name, "status": "ok", "tag": tag})
    chip_autorun.save_status(fake_repo, {"steps": steps})
    assert chip_autorun.attempt_window(fake_repo) is True


def test_always_run_step_reruns_despite_prior_ok(fake_repo):
    """diag is a health probe: a past ok says nothing about THIS
    window, so resume must never skip an always_run step."""
    q = [
        _stub_step("diag", "open('d.txt','a').write('x')",
                   artifacts=["d.txt"], abort_queue_on_fail=True,
                   always_run=True),
        _stub_step("work", "open('w.txt','w').write('x')",
                   artifacts=["w.txt"]),
    ]
    assert chip_autorun.run_queue(fake_repo, q, resume_from={"diag"})
    assert os.path.exists(os.path.join(fake_repo, "d.txt"))


def test_diag_never_given_up_while_work_pends(fake_repo, monkeypatch):
    """Two diag timeouts must NOT retire the health probe: skipping it
    would launch long bench clients against an unverified relay
    (code-review r5 finding)."""
    monkeypatch.setattr(chip_autorun, "CONFIRM_S", 0.0)
    tag = chip_autorun.ROUND_TAG
    chip_autorun.save_status(fake_repo, {"steps": [
        {"name": "diag", "status": "timeout_killed", "tag": tag},
        {"name": "diag", "status": "timeout_killed", "tag": tag},
    ]})
    ran = []

    def fake_run_queue(repo, queue, resume_from=frozenset(), mode=None):
        ran.append([s.name for s in queue
                    if s.name not in resume_from or s.always_run])
        return False

    monkeypatch.setattr(chip_autorun, "run_queue", fake_run_queue)
    assert chip_autorun.attempt_window(fake_repo) is False
    # the probe still runs every attempt (right after the static
    # preflights, which need no TPU and so precede it)
    assert ran and ran[0][:3] == ["graftlint", "comms_census", "diag"]


def test_run_queue_stops_on_mode_shift(fake_repo, monkeypatch):
    """remote -> local_compile mid-queue must stop the queue (next
    attempt rebuilds with the local-compile env) instead of running a
    step against the dead remote-compile leg."""
    monkeypatch.setenv("CHIP_AUTORUN_FAKE_RELAY",
                       "8082:open,8083:open,8093:closed")  # local_compile
    q = [_stub_step("one", "open('a.txt','w').write('1')",
                    artifacts=["a.txt"])]
    assert chip_autorun.run_queue(fake_repo, q, mode="remote") is False
    assert not os.path.exists(os.path.join(fake_repo, "a.txt"))
    # matching mode proceeds
    assert chip_autorun.run_queue(fake_repo, q, mode="local_compile")
    assert os.path.exists(os.path.join(fake_repo, "a.txt"))


def test_argv_matching_is_token_based(tmp_path):
    """A marker NAME inside a long argument string (a harness process
    whose embedded prompt mentions bench.py, a grep over the repo) must
    NOT read as a chip client — only an actual argv SCRIPT token
    invoking the entry point does. The substring version of this bug
    made the deployed watcher refuse every window while the session
    driver was alive (found via the full-suite run, where pytest is
    reparented away from the driver's ancestor chain)."""
    repo = str(tmp_path)
    is_client = chip_autorun._argv_is_chip_client
    # real clients
    assert is_client(["python", "bench.py"], repo)
    assert is_client(["/opt/venv/bin/python", "tools/tpu_diag.py",
                      "--full"], repo)
    assert is_client(["python3", "/x/tools/chip_sweep.py", "scan:b16"],
                     repo)
    # marker name embedded in a prompt/argument string: NOT a client
    assert not is_client(
        ["claude", "-p", "--append-system-prompt",
         "keep tests green (python -m pytest); run bench.py and "
         "tools/tpu_diag.py when the relay recovers"], repo)
    assert not is_client(["grep", "-rn", "bench.py", "."], repo)
    # marker as a DATA argument after a non-marker script: not a client
    assert not is_client(["python", "tools/plot.py", "--input",
                          "bench.py"], repo)
    assert not is_client(["python", "-m", "pydoc", "bench.py"], repo)
    # non-python argv0 never matches even with a marker token
    assert not is_client(["bash", "bench.py"], repo)
    # main.py: only THIS repo's, resolved against the PROCESS's cwd
    assert is_client(["python", os.path.join(repo, "main.py")], repo)
    assert is_client(["python", "-u", "main.py"], repo, cwd=repo)
    assert not is_client(["python", "-u", "main.py"], repo,
                         cwd="/somewhere/else")
    # relative main.py with unknown cwd: cannot be claimed as ours
    assert not is_client(["python", "-u", "main.py"], repo)
    assert not is_client(["python", "/somewhere/else/main.py"], repo)


def test_other_chip_clients_cpu_pinned_exempt_with_positive_control():
    """A JAX_PLATFORMS=cpu process (offline tests, quality A/B runs)
    can never claim the chip and must not block a window — while the
    SAME entry point without the pin (positive control) must be
    reported. Both processes are killed during interpreter startup
    (init-phase kills are safe — TPU_RUNBOOK ground rules); the
    control uses cache_warm --list, which never opens a backend."""
    import subprocess as sp
    import time as _t

    tool = os.path.join(REPO, "tools", "cache_warm.py")
    env_cpu = dict(os.environ)
    env_cpu["JAX_PLATFORMS"] = "cpu"
    env_free = {k: v for k, v in os.environ.items()
                if k != "JAX_PLATFORMS"}
    p_cpu = sp.Popen([sys.executable, tool, "--list"], env=env_cpu,
                     stdout=sp.DEVNULL, stderr=sp.DEVNULL)
    p_free = sp.Popen([sys.executable, tool, "--list"], env=env_free,
                      stdout=sp.DEVNULL, stderr=sp.DEVNULL)
    try:
        _t.sleep(0.5)  # let /proc entries appear
        assert p_cpu.poll() is None and p_free.poll() is None, (
            "probe processes died before the scan — test would be vacuous")
        hits = [pid for pid, _ in chip_autorun.other_chip_clients(REPO)]
        assert p_free.pid in hits  # positive control: detection works
        assert p_cpu.pid not in hits  # cpu-pinned is exempt
    finally:
        for p in (p_cpu, p_free):
            p.kill()
            p.wait()


def test_commit_paths_manifests_oversized_dirs(fake_repo, monkeypatch):
    big = os.path.join(fake_repo, "trace")
    os.makedirs(big)
    with open(os.path.join(big, "trace.pb"), "wb") as f:
        f.write(b"\0" * 4096)
    monkeypatch.setattr(chip_autorun, "MAX_COMMIT_DIR_BYTES", 1024)
    assert chip_autorun.commit_paths(fake_repo, ["trace"], "trace step")
    committed = subprocess.run(
        ["git", "ls-tree", "-r", "--name-only", "HEAD"], cwd=fake_repo,
        capture_output=True, text=True).stdout.split()
    assert committed == ["trace.MANIFEST"]
    assert "trace.pb" in open(os.path.join(fake_repo,
                                           "trace.MANIFEST")).read()


def test_attempt_window_refuses_when_relay_down(fake_repo, monkeypatch):
    monkeypatch.setenv("CHIP_AUTORUN_FAKE_RELAY",
                       "8082:closed,8083:closed,8093:closed")
    assert chip_autorun.attempt_window(fake_repo) is False


def test_attempt_window_noop_when_queue_done(fake_repo, monkeypatch):
    monkeypatch.setattr(chip_autorun, "CONFIRM_S", 0.0)
    chip_autorun.save_status(fake_repo, {"steps": [
        {"name": s.name, "status": "ok", "tag": chip_autorun.ROUND_TAG}
        for s in build_queue("remote")
    ]})
    assert chip_autorun.attempt_window(fake_repo) is True


def test_collect_copies_from_outside_repo(fake_repo, tmp_path):
    """A step may write its bulky output OUTSIDE the repo (checkpoints
    must never be committable); `collect` copies just the evidence in."""
    src = tmp_path / "ext_out" / "traces"
    src.mkdir(parents=True)
    (src / "trace.json.gz").write_bytes(b"tracedata")
    q = [Step("trace_stub", [sys.executable, "-c", "pass"], 30.0,
              collect=[(str(src), "docs/chip_logs/r05/trace_run/traces")])]
    assert chip_autorun.run_queue(fake_repo, q)
    dest = os.path.join(fake_repo, "docs", "chip_logs", "r05",
                        "trace_run", "traces", "trace.json.gz")
    assert os.path.exists(dest)
    committed = subprocess.run(
        ["git", "ls-tree", "-r", "--name-only", "HEAD"], cwd=fake_repo,
        capture_output=True, text=True).stdout
    assert "trace.json.gz" in committed


def test_trace_step_outputs_outside_repo_and_collects_traces():
    by = {s.name: s for s in build_queue("remote")}
    argv = by["trace"].argv
    out = argv[argv.index("--output_dir") + 1]
    assert os.path.isabs(out) and not out.startswith(REPO + os.sep)
    (src, dest_rel), = by["trace"].collect
    assert src.startswith(out)  # only the trace subdir is collected
    assert dest_rel.startswith("docs/chip_logs/")


def test_flock_single_instance(tmp_path, monkeypatch):
    """The single-instance lock must hold atomically (no stale-file
    TOCTOU): with the lock held, --once exits 1 before doing anything."""
    import fcntl

    lock = tmp_path / "autorun.lock"
    fd = os.open(str(lock), os.O_CREAT | os.O_WRONLY)
    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    env = dict(os.environ)
    env["CHIP_AUTORUN_LOCK"] = str(lock)
    env["CHIP_AUTORUN_FAKE_RELAY"] = "8082:closed,8083:closed,8093:closed"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chip_autorun.py"),
         "--once"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=60)
    os.close(fd)
    assert r.returncode == 1
    assert "holds the lock" in r.stdout
    # once released, --once proceeds to the (refused: relay down) attempt
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chip_autorun.py"),
         "--once"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=60)
    assert r2.returncode == 1 and "relay not usable" in r2.stdout
