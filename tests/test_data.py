"""Input-pipeline tests: shapes, normalization range, determinism,
cache-after-augment reproduction, ragged-batch padding, zip semantics
(reference main.py:18-83)."""

import numpy as np
import pytest

from cyclegan_tpu.config import Config, DataConfig, TrainConfig, tiny_test_config
from cyclegan_tpu.data import build_data
from cyclegan_tpu.data.augment import (
    normalize_image,
    preprocess_test,
    preprocess_train,
    resize_bilinear,
)
from cyclegan_tpu.data.sources import SyntheticSource


def test_normalize_range():
    img = np.asarray([[0, 127.5, 255]], np.float32)[..., None]
    out = normalize_image(img)
    np.testing.assert_allclose(out.ravel(), [-1.0, 0.0, 1.0])


def test_resize_bilinear_identity():
    img = np.random.RandomState(0).rand(8, 8, 3).astype(np.float32)
    np.testing.assert_array_equal(resize_bilinear(img, 8, 8), img)


def test_resize_bilinear_constant_preserved():
    img = np.full((10, 10, 3), 7.0, np.float32)
    out = resize_bilinear(img, 286, 286)
    assert out.shape == (286, 286, 3)
    np.testing.assert_allclose(out, 7.0, rtol=1e-6)


def test_resize_bilinear_matches_tf_convention():
    # 2x upsample of [0, 1] with half-pixel centers:
    # out coords map to src [-0.25, 0.25, 0.75, 1.25] -> [0, .25, .75, 1]
    img = np.asarray([[0.0, 1.0]], np.float32).reshape(1, 2, 1)
    out = resize_bilinear(img, 1, 4)
    np.testing.assert_allclose(out.ravel(), [0.0, 0.25, 0.75, 1.0], atol=1e-6)


def test_preprocess_train_shape_and_range():
    img = np.random.RandomState(0).randint(0, 256, (300, 200, 3), dtype=np.uint8)
    rng = np.random.default_rng(0)
    out = preprocess_train(img, rng, resize_size=286, crop_size=256)
    assert out.shape == (256, 256, 3)
    assert out.min() >= -1.0 and out.max() <= 1.0


def test_preprocess_test_deterministic():
    img = np.random.RandomState(1).randint(0, 256, (100, 120, 3), dtype=np.uint8)
    a = preprocess_test(img, 256)
    b = preprocess_test(img, 256)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (256, 256, 3)


@pytest.fixture(scope="module")
def tiny_data():
    cfg = tiny_test_config()
    return build_data(cfg, global_batch_size=4)


def test_steps_ceil_semantics(tiny_data):
    # 8 train samples at global batch 4 -> 2 steps; 4 test at 4 -> 1.
    assert tiny_data.train_steps == 2
    assert tiny_data.test_steps == 1


def test_train_epoch_batches(tiny_data):
    batches = list(tiny_data.train_epoch(0, prefetch=False))
    assert len(batches) == tiny_data.train_steps
    for x, y, w in batches:
        assert x.shape == (4, 32, 32, 3)
        assert y.shape == (4, 32, 32, 3)
        assert w.shape == (4,)
        assert x.min() >= -1.0 and x.max() <= 1.0


def test_cache_augmented_frozen_across_epochs(tiny_data):
    """Reference quirk (main.py:53-54): augmentations frozen after epoch 1
    — same images across epochs, possibly different order."""
    b0 = sorted(list(tiny_data.train_epoch(0, prefetch=False))[0][0].sum(axis=(1, 2, 3)).tolist())
    b1 = sorted(list(tiny_data.train_epoch(1, prefetch=False))[0][0].sum(axis=(1, 2, 3)).tolist())
    all0 = np.concatenate([b[0] for b in tiny_data.train_epoch(0, prefetch=False)])
    all1 = np.concatenate([b[0] for b in tiny_data.train_epoch(1, prefetch=False)])
    s0 = sorted(all0.sum(axis=(1, 2, 3)).tolist())
    s1 = sorted(all1.sum(axis=(1, 2, 3)).tolist())
    np.testing.assert_allclose(s0, s1, rtol=1e-5)


def test_fresh_augment_varies_across_epochs():
    cfg = tiny_test_config()
    cfg = Config(
        model=cfg.model,
        data=DataConfig(
            source="synthetic", resize_size=36, crop_size=32,
            synthetic_train_size=8, synthetic_test_size=4,
            cache_augmented=False,
        ),
        train=cfg.train,
    )
    data = build_data(cfg, global_batch_size=4)
    all0 = np.concatenate([b[0] for b in data.train_epoch(0, prefetch=False)])
    all1 = np.concatenate([b[0] for b in data.train_epoch(1, prefetch=False)])
    assert not np.allclose(sorted(all0.sum(axis=(1, 2, 3))), sorted(all1.sum(axis=(1, 2, 3))))


def test_shuffle_differs_between_epochs(tiny_data):
    x0 = list(tiny_data.train_epoch(0, prefetch=False))[0][0]
    x1 = list(tiny_data.train_epoch(1, prefetch=False))[0][0]
    # same cached images (above test), different order with high prob
    assert not np.array_equal(x0, x1)


def test_ragged_final_batch_padded():
    cfg = tiny_test_config()  # 8 train samples
    data = build_data(cfg, global_batch_size=3)  # 3 steps: 3+3+2
    assert data.train_steps == 3
    batches = list(data.train_epoch(0, prefetch=False))
    x, y, w = batches[-1]
    assert x.shape[0] == 3
    np.testing.assert_array_equal(w, [1.0, 1.0, 0.0])
    # padded sample must be zeroed
    assert np.abs(x[2]).sum() == 0


def test_plot_pairs(tiny_data):
    pairs = tiny_data.plot_pairs(5)
    # min(5, n_test=4) pairs at batch 1 (main.py:76-77)
    assert len(pairs) == 4
    for x, y in pairs:
        assert x.shape == (1, 32, 32, 3)
        assert y.shape == (1, 32, 32, 3)


def test_prefetch_yields_same_batches(tiny_data):
    direct = list(tiny_data.train_epoch(0, prefetch=False))
    pre = list(tiny_data.train_epoch(0, prefetch=True))
    assert len(direct) == len(pre)
    for (a, b, c), (d, e, f) in zip(direct, pre):
        np.testing.assert_array_equal(a, d)
        np.testing.assert_array_equal(c, f)


def test_synthetic_source_deterministic():
    s1 = SyntheticSource(4, 2, 32)
    s2 = SyntheticSource(4, 2, 32)
    np.testing.assert_array_equal(s1.load("trainA", 0), s2.load("trainA", 0))
    assert not np.array_equal(s1.load("trainA", 0), s1.load("trainA", 1))
    assert not np.array_equal(s1.load("trainA", 0), s1.load("trainB", 0))


def test_separate_test_batch_size():
    """Under --grad_accum the train batch is the effective (accumulated)
    batch, but eval forwards have no microbatching: test_epoch must use
    its own smaller batch size."""
    cfg = Config(
        data=DataConfig(
            source="synthetic", resize_size=20, crop_size=16,
            synthetic_train_size=8, synthetic_test_size=6,
        ),
        train=TrainConfig(batch_size=8),
    )
    data = build_data(cfg, global_batch_size=8, test_batch_size=2)
    assert data.train_steps == 1
    assert data.test_steps == 3  # ceil(6 / 2), not ceil(6 / 8)
    train_batches = list(data.train_epoch(0, prefetch=False))
    test_batches = list(data.test_epoch(prefetch=False))
    assert train_batches[0][0].shape[0] == 8
    assert len(test_batches) == 3
    assert all(b[0].shape[0] == 2 for b in test_batches)
    assert sum(int(b[2].sum()) for b in test_batches) == 6
