"""Data-pipeline memory ledger: the caches must stay uint8 and bounded.

The reference caches mapped float32 tensors (tf.data cache after map,
/root/reference/main.py:53-60) — at monet2photo scale that is several GB.
Here the caches hold post-augment uint8 (4x smaller), normalization
happens batch-at-a-time in the prefetch thread, and native preprocessing
runs in bounded windows, so the default config stays well under 1GB at
the scale of every cycle_gan/* dataset.
"""

import numpy as np

from cyclegan_tpu.config import Config, DataConfig, TrainConfig
from cyclegan_tpu.data.pipeline import CycleGANData


class _CountingSource:
    """Constant-image source that records every load (cheap enough to run
    monet2photo-scale constructions in a unit test)."""

    def __init__(self, sizes, hw=256):
        self.name = "counting"
        self._sizes = dict(sizes)
        self._img = np.full((hw, hw, 3), 128, np.uint8)
        self.loads = []

    def split_size(self, split):
        return self._sizes[split]

    def load(self, split, index):
        self.loads.append((split, index))
        return self._img


def _build(sizes, crop=256, cache=True, batch=1):
    cfg = Config(
        data=DataConfig(resize_size=crop + 30, crop_size=crop, cache_augmented=cache),
        train=TrainConfig(batch_size=batch),
    )
    src = _CountingSource(sizes, hw=crop)
    return CycleGANData(cfg, global_batch_size=batch, source=src), src


def test_caches_are_uint8():
    data, _ = _build(
        {"trainA": 6, "trainB": 5, "testA": 3, "testB": 3}, crop=32
    )
    for img in data._test_a + data._test_b:
        assert img.dtype == np.uint8
    a, b = data._train_cache
    for img in a + b:
        assert img.dtype == np.uint8
    # Ledger equals the exact uint8 footprint.
    expected = (2 * data.n_train + 2 * data.n_test) * 32 * 32 * 3
    assert data.cache_nbytes() == expected


def test_batches_are_normalized_float32():
    data, _ = _build({"trainA": 4, "trainB": 4, "testA": 2, "testB": 2}, crop=32, batch=2)
    for x, y, w in data.train_epoch(0, prefetch=False):
        assert x.dtype == np.float32 and y.dtype == np.float32
        assert float(x.min()) >= -1.0 and float(x.max()) <= 1.0
    (x, y, w) = next(iter(data.test_epoch(prefetch=False)))
    assert x.dtype == np.float32
    px, py = data.plot_pairs(1)[0]
    assert px.dtype == np.float32 and float(px.max()) <= 1.0


def test_monet2photo_scale_ledger_under_1gb():
    """monet2photo split sizes (the largest-RAM cycle_gan configuration
    the VERDICT flagged): trainA 1072, trainB 6287, testA 121, testB 751.
    min-truncation (main.py:30-31) + uint8 caches keep the resident
    ledger ~0.5GB where float32 full-split materialization was ~5GB."""
    sizes = {"trainA": 1072, "trainB": 6287, "testA": 121, "testB": 751}
    data, src = _build(sizes, crop=256)
    ledger = data.cache_nbytes()
    assert ledger < 1_000_000_000, f"cache ledger {ledger/1e9:.2f}GB"
    # Expected exactly: (2*1072 + 2*121) images * 256*256*3 bytes ~ 0.47GB
    assert ledger == (2 * 1072 + 2 * 121) * 256 * 256 * 3
    # Lazy discipline: nothing beyond the min-truncated counts was ever
    # pulled from the source — the 6287-image trainB tail stays unread.
    from collections import Counter

    per_split = Counter(s for s, _ in src.loads)
    assert per_split["trainA"] == 1072
    assert per_split["trainB"] == 1072
    assert per_split["testA"] == 121
    assert per_split["testB"] == 121


def test_native_window_bounds_transients():
    """The native batch path must process in windows, never stacking the
    whole split (the transient raw stack at monet2photo scale would be
    GBs). Window size is the class constant; a split larger than it
    still produces identical per-image results to the unwindowed numpy
    path (same RNG streams)."""
    n = CycleGANData._NATIVE_WINDOW + 7
    data, src = _build(
        {"trainA": n, "trainB": n, "testA": 1, "testB": 1}, crop=16, cache=True
    )
    a, b = data._train_cache
    assert len(a) == n and len(b) == n
    for img in (a[0], a[-1], b[CycleGANData._NATIVE_WINDOW]):
        assert img.dtype == np.uint8 and img.shape == (16, 16, 3)
