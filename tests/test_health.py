"""Model-health flight recorder (obs/health.py): in-step numerics
metrics, host-side anomaly detectors, and the halt policy.

The device half must add its statistics INSIDE the existing jitted
dispatch (same metrics dict, same deferred-fetch path — dispatch count
per step pinned unchanged here via the StepClock aggregate), and the
host half must catch a poisoned step within one deferred-fetch horizon
of the loop, halting with the checkpoint slot untouched when asked to.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu.config import ObsConfig
from cyclegan_tpu.obs import (
    HealthFault,
    HealthMonitor,
    make_health_monitor,
    make_telemetry,
)
from cyclegan_tpu.obs.health import (
    DISC_STATS,
    INTERNAL_PREFIX,
    NETWORKS,
)
from cyclegan_tpu.train import create_state, make_train_step

REFERENCE_KEYS = {
    "loss_G/loss", "loss_G/cycle", "loss_G/identity", "loss_G/total",
    "loss_F/loss", "loss_F/cycle", "loss_F/identity", "loss_F/total",
    "loss_X/loss", "loss_Y/loss",
}

HEALTH_KEYS = (
    {f"health/{s}_{w}_{stat}" for s, w in DISC_STATS
     for stat in ("mean", "std")}
    | {f"health/gnorm_{n}" for n in NETWORKS}
    | {f"health/upd_ratio_{n}" for n in NETWORKS}
    | {"health/nonfinite"}
)


@pytest.fixture(scope="module")
def setup(tiny_config):
    cfg = tiny_config
    state = create_state(cfg, jax.random.PRNGKey(0))
    kx, ky = jax.random.split(jax.random.PRNGKey(7))
    n = 2
    shape = (n, cfg.model.image_size, cfg.model.image_size, 3)
    x = jax.random.uniform(kx, shape, minval=-1, maxval=1)
    y = jax.random.uniform(ky, shape, minval=-1, maxval=1)
    w = jnp.ones((n,), jnp.float32)
    return cfg, state, x, y, w


# ------------------------------------------------- device-side metrics


def test_train_step_emits_health_metrics(setup):
    """The health stats ride the train step's metrics dict: reference
    keys plus the full health/* set, all finite on a healthy step, and
    no internal `_health/` moment keys leaking past finalization."""
    cfg, state, x, y, w = setup
    train_step = jax.jit(make_train_step(cfg, x.shape[0]))
    _, metrics = train_step(state, x, y, w)
    assert REFERENCE_KEYS <= set(metrics)
    assert set(metrics) == REFERENCE_KEYS | HEALTH_KEYS
    assert not any(k.startswith(INTERNAL_PREFIX) for k in metrics)
    for k in HEALTH_KEYS:
        assert np.isfinite(float(metrics[k])), f"{k} not finite"
    assert float(metrics["health/nonfinite"]) == 0.0
    for net in NETWORKS:
        assert float(metrics[f"health/gnorm_{net}"]) > 0.0
        assert float(metrics[f"health/upd_ratio_{net}"]) > 0.0


def test_health_disabled_restores_reference_metrics(setup):
    """obs.health=False must reproduce the historical metrics dict
    exactly — the layer is strictly additive."""
    cfg, state, x, y, w = setup
    cfg_off = dataclasses.replace(
        cfg, obs=dataclasses.replace(cfg.obs, health=False)
    )
    train_step = jax.jit(make_train_step(cfg_off, x.shape[0]))
    _, metrics = train_step(state, x, y, w)
    assert set(metrics) == REFERENCE_KEYS


def test_nonfinite_counter_trips_on_poisoned_params(setup):
    """NaN parameters poison the backward pass; the fused isfinite
    reduction must report a nonzero count in the same step's metrics."""
    cfg, state, x, y, w = setup
    poisoned = state.replace(
        g_params=jax.tree.map(
            lambda a: jnp.full_like(a, jnp.nan), state.g_params
        )
    )
    train_step = jax.jit(make_train_step(cfg, x.shape[0]))
    _, metrics = train_step(poisoned, x, y, w)
    assert float(metrics["health/nonfinite"]) > 0


# ------------------------------------------------- host-side detectors


class FakeTelemetry:
    def __init__(self):
        self.events = []
        self.flushed = 0

    def event(self, kind, /, **fields):
        # Positional-only `kind`, like obs.Telemetry.event: fault events
        # carry a "kind" FIELD too (the detector name).
        self.events.append(dict(fields, event=kind))

    def flush(self):
        self.flushed += 1


def _healthy_row(**over):
    row = {
        "loss_G/total": 3.0, "loss_F/total": 3.1,
        "loss_X/loss": 0.5, "loss_Y/loss": 0.5,
        "health/nonfinite": 0.0,
    }
    for net in NETWORKS:
        row[f"health/gnorm_{net}"] = 1.0
        row[f"health/upd_ratio_{net}"] = 1e-4
    for side in ("dX", "dY"):
        row[f"health/{side}_real_mean"] = 0.6
        row[f"health/{side}_fake_mean"] = 0.4
        row[f"health/{side}_real_std"] = 0.2
        row[f"health/{side}_fake_std"] = 0.2
    row.update(over)
    return row


def test_nonfinite_tripwire_warn_vs_halt():
    tele = FakeTelemetry()
    mon = HealthMonitor(telemetry=tele, on_nan="warn")
    mon.observe(_healthy_row())
    mon.observe(_healthy_row(**{"health/nonfinite": 12.0}))
    assert mon.fault_counts == {"nonfinite": 1}
    faults = [e for e in tele.events if e["event"] == "health_fault"]
    assert len(faults) == 1
    assert faults[0]["kind"] == "nonfinite"
    assert faults[0]["policy"] == "warn"
    assert faults[0]["count"] == 12

    tele = FakeTelemetry()
    mon = HealthMonitor(telemetry=tele, on_nan="halt")
    with pytest.raises(HealthFault) as e:
        mon.observe(_healthy_row(**{"loss_G/total": float("nan")}))
    assert e.value.kind == "nonfinite"
    # The stream is flushed BEFORE the raise: the fault record must
    # survive the process dying on the way out.
    assert tele.flushed == 1
    assert tele.events[-1]["event"] == "health_fault"
    assert tele.events[-1]["policy"] == "halt"


def test_nonfinite_tripwire_rejects_bad_policy():
    with pytest.raises(ValueError):
        HealthMonitor(on_nan="explode")


def test_divergence_detector_fires_after_warmup_once_per_epoch():
    # A spike INSIDE warmup never fires: the detector arms only after
    # divergence_warmup rows of EMA history.
    cold = HealthMonitor(divergence_multiple=4.0)
    for _ in range(cold.divergence_warmup - 1):
        cold.observe(_healthy_row())
    cold.observe(_healthy_row(**{"loss_G/total": 100.0}))
    assert cold.fault_counts.get("divergence", 0) == 0

    tele = FakeTelemetry()
    mon = HealthMonitor(telemetry=tele, divergence_multiple=4.0)
    for _ in range(mon.divergence_warmup + 5):
        mon.observe(_healthy_row())
    # The EMA sits at 3.0; a 4x excursion fires exactly once per epoch
    # per key even if it persists.
    mon.observe(_healthy_row(**{"loss_G/total": 50.0}))
    mon.observe(_healthy_row(**{"loss_G/total": 50.0}))
    assert mon.fault_counts == {"divergence": 1}
    fault = [e for e in tele.events if e["event"] == "health_fault"][0]
    assert fault["kind"] == "divergence" and fault["key"] == "loss_G/total"
    # Next epoch re-arms the once-per-epoch latch.
    mon.epoch_rollup()
    mon.begin_epoch(1)
    mon.observe(_healthy_row(**{"loss_G/total": 80.0}))
    assert mon.fault_counts == {"divergence": 2}


def test_collapse_detector_needs_patience_and_fires_once():
    tele = FakeTelemetry()
    mon = HealthMonitor(telemetry=tele, collapse_eps=0.05,
                        collapse_patience=5)
    saturated = {
        "health/dX_real_mean": 0.99, "health/dX_fake_mean": 0.01,
        "health/dX_real_std": 0.01, "health/dX_fake_std": 0.01,
    }
    for _ in range(4):
        mon.observe(_healthy_row(**saturated))
    assert mon.fault_counts.get("d_collapse", 0) == 0
    mon.observe(_healthy_row(**saturated))  # 5th consecutive: fires
    mon.observe(_healthy_row(**saturated))  # latched: no refire
    assert mon.fault_counts == {"d_collapse": 1}
    fault = [e for e in tele.events if e["event"] == "health_fault"][0]
    assert fault["side"] == "dX"
    # A healthy row breaks the streak and resets the latch.
    mon.observe(_healthy_row())
    for _ in range(5):
        mon.observe(_healthy_row(**saturated))
    assert mon.fault_counts == {"d_collapse": 2}


def test_epoch_rollup_event_and_flat_summary():
    tele = FakeTelemetry()
    mon = HealthMonitor(telemetry=tele)
    mon.begin_epoch(3)
    mon.observe(_healthy_row(**{"health/gnorm_G": 0.5}))
    mon.observe(_healthy_row(**{"health/gnorm_G": 1.5}))
    flat = mon.epoch_rollup()
    ev = [e for e in tele.events if e["event"] == "health"][0]
    assert ev["epoch"] == 3 and ev["rows"] == 2
    assert ev["gnorm"]["G"] == {"min": 0.5, "mean": 1.0, "max": 1.5}
    assert ev["loss"]["loss_G/total"] == pytest.approx(3.0)
    assert ev["disc"]["dX"]["real_mean"] == pytest.approx(0.6)
    assert ev["anomalies"] == {} and ev["nonfinite_rows"] == 0
    assert flat["gnorm_G"] == pytest.approx(1.0)
    assert flat["dY_fake_mean"] == pytest.approx(0.4)
    # Rollup resets the epoch accumulators.
    mon.begin_epoch(4)
    assert mon.epoch_rollup() == {}


def test_observe_unstacks_fused_multi_step_rows():
    """A fused K-step dispatch fetches [K]-stacked metric arrays; the
    monitor must see K individual rows."""
    mon = HealthMonitor()
    stacked = {k: np.array([v, v, v]) for k, v in _healthy_row().items()}
    stacked["health/nonfinite"] = np.array([0.0, 3.0, 0.0])
    mon.observe(stacked, steps=3)
    assert mon._row == 3
    assert mon.fault_counts == {"nonfinite": 1}


def test_make_health_monitor_respects_config():
    assert make_health_monitor(ObsConfig(health=False)) is None
    mon = make_health_monitor(
        ObsConfig(on_nan="halt", divergence_multiple=6.0,
                  collapse_eps=0.1, collapse_patience=9),
        primary=False,
    )
    assert mon.on_nan == "halt"
    assert mon.divergence_multiple == 6.0
    assert mon.collapse_eps == 0.1 and mon.collapse_patience == 9
    assert mon.echo is None  # non-primary hosts detect silently


# ------------------------------------------------- loop integration


def _loop_setup(config, devices, gb=4):
    from cyclegan_tpu.data import build_data
    from cyclegan_tpu.parallel import make_mesh_plan, shard_train_step
    from cyclegan_tpu.parallel.mesh import replicated
    from cyclegan_tpu.train import loop

    plan = make_mesh_plan(config.parallel, devices[:4])
    data = build_data(config, gb)
    state = jax.device_put(create_state(config, jax.random.PRNGKey(0)),
                           replicated(plan))
    step = shard_train_step(plan, make_train_step(config, gb))
    return loop, plan, data, state, step


def test_loop_feeds_monitor_without_extra_dispatches(tiny_config, devices,
                                                     tmp_path):
    """The monitor sees every train step through the loop's existing
    fetch sites, and the dispatch count is EXACTLY the step count — the
    health layer adds no dispatches and no fetches (the no-sync check
    pins the no-added-sync half: tools/check_no_sync.py scans obs/)."""
    from cyclegan_tpu.utils.summary import NullSummary

    loop, plan, data, state, step = _loop_setup(tiny_config, devices)
    path = str(tmp_path / "t.jsonl")
    tele = make_telemetry(ObsConfig(jsonl_path=path), str(tmp_path))
    mon = HealthMonitor(telemetry=tele)
    mon.begin_epoch(0)
    loop.train_epoch(tiny_config, data, plan, step, state, NullSummary(),
                     epoch=0, obs=tele, health=mon)
    assert mon._row == data.train_steps
    mon.epoch_rollup(0)
    tele.close()

    evs = [json.loads(l) for l in open(path) if l.strip()]
    agg = [e for e in evs if e["event"] == "epoch_steps"][0]
    assert agg["n_dispatches"] == data.train_steps
    health = [e for e in evs if e["event"] == "health"]
    assert len(health) == 1 and health[0]["rows"] == data.train_steps
    assert set(health[0]["gnorm"]) == set(NETWORKS)
    assert not [e for e in evs if e["event"] == "health_fault"]


def test_rollback_policy_no_fault_path_adds_no_dispatches(
        tiny_config, devices, tmp_path):
    """The resilience stack must be free when nothing fails: with
    on_nan='rollback' AND an armed injector whose fault never fires,
    the StepClock dispatch count stays EXACTLY the step count — same
    pin as the health layer's, extended over the rollback path (the
    no-sync half is tools/check_no_sync.py scanning resil/)."""
    from cyclegan_tpu.resil import FaultInjector
    from cyclegan_tpu.utils.summary import NullSummary

    loop, plan, data, state, step = _loop_setup(tiny_config, devices)
    path = str(tmp_path / "t.jsonl")
    tele = make_telemetry(ObsConfig(jsonl_path=path), str(tmp_path))
    mon = HealthMonitor(telemetry=tele, on_nan="rollback")
    injector = FaultInjector.from_spec("nan_grads@step=100000",
                                       telemetry=tele)
    mon.begin_epoch(0)
    loop.train_epoch(tiny_config, data, plan, step, state, NullSummary(),
                     epoch=0, obs=tele, health=mon, injector=injector)
    mon.epoch_rollup(0)
    tele.close()

    evs = [json.loads(l) for l in open(path) if l.strip()]
    agg = [e for e in evs if e["event"] == "epoch_steps"][0]
    assert agg["n_dispatches"] == data.train_steps
    assert not [e for e in evs if e["event"] == "health_fault"]
    assert not [e for e in evs if e["event"] == "fault_injected"]
    assert not [e for e in evs if e["event"] == "retry"]


def test_loop_nan_injection_halts_within_fetch_horizon(tiny_config, devices,
                                                       tmp_path):
    """Poisoned params under on_nan='halt': train_epoch raises
    HealthFault (within the deferred-fetch horizon — i.e. during the
    epoch, not after it), and the flushed stream carries the
    health_fault record."""
    from cyclegan_tpu.utils.summary import NullSummary

    loop, plan, data, state, step = _loop_setup(tiny_config, devices)
    poisoned = state.replace(
        g_params=jax.tree.map(
            lambda a: jnp.full_like(a, jnp.nan), state.g_params
        )
    )
    path = str(tmp_path / "t.jsonl")
    tele = make_telemetry(ObsConfig(jsonl_path=path), str(tmp_path))
    mon = HealthMonitor(telemetry=tele, on_nan="halt")
    mon.begin_epoch(0)
    with pytest.raises(HealthFault) as e:
        loop.train_epoch(tiny_config, data, plan, step, poisoned,
                         NullSummary(), epoch=0, obs=tele, health=mon)
    assert e.value.kind == "nonfinite"
    # The fault record is on disk BEFORE close (the halt path flushes).
    evs = [json.loads(l) for l in open(path) if l.strip()]
    faults = [e for e in evs if e["event"] == "health_fault"]
    assert faults and faults[0]["kind"] == "nonfinite"
    assert faults[0]["policy"] == "halt"
    tele.close("health_fault")


def test_loop_nan_injection_warn_completes_epoch(tiny_config, devices,
                                                 capsys):
    """Same poison under the default warn policy: the epoch completes,
    every row is flagged, and the console carries one echo line (not
    one per step)."""
    from cyclegan_tpu.utils.summary import NullSummary

    loop, plan, data, state, step = _loop_setup(tiny_config, devices)
    poisoned = state.replace(
        g_params=jax.tree.map(
            lambda a: jnp.full_like(a, jnp.nan), state.g_params
        )
    )
    mon = HealthMonitor(on_nan="warn", echo=print)
    mon.begin_epoch(0)
    loop.train_epoch(tiny_config, data, plan, step, poisoned,
                     NullSummary(), epoch=0, health=mon)
    assert mon.fault_counts["nonfinite"] == data.train_steps
    assert capsys.readouterr().out.count("health:") == 1
    flat = mon.epoch_rollup(0)
    assert "gnorm_G" in flat and math.isnan(flat["gnorm_G"])


def test_main_on_nan_halt_exits_3_with_stream_record(tmp_path):
    """The CLI-level halt contract: a NaN reaching the monitor under
    --on_nan halt makes `python main.py` exit 3 (not 0, not a crash),
    with the health_fault record flushed and the end event carrying
    status=health_fault. train_epoch is stubbed to feed the monitor one
    poisoned row, so the test exercises exactly main.py's wiring (flag
    -> config -> monitor -> except HealthFault) without paying a train
    compile."""
    import subprocess

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "run"
    driver = tmp_path / "driver.py"
    driver.write_text(
        "import runpy, sys\n"
        "from cyclegan_tpu.train import loop\n"
        "def poisoned(config, data, plan, step_fn, state, summary, epoch,"
        " **kw):\n"
        "    kw['health'].observe({'loss_G/total': float('nan')})\n"
        "    return state\n"
        "loop.train_epoch = poisoned\n"
        f"sys.argv = ['main.py', '--output_dir', {str(out)!r},\n"
        "            '--epochs', '1', '--batch_size', '2', '--verbose', '0',\n"
        "            '--data_source', 'synthetic', '--image_size', '32',\n"
        "            '--filters', '8', '--residual_blocks', '1',\n"
        "            '--synthetic_train_size', '4',\n"
        "            '--synthetic_test_size', '2', '--on_nan', 'halt']\n"
        "runpy.run_path('main.py', run_name='__main__')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run([sys.executable, str(driver)], cwd=repo, env=env,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 3, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "HEALTH FAULT (nonfinite)" in r.stdout
    assert "last-good checkpoint intact" in r.stdout
    evs = [json.loads(l)
           for l in open(out / "telemetry.jsonl") if l.strip()]
    assert any(e["event"] == "health_fault" and e["policy"] == "halt"
               for e in evs)
    assert evs[-1]["event"] == "end"
    assert evs[-1]["status"] == "health_fault"


# ------------------------------------------------- console summary


def test_print_epoch_summary_health_line(capsys):
    from cyclegan_tpu.train import loop

    results = {"error/MAE(X, F(G(X)))": 0.25}
    # health=None reproduces the historical output exactly.
    loop.print_epoch_summary(results, elapse=1.0)
    base = capsys.readouterr().out
    assert "grad-norm" not in base

    loop.print_epoch_summary(
        results, elapse=1.0,
        health={"gnorm_G": 1.25, "gnorm_F": 0.5, "gnorm_dX": 0.25,
                "gnorm_dY": 0.125, "dX_real_mean": 0.61,
                "dX_fake_mean": 0.39, "dY_real_mean": 0.55,
                "dY_fake_mean": 0.45},
    )
    out = capsys.readouterr().out
    assert "grad-norm G/F/dX/dY: 1.25/0.5/0.25/0.125" in out
    assert "D(real)/D(fake) X: 0.61/0.39" in out
    assert "Y: 0.55/0.45" in out

    # Missing keys print as nan instead of raising (empty epoch).
    loop.print_epoch_summary(results, elapse=1.0, health={})
    assert "nan/nan/nan/nan" in capsys.readouterr().out
