"""Guard tools/cache_warm.py — the driver-window cache-readiness tool.

What must not drift (VERDICT r4 weak #6): the warm list must cover the
PROGRAM of every official bench config (a missing one means a 2-5 min
cold compile inside the driver's 480 s window), while deduplicating
configs that share an XLA program (pf = host-side staging only;
steps ≡ dispatch-k1). Compilation itself is a TPU job — these tests
never compile; the compile-path machinery they rely on
(lower+compile on the local_only AOT backend, persistent cache) is the
same one tools/aot_analyze.py exercises.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
sys.path.insert(0, REPO)

import cache_warm  # noqa: E402


def test_every_official_config_program_is_covered():
    import bench

    progs = cache_warm.official_programs()
    covered = {key for p in progs for key in p["covers"]}
    for c in bench.TPU_CONFIGS:
        assert bench._config_key(c) in covered, (
            f"{bench._config_key(c)} missing from the warm list — its "
            "cold compile would eat the driver's bench budget")


def test_autorun_sweep_rows_are_covered():
    # covered = owns a program OR rides one (scan:b16zero now dedups
    # into the official scan/bfloat16/b16/zero TPU_CONFIGS row)
    covered = {key for p in cache_warm.official_programs()
               for key in p["covers"]}
    for spec in ("scan:b16zero", "scan:b24zero", "scan:b16fused",
                 "scan:b16epi", "scan:b16fp", "scan:b16pb",
                 "scan:b16fppb", "accum:b1k8i512", "scan:b4k2i512",
                 "scan:b4k2zeroi512"):
        assert f"sweep {spec}" in covered


def test_shared_programs_deduplicated():
    import bench

    progs = cache_warm.official_programs()
    # the pf config must NOT be a separate compile: same XLA program as
    # dispatch k8 (bench.bench_dispatch prefetch docstring)
    pf_key = bench._config_key(
        {"mode": "dispatch", "dtype": "bfloat16", "batch": 16, "k": 8,
         "prefetch": True})
    owners = [p for p in progs if pf_key in p["covers"]]
    assert len(owners) == 1
    assert owners[0]["key"] != pf_key  # it rides the earlier k8 program
    # scan b16 (k=8) and dispatch b16 k8 share the fused program too
    scan_key = bench._config_key(
        {"mode": "scan", "dtype": "bfloat16", "batch": 16})
    k8_key = bench._config_key(
        {"mode": "dispatch", "dtype": "bfloat16", "batch": 16, "k": 8})
    owner = [p for p in progs if scan_key in p["covers"]][0]
    assert k8_key in owner["covers"]


def test_absent_axon_writes_report_and_check_fails(tmp_path, monkeypatch):
    """With no axon plugin, --check must FAIL (readiness unverifiable)
    and a fresh report must be written anyway — otherwise a stale prior
    container's report would masquerade as this run's evidence
    (code-review r5 finding)."""
    import json

    import cyclegan_tpu.utils.axon_compat as axon_compat

    monkeypatch.setattr(axon_compat, "register_axon_local",
                        lambda **kw: False)
    report = tmp_path / "report.json"
    monkeypatch.setattr(cache_warm, "REPORT_PATH", str(report))
    assert cache_warm.main(["--check"]) == 1
    rec = json.loads(report.read_text())
    assert rec["axon_plugin"] == "absent" and rec["programs"] == []
    # warm mode on a CPU box is a harmless no-op, not a failure
    assert cache_warm.main([]) == 0


def test_list_mode_needs_no_axon(tmp_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "cache_warm.py"),
         "--list"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=180)
    assert r.returncode == 0, r.stderr
    assert "scan/bfloat16/b16" in r.stdout
    assert "sweep accum:b1k8i512" in r.stdout
