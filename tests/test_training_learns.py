"""Training dynamics smoke test: the system actually optimizes.

Gradient-structure tests (test_steps, test_torch_parity) prove the step
computes the right gradients; this proves the assembled system — data,
losses, four Adams at the reference's lr=2e-4/b1=0.5/b2=0.9 — moves the
networks in the right direction. The DISCRIMINATOR objective is the
probe: separating real images from the near-constant outputs of freshly
initialized generators is easy, so `loss_X + loss_Y` must fall fast
(measured: 1.00 -> ~0.62 in 120 steps). Reconstruction losses are NOT
asserted: with the reference's IN-gamma ~ N(0, 0.02) init the signal
path is crushed and cycle/identity improvement takes thousands of steps
— far beyond a test budget. Deterministic (fixed seed, CPU), so not
flaky.
"""

import jax
import numpy as np

from cyclegan_tpu.train import create_state, make_train_step


def test_discriminator_losses_decrease(tiny_config):
    config = tiny_config
    batch = 4
    step = jax.jit(make_train_step(config, batch))
    state = create_state(config, jax.random.PRNGKey(3))

    rng = np.random.RandomState(3)
    s = config.model.image_size
    # Fixed small dataset of 2 batches, cycled.
    data = [
        (
            (rng.rand(batch, s, s, 3).astype(np.float32) * 2 - 1),
            (rng.rand(batch, s, s, 3).astype(np.float32) * 2 - 1),
        )
        for _ in range(2)
    ]
    w = np.ones((batch,), np.float32)

    history = []
    for i in range(120):
        x, y = data[i % len(data)]
        state, metrics = step(state, x, y, w)
        m = jax.device_get(metrics)
        history.append(float(m["loss_X/loss"]) + float(m["loss_Y/loss"]))

    early = np.mean(history[:5])
    late = np.mean(history[-5:])
    assert np.isfinite(history).all()
    assert late < 0.8 * early, (
        f"discriminator losses did not improve: early {early:.4f} -> late {late:.4f}"
    )
