"""spatial_impl="halo" vs the XLA-SPMD spatial path.

The explicit-halo backend (parallel/halo.py via models.modules.HaloConv)
must be a drop-in for the partitioner-driven path: same param tree (so
checkpoints interchange across --spatial_impl), and forward + backward
agreement <= 1e-5 on a real mesh — the halo exchanges it states in user
code are exactly the collectives XLA would have synthesized.

Mesh geometry: 4x2 (data x spatial) over the 8 virtual CPU devices. At
the tiny 32^2 size the discriminator's stride-1 4x4 sites see H=4, so
n_spatial=2 is the deepest sharding its (1, 2) asymmetric halo supports
(H_local=2 >= hi=2); the generator trunk's 3x3 reflect sites have H=8
there and are unconstrained.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu.config import Config, ModelConfig, ParallelConfig
from cyclegan_tpu.parallel import make_mesh_plan, shard_batch
from cyclegan_tpu.parallel.mesh import replicated
from cyclegan_tpu.train import build_models, create_state
from cyclegan_tpu.train.steps import make_grad_fn


def _cfg(tiny_config, spatial_impl):
    return tiny_config.replace(
        model=dataclasses.replace(tiny_config.model, spatial_impl=spatial_impl),
        parallel=ParallelConfig(spatial_parallelism=2),
    )


def _batch(gb, size=32, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(gb, size, size, 3).astype(np.float32) * 2 - 1
    y = rng.rand(gb, size, size, 3).astype(np.float32) * 2 - 1
    return x, y, np.ones((gb,), np.float32)


def _tree_close(a, b, atol, what):
    flat_a = jax.tree_util.tree_leaves_with_path(a)
    flat_b = jax.tree.leaves(b)
    assert len(flat_a) == len(flat_b)
    for (path, la), lb in zip(flat_a, flat_b):
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), atol=atol, rtol=0,
            err_msg=f"{what}: {jax.tree_util.keystr(path)}",
        )


def test_config_rejects_unknown_spatial_impl():
    with pytest.raises(ValueError, match="spatial_impl"):
        ModelConfig(spatial_impl="ring")


@pytest.mark.parametrize("pad_impl", ["fused", "epilogue"])
def test_config_rejects_halo_with_fused_pads(pad_impl):
    with pytest.raises(ValueError, match="spatial_impl='halo'"):
        ModelConfig(spatial_impl="halo", pad_impl=pad_impl)


def test_param_trees_identical_across_impls(tiny_config, devices):
    """Same init key -> bit-identical param trees under both impls: the
    checkpoint-interchange contract is structural, not approximate."""
    cfg_x = _cfg(tiny_config, "xla")
    cfg_h = _cfg(tiny_config, "halo")
    plan = make_mesh_plan(cfg_h.parallel, devices)
    gen_x, disc_x = build_models(cfg_x, plan)
    gen_h, disc_h = build_models(cfg_h, plan)
    dummy = jnp.zeros((1, 32, 32, 3), jnp.float32)
    key = jax.random.PRNGKey(3)
    for mx, mh in ((gen_x, gen_h), (disc_x, disc_h)):
        px, ph = mx.init(key, dummy), mh.init(key, dummy)
        assert jax.tree_util.tree_structure(px) == (
            jax.tree_util.tree_structure(ph)
        )
        _tree_close(px, ph, 0.0, "init params")


def test_forward_parity_on_mesh(tiny_config, devices):
    cfg_h = _cfg(tiny_config, "halo")
    plan = make_mesh_plan(cfg_h.parallel, devices)
    gen_x, disc_x = build_models(_cfg(tiny_config, "xla"), plan)
    gen_h, disc_h = build_models(cfg_h, plan)
    gb = plan.n_data * 2
    x, _, _ = _batch(gb)
    for mod_x, mod_h in ((gen_x, gen_h), (disc_x, disc_h)):
        params = jax.device_put(
            mod_x.init(jax.random.PRNGKey(0), x[:1]), replicated(plan)
        )
        xs = jax.device_put(
            x, jax.sharding.NamedSharding(plan.mesh, plan.batch_spec())
        )
        out_x = jax.jit(mod_x.apply)(params, xs)
        out_h = jax.jit(mod_h.apply)(params, xs)
        np.testing.assert_allclose(
            np.asarray(out_x), np.asarray(out_h), atol=1e-5, rtol=0
        )


def test_grad_parity_on_mesh(tiny_config, devices):
    """Backward parity: the four per-network gradient trees from the
    fused step agree <= 1e-5 between impls, with ONE shared state (a
    checkpoint written under either impl trains under the other)."""
    cfg_x = _cfg(tiny_config, "xla")
    cfg_h = _cfg(tiny_config, "halo")
    plan = make_mesh_plan(cfg_h.parallel, devices)
    gb = plan.n_data * cfg_x.train.batch_size
    state = jax.device_put(
        create_state(cfg_x, jax.random.PRNGKey(0)), replicated(plan)
    )
    xs, ys, ws = shard_batch(plan, *_batch(gb))
    params = (state.g_params, state.f_params, state.dx_params, state.dy_params)
    grads_x, metrics_x = jax.jit(make_grad_fn(cfg_x, gb, plan))(
        *params, xs, ys, ws
    )
    grads_h, metrics_h = jax.jit(make_grad_fn(cfg_h, gb, plan))(
        *params, xs, ys, ws
    )
    _tree_close(grads_x, grads_h, 1e-5, "grads")
    for k in metrics_x:
        np.testing.assert_allclose(
            float(metrics_x[k]), float(metrics_h[k]), atol=1e-5, rtol=0,
            err_msg=k,
        )


def test_halo_not_engaged_without_spatial_axis(tiny_config, devices):
    """halo config on a pure-DP mesh (n_spatial=1) must fall back to the
    plain path — build_models only binds the mesh when there is a >1
    spatial axis to shard over."""
    cfg_h = tiny_config.replace(
        model=dataclasses.replace(tiny_config.model, spatial_impl="halo"),
        parallel=ParallelConfig(spatial_parallelism=1),
    )
    plan = make_mesh_plan(cfg_h.parallel, devices)
    gen, disc = build_models(cfg_h, plan)
    assert gen.halo_mesh is None and disc.halo_mesh is None
