"""Relay-socket probing and local-compile gating in bench.py.

The axon loopback relay (docs/TUNNEL_POSTMORTEM.md) carries every
terminal leg; jax.devices() succeeds even with the relay dead (device
list synthesized from the AOT topology), so bench.py's probe gates on
the relay SOCKETS. These tests pin that gate's semantics: which ports
each mode requires, what a non-relay environment looks like, and that
the status reader reports real listeners as open.
"""

import os
import socket
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def _clear_env(monkeypatch):
    for var in ("AXON_LOOPBACK_RELAY", "PALLAS_AXON_POOL_IPS",
                "PALLAS_AXON_REMOTE_COMPILE", "CYCLEGAN_AXON_LOCAL_COMPILE"):
        monkeypatch.delenv(var, raising=False)


def test_status_none_outside_relay_env(monkeypatch):
    _clear_env(monkeypatch)
    assert bench._relay_ports_status() is None
    assert bench._relay_ok(None) is True


def test_status_reports_refused_ports(monkeypatch):
    _clear_env(monkeypatch)
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    status = bench._relay_ports_status()
    assert status is not None and set(status) == {8082, 8083, 8093}
    # Every port gets a definite state string (open/refused/errno name).
    assert all(isinstance(v, str) and v for v in status.values())


def test_status_sees_real_listener(monkeypatch):
    """A live listener on one relay port must be reported 'open'."""
    _clear_env(monkeypatch)
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    srv = socket.socket()
    # Before the try: the finally below iterates it, and pytest.skip on a
    # failed bind() would otherwise reach it unbound (UnboundLocalError
    # masking the skip).
    accepted = []
    try:
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind(("127.0.0.1", 8093))
        except OSError:
            import pytest

            pytest.skip("port 8093 unavailable in this environment")
        srv.listen(4)

        def accept_loop():
            try:
                while True:
                    c, _ = srv.accept()
                    accepted.append(c)
            except OSError:
                pass

        t = threading.Thread(target=accept_loop, daemon=True)
        t.start()
        status = bench._relay_ports_status()
        assert status[8093] == "open"
    finally:
        srv.close()
        for c in accepted:
            c.close()


def test_relay_ok_remote_compile_requires_8093_and_8082(monkeypatch):
    _clear_env(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_REMOTE_COMPILE", "1")
    ok = {8082: "open", 8083: "open", 8093: "open"}
    assert bench._relay_ok(ok) is True
    assert bench._relay_ok({**ok, 8093: "refused"}) is False
    assert bench._relay_ok({**ok, 8082: "refused"}) is False
    # stateless leg not required for the bench's measurement path
    assert bench._relay_ok({**ok, 8083: "refused"}) is True


def test_relay_ok_local_compile_skips_8093(monkeypatch):
    """Under the local-compile workaround the remote-compile service is
    not needed: claim (:8082) + stateless (:8083) suffice."""
    _clear_env(monkeypatch)
    monkeypatch.setenv("PALLAS_AXON_REMOTE_COMPILE", "1")
    monkeypatch.setenv("CYCLEGAN_AXON_LOCAL_COMPILE", "1")
    up_except_compile = {8082: "open", 8083: "open", 8093: "refused"}
    assert bench._relay_ok(up_except_compile) is True
    assert bench._relay_ok({**up_except_compile, 8082: "refused"}) is False
    assert bench._relay_ok({**up_except_compile, 8083: "refused"}) is False


def test_ensure_local_compile_noop_without_request(monkeypatch):
    _clear_env(monkeypatch)
    from cyclegan_tpu.utils import axon_compat

    assert axon_compat.local_compile_requested() is False
    assert axon_compat.ensure_local_compile() is False


def test_register_axon_local_guards_frozen_registration(monkeypatch):
    """With the sitecustomize's env still present, registering a second
    (different) backend config would hit the process-wide OnceLock —
    the helper must refuse up front with actionable guidance."""
    from cyclegan_tpu.utils import axon_compat

    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "127.0.0.1")
    import pytest

    with pytest.raises(RuntimeError, match="PALLAS_AXON_POOL_IPS"):
        axon_compat.register_axon_local(local_only=True)


def test_warn_if_relay_down_noop_on_cpu(monkeypatch):
    from cyclegan_tpu.utils import axon_compat

    _clear_env(monkeypatch)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    lines = []
    assert axon_compat.warn_if_relay_down(print_fn=lines.append) is True
    assert lines == []


def test_warn_if_relay_down_diagnoses_dead_relay(monkeypatch):
    from cyclegan_tpu.utils import axon_compat

    _clear_env(monkeypatch)
    monkeypatch.setenv("JAX_PLATFORMS", "axon")
    monkeypatch.setenv("AXON_LOOPBACK_RELAY", "1")
    lines = []
    viable = axon_compat.warn_if_relay_down(print_fn=lines.append)
    status = axon_compat.relay_ports_status()
    if axon_compat.relay_ok(status):
        assert viable is True and lines == []  # relay healthy in this env
    else:
        assert viable is False
        assert len(lines) == 1 and "relay" in lines[0]
        assert "TUNNEL_POSTMORTEM" in lines[0]


def test_cli_startup_is_safe_without_axon_request(monkeypatch):
    """cli_startup must be a no-op (no registration, no raise) in the
    plain CPU test environment."""
    from cyclegan_tpu.utils import axon_compat

    _clear_env(monkeypatch)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    axon_compat.cli_startup()  # must not raise
