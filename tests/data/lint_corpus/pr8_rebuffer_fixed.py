# Lint corpus: the PR-8 pattern, post-fix — restored state is
# deep-copied into XLA-owned buffers (checkpoint._rebuffer) before the
# donating step ever sees it. Must analyze clean.
import jax
import jax.numpy as jnp


def _rebuffer(tree):
    return jax.tree.map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, tree)


def resume_and_train(ckptr, slot, abstract, data, train_step):
    state = ckptr.restore(slot, abstract)
    state = _rebuffer(state)  # XLA owns every leaf from here on
    step = jax.jit(train_step, donate_argnums=(0,))
    for x, y in data:
        state, metrics = step(state, x, y)
    return state
