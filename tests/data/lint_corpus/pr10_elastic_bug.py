# Lint corpus: the PR-10 elastic-reshard donation bug, pre-fix shape
# (condensed from resil/elastic.py reshard_to_plan).
#
# The reshard path gathered each restored leaf to host and device_put
# it under the new mesh's sharding. On CPU BOTH hops can be ZERO-copy,
# so the "placed" array aliased the restored buffer — and the train
# step donates its state. Same heap corruption as PR-8, one
# abstraction higher. The donation-aliasing rule must flag the step
# call below: device_put does not launder host-buffer taint.
import jax


def reshard_and_resume(leaves, treedef, sharding, data, train_step):
    out = []
    for leaf in leaves:
        host = jax.device_get(leaf)          # host gather (zero-copy on CPU)
        placed = jax.device_put(host, sharding)  # can alias `host`
        out.append(placed)                   # BUG: no jnp.copy
    state = jax.tree_util.tree_unflatten(treedef, out)
    step = jax.jit(train_step, donate_argnums=(0,))
    for x, y in data:
        state, metrics = step(state, x, y)   # donates the aliased buffer
    return state
