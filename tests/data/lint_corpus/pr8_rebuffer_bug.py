# Lint corpus: the PR-8 donation bug, pre-fix shape (condensed).
#
# utils/checkpoint.py restored state straight out of orbax and the
# train loop's jitted step donated it (donate_argnums=(0,)) — XLA wrote
# into buffers tensorstore still managed. Observed: every post-resume
# save NaN-corrupt (22k-250k bad elements), intermittent
# "malloc(): largebin double linked list corrupted" aborts.
# graftlint's donation-aliasing rule must flag the step call below.
import jax


def resume_and_train(ckptr, slot, abstract, data, train_step):
    state = ckptr.restore(slot, abstract)  # orbax-owned buffers
    step = jax.jit(train_step, donate_argnums=(0,))
    for x, y in data:
        state, metrics = step(state, x, y)  # donates the orbax buffer
    return state
