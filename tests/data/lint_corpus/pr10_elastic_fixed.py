# Lint corpus: the PR-10 elastic pattern, post-fix — every placed leaf
# routes through jnp.copy (an XLA computation), so the result is a
# genuinely XLA-owned buffer with the same sharding. Must analyze
# clean.
import jax
import jax.numpy as jnp


def reshard_and_resume(leaves, treedef, sharding, data, train_step):
    out = []
    for leaf in leaves:
        host = jax.device_get(leaf)
        placed = jax.device_put(host, sharding)
        out.append(jnp.copy(placed))  # load-bearing: defeats zero-copy alias
    state = jax.tree_util.tree_unflatten(treedef, out)
    step = jax.jit(train_step, donate_argnums=(0,))
    for x, y in data:
        state, metrics = step(state, x, y)
    return state
