"""Scale-out observatory (obs/comms.py + obs/goodput.py): the analytic
collective ledger pinned against REAL compiled HLO on 2x1 and 2x2 host
meshes, the HLO collective parser on synthetic programs, the goodput
phase math (exact wall-clock accounting), the telemetry wiring, and
the zero-extra-dispatch pin (a goodput-traced run performs exactly the
dispatches an untraced run does).

All CPU-runnable tier-1: the census compiles on virtual host devices
and reads program text; the ledger is pure host arithmetic.
"""

import json
import os
import sys

import jax
import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))
sys.path.insert(0, REPO)

from cyclegan_tpu.config import ObsConfig, ParallelConfig, tiny_test_config  # noqa: E402
from cyclegan_tpu.obs import GoodputLedger, MetricsLogger, make_telemetry  # noqa: E402
from cyclegan_tpu.obs.comms import (  # noqa: E402
    DISC_GRAD_SITES_PER_STEP,
    GEN_APPS_PER_STEP,
    RECON_TOLERANCE,
    analytic_census,
    build_census,
    data_axis_bytes,
    grad_tree_bytes,
    parse_hlo_collectives,
)
from cyclegan_tpu.obs.goodput import classify_pass, rollup_phases  # noqa: E402
from cyclegan_tpu.obs.telemetry import Telemetry  # noqa: E402
from cyclegan_tpu.parallel import make_mesh_plan, shard_train_step  # noqa: E402
from cyclegan_tpu.train import create_state, make_train_step  # noqa: E402


def _events(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _census_for_mesh(devices, n_devices, spatial, spatial_impl="xla"):
    """Compile the REAL sharded tiny train step (abstract avals, the
    dryrun stage-2 pattern) and census it against its own HLO."""
    import dataclasses

    par = ParallelConfig(spatial_parallelism=spatial)
    plan = make_mesh_plan(par, devices[:n_devices])
    cfg = tiny_test_config().replace(parallel=par)
    cfg = cfg.replace(
        model=dataclasses.replace(cfg.model, spatial_impl=spatial_impl))
    gb = plan.n_data * cfg.train.batch_size
    s = cfg.model.image_size
    state = jax.eval_shape(lambda: create_state(cfg, jax.random.PRNGKey(0)))
    step = shard_train_step(plan, make_train_step(cfg, gb, plan))
    img = jax.ShapeDtypeStruct((gb, s, s, 3), np.float32)
    w = jax.ShapeDtypeStruct((gb,), np.float32)
    hlo = step.lower(state, img, img, w).compile().as_text()
    return build_census(plan, cfg, gb, state, hlo_text=hlo, link_gbps=45.0)


# ------------------------------------------------- census vs real HLO


def test_census_reconciles_on_2x1_mesh(devices):
    """Pure data parallelism: the 3x(G+F) + 2x(DX+DY) per-site payload
    must match the compiled program's data-axis all-reduces tightly
    (residual: loss-scalar reduces), and no spatial axis exists."""
    census = _census_for_mesh(devices, 2, 1)
    assert census["ok"], census["reconciliation"]
    recon = census["reconciliation"]
    assert "data" in recon and "spatial" not in recon
    assert recon["data"]["error"] <= 0.01
    assert recon["data"]["measured_ops"] > 0
    assert census["analytic"]["spatial_bytes"] == 0.0


def test_census_reconciles_on_2x2_mesh(devices):
    """Both mesh axes live: data within 1%, spatial (halo + edge-site
    full reduces + ConvTranspose reshards + IN stats) within the 10%
    census tolerance."""
    census = _census_for_mesh(devices, 4, 2)
    assert census["ok"], census["reconciliation"]
    recon = census["reconciliation"]
    assert recon["data"]["error"] <= 0.01
    assert recon["spatial"]["error"] <= RECON_TOLERANCE
    # Spatial traffic is real on this mesh, not a vacuous 0==0 pass.
    assert recon["spatial"]["measured_bytes"] > 0
    assert census["measured"]["unknown_dtypes"] == []


def test_halo_census_reconciles_on_2x2_mesh(devices):
    """The halo impl restructures the ledger: explicit ppermute rows on
    the spatial axis, a mesh-wide kernel-psum axis from the shard_map
    transpose, and a data axis shrunk by exactly those kernel bytes.
    All three axes must reconcile against the compiled program."""
    census = _census_for_mesh(devices, 4, 2, spatial_impl="halo")
    assert census["ok"], census["reconciliation"]
    recon = census["reconciliation"]
    assert recon["data"]["error"] <= 0.05
    assert recon["spatial"]["error"] <= RECON_TOLERANCE
    # check_rep's replicated-cotangent reduction is structural, not
    # statistical: the mesh-wide bucket must be EXACTLY the halo
    # kernel bytes at data-axis multiplicities.
    assert recon["other"]["error"] == 0.0
    assert recon["other"]["measured_bytes"] > 0
    ana = census["analytic"]
    assert ana["spatial_impl"] == "halo"
    assert ana["spatial_terms"]["halo_exchange"] > 0
    assert ana["data_bytes"] + ana["mesh_bytes"] == data_axis_bytes(
        ana["grad_tree_bytes"])


def test_halo_spatial_traffic_below_xla(devices):
    """The point of the explicit halo impl: trading (k-1) boundary rows
    beats the partitioner's edge-site full-activation reduces. Both
    the analytic model and the measured programs must agree that the
    halo program moves strictly fewer spatial-axis bytes."""
    xla = _census_for_mesh(devices, 4, 2, spatial_impl="xla")
    halo = _census_for_mesh(devices, 4, 2, spatial_impl="halo")
    assert (halo["analytic"]["spatial_bytes"]
            < xla["analytic"]["spatial_bytes"])
    assert (halo["measured"]["axes"]["spatial"]["bytes"]
            < xla["measured"]["axes"]["spatial"]["bytes"])
    # total traffic (all axes) also drops
    def total(c):
        return sum(v["bytes"] for v in c["measured"]["axes"].values())
    assert total(halo) < total(xla)


def test_halo_analytic_falls_back_to_xla_without_spatial_axis():
    """spatial_impl='halo' with n_spatial == 1 compiles the plain path
    (HaloConv never engages), so the ledger must be the xla one."""
    import dataclasses

    par = ParallelConfig(spatial_parallelism=1)
    plan = make_mesh_plan(par, jax.devices()[:2])
    cfg = tiny_test_config().replace(parallel=par)
    cfg = cfg.replace(
        model=dataclasses.replace(cfg.model, spatial_impl="halo"))
    state = jax.eval_shape(lambda: create_state(cfg, jax.random.PRNGKey(0)))
    out = analytic_census(plan, cfg, 2 * plan.n_data, state)
    assert out["spatial_impl"] == "xla"
    assert out["mesh_bytes"] == 0.0
    assert out["data_bytes"] == data_axis_bytes(out["grad_tree_bytes"])


def test_analytic_multiplicities(tiny_config):
    """Data-axis payload counts gradient trees per application site:
    generators 3x (translate/cycle/identity), discriminators 2x
    (real + fake; the adversarial site stop-gradients D)."""
    state = jax.eval_shape(
        lambda: create_state(tiny_config, jax.random.PRNGKey(0)))
    trees = grad_tree_bytes(state)
    expected = (GEN_APPS_PER_STEP * (trees["g"] + trees["f"])
                + DISC_GRAD_SITES_PER_STEP * (trees["dx"] + trees["dy"]))
    assert data_axis_bytes(trees) == expected
    assert trees["g"] == trees["f"] and trees["dx"] == trees["dy"]


def test_analytic_census_axis_gating(devices):
    """n_data == 1 zeroes the data axis; n_spatial == 1 zeroes the
    spatial axis — an axis of extent 1 has no collectives."""
    par = ParallelConfig(spatial_parallelism=2)
    plan = make_mesh_plan(par, devices[:2])  # 1 data x 2 spatial
    cfg = tiny_test_config().replace(parallel=par)
    state = jax.eval_shape(lambda: create_state(cfg, jax.random.PRNGKey(0)))
    out = analytic_census(plan, cfg, cfg.train.batch_size, state)
    assert plan.n_data == 1
    assert out["data_bytes"] == 0
    assert out["spatial_bytes"] > 0


# ------------------------------------------------- HLO parser (pinned)

# dp=2 x sp=2 mesh, flat device id = d * sp + s.
_SYNTH_HLO = """\
HloModule synth
  %ar0 = f32[100]{0} all-reduce(f32[100]{0} %a), replica_groups={{0,2},{1,3}}, to_apply=%sum
  %ar1 = f32[50]{0} all-reduce(f32[50]{0} %b), replica_groups={{0,1},{2,3}}, to_apply=%sum
  %ag = f32[8,16]{1,0} all-gather(f32[4,16]{1,0} %c), replica_groups=[2,2]<=[4], dimensions={0}
  %ar2 = f32[10]{0} all-reduce-start(f32[10]{0} %d), replica_groups=[2,2]<=[2,2]T(1,0), to_apply=%sum
  %cp = f32[10]{0} collective-permute(f32[10]{0} %e), source_target_pairs={{0,1},{1,0},{2,3},{3,2}}
  %cpx = f32[10]{0} collective-permute(f32[10]{0} %f), source_target_pairs={{0,3},{3,0}}
  %weird = c64[5]{0} all-gather(c64[5]{0} %g), replica_groups={{0,1},{2,3}}, dimensions={0}
"""


def test_parse_hlo_synthetic_attribution():
    out = parse_hlo_collectives(_SYNTH_HLO, 2, 2)
    axes = out["axes"]
    # data: ar0 (groups fix i%sp) 400B + ar2 (iota transposed ->
    # [[0,2],[1,3]]) 40B.
    assert axes["data"] == {"bytes": 440, "ops": 2}
    # spatial: ar1 200B + ag (iota [[0,1],[2,3]], RESULT shape 8x16)
    # 512B + cp (all pairs within a dp row) 40B. The c64 all-gather's
    # bytes are excluded (unknown dtype) but the op still lands on its
    # axis with 0 bytes.
    assert axes["spatial"] == {"bytes": 752, "ops": 4}
    # cpx crosses both axes -> other.
    assert axes["other"] == {"bytes": 40, "ops": 1}
    assert out["unknown_dtypes"] == ["c64"]
    assert out["by_kind"]["all-reduce:data"]["ops"] == 2
    assert out["by_kind"]["collective-permute:spatial"]["ops"] == 1


def test_build_census_failure_and_analytic_only(devices):
    par = ParallelConfig(spatial_parallelism=1)
    plan = make_mesh_plan(par, devices[:2])
    cfg = tiny_test_config().replace(parallel=par)
    gb = plan.n_data * cfg.train.batch_size
    state = jax.eval_shape(lambda: create_state(cfg, jax.random.PRNGKey(0)))
    # Analytic-only census (no HLO): no verdict, but a per-link model
    # and a collective-seconds estimate for the goodput ledger.
    ana = build_census(plan, cfg, gb, state, link_gbps=45.0)
    assert "reconciliation" not in ana and "ok" not in ana
    assert ana["per_link"]["data_allreduce_bytes"] > 0
    assert ana["est_step_comms_s"] > 0
    # A program whose collectives do NOT match (one tiny all-reduce)
    # must fail reconciliation — this is the chip_autorun abort path.
    bad_hlo = ("  %ar = f32[10]{0} all-reduce(f32[10]{0} %a), "
               "replica_groups={{0,1}}, to_apply=%sum\n")
    bad = build_census(plan, cfg, gb, state, hlo_text=bad_hlo)
    assert not bad["ok"]
    assert bad["max_recon_error"] > RECON_TOLERANCE


# ------------------------------------------------- goodput phase math


def test_classify_pass_pinned():
    agg = {"wall_s": 10.0, "stage_s": 1.0, "dispatch_s": 2.0,
           "fetch_block_s": 3.0, "drain_s": 0.5, "host_work_s": 0.5,
           "dispatch0_s": 1.1, "n_dispatches": 10, "n_steps": 20}
    ph = classify_pass(agg)
    # steady dispatch = (2.0 - 1.1) / 9 = 0.1; compile = 1.1 - 0.1.
    assert ph["compile"] == pytest.approx(1.0)
    assert ph["compute"] == pytest.approx(3.5)  # fetch + drain
    assert ph["data_wait"] == pytest.approx(1.0)
    # host = steady dispatch (1.0) + host_work (0.5) + wall residue
    # (10 - 1 - 2 - 3 - 0.5 - 0.5 = 3.0).
    assert ph["host"] == pytest.approx(4.5)
    total = ph["compute"] + ph["data_wait"] + ph["host"] + ph["compile"]
    assert total == pytest.approx(agg["wall_s"])
    # Single-dispatch pass: all of dispatch 0 is the compile estimate.
    one = classify_pass({"wall_s": 2.0, "dispatch_s": 1.5,
                         "dispatch0_s": 1.5, "n_dispatches": 1,
                         "n_steps": 1})
    assert one["compile"] == pytest.approx(1.5)


def test_rollup_sums_to_elapse_exactly():
    passes = [classify_pass({"wall_s": 10.0, "stage_s": 1.0,
                             "dispatch_s": 2.0, "fetch_block_s": 3.0,
                             "drain_s": 0.5, "host_work_s": 0.5,
                             "dispatch0_s": 1.1, "n_dispatches": 10,
                             "n_steps": 20}),
              classify_pass({"wall_s": 4.0, "fetch_block_s": 2.0,
                             "dispatch_s": 1.0, "dispatch0_s": 0.1,
                             "n_dispatches": 10, "n_steps": 10})]
    out = rollup_phases(passes, service_s=2.0, elapse_s=20.0)
    assert sum(out["phases_s"].values()) == pytest.approx(20.0)
    assert sum(out["phase_fractions"].values()) == pytest.approx(1.0, abs=1e-4)
    assert out["goodput_fraction"] == out["phase_fractions"]["compute"]
    assert out["n_steps"] == 30 and out["n_passes"] == 2
    # Badput census is sorted most-expensive-first and excludes compute.
    badput = list(out["badput"].values())
    assert badput == sorted(badput, reverse=True)
    assert "compute" not in out["badput"]
    # Services fit the epoch remainder here: nothing overlapped.
    assert out["phases_s"]["services"] == pytest.approx(2.0)
    assert out["service_overlap_s"] == 0.0


def test_rollup_service_overlap_and_collective_carve():
    passes = [classify_pass({"wall_s": 8.0, "fetch_block_s": 6.0,
                             "dispatch_s": 1.0, "stage_s": 1.0,
                             "dispatch0_s": 0.1, "n_dispatches": 10,
                             "n_steps": 10})]
    # Epoch barely longer than the pass: a 5s service job mostly
    # overlapped device time and must NOT inflate the ledger past
    # elapse — the excess is reported separately.
    out = rollup_phases(passes, service_s=5.0, elapse_s=9.0)
    assert sum(out["phases_s"].values()) == pytest.approx(9.0)
    assert out["phases_s"]["services"] == pytest.approx(1.0)
    assert out["service_overlap_s"] == pytest.approx(4.0)
    # Census-informed collective share is carved OUT of compute and
    # bounded by it.
    carved = rollup_phases(passes, 0.0, 9.0, comms_s_per_step=0.2)
    assert carved["phases_s"]["collective"] == pytest.approx(2.0)
    assert carved["phases_s"]["compute"] == pytest.approx(4.0)
    assert sum(carved["phases_s"].values()) == pytest.approx(9.0)
    bounded = rollup_phases(passes, 0.0, 9.0, comms_s_per_step=10.0)
    assert bounded["phases_s"]["collective"] == pytest.approx(6.0)
    assert bounded["phases_s"]["compute"] == 0.0


def test_ledger_empty_window_emits_nothing():
    led = GoodputLedger()
    assert led.rollup(0, 5.0) is None
    led.note_service(0.25)
    assert led.rollup(1, 5.0) is not None
    # The window reset: the next epoch is empty again.
    assert led.rollup(2, 5.0) is None


# ------------------------------------------------- telemetry wiring


def test_goodput_rides_telemetry_events(tmp_path):
    """The ledger is fed entirely by Telemetry: StepClock on_finish,
    service_job interception, census est pickup — and the `goodput`
    event trails the `epoch` event with fractions summing to 1."""
    path = str(tmp_path / "t.jsonl")
    tele = Telemetry(MetricsLogger(path), goodput=GoodputLedger())
    clock = tele.step_clock(0)
    clock.stage_begin(); clock.staged()
    clock.dispatched(steps=2, kind="multi")
    clock.fetched(0.01, steps=2)
    clock.finish()
    tele.event("service_job", job="checkpoint:e0", seconds=0.5)
    tele.event("comms_census", est_step_comms_s=1e-4)
    tele.epoch(0, elapse_s=5.0, images_per_sec=1.0)
    # An epoch with no passes and no services stays ledger-free.
    tele.epoch(1, elapse_s=5.0, images_per_sec=1.0)
    tele.close()

    evs = _events(path)
    kinds = [e["event"] for e in evs]
    assert kinds.count("goodput") == 1
    assert "comms_census" in kinds  # interception still logs the event
    gp = evs[kinds.index("goodput")]
    assert gp["epoch"] == 0
    assert kinds.index("goodput") == kinds.index("epoch") + 1
    assert sum(gp["phase_fractions"].values()) == pytest.approx(1.0,
                                                                abs=1e-4)
    assert gp["comms_s_per_step"] == pytest.approx(1e-4)
    assert gp["phases_s"]["services"] + gp["service_overlap_s"] == \
        pytest.approx(0.5)


def test_traced_run_dispatches_exactly_like_untraced(tiny_config, devices,
                                                     tmp_path):
    """Zero-extra-dispatch pin: the goodput ledger classifies existing
    timestamps — a run with full telemetry performs EXACTLY the step
    dispatches of an obs=None run."""
    from cyclegan_tpu.data import build_data
    from cyclegan_tpu.parallel.mesh import replicated
    from cyclegan_tpu.train import loop
    from cyclegan_tpu.utils.summary import NullSummary

    config = tiny_config
    plan = make_mesh_plan(config.parallel, devices[:4])
    data = build_data(config, 4)
    step = shard_train_step(plan, make_train_step(config, 4))

    def fresh_state():
        # The step donates its state buffers: each run needs its own.
        return jax.device_put(create_state(config, jax.random.PRNGKey(0)),
                              replicated(plan))

    def counting(counter):
        def wrapped(*args, **kw):
            counter.append(1)
            return step(*args, **kw)
        return wrapped

    untraced = []
    loop.train_epoch(config, data, plan, counting(untraced), fresh_state(),
                     NullSummary(), epoch=0)

    traced = []
    tele = make_telemetry(
        ObsConfig(jsonl_path=str(tmp_path / "t.jsonl")), str(tmp_path))
    assert tele.goodput is not None  # the ledger is on by default
    loop.train_epoch(config, data, plan, counting(traced), fresh_state(),
                     NullSummary(), epoch=0, obs=tele)
    tele.epoch(0, elapse_s=1.0)
    tele.close()

    assert len(traced) == len(untraced)
    evs = _events(str(tmp_path / "t.jsonl"))
    assert any(e["event"] == "goodput" for e in evs)


# ------------------------------------------------- downstream folding


def test_obs_report_folds_goodput_and_census(tmp_path):
    """The report renders both new sections — and names their absence
    explicitly on streams that predate them."""
    from obs_report import fold, load_events, render

    path = str(tmp_path / "t.jsonl")
    tele = Telemetry(MetricsLogger(path), goodput=GoodputLedger())
    clock = tele.step_clock(0)
    clock.stage_begin(); clock.staged()
    clock.dispatched(steps=1, kind="single")
    clock.fetched(0.01, steps=1)
    clock.finish()
    tele.event("comms_census", mesh={"n_data": 2, "n_spatial": 1},
               analytic={"data_bytes": 1000, "spatial_bytes": 0},
               reconciliation={"data": {"analytic_bytes": 1000,
                                        "measured_bytes": 990,
                                        "measured_ops": 3,
                                        "error": 0.0101}},
               max_recon_error=0.0101, tolerance=0.10, ok=True)
    tele.epoch(0, elapse_s=2.0)
    tele.close()

    events, skipped = load_events(path)
    report = fold(events, skipped)
    assert not report["unknown_kinds"]  # both kinds are folded
    assert report["goodput_rollup"]["n_epochs"] == 1
    assert report["comms_census_rollup"]["ok"] is True
    text = render(report)
    assert "goodput ledger" in text and "comms census" in text
    assert "RECONCILIATION FAILED" not in text

    # A stream with loop aggregates but neither event renders the
    # explicit absence lines, not silence.
    path2 = str(tmp_path / "old.jsonl")
    tele2 = Telemetry(MetricsLogger(path2), goodput=None)
    clock = tele2.step_clock(0)
    clock.stage_begin(); clock.staged()
    clock.dispatched(steps=1, kind="single")
    clock.finish()
    tele2.close()
    events2, _ = load_events(path2)
    text2 = render(fold(events2, 0))
    assert "goodput ledger: absent" in text2
    assert "comms census: absent" in text2


def test_no_sync_covers_observatory_modules():
    """obs/comms.py and obs/goodput.py live in the hot-path no-sync
    scan set: the census and ledger must never add a device sync."""
    from check_no_sync import HOT_PATH_DIRS, run_check

    assert "cyclegan_tpu/obs" in dict(HOT_PATH_DIRS)
    assert run_check() == []
