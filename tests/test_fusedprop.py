"""FusedProp gradient engine (train/steps.py, --grad_impl fusedprop).

The contract is EXACTNESS against the combined-scalar engine: fusedprop
reorganizes WHICH vjp calls produce the four gradients (each
discriminator runs once per fake, its pullback feeds both the
generator's adversarial gradient and the D fake-term gradient) but the
math is the same chain rule over the same graph, so every gradient leaf
must match the combined engine to f32 tolerance (<=1e-5) and every
metric — including the `_health/` moment scalars — must exist under the
same key with the same value. Parity is pinned for the plain step, the
accumulation step, and both data-parallel paths.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import NamedSharding, PartitionSpec

from cyclegan_tpu.config import ParallelConfig, TrainConfig
from cyclegan_tpu.parallel import make_mesh_plan, shard_batch, shard_train_step
from cyclegan_tpu.parallel.collective import shard_map_train_step
from cyclegan_tpu.train import (
    create_state,
    make_accum_train_step,
    make_train_step,
)
from cyclegan_tpu.train.steps import make_grad_fn

RTOL, ATOL = 1e-5, 1e-6


def _with_grad_impl(config, impl):
    return dataclasses.replace(
        config, train=dataclasses.replace(config.train, grad_impl=impl)
    )


def _batch(config, n, seed=11):
    rng = np.random.RandomState(seed)
    s = config.model.image_size
    x = rng.rand(n, s, s, 3).astype(np.float32) * 2 - 1
    y = rng.rand(n, s, s, 3).astype(np.float32) * 2 - 1
    w = np.ones((n,), np.float32)
    return x, y, w


def _assert_trees_close(a, b, what):
    for (pa, la), (pb, lb) in zip(
        jax.tree_util.tree_leaves_with_path(a),
        jax.tree_util.tree_leaves_with_path(b),
    ):
        assert pa == pb
        np.testing.assert_allclose(
            np.asarray(la), np.asarray(lb), rtol=RTOL, atol=ATOL,
            err_msg=f"{what}: {jax.tree_util.keystr(pa)}",
        )


def test_grad_impl_is_validated():
    with pytest.raises(ValueError, match="grad_impl"):
        TrainConfig(grad_impl="backprop")


def test_fusedprop_gradients_match_combined(tiny_config):
    """The acceptance bar: all four per-network gradient trees from the
    fusedprop engine equal the combined engine's at <=1e-5."""
    gbs = 2
    x, y, w = _batch(tiny_config, gbs)
    state = create_state(tiny_config, jax.random.PRNGKey(0))
    args = (state.g_params, state.f_params, state.dx_params,
            state.dy_params, x, y, w)

    combined = jax.jit(make_grad_fn(_with_grad_impl(tiny_config, "combined"), gbs))
    fusedprop = jax.jit(make_grad_fn(_with_grad_impl(tiny_config, "fusedprop"), gbs))
    (gc_g, gc_f, gc_dx, gc_dy), m_c = combined(*args)
    (gf_g, gf_f, gf_dx, gf_dy), m_f = fusedprop(*args)

    _assert_trees_close(gc_g, gf_g, "g_params grad")
    _assert_trees_close(gc_f, gf_f, "f_params grad")
    _assert_trees_close(gc_dx, gf_dx, "dx_params grad")
    _assert_trees_close(gc_dy, gf_dy, "dy_params grad")

    # Metric parity: SAME key set (health moments included) and values.
    assert set(m_c) == set(m_f)
    assert any(k.startswith("_health/") for k in m_c)
    for k in m_c:
        np.testing.assert_allclose(
            float(m_c[k]), float(m_f[k]), rtol=RTOL, atol=ATOL, err_msg=k
        )


def test_fusedprop_train_step_matches_combined(tiny_config):
    """One full optimizer update (four Adams) lands on the same params."""
    gbs = 2
    x, y, w = _batch(tiny_config, gbs)

    s_c, m_c = jax.jit(make_train_step(_with_grad_impl(tiny_config, "combined"), gbs))(
        create_state(tiny_config, jax.random.PRNGKey(0)), x, y, w)
    s_f, m_f = jax.jit(make_train_step(_with_grad_impl(tiny_config, "fusedprop"), gbs))(
        create_state(tiny_config, jax.random.PRNGKey(0)), x, y, w)

    for k in m_c:
        np.testing.assert_allclose(
            float(m_c[k]), float(m_f[k]), rtol=RTOL, atol=ATOL, err_msg=k
        )
    _assert_trees_close(s_c.g_params, s_f.g_params, "g_params")
    _assert_trees_close(s_c.f_params, s_f.f_params, "f_params")
    _assert_trees_close(s_c.dx_params, s_f.dx_params, "dx_params")
    _assert_trees_close(s_c.dy_params, s_f.dy_params, "dy_params")
    assert int(s_c.step) == int(s_f.step) == 1


def test_fusedprop_accum_matches_combined(tiny_config):
    """Microbatch accumulation sums per-microbatch gradients — linearity
    must hold for the vjp engine exactly as for jax.grad."""
    micro, accum = 2, 2
    gbs = micro * accum
    x, y, w = _batch(tiny_config, gbs)
    xs = x.reshape(accum, micro, *x.shape[1:])
    ys = y.reshape(accum, micro, *y.shape[1:])
    ws = w.reshape(accum, micro)

    s_c, m_c = jax.jit(make_accum_train_step(
        _with_grad_impl(tiny_config, "combined"), gbs, accum))(
        create_state(tiny_config, jax.random.PRNGKey(0)), xs, ys, ws)
    s_f, m_f = jax.jit(make_accum_train_step(
        _with_grad_impl(tiny_config, "fusedprop"), gbs, accum))(
        create_state(tiny_config, jax.random.PRNGKey(0)), xs, ys, ws)

    for k in m_c:
        np.testing.assert_allclose(
            float(m_c[k]), float(m_f[k]), rtol=RTOL, atol=ATOL, err_msg=k
        )
    _assert_trees_close(s_c.g_params, s_f.g_params, "g_params")
    _assert_trees_close(s_c.dx_params, s_f.dx_params, "dx_params")
    _assert_trees_close(s_c.g_opt, s_f.g_opt, "g_opt")


def test_fusedprop_dp_jit_matches_combined(tiny_config, devices):
    """8-way compiler-scheduled data parallelism: sharded fusedprop step
    equals the sharded combined step."""
    n = 8
    x, y, w = _batch(tiny_config, n)
    plan = make_mesh_plan(ParallelConfig(), devices)
    xs, ys, ws = shard_batch(plan, x, y, w)

    results = {}
    for impl in ("combined", "fusedprop"):
        step = shard_train_step(
            plan, make_train_step(_with_grad_impl(tiny_config, impl), n))
        state = jax.device_put(
            create_state(tiny_config, jax.random.PRNGKey(0)),
            NamedSharding(plan.mesh, PartitionSpec()))
        results[impl] = step(state, xs, ys, ws)

    s_c, m_c = results["combined"]
    s_f, m_f = results["fusedprop"]
    for k in m_c:
        np.testing.assert_allclose(
            float(m_c[k]), float(m_f[k]), rtol=RTOL, atol=ATOL, err_msg=k
        )
    _assert_trees_close(s_c.g_params, s_f.g_params, "g_params")
    _assert_trees_close(s_c.dy_params, s_f.dy_params, "dy_params")


def test_fusedprop_shard_map_psum_matches_combined(tiny_config, devices):
    """Explicit shard_map+psum path: the per-shard fusedprop gradients
    psum to the same global gradient (losses scale by global batch, so
    shard sums are exact, not averaged approximations)."""
    n = 8
    x, y, w = _batch(tiny_config, n)
    plan = make_mesh_plan(ParallelConfig(), devices)
    xs, ys, ws = shard_batch(plan, x, y, w)

    results = {}
    for impl in ("combined", "fusedprop"):
        step = shard_map_train_step(plan, _with_grad_impl(tiny_config, impl), n)
        results[impl] = step(
            create_state(tiny_config, jax.random.PRNGKey(0)), xs, ys, ws)

    s_c, m_c = results["combined"]
    s_f, m_f = results["fusedprop"]
    for k in m_c:
        np.testing.assert_allclose(
            float(m_c[k]), float(m_f[k]), rtol=RTOL, atol=ATOL, err_msg=k
        )
    _assert_trees_close(s_c.g_params, s_f.g_params, "g_params")
    _assert_trees_close(s_c.dx_params, s_f.dx_params, "dx_params")
