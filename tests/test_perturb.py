"""Perturbative cheap-trunk tier (--trunk_impl perturb).

Perturbative GAN (arXiv:1902.01514) replaces each residual block's two
3x3 convs with fixed random perturbation masks followed by 1x1 convs —
~9x fewer trunk conv FLOPs (utils/flops.py). Pinned here: the block's
parameter tree really is 1x1 (the FLOP claim is structural, not
aspirational), masks are deterministic functions of (salt, layer) and
NOT parameters (no checkpoint bloat), the architecture round-trips
through the checkpoint sidecar, config validation rejects the
unsupported combinations, and the assembled system still learns.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from cyclegan_tpu.config import Config, GeneratorConfig, ModelConfig
from cyclegan_tpu.models import PerturbBlock
from cyclegan_tpu.models.modules import perturb_mask
from cyclegan_tpu.train import build_models, create_state, make_train_step
from cyclegan_tpu.utils.checkpoint import Checkpointer


def _perturb_config(config):
    return dataclasses.replace(
        config, model=dataclasses.replace(config.model, trunk_impl="perturb")
    )


# ------------------------------------------------------------- validation

def test_unknown_trunk_impl_rejected():
    with pytest.raises(ValueError, match="trunk_impl"):
        ModelConfig(trunk_impl="dense")


def test_perturb_rejects_scan_blocks():
    with pytest.raises(ValueError, match="scan_blocks"):
        ModelConfig(trunk_impl="perturb", scan_blocks=True)


def test_perturb_rejects_pallas_epilogue():
    with pytest.raises(ValueError, match="epilogue"):
        ModelConfig(trunk_impl="perturb", pad_impl="epilogue")


# ----------------------------------------------------- structure + masks

def test_perturb_trunk_params_are_1x1(tiny_config):
    cfg = _perturb_config(tiny_config)
    gen, _ = build_models(cfg)
    s = cfg.model.image_size
    params = gen.init(jax.random.PRNGKey(0), jnp.zeros((1, s, s, 3)))

    tree = params["params"]
    blocks = [k for k in tree if k.startswith("ResidualBlock_")]
    assert len(blocks) == cfg.model.generator.num_residual_blocks
    for bk in blocks:
        block = tree[bk]
        assert set(block) == {"Conv_0", "InstanceNorm_0",
                              "Conv_1", "InstanceNorm_1"}
        for ck in ("Conv_0", "Conv_1"):
            kernel = block[ck]["kernel"]
            assert kernel.shape[:2] == (1, 1), (bk, ck, kernel.shape)
            assert "bias" not in block[ck]  # masks replace the bias role
        # Masks must NOT appear as parameters or variables of any kind.
        assert not any("mask" in k.lower() for k in block)


def test_perturb_forward_shape_and_dtype(tiny_config):
    cfg = _perturb_config(tiny_config)
    gen, _ = build_models(cfg)
    s = cfg.model.image_size
    x = jax.random.uniform(jax.random.PRNGKey(1), (2, s, s, 3),
                           minval=-1, maxval=1)
    params = gen.init(jax.random.PRNGKey(0), x)
    out = gen.apply(params, x)
    assert out.shape == (2, s, s, 3)
    assert out.dtype == jnp.float32
    assert bool(jnp.isfinite(out).all())


def test_perturb_masks_deterministic_and_distinct():
    shape = (8, 8, 4)
    m_a = perturb_mask(0, 0, shape)
    m_b = perturb_mask(0, 0, shape)
    np.testing.assert_array_equal(np.asarray(m_a), np.asarray(m_b))
    # Different layer or different block salt -> different mask.
    assert not np.array_equal(np.asarray(m_a),
                              np.asarray(perturb_mask(0, 1, shape)))
    assert not np.array_equal(np.asarray(m_a),
                              np.asarray(perturb_mask(1, 0, shape)))


def test_perturb_blocks_differ_by_salt(tiny_config):
    """Two blocks share parameter SHAPES but see different fixed masks, so
    with identical weights they compute different functions."""
    x = jax.random.uniform(jax.random.PRNGKey(2), (1, 8, 8, 4))
    b0 = PerturbBlock(salt=0)
    b1 = PerturbBlock(salt=1)
    params = b0.init(jax.random.PRNGKey(0), x)
    out0 = b0.apply(params, x)
    out1 = b1.apply(params, x)  # same params, different salt
    assert not np.allclose(np.asarray(out0), np.asarray(out1))


# ------------------------------------------------- checkpoint round-trip

def test_perturb_checkpoint_roundtrip(tiny_config, tmp_path):
    """The sidecar records trunk_impl, model_from_meta rebuilds the same
    architecture, and the saved params restore into it exactly."""
    cfg = _perturb_config(tiny_config)
    state = create_state(cfg, jax.random.PRNGKey(0))
    ckpt = Checkpointer(str(tmp_path))
    ckpt.save(state, epoch=0, meta=cfg.model_meta())

    meta = ckpt.read_meta()
    assert meta["model"]["trunk_impl"] == "perturb"
    rebuilt = Config.model_from_meta(meta)
    assert rebuilt.trunk_impl == "perturb"

    template = create_state(
        dataclasses.replace(cfg, model=rebuilt), jax.random.PRNGKey(7))
    restored, next_epoch = ckpt.restore(template)
    assert next_epoch == 1
    for (pa, la), (pb, lb) in zip(
        jax.tree_util.tree_leaves_with_path(state.g_params),
        jax.tree_util.tree_leaves_with_path(restored.g_params),
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_resnet_meta_does_not_leak_perturb(tiny_config):
    meta = tiny_config.model_meta()
    assert meta["model"]["trunk_impl"] == "resnet"
    assert Config.model_from_meta(meta).trunk_impl == "resnet"


# -------------------------------------------------------- learning smoke

def test_perturb_training_learns(tiny_config):
    """Same probe as tests/test_training_learns.py: the discriminator
    objective must fall fast against the perturb generator too — the
    cheap trunk changes the generator's function class, not the
    trainability of the assembled system."""
    cfg = _perturb_config(tiny_config)
    batch = 4
    step = jax.jit(make_train_step(cfg, batch))
    state = create_state(cfg, jax.random.PRNGKey(3))

    rng = np.random.RandomState(3)
    s = cfg.model.image_size
    data = [
        (
            (rng.rand(batch, s, s, 3).astype(np.float32) * 2 - 1),
            (rng.rand(batch, s, s, 3).astype(np.float32) * 2 - 1),
        )
        for _ in range(2)
    ]
    w = np.ones((batch,), np.float32)

    history = []
    for i in range(120):
        x, y = data[i % len(data)]
        state, metrics = step(state, x, y, w)
        m = jax.device_get(metrics)
        history.append(float(m["loss_X/loss"]) + float(m["loss_Y/loss"]))

    early = np.mean(history[:5])
    late = np.mean(history[-5:])
    assert np.isfinite(history).all()
    assert late < 0.8 * early, (
        f"perturb-trunk run did not improve: {early:.4f} -> {late:.4f}"
    )
