"""Test harness: force an 8-device virtual CPU mesh before JAX import.

The reference is smoke-tested only by running main.py on whatever devices
are visible (SURVEY.md §4 — it has no tests). Here every test runs on
8 virtual CPU devices so distributed semantics (batch sharding, grad
all-reduce) are exercised without TPU hardware.
"""

import os

# Force CPU even when the session env points JAX at a TPU tunnel
# (JAX_PLATFORMS=axon): tests must be hermetic and host-only.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# The environment's sitecustomize may import jax before this file runs,
# freezing JAX_PLATFORMS at its launch-time value — override post-import.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: XLA:CPU compiles dominate the suite's
# ~25 min wall time; repeat runs with a warm cache cut per-program
# compile ~5x (measured 11.8s -> 2.4s on the tiny train step). Tests
# get their OWN cache dir (never the user's production cache), and the
# env vars below propagate into the subprocess e2e tests so their
# main.py runs cache at the same threshold. Set
# CYCLEGAN_TEST_NO_COMP_CACHE=1 to bisect any suspected cache issue.
if not os.environ.get("CYCLEGAN_TEST_NO_COMP_CACHE"):
    from cyclegan_tpu.utils.platform import enable_compilation_cache

    os.environ["JAX_COMPILATION_CACHE_DIR"] = os.path.expanduser(
        "~/.cache/jax_comp_cache_tests"
    )
    os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "1.0"
    enable_compilation_cache()

import pytest  # noqa: E402

from cyclegan_tpu.config import tiny_test_config  # noqa: E402


@pytest.fixture(scope="session")
def tiny_config():
    return tiny_test_config()


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
