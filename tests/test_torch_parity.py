"""Cross-framework golden parity (SURVEY.md §4).

The reference's training step (persistent GradientTape, four per-net
gradient pulls from pre-update weights — /root/reference/main.py:207-262)
is re-implemented literally in torch (tests/torch_reference.py) with NO
stop-gradients, and compared numerically against our fused
single-backward JAX step under identical weights and inputs. Agreement
proves the stop_gradient placement in train/steps.py reproduces the
tape's var_list-restricted gradients exactly — via an independent autodiff
system, not our own code.
"""

import jax
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from cyclegan_tpu.config import (
    Config,
    DataConfig,
    DiscriminatorConfig,
    GeneratorConfig,
    ModelConfig,
    TrainConfig,
)
from cyclegan_tpu.models import PatchGANDiscriminator, ResNetGenerator
from cyclegan_tpu.train import create_state
from cyclegan_tpu.train.steps import make_grad_fn

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import torch_reference as tr  # noqa: E402  (first-party: fail loudly)


@pytest.fixture(scope="module")
def parity_config():
    return Config(
        model=ModelConfig(
            generator=GeneratorConfig(
                filters=4,
                num_downsampling_blocks=1,
                num_residual_blocks=1,
                num_upsample_blocks=1,
            ),
            discriminator=DiscriminatorConfig(filters=4, num_downsampling=3),
            image_size=16,
        ),
        data=DataConfig(crop_size=16, resize_size=18),
        train=TrainConfig(batch_size=2),
    )


@pytest.fixture(scope="module")
def state_and_inputs(parity_config):
    state = create_state(parity_config, jax.random.PRNGKey(7))
    rng = np.random.RandomState(7)
    x = rng.rand(2, 16, 16, 3).astype(np.float32) * 2 - 1
    y = rng.rand(2, 16, 16, 3).astype(np.float32) * 2 - 1
    return state, x, y


def nchw(a: np.ndarray) -> torch.Tensor:
    return torch.tensor(a.transpose(0, 3, 1, 2))


def test_generator_forward_parity(parity_config, state_and_inputs):
    state, x, _ = state_and_inputs
    gen = ResNetGenerator(config=parity_config.model.generator)
    ours = np.asarray(gen.apply(state.g_params, x))
    theirs = tr.generator_forward(
        tr.to_torch_tree(state.g_params), nchw(x), parity_config.model.generator
    )
    np.testing.assert_allclose(
        theirs.detach().numpy().transpose(0, 2, 3, 1), ours, atol=2e-6
    )


def test_discriminator_forward_parity(parity_config, state_and_inputs):
    state, x, _ = state_and_inputs
    disc = PatchGANDiscriminator(config=parity_config.model.discriminator)
    ours = np.asarray(disc.apply(state.dx_params, x))
    theirs = tr.discriminator_forward(
        tr.to_torch_tree(state.dx_params), nchw(x), parity_config.model.discriminator
    )
    np.testing.assert_allclose(
        theirs.detach().numpy().transpose(0, 2, 3, 1), ours, atol=2e-6
    )


def test_losses_and_gradients_match_reference_tape(parity_config, state_and_inputs):
    state, x, y = state_and_inputs
    gbs = 2.0
    w = np.ones((2,), np.float32)

    # Ours: fused single-backward step gradients.
    grad_fn = make_grad_fn(parity_config, int(gbs))
    (g_g, g_f, g_dx, g_dy), metrics = grad_fn(
        state.g_params, state.f_params, state.dx_params, state.dy_params, x, y, w
    )

    # Theirs: literal tape semantics in torch.
    tg = tr.to_torch_tree(state.g_params)
    tf_ = tr.to_torch_tree(state.f_params)
    tdx = tr.to_torch_tree(state.dx_params)
    tdy = tr.to_torch_tree(state.dy_params)
    L, grads = tr.reference_grads(
        parity_config, tg, tf_, tdx, tdy, nchw(x), nchw(y), gbs
    )

    # All ten loss scalars agree.
    for k, v in L.items():
        np.testing.assert_allclose(
            float(v.detach()), float(metrics[k]), rtol=2e-5, atol=2e-6, err_msg=k
        )

    # All four gradient trees agree leaf-by-leaf (jax sorts dict keys when
    # flattening; tr.leaves flattens in the same sorted order).
    for ours_tree, theirs_list, name in [
        (g_g, grads[0], "G"),
        (g_f, grads[1], "F"),
        (g_dx, grads[2], "dX"),
        (g_dy, grads[3], "dY"),
    ]:
        ours_leaves = jax.tree.leaves(ours_tree)
        assert len(ours_leaves) == len(theirs_list), name
        for ol, tl in zip(ours_leaves, theirs_list):
            np.testing.assert_allclose(
                np.asarray(ol),
                tl.detach().numpy(),
                rtol=1e-3,
                atol=3e-6,
                err_msg=f"{name} grad leaf shape {np.shape(ol)}",
            )
